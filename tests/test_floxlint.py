"""Self-tests for tools/floxlint: every rule against the fixture corpus, the
clean-package gate, suppression comments, CLI exit codes and JSON output.

The fixture contract: each seeded violation line carries a trailing
``# expect: FLXnnn`` marker; a fixture file's expected finding set is exactly
its markers (so new false positives in a rule fail these tests too).
"""

from __future__ import annotations

import json
import re
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tools" / "floxlint" / "fixtures"

sys.path.insert(0, str(REPO))

from tools.floxlint import RULES, get_rules, lint_file, lint_paths  # noqa: E402
from tools.floxlint.cli import main as floxlint_main  # noqa: E402

_EXPECT_RE = re.compile(r"#\s*expect:\s*((?:FLX\d{3}[,\s]*)+)")


def expected_findings(path: Path) -> set[tuple[str, int]]:
    out: set[tuple[str, int]] = set()
    for lineno, line in enumerate(path.read_text().splitlines(), start=1):
        m = _EXPECT_RE.search(line)
        if m:
            for rule in re.findall(r"FLX\d{3}", m.group(1)):
                out.add((rule, lineno))
    return out


def actual_findings(paths) -> set[tuple[str, int]]:
    return {(f.rule, f.line) for f in lint_paths(paths)}


# ---------------------------------------------------------------------------
# fixture corpus: exact (rule, line) agreement per file
# ---------------------------------------------------------------------------

def test_fixture_corpus_is_nonempty():
    assert len(list(FIXTURES.rglob("*.py"))) >= 7


@pytest.mark.parametrize(
    "fixture",
    ["flx001_host_sync.py", "flx002_recompile_traps.py", "flx003_dtype_policy.py",
     "flx004_version_gated.py", "flx006_swallow.py", "flx007_eager_logging.py",
     "flx007_print.py", "flx009_donation.py", "flx010_options_drift.py",
     "flx011_helper_sync.py", "clean_module.py", "suppressed.py"],
)
def test_fixture_findings_match_markers(fixture):
    path = FIXTURES / fixture
    assert actual_findings([path]) == expected_findings(path)


def test_flx005_package_fixture():
    pkg = FIXTURES / "flx005_pkg"
    expected = expected_findings(pkg / "api.py")
    assert expected  # the fixture seeds at least one violation
    assert actual_findings([pkg]) == expected


def test_flx008_package_fixture():
    # FLX008 is a whole-package contract (clear_all lives in one module, the
    # orphan cache in another), so like FLX005 it is asserted at package
    # granularity; file:line must point at the orphan's definition site
    pkg = FIXTURES / "flx008_pkg"
    expected = expected_findings(pkg / "registries.py")
    assert expected
    assert actual_findings([pkg]) == expected
    findings = [f for f in lint_paths([pkg]) if f.rule == "FLX008"]
    assert len(findings) == 1
    assert findings[0].path.endswith("registries.py")
    assert "_ORPHAN_CACHE" in findings[0].message


def test_every_rule_has_fixture_coverage():
    """Each FLX rule must fire at least once across the corpus."""
    seen = {rule for rule, _ in actual_findings([FIXTURES])}
    assert seen == set(RULES), f"rules without fixture coverage: {set(RULES) - seen}"


# ---------------------------------------------------------------------------
# the package itself is clean (the lint gate this PR establishes)
# ---------------------------------------------------------------------------


def test_flox_tpu_package_is_clean():
    findings = lint_paths([REPO / "flox_tpu"])
    assert findings == [], "\n".join(f.format_human() for f in findings)


def test_tools_and_tests_tpu_are_clean():
    # the gate lints beyond flox_tpu/ (ISSUE 5 satellite); the seeded
    # fixture corpus under tools/floxlint/fixtures is auto-pruned
    findings = lint_paths([REPO / "tools", REPO / "tests_tpu"])
    assert findings == [], "\n".join(f.format_human() for f in findings)


def test_fixture_corpus_is_not_pruned_when_passed_explicitly():
    # pruning only applies while recursing into a root — the corpus itself
    # stays lintable, which is what every fixture test here relies on
    assert lint_paths([FIXTURES])


# ---------------------------------------------------------------------------
# acceptance regressions: re-introducing the fixed hazards must fail the lint
# ---------------------------------------------------------------------------


def test_bare_shard_map_reintroduction_fails(tmp_path):
    bad = tmp_path / "regress_shard_map.py"
    bad.write_text(
        "import jax\n\n"
        "def build(program, mesh, in_specs, out_specs):\n"
        "    return jax.jit(jax.shard_map(program, mesh=mesh,\n"
        "        in_specs=in_specs, out_specs=out_specs))\n"
    )
    rc = floxlint_main([str(bad)])
    assert rc == 1
    assert any(f.rule == "FLX004" for f in lint_file(bad))


def test_swallowed_retry_exception_fails(tmp_path):
    # ISSUE 3 satellite: a retry loop that swallows with a broad except —
    # neither re-raising nor routing through resilience.classify_error —
    # must fail the lint (the shape that turns a TypeError into an
    # infinitely-spinning "transient" failure)
    bad = tmp_path / "regress_retry_swallow.py"
    bad.write_text(
        "import time\n\n"
        "def fetch_with_retry(loader, s, e):\n"
        "    for attempt in range(5):\n"
        "        try:\n"
        "            return loader(s, e)\n"
        "        except Exception:\n"
        "            time.sleep(0.1)\n"
    )
    rc = floxlint_main([str(bad)])
    assert rc == 1
    assert any(f.rule == "FLX006" for f in lint_file(bad))
    # the sanctioned shape — classify, re-raise the non-transient — is clean
    good = tmp_path / "clean_retry.py"
    good.write_text(
        "from flox_tpu.resilience import classify_error\n\n"
        "def fetch_with_retry(loader, s, e):\n"
        "    for attempt in range(5):\n"
        "        try:\n"
        "            return loader(s, e)\n"
        "        except Exception as exc:\n"
        "            if classify_error(exc) != 'transient':\n"
        "                raise\n"
    )
    assert not [f for f in lint_file(good) if f.rule == "FLX006"]


def test_unregistered_autotune_store_fails_flx008(tmp_path):
    # ISSUE 6 satellite: the autotune measurement store is a module-level
    # mutable cache that accretes at runtime; reintroducing it (or any
    # successor) WITHOUT the matching cache.clear_all registration must be
    # caught statically. This mirrors the real flox_tpu.autotune shape: a
    # sibling package whose clear_all forgets the store.
    pkg = tmp_path / "minipkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "autotune.py").write_text(
        '"""Mini autotune module with an unregistered store."""\n\n'
        "_AUTOTUNE_CACHE: dict = {}\n\n\n"
        "def record(key, candidate, gbps):\n"
        "    rec = _AUTOTUNE_CACHE.setdefault(key, {})\n"
        "    rec[candidate] = gbps\n"
        "    return rec\n"
    )
    (pkg / "cache.py").write_text(
        '"""clear_all that misses the autotune store."""\n\n\n'
        "def clear_all():\n"
        "    pass\n"
    )
    findings = [f for f in lint_paths([pkg]) if f.rule == "FLX008"]
    assert len(findings) == 1
    assert "_AUTOTUNE_CACHE" in findings[0].message
    assert findings[0].path.endswith("autotune.py")
    # registering it in clear_all makes the package clean again — the
    # spelling flox_tpu.cache.clear_all actually uses
    (pkg / "cache.py").write_text(
        '"""clear_all that registers the autotune store."""\n\n\n'
        "def clear_all():\n"
        "    from .autotune import _AUTOTUNE_CACHE\n\n"
        "    _AUTOTUNE_CACHE.clear()\n"
    )
    assert not [f for f in lint_paths([pkg]) if f.rule == "FLX008"]


def test_unregistered_serve_container_fails_flx008(tmp_path):
    # ISSUE 7 satellite: every serve-layer container (request queue,
    # coalescing table, AOT manifest memo) must be registered in
    # cache.clear_all — this proves reintroducing an UNREGISTERED one (in a
    # subpackage, like the real flox_tpu/serve/) is flagged statically.
    pkg = tmp_path / "minipkg"
    (pkg / "serve").mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "serve" / "__init__.py").write_text("")
    (pkg / "serve" / "dispatcher.py").write_text(
        '"""Mini dispatcher with serve-layer tables."""\n\n'
        "_PENDING_REGISTRY: dict = {}\n"
        "_COALESCE_CACHE: dict = {}\n\n\n"
        "def admit(rid, request):\n"
        "    _PENDING_REGISTRY[rid] = request\n\n\n"
        "def coalesce(key, leaf):\n"
        "    return _COALESCE_CACHE.setdefault(key, leaf)\n"
    )
    (pkg / "cache.py").write_text(
        '"""clear_all that forgets the coalescing table."""\n\n\n'
        "def clear_all():\n"
        "    from .serve.dispatcher import _PENDING_REGISTRY\n\n"
        "    _PENDING_REGISTRY.clear()\n"
    )
    findings = [f for f in lint_paths([pkg]) if f.rule == "FLX008"]
    assert len(findings) == 1
    assert "_COALESCE_CACHE" in findings[0].message
    assert findings[0].path.endswith("dispatcher.py")
    # registering it too makes the package clean again
    (pkg / "cache.py").write_text(
        '"""clear_all that registers every serve table."""\n\n\n'
        "def clear_all():\n"
        "    from .serve.dispatcher import _COALESCE_CACHE, _PENDING_REGISTRY\n\n"
        "    _PENDING_REGISTRY.clear()\n"
        "    _COALESCE_CACHE.clear()\n"
    )
    assert not [f for f in lint_paths([pkg]) if f.rule == "FLX008"]


def test_unregistered_cost_ledger_fails_flx008(tmp_path):
    # ISSUE 9 satellite: the cost-attribution tables accrete per program
    # key exactly like a cache — a LEDGER-named container mutated at
    # runtime (here one level through a helper, like telemetry._cost_entry)
    # without the matching clear_all registration must be flagged
    pkg = tmp_path / "minipkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "telemetry.py").write_text(
        '"""Mini telemetry with a cost ledger."""\n\n'
        "_COST_LEDGER: dict = {}\n\n\n"
        "def _cost_entry(axis, label):\n"
        "    return _COST_LEDGER.setdefault((axis, label), {})\n\n\n"
        "def observe_cost(program, device_ms=0.0):\n"
        "    entry = _cost_entry('program', program)\n"
        "    entry['device_ms'] = entry.get('device_ms', 0.0) + device_ms\n"
    )
    (pkg / "cache.py").write_text(
        '"""clear_all that forgets the ledger."""\n\n\ndef clear_all():\n    pass\n'
    )
    findings = [f for f in lint_paths([pkg]) if f.rule == "FLX008"]
    assert len(findings) == 1
    assert "_COST_LEDGER" in findings[0].message
    # registering it makes the package clean again — the spelling the real
    # flox_tpu.cache.clear_all uses
    (pkg / "cache.py").write_text(
        '"""clear_all that registers the ledger."""\n\n\n'
        "def clear_all():\n"
        "    from .telemetry import _COST_LEDGER\n\n"
        "    _COST_LEDGER.clear()\n"
    )
    assert not [f for f in lint_paths([pkg]) if f.rule == "FLX008"]


def test_unregistered_store_table_fails_flx008(tmp_path):
    # ISSUE 18 satellite: the durable-store table (name -> open store entry,
    # in a serve subpackage like the real flox_tpu/serve/stores.py) accretes
    # one entry per opened store — reintroducing it (or a successor)
    # WITHOUT the matching cache.clear_all registration must be flagged
    pkg = tmp_path / "minipkg"
    (pkg / "serve").mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "serve" / "__init__.py").write_text("")
    (pkg / "serve" / "stores.py").write_text(
        '"""Mini store registry with an unregistered table."""\n\n'
        "_STORE_TABLE: dict = {}\n\n\n"
        "def resolve(name, store):\n"
        "    return _STORE_TABLE.setdefault(name, store)\n"
    )
    (pkg / "cache.py").write_text(
        '"""clear_all that forgets the store table."""\n\n\n'
        "def clear_all():\n"
        "    pass\n"
    )
    findings = [f for f in lint_paths([pkg]) if f.rule == "FLX008"]
    assert len(findings) == 1
    assert "_STORE_TABLE" in findings[0].message
    assert findings[0].path.endswith("stores.py")
    # registering it makes the package clean again — same spelling the real
    # flox_tpu.cache.clear_all uses (delegating to the module's clear())
    (pkg / "cache.py").write_text(
        '"""clear_all that registers the store table."""\n\n\n'
        "def clear_all():\n"
        "    from .serve.stores import _STORE_TABLE\n\n"
        "    _STORE_TABLE.clear()\n"
    )
    assert not [f for f in lint_paths([pkg]) if f.rule == "FLX008"]


def test_real_store_table_is_registered(tmp_path):
    # the runtime complement: the REAL store table must empty under the
    # real clear_all (named here so a refactor cannot lose it silently)
    import flox_tpu.cache as flox_cache
    import flox_tpu.store as store_mod
    from flox_tpu.serve.stores import _STORE_TABLE, StoreEntry

    s = store_mod.IncrementalAggregationStore.create(
        str(tmp_path / "t"), funcs=("sum",), size=2
    )
    _STORE_TABLE["t"] = StoreEntry("t", s)
    flox_cache.clear_all()
    assert _STORE_TABLE == {}


def test_real_cost_ledger_is_registered():
    # the runtime complement: the REAL ledger must be reachable from the
    # real clear_all (named here so a refactor cannot lose it silently)
    import flox_tpu
    import flox_tpu.cache as flox_cache
    from flox_tpu.telemetry import _COST_LEDGER, observe_cost

    with flox_tpu.set_options(telemetry=True):
        observe_cost("probe[prog]", device_ms=1.0, nbytes=8)
    assert len(_COST_LEDGER) >= 1
    flox_cache.clear_all()
    assert _COST_LEDGER == {}


def test_unregistered_card_registry_fails_flx008(tmp_path):
    # ISSUE 14 satellite: the costmodel's compiled-program card registry
    # accretes one card per program exactly like a cache — a
    # REGISTRY-named container mutated one level through a helper (the
    # costmodel.record_compiled shape) without the matching clear_all
    # registration must be flagged
    pkg = tmp_path / "minipkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "costmodel.py").write_text(
        '"""Mini costmodel with a card registry."""\n\n'
        "_CARD_REGISTRY: dict = {}\n\n\n"
        "def _store(registry, digest, card):\n"
        "    registry[digest] = card\n\n\n"
        "def record_compiled(label, compiled):\n"
        "    card = {'label': label, 'flops': 0.0}\n"
        "    _store(_CARD_REGISTRY, label, card)\n"
        "    return card\n"
    )
    (pkg / "cache.py").write_text(
        '"""clear_all that forgets the card registry."""\n\n\n'
        "def clear_all():\n    pass\n"
    )
    findings = [f for f in lint_paths([pkg]) if f.rule == "FLX008"]
    assert len(findings) == 1
    assert "_CARD_REGISTRY" in findings[0].message
    # registering it makes the package clean again — the spelling the real
    # flox_tpu.cache.clear_all uses
    (pkg / "cache.py").write_text(
        '"""clear_all that registers the card registry."""\n\n\n'
        "def clear_all():\n"
        "    from .costmodel import _CARD_REGISTRY\n\n"
        "    _CARD_REGISTRY.clear()\n"
    )
    assert not [f for f in lint_paths([pkg]) if f.rule == "FLX008"]


def test_real_card_registry_is_registered():
    # the runtime complement: the REAL card registry must be reachable
    # from the real clear_all (named here so a refactor cannot lose it)
    import flox_tpu
    import flox_tpu.cache as flox_cache
    from flox_tpu.costmodel import _CARD_LABELS, _CARD_REGISTRY, record_compiled

    class _FakeCompiled:
        def cost_analysis(self):
            return [{"flops": 4.0, "bytes accessed": 8.0}]

        def memory_analysis(self):
            return None

        def as_text(self):
            return "HloModule probe"

    with flox_tpu.set_options(telemetry=True, costmodel=True):
        record_compiled("probe[card]", _FakeCompiled(), sig="probe")
    assert len(_CARD_REGISTRY) >= 1 and _CARD_LABELS
    flox_cache.clear_all()
    assert _CARD_REGISTRY == {} and _CARD_LABELS == {}


def test_lru_bound_cache_is_flx008_candidate(tmp_path):
    # the compiled-program caches are LRUCache instances now (ISSUE 7
    # eviction fix) — swapping dict for LRUCache must not take a cache off
    # FLX008's radar
    pkg = tmp_path / "minipkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "programs.py").write_text(
        '"""LRU-bound program cache, unregistered."""\n\n'
        "from .lru import LRUCache\n\n"
        "_PROGRAM_CACHE = LRUCache(maxsize=256)\n\n\n"
        "def remember(key, fn):\n"
        "    _PROGRAM_CACHE[key] = fn\n"
    )
    (pkg / "lru.py").write_text(
        '"""Stand-in LRU container."""\n\n\n'
        "class LRUCache(dict):\n"
        "    def __init__(self, maxsize=256):\n"
        "        super().__init__()\n"
    )
    (pkg / "cache.py").write_text('"""Empty clear_all."""\n\n\ndef clear_all():\n    pass\n')
    findings = [f for f in lint_paths([pkg]) if f.rule == "FLX008"]
    assert len(findings) == 1
    assert "_PROGRAM_CACHE" in findings[0].message


def test_real_autotune_store_is_registered():
    # the static complement: the REAL store must be reachable from the real
    # clear_all (covered by test_flox_tpu_package_is_clean too; this
    # assertion names the contract so a refactor cannot lose it silently)
    import flox_tpu.cache as flox_cache
    from flox_tpu.autotune import _AUTOTUNE_CACHE, record

    record("segment_sum", "scatter", 1.0, dtype="float32", ngroups=4, nelems=64)
    assert len(_AUTOTUNE_CACHE) >= 1
    flox_cache.clear_all()
    assert _AUTOTUNE_CACHE == {}


def test_eager_logging_reintroduction_fails(tmp_path):
    # ISSUE 4 satellite: hot-path logging that formats eagerly (f-string)
    # or prints straight to stdout must fail the lint; the lazy %-style
    # spelling and CLI-surface prints stay clean
    bad = tmp_path / "regress_eager_log.py"
    bad.write_text(
        "import logging\n\n"
        "logger = logging.getLogger('flox_tpu.regress')\n\n"
        "def hot_path(ngroups, result):\n"
        "    logger.debug(f'ngroups={ngroups}')\n"
        "    print(result)\n"
    )
    rc = floxlint_main([str(bad)])
    assert rc == 1
    assert sum(f.rule == "FLX007" for f in lint_file(bad)) == 2
    good = tmp_path / "clean_log.py"
    good.write_text(
        "import logging\n\n"
        "logger = logging.getLogger('flox_tpu.regress')\n\n"
        "def hot_path(ngroups):\n"
        "    logger.debug('ngroups=%d', ngroups)\n\n"
        "def main():\n"
        "    print('cli output is fine here')\n\n"
        "if __name__ == '__main__':\n"
        "    main()\n"
    )
    assert not [f for f in lint_file(good) if f.rule == "FLX007"]


def test_streaming_step_closure_host_sync_fails(tmp_path):
    # the donation-debugging hazard (ISSUE 2): a host-sync on a traced
    # value inside a streaming step closure — built by a factory, handed
    # to jax.jit with a donated carry — must keep firing FLX001
    bad = tmp_path / "regress_stream_step.py"
    bad.write_text(
        "import jax\n"
        "import jax.numpy as jnp\n\n"
        "def build_step(size):\n"
        "    def step(state, slab, codes):\n"
        "        if bool(jnp.any(jnp.isnan(slab))):\n"
        "            return state\n"
        "        return state + jnp.sum(slab)\n"
        "    return jax.jit(step, donate_argnums=(0,))\n"
    )
    rc = floxlint_main([str(bad)])
    assert rc == 1
    assert any(f.rule == "FLX001" for f in lint_file(bad))


def test_bf16_combine_accumulator_reintroduction_fails(tmp_path):
    bad = tmp_path / "regress_bf16.py"
    bad.write_text(
        "import jax.numpy as jnp\n\n"
        "def combine(partial, size):\n"
        "    acc = jnp.zeros((size,), dtype=jnp.bfloat16)\n"
        "    return acc + partial\n"
    )
    rc = floxlint_main([str(bad)])
    assert rc == 1
    assert any(f.rule == "FLX003" for f in lint_file(bad))


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------


def test_line_suppression(tmp_path):
    src = (
        "import jax.numpy as jnp\n\n"
        "def f(x):\n"
        "    return x.astype(jnp.bfloat16)  # floxlint: disable=FLX003\n"
    )
    p = tmp_path / "sup_line.py"
    p.write_text(src)
    assert lint_file(p) == []


def test_file_suppression(tmp_path):
    src = (
        "# floxlint: disable-file=FLX003\n"
        "import jax.numpy as jnp\n\n"
        "def f(x):\n"
        "    return x.astype(jnp.bfloat16)\n"
        "def g(x):\n"
        "    return x.astype('float16')\n"
    )
    p = tmp_path / "sup_file.py"
    p.write_text(src)
    assert lint_file(p) == []


def test_suppression_is_rule_scoped(tmp_path):
    # disabling FLX003 must not silence FLX004 on the same line
    src = (
        "import jax\n\n"
        "def f():\n"
        "    return jax.shard_map  # floxlint: disable=FLX003\n"
    )
    p = tmp_path / "sup_scoped.py"
    p.write_text(src)
    assert [f.rule for f in lint_file(p)] == ["FLX004"]


def test_disable_all(tmp_path):
    src = (
        "import jax\n\n"
        "def f():\n"
        "    return jax.shard_map  # floxlint: disable=all\n"
    )
    p = tmp_path / "sup_all.py"
    p.write_text(src)
    assert lint_file(p) == []


# ---------------------------------------------------------------------------
# CLI behavior
# ---------------------------------------------------------------------------


def test_cli_exit_zero_on_clean_package():
    assert floxlint_main([str(REPO / "flox_tpu")]) == 0


def test_cli_exit_one_on_fixtures():
    assert floxlint_main([str(FIXTURES)]) == 1


def test_cli_json_output(capsys):
    rc = floxlint_main(["--format", "json", str(FIXTURES / "flx003_dtype_policy.py")])
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["finding_count"] == len(payload["findings"]) > 0
    assert set(payload["findings_by_rule"]) == {"FLX003"}
    first = payload["findings"][0]
    assert {"path", "line", "col", "rule", "message"} <= set(first)


def test_cli_select_and_ignore():
    only_3 = {
        f.rule for f in lint_paths([FIXTURES], get_rules(select=["FLX003"]))
    }
    assert only_3 == {"FLX003"}
    without_3 = {
        f.rule for f in lint_paths([FIXTURES], get_rules(ignore=["FLX003"]))
    }
    assert "FLX003" not in without_3 and without_3


def test_cli_unknown_rule_is_usage_error(capsys):
    assert floxlint_main(["--select", "FLX999", str(FIXTURES)]) == 2
    assert "unknown rule" in capsys.readouterr().err


def test_cli_missing_path_is_usage_error():
    assert floxlint_main([]) == 2
    assert floxlint_main(["/nonexistent/die9ahPh"]) == 2


def test_syntax_error_reported_as_finding(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    findings = lint_file(p)
    assert [f.rule for f in findings] == ["FLX000"]


def test_cli_description_derives_rule_range_from_registry():
    # ISSUE 5 satellite: the stale hardcoded "FLX001-FLX005" is gone — the
    # blurb derives from the registry and tracks new rules automatically
    from tools.floxlint.cli import build_parser
    from tools.floxlint.registry import rule_id_range

    ids = sorted(RULES)
    assert rule_id_range() == f"{ids[0]}-{ids[-1]}"
    description = build_parser().description
    assert rule_id_range() in description
    assert "FLX001-FLX005" not in description


# ---------------------------------------------------------------------------
# semantic-rule regressions: reintroducing the fixed hazards must fail
# ---------------------------------------------------------------------------


def test_uncleared_cache_reintroduction_fails(tmp_path):
    # ISSUE 5 tentpole (FLX008): a new runtime cache without the matching
    # clear_all entry — the shape the PR 2 runtime introspection test could
    # only catch for caches clear_all already names — fails statically
    pkg = tmp_path / "minipkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "cache.py").write_text(
        "def clear_all():\n"
        "    from .state import _GOOD_CACHE\n"
        "    _GOOD_CACHE.clear()\n"
    )
    (pkg / "state.py").write_text(
        "_GOOD_CACHE: dict = {}\n"
        "_NEW_CACHE: dict = {}\n\n"
        "def put(k, v):\n"
        "    _GOOD_CACHE[k] = v\n"
        "    _NEW_CACHE[k] = v\n"
    )
    findings = [f for f in lint_paths([pkg]) if f.rule == "FLX008"]
    assert len(findings) == 1 and "_NEW_CACHE" in findings[0].message


def test_mutation_through_helper_param_is_detected(tmp_path):
    # the flox_tpu probe-memo shape: the cache is only ever mutated through
    # a helper that appends to its *parameter* — one-level interprocedural
    pkg = tmp_path / "minipkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "cache.py").write_text("def clear_all():\n    pass\n")
    (pkg / "state.py").write_text(
        "_PROBE_MEMO: list = []\n\n"
        "def _memoize(memo, value):\n"
        "    memo.append(value)\n"
        "    return memo[0]\n\n"
        "def probe():\n"
        "    return _memoize(_PROBE_MEMO, True)\n"
    )
    findings = [f for f in lint_paths([pkg]) if f.rule == "FLX008"]
    assert len(findings) == 1 and "_PROBE_MEMO" in findings[0].message


def test_static_registry_is_exempt_from_flx008(tmp_path):
    # import-time-populated tables (AGGREGATIONS/KERNELS shape) are not
    # caches: mutated only at module top level -> no finding
    pkg = tmp_path / "minipkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "cache.py").write_text("def clear_all():\n    pass\n")
    (pkg / "state.py").write_text(
        "KERNEL_REGISTRY: dict = {}\n"
        "KERNEL_REGISTRY['sum'] = sum\n"
        "KERNEL_REGISTRY['max'] = max\n"
    )
    assert [f for f in lint_paths([pkg]) if f.rule == "FLX008"] == []


def test_donation_after_use_reintroduction_fails(tmp_path):
    bad = tmp_path / "regress_donation.py"
    bad.write_text(
        "import jax\n"
        "import jax.numpy as jnp\n\n"
        "def reduce_slabs(state, slabs):\n"
        "    step = jax.jit(lambda acc, x: acc + x, donate_argnums=(0,))\n"
        "    out = step(state, slabs[0])\n"
        "    return out + jnp.sum(state)\n"
    )
    assert any(f.rule == "FLX009" for f in lint_paths([bad]))
    # the carry idiom must stay clean
    good = tmp_path / "clean_donation.py"
    good.write_text(
        "import jax\n\n"
        "def reduce_slabs(state, slabs):\n"
        "    step = jax.jit(lambda acc, x: acc + x, donate_argnums=(0,))\n"
        "    for slab in slabs:\n"
        "        state = step(state, slab)\n"
        "    return state\n"
    )
    assert not [f for f in lint_paths([good]) if f.rule == "FLX009"]


def test_options_drift_reintroduction_fails(tmp_path):
    bad = tmp_path / "regress_options.py"
    bad.write_text(
        "import os\n\n"
        "OPTIONS = {\n"
        "    'new_knob': 3,\n"
        "}\n\n"
        "_VALIDATORS = {}\n"
    )
    rules = {f.rule for f in lint_paths([bad])}
    assert "FLX010" in rules
    messages = [f.message for f in lint_paths([bad]) if f.rule == "FLX010"]
    assert any("env mirror" in m for m in messages)
    assert any("_VALIDATORS" in m for m in messages)


def test_flx012_serve_fixture():
    # FLX012 scopes to files under a `serve` path component: the fixture
    # package mirrors flox_tpu/serve and pins both the violations and the
    # sanctioned shapes (re-raise / classify / record / specific types)
    fixture = FIXTURES / "flx012_pkg" / "serve" / "handlers.py"
    expected = expected_findings(fixture)
    assert expected  # the fixture seeds at least one violation
    assert actual_findings([fixture]) == expected


def test_flx012_unforensic_serve_except_fails(tmp_path):
    # ISSUE 12 satellite: a serve-plane handler that answers the error but
    # neither classifies it nor leaves a flight trace must fail the lint —
    # a replica quietly eating device-loss errors looks healthy until the
    # fleet is not. Outside a serve/ directory the same shape is FLX012-free
    # (FLX006 still polices retry loops everywhere).
    serve_dir = tmp_path / "serve"
    serve_dir.mkdir()
    bad = serve_dir / "regress_swallow.py"
    src = (
        "def answer_request(emit, work):\n"
        "    try:\n"
        "        return work()\n"
        "    except Exception as exc:\n"
        "        emit({'ok': False, 'error': type(exc).__name__})\n"
    )
    bad.write_text(src)
    assert any(f.rule == "FLX012" for f in lint_file(bad))
    outside = tmp_path / "regress_swallow_outside.py"
    outside.write_text(src)
    assert not [f for f in lint_file(outside) if f.rule == "FLX012"]
    # the sanctioned shape: record to the flight ring, then answer
    good = serve_dir / "clean_records.py"
    good.write_text(
        "from flox_tpu import telemetry\n\n"
        "def answer_request(emit, work):\n"
        "    try:\n"
        "        return work()\n"
        "    except Exception as exc:\n"
        "        telemetry.record_serve_error(exc, what='request')\n"
        "        emit({'ok': False, 'error': type(exc).__name__})\n"
    )
    assert not [f for f in lint_file(good) if f.rule == "FLX012"]


def test_helper_host_sync_reintroduction_fails(tmp_path):
    bad = tmp_path / "regress_helper_sync.py"
    bad.write_text(
        "import jax\n"
        "import jax.numpy as jnp\n\n"
        "def _snapshot(arr):\n"
        "    return arr.item()\n\n"
        "@jax.jit\n"
        "def step(state, slab):\n"
        "    if _snapshot(jnp.sum(slab)) == 0:\n"
        "        return state\n"
        "    return state + jnp.sum(slab)\n"
    )
    findings = [f for f in lint_paths([bad]) if f.rule == "FLX011"]
    assert findings and "_snapshot" in findings[0].message


def test_flx011_resolves_through_import_alias(tmp_path):
    # the interprocedural point: the helper lives in ANOTHER module and is
    # re-exported under an alias; the project index follows the chain
    pkg = tmp_path / "aliaspkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "hostutils.py").write_text(
        "import numpy as np\n\n"
        "def pull(block):\n"
        "    return np.asarray(block)\n"
    )
    (pkg / "exports.py").write_text("from .hostutils import pull as to_host\n")
    (pkg / "kernelmod.py").write_text(
        "import jax\n"
        "import jax.numpy as jnp\n\n"
        "from .exports import to_host\n\n"
        "@jax.jit\n"
        "def step(state, slab):\n"
        "    host = to_host(slab)\n"
        "    return state + jnp.sum(slab)\n"
    )
    findings = [f for f in lint_paths([pkg]) if f.rule == "FLX011"]
    assert len(findings) == 1
    assert findings[0].path.endswith("kernelmod.py")


# ---------------------------------------------------------------------------
# SARIF output (--format sarif)
# ---------------------------------------------------------------------------


def _validate_sarif(doc):
    """Structural SARIF 2.1.0 validation: the required-property subset of
    the OASIS schema that code scanning actually consumes."""
    assert doc["version"] == "2.1.0"
    assert doc["$schema"].endswith("sarif-schema-2.1.0.json")
    assert isinstance(doc["runs"], list) and len(doc["runs"]) == 1
    run = doc["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "floxlint"
    rule_ids = [r["id"] for r in driver["rules"]]
    assert rule_ids == sorted(rule_ids)
    for rule in driver["rules"]:
        assert rule["shortDescription"]["text"]
    assert isinstance(run["results"], list)
    for result in run["results"]:
        assert result["ruleId"] in rule_ids
        assert result["level"] in ("none", "note", "warning", "error")
        assert result["message"]["text"]
        loc = result["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"]
        assert "\\" not in loc["artifactLocation"]["uri"]
        assert loc["region"]["startLine"] >= 1
        assert loc["region"]["startColumn"] >= 1
        assert driver["rules"][result["ruleIndex"]]["id"] == result["ruleId"]
    return run


def test_sarif_output_on_findings(capsys):
    rc = floxlint_main(["--format", "sarif", str(FIXTURES / "flx003_dtype_policy.py")])
    assert rc == 1
    run = _validate_sarif(json.loads(capsys.readouterr().out))
    assert run["results"]
    expected = expected_findings(FIXTURES / "flx003_dtype_policy.py")
    got = {
        (r["ruleId"], r["locations"][0]["physicalLocation"]["region"]["startLine"])
        for r in run["results"]
    }
    assert got == expected


def test_acceptance_sarif_clean_tree(capsys):
    # the ISSUE 5 acceptance command: schema-valid SARIF, exit 0, no results
    rc = floxlint_main([str(REPO / "flox_tpu"), str(REPO / "tools"), "--format", "sarif"])
    out = capsys.readouterr().out
    assert rc == 0
    run = _validate_sarif(json.loads(out))
    assert run["results"] == []
    # the full rule catalog rides along even with zero results
    assert [r["id"] for r in run["tool"]["driver"]["rules"]] == sorted(RULES)


# ---------------------------------------------------------------------------
# baseline (--baseline / --update-baseline)
# ---------------------------------------------------------------------------


def _seed_violation(tmp_path, name="bad.py"):
    p = tmp_path / name
    p.write_text(
        "import jax.numpy as jnp\n\n"
        "def f(x):\n"
        "    return x.astype(jnp.bfloat16)\n"
    )
    return p


def test_baseline_write_then_check(tmp_path, capsys):
    bad = _seed_violation(tmp_path)
    baseline = tmp_path / "baseline.json"
    assert floxlint_main([str(bad), "--baseline", str(baseline), "--update-baseline"]) == 0
    payload = json.loads(baseline.read_text())
    assert payload["version"] == 1 and len(payload["findings"]) == 1
    entry = payload["findings"][0]
    assert entry["rule"] == "FLX003" and entry["count"] == 1 and entry["fingerprint"]
    capsys.readouterr()
    # check mode: the baselined finding is absorbed, exit 0
    assert floxlint_main([str(bad), "--baseline", str(baseline)]) == 0
    assert "clean" in capsys.readouterr().out


def test_baseline_new_findings_still_fail(tmp_path, capsys):
    bad = _seed_violation(tmp_path)
    baseline = tmp_path / "baseline.json"
    floxlint_main([str(bad), "--baseline", str(baseline), "--update-baseline"])
    bad.write_text(
        bad.read_text() + "\ndef g(x):\n    return x.astype('float16')\n"
    )
    capsys.readouterr()
    rc = floxlint_main(
        [str(bad), "--baseline", str(baseline), "--format", "json"]
    )
    out = capsys.readouterr().out
    assert rc == 1
    payload = json.loads(out)
    # only the NEW finding is reported; the baselined one stays absorbed
    assert payload["finding_count"] == 1
    assert payload["findings"][0]["rule"] == "FLX003"


def test_baseline_drift_fails(tmp_path, capsys):
    # stale suppressions — entries whose finding was fixed — fail the gate
    bad = _seed_violation(tmp_path)
    baseline = tmp_path / "baseline.json"
    floxlint_main([str(bad), "--baseline", str(baseline), "--update-baseline"])
    bad.write_text("def f(x):\n    return x\n")  # hazard fixed, entry now stale
    capsys.readouterr()
    rc = floxlint_main([str(bad), "--baseline", str(baseline)])
    captured = capsys.readouterr()
    assert rc == 1
    assert "baseline drift" in captured.err
    assert "FLX003" in captured.err


def test_baseline_is_line_number_stable(tmp_path):
    # shifting a baselined finding down a file must not invalidate the entry
    bad = _seed_violation(tmp_path)
    baseline = tmp_path / "baseline.json"
    floxlint_main([str(bad), "--baseline", str(baseline), "--update-baseline"])
    bad.write_text("# a new leading comment\n# another\n" + bad.read_text())
    assert floxlint_main([str(bad), "--baseline", str(baseline)]) == 0


def test_baseline_partially_fixed_entry_is_drift(tmp_path, capsys):
    # an entry with count=2 where only one occurrence still fires leaves a
    # silent absorption budget for a reintroduced finding — the baseline
    # can only shrink, so the surplus is drift
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax.numpy as jnp\n\n"
        "def f(x):\n"
        "    return x.astype(jnp.bfloat16)\n"
        "def g(x):\n"
        "    return x.astype(jnp.bfloat16)\n"
    )
    baseline = tmp_path / "baseline.json"
    floxlint_main([str(bad), "--baseline", str(baseline), "--update-baseline"])
    assert json.loads(baseline.read_text())["findings"][0]["count"] == 2
    # fix ONE of the two occurrences
    bad.write_text(
        "import jax.numpy as jnp\n\n"
        "def f(x):\n"
        "    return x.astype(jnp.bfloat16)\n"
        "def g(x):\n"
        "    return x\n"
    )
    capsys.readouterr()
    rc = floxlint_main([str(bad), "--baseline", str(baseline)])
    captured = capsys.readouterr()
    assert rc == 1 and "baseline drift" in captured.err


def test_baseline_stable_for_interprocedural_rules(tmp_path):
    # FLX009/FLX011 messages must not embed line numbers, or the
    # line-number-free fingerprint promise breaks for exactly the rules the
    # baseline exists to stage in
    bad = tmp_path / "regress_helper_sync.py"
    bad.write_text(
        "import jax\n"
        "import jax.numpy as jnp\n\n"
        "def _snapshot(arr):\n"
        "    return arr.item()\n\n"
        "@jax.jit\n"
        "def step(state, slab):\n"
        "    return state + _snapshot(slab)\n"
    )
    baseline = tmp_path / "baseline.json"
    floxlint_main([str(bad), "--baseline", str(baseline), "--update-baseline"])
    bad.write_text("# shifted\n# down\n" + bad.read_text())
    assert floxlint_main([str(bad), "--baseline", str(baseline)]) == 0


def test_update_baseline_requires_baseline_path():
    assert floxlint_main(["--update-baseline", str(FIXTURES)]) == 2


def test_shipped_baseline_is_empty_and_tree_is_clean():
    # the repo ships a clean tree: the gate's baseline must stay empty (the
    # baseline can only shrink — see docs), and check mode must exit 0
    payload = json.loads((REPO / "tools" / "floxlint" / "baseline.json").read_text())
    assert payload["findings"] == []


# ---------------------------------------------------------------------------
# autofix (--fix)
# ---------------------------------------------------------------------------


def test_fix_flx007_fixture_relints_clean_and_is_byte_stable(tmp_path, capsys):
    # ISSUE 5 acceptance: --fix on the FLX007 fixture produces output that
    # re-lints clean and is byte-stable on a second pass
    import shutil

    target = tmp_path / "flx007_eager_logging.py"
    shutil.copy(FIXTURES / "flx007_eager_logging.py", target)
    rc = floxlint_main([str(target), "--fix"])
    capsys.readouterr()
    assert rc == 0  # everything in this fixture is mechanically fixable
    fixed_once = target.read_text()
    assert lint_paths([target]) == []
    assert "logger.debug('ngroups=%s', ngroups)" in fixed_once
    assert "logger.log(level, 'slabs=%s', n)" in fixed_once
    assert "log.error('cannot read %s', path)" in fixed_once
    # the clean non-logger shape keeps its f-string (not a logging call)
    assert 'tracer.debug(f"x={x}")' in fixed_once
    rc2 = floxlint_main([str(target), "--fix"])
    capsys.readouterr()
    assert rc2 == 0
    assert target.read_text() == fixed_once  # byte-stable second pass


def test_fix_flx004_rewrites_to_compat_spellings(tmp_path, capsys):
    import shutil

    target = tmp_path / "flx004_version_gated.py"
    shutil.copy(FIXTURES / "flx004_version_gated.py", target)
    floxlint_main([str(target), "--fix"])
    capsys.readouterr()
    fixed = target.read_text()
    assert "jax.tree.map(lambda x: x + 1, tree)" in fixed
    assert "from flox_tpu.parallel.mesh import axis_size, shard_map" in fixed
    assert "jax.lax.axis_size" not in fixed
    # the structural ImportFrom violation has no mechanical fix and remains
    remaining = [f for f in lint_paths([target]) if f.rule == "FLX004"]
    assert len(remaining) == 1 and remaining[0].line == 4
    # second pass: nothing left to fix, bytes stable
    floxlint_main([str(target), "--fix"])
    capsys.readouterr()
    assert target.read_text() == fixed


def test_fix_adds_missing_shim_name_to_partial_import(tmp_path, capsys):
    # a pre-existing mesh-shim import must not suppress the insert a NEW
    # bare name still needs (per-name check, not a substring check)
    p = tmp_path / "partial.py"
    p.write_text(
        "import jax\n"
        "from flox_tpu.parallel.mesh import shard_map\n\n"
        "def f(axes):\n"
        "    return jax.lax.axis_size(axes[0])\n"
    )
    floxlint_main([str(p), "--fix"])
    capsys.readouterr()
    fixed = p.read_text()
    assert "return axis_size(axes[0])" in fixed
    assert "from flox_tpu.parallel.mesh import axis_size" in fixed
    compile(fixed, str(p), "exec")  # the rewritten module must stay valid


def test_fix_preserves_format_spec_fstrings(tmp_path, capsys):
    # f"{x:.3f}" carries load-bearing formatting %s would lose — not fixed
    p = tmp_path / "spec.py"
    src = (
        "import logging\n\n"
        "logger = logging.getLogger('flox_tpu.x')\n\n"
        "def f(ms):\n"
        "    logger.debug(f'took {ms:.3f} ms')\n"
    )
    p.write_text(src)
    floxlint_main([str(p), "--fix"])
    capsys.readouterr()
    assert p.read_text() == src  # untouched (still a finding, but not broken)


def test_fix_skips_suppressed_lines(tmp_path, capsys):
    p = tmp_path / "sup.py"
    src = (
        "import logging\n\n"
        "logger = logging.getLogger('flox_tpu.x')\n\n"
        "def f(n):\n"
        "    logger.debug(f'n={n}')  # floxlint: disable=FLX007\n"
    )
    p.write_text(src)
    assert floxlint_main([str(p), "--fix"]) == 0
    capsys.readouterr()
    assert p.read_text() == src


# ---------------------------------------------------------------------------
# docs drift: the rule tables must list exactly the registry
# ---------------------------------------------------------------------------


def test_implementation_md_rule_table_matches_registry():
    # ISSUE 5 satellite: the docs table and the registry cannot drift
    text = (REPO / "docs" / "implementation.md").read_text()
    section = text.split("## Static analysis")[1]
    table_ids = set(re.findall(r"^\|\s*(FLX\d{3})", section, re.MULTILINE))
    assert table_ids == set(RULES), (
        f"docs/implementation.md rule table drifted: "
        f"missing {set(RULES) - table_ids}, extra {table_ids - set(RULES)}"
    )


def test_readme_lint_section_matches_registry():
    text = (REPO / "README.md").read_text()
    section = text.split("## Lint gate")[1].split("\n## ")[0]
    readme_ids = {m for m in re.findall(r"FLX\d{3}", section)}
    assert readme_ids == set(RULES), (
        f"README lint-gate section drifted: "
        f"missing {set(RULES) - readme_ids}, extra {readme_ids - set(RULES)}"
    )


# ---------------------------------------------------------------------------
# get_rules select/ignore edge cases (ISSUE 5 satellite)
# ---------------------------------------------------------------------------


def test_get_rules_lowercase_ids():
    rules = get_rules(select=["flx003"])
    assert [r.id for r in rules] == ["FLX003"]
    rules = get_rules(ignore=["flx003"])
    assert "FLX003" not in {r.id for r in rules}


def test_get_rules_unknown_select_raises():
    with pytest.raises(KeyError, match="FLX999"):
        get_rules(select=["FLX999"])
    with pytest.raises(KeyError, match="flx000"):
        get_rules(select=["FLX003", "flx000"])


def test_get_rules_unknown_ignore_is_silent():
    # ignoring a rule that does not exist is a no-op, not an error (the id
    # may belong to a newer floxlint; --ignore must stay forward-compatible)
    assert {r.id for r in get_rules(ignore=["FLX999"])} == set(RULES)


def test_get_rules_select_ignore_overlap_is_empty():
    assert get_rules(select=["FLX003"], ignore=["flx003"]) == []


def test_get_rules_duplicate_select_dedupes():
    rules = get_rules(select=["FLX003", "flx003", "FLX003"])
    assert [r.id for r in rules] == ["FLX003"]


# ---------------------------------------------------------------------------
# suppression-index behavior on multi-finding lines + noqa alias
# ---------------------------------------------------------------------------


def test_multi_rule_line_disable_both(tmp_path):
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n\n"
        "def f(x):\n"
        "    return jax.shard_map, x.astype(jnp.bfloat16)  # floxlint: disable=FLX003,FLX004\n"
    )
    p = tmp_path / "multi_both.py"
    p.write_text(src)
    assert lint_file(p) == []


def test_multi_rule_line_disable_one_keeps_other(tmp_path):
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n\n"
        "def f(x):\n"
        "    return jax.shard_map, x.astype(jnp.bfloat16)  # floxlint: disable=FLX004\n"
    )
    p = tmp_path / "multi_one.py"
    p.write_text(src)
    assert [f.rule for f in lint_file(p)] == ["FLX003"]


def test_noqa_alias_suppresses(tmp_path):
    src = (
        "import jax.numpy as jnp\n\n"
        "def f(x):\n"
        "    return x.astype(jnp.bfloat16)  # noqa: FLX003\n"
    )
    p = tmp_path / "noqa_ok.py"
    p.write_text(src)
    assert lint_file(p) == []


def test_noqa_multi_ids_on_multi_finding_line(tmp_path):
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n\n"
        "def f(x):\n"
        "    return jax.shard_map, x.astype(jnp.bfloat16)  # noqa: FLX003, FLX004\n"
    )
    p = tmp_path / "noqa_multi.py"
    p.write_text(src)
    assert lint_file(p) == []


def test_bare_noqa_does_not_suppress(tmp_path):
    # ruff-style bare `# noqa` (or foreign codes) must NOT silence floxlint:
    # floxlint suppressions are always rule-scoped
    src = (
        "import jax.numpy as jnp\n\n"
        "def f(x):\n"
        "    return x.astype(jnp.bfloat16)  # noqa\n"
        "def g(x):\n"
        "    return x.astype(jnp.bfloat16)  # noqa: E501\n"
    )
    p = tmp_path / "noqa_bare.py"
    p.write_text(src)
    assert [f.rule for f in lint_file(p)] == ["FLX003", "FLX003"]


# ---------------------------------------------------------------------------
# project-index cache (--index-cache)
# ---------------------------------------------------------------------------


def test_index_cache_roundtrip(tmp_path, capsys):
    from tools.floxlint.index import ProjectIndex, load_cached

    pkg = tmp_path / "cachedpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text("def f():\n    return 1\n")
    cache = tmp_path / "index.pickle"
    rc = floxlint_main([str(pkg), "--index-cache", str(cache)])
    capsys.readouterr()
    assert rc == 0 and cache.exists()
    files = sorted(pkg.rglob("*.py"))
    restored = load_cached(cache, files, pkg)
    assert isinstance(restored, ProjectIndex)
    assert "cachedpkg.mod" in restored.modules
    # an edit invalidates the fingerprint -> cache miss, not stale reuse
    (pkg / "mod.py").write_text("def f():\n    return 2\n")
    assert load_cached(cache, sorted(pkg.rglob("*.py")), pkg) is None
    # and the CLI transparently rebuilds + re-saves
    rc = floxlint_main([str(pkg), "--index-cache", str(cache)])
    capsys.readouterr()
    assert rc == 0
    assert load_cached(cache, sorted(pkg.rglob("*.py")), pkg) is not None


def test_index_resolves_reexport_chain(tmp_path):
    # the symbol table follows `from x import y as z` through a package
    # __init__ re-export to the defining module
    from tools.floxlint.index import ProjectIndex

    pkg = tmp_path / "chainpkg"
    (pkg / "sub").mkdir(parents=True)
    (pkg / "__init__.py").write_text("from .sub import helper as h\n")
    (pkg / "sub" / "__init__.py").write_text("from .impl import helper\n")
    (pkg / "sub" / "impl.py").write_text("def helper():\n    return 1\n")
    (pkg / "user.py").write_text("from chainpkg import h\n\ndef g():\n    return h()\n")
    files = sorted(pkg.rglob("*.py"))
    index = ProjectIndex.build(files, pkg)
    assert (
        index.resolve_symbol("chainpkg.user", "h") == "chainpkg.sub.impl.helper"
    )


def test_callgraph_edges(tmp_path):
    from tools.floxlint.callgraph import CallGraph
    from tools.floxlint.index import ProjectIndex

    p = tmp_path / "graphmod.py"
    p.write_text(
        "def a():\n    return b() + 1\n\n"
        "def b():\n    return c()\n\n"
        "def c():\n    return 0\n"
    )
    index = ProjectIndex.build([p], tmp_path)
    graph = CallGraph.build(index)
    assert graph.callees("graphmod.a") == {"graphmod.b"}
    assert graph.reachable("graphmod.a") == {"graphmod.b", "graphmod.c"}
    assert graph.reachable("graphmod.a", max_depth=1) == {"graphmod.b"}


# ---------------------------------------------------------------------------
# floxlint v3: concurrency & effect analysis (FLX013-FLX016, ISSUE 16)
# ---------------------------------------------------------------------------


def _pkg_findings(pkg, rule):
    return [f for f in lint_paths([pkg]) if f.rule == rule]


def _write_pkg(tmp_path, files):
    pkg = tmp_path / "conpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    for name, text in files.items():
        (pkg / name).write_text(text)
    return pkg


@pytest.mark.parametrize(
    "pkg,anchor",
    [("flx013_pkg", "state.py"), ("flx014_pkg", "order.py"),
     ("flx015_pkg", "loop.py"), ("flx016_pkg", "handlers.py")],
)
def test_concurrency_package_fixtures(pkg, anchor):
    root = FIXTURES / pkg
    expected = set()
    for f in root.rglob("*.py"):
        expected |= expected_findings(f)
    assert expected  # each package seeds at least one violation
    actual = {(f.rule, Path(f.path).name, f.line) for f in lint_paths([root])}
    want = set()
    for f in root.rglob("*.py"):
        for rule, line in expected_findings(f):
            want.add((rule, f.name, line))
    assert actual == want


def test_flx013_unlocked_set_ready_reintroduction_fails(tmp_path):
    # the exposition.set_ready bug this PR fixed: the readiness flag
    # written lock-free while the scrape-thread writers hold _STATE_LOCK
    pkg = _write_pkg(tmp_path, {"expo.py": (
        "import threading\n\n"
        "_SERVER_STATE = {'ready': False}\n"
        "_STATE_LOCK = threading.Lock()\n\n\n"
        "def set_ready(flag):\n"
        "    _SERVER_STATE['ready'] = flag\n\n\n"
        "def stop():\n"
        "    with _STATE_LOCK:\n"
        "        _SERVER_STATE['ready'] = False\n\n\n"
        "def start():\n"
        "    with _STATE_LOCK:\n"
        "        _SERVER_STATE['ready'] = True\n"
        "    threading.Thread(target=set_ready, args=(True,), daemon=True).start()\n"
    )})
    findings = _pkg_findings(pkg, "FLX013")
    assert len(findings) == 1
    assert "_SERVER_STATE" in findings[0].message
    assert "_STATE_LOCK" in findings[0].message
    # taking the lock clears it (the shipped fix)
    (pkg / "expo.py").write_text((pkg / "expo.py").read_text().replace(
        "def set_ready(flag):\n    _SERVER_STATE['ready'] = flag",
        "def set_ready(flag):\n    with _STATE_LOCK:\n"
        "        _SERVER_STATE['ready'] = flag",
    ))
    assert not _pkg_findings(pkg, "FLX013")


def test_flx013_minority_lock_is_not_the_discipline(tmp_path):
    # the fusion/mapreduce precision case: one caller holding a recovery
    # guard around a cache clear must not make the guard the cache's
    # "discipline" and flag every other (loop-confined) writer
    pkg = _write_pkg(tmp_path, {"cachemod.py": (
        "import threading\n\n"
        "_CACHE: dict = {}\n"
        "_GUARD = threading.Lock()\n\n\n"
        "def put(k, v):\n"
        "    _CACHE[k] = v\n\n\n"
        "def put2(k, v):\n"
        "    _CACHE[k] = v\n\n\n"
        "def evict(k):\n"
        "    del _CACHE[k]\n\n\n"
        "def recover():\n"
        "    with _GUARD:\n"
        "        _CACHE.clear()\n\n\n"
        "def spawn():\n"
        "    threading.Thread(target=put, args=(1, 2)).start()\n"
    )})
    assert not _pkg_findings(pkg, "FLX013")


def test_flx013_tie_between_candidate_locks_skips(tmp_path):
    pkg = _write_pkg(tmp_path, {"tied.py": (
        "import threading\n\n"
        "_D: dict = {}\n"
        "_A = threading.Lock()\n"
        "_B = threading.Lock()\n\n\n"
        "def wa():\n"
        "    with _A:\n"
        "        _D['a'] = 1\n\n\n"
        "def wb():\n"
        "    with _B:\n"
        "        _D['b'] = 1\n\n\n"
        "def free():\n"
        "    _D['c'] = 1\n\n\n"
        "def spawn():\n"
        "    threading.Thread(target=free).start()\n"
    )})
    assert not _pkg_findings(pkg, "FLX013")


def test_flx013_signal_reachable_write_fires(tmp_path):
    pkg = _write_pkg(tmp_path, {"sig.py": (
        "import signal\n"
        "import threading\n\n"
        "_S: dict = {}\n"
        "_L = threading.Lock()\n\n\n"
        "def _on_term(signum, frame):\n"
        "    _S['dumped'] = True\n\n\n"
        "def locked():\n"
        "    with _L:\n"
        "        _S['x'] = 1\n\n\n"
        "def locked2():\n"
        "    with _L:\n"
        "        _S['y'] = 1\n\n\n"
        "def install():\n"
        "    signal.signal(signal.SIGTERM, _on_term)\n"
    )})
    findings = _pkg_findings(pkg, "FLX013")
    assert len(findings) == 1
    assert "signal" in findings[0].message


def test_flx014_multi_item_with_inversion(tmp_path):
    # `with a, b:` against `with b, a:` is the same inversion as nesting
    pkg = _write_pkg(tmp_path, {"multi.py": (
        "import threading\n\n"
        "_A = threading.Lock()\n"
        "_B = threading.Lock()\n\n\n"
        "def fwd():\n"
        "    with _A, _B:\n"
        "        pass\n\n\n"
        "def rev():\n"
        "    with _B, _A:\n"
        "        pass\n"
    )})
    findings = _pkg_findings(pkg, "FLX014")
    assert len(findings) == 1
    assert "_A" in findings[0].message and "_B" in findings[0].message


def test_flx014_async_with_inversion(tmp_path):
    pkg = _write_pkg(tmp_path, {"amod.py": (
        "import asyncio\n\n"
        "_A = asyncio.Lock()\n"
        "_B = asyncio.Lock()\n\n\n"
        "async def fwd():\n"
        "    async with _A:\n"
        "        async with _B:\n"
        "            pass\n\n\n"
        "async def rev():\n"
        "    async with _B:\n"
        "        async with _A:\n"
        "            pass\n"
    )})
    assert len(_pkg_findings(pkg, "FLX014")) == 1


def test_flx014_parameter_lock_does_not_cross_fire(tmp_path):
    # a helper acquiring its lock parameter is one lock identity per
    # function, not an alias of every caller's lock — two callers holding
    # different locks around the same helper is NOT an inversion
    pkg = _write_pkg(tmp_path, {"param.py": (
        "import threading\n\n"
        "_A = threading.Lock()\n"
        "_B = threading.Lock()\n\n\n"
        "def helper(lock):\n"
        "    with lock:\n"
        "        pass\n\n\n"
        "def via_a():\n"
        "    with _A:\n"
        "        helper(_B)\n\n\n"
        "def via_b():\n"
        "    with _B:\n"
        "        helper(_A)\n"
    )})
    assert not _pkg_findings(pkg, "FLX014")


def test_flx015_spawn_boundaries_end_reachability(tmp_path):
    # to_thread with a functools.partial target and run_in_executor both
    # move the callee off the loop: no finding on either path
    pkg = _write_pkg(tmp_path, {"offload.py": (
        "import asyncio\n"
        "import functools\n\n\n"
        "def dump(path):\n"
        "    with open(path, 'w') as fh:\n"
        "        fh.write('x')\n\n\n"
        "async def via_partial():\n"
        "    await asyncio.to_thread(functools.partial(dump, '/tmp/p'))\n\n\n"
        "async def via_executor():\n"
        "    loop = asyncio.get_running_loop()\n"
        "    await loop.run_in_executor(None, dump, '/tmp/q')\n"
    )})
    assert not _pkg_findings(pkg, "FLX015")


def test_flx015_nested_coroutine_reported_once(tmp_path):
    # the blocking site inside the inner coroutine is the inner root's
    # finding; the outer awaiting it must not duplicate it
    pkg = _write_pkg(tmp_path, {"nested.py": (
        "import time\n\n\n"
        "async def inner():\n"
        "    time.sleep(0.1)\n\n\n"
        "async def outer():\n"
        "    await inner()\n"
    )})
    findings = _pkg_findings(pkg, "FLX015")
    assert len(findings) == 1
    assert "inner" in findings[0].message


def test_flx015_async_flight_dump_reintroduction_fails(tmp_path):
    # the dispatcher/drain bug this PR fixed: a file-writing dump called
    # directly from a coroutine stalls every in-flight request behind disk
    pkg = _write_pkg(tmp_path, {"srv.py": (
        "import asyncio\n\n\n"
        "def flight_dump(reason):\n"
        "    with open('/tmp/dump', 'w') as fh:\n"
        "        fh.write(reason)\n\n\n"
        "async def drain():\n"
        "    flight_dump('drain')\n"
    )})
    findings = _pkg_findings(pkg, "FLX015")
    assert len(findings) == 1
    assert "file-io" in findings[0].message
    # offloading it (the shipped fix) clears the finding
    (pkg / "srv.py").write_text((pkg / "srv.py").read_text().replace(
        "    flight_dump('drain')",
        "    await asyncio.to_thread(flight_dump, 'drain')",
    ))
    assert not _pkg_findings(pkg, "FLX015")


def test_flx016_blocking_queue_in_handler_fires(tmp_path):
    pkg = _write_pkg(tmp_path, {"h.py": (
        "import queue\n"
        "import signal\n\n"
        "_Q: queue.Queue = queue.Queue()\n\n\n"
        "def _on_usr1(signum, frame):\n"
        "    _Q.get(timeout=1)\n\n\n"
        "def install():\n"
        "    signal.signal(signal.SIGUSR1, _on_usr1)\n"
    )})
    findings = _pkg_findings(pkg, "FLX016")
    assert len(findings) == 1
    assert "_on_usr1" in findings[0].message


def test_flx016_rlock_and_thread_handoff_are_clean(tmp_path):
    pkg = _write_pkg(tmp_path, {"ok.py": (
        "import signal\n"
        "import threading\n\n"
        "_R = threading.RLock()\n"
        "_S: dict = {}\n\n\n"
        "def _flush():\n"
        "    with _R:\n"
        "        _S['x'] = 1\n\n\n"
        "def _on_term(signum, frame):\n"
        "    _flush()\n\n\n"
        "def _on_usr2(signum, frame):\n"
        "    threading.Thread(target=_flush, daemon=True).start()\n\n\n"
        "def install():\n"
        "    signal.signal(signal.SIGTERM, _on_term)\n"
        "    signal.signal(getattr(signal, 'SIGUSR2', signal.SIGTERM), _on_usr2)\n"
    )})
    assert not _pkg_findings(pkg, "FLX016")


# -- effect-summary unit tests ----------------------------------------------


def _build_index(pkg):
    from tools.floxlint.core import iter_python_files
    from tools.floxlint.index import ProjectIndex

    groups = {}
    for f, root in iter_python_files([str(pkg)]):
        groups.setdefault(root, []).append(f)
    (root, files), = groups.items()
    return ProjectIndex.build(files, root)


def test_effects_lock_on_self_attribute(tmp_path):
    from tools.floxlint import effects as fx

    pkg = _write_pkg(tmp_path, {"cls.py": (
        "import threading\n\n\n"
        "class Registry:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.RLock()\n\n"
        "    def put(self, k, v):\n"
        "        with self._lock:\n"
        "            pass\n"
    )})
    effects = fx.compute_effects(_build_index(pkg))
    acq = effects["conpkg.cls.Registry.put"].acquisitions
    assert [a.lock for a in acq] == ["conpkg.cls.Registry._lock"]
    assert acq[0].kind == fx.RLOCK


def test_effects_multi_item_with_held_ordering(tmp_path):
    from tools.floxlint import effects as fx

    pkg = _write_pkg(tmp_path, {"held.py": (
        "import threading\n\n"
        "_A = threading.Lock()\n"
        "_B = threading.Lock()\n\n\n"
        "def both():\n"
        "    with _A, _B:\n"
        "        pass\n\n\n"
        "def pair():\n"
        "    _A.acquire()\n"
        "    _B.acquire()\n"
        "    _B.release()\n"
        "    _A.release()\n"
    )})
    effects = fx.compute_effects(_build_index(pkg))
    both = effects["conpkg.held.both"].acquisitions
    assert [(a.lock.rsplit(".", 1)[1], a.held_before) for a in both] == [
        ("_A", ()), ("_B", ("conpkg.held._A",)),
    ]
    pair = effects["conpkg.held.pair"].acquisitions
    assert [a.held_before for a in pair] == [(), ("conpkg.held._A",)]


def test_effects_blocking_taxonomy(tmp_path):
    from tools.floxlint import effects as fx

    pkg = _write_pkg(tmp_path, {"blk.py": (
        "import queue\n"
        "import subprocess\n"
        "import time\n\n"
        "_Q: queue.Queue = queue.Queue()\n\n\n"
        "def nap():\n"
        "    time.sleep(1)\n\n\n"
        "def run():\n"
        "    subprocess.run(['true'])\n\n\n"
        "def pull():\n"
        "    return _Q.get()\n\n\n"
        "def poll():\n"
        "    return _Q.get_nowait()\n"
    )})
    effects = fx.compute_effects(_build_index(pkg))

    def kinds(fn):
        return [b.kind for b in effects[f"conpkg.blk.{fn}"].blocking]

    assert kinds("nap") == [fx.SLEEP]
    assert kinds("run") == [fx.SUBPROCESS]
    assert kinds("pull") == [fx.QUEUE_OP]
    assert kinds("poll") == []  # get_nowait never blocks


# -- lock-order graph + acceptance ------------------------------------------


@pytest.fixture(scope="module")
def flox_tpu_lock_graph():
    # one model build shared by the graph-acceptance tests — the full-tree
    # analysis costs seconds and the assertions are read-only
    from tools.floxlint.concurrency import lock_graph_for_paths

    return lock_graph_for_paths([str(REPO / "flox_tpu")])


def test_lock_order_graph_over_flox_tpu_is_cycle_free(flox_tpu_lock_graph):
    # the acceptance criterion: the package's global acquisition order is
    # consistent — FLX014 stays silent AND the artifact says 0 cycles
    assert flox_tpu_lock_graph.nodes, "expected module-level locks in flox_tpu"
    assert flox_tpu_lock_graph.cycles() == []


def test_lock_graph_names_match_runtime_watcher_naming(flox_tpu_lock_graph):
    # the stress harness wraps locks as "<module>.<attr>" — the static
    # graph must use the same ids or seeding the watcher is meaningless
    assert "flox_tpu.exposition._STATE_LOCK" in flox_tpu_lock_graph.nodes
    assert "flox_tpu.telemetry._EXPORT_LOCK" in flox_tpu_lock_graph.nodes


# -- CLI: --explain / --lock-graph ------------------------------------------


@pytest.mark.parametrize("rule_id", sorted(RULES))
def test_cli_explain_every_rule(rule_id, capsys):
    rc = floxlint_main(["--explain", rule_id])
    out = capsys.readouterr().out
    assert rc == 0
    assert rule_id in out
    assert RULES[rule_id].name in out


def test_cli_explain_unknown_rule_is_usage_error(capsys):
    rc = floxlint_main(["--explain", "FLX999"])
    assert rc == 2
    assert "FLX999" in capsys.readouterr().err


def test_cli_lock_graph_json(tmp_path, capsys):
    out = tmp_path / "locks.json"
    rc = floxlint_main(["--lock-graph", str(out), str(REPO / "flox_tpu")])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert doc["version"] == 1
    assert doc["cycles"] == []
    ids = {n["id"] for n in doc["nodes"]}
    assert "flox_tpu.exposition._STATE_LOCK" in ids
    assert "0 cycle(s)" in capsys.readouterr().err


def test_cli_lock_graph_dot(tmp_path):
    # format coverage only, so the small fixture package keeps it cheap
    out = tmp_path / "locks.dot"
    rc = floxlint_main(["--lock-graph", str(out), str(FIXTURES / "flx014_pkg")])
    assert rc == 0
    text = out.read_text()
    assert text.startswith("digraph lock_order")
    assert "flx014_pkg.order._A" in text


def test_cli_lock_graph_stdout(capsys):
    rc = floxlint_main(["--lock-graph", "-", str(FIXTURES / "flx014_pkg")])
    captured = capsys.readouterr()
    assert rc == 0
    doc = json.loads(captured.out)
    assert doc["version"] == 1
    assert doc["cycles"]  # the fixture package seeds an inversion


def test_cli_lock_graph_requires_paths(capsys):
    rc = floxlint_main(["--lock-graph", "out.json"])
    assert rc == 2
    assert "needs paths" in capsys.readouterr().err


# -- the shipped serve-plane fixes stay fixed --------------------------------


def test_async_flight_dump_call_sites_are_offloaded():
    # dispatcher watchdog/device-loss and the drain path write the flight
    # record through asyncio.to_thread — a bare call from a coroutine
    # reintroduces the loop stall (and FLX015 would flag it again)
    import ast as _ast

    for rel in ("flox_tpu/serve/dispatcher.py", "flox_tpu/serve/__main__.py"):
        tree = _ast.parse((REPO / rel).read_text())
        for node in _ast.walk(tree):
            if not isinstance(node, _ast.AsyncFunctionDef):
                continue
            for call in _ast.walk(node):
                if not isinstance(call, _ast.Call):
                    continue
                fn = call.func
                assert not (
                    isinstance(fn, _ast.Attribute)
                    and fn.attr == "flight_dump"
                ), f"bare flight_dump call in coroutine at {rel}:{call.lineno}"


# ---------------------------------------------------------------------------
# contract compiler (floxlint v4): fixtures, schema, determinism, CLI
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "pkg", ["flx017_pkg", "flx018_pkg", "flx019_pkg", "flx020_pkg"]
)
def test_contract_rule_package_fixtures(pkg):
    # exact (rule, line, file) agreement per package — positive cases AND
    # the seeded exemptions (narrow catches, _error_response spreads, the
    # correctly-resolved consumer names) must stay silent
    root = FIXTURES / pkg
    expected: set[tuple[str, int, str]] = set()
    for path in root.rglob("*.py"):
        for rule, line in expected_findings(path):
            expected.add((rule, line, path.name))
    assert expected, f"{pkg} seeds no violations"
    actual = {
        (f.rule, f.line, Path(f.path).name) for f in lint_paths([root])
    }
    assert actual == expected


def test_contract_schema_validates_and_is_deterministic():
    from tools.floxlint.contract import (
        contract_for_paths, render_contract, validate_contract,
    )

    doc = contract_for_paths([str(REPO / "flox_tpu")])
    assert validate_contract(doc) == []
    # byte-identical across two independent builds (CI diffs the artifact
    # between commits; nondeterminism would make every diff noise)
    again = contract_for_paths([str(REPO / "flox_tpu")])
    assert render_contract(doc) == render_contract(again)
    # round-trip: the rendered artifact re-validates after JSON parsing
    assert validate_contract(json.loads(render_contract(doc))) == []


def test_contract_covers_documented_surface():
    # acceptance: the artifact covers every documented serve op, error
    # code, endpoint, and knob — and the docs tables cover the artifact
    from tools.floxlint.contract import (
        cell_tokens, contract_for_paths, parse_contract_tables,
    )

    doc = contract_for_paths([str(REPO / "flox_tpu")])
    tables = parse_contract_tables((REPO / "docs" / "serving.md").read_text())

    def first_column(section):
        return {
            tok
            for row in tables[section]
            for tok in cell_tokens(next(iter(row.values())))
        }

    assert first_column("ops") == set(doc["ops"])
    assert first_column("errors") == set(doc["errors"])
    code_paths = {p for paths in doc["endpoints"].values() for p in paths}
    assert first_column("endpoints") == code_paths
    documented_metrics = {
        tok.partition("|")[0] for tok in first_column("metrics")
    }
    assert documented_metrics <= set(doc["metrics"])
    # knobs mirror the runtime OPTIONS table exactly (plain import — a
    # sys.modules re-import here would fork the process-wide OPTIONS
    # table out from under every already-imported flox_tpu module)
    from flox_tpu import options as _options

    assert set(doc["knobs"]) == set(_options.OPTIONS)
    for knob, entry in doc["knobs"].items():
        assert entry["env"].startswith("FLOX_TPU_"), knob


def test_cli_contract_artifact(tmp_path, capsys):
    from tools.floxlint.contract import CONTRACT_VERSION

    out = tmp_path / "contract.json"
    rc = floxlint_main(["--contract", str(out), str(REPO / "flox_tpu")])
    err = capsys.readouterr().err
    assert rc == 0, err
    data = json.loads(out.read_text())
    assert data["contract_version"] == CONTRACT_VERSION
    assert data["generated_by"]["tool"] == "floxlint"
    assert "reduce" in data["ops"]
    assert "load_shed" in data["errors"]
    assert "contract:" in err  # the stderr summary line


def test_cli_contract_stdout(capsys):
    rc = floxlint_main(["--contract", "-", str(REPO / "flox_tpu")])
    captured = capsys.readouterr()
    assert rc == 0
    data = json.loads(captured.out)
    assert set(data) >= {"ops", "errors", "endpoints", "metrics", "knobs"}


def test_contract_metric_names_constants_resolve():
    # the shared consumer-surface module: every constant must name an
    # emitted metric, and prom_name must match the exposition folding
    from tools.floxlint.contract import contract_for_paths
    from flox_tpu import metric_names

    doc = contract_for_paths([str(REPO / "flox_tpu")])
    constants = {
        v for k, v in vars(metric_names).items()
        if k.isupper() and isinstance(v, str)
    }
    unresolved = constants - set(doc["metrics"])
    assert not unresolved, f"metric_names constants with no emit: {unresolved}"
    assert metric_names.prom_name("serve.request_ms") == (
        "flox_tpu_serve_request_ms"
    )
    assert metric_names.prom_name("serve.requests", counter=True) == (
        "flox_tpu_serve_requests_total"
    )
