"""Self-tests for tools/floxlint: every rule against the fixture corpus, the
clean-package gate, suppression comments, CLI exit codes and JSON output.

The fixture contract: each seeded violation line carries a trailing
``# expect: FLXnnn`` marker; a fixture file's expected finding set is exactly
its markers (so new false positives in a rule fail these tests too).
"""

from __future__ import annotations

import json
import re
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tools" / "floxlint" / "fixtures"

sys.path.insert(0, str(REPO))

from tools.floxlint import RULES, get_rules, lint_file, lint_paths  # noqa: E402
from tools.floxlint.cli import main as floxlint_main  # noqa: E402

_EXPECT_RE = re.compile(r"#\s*expect:\s*((?:FLX\d{3}[,\s]*)+)")


def expected_findings(path: Path) -> set[tuple[str, int]]:
    out: set[tuple[str, int]] = set()
    for lineno, line in enumerate(path.read_text().splitlines(), start=1):
        m = _EXPECT_RE.search(line)
        if m:
            for rule in re.findall(r"FLX\d{3}", m.group(1)):
                out.add((rule, lineno))
    return out


def actual_findings(paths) -> set[tuple[str, int]]:
    return {(f.rule, f.line) for f in lint_paths(paths)}


# ---------------------------------------------------------------------------
# fixture corpus: exact (rule, line) agreement per file
# ---------------------------------------------------------------------------

def test_fixture_corpus_is_nonempty():
    assert len(list(FIXTURES.rglob("*.py"))) >= 7


@pytest.mark.parametrize(
    "fixture",
    ["flx001_host_sync.py", "flx002_recompile_traps.py", "flx003_dtype_policy.py",
     "flx004_version_gated.py", "flx006_swallow.py", "flx007_eager_logging.py",
     "clean_module.py", "suppressed.py"],
)
def test_fixture_findings_match_markers(fixture):
    path = FIXTURES / fixture
    assert actual_findings([path]) == expected_findings(path)


def test_flx005_package_fixture():
    pkg = FIXTURES / "flx005_pkg"
    expected = expected_findings(pkg / "api.py")
    assert expected  # the fixture seeds at least one violation
    assert actual_findings([pkg]) == expected


def test_every_rule_has_fixture_coverage():
    """Each FLX rule must fire at least once across the corpus."""
    seen = {rule for rule, _ in actual_findings([FIXTURES])}
    assert seen == set(RULES), f"rules without fixture coverage: {set(RULES) - seen}"


# ---------------------------------------------------------------------------
# the package itself is clean (the lint gate this PR establishes)
# ---------------------------------------------------------------------------


def test_flox_tpu_package_is_clean():
    findings = lint_paths([REPO / "flox_tpu"])
    assert findings == [], "\n".join(f.format_human() for f in findings)


# ---------------------------------------------------------------------------
# acceptance regressions: re-introducing the fixed hazards must fail the lint
# ---------------------------------------------------------------------------


def test_bare_shard_map_reintroduction_fails(tmp_path):
    bad = tmp_path / "regress_shard_map.py"
    bad.write_text(
        "import jax\n\n"
        "def build(program, mesh, in_specs, out_specs):\n"
        "    return jax.jit(jax.shard_map(program, mesh=mesh,\n"
        "        in_specs=in_specs, out_specs=out_specs))\n"
    )
    rc = floxlint_main([str(bad)])
    assert rc == 1
    assert any(f.rule == "FLX004" for f in lint_file(bad))


def test_swallowed_retry_exception_fails(tmp_path):
    # ISSUE 3 satellite: a retry loop that swallows with a broad except —
    # neither re-raising nor routing through resilience.classify_error —
    # must fail the lint (the shape that turns a TypeError into an
    # infinitely-spinning "transient" failure)
    bad = tmp_path / "regress_retry_swallow.py"
    bad.write_text(
        "import time\n\n"
        "def fetch_with_retry(loader, s, e):\n"
        "    for attempt in range(5):\n"
        "        try:\n"
        "            return loader(s, e)\n"
        "        except Exception:\n"
        "            time.sleep(0.1)\n"
    )
    rc = floxlint_main([str(bad)])
    assert rc == 1
    assert any(f.rule == "FLX006" for f in lint_file(bad))
    # the sanctioned shape — classify, re-raise the non-transient — is clean
    good = tmp_path / "clean_retry.py"
    good.write_text(
        "from flox_tpu.resilience import classify_error\n\n"
        "def fetch_with_retry(loader, s, e):\n"
        "    for attempt in range(5):\n"
        "        try:\n"
        "            return loader(s, e)\n"
        "        except Exception as exc:\n"
        "            if classify_error(exc) != 'transient':\n"
        "                raise\n"
    )
    assert not [f for f in lint_file(good) if f.rule == "FLX006"]


def test_eager_logging_reintroduction_fails(tmp_path):
    # ISSUE 4 satellite: hot-path logging that formats eagerly (f-string)
    # or prints straight to stdout must fail the lint; the lazy %-style
    # spelling and CLI-surface prints stay clean
    bad = tmp_path / "regress_eager_log.py"
    bad.write_text(
        "import logging\n\n"
        "logger = logging.getLogger('flox_tpu.regress')\n\n"
        "def hot_path(ngroups, result):\n"
        "    logger.debug(f'ngroups={ngroups}')\n"
        "    print(result)\n"
    )
    rc = floxlint_main([str(bad)])
    assert rc == 1
    assert sum(f.rule == "FLX007" for f in lint_file(bad)) == 2
    good = tmp_path / "clean_log.py"
    good.write_text(
        "import logging\n\n"
        "logger = logging.getLogger('flox_tpu.regress')\n\n"
        "def hot_path(ngroups):\n"
        "    logger.debug('ngroups=%d', ngroups)\n\n"
        "def main():\n"
        "    print('cli output is fine here')\n\n"
        "if __name__ == '__main__':\n"
        "    main()\n"
    )
    assert not [f for f in lint_file(good) if f.rule == "FLX007"]


def test_streaming_step_closure_host_sync_fails(tmp_path):
    # the donation-debugging hazard (ISSUE 2): a host-sync on a traced
    # value inside a streaming step closure — built by a factory, handed
    # to jax.jit with a donated carry — must keep firing FLX001
    bad = tmp_path / "regress_stream_step.py"
    bad.write_text(
        "import jax\n"
        "import jax.numpy as jnp\n\n"
        "def build_step(size):\n"
        "    def step(state, slab, codes):\n"
        "        if bool(jnp.any(jnp.isnan(slab))):\n"
        "            return state\n"
        "        return state + jnp.sum(slab)\n"
        "    return jax.jit(step, donate_argnums=(0,))\n"
    )
    rc = floxlint_main([str(bad)])
    assert rc == 1
    assert any(f.rule == "FLX001" for f in lint_file(bad))


def test_bf16_combine_accumulator_reintroduction_fails(tmp_path):
    bad = tmp_path / "regress_bf16.py"
    bad.write_text(
        "import jax.numpy as jnp\n\n"
        "def combine(partial, size):\n"
        "    acc = jnp.zeros((size,), dtype=jnp.bfloat16)\n"
        "    return acc + partial\n"
    )
    rc = floxlint_main([str(bad)])
    assert rc == 1
    assert any(f.rule == "FLX003" for f in lint_file(bad))


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------


def test_line_suppression(tmp_path):
    src = (
        "import jax.numpy as jnp\n\n"
        "def f(x):\n"
        "    return x.astype(jnp.bfloat16)  # floxlint: disable=FLX003\n"
    )
    p = tmp_path / "sup_line.py"
    p.write_text(src)
    assert lint_file(p) == []


def test_file_suppression(tmp_path):
    src = (
        "# floxlint: disable-file=FLX003\n"
        "import jax.numpy as jnp\n\n"
        "def f(x):\n"
        "    return x.astype(jnp.bfloat16)\n"
        "def g(x):\n"
        "    return x.astype('float16')\n"
    )
    p = tmp_path / "sup_file.py"
    p.write_text(src)
    assert lint_file(p) == []


def test_suppression_is_rule_scoped(tmp_path):
    # disabling FLX003 must not silence FLX004 on the same line
    src = (
        "import jax\n\n"
        "def f():\n"
        "    return jax.shard_map  # floxlint: disable=FLX003\n"
    )
    p = tmp_path / "sup_scoped.py"
    p.write_text(src)
    assert [f.rule for f in lint_file(p)] == ["FLX004"]


def test_disable_all(tmp_path):
    src = (
        "import jax\n\n"
        "def f():\n"
        "    return jax.shard_map  # floxlint: disable=all\n"
    )
    p = tmp_path / "sup_all.py"
    p.write_text(src)
    assert lint_file(p) == []


# ---------------------------------------------------------------------------
# CLI behavior
# ---------------------------------------------------------------------------


def test_cli_exit_zero_on_clean_package():
    assert floxlint_main([str(REPO / "flox_tpu")]) == 0


def test_cli_exit_one_on_fixtures():
    assert floxlint_main([str(FIXTURES)]) == 1


def test_cli_json_output(capsys):
    rc = floxlint_main(["--format", "json", str(FIXTURES / "flx003_dtype_policy.py")])
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["finding_count"] == len(payload["findings"]) > 0
    assert set(payload["findings_by_rule"]) == {"FLX003"}
    first = payload["findings"][0]
    assert {"path", "line", "col", "rule", "message"} <= set(first)


def test_cli_select_and_ignore():
    only_3 = {
        f.rule for f in lint_paths([FIXTURES], get_rules(select=["FLX003"]))
    }
    assert only_3 == {"FLX003"}
    without_3 = {
        f.rule for f in lint_paths([FIXTURES], get_rules(ignore=["FLX003"]))
    }
    assert "FLX003" not in without_3 and without_3


def test_cli_unknown_rule_is_usage_error(capsys):
    assert floxlint_main(["--select", "FLX999", str(FIXTURES)]) == 2
    assert "unknown rule" in capsys.readouterr().err


def test_cli_missing_path_is_usage_error():
    assert floxlint_main([]) == 2
    assert floxlint_main(["/nonexistent/die9ahPh"]) == 2


def test_syntax_error_reported_as_finding(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    findings = lint_file(p)
    assert [f.rule for f in findings] == ["FLX000"]
