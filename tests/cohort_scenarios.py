"""The reference's cohort-detection snapshot scenarios, reproduced exactly.

Each function mirrors one setup from
/root/reference/asv_bench/benchmarks/cohorts.py (the ten classes pinned by
/root/reference/tests/test_cohorts.py:10-29, plus ERA5Resampling — the
hourly->daily case, cohorts.py:119-132) without dask: chunk layouts become
chunk-length tuples (or per-axis tuples for the 2-D NWM case).

Returns ``(labels, chunks, expected_size)`` ready for
``flox_tpu.cohorts.find_group_cohorts``.
"""

from __future__ import annotations

import numpy as np
import pandas as pd


def _even_chunks(n: int, size: int) -> tuple[int, ...]:
    full, rem = divmod(n, size)
    return (size,) * full + ((rem,) if rem else ())


def _codes_for_resampling(index: pd.DatetimeIndex, freq: str) -> np.ndarray:
    # helpers.codes_for_resampling:5-11
    s = pd.Series(np.arange(index.size), index)
    grouped = s.groupby(pd.Grouper(freq=freq))
    counts = grouped.count()
    return np.repeat(np.arange(len(counts)), counts.values)


def era5_dayofyear():
    # ERA5DayOfYear (cohorts.py:135-140): 3 years hourly, 48 h chunks
    time = pd.date_range("2016-01-01", "2018-12-31 23:59", freq="h")
    by = time.dayofyear.values - 1
    return by, _even_chunks(len(time), 48), int(by.max()) + 1


def era5_google():
    # ERA5Google (cohorts.py:195-203): 900 6-hourly steps, chunks of 1
    time = pd.date_range("1959-01-01", freq="6h", periods=900)
    by = time.day.values - 1
    return by, (1,) * 900, int(by.max()) + 1


def _era5_monthhour_by():
    # ERA5MonthHour (cohorts.py:147-159): factorize (month, hour) against
    # (1..12, 1..24). Hour 0 is absent from the hour index, so those
    # timestamps factorize to -1 — the reference keeps that quirk and so
    # do we.
    time = pd.date_range("2016-01-01", "2018-12-31 23:59", freq="h")
    mcode = time.month.values - 1  # 0..11, always valid
    hcode = time.hour.values - 1  # -1 for hour 0 (not in 1..24)
    by = np.where(hcode >= 0, mcode * 24 + hcode, -1)
    return by


def era5_monthhour():
    by = _era5_monthhour_by()
    return by, _even_chunks(len(by), 48), int(by.max()) + 1


def era5_monthhour_rechunked():
    # ERA5MonthHourRechunked (cohorts.py:163-166): rechunk_for_cohorts with
    # a boundary forced wherever label 1 begins, chunksize 48
    from flox_tpu.rechunk import rechunk_for_cohorts

    by = _era5_monthhour_by()
    chunks = rechunk_for_cohorts(None, -1, by, force_new_chunk_at=[1], chunksize=48)
    return by, tuple(chunks), int(by.max()) + 1


def oisst():
    # OISST (cohorts.py:230-238): ~40 years daily, chunks of 10
    time = pd.date_range("1981-09-01 12:00", "2021-06-14 12:00", freq="D")
    by = time.dayofyear.values - 1
    return by, _even_chunks(len(time), 10), int(by.max()) + 1


def perfect_monthly():
    # PerfectMonthly (cohorts.py:169-180): monthly steps, chunks of 4
    time = pd.date_range("1961-01-01", "2018-12-31 23:59", freq="ME")
    by = time.month.values - 1
    return by, _even_chunks(len(time), 4), int(by.max()) + 1


def perfect_blockwise_resampling():
    # PerfectBlockwiseResampling (cohorts.py:205-215): daily data resampled
    # to 5D on 10-day chunks — every output group in exactly one chunk
    index = pd.date_range("1959-01-01", freq="D", end="1962-12-31")
    by = _codes_for_resampling(index, "5D")
    return by, _even_chunks(len(index), 10), int(by.max()) + 1


def single_chunk():
    # SingleChunk (cohorts.py:218-227): one chunk along the reduced axis
    index = pd.date_range("1959-01-01", freq="D", end="1962-12-31")
    by = _codes_for_resampling(index, "5D")
    return by, (len(index),), int(by.max()) + 1


def era5_resampling():
    # ERA5Resampling (cohorts.py:119-132): 5 years hourly resampled to
    # daily, per-timestep chunks — the hourly->daily case VERDICT r3 #9
    # called out as missing
    n = 5 * 365 * 24
    time = pd.date_range("2001-01-01", periods=n, freq="h")
    by = _codes_for_resampling(time, "D")
    return by, (1,) * n, int(by.max()) + 1


def random_big_array():
    # RandomBigArray (cohorts.py:242-248): 100k random labels over 5000
    # groups, 10 chunks. The reference seeds nothing; a fixed rng keeps the
    # snapshot stable without changing the statistics.
    rng = np.random.default_rng(1)
    by = rng.integers(0, 5000, size=100_000)
    return by, _even_chunks(100_000, 10_000), 5000


def nwm_midwest():
    # NWMMidwest (cohorts.py:84-97): 2-D label map (1800 x 4500) from an
    # outer product, factorized dense, chunked (350, 350) on BOTH axes
    x = np.repeat(np.arange(30), 150)  # (4500,)
    y = np.repeat(np.arange(30), 60)  # (1800,)
    by2d = x[np.newaxis, :] * y[:, np.newaxis]
    _, codes = np.unique(by2d, return_inverse=True)
    codes = codes.reshape(by2d.shape)
    chunks = (_even_chunks(1800, 350), _even_chunks(4500, 350))
    return codes, chunks, int(codes.max()) + 1


SCENARIOS = {
    "era5_dayofyear": era5_dayofyear,
    "era5_google": era5_google,
    "era5_monthhour": era5_monthhour,
    "era5_monthhour_rechunked": era5_monthhour_rechunked,
    "oisst": oisst,
    "perfect_blockwise_resampling": perfect_blockwise_resampling,
    "perfect_monthly": perfect_monthly,
    "random_big_array": random_big_array,
    "single_chunk": single_chunk,
    "era5_resampling": era5_resampling,
    "nwm_midwest": nwm_midwest,
}
