"""Doctest runner for the public API docstrings (the reference runs
doctests on aggregations/core/xarray in CI, ci-additional.yaml:59-64)."""

import doctest

import pytest

import flox_tpu.core
import flox_tpu.scan


@pytest.mark.parametrize("module", [flox_tpu.core, flox_tpu.scan])
def test_doctests(module):
    results = doctest.testmod(module, verbose=False)
    assert results.attempted > 0
    assert results.failed == 0
