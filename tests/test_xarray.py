"""Adapter-level tests for xarray_reduce, run against xrlite (and therefore
exercising the exact code path real xarray users hit — the adapter binds to
whichever labeled-array backend is present).

Ports the core scenarios of the reference's tests/test_xarray.py (846 LoC):
groupers by name/DataArray, bins, Datasets, skipna, multi-q quantile, attrs,
dim order, MultiIndex grouping.
"""

import numpy as np
import pandas as pd
import pytest

from flox_tpu import xrlite
from flox_tpu.xarray import xarray_reduce

DataArray = xrlite.DataArray
Dataset = xrlite.Dataset


@pytest.fixture
def da():
    # (lat, time) with a monthly label on time — classic climatology layout
    nt = 48
    time_months = (np.arange(nt) // 4) % 12
    data = np.linspace(0, 1, 3 * nt).reshape(3, nt)
    return DataArray(
        data,
        dims=("lat", "time"),
        coords={"lat": np.array([10.0, 20.0, 30.0]), "month": ("time", time_months)},
        name="temp",
        attrs={"units": "K"},
    )


def oracle_group_mean(data, labels, nlab):
    return np.stack([data[..., labels == g].mean(-1) for g in range(nlab)], axis=-1)


def test_reduce_by_coord_name(da):
    out = xarray_reduce(da, "month", func="mean")
    assert out.dims == ("lat", "month")  # group dim slots where time was
    assert out.name == "temp"
    assert out.attrs["units"] == "K"
    np.testing.assert_array_equal(np.asarray(out["month"].data), np.arange(12))
    labels = np.asarray(da["month"].data)
    expected = oracle_group_mean(da.values, labels, 12)
    np.testing.assert_allclose(np.asarray(out.transpose("lat", "month").data), expected)


def test_reduce_by_dataarray(da):
    by = da["month"]
    out = xarray_reduce(da, by, func="nanmean")
    labels = np.asarray(by.data)
    expected = oracle_group_mean(da.values, labels, 12)
    np.testing.assert_allclose(np.asarray(out.transpose("lat", "month").data), expected)


def test_skipna_rewrite(da):
    data = da.values.copy()
    data[0, ::5] = np.nan
    da_nan = DataArray(data, dims=da.dims, coords=da._coords, name="t")
    out_skip = xarray_reduce(da_nan, "month", func="mean", skipna=True)
    out_prop = xarray_reduce(da_nan, "month", func="mean", skipna=False)
    assert not np.isnan(np.asarray(out_skip.data)).any()
    assert np.isnan(np.asarray(out_prop.data)).any()


def test_binning(da):
    bins = np.array([0.0, 15.0, 35.0])
    out = xarray_reduce(da, "lat", func="count", expected_groups=bins, isbin=True, dim="lat")
    assert "lat_bins" in out.dims
    groups = out["lat_bins"].data
    assert isinstance(groups, pd.IntervalIndex)
    np.testing.assert_array_equal(
        np.asarray(out.transpose("lat_bins", "time").data)[:, 0], [1, 2]
    )


def test_dataset(da):
    ds = Dataset(
        {"temp": da, "scalarish": DataArray(np.arange(3.0), dims=("lat",))},
        attrs={"title": "demo"},
    )
    out = xarray_reduce(ds, "month", func="mean")
    assert isinstance(out, Dataset)
    assert out.attrs["title"] == "demo"
    # temp reduced; scalarish (no time dim) passes through
    assert "month" in out["temp"].dims
    np.testing.assert_array_equal(out["scalarish"].values, np.arange(3.0))
    # dataset members put the group dim first (reference no_groupby_reorder)
    assert out["temp"].dims[0] == "month"


def test_multi_by(da):
    half = (np.arange(48) >= 24).astype(int)
    da2 = da.assign_coords({"half": ("time", half)})
    out = xarray_reduce(da2, "month", "half", func="sum")
    assert set(("month", "half")).issubset(out.dims)
    assert out.sizes["month"] == 12 and out.sizes["half"] == 2


def test_quantile_vector_q(da):
    out = xarray_reduce(da, "month", func="quantile", q=[0.25, 0.5, 0.75])
    assert "quantile" in out.dims
    assert out.sizes["quantile"] == 3
    np.testing.assert_allclose(np.asarray(out["quantile"].data), [0.25, 0.5, 0.75])
    # dim order: month slots at time's position, quantile goes last
    assert out.dims == ("lat", "month", "quantile")


def test_expected_groups(da):
    out = xarray_reduce(da, "month", func="count", expected_groups=np.arange(14))
    assert out.sizes["month"] == 14
    counts = np.asarray(out.transpose("lat", "month").data)
    assert (counts[:, 12:] == 0).all()


def test_dim_ellipsis(da):
    out = xarray_reduce(da, "month", func="mean", dim=...)
    # all dims reduced -> only the group dim remains
    assert out.dims == ("month",)
    labels = np.asarray(da["month"].data)
    expected = np.array([da.values[:, labels == g].mean() for g in range(12)])
    np.testing.assert_allclose(np.asarray(out.data), expected)


def test_min_count_and_fill(da):
    data = da.values.copy()
    data[:, :4] = np.nan  # month 0 entirely NaN
    da_nan = DataArray(data, dims=da.dims, coords=da._coords)
    out = xarray_reduce(da_nan, "month", func="nansum", min_count=3)
    res = np.asarray(out.transpose("lat", "month").data)
    assert np.isnan(res[:, 0]).all()  # below min_count -> NaN, not 0
    assert np.isfinite(res[:, 1:]).all()


def test_multiindex_grouping():
    # grouping by a MultiIndex-backed coord (the reference's stacked case,
    # xarray.py:263-269, 468-479): groups come back as a MultiIndex coord
    mi = pd.MultiIndex.from_product([["a", "b"], [0, 1]], names=("letter", "num"))
    labels = mi.take(np.array([0, 1, 2, 3, 0, 1, 2, 3]))
    da = DataArray(
        np.arange(8.0),
        dims=("sample",),
        coords={"stacked": ("sample", labels)},
    )
    out = xarray_reduce(da, "stacked", func="sum")
    groups = out["stacked"].data
    assert isinstance(groups, pd.MultiIndex)
    assert groups.names == ["letter", "num"]
    np.testing.assert_allclose(np.asarray(out.data), [4.0, 6.0, 8.0, 10.0])


def test_mesh_method_through_adapter(da):
    from flox_tpu.parallel import make_mesh

    out_eager = xarray_reduce(da, "month", func="nanmean")
    out_mesh = xarray_reduce(da, "month", func="nanmean", method="map-reduce", mesh=make_mesh(8))
    np.testing.assert_allclose(
        np.asarray(out_mesh.data), np.asarray(out_eager.data), rtol=1e-12
    )


def test_distributed_quantile_through_adapter(da):
    # vector-q quantile under method='map-reduce' on the mesh (the
    # distributed radix-select) through the full labeled-array path: the
    # 'quantile' dim lands LAST like the eager path's (new dims trail,
    # xarray.py _restore_dim_order) and results match the eager sort path
    # bit-tight (the selection is count-exact)
    from flox_tpu.parallel import make_mesh

    out_eager = xarray_reduce(da, "month", func="quantile", q=[0.25, 0.75])
    out_mesh = xarray_reduce(
        da, "month", func="quantile", q=[0.25, 0.75],
        method="map-reduce", mesh=make_mesh(8),
    )
    assert out_mesh.dims == out_eager.dims
    assert "quantile" in out_mesh.dims
    np.testing.assert_allclose(
        np.asarray(out_mesh.data), np.asarray(out_eager.data),
        rtol=5e-16, atol=0, equal_nan=True,
    )


def test_keep_attrs_false(da):
    out = xarray_reduce(da, "month", func="mean", keep_attrs=False)
    assert out.attrs == {}


class TestXrlite:
    """xrlite's own semantics (the subset contract the adapter relies on)."""

    def test_broadcast(self):
        a = DataArray(np.arange(3.0), dims=("x",))
        b = DataArray(np.arange(4.0), dims=("y",))
        a2, b2 = xrlite.broadcast(a, b)
        assert a2.dims == b2.dims == ("x", "y")
        assert a2.shape == b2.shape == (3, 4)
        np.testing.assert_array_equal(a2.values, np.broadcast_to(np.arange(3.0)[:, None], (3, 4)))

    def test_transpose_and_expand(self):
        a = DataArray(np.arange(6.0).reshape(2, 3), dims=("x", "y"))
        t = a.transpose("y", "x")
        assert t.shape == (3, 2)
        e = a.expand_dims({"z": 4})
        assert e.dims == ("z", "x", "y") and e.shape == (4, 2, 3)

    def test_apply_ufunc_core_dims(self):
        a = DataArray(np.ones((2, 5)), dims=("x", "t"),
                      coords={"x": np.array([1.0, 2.0])}, attrs={"u": 1})
        out = xrlite.apply_ufunc(
            lambda arr: arr.sum(-1, keepdims=True) * np.ones((1, 3)),
            a,
            input_core_dims=[["t"]],
            output_core_dims=[["g"]],
        )
        assert out.dims == ("x", "g") and out.shape == (2, 3)
        assert out.attrs == {"u": 1}
        assert "x" in out._coords  # surviving coords carried

    def test_dataset_roundtrip(self):
        ds = Dataset({"v": DataArray(np.arange(4.0), dims=("t",),
                                     coords={"t": np.arange(4)})})
        v = ds["v"]
        assert "t" in v._coords
        ds["w"] = DataArray(np.zeros(4), dims=("t",))
        assert set(ds.data_vars) == {"v", "w"}
        assert ds.dims == {"t": 4}

    def test_conflicting_sizes_raise(self):
        a = DataArray(np.zeros(3), dims=("x",))
        b = DataArray(np.zeros(4), dims=("x",))
        with pytest.raises(ValueError, match="conflicting"):
            xrlite.broadcast(a, b)

    def test_jax_data_stays_device(self):
        import jax.numpy as jnp

        a = DataArray(jnp.arange(6.0).reshape(2, 3), dims=("x", "y"))
        t = a.transpose("y", "x")
        import jax

        assert isinstance(t.data, jax.Array)


def test_binned_grouper_dim_order(da):
    # review regression: the _bins-renamed group dim must slot where the
    # grouped dim was, same as the unbinned case
    da_t = DataArray(da.values.T, dims=("time", "lat"), coords=da._coords)
    out = xarray_reduce(da_t, "month", func="mean", isbin=True,
                        expected_groups=np.array([0, 6, 12]))
    assert out.dims == ("month_bins", "lat")


def test_rechunk_for_cohorts_wrapper():
    from flox_tpu.xarray import rechunk_for_cohorts

    da = DataArray(np.zeros(48), dims=("time",),
                   coords={"month": ("time", np.arange(48) % 12)})
    chunks = rechunk_for_cohorts(da, "time", da["month"], force_new_chunk_at=[0], chunksize=12)
    assert sum(chunks) == 48 and chunks == (12, 12, 12, 12)
    with pytest.raises(ValueError, match="labels have length"):
        rechunk_for_cohorts(da, "time", np.arange(20) % 12, force_new_chunk_at=[0])


def test_plain_reduction_fast_path(da):
    # reducing only over dims the groupers do not vary along is a plain
    # reduction, no groupby (parity: reference xarray.py:303-322)
    out = xarray_reduce(da, "month", func="mean", dim="lat")
    assert out.dims == ("time",)
    np.testing.assert_allclose(np.asarray(out.data), da.values.mean(0))
    # coords on surviving dims carry over; the grouper coord survives too
    assert "month" in out._coords
    outc = xarray_reduce(da, "month", func="count", dim="lat")
    np.testing.assert_array_equal(np.asarray(outc.data), np.full(48, 3))


def test_plain_path_argmax_and_vector_q(da):
    # review regressions: arg-reductions single-dim; vector q gets a coord;
    # jax-backed data stays on device
    import jax
    import jax.numpy as jnp

    da_t = DataArray(da.values, dims=da.dims, coords=da._coords)
    out = xarray_reduce(da_t, "month", func="argmax", dim="lat")
    np.testing.assert_array_equal(np.asarray(out.data), np.argmax(da.values, 0))
    oq = xarray_reduce(da_t, "month", func="quantile", dim="lat", q=[0.25, 0.75])
    np.testing.assert_allclose(np.asarray(oq["quantile"].data), [0.25, 0.75])
    daj = DataArray(jnp.asarray(da.values), dims=da.dims, coords=da._coords)
    oj = xarray_reduce(daj, "month", func="nanmean", dim="lat")
    assert isinstance(oj.data, jax.Array)


def test_plain_path_misaligned_grouper_raises(da):
    # review regression: the fast path must enforce alignment like the
    # general path's join='exact'
    bad = DataArray(np.arange(20) % 12, dims=("time",), name="m")
    with pytest.raises(ValueError, match="align"):
        xarray_reduce(da, bad, func="mean", dim="lat")


def test_sort_false_through_adapter(da):
    out_sorted = xarray_reduce(da, "month", func="sum")
    out_unsorted = xarray_reduce(da, "month", func="sum", sort=False)
    # labels appear in order here either way; results must agree
    np.testing.assert_allclose(
        np.asarray(out_sorted.data), np.asarray(out_unsorted.data)
    )


def test_fill_value_through_adapter(da):
    out = xarray_reduce(da, "month", func="sum", expected_groups=np.arange(14),
                        fill_value=-777.0)
    res = np.asarray(out.transpose("lat", "month").data)
    assert (res[:, 12:] == -777.0).all()


def test_cohorts_method_through_adapter(da):
    from flox_tpu.parallel import make_mesh

    out_eager = xarray_reduce(da, "month", func="nanvar", ddof=1)
    out_coh = xarray_reduce(da, "month", func="nanvar", ddof=1,
                            method="cohorts", mesh=make_mesh(8))
    np.testing.assert_allclose(
        np.asarray(out_coh.data), np.asarray(out_eager.data), rtol=1e-10
    )


def test_grouper_along_other_dim(da):
    # grouping along lat while reducing lat: groups vary along the reduced
    # dim -> the grouped path engages, dims = (time, lat-groups)
    lat_band = DataArray(np.array([0, 0, 1]), dims=("lat",), name="band")
    out = xarray_reduce(da, lat_band, func="mean", dim="lat")
    assert out.sizes["band"] == 2
    np.testing.assert_allclose(
        np.asarray(out.transpose("band", "time").data)[0],
        da.values[:2].mean(0),
    )


def test_dataset_multiple_reduced_vars(da):
    ds = Dataset({
        "a": da,
        "b": DataArray(da.values * 2, dims=da.dims, coords=da._coords),
        "static": DataArray(np.arange(3.0), dims=("lat",)),
    })
    out = xarray_reduce(ds, "month", func="nanmean")
    np.testing.assert_allclose(np.asarray(out["b"].data),
                               np.asarray(out["a"].data) * 2, rtol=1e-12)
    np.testing.assert_array_equal(out["static"].values, np.arange(3.0))


def test_datetime_bin_resample(da):
    # hourly -> daily-bin resampling via a datetime IntervalIndex, through
    # the adapter (reference user story: resampling with datetime bins)
    nt = 48
    t = pd.date_range("2001-01-01", periods=nt, freq="h")
    data = np.arange(float(nt))
    da_t = DataArray(
        data, dims=("time",), coords={"time": t.values}, name="x"
    )
    bins = pd.interval_range(t[0], periods=2, freq="24h")
    out = xarray_reduce(da_t, "time", func="mean", expected_groups=bins)
    groups = out["time_bins"].data
    assert isinstance(groups, pd.IntervalIndex)
    assert (groups == bins).all()
    # right-closed pd.cut semantics: hour 0 falls outside the first bin
    np.testing.assert_allclose(
        np.asarray(out.data), [np.arange(1, 25).mean(), np.arange(25, 48).mean()]
    )


def test_nongrouped_coord_preserved(da):
    # lat is not grouped and not reduced: its coordinate must survive
    out = xarray_reduce(da, "month", func="mean")
    assert "lat" in out._coords
    np.testing.assert_array_equal(np.asarray(out["lat"].data), [10.0, 20.0, 30.0])


def test_attrs_preserved_by_default(da):
    out = xarray_reduce(da, "month", func="sum")
    assert out.attrs == {"units": "K"}
    ds_out = xarray_reduce(Dataset({"temp": da}, attrs={"title": "t"}), "month", func="sum")
    assert ds_out.attrs == {"title": "t"}


def test_dataset_grouped_by_dim_coordinate():
    # grouping by a dimension coordinate: the group dim keeps the dim's own
    # name, which already exists on the variable (regression: the Dataset
    # branch must not require a brand-new dim name)
    x = np.array([0, 0, 1, 1])
    da2 = DataArray(
        np.arange(8.0).reshape(4, 2), dims=("x", "lat"), coords={"x": x}, name="a"
    )
    out = xarray_reduce(Dataset({"a": da2}), "x", func="mean")
    assert out["a"].sizes["x"] == 2
    np.testing.assert_allclose(
        np.asarray(out["a"].transpose("x", "lat").data),
        [[1.0, 2.0], [5.0, 6.0]],
    )
