"""The high-cardinality (present-groups / "sort") engine.

The dense runtimes materialize ``(..., ngroups)`` accumulators — the
"dense ceiling" of docs/distributed.md. The sort engine (kernels.py sort
section) compacts the codes to the groups actually present, runs the
UNCHANGED dense kernels over a banded capacity, and scatters the dense
layout back host-side — the TPU-native analogue of the reference's
sort+``ufunc.reduceat`` engine (aggregate_flox.py:133-192). Everything
here asserts BIT-identity against the dense path: compaction relabels
codes monotonically and never permutes elements, so per-group accumulation
order is byte-for-byte the dense path's.
"""

import numpy as np
import pytest

import jax

import flox_tpu
from flox_tpu import groupby_reduce
from flox_tpu.kernels import (
    compact_codes,
    present_cap,
    present_groups,
    scatter_present_dense,
    sort_segment_reduce,
)
from flox_tpu.multiarray import PresentGroups
from flox_tpu.parallel import make_mesh
from flox_tpu.streaming import streaming_groupby_reduce

RNG = np.random.default_rng(1234)

#: a sparse-presence workload: UNIVERSE labels, PRESENT distinct ones
UNIVERSE = 200_000
PRESENT = 300
N = 4096


def _sparse_codes(n=N, present=PRESENT, universe=UNIVERSE, rng=None):
    rng = rng or RNG
    ids = rng.choice(universe, present, replace=False)
    return ids[rng.integers(0, present, n)]


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()


@pytest.fixture(scope="module")
def codes():
    return _sparse_codes()


# ---------------------------------------------------------------------------
# the compaction primitives
# ---------------------------------------------------------------------------


class TestPrimitives:
    def test_present_groups_sorted_unique(self, codes):
        p = present_groups(codes, UNIVERSE)
        assert (np.diff(p) > 0).all()
        np.testing.assert_array_equal(p, np.unique(codes[codes >= 0]))

    def test_compact_codes_monotone_and_missing(self, codes):
        withmiss = codes.copy()
        withmiss[:7] = -1
        p = present_groups(withmiss, UNIVERSE)
        cc = compact_codes(withmiss, p)
        assert cc.dtype == np.int32
        assert (cc[:7] == -1).all()
        valid = cc[withmiss >= 0]
        assert valid.min() == 0 and valid.max() == len(p) - 1
        # monotone relabel: order of group ids preserved
        np.testing.assert_array_equal(p[valid], withmiss[withmiss >= 0])

    def test_present_cap_bands_and_pad_slot(self):
        # an absent-groups universe always keeps >= 1 empty pad slot (the
        # scatter fill source) and bands to powers of two
        assert present_cap(5, 1000) == 8
        assert present_cap(8, 1000) == 16  # 8 present needs a 9th slot
        assert present_cap(1000, 1000) == 1000  # fully present: no pad
        assert present_cap(0, 10) == 8
        cap = present_cap(300, UNIVERSE)
        assert cap == 512

    def test_scatter_uses_pad_slot_fill(self):
        p = np.array([3, 5])
        comp = np.array([[1.0, 2.0, -7.5, 0.0]])  # pad slot carries -7.5
        out = scatter_present_dense(comp, p, 6)
        np.testing.assert_array_equal(out, [[-7.5, -7.5, -7.5, 1.0, -7.5, 2.0]])

    def test_sort_segment_reduce_device(self, codes):
        data = RNG.normal(size=codes.shape[0])
        p = present_groups(codes, UNIVERSE)
        ncap = present_cap(len(p), UNIVERSE)
        pres, out, n_present = sort_segment_reduce("sum", data, codes, ncap=ncap)
        assert int(n_present) == len(p)
        np.testing.assert_array_equal(np.asarray(pres)[: len(p)], p)
        assert (np.asarray(pres)[len(p):] == -1).all()
        # bit-identical to the dense scatter's per-group accumulation
        import jax.numpy as jnp

        dense = jax.ops.segment_sum(
            jnp.asarray(data),
            jnp.asarray(codes).astype(jnp.int32),
            num_segments=UNIVERSE,
        )
        np.testing.assert_array_equal(
            np.asarray(out)[: len(p)], np.asarray(dense)[p]
        )


# ---------------------------------------------------------------------------
# bit-identity matrix: every family x NaN x min_count x dtypes x supersets
# ---------------------------------------------------------------------------

FAMILIES = [
    "sum", "nansum", "prod", "nanprod", "mean", "nanmean", "var", "nanvar",
    "std", "nanstd", "max", "nanmax", "min", "nanmin", "count", "any", "all",
    "argmax", "nanargmax", "argmin", "nanargmin", "first", "last",
    "nanfirst", "nanlast", "median", "nanmedian", "quantile", "nanquantile",
]


def _run_pair(vals, codes, func, **kw):
    rs, gs = groupby_reduce(vals, codes, func=func, engine="sort", **kw)
    rd, gd = groupby_reduce(vals, codes, func=func, engine="jax", **kw)
    np.testing.assert_array_equal(np.asarray(gs), np.asarray(gd))
    assert np.asarray(rs).dtype == np.asarray(rd).dtype
    np.testing.assert_array_equal(np.asarray(rs), np.asarray(rd), err_msg=func)


class TestBitIdentity:
    @pytest.mark.parametrize("func", FAMILIES)
    def test_family_superset_universe(self, func, codes):
        vals = RNG.normal(size=(2, N))
        vals[..., RNG.random(N) < 0.15] = np.nan
        kw = {"expected_groups": np.arange(UNIVERSE)}
        if func in ("quantile", "nanquantile"):
            kw["finalize_kwargs"] = {"q": [0.25, 0.75]}
        _run_pair(vals, codes, func, **kw)

    @pytest.mark.parametrize("func", ["sum", "nanmean", "nanmax", "count"])
    def test_family_labels_present_only(self, func, codes):
        # expected_groups exactly the present set: compact == dense domain
        vals = RNG.normal(size=N)
        _run_pair(vals, codes, func, expected_groups=np.unique(codes))

    @pytest.mark.parametrize("dtype", ["int32", "int64", "float32"])
    def test_int_and_narrow_dtypes(self, dtype, codes):
        vals = RNG.integers(-50, 50, N).astype(dtype)
        for func in ("sum", "max", "count", "first"):
            _run_pair(vals, codes, func, expected_groups=np.arange(UNIVERSE))

    @pytest.mark.parametrize("min_count", [1, 2, 4])
    def test_min_count_mask(self, min_count, codes):
        vals = RNG.normal(size=N)
        _run_pair(
            vals, codes, "nansum",
            expected_groups=np.arange(UNIVERSE), min_count=min_count,
        )

    def test_nan_fill_int_promotion(self, codes):
        # NaN fill on integer sums promotes on BOTH paths (the pad slot
        # makes the compact run contain an empty group exactly when the
        # dense one does — the dtype-parity mechanism)
        vals = RNG.integers(0, 100, N)
        _run_pair(
            vals, codes, "sum",
            expected_groups=np.arange(UNIVERSE), fill_value=np.nan, min_count=2,
        )

    def test_datetime_roundtrip(self, codes):
        vals = np.array(
            RNG.integers(0, 10**15, N), dtype="datetime64[ns]"
        )
        for func in ("nanmax", "first", "count", "nanmean"):
            _run_pair(vals, codes, func, expected_groups=np.arange(UNIVERSE))

    def test_multi_by_kept_dims(self):
        # kept by-dims fold into disjoint code ranges; the present set
        # lives in the flat offset space and scatters back flat
        rng = np.random.default_rng(7)
        by = rng.choice(rng.choice(50_000, 40, replace=False), size=(6, 128))
        vals = rng.normal(size=(6, 128))
        rs, _ = groupby_reduce(
            vals, by, func="nanmean", axis=-1,
            expected_groups=np.arange(50_000), engine="sort",
        )
        rd, _ = groupby_reduce(
            vals, by, func="nanmean", axis=-1,
            expected_groups=np.arange(50_000), engine="jax",
        )
        np.testing.assert_array_equal(np.asarray(rs), np.asarray(rd))


# ---------------------------------------------------------------------------
# the acceptance workload: >= 1M labels, <= 1% present, no dense allocation
# ---------------------------------------------------------------------------


class TestMillionLabels:
    SIZE = 1_000_000
    PRESENT = 8_000  # 0.8% of the universe
    N = 60_000

    def test_million_label_sort_no_dense_allocation(self):
        rng = np.random.default_rng(42)
        ids = rng.choice(self.SIZE, self.PRESENT, replace=False)
        codes = ids[rng.integers(0, self.PRESENT, self.N)]
        vals = rng.normal(size=self.N)
        vals[rng.random(self.N) < 0.1] = np.nan
        eg = np.arange(self.SIZE)
        dense_bytes = self.SIZE * 8

        with flox_tpu.set_options(telemetry=True):
            rs, _ = groupby_reduce(
                vals, codes, func="nanmean", expected_groups=eg, engine="sort"
            )
            # allocation accounting, leg 1: no live device buffer anywhere
            # near a dense (..., ngroups) accumulator's size survived the
            # sort run (the compact domain is <= 16384 slots)
            live_max = max(
                (a.nbytes for a in jax.live_arrays()), default=0
            )
            assert live_max < dense_bytes // 8, live_max
            # leg 2: the engine's own gauges record the compact capacity
            from flox_tpu import telemetry

            acc = telemetry.METRICS.gauges()["highcard.acc_groups"]
            assert 0 < acc <= 2 * present_cap(self.PRESENT, self.SIZE)
            assert (
                telemetry.METRICS.gauges()["highcard.dense_groups_avoided"]
                >= self.SIZE - 2 * present_cap(self.PRESENT, self.SIZE)
            )
            # leg 3 (when the backend reports memory at all): peak in use
            # stays far below the dense accumulator estimate
            from flox_tpu import device

            stats = device.memory_stats()
            if stats and stats.get("peak_bytes_in_use"):
                assert stats["peak_bytes_in_use"] < 4 * dense_bytes

        # bit-identical to the dense path on the present groups (the dense
        # run happens AFTER the allocation assertions so its buffers cannot
        # contaminate the live-array scan)
        rd, _ = groupby_reduce(
            vals, codes, func="nanmean", expected_groups=eg, engine="jax"
        )
        rs, rd = np.asarray(rs), np.asarray(rd)
        np.testing.assert_array_equal(rs[ids], rd[ids])
        np.testing.assert_array_equal(rs, rd)  # and everywhere (fills too)

    def test_million_label_over_ceiling_autoroutes(self):
        # heuristic-chosen engines degrade to sort instead of raising once
        # the dense estimate crosses the ceiling
        rng = np.random.default_rng(43)
        codes = rng.choice(self.SIZE, 64, replace=False)[
            rng.integers(0, 64, 2048)
        ]
        vals = rng.normal(size=(8, 2048))
        with flox_tpu.set_options(dense_intermediate_bytes_max=2**20):
            got, _ = groupby_reduce(
                vals, codes, func="nanmean",
                expected_groups=np.arange(self.SIZE),
            )
        want, _ = groupby_reduce(
            vals, codes, func="nanmean", expected_groups=np.arange(self.SIZE),
            engine="jax",
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_explicit_dense_over_ceiling_still_raises_naming_sort(self):
        rng = np.random.default_rng(44)
        codes = rng.integers(0, 8, 64)
        vals = np.ones((4, 64))
        with flox_tpu.set_options(dense_intermediate_bytes_max=2**20):
            with pytest.raises(ValueError, match="engine='sort'"):
                groupby_reduce(
                    vals, codes, func="sum",
                    expected_groups=np.arange(300_000), engine="jax",
                )


# ---------------------------------------------------------------------------
# mesh: compact collectives
# ---------------------------------------------------------------------------


class TestMesh:
    @pytest.mark.parametrize("method", ["map-reduce", "cohorts", "blockwise"])
    def test_methods_bit_identical(self, mesh, method, codes):
        vals = RNG.normal(size=N)
        vals[RNG.random(N) < 0.1] = np.nan
        eg = np.arange(UNIVERSE)
        rs, _ = groupby_reduce(
            vals, codes, func="nanmean", expected_groups=eg,
            engine="sort", method=method, mesh=mesh,
        )
        rd, _ = groupby_reduce(
            vals, codes, func="nanmean", expected_groups=eg,
            method=method, mesh=mesh,
        )
        np.testing.assert_array_equal(np.asarray(rs), np.asarray(rd))

    @pytest.mark.parametrize("func", ["sum", "nanvar", "nanargmax", "first"])
    def test_mapreduce_families(self, mesh, func, codes):
        vals = RNG.normal(size=N)
        eg = np.arange(UNIVERSE)
        rs, _ = groupby_reduce(
            vals, codes, func=func, expected_groups=eg,
            engine="sort", method="map-reduce", mesh=mesh,
        )
        rd, _ = groupby_reduce(
            vals, codes, func=func, expected_groups=eg,
            method="map-reduce", mesh=mesh,
        )
        np.testing.assert_array_equal(np.asarray(rs), np.asarray(rd))


# ---------------------------------------------------------------------------
# streaming: compact carry, checkpoint/resume, OOM ladder
# ---------------------------------------------------------------------------


class TestStreaming:
    @pytest.mark.parametrize("func", ["nanmean", "sum", "nanmax", "nanvar", "nanmedian"])
    def test_stream_bit_identical(self, func, codes):
        vals = RNG.normal(size=N)
        vals[RNG.random(N) < 0.1] = np.nan
        eg = np.arange(UNIVERSE)
        rs, _ = streaming_groupby_reduce(
            vals, codes, func=func, expected_groups=eg, batch_len=700,
            engine="sort",
        )
        rd, _ = streaming_groupby_reduce(
            vals, codes, func=func, expected_groups=eg, batch_len=700,
            engine="jax",
        )
        np.testing.assert_array_equal(np.asarray(rs), np.asarray(rd))

    def test_stream_mesh_bit_identical(self, mesh, codes):
        vals = RNG.normal(size=N)
        eg = np.arange(UNIVERSE)
        rs, _ = streaming_groupby_reduce(
            vals, codes, func="nanmean", expected_groups=eg, batch_len=1024,
            engine="sort", mesh=mesh,
        )
        rd, _ = streaming_groupby_reduce(
            vals, codes, func="nanmean", expected_groups=eg, batch_len=1024,
            engine="jax", mesh=mesh,
        )
        np.testing.assert_array_equal(np.asarray(rs), np.asarray(rd))

    def test_stream_fused_bit_identical(self, codes):
        from flox_tpu.streaming import streaming_groupby_aggregate_many

        vals = RNG.normal(size=N)
        eg = np.arange(UNIVERSE)
        rs, _ = streaming_groupby_aggregate_many(
            vals, codes, funcs=("sum", "count", "min", "max", "var"),
            expected_groups=eg, batch_len=700, engine="sort",
        )
        rd, _ = streaming_groupby_aggregate_many(
            vals, codes, funcs=("sum", "count", "min", "max", "var"),
            expected_groups=eg, batch_len=700, engine="jax",
        )
        assert set(rs) == set(rd)
        for f in rs:
            np.testing.assert_array_equal(
                np.asarray(rs[f]), np.asarray(rd[f]), err_msg=f
            )

    def test_kill_at_slab_k_resume(self, tmp_path, codes):
        # the checkpointed carry is the COMPACT state; a resuming process
        # recomputes the identical present table from the identical inputs,
        # so the snapshot folds back bit-identically
        from flox_tpu import faults

        vals = RNG.normal(size=N)
        eg = np.arange(UNIVERSE)
        with flox_tpu.set_options(
            stream_checkpoint_every=2, stream_checkpoint_path=str(tmp_path)
        ):
            with pytest.raises(Exception, match="killed|Killed|stream"):
                with faults.inject(kill_at=(2800,)):
                    streaming_groupby_reduce(
                        vals, codes, func="nanmean", expected_groups=eg,
                        batch_len=700, engine="sort",
                    )
            rs, _ = streaming_groupby_reduce(
                vals, codes, func="nanmean", expected_groups=eg,
                batch_len=700, engine="sort",
            )
        rd, _ = streaming_groupby_reduce(
            vals, codes, func="nanmean", expected_groups=eg, batch_len=700,
            engine="jax",
        )
        np.testing.assert_array_equal(np.asarray(rs), np.asarray(rd))

    def test_numpy_engine_rejected(self, codes):
        with pytest.raises(ValueError, match="numpy"):
            streaming_groupby_reduce(
                np.ones(N), codes, func="sum",
                expected_groups=np.arange(UNIVERSE), engine="numpy",
            )

    def test_oom_ladder_bottom_names_sort_engine(self, codes):
        # an ngroups-dominated dense stream whose ladder bottoms out gets
        # the typed remedy, not a bare ladder-exhausted RuntimeError
        from flox_tpu import faults
        from flox_tpu.resilience import (
            FATAL,
            HighCardinalityOOMError,
            classify_error,
        )

        vals = RNG.normal(size=N)
        with pytest.raises(HighCardinalityOOMError, match="engine='sort'"):
            with faults.inject(oom_at=(0,), oom_times=99):
                streaming_groupby_reduce(
                    vals, codes, func="nanmean",
                    expected_groups=np.arange(UNIVERSE), batch_len=700,
                    engine="jax",
                )
        # terminal: the classifier must never re-enter the split ladder
        err = HighCardinalityOOMError("x")
        err.__cause__ = faults.SimulatedOOM("RESOURCE_EXHAUSTED")
        assert classify_error(err) == FATAL

    def test_sorted_stream_splits_without_hint(self, codes):
        # compact (sort-engine) streams never flag ngroups domination: the
        # ladder handles their OOMs the ordinary way (split + recover).
        # Integer-valued data: an OOM split changes slab boundaries, and
        # float associativity across DIFFERENT boundaries is out of scope —
        # exact sums keep the comparison byte-for-byte.
        from flox_tpu import faults

        vals = RNG.integers(-5, 5, N).astype(np.float64)
        with faults.inject(oom_at=(0,), oom_times=1):
            rs, _ = streaming_groupby_reduce(
                vals, codes, func="nanmean",
                expected_groups=np.arange(UNIVERSE), batch_len=700,
                engine="sort",
            )
        rd, _ = streaming_groupby_reduce(
            vals, codes, func="nanmean", expected_groups=np.arange(UNIVERSE),
            batch_len=700, engine="jax",
        )
        np.testing.assert_array_equal(np.asarray(rs), np.asarray(rd))


# ---------------------------------------------------------------------------
# PresentGroups container
# ---------------------------------------------------------------------------


class TestPresentGroupsContainer:
    def test_scatter_dense_fill(self):
        pg = PresentGroups(np.array([1, 4]), np.array([2.0, 3.0, np.nan]), 6)
        out = pg.scatter_dense()
        np.testing.assert_array_equal(
            out, [np.nan, 2.0, np.nan, np.nan, 3.0, np.nan]
        )

    def test_fully_present_roundtrip(self):
        pg = PresentGroups(np.arange(4), np.array([[1.0, 2.0, 3.0, 4.0]]), 4)
        np.testing.assert_array_equal(pg.scatter_dense(), [[1.0, 2.0, 3.0, 4.0]])

    @pytest.mark.parametrize("op,expect", [
        ("sum", 12.0), ("max", 10.0), ("min", 2.0), ("prod", 20.0),
    ])
    def test_merge_ops(self, op, expect):
        a = PresentGroups(np.array([2, 7]), np.array([1.0, 2.0, 0.0]), 100)
        b = PresentGroups(np.array([7, 50]), np.array([10.0, 20.0, 0.0]), 100)
        m = a.merge(b, op)
        assert list(m.present) == [2, 7, 50]
        d = m.scatter_dense()
        assert d[7] == expect
        assert d[50] == 20.0

    def test_merge_universe_mismatch_raises(self):
        a = PresentGroups(np.array([0]), np.array([1.0, 0.0]), 10)
        b = PresentGroups(np.array([0]), np.array([1.0, 0.0]), 11)
        with pytest.raises(ValueError, match="universe"):
            a.merge(b, "sum")

    def test_cap_contract_raises(self):
        with pytest.raises(ValueError, match="trailing axis"):
            PresentGroups(np.array([0, 1, 2]), np.array([1.0, 2.0]), 10)


# ---------------------------------------------------------------------------
# routing, autotune family, cost-model prior, caches, gauges
# ---------------------------------------------------------------------------


class TestRoutingAndTuning:
    def test_default_engine_option_routes_sort(self, codes):
        import jax.numpy as jnp

        # device input: the small-host numpy fast path does not apply, so
        # engine=None resolves straight to the session default
        vals = jnp.asarray(RNG.normal(size=N))
        eg = np.arange(UNIVERSE)
        with flox_tpu.set_options(default_engine="sort", telemetry=True):
            from flox_tpu import telemetry

            n0 = telemetry.METRICS.get("highcard.sort_dispatches")
            rs, _ = groupby_reduce(vals, codes, func="nanmean", expected_groups=eg)
            assert telemetry.METRICS.get("highcard.sort_dispatches") > n0
        # return-type contract: a device-array input yields a device-array
        # result even when routing scattered host-side
        from flox_tpu import utils

        assert utils.is_jax_array(rs)
        rd, _ = groupby_reduce(
            vals, codes, func="nanmean", expected_groups=eg, engine="jax"
        )
        np.testing.assert_array_equal(np.asarray(rs), np.asarray(rd))

    def test_explicit_small_universe_sort_works(self):
        # explicitly chosen sort below every threshold still runs (and is
        # identical) — the thresholds gate only the automatic routing
        codes = RNG.integers(0, 10, 256)
        vals = RNG.normal(size=256)
        _run_pair(vals, codes, "nanmean", expected_groups=np.arange(10))

    def test_highcard_sweep_and_decide(self, codes):
        import jax.numpy as jnp

        from flox_tpu import autotune
        from flox_tpu.autotune import (
            _SWEEP_HIGHCARD_N_MAX,
            _SWEEP_HIGHCARD_SIZE_MAX,
        )

        vals = jnp.asarray(RNG.normal(size=100_000))
        big_codes = _sparse_codes(n=100_000)
        with flox_tpu.set_options(autotune=True):
            groupby_reduce(
                vals, big_codes, func="nanmean",
                expected_groups=np.arange(UNIVERSE),
            )
            rec = autotune.lookup(
                "highcard", dtype="float64",
                ngroups=min(UNIVERSE, _SWEEP_HIGHCARD_SIZE_MAX),
                nelems=min(100_000, _SWEEP_HIGHCARD_N_MAX),
            )
        assert rec is not None
        cands = rec.get("candidates") or {}
        assert {"dense", "sort"} <= set(cands)
        assert all(v["gbps"] > 0 for v in cands.values())

    def test_seed_from_bench_highcard_field(self):
        from flox_tpu import autotune

        import flox_tpu.cache as cache

        cache.clear_all()
        n = autotune._seed_from_bench_record({
            "platform": "cpu",
            "workload": {},
            "highcard": {
                "ngroups": 1 << 20, "nelems": 1 << 16,
                "dense_gbps": 1.0, "sort_gbps": 3.0,
            },
        })
        assert n == 2
        with flox_tpu.set_options(autotune=True):
            rec = autotune.lookup(
                "highcard", dtype="float32", ngroups=1 << 20, nelems=1 << 16
            )
            assert rec is not None
            chosen = autotune.decide(
                "highcard", "dense", ("dense", "sort"),
                dtype="float32", ngroups=1 << 20, nelems=1 << 16,
            )
        assert chosen == "sort"
        cache.clear_all()

    def test_nearest_band_bounds_the_group_axis(self):
        # the highcard winner is governed by ngroups (the crossover axis):
        # a record swept at the capped universe must not serve decisions
        # for universes on the other side of the crossover
        from flox_tpu import autotune

        import flox_tpu.cache as cache

        cache.clear_all()
        with flox_tpu.set_options(autotune=True):
            autotune.record(
                "highcard", "sort", 5.0, dtype="float32",
                ngroups=1 << 20, nelems=1 << 16, source="seed",
            )
            near = autotune.lookup(
                "highcard", dtype="float32", ngroups=1 << 19, nelems=1 << 16
            )
            far = autotune.lookup(
                "highcard", dtype="float32", ngroups=1 << 12, nelems=1 << 16
            )
        assert near is not None
        assert far is None, "a 2^20-universe record served a 2^12 decision"
        cache.clear_all()

    def test_analytic_prior_directions(self):
        with flox_tpu.set_options(costmodel=True, telemetry=True):
            from flox_tpu.costmodel import analytic_prior

            assert analytic_prior(
                "highcard", "dense", ("dense", "sort"),
                dtype="float64", ngroups=50_000_000, nelems=100_000,
            ) == "sort"
            assert analytic_prior(
                "highcard", "dense", ("dense", "sort"),
                dtype="float64", ngroups=64, nelems=10_000_000,
            ) == "dense"

    def test_present_cache_registered(self, codes):
        import flox_tpu.cache as cache

        cache.clear_all()
        present_groups(codes, UNIVERSE)
        assert cache.stats()["present_tables"] == 1
        # memo hit: same content -> same table object, no second entry
        present_groups(codes.copy(), UNIVERSE)
        assert cache.stats()["present_tables"] == 1
        cache.clear_all()
        assert cache.stats()["present_tables"] == 0

    def test_sort_program_label_in_cost_ledger(self, codes):
        from flox_tpu import telemetry

        vals = RNG.normal(size=N)
        with flox_tpu.set_options(telemetry=True):
            groupby_reduce(
                vals, codes, func="nanmean",
                expected_groups=np.arange(UNIVERSE), engine="sort",
            )
            rows = telemetry.cost_by_program()
        assert any(k.startswith("sort[") for k in rows), list(rows)


# ---------------------------------------------------------------------------
# the radix-binning Pallas kernel (interpret mode off-TPU)
# ---------------------------------------------------------------------------


class TestRadixBin:
    def test_past_dense_vmem_cap(self):
        # group counts past pallas_num_groups_max (512) are exactly the
        # radixbin regime
        import jax.numpy as jnp

        from flox_tpu.pallas_kernels import segment_sum_radixbin_pallas

        rng = np.random.default_rng(5)
        n, k, size = 2048, 24, 1800
        data = rng.normal(size=(n, k)).astype(np.float32)
        codes = np.sort(rng.integers(0, size, n)).astype(np.int32)
        out = segment_sum_radixbin_pallas(
            jnp.asarray(data), jnp.asarray(codes), size, interpret=True
        )
        oracle = jax.ops.segment_sum(
            jnp.asarray(data.astype(np.float64)), jnp.asarray(codes),
            num_segments=size,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(oracle).astype(np.float32), rtol=2e-4
        )

    def test_ieee_markers_and_missing(self):
        import jax.numpy as jnp

        from flox_tpu.pallas_kernels import segment_sum_radixbin_pallas

        rng = np.random.default_rng(6)
        n, size = 600, 700
        data = rng.normal(size=(n, 8)).astype(np.float32)
        data[4, 2] = np.nan
        data[9, 0] = np.inf
        codes = rng.integers(0, size, n).astype(np.int32)
        codes[17] = -1  # missing drops out
        out = np.asarray(segment_sum_radixbin_pallas(
            jnp.asarray(data), jnp.asarray(codes), size, interpret=True
        ))
        assert np.isnan(out[codes[4], 2])
        assert np.isposinf(out[codes[9], 0])

    def test_policy_dispatch(self):
        # segment_sum_impl="radixbin" routes _seg through the blocked grid
        # off-TPU via interpret mode; results match scatter to f32 accuracy
        codes = RNG.integers(0, 2000, 4096)
        vals = RNG.normal(size=4096).astype(np.float32)
        eg = np.arange(2000)
        with flox_tpu.set_options(segment_sum_impl="radixbin"):
            r1, _ = groupby_reduce(vals, codes, func="nansum", expected_groups=eg, engine="jax")
        r2, _ = groupby_reduce(vals, codes, func="nansum", expected_groups=eg, engine="jax")
        np.testing.assert_allclose(np.asarray(r1), np.asarray(r2), rtol=1e-4)

    def test_policy_guard_falls_back(self):
        # past radixbin_num_groups_max the policy degrades to scatter
        from flox_tpu.kernels import _segment_sum_impl

        class _Probe:
            dtype = np.dtype("float32")
            shape = (4096,)
            ndim = 1

        with flox_tpu.set_options(
            segment_sum_impl="radixbin", radixbin_num_groups_max=1024
        ):
            assert _segment_sum_impl(_Probe(), 2048) == "scatter"
            assert _segment_sum_impl(_Probe(), 512) == "radixbin"
