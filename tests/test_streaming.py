"""Out-of-core streaming runtime tests (flox_tpu/streaming.py).

The role model is the reference's chunked backends (dask.py:325-573,
cubed.py:30-162): arrays bigger than device memory reduce chunk-by-chunk.
Here slabs stream through device accumulators; every result must equal the
all-at-once eager path.
"""

import numpy as np
import pytest

from flox_tpu.core import groupby_reduce
from flox_tpu.streaming import streaming_groupby_reduce

STREAM_FUNCS = [
    "sum", "nansum", "prod", "nanprod", "mean", "nanmean", "var", "nanvar",
    "std", "nanstd", "max", "nanmax", "min", "nanmin", "count", "all", "any",
    "argmax", "argmin", "nanargmax", "nanargmin",
    "first", "last", "nanfirst", "nanlast",
]


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(0)
    n = 10_000
    vals = rng.normal(size=(4, n))
    vals[:, ::11] = np.nan
    labels = rng.integers(0, 7, n)
    return vals, labels


@pytest.mark.parametrize("func", STREAM_FUNCS)
@pytest.mark.parametrize("batch_len", [997, 4096])
def test_streaming_equals_eager(data, func, batch_len):
    vals, labels = data
    if func in ("argmax", "argmin"):
        vals = np.nan_to_num(vals, nan=0.5)  # propagating args: NaN-free data
    fkw = {"finalize_kwargs": {"ddof": 1}} if func in ("var", "nanvar") else {}
    ref, g1 = groupby_reduce(vals, labels, func=func, **fkw)
    got, g2 = streaming_groupby_reduce(vals, labels, func=func, batch_len=batch_len, **fkw)
    np.testing.assert_array_equal(g1, g2)
    np.testing.assert_allclose(
        np.asarray(got).astype(float), np.asarray(ref).astype(float),
        rtol=1e-10, atol=1e-10, equal_nan=True,
    )


def test_loader_callable(data):
    vals, labels = data

    calls = []

    def loader(s, e):
        calls.append((s, e))
        return vals[..., s:e]

    got, _ = streaming_groupby_reduce(loader, labels, func="nanmean", batch_len=1024)
    ref, _ = groupby_reduce(vals, labels, func="nanmean")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-10)
    # slabs were actually requested incrementally
    assert len([c for c in calls if c[1] - c[0] > 1]) == int(np.ceil(vals.shape[-1] / 1024))


def test_expected_groups_and_bins(data):
    vals, labels = data
    got, groups = streaming_groupby_reduce(
        vals, labels, func="count", batch_len=512, expected_groups=np.arange(10)
    )
    assert np.asarray(got).shape[-1] == 10
    assert (np.asarray(got)[..., 7:] == 0).all()
    # binning
    cont = labels.astype(float)
    got_b, bins = streaming_groupby_reduce(
        vals, cont, func="nansum", batch_len=512,
        expected_groups=np.array([0.0, 3.0, 7.0]), isbin=True,
    )
    ref_b, _ = groupby_reduce(vals, cont, func="nansum",
                              expected_groups=np.array([0.0, 3.0, 7.0]), isbin=True)
    np.testing.assert_allclose(np.asarray(got_b), np.asarray(ref_b), rtol=1e-10)


def test_min_count(data):
    vals, labels = data
    got, _ = streaming_groupby_reduce(vals, labels, func="nansum", batch_len=512,
                                      min_count=10_000)
    assert np.isnan(np.asarray(got)).all()  # nothing reaches min_count


def test_min_count_var_matches_eager(data):
    # regression: the min_count-appended nanlen leg used to leak into
    # _var_finalize as a stray positional (ddof became a count array),
    # poisoning every group to NaN on the streaming path; the runtime
    # computes its own counts, so the appended leg is stripped like
    # sharded_groupby_reduce strips it
    vals, labels = data
    got, _ = streaming_groupby_reduce(vals, labels, func="nanvar", batch_len=997,
                                      min_count=2)
    ref, _ = groupby_reduce(vals, labels, func="nanvar", min_count=2)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=1e-12, equal_nan=True
    )
    assert not np.isnan(np.asarray(got)).all()


def test_mode_rejected_median_streams(data):
    # median/quantile stream now (TestStreamingOrderStats); mode's
    # run-length structure still cannot
    vals, labels = data
    with pytest.raises(NotImplementedError, match="stream"):
        streaming_groupby_reduce(vals, labels, func="nanmode")
    got, _ = streaming_groupby_reduce(vals, labels, func="median", batch_len=2048)
    ref, _ = groupby_reduce(vals, labels, func="median")
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=5e-16, equal_nan=True
    )


def test_single_batch_degenerate(data):
    vals, labels = data
    got, _ = streaming_groupby_reduce(vals, labels, func="nanmean",
                                      batch_len=vals.shape[-1])
    ref, _ = groupby_reduce(vals, labels, func="nanmean")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-12)


def test_custom_aggregation_streams(data):
    # review regression: callable combines fold pairwise, MultiArray-safe
    import jax.numpy as jnp

    from flox_tpu import Aggregation

    def sq(gi, a, *, axis=-1, size, fill_value=None, dtype=None, **kw):
        from flox_tpu.kernels import generic_kernel

        return generic_kernel("nansum", gi, jnp.asarray(a) ** 2, size=size, fill_value=0.0)

    def ct(gi, a, *, axis=-1, size, fill_value=None, dtype=None, **kw):
        from flox_tpu.kernels import generic_kernel

        return generic_kernel("nanlen", gi, a, size=size)

    rms = Aggregation(
        "rms", numpy=(sq, ct), chunk=(sq, ct),
        combine=(lambda s: s.sum(0), lambda s: s.sum(0)),
        finalize=lambda ss, n, **kw: (ss / n) ** 0.5,
        fill_value={"intermediate": (0.0, 0)}, final_fill_value=np.nan,
    )
    vals, labels = data
    got, _ = streaming_groupby_reduce(vals, labels, func=rms, batch_len=997)
    ref, _ = groupby_reduce(vals, labels, func=rms)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=1e-12, equal_nan=True
    )


class TestStreamingPipeline:
    """ISSUE 2: the prefetched staging pipeline (flox_tpu/pipeline.py) must
    change WHEN slabs are staged, never what lands on device — prefetch
    on/off is bit-identical for every streaming entry point, a loader
    exception surfaces promptly, and the donation/throttle knobs never
    change results."""

    @staticmethod
    def _bits(x):
        return np.ascontiguousarray(np.asarray(x)).tobytes()

    @pytest.mark.parametrize("func", ["nansum", "mean", "nanvar", "argmax",
                                      "nanfirst", "count", "min"])
    def test_reduce_bit_identical(self, data, func):
        import flox_tpu

        vals, labels = data
        if func == "argmax":
            vals = np.nan_to_num(vals, nan=0.5)
        # batch_len=997 leaves a padded final slab (10000 % 997 != 0); the
        # NaN-seeded fixture exercises the NaN fill paths
        results = {}
        for depth in (0, 1, 3):
            with flox_tpu.set_options(stream_prefetch=depth):
                got, _ = streaming_groupby_reduce(vals, labels, func=func, batch_len=997)
            results[depth] = self._bits(got)
        assert results[1] == results[0]
        assert results[3] == results[0]

    def test_reduce_nan_fill_min_count_bit_identical(self, data):
        import flox_tpu

        vals, labels = data
        for depth in (0, 2):
            with flox_tpu.set_options(stream_prefetch=depth):
                got, _ = streaming_groupby_reduce(
                    vals, labels, func="nansum", batch_len=997, min_count=10_000
                )
            if depth == 0:
                base = self._bits(got)
        assert np.isnan(np.asarray(got)).all()
        assert self._bits(got) == base

    @pytest.mark.parametrize("func", ["cumsum", "nancumsum", "ffill", "bfill"])
    def test_scan_bit_identical(self, data, func):
        import flox_tpu
        from flox_tpu import streaming_groupby_scan

        vals, labels = data
        sub_v, sub_l = vals[:, :4000], labels[:4000]
        results = {}
        for depth in (0, 2):
            with flox_tpu.set_options(stream_prefetch=depth):
                got = streaming_groupby_scan(sub_v, sub_l, func=func, batch_len=700)
            results[depth] = self._bits(got)
        assert results[2] == results[0]

    def test_quantile_bit_identical(self, data):
        import flox_tpu

        vals, labels = data
        results = {}
        for depth in (0, 2):
            with flox_tpu.set_options(stream_prefetch=depth):
                # expected_groups=10 leaves empty groups -> the NaN fill path
                got, _ = streaming_groupby_reduce(
                    vals, labels, func="nanmedian", batch_len=700,
                    expected_groups=np.arange(10),
                )
            results[depth] = self._bits(got)
        assert results[2] == results[0]

    @pytest.mark.parametrize("depth", [0, 2])
    def test_loader_error_surfaces_promptly(self, data, depth):
        import time

        import flox_tpu

        vals, labels = data

        def bad_loader(s, e):
            if s >= 2048:
                raise RuntimeError("stream loader failed")
            return vals[:, s:e]

        t0 = time.perf_counter()
        with flox_tpu.set_options(stream_prefetch=depth):
            with pytest.raises(RuntimeError, match="stream loader failed"):
                streaming_groupby_reduce(
                    bad_loader, labels, func="nanmean", batch_len=1024
                )
        # "promptly": the pipeline must not sit on the exception (nor hang);
        # generous bound, only there to catch a wedged worker
        assert time.perf_counter() - t0 < 30.0
        # and the staging pool is torn down, not leaked
        import threading

        time.sleep(0.05)
        assert not [t for t in threading.enumerate() if "flox-tpu-stage" in t.name]

    def test_scan_loader_error_surfaces(self, data):
        import flox_tpu
        from flox_tpu import streaming_groupby_scan

        vals, labels = data

        def bad_loader(s, e):
            if s >= 2048:
                raise RuntimeError("scan loader failed")
            return vals[:, s:e]

        with flox_tpu.set_options(stream_prefetch=3):
            with pytest.raises(RuntimeError, match="scan loader failed"):
                streaming_groupby_scan(
                    bad_loader, labels, func="nancumsum", batch_len=1024
                )

    def test_throttle_and_donation_off_results_unchanged(self, data):
        import flox_tpu

        vals, labels = data
        # force donation ON for the reference: on a backend whose probe
        # fails, "auto" would compare the undonated path against itself
        # and a donation bug would pass silently
        with flox_tpu.set_options(stream_donate="on"):
            ref, _ = streaming_groupby_reduce(vals, labels, func="nanmean", batch_len=997)
        with flox_tpu.set_options(stream_dispatch_depth=1, stream_donate="off"):
            got, _ = streaming_groupby_reduce(vals, labels, func="nanmean", batch_len=997)
        assert self._bits(got) == self._bits(ref)

    def test_stream_monitor_reports_pipeline(self, data):
        import flox_tpu
        from flox_tpu import profiling

        vals, labels = data
        with flox_tpu.set_options(stream_prefetch=2):
            with profiling.stream_monitor() as reports:
                streaming_groupby_reduce(vals, labels, func="nanmean", batch_len=997)
        assert len(reports) == 1
        rep = reports[0]
        assert rep.prefetch == 2
        assert len(rep.slabs) == rep.nbatches == int(np.ceil(vals.shape[-1] / 997))
        assert rep.wall_ms > 0
        assert 0.0 <= rep.overlap_fraction <= 1.0
        assert "overlap" in rep.summary()
        # sync mode: the whole staging wall sits on the critical path
        with flox_tpu.set_options(stream_prefetch=0):
            with profiling.stream_monitor() as sync_reports:
                streaming_groupby_reduce(vals, labels, func="nanmean", batch_len=997)
        assert sync_reports[0].overlap_fraction == 0.0


class TestWideStreaming:
    """VERDICT r3 #8: nD labels and partial-axis reductions stream through
    the same flatten contract core.groupby_reduce uses."""

    @pytest.mark.parametrize("func", ["nansum", "nanmean", "nanvar", "nanmax", "count"])
    def test_nd_labels_match_eager(self, func):
        rng = np.random.default_rng(3)
        labels = rng.integers(0, 6, (12, 40))
        vals = rng.normal(size=(3, 12, 40))
        vals[:, rng.random((12, 40)) < 0.15] = np.nan
        ref, g1 = groupby_reduce(vals, labels, func=func)
        got, g2 = streaming_groupby_reduce(vals, labels, func=func, batch_len=53)
        np.testing.assert_array_equal(g1, g2)
        np.testing.assert_allclose(
            np.asarray(got).astype(float), np.asarray(ref).astype(float),
            rtol=1e-10, atol=1e-10, equal_nan=True,
        )

    @pytest.mark.parametrize("axis", [-1, (-2,), (-2, -1)])
    def test_partial_axis_matches_eager(self, axis):
        rng = np.random.default_rng(4)
        labels = rng.integers(0, 5, (10, 24))
        vals = rng.normal(size=(2, 10, 24))
        ref, g1 = groupby_reduce(vals, labels, func="nanmean", axis=axis)
        got, g2 = streaming_groupby_reduce(
            vals, labels, func="nanmean", axis=axis, batch_len=17
        )
        np.testing.assert_array_equal(g1, g2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-10,
                                   atol=1e-10, equal_nan=True)

    def test_axis_below_by_span_broadcasts(self):
        # reducing over a dim the labels don't cover: labels broadcast over
        # it, exactly as in groupby_reduce
        rng = np.random.default_rng(5)
        labels = rng.integers(0, 4, 30)
        vals = rng.normal(size=(6, 30))
        ref, _ = groupby_reduce(vals, labels, func="sum", axis=(0, 1))
        got, _ = streaming_groupby_reduce(vals, labels, func="sum", axis=(0, 1), batch_len=7)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-10)

    def test_loader_keeps_1d_contract(self):
        labels = np.zeros((2, 3), dtype=np.int64)
        with pytest.raises(NotImplementedError, match="1-D"):
            streaming_groupby_reduce(lambda s, e: np.ones((1, e - s)), labels, func="sum")
        with pytest.raises(NotImplementedError, match="host array"):
            streaming_groupby_reduce(
                lambda s, e: np.ones((1, e - s)), np.zeros(6, np.int64),
                func="sum", axis=(0,),
            )

    def test_datetime_all_with_epoch_zero_in_later_slab(self):
        # review regression: bool intermediates (the 'all' min-combine) must
        # not hit the NaT marker re-injection — the int64 marker casts to
        # True and would absorb the merge, turning 'all' into 'any'
        n = 100
        codes = np.zeros(n, dtype=np.int64)
        dt = np.full(n, np.datetime64("2020-01-01", "ns"))
        dt[80] = np.datetime64(0, "ns")  # epoch zero (falsy), second slab
        ref, _ = groupby_reduce(dt, codes, func="all")
        got, _ = streaming_groupby_reduce(dt, codes, func="all", batch_len=50)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
        assert not bool(np.asarray(got)[0])

    @pytest.mark.parametrize(
        "func",
        ["min", "nanmin", "max", "nanmax", "first", "last", "nanfirst",
         "nanlast", "count", "mean", "nanmean", "argmax", "nanargmin",
         "any", "all"],
    )
    def test_datetime_streams_like_eager(self, func):
        # VERDICT r3 weak #6 follow-through: datetime slabs stream with the
        # same NaT semantics as the eager path (int64 view for
        # dtype-preserving funcs, per-slab NaT->NaN f64 for float-returning)
        rng = np.random.default_rng(6)
        n = 300
        codes = rng.integers(0, 5, n)
        dt = (
            np.datetime64("2020-01-01", "ns")
            + rng.integers(0, 10**9, n).astype("timedelta64[ns]")
        )
        dt[rng.random(n) < 0.2] = np.datetime64("NaT")
        ref, _ = groupby_reduce(dt, codes, func=func)
        got, _ = streaming_groupby_reduce(dt, codes, func=func, batch_len=37)
        got, ref = np.asarray(got), np.asarray(ref)
        if func in ("mean", "nanmean"):
            # float-epoch round-trip: ~256 ns resolution at 2020 epoch
            # values (documented in core.py:535-540); slab-wise summation
            # orders differently than the eager single pass
            np.testing.assert_allclose(
                got.astype("int64").astype(np.float64),
                ref.astype("int64").astype(np.float64),
                rtol=1e-12,
            )
        else:
            np.testing.assert_array_equal(got, ref)

    def test_timedelta_sum_streams(self):
        rng = np.random.default_rng(7)
        n = 200
        codes = rng.integers(0, 4, n)
        td = rng.integers(1, 1000, n).astype("timedelta64[ns]")
        td[rng.random(n) < 0.2] = np.timedelta64("NaT")
        ref, _ = groupby_reduce(td, codes, func="nansum")
        got, _ = streaming_groupby_reduce(td, codes, func="nansum", batch_len=23)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


class TestMeshStreaming:
    """streaming x mesh composition (VERDICT r4 #2): slabs device_put
    sharded over the mesh, per-device local accumulation, ONE collective
    combine at the end — the chunked-runtime x scheduler composition the
    reference gets from dask (dask.py:325-573)."""

    @pytest.fixture(scope="class")
    def mesh(self):
        from flox_tpu.parallel.mesh import make_mesh

        return make_mesh()

    @pytest.fixture(scope="class")
    def mdata(self):
        rng = np.random.default_rng(11)
        n = 6000
        vals = rng.normal(size=(4, n))
        vals[:, ::13] = np.nan
        labels = rng.integers(0, 9, n)
        return vals, labels

    @pytest.mark.parametrize("func", STREAM_FUNCS)
    def test_matches_eager(self, mesh, mdata, func):
        vals, labels = mdata
        v = vals if func not in ("any", "all") else ~np.isnan(vals)
        expected, eg = groupby_reduce(v, labels, func=func)
        got, g = streaming_groupby_reduce(
            v, labels, func=func, batch_len=997, mesh=mesh
        )
        np.testing.assert_array_equal(g, eg)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=1e-12, equal_nan=True
        )

    def test_batch_len_rounds_to_shards(self, mesh, mdata):
        # batch_len not divisible by ndev rounds up; results unchanged
        vals, labels = mdata
        expected, _ = groupby_reduce(vals, labels, func="nansum")
        got, _ = streaming_groupby_reduce(
            vals, labels, func="nansum", batch_len=1001, mesh=mesh
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected), rtol=1e-12)

    def test_loader_streams_to_mesh(self, mesh, mdata):
        vals, labels = mdata
        expected, _ = groupby_reduce(vals, labels, func="nanmean")
        got, _ = streaming_groupby_reduce(
            lambda s, e: vals[:, s:e], labels, func="nanmean",
            batch_len=512, mesh=mesh,
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected), rtol=1e-12, equal_nan=True)

    def test_datetime_nat_crosses_shards_and_slabs(self, mesh):
        rng = np.random.default_rng(5)
        n = 4000
        labels = rng.integers(0, 6, n)
        dt = (
            np.datetime64("2021-06-01")
            + rng.integers(0, 10**6, n).astype("timedelta64[s]")
        ).astype("datetime64[ns]")
        dt[rng.random(n) < 0.04] = np.datetime64("NaT")
        for func in ("min", "nanmax", "first", "nanlast", "mean", "count"):
            expected, _ = groupby_reduce(dt, labels, func=func)
            got, _ = streaming_groupby_reduce(dt, labels, func=func, batch_len=640, mesh=mesh)
            np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))

    def test_custom_aggregation_on_mesh(self, mesh, mdata):
        import jax.numpy as jnp

        from flox_tpu import Aggregation

        def sq(gi, a, *, axis=-1, size, fill_value=None, dtype=None, **kw):
            from flox_tpu.kernels import generic_kernel

            return generic_kernel("nansum", gi, jnp.asarray(a) ** 2, size=size, fill_value=0.0)

        def ct(gi, a, *, axis=-1, size, fill_value=None, dtype=None, **kw):
            from flox_tpu.kernels import generic_kernel

            return generic_kernel("nanlen", gi, a, size=size)

        rms = Aggregation(
            "rms", numpy=(sq, ct), chunk=(sq, ct),
            combine=(lambda s: s.sum(0), lambda s: s.sum(0)),
            finalize=lambda ss, nn, **kw: (ss / nn) ** 0.5,
            fill_value={"intermediate": (0.0, 0)}, final_fill_value=np.nan,
        )
        vals, labels = mdata
        expected, _ = groupby_reduce(vals, labels, func=rms)
        got, _ = streaming_groupby_reduce(vals, labels, func=rms, batch_len=800, mesh=mesh)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=1e-12, equal_nan=True
        )

    def test_program_cache_reused(self, mesh, mdata):
        # repeat same-shaped calls must not retrace (code-review r5):
        # the compiled (step, final) pair is cached like the sharded
        # runtime's _PROGRAM_CACHE
        from flox_tpu.streaming import _STEP_CACHE

        vals, labels = mdata
        _STEP_CACHE.clear()
        streaming_groupby_reduce(vals, labels, func="nansum", batch_len=997, mesh=mesh)
        assert len(_STEP_CACHE) == 1
        vals2 = vals + 1.0
        streaming_groupby_reduce(vals2, labels, func="nansum", batch_len=997, mesh=mesh)
        assert len(_STEP_CACHE) == 1  # hit, not a rebuild
        # clear_all drops it with every other program cache
        import flox_tpu.cache

        flox_tpu.cache.clear_all()
        assert len(_STEP_CACHE) == 0

    def test_min_count_on_mesh(self, mesh, mdata):
        vals, labels = mdata
        expected, _ = groupby_reduce(vals, labels, func="nansum", min_count=800)
        got, _ = streaming_groupby_reduce(
            vals, labels, func="nansum", min_count=800, batch_len=900, mesh=mesh
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=1e-12, equal_nan=True
        )


class TestMeshStreamingBlocked:
    """Above dense_intermediate_bytes_max, additive reductions stream with
    owner-blocked (…, size/ndev) per-device accumulators — a group space
    above any single device's ceiling (VERDICT r4 #2 'above the
    single-device ceiling')."""

    @pytest.fixture(scope="class")
    def mesh(self):
        from flox_tpu.parallel.mesh import make_mesh

        return make_mesh()

    def test_blocked_sum_and_var_match_eager(self, mesh):
        import flox_tpu

        rng = np.random.default_rng(17)
        n, size = 6000, 40_000
        labels = rng.integers(0, size, n)
        vals = rng.normal(size=(4, n))
        exp_sum, _ = groupby_reduce(vals, labels, func="sum", expected_groups=np.arange(size), fill_value=0)
        exp_var, _ = groupby_reduce(vals, labels, func="nanvar", expected_groups=np.arange(size))
        # dense per-device accumulators (~4*40000*8B x legs) exceed the
        # ceiling; owned (size/8) blocks + the result fit under it
        with flox_tpu.set_options(dense_intermediate_bytes_max=4 * 2**20):
            got_sum, _ = streaming_groupby_reduce(
                vals, labels, func="sum", expected_groups=np.arange(size),
                fill_value=0, batch_len=800, mesh=mesh,
            )
            got_var, _ = streaming_groupby_reduce(
                vals, labels, func="nanvar", expected_groups=np.arange(size),
                batch_len=800, mesh=mesh,
            )
        np.testing.assert_allclose(np.asarray(got_sum), np.asarray(exp_sum), rtol=1e-12)
        np.testing.assert_allclose(
            np.asarray(got_var), np.asarray(exp_var), rtol=1e-9, equal_nan=True
        )

    def test_non_additive_above_ceiling_routes_to_sort(self, mesh):
        # a non-additive agg over the ceiling used to be a dead end (no
        # owner-blocked form for max); the present-groups engine now absorbs
        # it — the carry tracks the <= 2000 present groups, not the 40k
        # universe — bit-identical to the unconstrained dense run
        import flox_tpu
        from flox_tpu import groupby_reduce

        rng = np.random.default_rng(17)
        n, size = 2000, 40_000
        labels = rng.integers(0, size, n)
        vals = rng.normal(size=(4, n))
        want, _ = groupby_reduce(
            vals, labels, func="max", expected_groups=np.arange(size),
            engine="jax",
        )
        with flox_tpu.set_options(dense_intermediate_bytes_max=2 * 2**20):
            got, _ = streaming_groupby_reduce(
                vals, labels, func="max", expected_groups=np.arange(size),
                batch_len=800, mesh=mesh,
            )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_non_additive_above_ceiling_pinned_dense_raises(self, mesh):
        # an explicitly pinned dense engine is never second-guessed: the
        # old actionable error stands
        import flox_tpu

        rng = np.random.default_rng(17)
        n, size = 2000, 40_000
        labels = rng.integers(0, size, n)
        vals = rng.normal(size=(4, n))
        with flox_tpu.set_options(dense_intermediate_bytes_max=2 * 2**20):
            with pytest.raises(ValueError, match="cannot be distributed by group ownership"):
                streaming_groupby_reduce(
                    vals, labels, func="max", expected_groups=np.arange(size),
                    batch_len=800, mesh=mesh, engine="jax",
                )


class TestStreamingOrderStats:
    """Out-of-core EXACT quantile/median (beyond-reference capability —
    the reference's chunked quantile needs whole groups per block): the
    radix-select bisection consumes only per-group counts, which
    accumulate slab by slab in nbits+1 passes over the loader."""

    @pytest.fixture(scope="class")
    def qdata(self):
        rng = np.random.default_rng(23)
        n = 5000
        vals = rng.normal(size=(3, n))
        vals[:, ::11] = np.nan
        labels = rng.integers(0, 9, n)
        return vals, labels

    @pytest.mark.parametrize("func,fkw", [
        ("nanmedian", None),
        ("median", None),
        ("nanquantile", {"q": 0.9}),
        ("quantile", {"q": [0.25, 0.75]}),
        ("nanquantile", {"q": 0.3, "method": "nearest"}),
        ("nanquantile", {"q": 0.6, "method": "midpoint"}),
        ("nanquantile", {"q": 0.5, "method": "hazen"}),
    ])
    def test_matches_eager(self, qdata, func, fkw):
        vals, labels = qdata
        expected, eg = groupby_reduce(vals, labels, func=func, finalize_kwargs=fkw)
        got, g = streaming_groupby_reduce(
            vals, labels, func=func, finalize_kwargs=fkw, batch_len=700
        )
        np.testing.assert_array_equal(g, eg)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=5e-16, equal_nan=True
        )

    def test_loader_and_int_dtype(self, qdata):
        _, labels = qdata
        rng = np.random.default_rng(5)
        iv = rng.integers(-100, 100, size=labels.shape[0])
        expected, _ = groupby_reduce(iv, labels, func="median")
        got, _ = streaming_groupby_reduce(
            lambda s, e: iv[s:e], labels, func="median", batch_len=640
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected), rtol=1e-15)

    def test_datetime_nat(self, qdata):
        _, labels = qdata
        rng = np.random.default_rng(7)
        dt = np.datetime64("2020-01-01", "ns") + rng.integers(
            0, 10**9, labels.shape[0]
        ).astype("timedelta64[ns]")
        dt[::17] = np.datetime64("NaT")
        expected, _ = groupby_reduce(dt, labels, func="nanmedian")
        got, _ = streaming_groupby_reduce(dt, labels, func="nanmedian", batch_len=640)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))

    def test_expected_groups_with_empty(self, qdata):
        vals, labels = qdata
        expected, _ = groupby_reduce(
            vals, labels, func="nanmedian", expected_groups=np.arange(12)
        )
        got, _ = streaming_groupby_reduce(
            vals, labels, func="nanmedian", expected_groups=np.arange(12), batch_len=900
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=5e-16, equal_nan=True
        )

    def test_mode_still_rejected(self, qdata):
        vals, labels = qdata
        with pytest.raises(NotImplementedError, match="cannot stream"):
            streaming_groupby_reduce(vals, labels, func="mode", batch_len=700)

    def test_mesh_streaming_median_propagates_nan(self, qdata):
        # the non-skipna hasnan channel must pmax across shards: ONE NaN
        # total, placed so it lands on a single shard of a single slab —
        # without the pmax only that shard would flag the group, and the
        # check_vma=False replication claim would accept the wrong lanes
        import jax

        from flox_tpu.parallel.mesh import make_mesh

        rng = np.random.default_rng(99)
        n = 4096
        labels = rng.integers(0, 9, n)
        vals = rng.normal(size=(2, n))
        batch_len = 1024
        ndev = len(jax.devices())
        shard_len = batch_len // ndev
        # inside slab 1, shard 2: position = slab_start + shard*shard_len + 3
        vals[:, batch_len + 2 * shard_len + 3] = np.nan
        expected, _ = groupby_reduce(vals, labels, func="median")
        assert np.isnan(np.asarray(expected)).any()  # the case is exercised
        got, _ = streaming_groupby_reduce(
            vals, labels, func="median", batch_len=batch_len, mesh=make_mesh()
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=5e-16, atol=0, equal_nan=True
        )

    def test_mesh_streaming_quantile_two_axis_mesh(self, qdata):
        # ("dcn","ici")-style 2-axis mesh: the tuple spec_entry branch
        import jax

        from flox_tpu.parallel.mesh import make_mesh

        vals, labels = qdata
        ndev = len(jax.devices())
        if ndev < 4:
            pytest.skip("needs >= 4 devices for a 2-D mesh")
        mesh = make_mesh(shape=(2, ndev // 2), axis_names=("dcn", "ici"))
        expected, _ = streaming_groupby_reduce(
            vals, labels, func="nanmedian", batch_len=700
        )
        got, _ = streaming_groupby_reduce(
            vals, labels, func="nanmedian", batch_len=700,
            mesh=mesh, axis_name=("dcn", "ici"),
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=5e-16, atol=0, equal_nan=True
        )

    def test_mesh_streaming_quantile_composes(self, qdata):
        # out-of-core AND distributed at once: slabs scatter over the mesh,
        # every counting pass psums; bit-identical to eager select
        import flox_tpu
        from flox_tpu.parallel.mesh import make_mesh

        vals, labels = qdata
        with flox_tpu.set_options(quantile_impl="select"):
            expected, _ = groupby_reduce(vals, labels, func="nanmedian")
        got, _ = streaming_groupby_reduce(
            vals, labels, func="nanmedian", batch_len=700, mesh=make_mesh()
        )
        # selection is count-exact; the lerp may differ by an ULP (XLA FMA
        # contraction differs between the shard_map and eager programs)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=5e-16, atol=0, equal_nan=True
        )
        gotq, _ = streaming_groupby_reduce(
            vals, labels, func="nanquantile", batch_len=700, mesh=make_mesh(),
            finalize_kwargs={"q": [0.1, 0.9]},
        )
        with flox_tpu.set_options(quantile_impl="select"):
            expq, _ = groupby_reduce(
                vals, labels, func="nanquantile", finalize_kwargs={"q": [0.1, 0.9]}
            )
        np.testing.assert_allclose(
            np.asarray(gotq), np.asarray(expq), rtol=5e-16, atol=0, equal_nan=True
        )


class TestStreamingScan:
    """Out-of-core grouped scans (the sequential form of the Blelloch
    decomposition the reference runs through dask's cumreduction,
    dask.py:576-663): per-slab segmented scan + per-group carry."""

    @pytest.fixture(scope="class")
    def sdata(self):
        rng = np.random.default_rng(31)
        n = 4000
        vals = rng.normal(size=(2, n))
        vals[:, ::9] = np.nan
        labels = rng.integers(0, 6, n)
        return vals, labels

    @pytest.mark.parametrize("func", ["cumsum", "nancumsum", "ffill", "bfill"])
    @pytest.mark.parametrize("batch_len", [700, 4000])
    def test_matches_eager(self, sdata, func, batch_len):
        from flox_tpu import groupby_scan, streaming_groupby_scan

        vals, labels = sdata
        expected = groupby_scan(vals, labels, func=func)
        got = streaming_groupby_scan(vals, labels, func=func, batch_len=batch_len)
        # carry summation order differs from the eager log-tree scan:
        # last-ulp accumulation noise, same tolerance as the reduce suite
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=1e-10, atol=1e-12,
            equal_nan=True,
        )

    def test_int_promotion_matches(self, sdata):
        from flox_tpu import groupby_scan, streaming_groupby_scan

        _, labels = sdata
        iv = np.arange(labels.shape[0], dtype=np.int32) % 97
        expected = np.asarray(groupby_scan(iv, labels, func="cumsum"))
        got = streaming_groupby_scan(iv, labels, func="cumsum", batch_len=700)
        assert got.dtype == expected.dtype
        np.testing.assert_array_equal(got, expected)

    def test_timedelta_cumsum_nat_poisons_across_slabs(self, sdata):
        from flox_tpu import groupby_scan, streaming_groupby_scan

        _, labels = sdata
        rng = np.random.default_rng(3)
        td = rng.integers(1, 100, labels.shape[0]).astype("timedelta64[ns]")
        td[5] = np.timedelta64("NaT")  # poisons its group in every later slab
        expected = np.asarray(groupby_scan(td, labels, func="cumsum"))
        got = streaming_groupby_scan(td, labels, func="cumsum", batch_len=600)
        assert got.dtype == expected.dtype
        np.testing.assert_array_equal(got.view("int64"), expected.view("int64"))

    def test_datetime_ffill_bfill(self, sdata):
        from flox_tpu import groupby_scan, streaming_groupby_scan

        _, labels = sdata
        rng = np.random.default_rng(4)
        dt = np.datetime64("2020-01-01", "ns") + rng.integers(
            0, 10**9, labels.shape[0]
        ).astype("timedelta64[ns]")
        dt[::13] = np.datetime64("NaT")
        for func in ("ffill", "bfill"):
            expected = np.asarray(groupby_scan(dt, labels, func=func))
            got = streaming_groupby_scan(dt, labels, func=func, batch_len=600)
            np.testing.assert_array_equal(got.view("int64"), expected.view("int64"))

    def test_loader_and_writer_stream_both_ways(self, sdata):
        # the fully out-of-core path: loader in, writer out, nothing
        # array-sized materializes inside
        from flox_tpu import groupby_scan, streaming_groupby_scan

        vals, labels = sdata
        n = labels.shape[0]
        written = np.full((2, n), np.nan)
        spans = []

        def writer(s, e, res):
            spans.append((s, e))
            written[..., s:e] = res

        r = streaming_groupby_scan(
            lambda s, e: vals[:, s:e], labels, func="nancumsum",
            batch_len=512, out=writer,
        )
        assert r is None
        assert spans == [(i * 512, min((i + 1) * 512, n)) for i in range(len(spans))]
        expected = groupby_scan(vals, labels, func="nancumsum")
        # carry summation order differs from the eager log-tree scan:
        # last-ulp accumulation noise, same tolerance as the reduce suite
        np.testing.assert_allclose(
            written, np.asarray(expected), rtol=1e-10, atol=1e-12, equal_nan=True
        )

    def test_missing_labels_scan_to_nan(self, sdata):
        from flox_tpu import groupby_scan, streaming_groupby_scan

        vals, labels = sdata
        lab = labels.copy()
        lab[::50] = 99  # outside expected_groups -> code -1
        expected = np.asarray(
            groupby_scan(vals, lab, func="cumsum", expected_groups=np.arange(6))
        )
        got = streaming_groupby_scan(
            vals, lab, func="cumsum", expected_groups=np.arange(6), batch_len=700
        )
        np.testing.assert_allclose(got, expected, rtol=1e-10, atol=1e-12, equal_nan=True)
        assert np.isnan(got[..., ::50]).all()

    def test_nd_labels_rejected(self, sdata):
        from flox_tpu import streaming_groupby_scan

        vals, _ = sdata
        with pytest.raises(NotImplementedError, match="1-D"):
            streaming_groupby_scan(vals, np.zeros((2, 3), np.int64), func="cumsum")


class TestMeshStreamingScan:
    """streaming x mesh scans: each slab runs the distributed Blelloch with
    cross-slab carry I/O — out-of-core AND multi-chip, results still
    streamable through a writer."""

    @pytest.fixture(scope="class")
    def mesh(self):
        from flox_tpu.parallel.mesh import make_mesh

        return make_mesh()

    @pytest.fixture(scope="class")
    def msdata(self):
        rng = np.random.default_rng(41)
        n = 4096
        vals = rng.normal(size=(2, n))
        vals[:, ::9] = np.nan
        labels = rng.integers(0, 6, n)
        return vals, labels

    @pytest.mark.parametrize("func", ["cumsum", "nancumsum", "ffill", "bfill"])
    def test_matches_eager(self, mesh, msdata, func):
        from flox_tpu import groupby_scan, streaming_groupby_scan

        vals, labels = msdata
        expected = np.asarray(groupby_scan(vals, labels, func=func))
        got = streaming_groupby_scan(vals, labels, func=func, batch_len=1000, mesh=mesh)
        np.testing.assert_allclose(got, expected, rtol=1e-10, atol=1e-12, equal_nan=True)

    def test_timedelta_nat_poisons_across_slabs_and_shards(self, mesh, msdata):
        # ONE NaT: its poison must cross shard boundaries (within-slab
        # collective) AND slab boundaries (the sticky carry channel)
        from flox_tpu import groupby_scan, streaming_groupby_scan

        _, labels = msdata
        rng = np.random.default_rng(6)
        td = rng.integers(1, 100, labels.shape[0]).astype("timedelta64[ns]")
        td[7] = np.timedelta64("NaT")
        expected = np.asarray(groupby_scan(td, labels, func="cumsum"))
        got = streaming_groupby_scan(td, labels, func="cumsum", batch_len=1000, mesh=mesh)
        np.testing.assert_array_equal(got.view("int64"), expected.view("int64"))

    def test_int_promotion_and_writer(self, mesh, msdata):
        from flox_tpu import groupby_scan, streaming_groupby_scan

        _, labels = msdata
        n = labels.shape[0]
        iv = (np.arange(n) % 97).astype(np.int32)
        expected = np.asarray(groupby_scan(iv, labels, func="cumsum"))
        written = np.empty(n, expected.dtype)
        r = streaming_groupby_scan(
            iv, labels, func="cumsum", batch_len=1000, mesh=mesh,
            out=lambda s, e, res: written.__setitem__(slice(s, e), res),
        )
        assert r is None
        np.testing.assert_array_equal(written, expected)

    def test_datetime_ffill(self, mesh, msdata):
        from flox_tpu import groupby_scan, streaming_groupby_scan

        _, labels = msdata
        rng = np.random.default_rng(8)
        dt = np.datetime64("2020-01-01", "ns") + rng.integers(
            0, 10**9, labels.shape[0]
        ).astype("timedelta64[ns]")
        dt[::13] = np.datetime64("NaT")
        expected = np.asarray(groupby_scan(dt, labels, func="ffill"))
        got = streaming_groupby_scan(dt, labels, func="ffill", batch_len=1000, mesh=mesh)
        np.testing.assert_array_equal(got.view("int64"), expected.view("int64"))
