"""Per-round performance regression gate (VERDICT r3 #7).

The reference gates every PR with ``asv continuous --factor 1.5``
(reference .github/workflows/benchmarks.yml:35-58). Here the recorded
round benchmarks are the asv history: each round appends
``BENCH_HISTORY/r{N}_{platform}.jsonl`` (see BENCH_HISTORY/README.md),
and this test compares the latest file against the previous one, per
benchmark FAMILY (the name before ``[``), on the geometric mean of the
common-row ratios — a real code regression slows a family's rows
together and moves the geomean, while single-row timer noise is diluted.

Two tiers, because asv-continuous reruns both commits back-to-back on
one quiet host and a driver round comparing records from different
sessions cannot (observed cross-round swings on this shared host reach
2-3x on code that did not change):

* absolute — latest vs previous wall-clock, threshold
  ``FLOX_BENCH_REGRESSION_THRESHOLD`` (default 2.0): the gross-regression
  backstop.
* normalized — the jax-engine row divided by the SAME round's numpy-engine
  row for the same workload, compared across rounds, threshold
  ``FLOX_BENCH_REGRESSION_THRESHOLD_NORM`` (default 1.5, the reference's
  ASV_FACTOR): host speed cancels in the quotient, so this is the
  sensitive instrument for regressions in the jax compute path.
"""

from __future__ import annotations

import json
import math
import os
import re
from collections import defaultdict

import pytest

HISTORY = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                       "BENCH_HISTORY")


def _round_files(platform: str) -> list[str]:
    if not os.path.isdir(HISTORY):
        return []
    pat = re.compile(rf"^r(\d+)_{platform}\.jsonl$")
    found = []
    for f in os.listdir(HISTORY):
        m = pat.match(f)
        if m:
            found.append((int(m.group(1)), os.path.join(HISTORY, f)))
    return [p for _, p in sorted(found)]


def _load(path: str) -> dict[str, tuple[float, str]]:
    rows = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if isinstance(rec.get("value"), (int, float)):
                rows[rec["bench"]] = (float(rec["value"]), rec.get("unit", ""))
    return rows


def _family(bench: str) -> str:
    return bench.split("[", 1)[0]


def _ratio(latest, prev, bench) -> float | None:
    """latest/prev regression ratio for one bench; >1 means slower."""
    if bench not in latest or bench not in prev:
        return None
    (val, unit), (pval, punit) = latest[bench], prev[bench]
    if unit != punit or val <= 0 or pval <= 0:
        return None
    if unit == "ms":
        return val / pval  # lower is better
    if unit == "GB/s":
        return pval / val  # higher is better
    return None


def _gate(ratios: dict[str, list[tuple[str, float]]], threshold: float, label: str):
    failures = []
    for family, rows in sorted(ratios.items()):
        geomean = math.exp(sum(math.log(r) for _, r in rows) / len(rows))
        if geomean > threshold:
            worst = max(rows, key=lambda t: t[1])
            failures.append(
                f"{family}: {label} geomean {geomean:.2f}x over {len(rows)} "
                f"rows (worst {worst[0]} at {worst[1]:.2f}x)"
            )
    return failures


@pytest.mark.parametrize("platform", ["cpu", "tpu"])
def test_no_regression_vs_previous_round(platform):
    files = _round_files(platform)
    if len(files) < 2:
        pytest.skip(f"fewer than two {platform} rounds recorded")
    prev, latest = _load(files[-2]), _load(files[-1])
    thr_abs = float(os.environ.get("FLOX_BENCH_REGRESSION_THRESHOLD", "2.0"))
    thr_norm = float(os.environ.get("FLOX_BENCH_REGRESSION_THRESHOLD_NORM", "1.5"))

    absolute: dict[str, list[tuple[str, float]]] = defaultdict(list)
    normalized: dict[str, list[tuple[str, float]]] = defaultdict(list)
    for bench in latest:
        r = _ratio(latest, prev, bench)
        if r is None:
            continue
        absolute[_family(bench)].append((bench, r))
        # host-invariant: jax row / the same round's numpy row
        if "jax]" in bench:
            sibling = bench.replace("-jax]", "-numpy]").replace("[jax]", "[numpy]")
            rs = _ratio(latest, prev, sibling)
            if rs is not None:
                normalized[_family(bench)].append((bench, r / rs))

    assert absolute, (
        f"no comparable rows between {files[-2]} and {files[-1]} — "
        "did the bench names change?"
    )
    failures = _gate(absolute, thr_abs, "absolute") + _gate(
        normalized, thr_norm, "jax-vs-numpy normalized"
    )
    assert not failures, (
        f"performance regressed vs {os.path.basename(files[-2])}:\n  "
        + "\n  ".join(failures)
    )
