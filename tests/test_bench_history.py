"""Per-round performance regression gate (VERDICT r3 #7).

The reference gates every PR with ``asv continuous --factor 1.5``
(reference .github/workflows/benchmarks.yml:35-58). Here the recorded
round benchmarks are the asv history: each round appends
``BENCH_HISTORY/r{N}_{platform}.jsonl`` (see BENCH_HISTORY/README.md),
and this test compares the latest file against the previous one, per
benchmark FAMILY (the name before ``[``), on the geometric mean of the
common-row ratios — a real code regression slows a family's rows
together and moves the geomean, while single-row timer noise is diluted.

Two tiers, because asv-continuous reruns both commits back-to-back on
one quiet host and a driver round comparing records from different
sessions cannot (observed cross-round swings on this shared host reach
2-3x on code that did not change):

* absolute — latest vs previous wall-clock, threshold
  ``FLOX_BENCH_REGRESSION_THRESHOLD`` (default 2.0): the gross-regression
  backstop.
* normalized — the jax-engine row divided by the SAME round's numpy-engine
  row for the same workload, compared across rounds, threshold
  ``FLOX_BENCH_REGRESSION_THRESHOLD_NORM`` (default 1.5, the reference's
  ASV_FACTOR): host speed cancels in the quotient, so this is the
  sensitive instrument for regressions in the jax compute path.
"""

from __future__ import annotations

import json
import math
import os
import re
from collections import defaultdict

import pytest

HISTORY = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                       "BENCH_HISTORY")


def _round_files(platform: str) -> list[str]:
    if not os.path.isdir(HISTORY):
        return []
    pat = re.compile(rf"^r(\d+)_{platform}\.jsonl$")
    found = []
    for f in os.listdir(HISTORY):
        m = pat.match(f)
        if m:
            found.append((int(m.group(1)), os.path.join(HISTORY, f)))
    return [p for _, p in sorted(found)]


def _load(path: str) -> dict[str, tuple[float, str]]:
    rows = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if isinstance(rec.get("value"), (int, float)):
                rows[rec["bench"]] = (float(rec["value"]), rec.get("unit", ""))
    return rows


def _family(bench: str) -> str:
    return bench.split("[", 1)[0]


def _ratio(latest, prev, bench) -> float | None:
    """latest/prev regression ratio for one bench; >1 means slower."""
    if bench not in latest or bench not in prev:
        return None
    (val, unit), (pval, punit) = latest[bench], prev[bench]
    if unit != punit or val <= 0 or pval <= 0:
        return None
    if unit == "ms":
        return val / pval  # lower is better
    if unit == "GB/s":
        return pval / val  # higher is better
    return None


def _gate(ratios: dict[str, list[tuple[str, float]]], threshold: float, label: str):
    failures = []
    for family, rows in sorted(ratios.items()):
        geomean = math.exp(sum(math.log(r) for _, r in rows) / len(rows))
        if geomean > threshold:
            worst = max(rows, key=lambda t: t[1])
            failures.append(
                f"{family}: {label} geomean {geomean:.2f}x over {len(rows)} "
                f"rows (worst {worst[0]} at {worst[1]:.2f}x)"
            )
    return failures


def _failing_families(latest: dict, prev: dict, thr_abs: float, thr_norm: float):
    """Families whose geomean exceeds a threshold for ONE round pair.
    Returns {family: message} merged over both tiers."""
    absolute: dict[str, list[tuple[str, float]]] = defaultdict(list)
    normalized: dict[str, list[tuple[str, float]]] = defaultdict(list)
    for bench in latest:
        r = _ratio(latest, prev, bench)
        if r is None:
            continue
        absolute[_family(bench)].append((bench, r))
        # host-invariant: jax row / the same round's numpy row
        if "jax]" in bench:
            sibling = bench.replace("-jax]", "-numpy]").replace("[jax]", "[numpy]")
            rs = _ratio(latest, prev, sibling)
            if rs is not None:
                normalized[_family(bench)].append((bench, r / rs))
    out: dict[str, str] = {}
    for msg in _gate(absolute, thr_abs, "absolute"):
        out[msg.split(":", 1)[0]] = msg
    for msg in _gate(normalized, thr_norm, "jax-vs-numpy normalized"):
        out.setdefault(msg.split(":", 1)[0], msg)
    return out, bool(absolute)


def run_gate(files: list[str], thr_abs: float, thr_norm: float):
    """The regression verdict over a round history (VERDICT r4 #3).

    Cross-session host noise on this shared machine swings 2-3x on
    unchanged code (measured: BENCH_HISTORY/bench_runs.jsonl, vs_baseline
    1.87 -> 145 -> 32 -> 98 across rounds), so a single round-pair
    comparison cannot distinguish signal from a noisy PREVIOUS round.
    With >= 3 rounds recorded, a family fails only when the latest round
    exceeds the threshold against BOTH of the two preceding rounds — two
    independent baselines; one slow/fast outlier round upstream cannot
    produce both exceedances. (A noisy LATEST round is damped separately,
    by the median-of-sweeps recording, benchmarks.py --sweeps.)
    With exactly 2 rounds the single comparison gates alone.
    Returns (failures, comparable) — failures is a list of messages.
    """
    latest = _load(files[-1])
    prev = _load(files[-2])
    fail_prev, comparable = _failing_families(latest, prev, thr_abs, thr_norm)
    if not comparable:
        return [], False
    if len(files) < 3:
        return sorted(fail_prev.values()), True
    prevprev = _load(files[-3])
    fail_pp, pp_comparable = _failing_families(latest, prevprev, thr_abs, thr_norm)
    if not pp_comparable:
        # the second baseline has no rows in common with the latest round
        # (renamed benches, corrupt file) — fall back to the single-pair
        # gate rather than letting an empty intersection mask a regression
        return sorted(fail_prev.values()), True
    confirmed = sorted(
        f"{fam} (confirmed vs both prior rounds): {fail_prev[fam]} AND {fail_pp[fam]}"
        for fam in fail_prev.keys() & fail_pp.keys()
    )
    return confirmed, True


@pytest.mark.parametrize("platform", ["cpu", "tpu"])
def test_no_regression_vs_previous_round(platform):
    files = _round_files(platform)
    if len(files) < 2:
        pytest.skip(f"fewer than two {platform} rounds recorded")
    thr_abs = float(os.environ.get("FLOX_BENCH_REGRESSION_THRESHOLD", "2.0"))
    thr_norm = float(os.environ.get("FLOX_BENCH_REGRESSION_THRESHOLD_NORM", "1.5"))
    failures, comparable = run_gate(files, thr_abs, thr_norm)
    assert comparable, (
        f"no comparable rows between {files[-2]} and {files[-1]} — "
        "did the bench names change?"
    )
    assert not failures, (
        f"performance regressed vs {os.path.basename(files[-2])}:\n  "
        + "\n  ".join(failures)
    )


# ---------------------------------------------------------------------------
# synthetic histories: the gate must fail on signal and pass on the
# measured 2-3x cross-session host swing (VERDICT r4 #3 'done' criterion)
# ---------------------------------------------------------------------------


def _write_round(tmpdir, n, rows):
    path = os.path.join(tmpdir, f"r{n:02d}_cpu.jsonl")
    with open(path, "w") as f:
        for bench, value in rows.items():
            f.write(json.dumps({"bench": bench, "value": value, "unit": "ms"}) + "\n")
    return path


_BASE = {
    "time_reduce[1d-sum-jax]": 0.5,
    "time_reduce[1d-sum-numpy]": 1.0,
    "time_reduce[2d-mean-jax]": 0.8,
    "time_reduce[2d-mean-numpy]": 1.6,
    "time_scan[cumsum-jax]": 2.0,
    "time_scan[cumsum-numpy]": 4.0,
}


def _scaled(factor, only=None):
    return {
        k: round(v * (factor if (only is None or only(k)) else 1.0), 4)
        for k, v in _BASE.items()
    }


class TestSyntheticHistories:
    def test_real_regression_fails(self, tmp_path):
        # a true jax-path regression: the jax rows of one family slow 3x in
        # the latest round and stay slow against both prior baselines
        d = str(tmp_path)
        files = [
            _write_round(d, 1, _BASE),
            _write_round(d, 2, _scaled(1.1)),
            _write_round(d, 3, _scaled(3.0, only=lambda k: "reduce" in k and "jax" in k)),
        ]
        failures, comparable = run_gate(files, 2.0, 1.5)
        assert comparable
        assert failures and "time_reduce" in failures[0]

    def test_host_swing_passes(self, tmp_path):
        # the measured host pattern (BENCH_HISTORY/bench_runs.jsonl): one
        # 2.5x-slow outlier session, then recovery. Every row moves together
        # (both engines), so the jax/numpy quotient cancels the swing, and
        # the absolute tier never sees the latest round slow against BOTH
        # prior baselines.
        d = str(tmp_path)
        files = [
            _write_round(d, 1, _BASE),
            _write_round(d, 2, _scaled(2.5)),   # slow outlier session
            _write_round(d, 3, _scaled(1.2)),   # back to normal
        ]
        failures, comparable = run_gate(files, 2.0, 1.5)
        assert comparable
        # latest vs the outlier is a big IMPROVEMENT; vs r1 it's 1.2x; no fail
        assert failures == []

    def test_noisy_previous_round_cannot_fail_alone(self, tmp_path):
        # the case the 2-round gate got wrong: the PREVIOUS round was a fast
        # outlier (host quiet), latest is normal — latest/prev exceeds 2.0
        # but latest/prevprev does not; the gate must not fire
        d = str(tmp_path)
        files = [
            _write_round(d, 1, _BASE),
            _write_round(d, 2, _scaled(0.4)),   # anomalously fast session
            _write_round(d, 3, _scaled(1.1)),   # normal again: 2.75x vs r2!
        ]
        failures, comparable = run_gate(files, 2.0, 1.5)
        assert comparable
        assert failures == []

    def test_incomparable_prevprev_falls_back_to_pair_gate(self, tmp_path):
        # bench names renamed between r1 and r2: r3-vs-r1 has no common
        # rows, so the gate must fall back to the single-pair comparison
        # instead of letting the empty intersection mask a real regression
        d = str(tmp_path)
        old_names = {k.replace("time_", "old_"): v for k, v in _BASE.items()}
        files = [
            _write_round(d, 1, old_names),
            _write_round(d, 2, _BASE),
            _write_round(d, 3, _scaled(3.0, only=lambda k: "jax" in k)),
        ]
        failures, comparable = run_gate(files, 2.0, 1.5)
        assert comparable
        assert failures

    def test_two_rounds_still_gate(self, tmp_path):
        # with only two rounds the single comparison still gates (better a
        # noisy gate than none while history accumulates)
        d = str(tmp_path)
        files = [
            _write_round(d, 1, _BASE),
            _write_round(d, 2, _scaled(3.0, only=lambda k: "jax" in k)),
        ]
        failures, comparable = run_gate(files, 2.0, 1.5)
        assert comparable
        assert failures
