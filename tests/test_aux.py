"""Auxiliary subsystems: reindex, rechunk layouts, options, cache, visualize
gating, xarray helper functions."""

import numpy as np
import pandas as pd
import pytest

import flox_tpu
from flox_tpu import dtypes
from flox_tpu.rechunk import reshard_for_blockwise
from flox_tpu.reindex import ReindexArrayType, ReindexStrategy, reindex_


def test_reindex_basic():
    arr = np.array([1.0, 2.0, 3.0])
    out = reindex_(arr, pd.Index([10, 20, 30]), pd.Index([20, 30, 40]))
    np.testing.assert_allclose(out, [2.0, 3.0, np.nan], equal_nan=True)


def test_reindex_int_promotes():
    arr = np.array([1, 2], dtype=np.int32)
    out = reindex_(arr, pd.Index([0, 1]), pd.Index([0, 1, 2]))
    assert out.dtype.kind == "f"
    np.testing.assert_allclose(out, [1, 2, np.nan], equal_nan=True)


def test_reindex_axis():
    arr = np.arange(6.0).reshape(2, 3)
    out = reindex_(arr, pd.Index([0, 1, 2]), pd.Index([2, 0]), axis=-1)
    np.testing.assert_allclose(out, [[2, 0], [5, 3]])


def test_reindex_sentinel_fill():
    arr = np.array([5, 7], dtype=np.int64)
    out = reindex_(arr, pd.Index([0, 1]), pd.Index([0, 1, 9]), fill_value=dtypes.NINF)
    assert out[2] == np.iinfo(np.int64).min or np.isneginf(out[2])


def test_reindex_strategy_sparse_unavailable():
    with pytest.raises(NotImplementedError):
        ReindexStrategy(blockwise=True, array_type=ReindexArrayType.SPARSE_COO)


def test_reshard_layout_roundtrip():
    codes = np.array([2, 0, 1, 0, 2, 1, 0, 2])
    layout = reshard_for_blockwise(codes, 2)
    # every group's slots live within one shard
    for g in np.unique(codes):
        slots = np.flatnonzero(layout.codes == g)
        shards = slots // layout.shard_len
        assert len(np.unique(shards)) == 1
    # permutation covers every original element exactly once
    used = layout.permutation[layout.permutation >= 0]
    assert sorted(used) == list(range(len(codes)))


def test_set_options_roundtrip():
    from flox_tpu.options import OPTIONS

    before = OPTIONS["default_engine"]
    with flox_tpu.set_options(default_engine="numpy"):
        assert OPTIONS["default_engine"] == "numpy"
    assert OPTIONS["default_engine"] == before
    with pytest.raises(ValueError):
        flox_tpu.set_options(default_engine="bogus")
    with pytest.raises(ValueError):
        flox_tpu.set_options(not_an_option=1)


def test_is_supported_aggregation():
    assert flox_tpu.is_supported_aggregation("nanmean")
    assert not flox_tpu.is_supported_aggregation("bogus")


def test_xarray_helpers_no_xarray():
    from flox_tpu.xarray import _resolve_dim, _rewrite_func_for_skipna

    assert _rewrite_func_for_skipna("mean", True) == "nanmean"
    assert _rewrite_func_for_skipna("nanmean", False) == "mean"
    assert _rewrite_func_for_skipna("mean", None) == "mean"
    assert _rewrite_func_for_skipna("count", True) == "count"
    assert _resolve_dim(None, ("time",), ("x", "time")) == ("time",)
    assert _resolve_dim(Ellipsis, ("time",), ("x", "time")) == ("x", "time")
    assert _resolve_dim("time", ("time",), ("x", "time")) == ("time",)


def test_xarray_reduce_gated():
    from flox_tpu import utils

    if utils.HAS_XARRAY:
        pytest.skip("xarray installed; gating not applicable")
    from flox_tpu.xarray import xarray_reduce

    with pytest.raises(ImportError, match="xarray"):
        xarray_reduce(object(), "time", func="mean")


def test_visualize_gated():
    from flox_tpu import utils
    from flox_tpu.visualize import visualize_groups_1d

    if utils.HAS_MATPLOTLIB:
        ax = visualize_groups_1d(np.array([0, 0, 1, 1]), chunks=(2, 2))
        assert ax is not None
    else:
        with pytest.raises(ImportError):
            visualize_groups_1d(np.array([0, 1]))


def test_reindex_inf_fill_no_promotion():
    # INF/NINF fills are representable in int64; dtype must not change
    big = np.array([2**62, 2**62 + 1], dtype=np.int64)
    out = reindex_(big, pd.Index([0, 1]), pd.Index([0, 1, 2]), fill_value=dtypes.NINF)
    assert out.dtype == np.int64
    assert out[0] == 2**62 and out[1] == 2**62 + 1
    assert out[2] == np.iinfo(np.int64).min
