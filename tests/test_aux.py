"""Auxiliary subsystems: reindex, rechunk layouts, options, cache, visualize
gating, xarray helper functions."""

import numpy as np
import pandas as pd
import pytest

import flox_tpu
from flox_tpu import dtypes
from flox_tpu.rechunk import reshard_for_blockwise
from flox_tpu.reindex import ReindexArrayType, ReindexStrategy, reindex_


def test_reindex_basic():
    arr = np.array([1.0, 2.0, 3.0])
    out = reindex_(arr, pd.Index([10, 20, 30]), pd.Index([20, 30, 40]))
    np.testing.assert_allclose(out, [2.0, 3.0, np.nan], equal_nan=True)


def test_reindex_int_promotes():
    arr = np.array([1, 2], dtype=np.int32)
    out = reindex_(arr, pd.Index([0, 1]), pd.Index([0, 1, 2]))
    assert out.dtype.kind == "f"
    np.testing.assert_allclose(out, [1, 2, np.nan], equal_nan=True)


def test_reindex_axis():
    arr = np.arange(6.0).reshape(2, 3)
    out = reindex_(arr, pd.Index([0, 1, 2]), pd.Index([2, 0]), axis=-1)
    np.testing.assert_allclose(out, [[2, 0], [5, 3]])


def test_reindex_sentinel_fill():
    arr = np.array([5, 7], dtype=np.int64)
    out = reindex_(arr, pd.Index([0, 1]), pd.Index([0, 1, 9]), fill_value=dtypes.NINF)
    assert out[2] == np.iinfo(np.int64).min or np.isneginf(out[2])


def test_engine_flox_alias_and_numbagg_rejection():
    # reference engine names: "flox" aliases to our native "jax" engine;
    # "numbagg" raises with the design rationale (docs/api.md "Engines")
    vals = np.array([1.0, 2.0, 3.0, 4.0])
    labels = np.array([0, 0, 1, 1])
    expected, _ = flox_tpu.groupby_reduce(vals, labels, func="sum", engine="jax")
    aliased, _ = flox_tpu.groupby_reduce(vals, labels, func="sum", engine="flox")
    np.testing.assert_allclose(aliased, expected)
    with pytest.raises(ValueError, match="numbagg.*JIT-compiled by XLA"):
        flox_tpu.groupby_reduce(vals, labels, func="sum", engine="numbagg")
    with pytest.raises(ValueError, match="Unknown engine"):
        flox_tpu.groupby_reduce(vals, labels, func="sum", engine="cupy")


def test_reindex_strategy_sparse_supported():
    # SPARSE_COO is a real strategy (reindex_sparse_coo); blockwise=True +
    # sparse is rejected exactly as the reference rejects it (reindex.py:69-73)
    s = ReindexStrategy(blockwise=False, array_type=ReindexArrayType.SPARSE_COO)
    assert s.array_type is ReindexArrayType.SPARSE_COO
    with pytest.raises(ValueError, match="blockwise=True not allowed"):
        ReindexStrategy(blockwise=True, array_type=ReindexArrayType.SPARSE_COO)
    s2 = ReindexStrategy(blockwise=None)
    resolved = s2.set_blockwise_for_numpy()
    assert resolved.blockwise is True
    # dataclasses.replace semantics (ADVICE r5): the frozen original is
    # untouched, so instances used as cache keys keep their hash
    assert s2.blockwise is None
    assert hash(s2) == hash(ReindexStrategy(blockwise=None))
    # already-resolved strategies pass through unchanged
    assert resolved.set_blockwise_for_numpy() is resolved


class TestGroupbyReduceReindexParam:
    """groupby_reduce(reindex=...) accepts the reference's full surface
    (VERDICT r4 #4; parity: _validate_reindex, reference core.py:527-586)."""

    vals = np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0])
    labels = np.array([0, 0, 2, 2, 4, 4])

    def _dense(self, **kw):
        return flox_tpu.groupby_reduce(self.vals, self.labels, func="sum", **kw)

    def test_strategy_dense_values_match_implicit(self):
        expected, eg = self._dense()
        for reindex in (
            True,
            False,  # eager: accepted like the reference's all-eager leg
            ReindexStrategy(blockwise=True),
            ReindexStrategy(blockwise=None),
            ReindexStrategy(blockwise=False),
            ReindexStrategy(blockwise=True, array_type=ReindexArrayType.NUMPY),
        ):
            got, g = self._dense(reindex=reindex)
            np.testing.assert_allclose(got, expected)
            np.testing.assert_array_equal(g, eg)

    def test_bad_reindex_value_raises(self):
        with pytest.raises(TypeError, match="reindex must be"):
            self._dense(reindex="yes")

    def test_sparse_coo_result(self):
        # reference test_core.py::test_sparse_nan_fill_value-style contract:
        # sparse container over expected_groups, only found groups stored
        strat = ReindexStrategy(blockwise=False, array_type=ReindexArrayType.SPARSE_COO)
        result, groups = flox_tpu.groupby_reduce(
            self.vals, self.labels, func="sum",
            expected_groups=np.arange(6), fill_value=0, reindex=strat,
        )
        from jax.experimental.sparse import BCOO

        assert isinstance(result, BCOO)
        dense = np.asarray(result.todense())
        np.testing.assert_allclose(dense, [3.0, 0, 7.0, 0, 11.0, 0])
        # only the 3 found groups are stored
        assert result.nse == 3
        np.testing.assert_array_equal(groups, np.arange(6))

    def test_sparse_coo_nan_fill_hostcoo(self):
        strat = ReindexStrategy(blockwise=False, array_type=ReindexArrayType.SPARSE_COO)
        result, _ = flox_tpu.groupby_reduce(
            self.vals, self.labels, func="nanmean",
            expected_groups=np.arange(5), reindex=strat,
        )
        from flox_tpu.reindex import HostCOO

        assert isinstance(result, HostCOO)
        np.testing.assert_allclose(
            result.todense(), [1.5, np.nan, 3.5, np.nan, 5.5], equal_nan=True
        )
        assert result.nnz == 3

    def test_sparse_coo_2d_kept_axis(self):
        strat = ReindexStrategy(blockwise=False, array_type=ReindexArrayType.SPARSE_COO)
        arr = np.arange(12.0).reshape(2, 6)
        result, _ = flox_tpu.groupby_reduce(
            arr, self.labels, func="sum",
            expected_groups=np.arange(6), fill_value=0, reindex=strat,
        )
        dense = np.asarray(result.todense())
        expected, _ = flox_tpu.groupby_reduce(
            arr, self.labels, func="sum", expected_groups=np.arange(6), fill_value=0,
        )
        np.testing.assert_allclose(dense, np.asarray(expected))

    def test_sparse_coo_unsupported_funcs_raise(self):
        strat = ReindexStrategy(blockwise=False, array_type=ReindexArrayType.SPARSE_COO)
        for func in ("first", "nanlast", "prod", "var", "nanstd", "argmax"):
            with pytest.raises(ValueError, match="SPARSE_COO does not support"):
                flox_tpu.groupby_reduce(self.vals, self.labels, func=func, reindex=strat)

    def test_sparse_coo_kept_by_axis_offset_codes(self):
        # single multi-dim `by` with axis= reducing only the last by dim:
        # factorize offsets codes per kept row (row*ngroups + g); the sparse
        # leg must fold those back to group ids (code-review r5 finding)
        strat = ReindexStrategy(blockwise=False, array_type=ReindexArrayType.SPARSE_COO)
        labels = np.array([[0, 1, 0], [1, 0, 1]])
        vals = np.arange(6.0).reshape(2, 3)
        result, _ = flox_tpu.groupby_reduce(
            vals, labels, func="sum", axis=-1,
            expected_groups=np.arange(3), fill_value=0, reindex=strat,
        )
        expected, _ = flox_tpu.groupby_reduce(
            vals, labels, func="sum", axis=-1,
            expected_groups=np.arange(3), fill_value=0,
        )
        np.testing.assert_allclose(np.asarray(result.todense()), np.asarray(expected))
        # group 2 never occurs: only columns 0 and 1 stored (BCOO batch dims
        # share the sparse structure, so nse counts columns once)
        assert result.nse == 2

    def test_method_map_reduce_default_mesh_blockwise_false_raises(self):
        # method='map-reduce' without mesh= still runs the sharded program on
        # a default mesh — the raise must key on method, not mesh (code-review)
        with pytest.raises(NotImplementedError, match="dense_intermediate_bytes_max"):
            flox_tpu.groupby_reduce(
                self.vals, self.labels, func="sum", reindex=False,
                expected_groups=np.arange(5), method="map-reduce",
            )

    def test_frozen_strategy_and_sanctioned_mutation(self):
        s = ReindexStrategy(blockwise=False)
        with pytest.raises(AttributeError):
            s.blockwise = True
        assert hash(s) == hash(ReindexStrategy(blockwise=False))

    def test_scan_engine_alias_normalized(self):
        # the alias must hit groupby_scan's own engine=="jax" guards, not
        # just the deep generic_aggregate call (code-review r5 finding)
        vals = np.array([1.0, 2.0, 3.0, 4.0])
        labels = np.array([0, 1, 0, 1])
        a = flox_tpu.groupby_scan(vals, labels, func="cumsum", engine="flox")
        b = flox_tpu.groupby_scan(vals, labels, func="cumsum", engine="jax")
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
        with pytest.raises(ValueError, match="numbagg"):
            flox_tpu.groupby_scan(vals, labels, func="cumsum", engine="numbagg")

    def test_mesh_map_reduce_blockwise_false_raises(self):
        import jax

        mesh = jax.sharding.Mesh(np.array(jax.devices()[:1]), ("data",))
        with pytest.raises(NotImplementedError, match="dense_intermediate_bytes_max"):
            flox_tpu.groupby_reduce(
                self.vals, self.labels, func="sum", reindex=False,
                expected_groups=np.arange(5), mesh=mesh, method="map-reduce",
            )


def test_reshard_layout_roundtrip():
    codes = np.array([2, 0, 1, 0, 2, 1, 0, 2])
    layout = reshard_for_blockwise(codes, 2)
    # every group's slots live within one shard
    for g in np.unique(codes):
        slots = np.flatnonzero(layout.codes == g)
        shards = slots // layout.shard_len
        assert len(np.unique(shards)) == 1
    # permutation covers every original element exactly once
    used = layout.permutation[layout.permutation >= 0]
    assert sorted(used) == list(range(len(codes)))


def test_set_options_roundtrip():
    from flox_tpu.options import OPTIONS

    before = OPTIONS["default_engine"]
    with flox_tpu.set_options(default_engine="numpy"):
        assert OPTIONS["default_engine"] == "numpy"
    assert OPTIONS["default_engine"] == before
    with pytest.raises(ValueError):
        flox_tpu.set_options(default_engine="bogus")
    with pytest.raises(ValueError):
        flox_tpu.set_options(not_an_option=1)


def test_is_supported_aggregation():
    assert flox_tpu.is_supported_aggregation("nanmean")
    assert not flox_tpu.is_supported_aggregation("bogus")


def test_xarray_helpers_no_xarray():
    from flox_tpu.xarray import _resolve_dim, _rewrite_func_for_skipna

    assert _rewrite_func_for_skipna("mean", True) == "nanmean"
    assert _rewrite_func_for_skipna("nanmean", False) == "mean"
    assert _rewrite_func_for_skipna("mean", None) == "mean"
    assert _rewrite_func_for_skipna("count", True) == "count"
    assert _resolve_dim(None, ("time",), ("x", "time")) == ("time",)
    assert _resolve_dim(Ellipsis, ("time",), ("x", "time")) == ("x", "time")
    assert _resolve_dim("time", ("time",), ("x", "time")) == ("time",)


def test_xarray_adapter_backend_binding():
    # without xarray installed the adapter binds to the bundled xrlite
    # subset (same code path as real xarray); with xarray it binds to it
    from flox_tpu import utils, xrlite
    from flox_tpu.xarray import _get_xr

    xr = _get_xr()
    if utils.HAS_XARRAY:
        import xarray

        assert xr is xarray
    else:
        assert xr is xrlite


def test_visualize_gated():
    from flox_tpu import utils
    from flox_tpu.visualize import visualize_groups_1d

    if utils.HAS_MATPLOTLIB:
        ax = visualize_groups_1d(np.array([0, 0, 1, 1]), chunks=(2, 2))
        assert ax is not None
    else:
        with pytest.raises(ImportError):
            visualize_groups_1d(np.array([0, 1]))


def test_reindex_sparse_coo_x64_off_keeps_host_container(monkeypatch):
    # with x64 off, jnp.asarray would truncate 64-bit data to 32 bits; the
    # zero-fill leg must fall back to HostCOO (code-review r5 finding)
    import flox_tpu.reindex as rmod
    from flox_tpu.reindex import HostCOO, reindex_sparse_coo
    from flox_tpu import utils as futils

    monkeypatch.setattr(futils, "x64_enabled", lambda: False)
    big = np.array([2**40, 16], dtype=np.int64)
    out = reindex_sparse_coo(big, pd.Index([0, 1]), pd.Index([0, 1, 2]), fill_value=0)
    assert isinstance(out, HostCOO)
    np.testing.assert_array_equal(out.todense(), [2**40, 16, 0])
    assert out.data.dtype == np.int64


def test_reindex_inf_fill_no_promotion():
    # INF/NINF fills are representable in int64; dtype must not change
    big = np.array([2**62, 2**62 + 1], dtype=np.int64)
    out = reindex_(big, pd.Index([0, 1]), pd.Index([0, 1, 2]), fill_value=dtypes.NINF)
    assert out.dtype == np.int64
    assert out[0] == 2**62 and out[1] == 2**62 + 1
    assert out[2] == np.iinfo(np.int64).min


def test_rechunk_for_cohorts_boundaries():
    from flox_tpu.cohorts import find_group_cohorts
    from flox_tpu.rechunk import rechunk_for_cohorts

    # 3 "years" of 12 "months": anchors at month 0 + default subdivision
    # produce repeating-position chunks that form real cohorts
    labels = np.repeat(np.tile(np.arange(12), 3), 5)
    chunks = rechunk_for_cohorts(None, -1, labels, force_new_chunk_at=0)
    assert sum(chunks) == 180
    method, mapping = find_group_cohorts(labels, chunks)
    assert method == "cohorts" and len(mapping) > 1
    # explicit chunksize: boundaries at period starts + ~chunksize splits
    chunks2 = rechunk_for_cohorts(None, -1, labels, force_new_chunk_at=0, chunksize=30)
    assert sum(chunks2) == 180 and all(c <= 30 for c in chunks2)
    # alignment validation when an array is supplied
    with pytest.raises(ValueError, match="align"):
        rechunk_for_cohorts(np.zeros(10), -1, labels, force_new_chunk_at=0)


def test_profiling_timed(caplog):
    import logging

    from flox_tpu import profiling

    with caplog.at_level(logging.INFO, logger="flox_tpu"):
        with profiling.timed("unit-test block"):
            pass
    assert any("unit-test block" in r.message for r in caplog.records)


class TestDeviceGroupby:
    """groupby_reduce_device is fully traceable (usable inside user jit)."""

    def test_inside_jit(self):
        import jax
        import jax.numpy as jnp

        from flox_tpu.device import groupby_reduce_device

        vals = np.arange(24.0).reshape(2, 12)
        months = np.arange(12) % 3

        @jax.jit
        def step(v, m):
            return groupby_reduce_device(
                v, m, func="nanmean", expected_values=jnp.arange(3)
            )

        out = np.asarray(step(jnp.asarray(vals), jnp.asarray(months)))
        expected, _ = __import__("flox_tpu").groupby_reduce(
            vals, months, func="nanmean", expected_groups=np.arange(3)
        )
        np.testing.assert_allclose(out, np.asarray(expected))

    def test_bins_inside_jit(self):
        import jax
        import jax.numpy as jnp

        from flox_tpu.device import groupby_reduce_device

        vals = np.array([0.5, 1.5, 2.5, 3.5])

        @jax.jit
        def step(v):
            return groupby_reduce_device(v, v, func="count", bins=jnp.array([0.0, 2.0, 4.0]))

        out = np.asarray(step(jnp.asarray(vals)))
        np.testing.assert_array_equal(out, [2, 2])

    def test_multi_by(self):
        import jax.numpy as jnp

        from flox_tpu.device import groupby_reduce_device

        b1 = np.array([0, 0, 1, 1])
        b2 = np.array([0, 1, 0, 1])
        vals = np.array([1.0, 2.0, 3.0, 4.0])
        out = np.asarray(
            groupby_reduce_device(
                vals, b1, b2, func="sum",
                expected_values=(jnp.arange(2), jnp.arange(2)),
            )
        )
        np.testing.assert_allclose(out, [[1, 2], [3, 4]])

    def test_grad_through_groupby(self):
        # differentiable: the whole pipeline is traceable
        import jax
        import jax.numpy as jnp

        from flox_tpu.device import groupby_reduce_device

        months = jnp.asarray(np.arange(6) % 2)

        def loss(v):
            means = groupby_reduce_device(v, months, func="mean", expected_values=jnp.arange(2))
            return jnp.sum(means**2)

        g = jax.grad(loss)(jnp.arange(6.0))
        assert np.isfinite(np.asarray(g)).all()


def test_device_dtype_applied():
    from flox_tpu.device import groupby_reduce_device

    out = groupby_reduce_device(
        np.array([1, 2, 3, 4], dtype=np.int32), np.array([0, 0, 1, 1]),
        func="sum", expected_values=np.arange(2), dtype=np.float64,
    )
    assert np.asarray(out).dtype.kind == "f"
    np.testing.assert_allclose(np.asarray(out), [3.0, 7.0])


def test_pallas_knob_independent_of_matmul_knob():
    import jax.numpy as jnp

    import flox_tpu
    from flox_tpu.kernels import _segment_sum_impl

    data = jnp.zeros((64, 4), jnp.float32)
    with flox_tpu.set_options(segment_sum_impl="pallas", matmul_num_groups_max=0):
        assert _segment_sum_impl(data, 12) == "pallas"
    with flox_tpu.set_options(segment_sum_impl="pallas", pallas_num_groups_max=0):
        assert _segment_sum_impl(data, 12) == "scatter"


def test_factorize_cache_byte_budget():
    from flox_tpu import factorize as fct

    fct._FACTORIZE_CACHE.clear()
    fct._FACTORIZE_CACHE_BYTES[0] = 0
    old_budget = fct._FACTORIZE_BUDGET_BYTES
    try:
        fct._FACTORIZE_BUDGET_BYTES = 3000  # tiny budget
        for i in range(10):
            labels = (np.arange(200) % (i + 2)).astype(np.int64)  # 1600B codes each
            fct.factorize_cached((labels,), axes=(0,))
        assert fct._FACTORIZE_CACHE_BYTES[0] <= 3200  # at most budget + one entry
        assert len(fct._FACTORIZE_CACHE) <= 2
        # hot entry survives: re-use the last labels, then add another
        labels = (np.arange(200) % 11).astype(np.int64)
        r1 = fct.factorize_cached((labels,), axes=(0,))
        r2 = fct.factorize_cached((labels,), axes=(0,))
        assert r1 is r2
    finally:
        fct._FACTORIZE_BUDGET_BYTES = old_budget
        fct._FACTORIZE_CACHE.clear()
        fct._FACTORIZE_CACHE_BYTES[0] = 0


def test_scan_bad_axis_errors():
    import jax

    from flox_tpu.aggregations import SCANS
    from flox_tpu.parallel import make_mesh
    from flox_tpu.parallel.scan import sharded_groupby_scan

    mesh2 = make_mesh(shape=(2, 4), axis_names=("dcn", "ici"))
    with pytest.raises(ValueError, match="no axes"):
        sharded_groupby_scan(
            np.arange(16.0), np.arange(16) % 2, SCANS["cumsum"], size=2,
            mesh=mesh2, axis_name="bogus",
        )


def test_factorize_rangeindex_defensive_copy():
    # reference regression test_core.py:1828: the RangeIndex fast path must
    # copy — returning the caller's buffer caused a shared-memory race when
    # the clamp wrote -1 into it
    from flox_tpu.factorize import factorize_single

    labels = np.array([0, 1, 5, 2], dtype=np.int64)
    orig = labels.copy()
    codes, groups = factorize_single(labels, pd.RangeIndex(3))
    np.testing.assert_array_equal(labels, orig)  # input untouched
    assert codes.base is not labels and codes is not labels
    np.testing.assert_array_equal(codes, [0, 1, -1, 2])
