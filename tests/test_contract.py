"""Runtime conformance harness for the static contract artifact.

The contract compiler (``tools/floxlint/contract.py``) extracts the
serve/telemetry surface from the AST; these tests prove the artifact
against a LIVE replica: every contract-declared op is replayed through
``python -m flox_tpu.serve`` (including error probes — every ``ok:
false`` answer must carry a ``code`` the contract declares), and every
contract-declared HTTP endpoint of the exposition server is probed
in-process with its answered status asserted against the declared set.
CI runs this file as the conformance leg next to the lint gate.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from tools.floxlint.contract import (  # noqa: E402
    cell_tokens,
    contract_for_paths,
    parse_contract_tables,
    validate_contract,
)


@pytest.fixture(scope="module")
def contract():
    doc = contract_for_paths([str(REPO / "flox_tpu")])
    assert validate_contract(doc) == []
    return doc


# ---------------------------------------------------------------------------
# serve-protocol conformance: replay every declared op against a live replica
# ---------------------------------------------------------------------------

#: minimal replayable request per declared op. Deliberately includes
#: error probes (append with no store, profile on a CPU-only runtime):
#: the conformance property for those is that the failure is TYPED — the
#: answer carries a contract-declared "code", never a bare stack trace.
_OP_PROBES = {
    "reduce": {
        "id": "reduce", "func": "sum",
        "array": [1.0, 2.0, 4.0, 8.0], "by": [0, 0, 1, 1],
    },
    "warmup": {"op": "warmup"},
    "stats": {"op": "stats"},
    "put_dataset": {
        "op": "put_dataset", "name": "conf_ds",
        "array": [1.0, 2.0, 3.0], "by": [0, 1, 1],
    },
    "list_datasets": {"op": "list_datasets"},
    "del_dataset": {"op": "del_dataset", "name": "conf_ds"},
    "append": {"op": "append", "store": "conf_missing"},
    "query": {"op": "query", "store": "conf_missing"},
    "compact": {"op": "compact", "store": "conf_missing"},
    "list_stores": {"op": "list_stores"},
    "profile": {"op": "profile", "seconds": 0.01},
    "drain": {"op": "drain"},
    "shutdown": {"op": "shutdown"},
}


@pytest.fixture(scope="module")
def replica_records(contract):
    missing = set(contract["ops"]) - set(_OP_PROBES)
    assert not missing, f"contract declares ops with no probe: {missing}"
    # lines are submitted concurrently as read, so the dataset lifecycle
    # (put -> list -> del) is sequenced with drain barriers; everything
    # else is order-independent
    sequenced = ("put_dataset", "list_datasets", "del_dataset",
                 "drain", "shutdown")
    probes = [
        _OP_PROBES[op] for op in contract["ops"] if op not in sequenced
    ]
    for op in ("put_dataset", "list_datasets", "del_dataset"):
        probes += [{"op": "drain"}, _OP_PROBES[op]]
    probes += [{"op": "drain"}, _OP_PROBES["shutdown"]]
    lines = "\n".join(json.dumps(p) for p in probes) + "\n"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("FLOX_TPU_TELEMETRY", None)
    env.pop("FLOX_TPU_TELEMETRY_EXPORT_PATH", None)
    proc = subprocess.run(
        [sys.executable, "-m", "flox_tpu.serve"],
        input=lines, cwd=REPO, env=env,
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr
    records = [
        json.loads(line) for line in proc.stdout.splitlines() if line.strip()
    ]
    assert records, proc.stderr
    return records


def _by_op(records):
    out = {}
    for rec in records:
        key = rec.get("op") or ("reduce" if rec.get("id") == "reduce" else None)
        if key is not None:
            out.setdefault(key, rec)
        elif "warmed" in rec:
            out.setdefault("warmup", rec)
    return out


def test_every_declared_op_is_dispatched(contract, replica_records):
    """No probe of a contract-declared op may come back as the unknown-op
    protocol error — the artifact's op table IS the dispatch table."""
    for rec in replica_records:
        message = str(rec.get("message", ""))
        assert "unknown op" not in message, rec


def test_error_answers_carry_declared_codes(contract, replica_records):
    """Every ok:false answer on the wire carries a machine-readable code
    the contract declares (the FLX019 property, proven at runtime)."""
    errors = [r for r in replica_records if r.get("ok") is False]
    assert errors, "expected at least the append/query/compact error probes"
    for rec in errors:
        assert "code" in rec, rec
        assert rec["code"] in contract["errors"], rec


def test_reduce_answer_covers_documented_fields(contract, replica_records):
    """The docs contract:ops row for reduce promises fields clients will
    index — the live success answer must produce every one of them."""
    tables = parse_contract_tables((REPO / "docs" / "serving.md").read_text())
    rows = {
        tok: row
        for row in tables["ops"]
        for tok in cell_tokens(next(iter(row.values())))
    }
    reduce_rec = _by_op(replica_records)["reduce"]
    assert reduce_rec["ok"] is True
    documented = set(cell_tokens(rows["reduce"].get("response fields", "")))
    assert documented, "docs reduce row lost its response-fields cell"
    missing = documented - set(reduce_rec)
    assert not missing, f"documented reduce fields absent on the wire: {missing}"
    assert reduce_rec["result"] == [3.0, 12.0]


def test_dataset_and_store_ops_round_trip(replica_records):
    recs = _by_op(replica_records)
    assert recs["put_dataset"]["ok"] is True
    assert recs["del_dataset"]["ok"] is True and recs["del_dataset"]["deleted"]
    assert recs["list_datasets"]["ok"] is True
    assert recs["list_stores"]["ok"] is True
    assert recs["warmup"].get("warmed") == 0  # no manifest: replayed nothing
    # the store error probes fail TYPED (unknown_store), never with a trace
    for op in ("append", "query", "compact"):
        assert recs[op]["ok"] is False
        assert recs[op]["code"] == "unknown_store", recs[op]


# ---------------------------------------------------------------------------
# endpoint conformance: probe every declared exposition path against a live
# endpoint. The server runs in a SUBPROCESS: start_metrics_server seeds
# gauges, starts the saturation sampler, and warms SLO state process-wide,
# and the registry is a process singleton — booting it inside the pytest
# process would leak that state into every later test module.
# ---------------------------------------------------------------------------

_PROBE_SCRIPT = """\
import json, sys, urllib.error, urllib.request
from flox_tpu import exposition

port = exposition.start_metrics_server(port=0)
assert port
statuses = {}
for path in json.load(sys.stdin):
    try:
        with urllib.request.urlopen(
            "http://127.0.0.1:%d%s" % (port, path), timeout=30
        ) as resp:
            statuses[path] = resp.status
    except urllib.error.HTTPError as err:
        statuses[path] = err.code
with urllib.request.urlopen(
    "http://127.0.0.1:%d/metrics" % port, timeout=30
) as resp:
    body = resp.read().decode()
json.dump({"statuses": statuses, "metrics_body": body}, sys.stdout)
"""


@pytest.fixture(scope="module")
def endpoint_probe(contract):
    paths = sorted(contract["endpoints"]["flox_tpu.exposition"])
    assert paths, "contract lost the exposition endpoint table"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("FLOX_TPU_TELEMETRY", None)
    env.pop("FLOX_TPU_TELEMETRY_EXPORT_PATH", None)
    proc = subprocess.run(
        [sys.executable, "-c", _PROBE_SCRIPT],
        input=json.dumps(paths), cwd=REPO, env=env,
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr
    return json.loads(proc.stdout)


def test_every_declared_endpoint_answers_a_declared_status(
    contract, endpoint_probe
):
    endpoints = contract["endpoints"]["flox_tpu.exposition"]
    for path, entry in endpoints.items():
        status = endpoint_probe["statuses"][path]
        assert status in entry["statuses"], (
            f"{path} answered {status}, contract declares {entry['statuses']}"
        )


def test_scrape_names_fold_back_to_contract_metrics(contract, endpoint_probe):
    """Every flox_tpu_* series the live endpoint renders must fold back
    (prefix/suffix stripped, dots folded) to a contract emit-site name —
    the exposition renderer cannot invent series the contract misses."""
    body = endpoint_probe["metrics_body"]
    folded = {name.replace(".", "_") for name in contract["metrics"]}
    unmatched = []
    for line in body.splitlines():
        if not line.startswith("flox_tpu_"):
            continue
        series = line.split(None, 1)[0].partition("{")[0]
        candidate = series[len("flox_tpu_"):]
        for suffix in ("_bucket", "_sum", "_count", "_total"):
            if candidate.endswith(suffix):
                candidate = candidate[: -len(suffix)]
                break
        if candidate not in folded:
            unmatched.append(series)
    assert not unmatched, f"live series with no contract emit: {unmatched}"
