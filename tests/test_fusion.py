"""Multi-statistic fusion suite (ISSUE 10).

Acceptance: ``groupby_aggregate_many`` is bit-identical to N sequential
``groupby_reduce`` calls on every runtime (eager jax/numpy, mesh,
streaming single-device and mesh, prefetch on/off), compiles exactly ONE
program for N statistics, bills staged bytes exactly once in the cost
ledger, and its streaming form survives kill-at-slab-k resume and OOM
slab-splitting on the fused carry.
"""

import numpy as np
import pytest

import flox_tpu
from flox_tpu import (
    cache,
    faults,
    groupby_aggregate_many,
    groupby_reduce,
    streaming_groupby_aggregate_many,
    streaming_groupby_reduce,
    telemetry,
)
from flox_tpu.aggregations import FUSABLE_FUNCS, plan_fused

CLIMATOLOGY = ("mean", "var", "min", "max")
FUNC_SETS = [
    CLIMATOLOGY,
    ("sum", "count", "min", "max", "var"),
    ("nanmean", "nanstd", "nanmin", "nanmax", "count"),
    ("nansum", "nanvar", "mean"),
    ("std", "prod", "any", "all"),
    ("mean", "nanmean", "var", "nanvar"),  # mixed skipna: no cross-aliasing
]


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    vals = rng.normal(size=(2, 2400))
    vals[0, 5] = np.nan
    vals[1, 100:200] = np.nan
    vals[1, ::37] = np.nan
    labels = rng.integers(0, 7, 2400)
    return vals, labels


def _assert_same(got, want, label):
    got, want = np.asarray(got), np.asarray(want)
    assert got.dtype == want.dtype, f"{label}: dtype {got.dtype} != {want.dtype}"
    np.testing.assert_array_equal(got, want, err_msg=label)


# ---------------------------------------------------------------------------
# planner
# ---------------------------------------------------------------------------


class TestPlanner:
    def test_var_triple_feeds_mean(self):
        fused = plan_fused(("mean", "var", "std"), None, np.dtype("f8"), None, 0, None)
        # ONE var_chunk leg serves all three statistics: mean reads the
        # triple's (total, count) leaves — no sum/count legs at all
        assert fused.chunk == (("var_chunk", {"skipna": False}),)
        assert fused.slots[0]["sum"] == (0, 1)
        assert fused.slots[0]["count"] == (0, 2)

    def test_dedup_shared_legs(self):
        fused = plan_fused(("sum", "mean", "count"), None, np.dtype("f8"), None, 0, None)
        names = [c[0] if isinstance(c, tuple) else c for c in fused.chunk]
        # sum shared by the sum stat and mean; one nanlen; one len presence
        assert names.count("sum") == 1
        assert names.count("nanlen") == 1

    def test_rejects_unfusable(self):
        with pytest.raises(NotImplementedError, match="cannot fuse"):
            plan_fused(("mean", "argmax"), None, np.dtype("f8"), None, 0, None)
        with pytest.raises(NotImplementedError, match="cannot fuse"):
            plan_fused(("quantile",), None, np.dtype("f8"), None, 0, None)

    def test_rejects_duplicates_and_empty(self):
        with pytest.raises(ValueError, match="duplicate"):
            plan_fused(("mean", "mean"), None, np.dtype("f8"), None, 0, None)
        with pytest.raises(ValueError, match="at least one"):
            plan_fused((), None, np.dtype("f8"), None, 0, None)

    def test_fusable_set_excludes_order_stats(self):
        assert "quantile" not in FUSABLE_FUNCS
        assert "argmin" not in FUSABLE_FUNCS
        assert {"mean", "var", "min", "max", "count"} <= FUSABLE_FUNCS


# ---------------------------------------------------------------------------
# eager bit-identity
# ---------------------------------------------------------------------------


class TestEagerBitIdentity:
    @pytest.mark.parametrize(
        "funcs,engine",
        # every set on the jax engine; the numpy engine shares the planner
        # and finalize, so three sets cover its engine-specific kernels
        [(f, "jax") for f in FUNC_SETS] + [(f, "numpy") for f in FUNC_SETS[:3]],
        ids=lambda v: "+".join(v) if isinstance(v, tuple) else str(v),
    )
    def test_matches_sequential(self, data, funcs, engine):
        vals, labels = data
        out, groups = groupby_aggregate_many(vals, labels, funcs=funcs, engine=engine)
        assert tuple(out) == funcs  # request order preserved
        for f in funcs:
            seq, seq_groups = groupby_reduce(vals, labels, func=f, engine=engine)
            _assert_same(out[f], seq, f"{f} ({engine})")
            np.testing.assert_array_equal(groups, seq_groups)

    def test_float32(self, data):
        vals, labels = data
        v32 = vals.astype(np.float32)
        out, _ = groupby_aggregate_many(v32, labels, funcs=CLIMATOLOGY)
        for f in CLIMATOLOGY:
            _assert_same(out[f], groupby_reduce(v32, labels, func=f)[0], f"{f} f32")

    def test_int_input(self, data):
        _, labels = data
        ints = np.arange(labels.size, dtype=np.int64) % 101
        funcs = ("sum", "count", "min", "max", "mean", "var")
        out, _ = groupby_aggregate_many(ints, labels, funcs=funcs)
        for f in funcs:
            _assert_same(out[f], groupby_reduce(ints, labels, func=f)[0], f"{f} int")

    def test_all_nan_group_per_statistic(self):
        # skipna presence semantics diverge per statistic: nansum of an
        # all-NaN group is 0, nanmean/nanmin are the fill (NaN)
        vals = np.array([1.0, np.nan, np.nan, 4.0])
        labels = np.array([0, 1, 1, 0])
        funcs = ("nansum", "nanmean", "nanmin", "count")
        out, _ = groupby_aggregate_many(vals, labels, funcs=funcs)
        for f in funcs:
            _assert_same(out[f], groupby_reduce(vals, labels, func=f)[0], f)
        assert np.asarray(out["nansum"])[1] == 0.0
        assert np.isnan(np.asarray(out["nanmean"])[1])

    def test_empty_group_fill(self, data):
        vals, labels = data
        expected = np.arange(9)  # groups 7, 8 never occur
        out, _ = groupby_aggregate_many(
            vals, labels, funcs=CLIMATOLOGY, expected_groups=expected
        )
        for f in CLIMATOLOGY:
            seq = groupby_reduce(vals, labels, func=f, expected_groups=expected)[0]
            _assert_same(out[f], seq, f"{f} empty-group")

    def test_per_func_fill_value_and_kwargs(self, data):
        vals, labels = data
        out, _ = groupby_aggregate_many(
            vals, labels, funcs=("nanmin", "nanvar"),
            expected_groups=np.arange(9),
            fill_value={"nanmin": -1.0},
            finalize_kwargs={"nanvar": {"ddof": 1}},
        )
        _assert_same(
            out["nanmin"],
            groupby_reduce(vals, labels, func="nanmin", fill_value=-1.0,
                           expected_groups=np.arange(9))[0],
            "nanmin fill",
        )
        _assert_same(
            out["nanvar"],
            groupby_reduce(vals, labels, func="nanvar",
                           finalize_kwargs={"ddof": 1},
                           expected_groups=np.arange(9))[0],
            "nanvar ddof",
        )

    def test_min_count(self, data):
        vals, labels = data
        out, _ = groupby_aggregate_many(
            vals, labels, funcs=("nansum", "nanmean"), min_count=200
        )
        for f in ("nansum", "nanmean"):
            seq = groupby_reduce(vals, labels, func=f, min_count=200)[0]
            _assert_same(out[f], seq, f"{f} min_count")

    @pytest.mark.parametrize("engine", ["jax", "numpy"])
    def test_min_count_var_family(self, engine):
        # regression: _initialize_aggregation's appended nanlen used to
        # mask var's ("var",) combine signature, misclassifying the Chan
        # triple in the planner (review finding)
        vals = np.array([[1.0, 2.0, np.nan, 3.0, 7.0, 2.0]])
        labels = np.array([0, 0, 1, 1, 2, 2])
        funcs = ("var", "mean", "std", "count")
        out, _ = groupby_aggregate_many(
            vals, labels, funcs=funcs, min_count=2, engine=engine
        )
        for f in funcs:
            seq = groupby_reduce(vals, labels, func=f, min_count=2, engine=engine)[0]
            _assert_same(out[f], seq, f"{f} min_count var-family ({engine})")

    def test_nd_by_and_axis(self):
        rng = np.random.default_rng(3)
        vals = rng.normal(size=(2, 4, 50))
        labels = rng.integers(0, 3, (4, 50))
        out, _ = groupby_aggregate_many(vals, labels, funcs=("mean", "max"))
        for f in ("mean", "max"):
            _assert_same(out[f], groupby_reduce(vals, labels, func=f)[0], f)

    def test_bool_input(self, data):
        _, labels = data
        b = (np.arange(labels.size) % 3).astype(bool)
        funcs = ("sum", "count", "all", "any")
        out, _ = groupby_aggregate_many(b, labels, funcs=funcs, engine="jax")
        for f in funcs:
            _assert_same(out[f], groupby_reduce(b, labels, func=f, engine="jax")[0],
                         f"{f} bool")
        with pytest.raises(NotImplementedError, match="bool data"):
            groupby_aggregate_many(b, labels, funcs=("mean", "sum"))

    def test_rejects_datetime_and_blockwise(self, data):
        vals, labels = data
        dt = np.arange(labels.size, dtype=np.int64).view("datetime64[ns]")
        with pytest.raises(NotImplementedError, match="numeric"):
            groupby_aggregate_many(dt, labels, funcs=("min", "max"))
        with pytest.raises(NotImplementedError, match="method"):
            groupby_aggregate_many(vals, labels, funcs=("min",), method="blockwise")


# ---------------------------------------------------------------------------
# one compiled program + cost ledger bytes staged once
# ---------------------------------------------------------------------------


class TestOneProgram:
    def test_one_compile_for_n_statistics(self, data):
        import jax

        vals, labels = data
        with flox_tpu.set_options(telemetry=True):
            cache.clear_all()
            jax.clear_caches()
            c0 = telemetry.METRICS.get("jax.compiles")
            groupby_aggregate_many(vals, labels, funcs=CLIMATOLOGY, engine="jax")
            fused_compiles = telemetry.METRICS.get("jax.compiles") - c0
            # same-shape re-dispatch reuses the program: zero new compiles
            c1 = telemetry.METRICS.get("jax.compiles")
            groupby_aggregate_many(vals, labels, funcs=CLIMATOLOGY, engine="jax")
            assert telemetry.METRICS.get("jax.compiles") - c1 == 0

            cache.clear_all()
            jax.clear_caches()
            c0 = telemetry.METRICS.get("jax.compiles")
            for f in CLIMATOLOGY:
                groupby_reduce(vals, labels, func=f, engine="jax")
            seq_compiles = telemetry.METRICS.get("jax.compiles") - c0
        assert fused_compiles == 1
        assert seq_compiles == len(CLIMATOLOGY)

    def test_ledger_bills_bytes_once(self, data):
        vals, labels = data
        with flox_tpu.set_options(telemetry=True):
            cache.clear_all()
            groupby_aggregate_many(vals, labels, funcs=CLIMATOLOGY, engine="jax")
            row = telemetry.cost_by_program()["fused[mean+var+min+max]"]
            expected = vals.nbytes + labels.size * np.asarray(labels).itemsize
            assert row["dispatches"] == 1
            # bytes staged ONCE for the whole statistic set — no
            # per-statistic double counting at any observe_cost site
            assert row["bytes"] == expected

            # the sequential baseline pays ~N x the staged bytes
            cache.clear_all()
            for f in CLIMATOLOGY:
                groupby_reduce(vals, labels, func=f, engine="jax")
            seq_bytes = sum(
                r["bytes"]
                for k, r in telemetry.cost_by_program().items()
                if k.startswith("bundle[")
            )
            assert seq_bytes == len(CLIMATOLOGY) * expected

    def test_fused_program_cache_registered(self):
        # FLX008 discipline: the fused-program LRU is reachable from
        # cache.clear_all and visible in cache.stats
        from flox_tpu.fusion import _FUSED_PROGRAM_CACHE

        rng = np.random.default_rng(0)
        groupby_aggregate_many(
            rng.normal(size=64), rng.integers(0, 4, 64), funcs=("mean", "max"),
            engine="jax",
        )
        assert len(_FUSED_PROGRAM_CACHE) >= 1
        assert cache.stats()["fused_programs"] == len(_FUSED_PROGRAM_CACHE)
        cache.clear_all()
        assert len(_FUSED_PROGRAM_CACHE) == 0


# ---------------------------------------------------------------------------
# mesh
# ---------------------------------------------------------------------------


class TestMesh:
    @pytest.fixture(scope="class")
    def mesh(self):
        from flox_tpu.parallel.mesh import make_mesh

        return make_mesh()

    @pytest.mark.parametrize("funcs", FUNC_SETS[:3], ids=["+".join(f) for f in FUNC_SETS[:3]])
    def test_matches_sequential(self, data, mesh, funcs):
        vals, labels = data
        out, _ = groupby_aggregate_many(
            vals, labels, funcs=funcs, method="map-reduce", mesh=mesh
        )
        for f in funcs:
            seq = groupby_reduce(vals, labels, func=f, method="map-reduce", mesh=mesh)[0]
            _assert_same(out[f], seq, f"{f} mesh")

    def test_one_program_one_cache_key(self, data, mesh):
        from flox_tpu.parallel.mapreduce import _PROGRAM_CACHE

        vals, labels = data
        cache.clear_all()
        groupby_aggregate_many(
            vals, labels, funcs=CLIMATOLOGY, method="map-reduce", mesh=mesh
        )
        # the whole statistic set lowered as ONE program under ONE key
        assert len(_PROGRAM_CACHE) == 1
        misses0 = telemetry.METRICS.get("cache.program_misses")
        groupby_aggregate_many(
            vals, labels, funcs=CLIMATOLOGY, method="map-reduce", mesh=mesh
        )
        assert len(_PROGRAM_CACHE) == 1
        assert telemetry.METRICS.get("cache.program_misses") == misses0

    def test_distinct_fills_get_distinct_programs(self, data, mesh):
        # per-statistic identity rides the program key: same legs,
        # different final fill -> different compiled program
        from flox_tpu.parallel.mapreduce import _agg_cache_key

        k1 = _agg_cache_key(
            plan_fused(("min", "max"), None, np.dtype("f8"), None, 0, None)
        )
        k2 = _agg_cache_key(
            plan_fused(("min", "max"), None, np.dtype("f8"), {"min": -1.0}, 0, None)
        )
        assert k1 != k2


# ---------------------------------------------------------------------------
# streaming: one pass, fused carry, resilience
# ---------------------------------------------------------------------------


class TestStreaming:
    @pytest.mark.parametrize(
        "funcs,depth",
        [(FUNC_SETS[0], 0), (FUNC_SETS[0], 2), (FUNC_SETS[1], 0), (FUNC_SETS[2], 2)],
        ids=lambda v: "+".join(v) if isinstance(v, tuple) and v and isinstance(v[0], str) else str(v),
    )
    def test_matches_sequential(self, data, funcs, depth):
        vals, labels = data
        with flox_tpu.set_options(stream_prefetch=depth):
            out, _ = streaming_groupby_aggregate_many(
                vals, labels, funcs=funcs, batch_len=600
            )
            for f in funcs:
                seq = streaming_groupby_reduce(vals, labels, func=f, batch_len=600)[0]
                _assert_same(out[f], seq, f"{f} stream depth={depth}")

    def test_close_to_eager(self, data):
        # slab-by-slab folds reorder float accumulation vs the eager
        # one-pass program — allclose, not bit-equal (the same contract
        # the sequential streaming runtime has with the eager path)
        vals, labels = data
        out, _ = streaming_groupby_aggregate_many(
            vals, labels, funcs=CLIMATOLOGY, batch_len=700
        )
        eager, _ = groupby_aggregate_many(vals, labels, funcs=CLIMATOLOGY)
        for f in CLIMATOLOGY:
            np.testing.assert_allclose(
                np.asarray(out[f]), np.asarray(eager[f]), rtol=1e-12,
                equal_nan=True, err_msg=f,
            )

    def test_loader_single_pass(self, data):
        # the whole statistic set streams the loader ONCE (the sequential
        # baseline would read it len(funcs) times)
        vals, labels = data
        reads = []

        def loader(s, e):
            reads.append((s, e))
            return vals[:, s:e]

        streaming_groupby_aggregate_many(loader, labels, funcs=CLIMATOLOGY, batch_len=800)
        spans = [(s, e) for s, e in reads if e - s > 1]  # drop the dtype probe
        total = sum(e - s for s, e in spans)
        assert total == labels.size  # every byte staged exactly once

    def test_mesh_matches_sequential(self, data):
        from flox_tpu.parallel.mesh import make_mesh

        mesh = make_mesh()
        vals, labels = data
        out, _ = streaming_groupby_aggregate_many(
            vals, labels, funcs=CLIMATOLOGY, batch_len=800, mesh=mesh
        )
        for f in CLIMATOLOGY:
            seq = streaming_groupby_reduce(
                vals, labels, func=f, batch_len=800, mesh=mesh
            )[0]
            _assert_same(out[f], seq, f"{f} stream-mesh")

    @pytest.mark.parametrize("depth", [0, 2])
    def test_kill_resume_bit_identical(self, data, depth, tmp_path):
        from flox_tpu.resilience import _SNAPSHOTS

        vals, labels = data
        funcs = ("mean", "var", "min", "max", "count")

        def run():
            out, _ = streaming_groupby_aggregate_many(
                vals, labels, funcs=funcs, batch_len=500
            )
            return {f: np.asarray(v).tobytes() for f, v in out.items()}

        with flox_tpu.set_options(stream_prefetch=depth):
            base = run()
            with flox_tpu.set_options(stream_checkpoint_every=2):
                with faults.inject(kill_at=[1000]):
                    with pytest.raises(faults.StreamKilled):
                        run()
                assert len(_SNAPSHOTS) == 1  # the fused carry snapshotted
                resumed = run()
        assert resumed == base
        assert _SNAPSHOTS == {}

    def test_oom_split_on_fused_carry(self, data):
        # the OOM ladder re-folds sub-slabs through the fused carry: the
        # split run is bit-identical to each SEQUENTIAL statistic under
        # the same injection (the established split contract), and
        # allclose to the unsplit fused run
        from flox_tpu import profiling

        vals, labels = data
        funcs = ("mean", "var", "min", "max")
        base, _ = streaming_groupby_aggregate_many(
            vals, labels, funcs=funcs, batch_len=500
        )
        with faults.inject(oom_at=[1500]):
            with profiling.stream_monitor() as reports:
                out, _ = streaming_groupby_aggregate_many(
                    vals, labels, funcs=funcs, batch_len=500
                )
        assert reports[0].oom_splits == 1
        for f in funcs:
            with faults.inject(oom_at=[1500]):
                seq = streaming_groupby_reduce(vals, labels, func=f, batch_len=500)[0]
            _assert_same(out[f], seq, f"{f} oom-split")
            np.testing.assert_allclose(
                np.asarray(out[f]), np.asarray(base[f]), rtol=1e-12, equal_nan=True
            )

    def test_rejects_datetime(self, data):
        _, labels = data
        dt = np.arange(labels.size, dtype=np.int64).view("datetime64[ns]")
        with pytest.raises(NotImplementedError, match="numeric"):
            streaming_groupby_aggregate_many(dt, labels, funcs=("min", "max"))

    def test_single_stat_api_rejects_func_lists(self, data):
        # the single-statistic boundary must fail loudly rather than
        # silently switch its return contract to (dict, groups)
        vals, labels = data
        with pytest.raises(TypeError, match="aggregate_many"):
            streaming_groupby_reduce(vals, labels, func=["sum"])


# ---------------------------------------------------------------------------
# kernels: the absorbed fused primitive + megakernel
# ---------------------------------------------------------------------------


class TestFusedKernelPrimitive:
    def test_megakernel_matches_per_leg(self):
        # force the pallas policy (interpret mode on CPU) and check the
        # one-pass multi-output primitive against the per-leg kernels
        import jax.numpy as jnp

        from flox_tpu.kernels import fused_segment_stats, generic_kernel

        rng = np.random.default_rng(5)
        vals = rng.normal(size=(2, 320)).astype(np.float32)
        vals[0, 3] = np.nan
        vals[1, 7] = np.inf
        labels = rng.integers(0, 4, 320).astype(np.int32)
        with flox_tpu.set_options(segment_sum_impl="pallas"):
            got = fused_segment_stats(
                labels, jnp.asarray(vals), size=4,
                want=("sum", "nansum", "min", "max", "nanmin", "len", "nanlen"),
            )
        assert got is not None
        with flox_tpu.set_options(segment_sum_impl="pallas"):
            for name in ("sum", "nansum", "min", "max", "nanmin"):
                ref = generic_kernel(
                    name, labels, jnp.asarray(vals), size=4,
                    fill_value=None if name in ("sum", "nansum") else
                    (np.inf if "min" in name else -np.inf),
                )
                np.testing.assert_array_equal(
                    np.asarray(got[name]), np.asarray(ref), err_msg=name
                )

    def test_scatter_policy_returns_none(self):
        import jax.numpy as jnp

        from flox_tpu.kernels import fused_segment_stats

        vals = jnp.ones((32,), jnp.float32)
        labels = np.zeros(32, np.int32)
        with flox_tpu.set_options(segment_sum_impl="scatter"):
            assert fused_segment_stats(labels, vals, size=2, want=("sum", "nanlen")) is None

    def test_counts_alone_never_fuse(self):
        import jax.numpy as jnp

        from flox_tpu.kernels import fused_segment_stats

        vals = jnp.ones((32,), jnp.float32)
        with flox_tpu.set_options(segment_sum_impl="pallas"):
            assert (
                fused_segment_stats(
                    np.zeros(32, np.int32), vals, size=2, want=("len", "nanlen")
                )
                is None
            )

    def test_mean_var_ride_the_shared_primitive(self):
        # satellite: _fused_sum_counts is now a `want` set of the general
        # primitive — mean/var single-statistic calls share it
        import jax.numpy as jnp

        from flox_tpu.kernels import _fused_sum_counts

        rng = np.random.default_rng(2)
        vals = jnp.asarray(rng.normal(size=(2, 160)).astype(np.float32))
        labels = jnp.asarray(rng.integers(0, 3, 160).astype(np.int32))
        with flox_tpu.set_options(segment_sum_impl="pallas"):
            got = _fused_sum_counts(
                jnp.moveaxis(vals, -1, 0), jnp.asarray(labels), 3
            )
        assert got is not None
        total, cnt = got
        np.testing.assert_allclose(
            np.asarray(total).sum(), np.asarray(vals).sum(), rtol=1e-5
        )
        np.testing.assert_array_equal(
            np.asarray(cnt).sum(axis=0), np.full(2, 160.0)
        )


# ---------------------------------------------------------------------------
# autotune dispatch + serve integration
# ---------------------------------------------------------------------------


class TestDispatchIntegration:
    def test_autotune_sequential_winner_falls_back(self, data, tmp_path):
        from flox_tpu import autotune

        vals, labels = data
        with flox_tpu.set_options(
            autotune=True, autotune_cache_path=str(tmp_path / "at.json")
        ):
            cache.clear_all()
            nelems = vals.size
            autotune.record("fused", "sequential", 100.0, dtype=str(vals.dtype),
                            ngroups=7, nelems=nelems)
            autotune.record("fused", "fused", 1.0, dtype=str(vals.dtype),
                            ngroups=7, nelems=nelems)
            out, _ = groupby_aggregate_many(vals, labels, funcs=("mean", "max"))
            # the sequential branch is still correct — and bit-identical
            for f in ("mean", "max"):
                _assert_same(out[f], groupby_reduce(vals, labels, func=f)[0], f)
        cache.clear_all()

    def test_serve_multi_stat_coalesce_and_batch(self, data):
        import asyncio

        from flox_tpu.serve.dispatcher import AggregationRequest, Dispatcher
        from flox_tpu.telemetry import METRICS

        vals, labels = data
        arr = np.ascontiguousarray(vals[0])

        async def main():
            d = Dispatcher()
            d0 = METRICS.get("serve.dispatches")
            r1, r2 = await asyncio.gather(
                d.submit(AggregationRequest(func=["mean", "max"], array=arr, by=labels)),
                d.submit(AggregationRequest(func=["mean", "max"], array=arr, by=labels)),
            )
            assert METRICS.get("serve.dispatches") - d0 == 1  # coalesced
            assert r1.coalesced or r2.coalesced
            d1 = METRICS.get("serve.dispatches")
            r3, r4 = await asyncio.gather(
                d.submit(AggregationRequest(func=("mean", "max"), array=arr, by=labels)),
                d.submit(AggregationRequest(func=("mean", "max"), array=arr * 2, by=labels)),
            )
            assert METRICS.get("serve.dispatches") - d1 == 1  # micro-batched
            assert r3.batch_size == 2 and r4.batch_size == 2
            await d.close()
            return r1, r4

        r1, r4 = asyncio.run(main())
        _assert_same(
            r1.result["mean"], groupby_reduce(arr, labels, func="mean")[0],
            "serve mean",
        )
        _assert_same(
            r4.result["max"], groupby_reduce(arr * 2, labels, func="max")[0],
            "serve batched max row",
        )

    def test_bench_seed_feeds_fused_family(self):
        from flox_tpu import autotune

        n = autotune._seed_from_bench_record(
            {
                "platform": "cpu",
                "workload": {"nlat": 2, "nlon": 2, "ntime": 100, "ngroups": 4},
                "fused": {"fused_sweep_gbps": {"fused": 5.0, "sequential": 1.5}},
            }
        )
        assert n == 2
        rec = autotune.lookup("fused", dtype="float32", ngroups=4, nelems=400,
                              platform="cpu")
        assert rec is not None and set(rec["candidates"]) == {"fused", "sequential"}
        cache.clear_all()
