"""Telemetry test suite (ISSUE 4).

The contract under test: with telemetry disabled nothing changes — results
are bit-identical, no span objects are allocated, counters stay untouched;
with telemetry enabled every execution path produces a hierarchical trace
(factorize/dispatch/combine/finalize for ``groupby_reduce``), the exporters
round-trip (emit -> parse -> report), the Chrome trace file is
Perfetto-loadable JSON, and ``cache.clear_all`` resets the metrics registry.
"""

from __future__ import annotations

import json

import numpy as np
import pytest

import flox_tpu
from flox_tpu import cache, telemetry
from flox_tpu.core import groupby_reduce
from flox_tpu.scan import groupby_scan
from flox_tpu.streaming import streaming_groupby_reduce

RNG = np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Every test starts from an empty buffer + registry with telemetry OFF
    and no export path — even when the suite itself runs under
    FLOX_TPU_TELEMETRY=1 (the CI instrumented leg), so the disabled-mode
    assertions test the option, not the environment."""
    with flox_tpu.set_options(telemetry=False, telemetry_export_path=None):
        telemetry.reset()
        yield
        telemetry.reset()


def _run_reduce(**kw):
    # a FIXED workload: bit-identity tests compare two runs of this
    vals = np.random.default_rng(0).normal(size=(3, 48)).astype(np.float64)
    codes = np.arange(48) % 5
    return groupby_reduce(vals, codes, func="nanmean", engine="jax", **kw)


# ---------------------------------------------------------------------------
# disabled mode: a true no-op
# ---------------------------------------------------------------------------


class TestDisabledMode:
    def test_span_is_the_shared_noop_singleton(self):
        # no allocation when disabled: every span() call returns ONE object
        s1 = telemetry.span("groupby_reduce")
        s2 = telemetry.span("factorize", ngroups=3)
        assert s1 is s2 is telemetry._NOOP
        with s1 as sp:
            sp.set(attr=1)  # the no-op API surface still chains
        assert telemetry.spans() == []

    def test_counters_untouched_and_no_records(self):
        result_off, _ = _run_reduce()
        streaming_groupby_reduce(
            lambda s, e: np.ones((2, e - s)), np.arange(32) % 4,
            func="nansum", batch_len=8,
        )
        assert telemetry.spans() == []
        assert telemetry.METRICS.snapshot() == {}

    def test_module_helpers_noop(self):
        telemetry.count("x")
        telemetry.event("y", a=1)
        telemetry.record_span("z", 0.0, 1.0)
        telemetry.current_set(a=1)
        assert telemetry.spans() == []
        assert telemetry.METRICS.snapshot() == {}


# ---------------------------------------------------------------------------
# enabled/disabled bit-identity
# ---------------------------------------------------------------------------


class TestBitIdentity:
    def test_reduce_identical(self):
        off, _ = _run_reduce()
        with flox_tpu.set_options(telemetry=True):
            on, _ = _run_reduce()
        np.testing.assert_array_equal(np.asarray(off), np.asarray(on))

    def test_mesh_reduce_identical(self):
        off, _ = _run_reduce(method="map-reduce")
        with flox_tpu.set_options(telemetry=True, telemetry_level="detailed"):
            on, _ = _run_reduce(method="map-reduce")
        np.testing.assert_array_equal(np.asarray(off), np.asarray(on))

    def test_scan_identical(self):
        vals = RNG.normal(size=64)
        codes = np.arange(64) % 3
        off = groupby_scan(vals, codes, func="cumsum")
        with flox_tpu.set_options(telemetry=True):
            on = groupby_scan(vals, codes, func="cumsum")
        np.testing.assert_array_equal(np.asarray(off), np.asarray(on))

    def test_streaming_identical(self):
        vals = RNG.normal(size=(2, 96))
        codes = np.arange(96) % 7

        def loader(s, e):
            return vals[:, s:e]

        off, _ = streaming_groupby_reduce(loader, codes, func="nanmean", batch_len=16)
        with flox_tpu.set_options(telemetry=True, telemetry_level="detailed"):
            on, _ = streaming_groupby_reduce(loader, codes, func="nanmean", batch_len=16)
        np.testing.assert_array_equal(np.asarray(off), np.asarray(on))


# ---------------------------------------------------------------------------
# span hierarchy per execution path
# ---------------------------------------------------------------------------


def _by_name(records):
    out = {}
    for rec in records:
        out.setdefault(rec["name"], []).append(rec)
    return out


class TestSpans:
    def test_eager_reduce_phases(self):
        with flox_tpu.set_options(telemetry=True):
            _run_reduce()
        spans = _by_name([r for r in telemetry.spans() if r["type"] == "span"])
        for phase in ("groupby_reduce", "factorize", "dispatch", "combine", "finalize"):
            assert phase in spans, f"missing {phase} span"
        root = spans["groupby_reduce"][0]
        # the phases nest under the root span
        for phase in ("factorize", "dispatch", "combine", "finalize"):
            assert spans[phase][0]["parent"] == root["id"], phase
        assert root["parent"] is None
        assert spans["factorize"][0]["attrs"]["size"] == 5
        assert spans["dispatch"][0]["attrs"]["engine"] == "jax"

    def test_mesh_reduce_phases(self):
        with flox_tpu.set_options(telemetry=True):
            _run_reduce(method="map-reduce")
        names = {r["name"] for r in telemetry.spans()}
        assert {"groupby_reduce", "factorize", "combine", "finalize"} <= names
        # first call builds the SPMD program, later ones hit the cache and
        # dispatch under the annotated span — one of the two must be present
        assert ("program-build" in names) or any(
            n.startswith("flox:mesh-dispatch") for n in names
        )

    def test_scan_phases(self):
        with flox_tpu.set_options(telemetry=True):
            groupby_scan(RNG.normal(size=32), np.arange(32) % 3, func="cumsum")
        names = {r["name"] for r in telemetry.spans()}
        assert {"groupby_scan", "factorize", "dispatch", "finalize"} <= names

    def test_streaming_phases_and_stream_report_attrs(self):
        vals = RNG.normal(size=(2, 64))
        codes = np.arange(64) % 4
        with flox_tpu.set_options(telemetry=True):
            streaming_groupby_reduce(
                lambda s, e: vals[:, s:e], codes, func="nanmean", batch_len=16
            )
        spans = _by_name([r for r in telemetry.spans() if r["type"] == "span"])
        assert "streaming_groupby_reduce" in spans
        assert "factorize" in spans and "finalize" in spans
        stream = [n for n in spans if n.startswith("stream[")]
        assert stream, f"no stream pass span in {sorted(spans)}"
        attrs = spans[stream[0]][0]["attrs"]
        # the StreamReport totals ride the span as attributes
        for key in ("slabs", "prefetch", "load_ms", "stage_ms", "wait_ms",
                    "dispatch_ms", "overlap_fraction", "retries"):
            assert key in attrs, key
        assert attrs["slabs"] == 4

    def test_detailed_level_stage_spans(self):
        vals = RNG.normal(size=(2, 64))
        codes = np.arange(64) % 4
        with flox_tpu.set_options(telemetry=True, telemetry_level="detailed"):
            streaming_groupby_reduce(
                lambda s, e: vals[:, s:e], codes, func="nansum", batch_len=16
            )
        stage = [r for r in telemetry.spans() if r["name"] == "stage"]
        assert len(stage) == 4  # one per slab
        assert {s["attrs"]["index"] for s in stage} == {0, 1, 2, 3}

    def test_basic_level_omits_stage_spans(self):
        vals = RNG.normal(size=(2, 64))
        codes = np.arange(64) % 4
        with flox_tpu.set_options(telemetry=True, telemetry_level="basic"):
            streaming_groupby_reduce(
                lambda s, e: vals[:, s:e], codes, func="nansum", batch_len=16
            )
        assert not [r for r in telemetry.spans() if r["name"] == "stage"]


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


class TestMetrics:
    def test_compile_counter_nonzero_on_fresh_program(self):
        cache.clear_all()
        with flox_tpu.set_options(telemetry=True):
            # a fresh shape after clear_all: the kernel bundle rebuilds and
            # jax compiles it — both layers must see it
            vals = RNG.normal(size=(2, 101)).astype(np.float64)
            groupby_reduce(vals, np.arange(101) % 6, func="nanmean", engine="jax")
        snap = telemetry.METRICS.snapshot()
        assert snap.get("cache.bundle_builds", 0) >= 1
        assert snap.get("cache.bundle_calls", 0) >= 1
        assert snap.get("jax.compiles", 0) >= 1, snap
        assert snap.get("jax.traces", 0) >= 1
        assert snap.get("jax.compile_ms", 0) > 0

    def test_clear_all_resets_metrics_registry(self):
        with flox_tpu.set_options(telemetry=True):
            _run_reduce()
        assert telemetry.METRICS.snapshot()
        cache.clear_all()
        assert telemetry.METRICS.snapshot() == {}

    def test_h2d_bytes_counted_by_stager(self):
        vals = RNG.normal(size=(2, 64))
        codes = np.arange(64) % 4
        with flox_tpu.set_options(telemetry=True):
            streaming_groupby_reduce(
                lambda s, e: vals[:, s:e], codes, func="nansum", batch_len=16
            )
        # every slab's data + codes crossed H2D at least once
        assert telemetry.METRICS.get("bytes.h2d") >= vals.nbytes

    def test_retry_counter_and_event(self):
        from flox_tpu.resilience import RetryPolicy, call_with_retry

        attempts = {"n": 0}

        def flaky():
            attempts["n"] += 1
            if attempts["n"] < 3:
                raise OSError("transient hiccup")
            return "ok"

        with flox_tpu.set_options(telemetry=True):
            out = call_with_retry(
                flaky, policy=RetryPolicy(retries=5, backoff=0.0), what="[0:8)"
            )
        assert out == "ok"
        assert telemetry.METRICS.get("stream.retries") == 2
        events = [r for r in telemetry.spans() if r["type"] == "event"]
        assert [e["name"] for e in events] == ["retry", "retry"]
        assert events[0]["attrs"]["what"] == "[0:8)"
        assert events[0]["attrs"]["error"] == "OSError"

    def test_profile_call_shape(self):
        profile = telemetry.profile_call(lambda: _run_reduce())
        for key in ("compile_count", "trace_count", "compile_ms", "h2d_bytes",
                    "phase_ms", "cache_sizes"):
            assert key in profile, key
        assert "groupby_reduce" in profile["phase_ms"]
        assert "bundle_lru" in profile["cache_sizes"]
        # profile_call restores the switch: nothing keeps recording after
        from flox_tpu.options import OPTIONS

        assert OPTIONS["telemetry"] is False

    def test_registry_is_threadsafe_counterwise(self):
        import threading

        reg = telemetry.MetricsRegistry()

        def spin():
            for _ in range(1000):
                reg.inc("n")

        threads = [threading.Thread(target=spin) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert reg.get("n") == 4000

    def test_gauges(self):
        reg = telemetry.MetricsRegistry()
        reg.set_gauge("g", 2.0)
        reg.max_gauge("g", 1.0)
        assert reg.get("g") == 2.0
        reg.max_gauge("g", 5.0)
        assert reg.get("g") == 5.0


# ---------------------------------------------------------------------------
# exporters: emit -> parse -> report
# ---------------------------------------------------------------------------


class TestExporters:
    def _instrumented_records(self):
        with flox_tpu.set_options(telemetry=True):
            _run_reduce()
        return telemetry.spans()

    def test_chrome_trace_roundtrip(self, tmp_path):
        records = self._instrumented_records()
        path = tmp_path / "trace.json"
        telemetry.export_chrome_trace(str(path), records)
        payload = json.loads(path.read_text())  # must be ONE valid JSON doc
        events = payload["traceEvents"]
        assert events
        # the Perfetto/Chrome contract: complete events with ts+dur, us units
        for ev in events:
            assert ev["ph"] in ("X", "i")
            assert "ts" in ev and "pid" in ev and "tid" in ev
            if ev["ph"] == "X":
                assert "dur" in ev
        names = {ev["name"] for ev in events}
        for phase in ("groupby_reduce", "factorize", "dispatch", "combine", "finalize"):
            assert phase in names
        assert "floxTpuCounters" in payload

    def test_jsonl_roundtrip_and_report(self, tmp_path):
        records = self._instrumented_records()
        path = tmp_path / "trace.jsonl"
        telemetry.export_jsonl(str(path), records)
        parsed, counters = telemetry._load_export(str(path))
        assert {r["name"] for r in parsed} == {r["name"] for r in records}
        assert counters == telemetry.METRICS.snapshot()
        lines = telemetry._report_lines(str(path))
        text = "\n".join(lines)
        assert "factorize" in text and "dispatch" in text
        assert "cache.bundle_calls" in text

    def test_report_reads_both_formats_identically(self, tmp_path):
        records = self._instrumented_records()
        j = tmp_path / "t.jsonl"
        c = tmp_path / "t.json"
        telemetry.export_jsonl(str(j), records)
        telemetry.export_chrome_trace(str(c), records)
        rows_j = telemetry.summarize(telemetry._load_export(str(j))[0])
        rows_c = telemetry.summarize(telemetry._load_export(str(c))[0])
        assert [r["name"] for r in rows_j] == [r["name"] for r in rows_c]
        assert [r["count"] for r in rows_j] == [r["count"] for r in rows_c]

    def test_report_cli(self, tmp_path, capsys):
        records = self._instrumented_records()
        path = tmp_path / "trace.json"
        telemetry.export_chrome_trace(str(path), records)
        rc = telemetry.main(["report", str(path)])
        assert rc == 0
        out = capsys.readouterr().out
        assert "groupby_reduce" in out
        assert "counters/gauges" in out

    def test_export_path_jsonl_streams_incrementally(self, tmp_path):
        path = tmp_path / "stream.jsonl"
        with flox_tpu.set_options(telemetry=True, telemetry_export_path=str(path)):
            _run_reduce()
            telemetry.flush()
        lines = [json.loads(line) for line in path.read_text().splitlines() if line]
        assert any(r.get("name") == "groupby_reduce" for r in lines)
        assert lines[-1]["type"] == "counters"
        # streamed records left the in-process buffer
        assert telemetry.spans() == []

    def test_export_path_chrome_written_on_flush(self, tmp_path):
        path = tmp_path / "trace.json"
        with flox_tpu.set_options(telemetry=True, telemetry_export_path=str(path)):
            _run_reduce()
            telemetry.flush()
        payload = json.loads(path.read_text())
        assert payload["traceEvents"]
        assert payload["floxTpuCounters"].get("cache.bundle_calls", 0) >= 1

    def test_report_cli_rejects_garbage(self, tmp_path):
        bad = tmp_path / "not-a-trace.json"
        bad.write_text("{definitely not json")
        with pytest.raises(SystemExit):
            telemetry.main(["report", str(bad)])


# ---------------------------------------------------------------------------
# option validation
# ---------------------------------------------------------------------------


class TestOptions:
    def test_validated_at_set_time(self):
        with pytest.raises(ValueError):
            flox_tpu.set_options(telemetry=1)  # bool, not int
        with pytest.raises(ValueError):
            flox_tpu.set_options(telemetry_level="verbose")
        with pytest.raises(ValueError):
            flox_tpu.set_options(telemetry_export_path="")

    def test_context_manager_restores(self):
        from flox_tpu.options import OPTIONS

        before = OPTIONS["telemetry"]
        with flox_tpu.set_options(telemetry=True):
            assert OPTIONS["telemetry"] is True
        assert OPTIONS["telemetry"] is before


# ---------------------------------------------------------------------------
# the acceptance criterion, end to end
# ---------------------------------------------------------------------------


def test_acceptance_perfetto_trace_with_compile_counter(tmp_path):
    """A groupby_reduce with telemetry enabled produces a Perfetto-loadable
    trace containing factorize/dispatch/combine/finalize spans and a nonzero
    compile counter (ISSUE 4 acceptance)."""
    cache.clear_all()
    telemetry.reset()
    path = tmp_path / "acceptance.json"
    with flox_tpu.set_options(telemetry=True):
        vals = RNG.normal(size=(4, 97)).astype(np.float64)
        result, groups = groupby_reduce(
            vals, np.arange(97) % 9, func="nanmean", engine="jax"
        )
        telemetry.export_chrome_trace(str(path))
    payload = json.loads(path.read_text())
    names = {ev["name"] for ev in payload["traceEvents"]}
    assert {"groupby_reduce", "factorize", "dispatch", "combine", "finalize"} <= names
    assert payload["floxTpuCounters"].get("jax.compiles", 0) > 0
    # and the trace is self-describing enough for the report tool
    rows = telemetry.summarize(telemetry._load_export(str(path))[0])
    assert any(r["name"] == "dispatch" for r in rows)


# ---------------------------------------------------------------------------
# histograms (ISSUE 6): log-spaced buckets, p50/p99, report CLI
# ---------------------------------------------------------------------------


class TestHistograms:
    def test_observe_and_percentile(self):
        reg = telemetry.MetricsRegistry()
        for v in (1.0, 1.0, 1.0, 1.0, 100.0):
            reg.observe("lat_ms", v)
        hist = reg.histograms()["lat_ms"]
        assert hist["count"] == 5
        assert hist["sum"] == pytest.approx(104.0)
        assert hist["min"] == 1.0 and hist["max"] == 100.0
        assert sum(hist["counts"]) == 5
        p50 = reg.percentile("lat_ms", 0.50)
        assert 0.5 <= p50 <= 2.1  # inside (or clamped to) the 1.0 bucket
        assert reg.percentile("lat_ms", 0.99) == pytest.approx(100.0)
        assert reg.percentile("lat_ms", 0.0) == 1.0  # clamped to observed min
        assert reg.percentile("unknown", 0.5) is None

    def test_reset_clears_histograms(self):
        telemetry.METRICS.observe("lat_ms", 5.0)
        cache.clear_all()
        assert telemetry.METRICS.histograms() == {}

    def test_spans_feed_histograms_when_enabled(self):
        with flox_tpu.set_options(telemetry=True):
            with telemetry.span("phasex"):
                pass
        hists = telemetry.METRICS.histograms()
        assert "span_ms.phasex" in hists
        assert hists["span_ms.phasex"]["count"] == 1

    def test_disabled_spans_leave_histograms_untouched(self):
        with telemetry.span("phasex"):
            pass
        assert telemetry.METRICS.histograms() == {}

    def test_summarize_has_exact_percentiles(self):
        records = [
            {"type": "span", "name": "p", "dur_us": d * 1e3}
            for d in (1.0, 2.0, 3.0, 4.0, 100.0)
        ]
        row = telemetry.summarize(records)[0]
        assert row["p50_ms"] == 3.0
        assert row["p99_ms"] == 100.0

    def test_exports_carry_histograms_both_formats(self, tmp_path):
        with flox_tpu.set_options(telemetry=True):
            with telemetry.span("phasex"):
                pass
            j, c = tmp_path / "t.jsonl", tmp_path / "t.json"
            telemetry.export_jsonl(str(j))
            telemetry.export_chrome_trace(str(c))
        payload = json.loads(c.read_text())
        assert "span_ms.phasex" in payload["floxTpuHistograms"]
        assert payload["floxTpuHistEdgesMs"] == list(telemetry.HIST_EDGES_MS)
        _, _, hists_j = telemetry._parse_export(str(j))
        _, _, hists_c = telemetry._parse_export(str(c))
        assert hists_j["span_ms.phasex"]["count"] == 1
        assert hists_c["span_ms.phasex"]["count"] == 1

    def test_report_cli_histograms_flag(self, tmp_path, capsys):
        with flox_tpu.set_options(telemetry=True):
            with telemetry.span("phasex"):
                pass
            path = tmp_path / "t.jsonl"
            telemetry.export_jsonl(str(path))
        rc = telemetry.main(["report", str(path), "--histograms"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "histograms" in out
        assert "span_ms.phasex" in out
        assert "p99" in out
        # the default table now carries the exact per-phase percentiles too
        assert "p50 ms" in out and "p99 ms" in out

    def test_report_cli_rejects_malformed_jsonl_line(self, tmp_path, capsys):
        path = tmp_path / "t.jsonl"
        path.write_text(
            '{"type": "span", "name": "ok", "dur_us": 1.0}\n'
            "{broken json line\n"
        )
        with pytest.raises(SystemExit) as exc_info:
            telemetry.main(["report", str(path)])
        assert exc_info.value.code != 0
        err = capsys.readouterr().err
        assert ":2:" in err  # the error names the malformed line

    def test_report_cli_rejects_non_object_jsonl_line(self, tmp_path):
        path = tmp_path / "t.jsonl"
        path.write_text('{"type": "span", "name": "ok", "dur_us": 1.0}\n[1, 2]\n')
        with pytest.raises(SystemExit) as exc_info:
            telemetry.main(["report", str(path)])
        assert exc_info.value.code != 0
