"""Tests for ``faults.stress_schedule`` — the schedule-stress race harness
(ISSUE 16): switch-interval handling, lock wrapping/unwrapping, the
acquisition-order watcher's inversion/self-deadlock detection, static-graph
seeding, and probes that the serve-plane races fixed in this PR stay fixed.
"""

from __future__ import annotations

import json
import sys
import threading
import types

import pytest

from flox_tpu import faults
from flox_tpu.faults import LockOrderViolation, stress_schedule


def _demo_module(name="stress_demo_mod"):
    mod = types.ModuleType(name)
    mod._A = threading.Lock()
    mod._B = threading.Lock()
    mod._R = threading.RLock()
    sys.modules[name] = mod
    return mod


@pytest.fixture
def demo():
    mod = _demo_module()
    yield mod
    sys.modules.pop(mod.__name__, None)


def test_switch_interval_set_and_restored():
    prev = sys.getswitchinterval()
    with stress_schedule(switch_interval=1e-6) as watcher:
        assert watcher is None  # nothing watched
        assert sys.getswitchinterval() == pytest.approx(1e-6)
    assert sys.getswitchinterval() == pytest.approx(prev)


def test_switch_interval_restored_on_error():
    prev = sys.getswitchinterval()
    with pytest.raises(RuntimeError):
        with stress_schedule(switch_interval=1e-6):
            raise RuntimeError("body failed")
    assert sys.getswitchinterval() == pytest.approx(prev)


def test_wraps_and_restores_module_locks(demo):
    raw_a, raw_r = demo._A, demo._R
    with stress_schedule(watch=(demo.__name__,)) as watcher:
        assert watcher is not None
        assert demo._A is not raw_a  # proxied
        with demo._A:  # the proxy is a drop-in context manager
            pass
        assert not demo._A.locked()
    assert demo._A is raw_a and demo._R is raw_r  # originals restored


def test_lock_order_inversion_raises(demo):
    with stress_schedule(watch=(demo.__name__,)):
        with demo._A:
            with demo._B:
                pass
        with pytest.raises(LockOrderViolation) as exc:
            with demo._B:
                with demo._A:
                    pass
        msg = str(exc.value)
        assert "_A" in msg and "_B" in msg and "inversion" in msg


def test_self_reentry_raises_instead_of_deadlocking(demo):
    with stress_schedule(watch=(demo.__name__,)):
        with pytest.raises(LockOrderViolation, match="self-deadlock"):
            with demo._A:
                with demo._A:
                    pass


def test_rlock_reentry_allowed(demo):
    with stress_schedule(watch=(demo.__name__,)):
        with demo._R:
            with demo._R:
                pass
        assert True  # reached without a violation


def test_release_pops_held_stack(demo):
    # sequential (non-nested) acquisitions record no order edges; the
    # cumulative graph still catches a later genuine inversion
    with stress_schedule(watch=(demo.__name__,)) as watcher:
        with demo._A:
            pass
        with demo._B:
            with demo._A:
                pass  # order is now B -> A
        assert (f"{demo.__name__}._B", f"{demo.__name__}._A") in watcher.edges
        with pytest.raises(LockOrderViolation):
            with demo._A:
                with demo._B:
                    pass


def test_seeded_graph_from_dict(demo):
    # one runtime acquire against the statically-established order fails
    seed = {"edges": [{"from": f"{demo.__name__}._A",
                       "to": f"{demo.__name__}._B",
                       "site": "static.py:1"}]}
    with stress_schedule(watch=(demo.__name__,), order_graph=seed):
        with pytest.raises(LockOrderViolation, match="static.py:1"):
            with demo._B:
                with demo._A:
                    pass


def test_seeded_graph_from_file(tmp_path, demo):
    path = tmp_path / "locks.json"
    path.write_text(json.dumps({"edges": [
        {"from": f"{demo.__name__}._A", "to": f"{demo.__name__}._B",
         "site": "static.py:1"},
    ]}))
    with stress_schedule(watch=(demo.__name__,), order_graph=str(path)):
        with pytest.raises(LockOrderViolation):
            with demo._B:
                with demo._A:
                    pass


def test_nonblocking_acquire_failure_is_not_recorded(demo):
    with stress_schedule(watch=(demo.__name__,)) as watcher:
        raw = demo._A._inner
        raw.acquire()  # another owner holds the underlying lock
        try:
            assert demo._A.acquire(blocking=False) is False
        finally:
            raw.release()
        # a failed acquire must not leave _A on the held stack
        with demo._A:
            pass
        assert watcher.edges == {}


def test_cross_thread_inversion_caught(demo):
    # thread 1 establishes A -> B; thread 2's B -> A attempt must raise in
    # thread 2, not deadlock the suite
    errors: list[BaseException] = []
    with stress_schedule(watch=(demo.__name__,)):
        def fwd():
            with demo._A:
                with demo._B:
                    pass

        def rev():
            try:
                with demo._B:
                    with demo._A:
                        pass
            except LockOrderViolation as exc:
                errors.append(exc)

        t1 = threading.Thread(target=fwd)
        t1.start(); t1.join()
        t2 = threading.Thread(target=rev)
        t2.start(); t2.join()
    assert len(errors) == 1


# -- the races this PR fixed stay fixed --------------------------------------


class _ProbeLock:
    def __init__(self):
        self.events: list[str] = []

    def __enter__(self):
        self.events.append("acquire")
        return self

    def __exit__(self, *exc):
        self.events.append("release")
        return False


def test_exposition_set_ready_takes_state_lock(monkeypatch):
    from flox_tpu import exposition

    probe = _ProbeLock()
    monkeypatch.setattr(exposition, "_STATE_LOCK", probe)
    exposition.set_ready(False, reason="probe")
    assert probe.events == ["acquire", "release"]
    assert exposition.ready() is False
    assert exposition.ready_reason() == "probe"
    exposition.set_ready(True)


def test_autotune_register_atexit_takes_lock(monkeypatch):
    from flox_tpu import autotune

    probe = _ProbeLock()
    monkeypatch.setattr(autotune, "_LOCK", probe)
    monkeypatch.setitem(autotune._AUTOTUNE_STATE, "atexit", True)
    autotune._register_atexit()  # already registered: early return, but locked
    assert probe.events == ["acquire", "release"]


def test_set_ready_races_clean_under_stress():
    # the set_ready/stop write-write race fixed in this PR, driven hard:
    # flipping threads under a ~1 µs switch interval with the proxied lock
    # asserting order — consistent final state, no violation
    from flox_tpu import exposition

    with stress_schedule(watch=("flox_tpu.exposition",)):
        def flip(n):
            for i in range(200):
                exposition.set_ready(i % 2 == 0, reason=f"t{n}")

        threads = [threading.Thread(target=flip, args=(n,)) for n in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    exposition.set_ready(True)
    assert exposition.ready() is True


def test_stress_schedule_exports():
    assert "stress_schedule" in faults.__all__
    assert "LockOrderViolation" in faults.__all__
