"""Durable incremental aggregation store (ROADMAP item 2: checkpoint/resume
promoted to a living store).

An :class:`IncrementalAggregationStore` appends newly arrived slabs to
persisted per-group intermediate state and serves finalized reads without
recomputing history. The stored carry is the fused multi-stat leg set
(:func:`flox_tpu.aggregations.plan_fused` — one deduplicated chunk plan for
all N requested statistics), held compactly as one
:class:`~flox_tpu.multiarray.PresentGroups` layer per leg: a million-label
universe persists only the groups ever seen, and two ingests with different
present sets fold via the union merge (``PresentGroups.merge`` /
``merge_present_var``).

On-disk layout of a store directory::

    journal.log            append-only WAL; one checksummed JSON record per
                           line (create / append-intent / compact-commit)
    seg-<g>.npz            delta segment: generation g's compact slab layers
    seg-<lo>-<hi>.npz      compacted segment covering generations lo..hi
    *.corrupt[.N]          quarantined segments (recovery evidence, never read)

Durability protocol (the robustness core):

* **Exactly-once ingestion.** ``append`` journals the slab fingerprint +
  generation (fsynced) BEFORE any state lands; the delta segment landing is
  the commit point. A replayed slab whose fingerprint is already committed
  acks as a no-op; a crash between journal intent and segment leaves an
  uncommitted intent that recovery skips — the store reopens at the last
  durable generation and the client's retry ingests the slab once.
* **Checksummed atomic segments.** Every segment is a format-versioned
  ``.npz`` with per-array blake2b digests in the header, serialized to
  bytes and landed tmp → fsync → rename (+ directory fsync), so a torn
  write can exist only as a detectable half-file, never as silently wrong
  arrays.
* **Crash recovery on open.** The journal replays with per-line checksums
  (a torn tail line is dropped); every live segment verifies before use. An
  unverifiable TAIL append rolls back to the last complete generation
  (quarantined, warned, counted on ``store.recoveries``); unverifiable
  mid-history state quarantines the segment to ``.corrupt`` and raises a
  typed :class:`StoreCorruptionError` naming it.
* **Crash-safe compaction.** The merged segment lands and the journal's
  compact record fsyncs BEFORE any replaced segment deletes; recovery falls
  back to the replaced segments when the compacted one is damaged and they
  still verify, and finishes interrupted deletes idempotently.

The deterministic chaos harness is :func:`flox_tpu.faults.store_inject`
(kill-at-write-N / torn-write / bit-flip at any durable event); the
recovery-matrix tests kill at every fault point and assert the reopened
store is bit-identical to an uninterrupted run.
"""

from __future__ import annotations

import contextlib
import hashlib
import io
import json
import os
import threading
import warnings
from typing import Any

import numpy as np

from . import faults
from .aggregations import FusedAggregation, fused_chunk_stats, plan_fused
from .multiarray import MultiArray, PresentGroups, merge_present_var

__all__ = [
    "IncrementalAggregationStore",
    "StoreCorruptionError",
    "open_store",
    "write_checksummed_npz",
    "read_checksummed_npz",
]

#: on-disk format version of checksummed segments and the journal
STORE_FORMAT_VERSION = 1

_JOURNAL = "journal.log"
_HEADER_KEY = "__header__"


class StoreCorruptionError(RuntimeError):
    """Unrecoverable on-disk damage: a mid-history segment (or the journal
    itself) failed verification and no fallback state survives. Carries the
    offending file's name so operators can locate the quarantined
    ``.corrupt`` evidence."""

    def __init__(self, segment: str, message: str) -> None:
        super().__init__(f"{message} (segment: {segment})")
        self.segment = segment


def _digest(data: bytes) -> str:
    return hashlib.blake2b(data, digest_size=16).hexdigest()


def _array_digest(arr: np.ndarray) -> str:
    a = np.ascontiguousarray(arr)
    return _digest(a.tobytes() + f"|{a.dtype.str}|{a.shape}".encode())


def _fsync_dir(path: str) -> None:
    # rename durability: the directory entry itself must reach disk
    try:
        fd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
    except OSError:  # pragma: no cover — exotic fs without dir open
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _land_bytes(path: str, data: bytes, *, kind: str, fsync: bool) -> None:
    """The durable-write funnel every segment goes through: one
    :func:`faults.store_poke` fault point, then tmp → fsync → rename."""
    action = faults.store_poke(kind, path) if faults.store_active() else None
    if action == "kill":
        raise faults.StoreWriteKilled(f"before {os.path.basename(path)}")
    if action == "torn":
        # the rename-happened-but-bytes-did-not-flush crash: half a file at
        # the final path, then death
        with open(path, "wb") as f:
            f.write(data[: max(1, len(data) // 2)])
        raise faults.StoreWriteKilled(f"torn write of {os.path.basename(path)}")
    if action == "flip":
        mangled = bytearray(data)
        mangled[len(mangled) // 2] ^= 0x40
        data = bytes(mangled)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, path)
    if fsync:
        _fsync_dir(path)


def write_checksummed_npz(
    path: str, arrays: dict, meta: dict, *, kind: str = "segment", fsync: bool = True
) -> None:
    """Write a checksummed, format-versioned ``.npz`` atomically.

    The ``__header__`` member carries ``{"format", "meta", "digests"}`` with
    a blake2b digest per array (over bytes + dtype + shape), so any torn or
    bit-flipped payload fails :func:`read_checksummed_npz` instead of
    loading silently wrong. Shared with the streaming checkpoint spill
    (``resilience._dump_snapshot``)."""
    header = {
        "format": STORE_FORMAT_VERSION,
        "meta": meta,
        "digests": {name: _array_digest(np.asarray(a)) for name, a in arrays.items()},
    }
    hdr = np.frombuffer(json.dumps(header, sort_keys=True).encode(), dtype=np.uint8)
    buf = io.BytesIO()
    np.savez(buf, **{_HEADER_KEY: hdr}, **arrays)
    _land_bytes(path, buf.getvalue(), kind=kind, fsync=fsync)


def read_checksummed_npz(path: str) -> tuple[dict, dict]:
    """Load and verify a checksummed ``.npz`` -> ``(arrays, meta)``.

    Raises :class:`StoreCorruptionError` on ANY verification failure — an
    unreadable zip (torn write), a missing/unknown header, a format version
    from the future, or a digest mismatch (bit rot). ``FileNotFoundError``
    passes through untouched (absence is not corruption)."""
    name = os.path.basename(path)
    try:
        with np.load(path, allow_pickle=False) as z:
            if _HEADER_KEY not in z.files:
                raise StoreCorruptionError(name, "missing checksummed header")
            header = json.loads(z[_HEADER_KEY].tobytes().decode())
            if int(header.get("format", -1)) > STORE_FORMAT_VERSION:
                raise StoreCorruptionError(
                    name, f"format {header.get('format')} is from the future"
                )
            digests = header.get("digests", {})
            arrays = {}
            for arr_name in z.files:
                if arr_name == _HEADER_KEY:
                    continue
                arr = z[arr_name]
                want = digests.get(arr_name)
                if want is None or _array_digest(arr) != want:
                    raise StoreCorruptionError(
                        name, f"checksum mismatch on array {arr_name!r}"
                    )
                arrays[arr_name] = arr
            if set(digests) - set(arrays):
                raise StoreCorruptionError(
                    name, f"arrays missing: {sorted(set(digests) - set(arrays))}"
                )
    except FileNotFoundError:
        raise
    except StoreCorruptionError:
        raise
    except Exception as exc:
        # BadZipFile / ValueError / truncated-read OSError — every way a
        # torn or mangled file can fail to parse means the same thing
        raise StoreCorruptionError(name, f"unreadable segment ({exc})") from exc
    return arrays, header.get("meta", {})


# ---------------------------------------------------------------------------
# journal: one checksummed JSON record per line
# ---------------------------------------------------------------------------


def _journal_line(record: dict) -> bytes:
    body = json.dumps(record, sort_keys=True)
    return (body + "\t#" + _digest(body.encode()) + "\n").encode()


def _parse_journal(path: str) -> tuple[list[dict], bool, int]:
    """Replay the journal -> ``(records, dropped_tail, valid_bytes)``.

    A line failing its checksum at the TAIL (nothing valid after it) is a
    torn write: dropped, reported via the flag. A bad line with valid lines
    AFTER it is mid-history damage -> :class:`StoreCorruptionError`.
    ``valid_bytes`` is the length of the longest prefix holding only
    complete, checksum-valid records — the truncation point that repairs a
    torn tail, so the NEXT append starts on a clean line boundary instead
    of gluing its record onto the half-written one (which a later open
    would drop as a torn tail, silently losing an acked generation)."""
    with open(path, "rb") as f:
        raw = f.read()
    records: list[dict] = []
    bad_at: int | None = None
    valid_bytes = 0
    offset = 0
    for i, line in enumerate(raw.split(b"\n")):
        line_end = min(offset + len(line) + 1, len(raw))
        if line.strip():
            rec = None
            try:
                body, got = line.decode().rsplit("\t#", 1)
                if _digest(body.encode()) == got:
                    rec = json.loads(body)
            except (ValueError, UnicodeDecodeError):
                rec = None
            if rec is None:
                if bad_at is None:
                    bad_at = i
            else:
                if bad_at is not None:
                    raise StoreCorruptionError(
                        _JOURNAL,
                        f"journal line {bad_at + 1} failed its checksum mid-history",
                    )
                records.append(rec)
                valid_bytes = line_end
        offset = line_end
    return records, bad_at is not None, valid_bytes


def _append_journal(path: str, record: dict, *, fsync: bool) -> None:
    data = _journal_line(record)
    action = faults.store_poke("journal", path) if faults.store_active() else None
    if action == "kill":
        raise faults.StoreWriteKilled("before journal append")
    if action == "torn":
        data = data[: max(1, len(data) // 2)]
    elif action == "flip":
        mangled = bytearray(data)
        mangled[len(mangled) // 3] ^= 0x40
        data = bytes(mangled)
    with open(path, "ab") as f:
        f.write(data)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    if action == "torn":
        raise faults.StoreWriteKilled("torn journal append")


# ---------------------------------------------------------------------------
# layer (de)serialization: PresentGroups legs <-> npz arrays
# ---------------------------------------------------------------------------


def _layers_to_arrays(layers: list) -> dict:
    arrays: dict[str, np.ndarray] = {}
    for i, layer in enumerate(layers):
        if isinstance(layer, tuple):  # var triple (m2, total, count)
            arrays[f"leg{i}.present"] = layer[0].present
            for pg, leaf in zip(layer, ("m2", "total", "count")):
                arrays[f"leg{i}.{leaf}"] = np.asarray(pg.values)
        else:
            arrays[f"leg{i}.present"] = layer.present
            arrays[f"leg{i}.values"] = np.asarray(layer.values)
    return arrays


def _arrays_to_layers(arrays: dict, fused: FusedAggregation, size: int) -> list:
    layers: list = []
    for i, op in enumerate(fused.combine):
        present = arrays[f"leg{i}.present"]
        if op == "var":
            layers.append(
                tuple(
                    PresentGroups(present, arrays[f"leg{i}.{leaf}"], size)
                    for leaf in ("m2", "total", "count")
                )
            )
        else:
            layers.append(PresentGroups(present, arrays[f"leg{i}.values"], size))
    return layers


class IncrementalAggregationStore:
    """One durable store: open with :meth:`create` / :meth:`open` (or the
    :func:`open_store` convenience), then :meth:`append` slabs,
    :meth:`query` finalized statistics, :meth:`compact` history. Thread-safe
    (one lock per store); all state is host-resident numpy, so recovery and
    serving restage never depend on a live accelerator."""

    def __init__(self, path: str, *, _token: object = None) -> None:
        if _token is not _CTOR_TOKEN:
            raise TypeError("use IncrementalAggregationStore.create/.open")
        self.path = str(path)
        self.name = os.path.basename(os.path.normpath(self.path))
        self._lock = threading.RLock()
        self._layers: list | None = None
        self._lead_shape: tuple = ()
        self._gen = 0
        self._ingested: set[str] = set()
        #: committed deltas since the last compaction: (gen, segname | None)
        self._live: list[tuple[int, str | None]] = []
        self._base: str | None = None
        self._base_lo = 1
        self.recovered = False

    # -- construction -------------------------------------------------------

    @classmethod
    def create(
        cls,
        path: str,
        *,
        funcs,
        size: int,
        array_dtype: Any = "float64",
        fill_value: Any = None,
        min_count: int = 0,
        finalize_kwargs: Any = None,
        engine: str = "numpy",
    ) -> "IncrementalAggregationStore":
        """Create an empty store at ``path`` (the directory must not already
        hold one). The aggregation plan — statistic set, label-universe
        size, slab dtype, fills — is fixed at creation and persisted in the
        journal's create record; every later open replays it."""
        if engine not in ("numpy", "jax"):
            raise ValueError(f"store engine must be 'numpy' or 'jax', got {engine!r}")
        os.makedirs(path, exist_ok=True)
        jpath = os.path.join(path, _JOURNAL)
        if os.path.exists(jpath):
            raise FileExistsError(f"store already exists at {path}")
        self = cls(path, _token=_CTOR_TOKEN)
        self._setup_plan(
            funcs=tuple(funcs), size=int(size),
            array_dtype=np.dtype(array_dtype).name, fill_value=fill_value,
            min_count=int(min_count), finalize_kwargs=finalize_kwargs,
            engine=engine,
        )
        _append_journal(
            jpath,
            {
                "rec": "create", "format": STORE_FORMAT_VERSION,
                "funcs": list(self.funcs), "size": self.size,
                "array_dtype": self.array_dtype.name, "fill_value": fill_value,
                "min_count": self.min_count, "finalize_kwargs": finalize_kwargs,
                "engine": engine,
            },
            fsync=self._fsync,
        )
        from . import telemetry

        telemetry.METRICS.inc("store.opens")
        return self

    @classmethod
    def open(cls, path: str) -> "IncrementalAggregationStore":
        """Open an existing store, running crash recovery: replay the
        journal, verify every live segment, roll back an unverifiable tail
        append, quarantine damage, finish interrupted compaction swaps."""
        jpath = os.path.join(path, _JOURNAL)
        if not os.path.exists(jpath):
            raise FileNotFoundError(f"no store at {path}")
        self = cls(path, _token=_CTOR_TOKEN)
        records, dropped_tail, valid_bytes = _parse_journal(jpath)
        if not records or records[0].get("rec") != "create":
            raise StoreCorruptionError(_JOURNAL, "journal has no create record")
        if dropped_tail:
            # Repair the torn tail NOW: the half-written bytes never formed
            # a valid record, and leaving them would make the next append
            # glue onto them — producing a line a later open drops as torn,
            # silently rolling back that acked generation.
            with open(jpath, "r+b") as f:
                f.truncate(valid_bytes)
                f.flush()
                os.fsync(f.fileno())
        c = records[0]
        self._setup_plan(
            funcs=tuple(c["funcs"]), size=int(c["size"]),
            array_dtype=c["array_dtype"], fill_value=c.get("fill_value"),
            min_count=int(c.get("min_count", 0)),
            finalize_kwargs=c.get("finalize_kwargs"),
            engine=c.get("engine", "numpy"),
        )
        self.recovered = dropped_tail
        self._recover(records[1:])
        from . import telemetry

        telemetry.METRICS.inc("store.opens")
        if self.recovered:
            telemetry.METRICS.inc("store.recoveries")
        return self

    def _setup_plan(
        self, *, funcs, size, array_dtype, fill_value, min_count,
        finalize_kwargs, engine,
    ) -> None:
        self.funcs = tuple(funcs)
        self.size = int(size)
        if self.size <= 0:
            raise ValueError(f"store size must be positive, got {size}")
        self.array_dtype = np.dtype(array_dtype)
        self.fill_value = fill_value
        self.min_count = int(min_count)
        self.finalize_kwargs = finalize_kwargs
        self.engine = engine
        self.fused: FusedAggregation = plan_fused(
            self.funcs, None, self.array_dtype, fill_value, self.min_count,
            finalize_kwargs,
        )
        from .options import OPTIONS

        self._fsync = OPTIONS["store_fsync"] != "off"
        self._compact_threshold = int(OPTIONS["store_compact_threshold"])

    # -- recovery -----------------------------------------------------------

    def _seg_path(self, seg: str) -> str:
        return os.path.join(self.path, seg)

    def _quarantine(self, seg: str) -> str | None:
        src = self._seg_path(seg)
        if not os.path.exists(src):
            return None
        dst = src + ".corrupt"
        n = 0
        while os.path.exists(dst):
            n += 1
            dst = src + f".corrupt.{n}"
        os.replace(src, dst)
        return os.path.basename(dst)

    def _verify_entry(self, entry: dict) -> tuple[dict, dict] | None:
        """Load + verify one stack entry's segment against the journal's
        claim; None means the entry is not usable (missing, torn, rotten,
        or claimed by a later record)."""
        try:
            arrays, meta = read_checksummed_npz(self._seg_path(entry["seg"]))
        except (FileNotFoundError, StoreCorruptionError):
            return None
        if entry["kind"] == "delta":
            if meta.get("gen") != entry["gen"] or meta.get("slab") != entry["fp"]:
                return None
        else:
            if meta.get("lo") != entry["lo"] or meta.get("hi") != entry["hi"]:
                return None
        return arrays, meta

    def _resolve_stack(self, stack: list[dict], warn: list[str]) -> list[dict]:
        """Journal-derived entry stack -> the verified entries recovery will
        fold, applying the tail-rollback and compaction-fallback rules.
        Verified arrays ride each entry under ``"loaded"``."""
        if stack and stack[0]["kind"] == "compact":
            head = stack[0]
            if head["empty"]:
                return [head] + self._resolve_stack(stack[1:], warn)
            loaded = self._verify_entry(head)
            if loaded is not None:
                head["loaded"] = loaded
                return [head] + self._resolve_stack(stack[1:], warn)
            # the compacted segment is damaged: fall back to the replaced
            # segments when they still verify (the kill-during-swap case)
            q = self._quarantine(head["seg"])
            warn.append(
                f"compacted segment {head['seg']} failed verification"
                + (f" (quarantined as {q})" if q else "")
                + "; falling back to its replaced segments"
            )
            return self._resolve_stack(head["prev"] + stack[1:], warn)
        out: list[dict] = []
        for i, entry in enumerate(stack):
            if entry["kind"] == "empty":
                out.append(entry)
                continue
            loaded = self._verify_entry(entry)
            if loaded is not None:
                entry["loaded"] = loaded
                out.append(entry)
                continue
            if i == len(stack) - 1:
                # unverifiable TAIL append: the crash-mid-append case — roll
                # back to the last complete generation
                q = self._quarantine(entry["seg"])
                warn.append(
                    f"rolling back generation {entry['gen']}: segment "
                    f"{entry['seg']} is torn or missing"
                    + (f" (quarantined as {q})" if q else "")
                )
                continue
            self._quarantine(entry["seg"])
            raise StoreCorruptionError(
                entry["seg"],
                f"mid-history segment for generation {entry['gen']} failed "
                "verification (quarantined)",
            )
        return out

    def _recover(self, records: list[dict]) -> None:
        stack: list[dict] = []
        gen_fp: dict[int, str] = {}
        for r in records:
            if r.get("rec") == "append":
                gen = int(r["gen"])
                stack = [
                    e for e in stack
                    if e["kind"] == "compact" or e["gen"] != gen
                ]
                stack.append(
                    {
                        "kind": "empty" if r.get("empty") else "delta",
                        "gen": gen, "seg": r.get("seg"), "fp": r["slab"],
                    }
                )
                gen_fp[gen] = r["slab"]
            elif r.get("rec") == "compact":
                stack = [
                    {
                        "kind": "compact", "lo": int(r["lo"]), "hi": int(r["hi"]),
                        "seg": r["seg"], "empty": bool(r.get("empty")),
                        "prev": stack,
                    }
                ]
        warn: list[str] = []
        resolved = self._resolve_stack(stack, warn)
        if warn:
            self.recovered = True
            for w in warn:
                warnings.warn(f"store {self.name}: {w}", RuntimeWarning, stacklevel=3)
        # fold the verified entries, in order, into memory state
        self._gen = 0
        referenced: set[str] = set()
        for entry in resolved:
            if entry["kind"] == "compact":
                self._gen = entry["hi"]
                self._base_lo = entry["lo"]
                if not entry["empty"]:
                    arrays, meta = entry["loaded"]
                    self._layers = _arrays_to_layers(arrays, self.fused, self.size)
                    self._lead_shape = tuple(meta.get("lead_shape", ()))
                    self._base = entry["seg"]
                    referenced.add(entry["seg"])
            elif entry["kind"] == "empty":
                self._gen = entry["gen"]
                self._live.append((entry["gen"], None))
            else:
                arrays, meta = entry["loaded"]
                layers = _arrays_to_layers(arrays, self.fused, self.size)
                self._merge_layers(layers, tuple(meta.get("lead_shape", ())))
                self._gen = entry["gen"]
                self._live.append((entry["gen"], entry["seg"]))
                referenced.add(entry["seg"])
        self._ingested = {fp for g, fp in gen_fp.items() if g <= self._gen}
        # finish interrupted swaps / drop orphans: any segment file the
        # resolved state does not reference is garbage (an uncommitted
        # compaction, a replaced segment whose delete was killed)
        for fn in sorted(os.listdir(self.path)):
            full = os.path.join(self.path, fn)
            if fn.endswith(".tmp"):
                with contextlib.suppress(OSError):
                    os.unlink(full)
            elif fn.startswith("seg-") and fn.endswith(".npz") and fn not in referenced:
                with contextlib.suppress(OSError):
                    os.unlink(full)

    # -- slab math ----------------------------------------------------------

    def _slab_fingerprint(self, codes: np.ndarray, array: np.ndarray) -> str:
        h = hashlib.blake2b(digest_size=16)
        h.update(np.ascontiguousarray(codes).tobytes())
        h.update(f"|{codes.dtype.str}|{codes.shape}|".encode())
        h.update(np.ascontiguousarray(array).tobytes())
        h.update(f"|{array.dtype.str}|{array.shape}".encode())
        return h.hexdigest()

    def _slab_layers(self, codes: np.ndarray, array: np.ndarray) -> list | None:
        valid = (codes >= 0) & (codes < self.size)
        if not valid.all():
            array = array[..., valid]
            codes = codes[valid]
        if codes.size == 0:
            return None
        present, cidx = np.unique(codes, return_inverse=True)
        # one extra column past the present set: no element maps there, so
        # the kernels fill it with the leg identity — exactly the pad
        # column PresentGroups.scatter_dense / .merge expect
        inters = fused_chunk_stats(
            self.fused, cidx.reshape(-1), array,
            size=len(present) + 1, engine=self.engine, eager=True,
        )
        layers: list = []
        for inter in inters:
            if isinstance(inter, MultiArray):
                layers.append(
                    tuple(
                        PresentGroups(present, np.asarray(leaf), self.size)
                        for leaf in inter.arrays
                    )
                )
            else:
                layers.append(PresentGroups(present, np.asarray(inter), self.size))
        return layers

    def _merge_layers(self, layers: list, lead_shape: tuple) -> None:
        if self._layers is None:
            self._layers = layers
            self._lead_shape = lead_shape
            return
        if lead_shape != self._lead_shape:
            raise ValueError(
                f"slab lead shape {lead_shape} != store lead shape "
                f"{self._lead_shape}"
            )
        merged: list = []
        for cur, new, op in zip(self._layers, layers, self.fused.combine):
            if op == "var":
                merged.append(merge_present_var(cur, new))
            else:
                merged.append(cur.merge(new, op))
        self._layers = merged

    # -- public API ---------------------------------------------------------

    def append(self, codes, array, *, slab_id: str | None = None) -> dict:
        """Ingest one slab exactly once. ``codes`` are dense group codes in
        ``[0, size)`` (out-of-range codes are dropped, the pipeline's
        missing-label convention); ``array`` is ``(..., len(codes))`` and is
        cast to the store's slab dtype. ``slab_id`` overrides the content
        fingerprint as the idempotency key. Returns the ack dict — ``ack``
        is ``"ingested"`` or ``"slab_already_ingested"`` (a no-op replay)."""
        from . import telemetry

        codes = np.asarray(codes).reshape(-1)
        array = np.asarray(array, dtype=self.array_dtype)
        if array.shape[-1] != codes.shape[0]:
            raise ValueError(
                f"array trailing axis {array.shape[-1]} != len(codes) "
                f"{codes.shape[0]}"
            )
        fp = str(slab_id) if slab_id is not None else self._slab_fingerprint(codes, array)
        with self._lock:
            if fp in self._ingested:
                telemetry.METRICS.inc("store.duplicates")
                return {
                    "store": self.name, "ack": "slab_already_ingested",
                    "gen": self._gen, "slab": fp,
                }
            layers = self._slab_layers(codes, array)
            gen = self._gen + 1
            seg = f"seg-{gen:08d}.npz" if layers is not None else None
            # WAL intent first: fingerprint + generation are durable before
            # any state lands — the exactly-once ledger
            _append_journal(
                os.path.join(self.path, _JOURNAL),
                {"rec": "append", "gen": gen, "slab": fp, "seg": seg,
                 "empty": layers is None},
                fsync=self._fsync,
            )
            if layers is not None:
                write_checksummed_npz(
                    self._seg_path(seg),
                    _layers_to_arrays(layers),
                    {"kind": "delta", "gen": gen, "slab": fp,
                     "lead_shape": list(array.shape[:-1])},
                    kind="segment", fsync=self._fsync,
                )
                # commit point reached: the verified segment IS the commit
                self._merge_layers(layers, array.shape[:-1])
            self._gen = gen
            self._ingested.add(fp)
            self._live.append((gen, seg))
            telemetry.METRICS.inc("store.appends")
            telemetry.METRICS.inc("store.append_bytes", int(array.nbytes))
            n_live = len([1 for _, s in self._live if s is not None])
            if self._compact_threshold and n_live > self._compact_threshold:
                self.compact()
            return {
                "store": self.name, "ack": "ingested", "gen": gen, "slab": fp,
                "n_present": 0 if self._layers is None else self._n_present(),
            }

    @property
    def gen(self) -> int:
        """The last durable generation (0 = empty store)."""
        return self._gen

    def _n_present(self) -> int:
        first = self._layers[0]
        pg = first[0] if isinstance(first, tuple) else first
        return pg.n_present

    def _dense_inters(self) -> list:
        layers = self._layers
        if layers is None:
            # empty store: a zero-element slab through the real kernels
            # gives every leg its fill/identity in the right dtype
            codes = np.zeros(0, dtype=np.intp)
            array = np.zeros(self._lead_shape + (0,), dtype=self.array_dtype)
            inters = fused_chunk_stats(
                self.fused, codes, array, size=1, engine=self.engine, eager=True,
            )
            empty = np.zeros(0, dtype=np.int64)
            layers = [
                tuple(
                    PresentGroups(empty, np.asarray(leaf), self.size)
                    for leaf in inter.arrays
                )
                if isinstance(inter, MultiArray)
                else PresentGroups(empty, np.asarray(inter), self.size)
                for inter in inters
            ]
        dense: list = []
        for layer in layers:
            if isinstance(layer, tuple):
                dense.append(MultiArray(tuple(pg.scatter_dense() for pg in layer)))
            else:
                dense.append(layer.scatter_dense())
        return dense

    def query(self, funcs=None) -> dict:
        """Finalized ``{func: dense (..., size) array}`` for the requested
        statistic subset (default: all), served from the persisted carry —
        history is never recomputed."""
        from . import telemetry
        from .fusion import finalize_many

        sel = tuple(funcs) if funcs is not None else self.funcs
        unknown = [f for f in sel if f not in self.funcs]
        if unknown:
            raise ValueError(
                f"store {self.name} does not carry {unknown!r} "
                f"(created with {list(self.funcs)})"
            )
        with self._lock:
            results = self.fused.finalize_fused(self._dense_inters())
            out = finalize_many(self.fused, results)
            telemetry.METRICS.inc("store.queries")
            return {f: out[f] for f in sel}

    def compact(self) -> dict:
        """Fold all live segments into one covering segment. Crash-safe: the
        merged segment lands and the journal's compact record fsyncs before
        any replaced segment is deleted — a kill at any point leaves either
        the old segments or the new one fully live."""
        from . import telemetry

        with self._lock:
            live_segs = [s for _, s in self._live if s is not None]
            if not live_segs and self._base is None:
                return {"store": self.name, "compacted": False, "gen": self._gen,
                        "segments": 0}
            if self._base is None and len(live_segs) < 2:
                return {"store": self.name, "compacted": False, "gen": self._gen,
                        "segments": len(live_segs)}
            lo, hi = self._base_lo, self._gen
            seg = f"seg-{lo:08d}-{hi:08d}.npz"
            empty = self._layers is None
            if not empty:
                write_checksummed_npz(
                    self._seg_path(seg),
                    _layers_to_arrays(self._layers),
                    {"kind": "compact", "lo": lo, "hi": hi,
                     "lead_shape": list(self._lead_shape)},
                    kind="segment", fsync=self._fsync,
                )
            replaced = ([self._base] if self._base else []) + live_segs
            # the journal flip is the commit: from here the compacted
            # segment is the store's base and the replaced ones are garbage
            _append_journal(
                os.path.join(self.path, _JOURNAL),
                {"rec": "compact", "lo": lo, "hi": hi, "seg": seg,
                 "empty": empty, "replaces": replaced},
                fsync=self._fsync,
            )
            self._base = None if empty else seg
            self._live = []
            for old in replaced:
                if old == seg:
                    continue
                path = self._seg_path(old)
                action = (
                    faults.store_poke("swap", path) if faults.store_active() else None
                )
                if action == "kill":
                    raise faults.StoreWriteKilled(f"before swap delete of {old}")
                with contextlib.suppress(FileNotFoundError):
                    os.unlink(path)
            telemetry.METRICS.inc("store.compactions")
            return {"store": self.name, "compacted": True, "gen": self._gen,
                    "segments": 0 if empty else 1, "replaced": len(replaced)}

    def info(self) -> dict:
        """A JSON-able snapshot (no device, no disk touch)."""
        with self._lock:
            return {
                "store": self.name, "path": self.path,
                "funcs": list(self.funcs), "size": self.size,
                "array_dtype": self.array_dtype.name, "engine": self.engine,
                "gen": self._gen, "slabs": len(self._ingested),
                "n_present": 0 if self._layers is None else self._n_present(),
                "segments": (1 if self._base else 0)
                + len([1 for _, s in self._live if s is not None]),
                "recovered": self.recovered,
                "nbytes": self._state_nbytes(),
            }

    def _state_nbytes(self) -> int:
        if self._layers is None:
            return 0
        total = 0
        for layer in self._layers:
            pgs = layer if isinstance(layer, tuple) else (layer,)
            for pg in pgs:
                total += int(np.asarray(pg.values).nbytes) + int(pg.present.nbytes)
        return total


_CTOR_TOKEN = object()


def open_store(path: str, *, create: dict | None = None) -> IncrementalAggregationStore:
    """Open the store at ``path``; when it does not exist and ``create``
    gives the plan (``{"funcs", "size", ...}`` — the :meth:`create`
    keywords), create it instead."""
    if os.path.exists(os.path.join(path, _JOURNAL)):
        return IncrementalAggregationStore.open(path)
    if create is None:
        raise FileNotFoundError(f"no store at {path}")
    return IncrementalAggregationStore.create(path, **create)
