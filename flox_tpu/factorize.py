"""Factorization: labels -> dense integer group codes (L3).

Parity target: /root/reference/flox/factorize.py (single-by paths at
factorize.py:42-99, multi-by raveling at 102-213, early factorization at
221-275). Architecture split, TPU-first:

* **Host factorize** (this module's ``factorize_``): data-dependent discovery
  of unknown labels (``pd.factorize``), pandas Index alignment, interval
  binning. Stays in numpy/pandas land exactly as the reference keeps it.
* **Device factorize** (``factorize_device`` / ``bin_device``): when
  ``expected_groups`` is known, codes are computed *on device* with
  ``jnp.searchsorted`` against sorted expected values / bin edges — static
  shapes, fully jittable, fusable into the reduction kernel. This is the
  path the reference cannot have (its kernels are host-side numpy).

NaN-label convention: missing/unmatched labels get code ``-1`` everywhere;
device kernels clamp ``-1`` to an extra trailing segment that is sliced off
(mirroring the nan-sentinel trick at factorize.py:201-210 without the
host-side size bump).
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np
import pandas as pd

from . import utils
from .types import FactorProps

__all__ = [
    "Prefactorized",
    "bin_device",
    "factorize_",
    "factorize_cached",
    "factorize_device",
    "factorize_single",
    "prefactorize",
]


def _view_if_datetime(values: np.ndarray) -> np.ndarray:
    if values.dtype.kind in "mM":
        return values.view("int64")
    return values


def factorize_single(
    flat: np.ndarray,
    expect: pd.Index | None,
    *,
    sort: bool = True,
) -> tuple[np.ndarray, pd.Index]:
    """Codes for one label array. Returns (codes int64 with -1 for missing, groups).

    Fast paths mirror the reference (factorize.py:42-99): RangeIndex identity
    with clamp, IntervalIndex binning via digitize, known-Index alignment via
    get_indexer, generic pd.factorize for unknown labels.
    """
    if expect is not None and not isinstance(expect, pd.Index):
        expect = pd.Index(expect)

    if expect is None:
        codes, groups = pd.factorize(flat.reshape(-1), sort=sort)
        return codes.astype(np.int64, copy=False), pd.Index(groups)

    # sort=True factorizes against the SORTED expected index — the groups
    # axis of the result is ordered, whatever order the user supplied
    # (parity: core.py:616-637 sort_values + test_core.py:1465-1508).
    # IntervalIndex binning requires monotonic edges anyway.
    if sort and not expect.is_monotonic_increasing:
        expect = expect.sort_values()

    flat = flat.reshape(-1)
    if isinstance(expect, pd.RangeIndex) and expect.start == 0 and expect.step == 1:
        # Labels are already integer codes. Copy (the reference found a
        # shared-memory race without it, factorize.py:44-52) and mark
        # out-of-range as missing.
        codes = flat.astype(np.int64)
        out = (codes < 0) | (codes >= expect.stop)
        if out.any():
            codes[out] = -1
        if utils.isnull(flat).any():  # e.g. float labels with NaN
            codes[utils.isnull(flat)] = -1
        return codes, expect

    if isinstance(expect, pd.IntervalIndex):
        left = _view_if_datetime(np.asarray(expect.left))
        right = _view_if_datetime(np.asarray(expect.right))
        edges = np.concatenate([left[:1], right])
        # Keep integer (incl. datetime64-viewed int64) values integral through
        # digitize — a float64 cast would round ns-resolution timestamps.
        vals = _view_if_datetime(np.asarray(flat))
        if expect.closed == "right":
            codes = np.digitize(vals, edges, right=True) - 1
            with np.errstate(invalid="ignore"):
                invalid = (vals <= edges[0]) | (vals > edges[-1])
        else:
            codes = np.digitize(vals, edges, right=False) - 1
            with np.errstate(invalid="ignore"):
                invalid = (vals < edges[0]) | (vals >= edges[-1])
        invalid |= np.asarray(utils.isnull(flat))
        codes = codes.astype(np.int64, copy=False)
        codes[invalid] = -1
        return codes, expect

    # Known labels: align against the provided index.
    codes = expect.get_indexer(flat).astype(np.int64, copy=False)
    return codes, expect


def ravel_multi_codes(codes: Sequence[np.ndarray], shape: tuple[int, ...]) -> np.ndarray:
    """Combine per-by codes into one flat code over the product grid.

    Any component code of -1 (missing) makes the combined code -1
    (parity: _ravel_factorized, factorize.py:102-108).
    """
    if len(codes) == 1:
        return codes[0]
    missing = np.zeros(codes[0].shape, dtype=bool)
    clipped = []
    for c in codes:
        missing |= c < 0
        clipped.append(np.where(c < 0, 0, c))
    flat = np.ravel_multi_index(clipped, shape, mode="wrap").astype(np.int64)
    flat[missing] = -1
    return flat


def offset_labels(codes: np.ndarray, ngroups: int) -> tuple[np.ndarray, int]:
    """Make group codes disjoint per leading position.

    Used when only a subset of the label-array's axes are reduced: the
    non-reduced label axes each get their own code range so one flat
    segment-reduce handles all of them (parity: factorize.py:24-39).

    ``codes`` has shape (M, N) where N covers the reduced axes; output is the
    same shape with row ``i`` offset by ``i * ngroups``, and the new total
    size ``M * ngroups``.
    """
    m = codes.shape[0]
    offset = np.arange(m, dtype=np.int64)[:, None] * ngroups
    out = np.where(codes < 0, -1, codes + offset)
    return out, m * ngroups


def factorize_(
    by: Sequence[np.ndarray],
    axes: tuple[int, ...],
    expected_groups: Sequence[pd.Index | None] | None = None,
    *,
    sort: bool = True,
) -> tuple[np.ndarray, tuple[pd.Index, ...], tuple[int, ...], int, int, FactorProps]:
    """Multi-``by`` factorization (parity: factorize.py:147-213).

    Returns ``(codes, found_groups, group_shape, ngroups, size, props)`` where
    ``codes`` has the shape of ``by[0]`` (or offset-expanded when ``axes`` is
    a strict subset of the by dims), ``ngroups`` is the dense product-grid
    size, and ``size`` is the segment count the kernels must allocate
    (``ngroups`` or ``M * ngroups`` after offsetting).
    """
    if expected_groups is None:
        expected_groups = [None] * len(by)

    codes_per_by: list[np.ndarray] = []
    found: list[pd.Index] = []
    for b, expect in zip(by, expected_groups):
        codes, groups = factorize_single(np.asarray(b), expect, sort=sort)
        codes_per_by.append(codes.reshape(np.asarray(b).shape))
        found.append(groups)

    group_shape = tuple(len(g) for g in found)
    ngroups = int(np.prod(group_shape)) if group_shape else 0
    codes = ravel_multi_codes([c.reshape(-1) for c in codes_per_by], group_shape).reshape(
        codes_per_by[0].shape
    )

    offset = len(axes) < codes.ndim
    if offset:
        # Flatten: leading (non-reduced) label dims become rows. Precondition
        # (enforced by core.py, which moves reduced axes last before calling,
        # mirroring reference core.py:957-1018): ``axes`` must be the trailing
        # contiguous block of the label array's dims.
        if tuple(axes) != tuple(range(codes.ndim - len(axes), codes.ndim)):
            raise ValueError(
                f"factorize_ requires the reduced axes to be trailing; got axes={axes} "
                f"for a {codes.ndim}-d label array"
            )
        nred = int(np.prod([codes.shape[ax] for ax in axes]))
        codes2d = codes.reshape(-1, nred)
        codes2d, size = offset_labels(codes2d, ngroups)
        codes = codes2d
    else:
        size = ngroups

    nanmask = codes < 0
    props = FactorProps(offset_group=offset, nan_sentinel=False, nanmask=nanmask if nanmask.any() else None)
    return codes, tuple(found), group_shape, ngroups, size, props


# ---------------------------------------------------------------------------
# Device-resident factorization (no reference analogue; TPU-first feature)
# ---------------------------------------------------------------------------


def factorize_device(by, expected_values):
    """Codes on device for *known, sorted, unique* expected values.

    ``jnp.searchsorted`` + equality check; unmatched -> -1. Jittable, so the
    whole labels->codes->reduce pipeline stays on device.
    """
    import jax.numpy as jnp

    expected_values = jnp.asarray(expected_values)
    by = jnp.asarray(by)
    idx = jnp.searchsorted(expected_values, by, side="left")
    idx_c = jnp.clip(idx, 0, expected_values.shape[0] - 1)
    valid = expected_values[idx_c] == by
    return jnp.where(valid, idx_c, -1).astype(jnp.int32)


def bin_device(by, edges, closed: str = "right"):
    """Interval binning on device (pd.cut semantics). Out-of-range/NaN -> -1."""
    import jax.numpy as jnp

    edges = jnp.asarray(edges)
    by = jnp.asarray(by)
    if closed == "right":
        codes = jnp.searchsorted(edges, by, side="left") - 1
        valid = (by > edges[0]) & (by <= edges[-1])
    else:
        codes = jnp.searchsorted(edges, by, side="right") - 1
        valid = (by >= edges[0]) & (by < edges[-1])
    return jnp.where(valid, codes, -1).astype(jnp.int32)


# ---------------------------------------------------------------------------
# prefactorized labels: the serving registry's factorize-once artifact
# ---------------------------------------------------------------------------


class Prefactorized:
    """A put-time factorization artifact: codes, group tables, and device
    stages, computed ONCE and reused across requests.

    This is the serving-era realization of flox's "factorize once, reduce
    many" (PAPER.md): the dataset registry builds one of these at
    ``put_dataset`` time via :func:`prefactorize` and every later request
    passes it AS the single ``by`` to ``groupby_reduce`` /
    ``groupby_aggregate_many``. The core paths detect it and skip the
    ``factorize`` telemetry span, the pandas factorize, *and* the codes
    H2D — the dense codes (``codes_dev``) and the sort engine's compact
    codes (``ccodes_dev``) were staged on device here, so they pass
    ``utils.asarray_device`` untouched and unbilled (``bytes.h2d`` == 0 on
    the hit path).

    Host mirrors (``codes`` / ``ccodes``) are kept for the numpy engine,
    mesh cohort detection, and device-loss restaging (:meth:`stage` is
    idempotent and re-runs after ``device.reinitialize()``).
    """

    __slots__ = (
        "codes", "codes_dev", "ccodes", "ccodes_dev", "present", "ncap",
        "found_groups", "group_shape", "ngroups", "size", "n",
        "by_shape", "by_dtype", "props", "fingerprint",
    )

    # -- numpy-duck attributes: the serve dispatcher treats `by` uniformly -
    @property
    def shape(self) -> tuple:
        return self.by_shape

    @property
    def dtype(self) -> np.dtype:
        return self.by_dtype

    @property
    def ndim(self) -> int:
        return len(self.by_shape)

    @property
    def nbytes(self) -> int:
        return int(self.codes.nbytes)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"Prefactorized(shape={self.by_shape}, ngroups={self.ngroups}, "
            f"size={self.size}, present={len(self.present)}, "
            f"staged={self.codes_dev is not None})"
        )

    def device_nbytes(self) -> int:
        """Bytes this artifact pins on device (the registry's HBM account)."""
        total = 0
        for a in (self.codes_dev, self.ccodes_dev):
            total += int(getattr(a, "nbytes", 0) or 0)
        return total

    def stage(self) -> "Prefactorized":
        """(Re-)stage the dense and compact codes on device. Idempotent by
        value: runs at put time, and again from the device-loss recovery
        hook — the host mirrors are the spill copies."""
        self.codes_dev = utils.asarray_device(self.codes)
        self.ccodes_dev = utils.asarray_device(self.ccodes)
        return self

    def _derive(self, codes: np.ndarray, codes_dev, by_shape: tuple) -> "Prefactorized":
        """A selector view sharing this artifact's group tables: new codes,
        same groups/size, sort tables recomputed for the selection (the
        ``present_groups`` memo makes repeats content-keyed hits)."""
        from .kernels import compact_codes, present_cap, present_groups

        out = Prefactorized()
        out.codes = codes
        out.found_groups = self.found_groups
        out.group_shape = self.group_shape
        out.ngroups = self.ngroups
        out.size = self.size
        out.n = int(codes.size)
        out.by_shape = tuple(by_shape)
        out.by_dtype = self.by_dtype
        out.props = self.props
        out.fingerprint = None
        out.present = present_groups(codes, self.size)
        out.ncap = present_cap(len(out.present), self.size)
        out.ccodes = compact_codes(codes, out.present)
        out.codes_dev = codes_dev
        # the view's compact codes are new host values: one small H2D at
        # view-build time (views are memoized per selector by the registry)
        out.ccodes_dev = utils.asarray_device(out.ccodes) if codes_dev is not None else None
        return out

    def slice_rows(self, start: int, stop: int) -> "Prefactorized":
        """Row-range view over the flat span: host codes sliced, device
        codes sliced ON device (zero H2D for the dense engine)."""
        start, stop = int(start), int(stop)
        if not (0 <= start < stop <= self.n):
            raise ValueError(
                f"row range [{start}, {stop}) out of bounds for span {self.n}"
            )
        sub = np.ascontiguousarray(self.codes[start:stop])
        dev = self.codes_dev[start:stop] if self.codes_dev is not None else None
        return self._derive(sub, dev, (int(sub.size),))

    def select_mask(self, mask) -> "Prefactorized":
        """Boolean-mask view over the flat span (device gather of the
        staged codes; only the small index vector transfers)."""
        mask = np.asarray(mask, dtype=bool).reshape(-1)
        if int(mask.size) != self.n:
            raise ValueError(f"mask length {mask.size} != dataset span {self.n}")
        idx = np.flatnonzero(mask)
        if idx.size == 0:
            raise ValueError("mask selects no rows")
        sub = np.ascontiguousarray(self.codes[idx])
        dev = None
        if self.codes_dev is not None:
            import jax.numpy as jnp

            dev = jnp.take(self.codes_dev, jnp.asarray(idx), axis=0)
        return self._derive(sub, dev, (int(sub.size),))


def prefactorize(
    by,
    expected_groups=None,
    *,
    sort: bool = True,
    stage: bool = True,
    fingerprint: str | None = None,
) -> Prefactorized:
    """Factorize ``by`` once, eagerly, with the sort engine's present
    tables and (by default) device-staged codes — the registry put path.

    Reduces over ALL of ``by``'s axes (the serving contract: a dataset's
    labels are fully reduced; kept axes belong to ``array``'s lead dims).
    """
    b = utils.asarray_host(np.asarray(by))
    if b.size == 0:
        raise ValueError("cannot prefactorize empty labels")
    expected_idx = None
    if expected_groups is not None:
        from .core import _convert_expected_groups_to_index, _normalize_expected

        expected_idx = _convert_expected_groups_to_index(
            _normalize_expected(expected_groups, 1), (False,), sort
        )
    codes, found_groups, grp_shape, ngroups, size, props = factorize_cached(
        (b,), axes=tuple(range(b.ndim)), expected_groups=expected_idx, sort=sort
    )
    if ngroups == 0 or size == 0:
        raise ValueError("No groups to reduce over (empty expected_groups?)")
    from .kernels import compact_codes, present_cap, present_groups

    codes_flat = np.ascontiguousarray(np.asarray(codes).reshape(-1), dtype=np.int64)
    pf = Prefactorized()
    pf.codes = codes_flat
    pf.found_groups = tuple(found_groups)
    pf.group_shape = tuple(grp_shape)
    pf.ngroups = int(ngroups)
    pf.size = int(size)
    pf.n = int(codes_flat.size)
    pf.by_shape = tuple(b.shape)
    pf.by_dtype = np.dtype(b.dtype)
    pf.props = props
    pf.fingerprint = fingerprint
    pf.present = present_groups(codes_flat, pf.size)
    pf.ncap = present_cap(len(pf.present), pf.size)
    pf.ccodes = compact_codes(codes_flat, pf.present)
    pf.codes_dev = None
    pf.ccodes_dev = None
    if stage:
        pf.stage()
    return pf


# ---------------------------------------------------------------------------
# memoized factorization: repeated reductions over the same labels (e.g. a
# per-step climatology) skip the pandas factorize entirely (the reference
# gets the same effect from dask token-level caching of the graph)
# ---------------------------------------------------------------------------

_FACTORIZE_CACHE: "dict" = {}  # insertion-ordered: oldest first
_FACTORIZE_CACHE_BYTES = [0]
_FACTORIZE_MAX_INPUT_BYTES = 1 << 26  # don't fingerprint labels over 64 MB
_FACTORIZE_BUDGET_BYTES = 1 << 28  # cached codes arrays: 256 MB total


def _fingerprint_array(a: np.ndarray) -> tuple:
    import hashlib

    if not a.flags["C_CONTIGUOUS"] and a.nbytes > (1 << 24):
        # hashing would first materialize a large copy; not worth it
        raise TypeError("skip cache: large non-contiguous labels")
    return (a.shape, a.dtype.str, hashlib.sha1(np.ascontiguousarray(a)).hexdigest())


def _fingerprint_index(idx) -> tuple | None:
    if idx is None:
        return None
    if isinstance(idx, pd.IntervalIndex):
        return ("interval", idx.closed, _fingerprint_array(np.asarray(idx.left)),
                _fingerprint_array(np.asarray(idx.right)))
    return ("index", _fingerprint_array(np.asarray(idx.values)))


def factorize_cached(by, axes, expected_groups=None, *, sort: bool = True):
    """Memoizing wrapper over :func:`factorize_` (same signature/returns).

    Byte-budgeted LRU: entries are evicted oldest-first once the cached
    codes arrays exceed the budget, so a cycling workload cannot pin
    unbounded memory and hot entries survive eviction of cold ones.
    """
    total = sum(np.asarray(b).nbytes for b in by)
    if total > _FACTORIZE_MAX_INPUT_BYTES:
        return factorize_(by, axes, expected_groups, sort=sort)
    try:
        key = (
            tuple(_fingerprint_array(np.asarray(b)) for b in by),
            tuple(axes),
            None if expected_groups is None else tuple(_fingerprint_index(e) for e in expected_groups),
            sort,
        )
    except TypeError:  # exotic/large-noncontiguous labels: just compute
        return factorize_(by, axes, expected_groups, sort=sort)
    hit = _FACTORIZE_CACHE.get(key)
    if hit is not None:
        # refresh LRU position
        _FACTORIZE_CACHE[key] = _FACTORIZE_CACHE.pop(key)
        return hit
    out = factorize_(by, axes, expected_groups, sort=sort)
    _FACTORIZE_CACHE[key] = out
    _FACTORIZE_CACHE_BYTES[0] += int(np.asarray(out[0]).nbytes)
    # evict oldest-first until the cached codes fit the byte budget (dicts
    # preserve insertion order; hits re-insert, so hot entries survive)
    while _FACTORIZE_CACHE_BYTES[0] > _FACTORIZE_BUDGET_BYTES and len(_FACTORIZE_CACHE) > 1:
        oldest = next(iter(_FACTORIZE_CACHE))
        evicted = _FACTORIZE_CACHE.pop(oldest)
        _FACTORIZE_CACHE_BYTES[0] -= int(np.asarray(evicted[0]).nbytes)
    return out
