"""Aggregation blueprints, registry, and engine dispatch (L2).

Parity target: /root/reference/flox/aggregations.py — the ``Aggregation``
declarative blueprint (aggregations.py:161-301), the ~30-entry registry
(881-922), ``generic_aggregate`` engine dispatch (60-133), the single-pass
variance machinery (348-526), scans (716-922) and
``_initialize_aggregation`` (925-1030).

TPU-first deltas:

* Combines are expressed as *collective-friendly* elementwise merge ops over
  dense, shape-static intermediates ("sum" → ``lax.psum``, "max" → ``pmax``,
  the variance triple → a two-phase psum Chan merge) rather than
  concatenate-then-regroup.
* ``reindex=True`` semantics are baked in: every intermediate is dense over
  ``expected_groups``, which is what XLA fusion and mesh collectives need.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Callable, Literal

import numpy as np

from . import dtypes, utils
from .multiarray import MultiArray

__all__ = [
    "Aggregation",
    "Scan",
    "AGGREGATIONS",
    "SCANS",
    "generic_aggregate",
    "_initialize_aggregation",
    "_initialize_scan",
    "is_supported_aggregation",
]


def normalize_engine(engine: str) -> str:
    """Map engine names (including the reference's) to ours.

    The reference's ``engine="flox"`` is its native vectorised engine
    (reference aggregate_flox.py); ours is the jax/XLA engine, so the name
    aliases to ``"jax"``. ``"numbagg"`` (reference aggregate_numbagg.py)
    has no analogue by design — every device path here is already
    JIT-compiled by XLA — so it raises with that explanation rather than
    "unknown".
    """
    if engine == "flox":
        return "jax"
    if engine == "numbagg":
        raise ValueError(
            "engine='numbagg' has no analogue in flox_tpu: numbagg exists to "
            "give the reference a JIT-compiled kernel path, and every device "
            "path here is already JIT-compiled by XLA. Use engine='jax' (the "
            "default; alias 'flox') or engine='numpy' (independent host "
            "engine). See docs/api.md, 'Engines'."
        )
    if engine not in ("jax", "numpy"):
        raise ValueError(f"Unknown engine {engine!r}; expected 'jax' or 'numpy'.")
    return engine


def generic_aggregate(
    group_idx,
    array,
    *,
    engine: str,
    func: str | Callable,
    axis: int = -1,
    size: int,
    fill_value=None,
    dtype=None,
    **kwargs,
):
    """Engine dispatcher (parity: aggregations.py:60-133)."""
    engine = normalize_engine(engine)
    if callable(func):
        return func(
            group_idx, array, axis=axis, size=size, fill_value=fill_value, dtype=dtype, **kwargs
        )
    if engine == "jax":
        from . import kernels

        return kernels.generic_kernel(
            func, group_idx, array, axis=axis, size=size, fill_value=fill_value, dtype=dtype, **kwargs
        )
    if engine == "numpy":
        from . import engine_numpy

        return engine_numpy.generic_kernel(
            func, group_idx, array, axis=axis, size=size, fill_value=fill_value, dtype=dtype, **kwargs
        )
    raise ValueError(f"Unknown engine {engine!r}; expected 'jax' or 'numpy'.")


# ---------------------------------------------------------------------------
# Aggregation blueprint
# ---------------------------------------------------------------------------

# Combine ops understood by the tree/collective combiner. "sum"/"max"/"min"/
# "prod" merge dense intermediates elementwise; "var" is the Chan-style
# triple merge; "arg" merges (value, global-index) pairs; "first"/"last"
# merge (value, global-position) picking the extreme position.
T_Combine = Literal["sum", "max", "min", "prod", "var", "argmax", "argmin", "first", "last", "concat"]


@dataclass
class Aggregation:
    """Declarative recipe for one grouped reduction.

    Stages (parity with aggregations.py:161-301):

    * ``numpy``:   kernels for the single-device eager path (fused, direct).
    * ``chunk``:   kernels run per shard/block producing dense intermediates.
    * ``combine``: merge ops applied across shards/blocks (collectives).
    * ``finalize``: maps combined intermediates -> final result.

    ``numpy``/``chunk`` entries may be user callables with the engine plugin
    signature ``f(group_idx, array, *, axis, size, fill_value, dtype, **kw)``.
    ``combine`` entries may be user callables too: on the mesh the shards'
    dense intermediates are all-gathered and the callable folds the stack,
    ``op(stacked)`` with ``stacked`` shaped ``(n_shards, ..., size)`` ->
    ``(..., size)`` (the collective analogue of the reference's
    ``_grouped_combine``, dask.py:233-317).
    """

    name: str
    numpy: tuple[str | Callable, ...] = ()
    chunk: tuple[str | Callable, ...] | None = None
    combine: tuple[T_Combine, ...] | None = None
    finalize: Callable | None = None
    preprocess: Callable | None = None
    fill_value: dict[str, Any] = field(default_factory=dict)  # {"intermediate": (...), "numpy": (...)}
    final_fill_value: Any = dtypes.NA
    dtypes_: dict[str, Any] = field(default_factory=dict)
    final_dtype: Any = None
    reduction_type: Literal["reduce", "argreduce"] = "reduce"
    preserves_dtype: bool = False
    new_dims_func: Callable | None = None  # finalize_kwargs -> tuple of new dim sizes
    # resolved by _initialize_aggregation:
    finalize_kwargs: dict[str, Any] = field(default_factory=dict)
    min_count: int = 0
    appended_count: bool = False  # a trailing nanlen was added for min_count

    def __post_init__(self):
        if not self.numpy:
            self.numpy = (self.name,)
        if self.chunk is None and self.combine is None:
            # blockwise-only aggregation (median/quantile/mode/first/last on
            # float): must see all data for a group at once
            pass

    @property
    def blockwise_only(self) -> bool:
        return self.chunk is None

    def new_dims(self) -> tuple[int, ...]:
        if self.new_dims_func is None:
            return ()
        return self.new_dims_func(**self.finalize_kwargs)


# --- finalize helpers -------------------------------------------------------


def _is_jaxish(x) -> bool:
    import jax

    return isinstance(x, (jax.Array, jax.core.Tracer))


def _mean_finalize(total, count, **kw):
    import numpy as _np

    if _is_jaxish(total):
        return total / count
    with _np.errstate(invalid="ignore", divide="ignore"):
        return total / count


def _var_finalize(ma: MultiArray, ddof=0, **kw):
    m2, total, count = ma.arrays
    denom = count - ddof
    if _is_jaxish(m2):
        import jax.numpy as jnp

        out = m2 / jnp.where(denom > 0, denom, 1)
        return jnp.where(denom > 0, out, jnp.asarray(jnp.nan, out.dtype))
    import numpy as _np

    with _np.errstate(invalid="ignore", divide="ignore"):
        out = m2 / _np.where(denom > 0, denom, 1)
    return _np.where(denom > 0, out, _np.nan)


def _std_finalize(ma: MultiArray, ddof=0, **kw):
    out = _var_finalize(ma, ddof=ddof)
    if _is_jaxish(out):
        import jax.numpy as jnp

        return jnp.sqrt(out)
    return np.sqrt(out)


def _pick_second(a, b, **kw):
    return b


def _quantile_new_dims(q=0.5, **kw):
    return () if np.ndim(q) == 0 else (len(q),)


# --- registry ---------------------------------------------------------------


def _agg(name, **kw) -> Aggregation:
    return Aggregation(name, **kw)


AGGREGATIONS: dict[str, Aggregation] = {}


def _register(agg: Aggregation) -> None:
    AGGREGATIONS[agg.name] = agg


# counts
_register(_agg("count", numpy=("nanlen",), chunk=("nanlen",), combine=("sum",),
               fill_value={"intermediate": (0,), "numpy": (0,)}, final_fill_value=0,
               final_dtype=np.intp))

# sums / products
for nm, skipna in [("sum", False), ("nansum", True)]:
    _register(_agg(nm, chunk=(nm,), combine=("sum",),
                   fill_value={"intermediate": (0,), "numpy": (0,)}, final_fill_value=0))
for nm in ["prod", "nanprod"]:
    _register(_agg(nm, chunk=(nm,), combine=("prod",),
                   fill_value={"intermediate": (1,), "numpy": (1,)}, final_fill_value=1))

# mean family: chunk = (sum, count), combine = (sum, sum), finalize = divide
for nm, sum_k, len_k in [("mean", "sum", "len"), ("nanmean", "nansum", "nanlen")]:
    _register(_agg(nm, numpy=(nm,), chunk=(sum_k, len_k), combine=("sum", "sum"),
                   finalize=_mean_finalize,
                   fill_value={"intermediate": (0, 0), "numpy": (np.nan,)},
                   final_fill_value=dtypes.NA, final_dtype=None))

# var/std family: chunk = var_chunk triple, combine = Chan merge
for nm, skipna, fin in [("var", False, _var_finalize), ("nanvar", True, _var_finalize),
                        ("std", False, _std_finalize), ("nanstd", True, _std_finalize)]:
    _register(_agg(nm, numpy=(nm,),
                   chunk=(("var_chunk", {"skipna": skipna}),), combine=("var",), finalize=fin,
                   fill_value={"intermediate": (0,), "numpy": (np.nan,)},
                   final_fill_value=dtypes.NA))

# min/max
for nm, comb, sentinel in [("max", "max", dtypes.NINF), ("nanmax", "max", dtypes.NINF),
                           ("min", "min", dtypes.INF), ("nanmin", "min", dtypes.INF)]:
    _register(_agg(nm, chunk=(nm,), combine=(comb,),
                   fill_value={"intermediate": (sentinel,), "numpy": (dtypes.NA,)},
                   final_fill_value=dtypes.NA, preserves_dtype=True))

# bool reductions
_register(_agg("all", chunk=("all",), combine=("min",),
               fill_value={"intermediate": (True,), "numpy": (True,)}, final_fill_value=True,
               final_dtype=np.bool_))
_register(_agg("any", chunk=("any",), combine=("max",),
               fill_value={"intermediate": (False,), "numpy": (False,)}, final_fill_value=False,
               final_dtype=np.bool_))

# argreductions: eager path = direct kernel; chunked path pairs the extreme
# value with its global index (parity: aggregations.py:549-632)
for nm in ["argmax", "argmin", "nanargmax", "nanargmin"]:
    base = nm.removeprefix("nan")
    val_k = nm.replace("arg", "")  # max / nanmax / ...
    _register(_agg(nm, numpy=(nm,), chunk=(val_k, nm), combine=(base,),
                   finalize=_pick_second, reduction_type="argreduce",
                   fill_value={"intermediate": (dtypes.NINF if "max" in nm else dtypes.INF, -1),
                               "numpy": (-1,)},
                   final_fill_value=-1, final_dtype=np.intp))

# first/last: order-dependent; combine by tracking the global position
for nm, comb in [("first", "first"), ("last", "last"),
                 ("nanfirst", "first"), ("nanlast", "last")]:
    _register(_agg(nm, chunk=(nm,), combine=(comb,),
                   fill_value={"intermediate": (dtypes.NA,), "numpy": (dtypes.NA,)},
                   final_fill_value=dtypes.NA, preserves_dtype=True))

# order statistics: blockwise-only (chunk=None), like the reference
# (aggregations.py:672-712) — they need every element of a group at once.
for nm in ["median", "nanmedian"]:
    _register(_agg(nm, chunk=None, combine=None,
                   fill_value={"numpy": (dtypes.NA,)}, final_fill_value=dtypes.NA))
for nm in ["quantile", "nanquantile"]:
    _register(_agg(nm, chunk=None, combine=None,
                   fill_value={"numpy": (dtypes.NA,)}, final_fill_value=dtypes.NA,
                   new_dims_func=_quantile_new_dims))
for nm in ["mode", "nanmode"]:
    _register(_agg(nm, chunk=None, combine=None,
                   fill_value={"numpy": (dtypes.NA,)}, final_fill_value=dtypes.NA,
                   preserves_dtype=True))


def is_supported_aggregation(func: str) -> bool:
    """Public capability probe (parity: aggregations.py:1033-1054)."""
    return func in AGGREGATIONS


# ---------------------------------------------------------------------------
# initialization: resolve dtypes and fill values against the input array
# ---------------------------------------------------------------------------


def set_nat_final_fill(agg: "Aggregation", fill_value) -> None:
    """Dtype-preserving datetime reductions: the missing marker is NaT
    (INT64_MIN on the int64 view), never float NaN — float would corrupt
    ns-resolution timestamps; an explicit datetime/NaT fill is viewed to
    its int64 representation. ONE implementation shared by the eager core
    and the streaming runtime so the NaT discipline cannot drift."""
    if fill_value is None:
        agg.final_fill_value = np.iinfo(np.int64).min
    elif isinstance(agg.final_fill_value, (np.datetime64, np.timedelta64)):
        agg.final_fill_value = int(agg.final_fill_value.astype("int64"))
    agg.final_dtype = np.dtype("int64")


def shift_nat_identity_fills(agg: "Aggregation") -> None:
    """The NINF-resolved empty fill (iinfo.min) is byte-identical to the
    NaT marker; shift it so groups absent from a shard/slab are not
    mistaken for NaT-containing ones by marker re-injection. Shared by the
    mesh programs and the streaming runtime."""
    nat = np.iinfo(np.int64).min
    agg.fill_value["intermediate"] = tuple(
        (fv + 1 if isinstance(fv, (int, np.integer)) and fv == nat else fv)
        for fv in agg.fill_value.get("intermediate", ())
    )


def _initialize_aggregation(
    func: str | Aggregation,
    dtype,
    array_dtype,
    fill_value,
    min_count: int,
    finalize_kwargs: dict[str, Any] | None,
) -> Aggregation:
    """Resolve a registry template into a concrete plan
    (parity: aggregations.py:925-1030)."""
    if isinstance(func, Aggregation):
        agg = copy.deepcopy(func)
    else:
        try:
            agg = copy.deepcopy(AGGREGATIONS[func])
        except KeyError:
            raise ValueError(f"Unsupported aggregation: {func!r}") from None

    array_dtype = np.dtype(array_dtype)
    agg.finalize_kwargs = dict(finalize_kwargs or {})
    agg.min_count = min_count

    # final dtype
    if agg.final_dtype is not None and dtype is None:
        final = np.dtype(agg.final_dtype)
    else:
        final = dtypes.normalize_dtype(
            dtype, array_dtype, preserves_dtype=agg.preserves_dtype, fill_value=fill_value
        )
        if not agg.preserves_dtype and agg.name not in ("sum", "nansum", "prod", "nanprod"):
            # mean/var/etc. of int data is float
            if agg.name not in ("count", "all", "any") and final.kind in "iub":
                final = np.result_type(final, np.float64 if utils.x64_enabled() else np.float32)
    agg.final_dtype = final

    # resolve final fill value; with min_count the default must be a missing
    # marker (NaN), not the reduction identity — that's the whole point of
    # min_count (parity: core.py:1026-1038 + aggregations.py:1005-1014)
    if fill_value is None:
        fill_value = dtypes.NA if min_count > 0 else agg.final_fill_value
    if fill_value in (dtypes.NA, dtypes.INF, dtypes.NINF):
        promoted, na = dtypes.maybe_promote(final)
        if fill_value is dtypes.NA:
            # only promote if some group can actually be missing; the caller
            # decides — record the NA-resolved value for use at finalize time
            fill_value = dtypes.get_fill_value(promoted, dtypes.NA)
        else:
            fill_value = dtypes.get_fill_value(final, fill_value)
    agg.final_fill_value = fill_value

    # resolve intermediate fills against the working dtype; argreductions'
    # first intermediate is the extreme VALUE (array dtype), not the index
    work_dtype = (
        array_dtype if (agg.preserves_dtype or agg.reduction_type == "argreduce") else final
    )
    inter = agg.fill_value.get("intermediate", ())
    agg.fill_value["intermediate"] = tuple(
        dtypes.get_fill_value(work_dtype, fv) if fv in (dtypes.NA, dtypes.INF, dtypes.NINF) else fv
        for fv in inter
    )

    # min_count: append a count intermediate so finalize can mask
    # (parity: aggregations.py:1005-1014)
    if min_count > 0 and agg.chunk is not None and "nanlen" not in _chunk_names(agg):
        agg.chunk = tuple(agg.chunk) + ("nanlen",)
        agg.combine = tuple(agg.combine) + ("sum",)
        agg.fill_value["intermediate"] = tuple(agg.fill_value["intermediate"]) + (0,)
        agg.appended_count = True

    return agg


def _chunk_names(agg: Aggregation) -> tuple[str, ...]:
    out = []
    for c in agg.chunk or ():
        if isinstance(c, tuple):
            out.append(c[0])
        elif isinstance(c, str):
            out.append(c)
    return tuple(out)


# ---------------------------------------------------------------------------
# scans (parity: aggregations.py:716-922)
# ---------------------------------------------------------------------------


@dataclass
class Scan:
    """Blueprint for a grouped scan.

    * ``scan``: the within-block grouped scan kernel.
    * ``reduction``: per-block per-group summary carried across blocks
      (cumsum -> "sum" of the block; ffill -> "nanlast" value).
    * ``binary_op``: how an incoming carry combines with block values.
    * ``identity``: carry for groups not yet seen.
    """

    name: str
    scan: str
    reduction: str
    binary_op: Callable | None
    identity: Any
    # "apply_binary_op": add carry to scanned block; "ffill": where-NaN fill
    mode: Literal["apply_binary_op", "ffill"] = "apply_binary_op"
    preserves_dtype: bool = False


SCANS: dict[str, Scan] = {
    "cumsum": Scan("cumsum", scan="cumsum", reduction="sum", binary_op=None, identity=0),
    "nancumsum": Scan("nancumsum", scan="nancumsum", reduction="nansum", binary_op=None, identity=0),
    "ffill": Scan("ffill", scan="ffill", reduction="nanlast", binary_op=None, identity=np.nan,
                  mode="ffill", preserves_dtype=True),
    "bfill": Scan("bfill", scan="bfill", reduction="nanfirst", binary_op=None, identity=np.nan,
                  mode="ffill", preserves_dtype=True),
}


def _initialize_scan(func: str | Scan) -> Scan:
    if isinstance(func, Scan):
        return copy.deepcopy(func)
    try:
        return copy.deepcopy(SCANS[func])
    except KeyError:
        raise ValueError(f"Unsupported scan: {func!r}") from None
