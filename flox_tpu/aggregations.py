"""Aggregation blueprints, registry, and engine dispatch (L2).

Parity target: /root/reference/flox/aggregations.py — the ``Aggregation``
declarative blueprint (aggregations.py:161-301), the ~30-entry registry
(881-922), ``generic_aggregate`` engine dispatch (60-133), the single-pass
variance machinery (348-526), scans (716-922) and
``_initialize_aggregation`` (925-1030).

TPU-first deltas:

* Combines are expressed as *collective-friendly* elementwise merge ops over
  dense, shape-static intermediates ("sum" → ``lax.psum``, "max" → ``pmax``,
  the variance triple → a two-phase psum Chan merge) rather than
  concatenate-then-regroup.
* ``reindex=True`` semantics are baked in: every intermediate is dense over
  ``expected_groups``, which is what XLA fusion and mesh collectives need.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Callable, Literal

import numpy as np

from . import dtypes, utils
from .multiarray import MultiArray

__all__ = [
    "Aggregation",
    "FusedAggregation",
    "Scan",
    "AGGREGATIONS",
    "SCANS",
    "FUSABLE_FUNCS",
    "generic_aggregate",
    "plan_fused",
    "fused_chunk_stats",
    "_initialize_aggregation",
    "_initialize_scan",
    "is_supported_aggregation",
]


def normalize_engine(engine: str) -> str:
    """Map engine names (including the reference's) to ours.

    The reference's ``engine="flox"`` is its native vectorised engine
    (reference aggregate_flox.py); ours is the jax/XLA engine, so the name
    aliases to ``"jax"``. ``"sort"`` is the present-groups engine
    (kernels.py sort section): the jax kernels run over the compact domain
    of groups actually present, the high-cardinality analogue of the
    reference's sort+``ufunc.reduceat`` engine. ``"numbagg"`` (reference
    aggregate_numbagg.py) has no analogue by design — every device path
    here is already JIT-compiled by XLA — so it raises with that
    explanation rather than "unknown".
    """
    if engine == "flox":
        return "jax"
    if engine == "numbagg":
        raise ValueError(
            "engine='numbagg' has no analogue in flox_tpu: numbagg exists to "
            "give the reference a JIT-compiled kernel path, and every device "
            "path here is already JIT-compiled by XLA. Use engine='jax' (the "
            "default; alias 'flox') or engine='numpy' (independent host "
            "engine). See docs/api.md, 'Engines'."
        )
    if engine not in ("jax", "numpy", "sort"):
        raise ValueError(
            f"Unknown engine {engine!r}; expected 'jax', 'numpy' or 'sort'."
        )
    return engine


def generic_aggregate(
    group_idx,
    array,
    *,
    engine: str,
    func: str | Callable,
    axis: int = -1,
    size: int,
    fill_value=None,
    dtype=None,
    **kwargs,
):
    """Engine dispatcher (parity: aggregations.py:60-133)."""
    engine = normalize_engine(engine)
    if callable(func):
        return func(
            group_idx, array, axis=axis, size=size, fill_value=fill_value, dtype=dtype, **kwargs
        )
    if engine == "jax":
        from . import kernels

        return kernels.generic_kernel(
            func, group_idx, array, axis=axis, size=size, fill_value=fill_value, dtype=dtype, **kwargs
        )
    if engine == "numpy":
        from . import engine_numpy

        return engine_numpy.generic_kernel(
            func, group_idx, array, axis=axis, size=size, fill_value=fill_value, dtype=dtype, **kwargs
        )
    if engine == "sort":
        from . import kernels

        return kernels.sort_kernel(
            func, group_idx, array, axis=axis, size=size, fill_value=fill_value, dtype=dtype, **kwargs
        )
    raise ValueError(f"Unknown engine {engine!r}; expected 'jax', 'numpy' or 'sort'.")


# ---------------------------------------------------------------------------
# Aggregation blueprint
# ---------------------------------------------------------------------------

# Combine ops understood by the tree/collective combiner. "sum"/"max"/"min"/
# "prod" merge dense intermediates elementwise; "var" is the Chan-style
# triple merge; "arg" merges (value, global-index) pairs; "first"/"last"
# merge (value, global-position) picking the extreme position.
T_Combine = Literal["sum", "max", "min", "prod", "var", "argmax", "argmin", "first", "last", "concat"]


@dataclass
class Aggregation:
    """Declarative recipe for one grouped reduction.

    Stages (parity with aggregations.py:161-301):

    * ``numpy``:   kernels for the single-device eager path (fused, direct).
    * ``chunk``:   kernels run per shard/block producing dense intermediates.
    * ``combine``: merge ops applied across shards/blocks (collectives).
    * ``finalize``: maps combined intermediates -> final result.

    ``numpy``/``chunk`` entries may be user callables with the engine plugin
    signature ``f(group_idx, array, *, axis, size, fill_value, dtype, **kw)``.
    ``combine`` entries may be user callables too: on the mesh the shards'
    dense intermediates are all-gathered and the callable folds the stack,
    ``op(stacked)`` with ``stacked`` shaped ``(n_shards, ..., size)`` ->
    ``(..., size)`` (the collective analogue of the reference's
    ``_grouped_combine``, dask.py:233-317).
    """

    name: str
    numpy: tuple[str | Callable, ...] = ()
    chunk: tuple[str | Callable, ...] | None = None
    combine: tuple[T_Combine, ...] | None = None
    finalize: Callable | None = None
    preprocess: Callable | None = None
    fill_value: dict[str, Any] = field(default_factory=dict)  # {"intermediate": (...), "numpy": (...)}
    final_fill_value: Any = dtypes.NA
    dtypes_: dict[str, Any] = field(default_factory=dict)
    final_dtype: Any = None
    reduction_type: Literal["reduce", "argreduce"] = "reduce"
    preserves_dtype: bool = False
    new_dims_func: Callable | None = None  # finalize_kwargs -> tuple of new dim sizes
    # resolved by _initialize_aggregation:
    finalize_kwargs: dict[str, Any] = field(default_factory=dict)
    min_count: int = 0
    appended_count: bool = False  # a trailing nanlen was added for min_count

    def __post_init__(self):
        if not self.numpy:
            self.numpy = (self.name,)
        if self.chunk is None and self.combine is None:
            # blockwise-only aggregation (median/quantile/mode/first/last on
            # float): must see all data for a group at once
            pass

    @property
    def blockwise_only(self) -> bool:
        return self.chunk is None

    def new_dims(self) -> tuple[int, ...]:
        if self.new_dims_func is None:
            return ()
        return self.new_dims_func(**self.finalize_kwargs)


# --- finalize helpers -------------------------------------------------------


def _is_jaxish(x) -> bool:
    import jax

    return isinstance(x, (jax.Array, jax.core.Tracer))


def _mean_finalize(total, count, **kw):
    import numpy as _np

    if _is_jaxish(total):
        return total / count
    with _np.errstate(invalid="ignore", divide="ignore"):
        return total / count


def _var_finalize(ma: MultiArray, ddof=0, **kw):
    m2, total, count = ma.arrays
    denom = count - ddof
    if _is_jaxish(m2):
        import jax.numpy as jnp

        out = m2 / jnp.where(denom > 0, denom, 1)
        return jnp.where(denom > 0, out, jnp.asarray(jnp.nan, out.dtype))
    import numpy as _np

    with _np.errstate(invalid="ignore", divide="ignore"):
        out = m2 / _np.where(denom > 0, denom, 1)
    return _np.where(denom > 0, out, _np.nan)


def _std_finalize(ma: MultiArray, ddof=0, **kw):
    out = _var_finalize(ma, ddof=ddof)
    if _is_jaxish(out):
        import jax.numpy as jnp

        return jnp.sqrt(out)
    return np.sqrt(out)


def _pick_second(a, b, **kw):
    return b


def _quantile_new_dims(q=0.5, **kw):
    return () if np.ndim(q) == 0 else (len(q),)


# --- registry ---------------------------------------------------------------


def _agg(name, **kw) -> Aggregation:
    return Aggregation(name, **kw)


AGGREGATIONS: dict[str, Aggregation] = {}


def _register(agg: Aggregation) -> None:
    AGGREGATIONS[agg.name] = agg


# counts
_register(_agg("count", numpy=("nanlen",), chunk=("nanlen",), combine=("sum",),
               fill_value={"intermediate": (0,), "numpy": (0,)}, final_fill_value=0,
               final_dtype=np.intp))

# sums / products
for nm, skipna in [("sum", False), ("nansum", True)]:
    _register(_agg(nm, chunk=(nm,), combine=("sum",),
                   fill_value={"intermediate": (0,), "numpy": (0,)}, final_fill_value=0))
for nm in ["prod", "nanprod"]:
    _register(_agg(nm, chunk=(nm,), combine=("prod",),
                   fill_value={"intermediate": (1,), "numpy": (1,)}, final_fill_value=1))

# mean family: chunk = (sum, count), combine = (sum, sum), finalize = divide
for nm, sum_k, len_k in [("mean", "sum", "len"), ("nanmean", "nansum", "nanlen")]:
    _register(_agg(nm, numpy=(nm,), chunk=(sum_k, len_k), combine=("sum", "sum"),
                   finalize=_mean_finalize,
                   fill_value={"intermediate": (0, 0), "numpy": (np.nan,)},
                   final_fill_value=dtypes.NA, final_dtype=None))

# var/std family: chunk = var_chunk triple, combine = Chan merge
for nm, skipna, fin in [("var", False, _var_finalize), ("nanvar", True, _var_finalize),
                        ("std", False, _std_finalize), ("nanstd", True, _std_finalize)]:
    _register(_agg(nm, numpy=(nm,),
                   chunk=(("var_chunk", {"skipna": skipna}),), combine=("var",), finalize=fin,
                   fill_value={"intermediate": (0,), "numpy": (np.nan,)},
                   final_fill_value=dtypes.NA))

# min/max
for nm, comb, sentinel in [("max", "max", dtypes.NINF), ("nanmax", "max", dtypes.NINF),
                           ("min", "min", dtypes.INF), ("nanmin", "min", dtypes.INF)]:
    _register(_agg(nm, chunk=(nm,), combine=(comb,),
                   fill_value={"intermediate": (sentinel,), "numpy": (dtypes.NA,)},
                   final_fill_value=dtypes.NA, preserves_dtype=True))

# bool reductions
_register(_agg("all", chunk=("all",), combine=("min",),
               fill_value={"intermediate": (True,), "numpy": (True,)}, final_fill_value=True,
               final_dtype=np.bool_))
_register(_agg("any", chunk=("any",), combine=("max",),
               fill_value={"intermediate": (False,), "numpy": (False,)}, final_fill_value=False,
               final_dtype=np.bool_))

# argreductions: eager path = direct kernel; chunked path pairs the extreme
# value with its global index (parity: aggregations.py:549-632)
for nm in ["argmax", "argmin", "nanargmax", "nanargmin"]:
    base = nm.removeprefix("nan")
    val_k = nm.replace("arg", "")  # max / nanmax / ...
    _register(_agg(nm, numpy=(nm,), chunk=(val_k, nm), combine=(base,),
                   finalize=_pick_second, reduction_type="argreduce",
                   fill_value={"intermediate": (dtypes.NINF if "max" in nm else dtypes.INF, -1),
                               "numpy": (-1,)},
                   final_fill_value=-1, final_dtype=np.intp))

# first/last: order-dependent; combine by tracking the global position
for nm, comb in [("first", "first"), ("last", "last"),
                 ("nanfirst", "first"), ("nanlast", "last")]:
    _register(_agg(nm, chunk=(nm,), combine=(comb,),
                   fill_value={"intermediate": (dtypes.NA,), "numpy": (dtypes.NA,)},
                   final_fill_value=dtypes.NA, preserves_dtype=True))

# order statistics: blockwise-only (chunk=None), like the reference
# (aggregations.py:672-712) — they need every element of a group at once.
for nm in ["median", "nanmedian"]:
    _register(_agg(nm, chunk=None, combine=None,
                   fill_value={"numpy": (dtypes.NA,)}, final_fill_value=dtypes.NA))
for nm in ["quantile", "nanquantile"]:
    _register(_agg(nm, chunk=None, combine=None,
                   fill_value={"numpy": (dtypes.NA,)}, final_fill_value=dtypes.NA,
                   new_dims_func=_quantile_new_dims))
for nm in ["mode", "nanmode"]:
    _register(_agg(nm, chunk=None, combine=None,
                   fill_value={"numpy": (dtypes.NA,)}, final_fill_value=dtypes.NA,
                   preserves_dtype=True))


def is_supported_aggregation(func: str) -> bool:
    """Public capability probe (parity: aggregations.py:1033-1054)."""
    return func in AGGREGATIONS


# ---------------------------------------------------------------------------
# initialization: resolve dtypes and fill values against the input array
# ---------------------------------------------------------------------------


def set_nat_final_fill(agg: "Aggregation", fill_value) -> None:
    """Dtype-preserving datetime reductions: the missing marker is NaT
    (INT64_MIN on the int64 view), never float NaN — float would corrupt
    ns-resolution timestamps; an explicit datetime/NaT fill is viewed to
    its int64 representation. ONE implementation shared by the eager core
    and the streaming runtime so the NaT discipline cannot drift."""
    if fill_value is None:
        agg.final_fill_value = np.iinfo(np.int64).min
    elif isinstance(agg.final_fill_value, (np.datetime64, np.timedelta64)):
        agg.final_fill_value = int(agg.final_fill_value.astype("int64"))
    agg.final_dtype = np.dtype("int64")


def shift_nat_identity_fills(agg: "Aggregation") -> None:
    """The NINF-resolved empty fill (iinfo.min) is byte-identical to the
    NaT marker; shift it so groups absent from a shard/slab are not
    mistaken for NaT-containing ones by marker re-injection. Shared by the
    mesh programs and the streaming runtime."""
    nat = np.iinfo(np.int64).min
    agg.fill_value["intermediate"] = tuple(
        (fv + 1 if isinstance(fv, (int, np.integer)) and fv == nat else fv)
        for fv in agg.fill_value.get("intermediate", ())
    )


def _initialize_aggregation(
    func: str | Aggregation,
    dtype,
    array_dtype,
    fill_value,
    min_count: int,
    finalize_kwargs: dict[str, Any] | None,
) -> Aggregation:
    """Resolve a registry template into a concrete plan
    (parity: aggregations.py:925-1030)."""
    if isinstance(func, FusedAggregation):
        # a fused plan is already fully resolved (per-statistic fills and
        # dtypes live in its member aggs); re-resolving would mangle it
        raise TypeError(
            "FusedAggregation plans run through groupby_aggregate_many / "
            "streaming_groupby_aggregate_many, not single-statistic entry "
            "points"
        )
    if isinstance(func, Aggregation):
        agg = copy.deepcopy(func)
    else:
        try:
            agg = copy.deepcopy(AGGREGATIONS[func])
        except KeyError:
            raise ValueError(f"Unsupported aggregation: {func!r}") from None

    array_dtype = np.dtype(array_dtype)
    agg.finalize_kwargs = dict(finalize_kwargs or {})
    agg.min_count = min_count

    # final dtype
    if agg.final_dtype is not None and dtype is None:
        final = np.dtype(agg.final_dtype)
    else:
        final = dtypes.normalize_dtype(
            dtype, array_dtype, preserves_dtype=agg.preserves_dtype, fill_value=fill_value
        )
        if not agg.preserves_dtype and agg.name not in ("sum", "nansum", "prod", "nanprod"):
            # mean/var/etc. of int data is float
            if agg.name not in ("count", "all", "any") and final.kind in "iub":
                final = np.result_type(final, np.float64 if utils.x64_enabled() else np.float32)
    agg.final_dtype = final

    # resolve final fill value; with min_count the default must be a missing
    # marker (NaN), not the reduction identity — that's the whole point of
    # min_count (parity: core.py:1026-1038 + aggregations.py:1005-1014)
    if fill_value is None:
        fill_value = dtypes.NA if min_count > 0 else agg.final_fill_value
    if fill_value in (dtypes.NA, dtypes.INF, dtypes.NINF):
        promoted, na = dtypes.maybe_promote(final)
        if fill_value is dtypes.NA:
            # only promote if some group can actually be missing; the caller
            # decides — record the NA-resolved value for use at finalize time
            fill_value = dtypes.get_fill_value(promoted, dtypes.NA)
        else:
            fill_value = dtypes.get_fill_value(final, fill_value)
    agg.final_fill_value = fill_value

    # resolve intermediate fills against the working dtype; argreductions'
    # first intermediate is the extreme VALUE (array dtype), not the index
    work_dtype = (
        array_dtype if (agg.preserves_dtype or agg.reduction_type == "argreduce") else final
    )
    inter = agg.fill_value.get("intermediate", ())
    agg.fill_value["intermediate"] = tuple(
        dtypes.get_fill_value(work_dtype, fv) if fv in (dtypes.NA, dtypes.INF, dtypes.NINF) else fv
        for fv in inter
    )

    # min_count: append a count intermediate so finalize can mask
    # (parity: aggregations.py:1005-1014)
    if min_count > 0 and agg.chunk is not None and "nanlen" not in _chunk_names(agg):
        agg.chunk = tuple(agg.chunk) + ("nanlen",)
        agg.combine = tuple(agg.combine) + ("sum",)
        agg.fill_value["intermediate"] = tuple(agg.fill_value["intermediate"]) + (0,)
        agg.appended_count = True

    return agg


def _chunk_names(agg: Aggregation) -> tuple[str, ...]:
    out = []
    for c in agg.chunk or ():
        if isinstance(c, tuple):
            out.append(c[0])
        elif isinstance(c, str):
            out.append(c)
    return tuple(out)


# ---------------------------------------------------------------------------
# multi-statistic fusion: one chunk pass serving N requested statistics
# ---------------------------------------------------------------------------

#: statistics the fusion planner can merge into one multi-output chunk plan:
#: everything whose chunk intermediates merge with the elementwise/Chan
#: combines. Argreductions and first/last carry position channels with
#: order-dependent merges, and order statistics are multi-pass — they stay
#: on the sequential path.
FUSABLE_FUNCS = frozenset(
    {
        "sum", "nansum", "prod", "nanprod", "count",
        "min", "nanmin", "max", "nanmax",
        "mean", "nanmean", "var", "nanvar", "std", "nanstd",
        "all", "any",
    }
)

_SKIPNA_FUNCS = frozenset(
    {"nansum", "nanprod", "count", "nanmin", "nanmax", "nanmean",
     "nanvar", "nanstd"}
)


@dataclass
class FusedAggregation(Aggregation):
    """A multi-output aggregation: one deduplicated chunk plan serving N
    requested statistics.

    The planner (:func:`plan_fused`) merges the requested ``Aggregation``
    blueprints: identical chunk kernels collapse to one leg (mean's
    sum+count, min_count's nanlen, every presence count), and when a
    var-family statistic is requested its Chan triple's (total, count)
    leaves serve mean directly — the data is touched once for the whole
    statistic set. ``chunk`` / ``combine`` / ``fill_value`` hold the
    deduplicated legs in the exact layout the generic runtimes consume
    (``_local_chunk`` iteration, ``_combine_intermediates`` psum/pmax/Chan
    merges, the streaming ``_merge_into`` carry), so ONE mesh program /
    ONE streaming carry covers all N statistics. ``slots`` maps each
    statistic to its legs; :meth:`finalize_fused` folds the combined legs
    into the per-statistic results.
    """

    #: resolved per-statistic blueprints, request order
    aggs: tuple = ()
    #: requested names, request order (the output dict keys)
    funcs: tuple = ()
    #: per-statistic addressing into the deduplicated legs (see plan_fused)
    slots: tuple = ()
    #: per-leg eager-path dtype requests (None on the mesh/streaming paths,
    #: which never request dtypes — mirroring _local_chunk vs chunk_reduce)
    eager_dtypes: tuple = ()

    def finalize_fused(self, inters, counts=None):
        """Combined legs -> tuple of finalized per-statistic results.

        ``counts`` (the runtimes' generic count channel) is ignored: every
        statistic reads its OWN presence leg, because skipna and
        propagating statistics disagree about what "empty" means. Works on
        jax arrays (traced — the eager program and the mesh programs call
        it in-jit) and on host numpy (the numpy engine).
        """
        results = []
        for agg, slot in zip(self.aggs, self.slots):
            results.append(_finalize_slot(agg, slot, inters, self.min_count))
        return tuple(results)


def _read_leg(inters, addr):
    """Resolve a leg address: an int (whole leg) or (leg, leaf) into a
    MultiArray leg (the var triple's total/count leaves)."""
    if isinstance(addr, tuple):
        leg, leaf = addr
        return inters[leg].arrays[leaf]
    return inters[addr]


def _xp_for(x):
    if _is_jaxish(x):
        import jax.numpy as jnp

        return jnp
    return np


def _masked_fill(result, empty, fill_value):
    """Apply a final fill where ``empty`` — THE final-fill promotion rules
    (NaN fills promote int results to float, identity fills cast to the
    result dtype, complex counts as inexact), dual-mode jax/numpy. The
    single implementation behind the fused finalize AND the mesh programs'
    ``_apply_final_fill`` (parallel/mapreduce.py), so fused/sequential
    parity cannot drift."""
    if fill_value is None:
        return result
    xp = _xp_for(result)
    try:
        fill_is_nan = bool(np.isnan(fill_value))
    except (TypeError, ValueError):
        fill_is_nan = False
    fv = xp.asarray(fill_value)
    res_inexact = xp.issubdtype(result.dtype, xp.floating) or xp.issubdtype(
        result.dtype, xp.complexfloating
    )
    if xp.issubdtype(fv.dtype, xp.floating) and not res_inexact:
        if fill_is_nan:
            promoted = (
                xp.float64 if (xp is np or utils.x64_enabled()) else xp.float32
            )
            result = result.astype(promoted)
        else:
            fv = fv.astype(result.dtype)
    empty_b = xp.broadcast_to(xp.asarray(empty), result.shape)
    return xp.where(empty_b, fv.astype(result.dtype), result)


def _finalize_slot(agg: Aggregation, slot: dict, inters, min_count: int):
    """One statistic's result from the combined legs."""
    kind = slot["kind"]
    if kind == "var":
        ma = inters[slot["leg"]]
        fin = _std_finalize if slot["std"] else _var_finalize
        out = fin(ma, **agg.finalize_kwargs)
        present = ma.arrays[2] > 0
    elif kind == "mean":
        total = _read_leg(inters, slot["sum"])
        cnt = _read_leg(inters, slot["count"])
        cntf = cnt.astype(total.dtype) if cnt.dtype != total.dtype else cnt
        if _is_jaxish(total):
            out = total / cntf
        else:
            with np.errstate(invalid="ignore", divide="ignore"):
                out = total / cntf
        present = cnt > 0
    elif kind == "count":
        out = inters[slot["leg"]]
        present = out > 0
    else:  # "direct": sum/prod/min/max/all/any — the leg IS the value
        out = inters[slot["leg"]]
        present = _read_leg(inters, slot["present"]) > 0
    xp = _xp_for(out)
    out = _masked_fill(out, ~xp.asarray(present), agg.final_fill_value)
    if min_count > 0:
        nn = inters[slot["nanlen"]]
        out = _masked_fill(out, nn < min_count, agg.final_fill_value)
    return out


def plan_fused(
    funcs,
    dtype,
    array_dtype,
    fill_value,
    min_count: int,
    finalize_kwargs,
) -> FusedAggregation:
    """The fusion planner: merge N statistic blueprints into one
    multi-output chunk plan (the generalization of the reference's
    mean = sum+count single-pass blueprint, aggregations.py:161, to an
    arbitrary statistic set).

    ``funcs``: statistic names (see :data:`FUSABLE_FUNCS`). ``fill_value``
    and ``finalize_kwargs`` may be per-statistic dicts (``{"var": ...}``)
    or a single value applied to all. Deduplication: identical chunk legs
    collapse; when a var-family statistic shares its skipna mode with
    mean, mean reads the Chan triple's (total, count) leaves instead of
    adding legs — min/max ride free next to them.
    """
    funcs = tuple(funcs)
    if len(funcs) == 0:
        raise ValueError("groupby_aggregate_many needs at least one func")
    if len(set(funcs)) != len(funcs):
        raise ValueError(f"duplicate funcs in {funcs!r}")
    bad = [f for f in funcs if not isinstance(f, str) or f not in FUSABLE_FUNCS]
    if bad:
        raise NotImplementedError(
            f"cannot fuse {bad!r}: fusable statistics are "
            f"{sorted(FUSABLE_FUNCS)} (argreductions, first/last and order "
            "statistics keep their sequential paths)"
        )

    def per_func(v, f):
        if isinstance(v, dict):
            return v.get(f)
        return v

    aggs = []
    for f in funcs:
        agg = _initialize_aggregation(
            f, per_func(dtype, f), array_dtype, per_func(fill_value, f),
            min_count, per_func(finalize_kwargs, f) or {},
        )
        if agg.appended_count:
            # the fused plan carries ONE shared nanlen leg for min_count;
            # the per-agg appended count would otherwise mask the combine
            # signature (var's ("var",) becomes ("var", "sum")) and
            # misclassify the Chan triple below
            agg.chunk = agg.chunk[:-1]
            agg.combine = agg.combine[:-1]
            agg.fill_value["intermediate"] = agg.fill_value["intermediate"][:-1]
            agg.appended_count = False
        aggs.append(agg)
    aggs = tuple(aggs)

    legs: list[dict] = []  # {"entry", "combine", "fill", "eager_dtype"}
    index: dict[tuple, int] = {}

    def add_leg(entry, combine, fill, eager_dtype=None):
        if isinstance(entry, tuple):
            name, kw = entry[0], tuple(sorted(dict(entry[1]).items()))
        else:
            name, kw = entry, ()
        key = (
            name, kw, repr(fill),
            None if eager_dtype is None else np.dtype(eager_dtype).name,
        )
        if key in index:
            return index[key]
        index[key] = len(legs)
        legs.append(
            {"entry": entry, "combine": combine, "fill": fill,
             "eager_dtype": eager_dtype}
        )
        return index[key]

    # pass 1: var-family triples first, so mean can alias into them
    var_leg: dict[bool, int] = {}  # skipna -> leg index
    for f, agg in zip(funcs, aggs):
        if agg.combine == ("var",):
            skipna = f in _SKIPNA_FUNCS
            var_leg.setdefault(
                skipna,
                add_leg(("var_chunk", {"skipna": skipna}), "var",
                        agg.fill_value["intermediate"][0]),
            )

    nanlen_leg = add_leg("nanlen", "sum", 0) if min_count > 0 else None

    slots: list[dict] = []
    for f, agg in zip(funcs, aggs):
        skipna = f in _SKIPNA_FUNCS
        # presence ("no fill needed") semantics per statistic: nanmin/nanmax
        # of an all-NaN group is missing (nanlen), but nansum/nanprod of one
        # is the identity — numpy semantics: only zero-TOTAL-element groups
        # take the fill there (kernels._make_addlike's comment)
        presence_entry = "nanlen" if f in ("nanmin", "nanmax") else "len"
        if agg.combine == ("var",):
            slot = {
                "kind": "var", "leg": var_leg[skipna],
                "std": f in ("std", "nanstd"),
            }
        elif f in ("mean", "nanmean"):
            if skipna in var_leg:
                # sum/count feed mean AND var: read the Chan triple's
                # (total, count) leaves — zero extra legs
                tleg = var_leg[skipna]
                slot = {
                    "kind": "mean",
                    "sum": (tleg, 1), "count": (tleg, 2),
                    "present": (tleg, 2),
                }
            else:
                sum_k, len_k = agg.chunk[0], agg.chunk[1]
                # the float work dtype, so int inputs promote exactly as
                # the direct eager mean kernel does
                s = add_leg(sum_k, "sum", 0, eager_dtype=agg.final_dtype)
                c = add_leg(len_k, "sum", 0)
                slot = {"kind": "mean", "sum": s, "count": c, "present": c}
        elif f == "count":
            leg = add_leg("nanlen", "sum", 0)
            slot = {"kind": "count", "leg": leg}
        else:
            entry = agg.chunk[0]
            fill = agg.fill_value["intermediate"][0]
            edt = None
            if f in ("sum", "nansum", "prod", "nanprod") and not agg.preserves_dtype:
                edt = agg.final_dtype  # chunk_reduce's kdtypes[0] rule
            leg = add_leg(entry, agg.combine[0], fill, eager_dtype=edt)
            p = add_leg(presence_entry, "sum", 0)
            slot = {"kind": "direct", "leg": leg, "present": p}
        if min_count > 0:
            slot["nanlen"] = nanlen_leg
        slots.append(slot)

    fused = FusedAggregation(
        name="fused[" + "+".join(funcs) + "]",
        numpy=funcs,
        chunk=tuple(leg["entry"] for leg in legs),
        combine=tuple(leg["combine"] for leg in legs),
        fill_value={"intermediate": tuple(leg["fill"] for leg in legs)},
        final_fill_value=0,
        min_count=min_count,
        aggs=aggs,
        funcs=funcs,
        slots=tuple(slots),
        eager_dtypes=tuple(leg["eager_dtype"] for leg in legs),
    )
    return fused


def fused_chunk_stats(
    agg: FusedAggregation, group_idx, array, *, size: int, engine: str = "jax",
    eager: bool = False,
):
    """Run the fused chunk plan: one intermediate per leg.

    The jax-engine path routes the megakernel-eligible legs (sums, counts,
    min/max over the same float data) through
    ``kernels.fused_segment_stats`` — ONE Pallas pass with every
    accumulator resident in VMEM — and falls back to per-leg XLA
    ``segment_*`` kernels otherwise (still one jitted program, fused by
    XLA). ``eager=True`` applies the per-leg dtype requests the eager
    bundle makes (mesh/streaming never request dtypes — parity with
    ``_local_chunk``)."""
    from . import kernels

    names = [leg[0] if isinstance(leg, tuple) else leg for leg in agg.chunk]
    # resolved BEFORE any array-derived name exists: only dtype NAMES are
    # compared below, so no traced value ever reaches a numpy call
    # (FLX011-clean — .dtype is a host attribute on tracers too)
    req_names = [
        None if _rd is None else np.dtype(_rd).name for _rd in agg.eager_dtypes
    ]
    dtype_name = str(array.dtype)

    mega: dict[int, Any] = {}
    if engine == "jax":
        mega_mask = [
            n in ("sum", "nansum", "min", "nanmin", "max", "nanmax",
                  "len", "nanlen")
            # a pending dtype-request cast would change what the one-pass
            # kernel sums; only no-op requests may ride it
            and (not eager or req_names[i] is None or req_names[i] == dtype_name)
            for i, n in enumerate(names)
        ]
        wanted = tuple(dict.fromkeys(
            names[i] for i, ok in enumerate(mega_mask) if ok
        ))
        if len(wanted) >= 2:
            got = kernels.fused_segment_stats(
                group_idx, array, size=size, want=wanted
            )
            if got is not None:
                for i, ok in enumerate(mega_mask):
                    if ok and names[i] in got:
                        mega[i] = got[names[i]]

    inters = []
    for i, (entry, fv) in enumerate(zip(agg.chunk, agg.fill_value["intermediate"])):
        if i in mega:
            inters.append(mega[i])
            continue
        if isinstance(entry, tuple):
            name, extra = entry[0], dict(entry[1])
        else:
            name, extra = entry, {}
        dt = agg.eager_dtypes[i] if eager else None
        if engine == "jax" and not eager and name in (
            "sum", "nansum", "prod", "nanprod"
        ):
            # bf16/f16 intermediates travel and merge in the f32
            # accumulator (parity: _local_chunk's keep_acc)
            extra["keep_acc"] = True
        inters.append(
            generic_aggregate(
                group_idx, array, engine=engine, func=name, size=size,
                fill_value=fv, dtype=dt, **extra,
            )
        )
    return inters


# ---------------------------------------------------------------------------
# scans (parity: aggregations.py:716-922)
# ---------------------------------------------------------------------------


@dataclass
class Scan:
    """Blueprint for a grouped scan.

    * ``scan``: the within-block grouped scan kernel.
    * ``reduction``: per-block per-group summary carried across blocks
      (cumsum -> "sum" of the block; ffill -> "nanlast" value).
    * ``binary_op``: how an incoming carry combines with block values.
    * ``identity``: carry for groups not yet seen.
    """

    name: str
    scan: str
    reduction: str
    binary_op: Callable | None
    identity: Any
    # "apply_binary_op": add carry to scanned block; "ffill": where-NaN fill
    mode: Literal["apply_binary_op", "ffill"] = "apply_binary_op"
    preserves_dtype: bool = False


SCANS: dict[str, Scan] = {
    "cumsum": Scan("cumsum", scan="cumsum", reduction="sum", binary_op=None, identity=0),
    "nancumsum": Scan("nancumsum", scan="nancumsum", reduction="nansum", binary_op=None, identity=0),
    "ffill": Scan("ffill", scan="ffill", reduction="nanlast", binary_op=None, identity=np.nan,
                  mode="ffill", preserves_dtype=True),
    "bfill": Scan("bfill", scan="bfill", reduction="nanfirst", binary_op=None, identity=np.nan,
                  mode="ffill", preserves_dtype=True),
}


def _initialize_scan(func: str | Scan) -> Scan:
    if isinstance(func, Scan):
        return copy.deepcopy(func)
    try:
        return copy.deepcopy(SCANS[func])
    except KeyError:
        raise ValueError(f"Unsupported scan: {func!r}") from None
