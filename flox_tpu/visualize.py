"""Debug visualizers for group/shard/cohort layouts.

Parity target: /root/reference/flox/visualize.py:13-191
(``visualize_groups_1d`` :79, ``visualize_cohorts_2d`` :139,
``visualize_groups_2d`` :178). matplotlib is optional; every entry point
raises a clear error when it is missing.
"""

from __future__ import annotations

import numpy as np

from . import factorize as fct
from .utils import HAS_MATPLOTLIB

__all__ = ["visualize_groups_1d", "visualize_cohorts_2d", "visualize_groups_2d"]


def _require_mpl():
    if not HAS_MATPLOTLIB:
        raise ImportError("matplotlib is required for flox_tpu.visualize")
    import matplotlib.pyplot as plt

    return plt


def _shard_boundaries(n: int, chunks) -> list[int]:
    bounds = [0]
    for c in chunks:
        bounds.append(bounds[-1] + c)
    return bounds


def visualize_groups_1d(labels, chunks=None, ax=None, colors=None):
    """Color-striped view of 1-D labels with shard boundaries overlaid
    (parity: visualize.py:79-136)."""
    plt = _require_mpl()
    labels = np.asarray(labels).reshape(-1)
    codes, groups = fct.factorize_single(labels, None, sort=True)
    if ax is None:
        _, ax = plt.subplots(figsize=(12, 1.5))
    ax.imshow(codes[np.newaxis, :], aspect="auto", cmap=colors or "tab20", interpolation="none")
    if chunks is not None:
        for b in _shard_boundaries(len(labels), chunks)[1:-1]:
            ax.axvline(b - 0.5, color="k", lw=1.5)
    ax.set_yticks([])
    ax.set_xlabel("position")
    return ax


def visualize_cohorts_2d(chunks_cohorts, nlabels: int, nchunks: int, ax=None):
    """Heatmap of the cohort assignment: chunk x label membership
    (parity: visualize.py:139-175)."""
    plt = _require_mpl()
    grid = np.zeros((nchunks, nlabels))
    for ci, (chunk_ids, labels) in enumerate(chunks_cohorts.items(), start=1):
        for c in chunk_ids:
            for lab in labels:
                grid[c, lab] = ci
    if ax is None:
        _, ax = plt.subplots(figsize=(8, 4))
    ax.imshow(grid, aspect="auto", cmap="tab20", interpolation="none")
    ax.set_xlabel("label")
    ax.set_ylabel("shard")
    return ax


def visualize_groups_2d(labels, ax=None, **kwargs):
    """2-D label map (zonal-stats style; parity: visualize.py:178-191)."""
    plt = _require_mpl()
    labels = np.asarray(labels)
    codes, _ = fct.factorize_single(labels.reshape(-1), None, sort=True)
    if ax is None:
        _, ax = plt.subplots()
    ax.imshow(codes.reshape(labels.shape), cmap="tab20", interpolation="none", **kwargs)
    return ax
