"""xarray adapter: ``xarray_reduce`` (L6).

Parity target: /root/reference/flox/xarray.py:73-516 — named/DataArray
groupers, dim=... semantics, skipna -> nan-func rewriting (xarray.py:369-371),
``xr.apply_ufunc`` dispatch (416-446), coordinate/attr restoration and dim
order (448-516, 37-50), MultiIndex group coords (263-269, 468-479).

The adapter binds to real xarray when installed and to :mod:`flox_tpu.xrlite`
otherwise — the same code path runs either way, so adapter behavior is
exercised in CI even without the xarray package.
"""

from __future__ import annotations

from typing import Any, Hashable, Sequence

import numpy as np
import pandas as pd

from .aggregations import AGGREGATIONS
from .utils import HAS_XARRAY

__all__ = ["xarray_reduce", "rechunk_for_blockwise", "rechunk_for_cohorts"]


def _get_xr():
    """Real xarray if installed, else the bundled xrlite subset."""
    if HAS_XARRAY:
        import xarray as xr

        return xr
    from . import xrlite

    return xrlite


_require_xarray = _get_xr  # backwards-compatible alias


def _restore_dim_order(result, obj, by, no_groupby_reorder: bool = False):
    """Reorder result dims to match the input object's order, slotting the
    new group dim where the grouped dim was (parity: xarray.py:37-50)."""

    def lookup_order(dimension):
        if dimension == by.name and by.ndim == 1:
            (dimension,) = by.dims
            if no_groupby_reorder:
                return -1e6  # group dim first
        if dimension in obj.dims:
            return list(obj.dims).index(dimension)
        return 1e6  # new dims (e.g. quantile) go last

    new_order = sorted(result.dims, key=lookup_order)
    return result.transpose(*new_order)


def _rewrite_func_for_skipna(func: str, skipna: bool | None) -> str:
    """skipna=True -> nan-variant; skipna=False -> plain variant
    (parity: xarray.py:369-386)."""
    if not isinstance(func, str) or skipna is None:
        return func
    has_nan_variant = f"nan{func}" in AGGREGATIONS
    if skipna and not func.startswith("nan") and has_nan_variant:
        return f"nan{func}"
    if skipna is False and func.startswith("nan"):
        return func.removeprefix("nan")
    return func


def _resolve_dim(dim, by_dims: tuple[Hashable, ...], obj_dims: tuple[Hashable, ...]):
    """dim=None -> reduce over all grouper dims; dim=... -> all object dims
    (parity: xarray.py:271-282)."""
    if dim is None:
        return tuple(by_dims)
    if dim is Ellipsis:
        return tuple(obj_dims)
    if isinstance(dim, str):
        return (dim,)
    return tuple(dim)


def _plain_reduce(obj, dims, func: str, finalize_kwargs, keep_attrs: bool):
    """Non-grouped reduction over ``dims`` (parity: xarray.py:303-322).

    With real xarray, delegate to the object's own reduction method (as the
    reference does) so coords/attrs survive natively. On xrlite, reduce with
    the array's own namespace — jax arrays stay on device. Explicit
    nan-funcs map to skipna semantics here (the reference instead raises
    and asks for ``skipna=True`` — our skipna rewrite runs before this gate,
    so both spellings are equivalent by the time they arrive).
    """
    if not isinstance(func, str):
        raise NotImplementedError(
            "func must be a string when reducing along dimensions not in `by`"
        )
    kwargs = dict(finalize_kwargs or {})
    skipna = func.startswith("nan")
    base = func.removeprefix("nan") if skipna else func
    if base in ("argmax", "argmin") and len(dims) != 1:
        raise NotImplementedError("arg-reductions reduce a single dim")

    if HAS_XARRAY and hasattr(obj, base):
        kw = dict(kwargs)
        if skipna:
            kw["skipna"] = True
        kw["keep_attrs"] = keep_attrs
        # scalar dim for arg-reductions: xarray returns a dict for list dims
        dim_arg = dims[0] if base in ("argmax", "argmin") else list(dims)
        return getattr(obj, base)(dim=dim_arg, **kw)

    axes = tuple(list(obj.dims).index(d) for d in dims)
    data = obj.data if hasattr(obj, "data") else obj
    from .utils import is_jax_array

    if is_jax_array(data):
        import jax.numpy as xp
    else:
        xp = np
        data = np.asarray(data)
    q = kwargs.pop("q", 0.5) if base == "quantile" else None
    if base in ("argmax", "argmin"):
        if len(axes) != 1:
            raise NotImplementedError("arg-reductions reduce a single dim")
        result = getattr(xp, func)(data, axis=axes[0], **kwargs)
    elif func == "count":
        result = xp.sum(~xp.isnan(data), axis=axes)
    elif base == "quantile":
        result = (xp.nanquantile if skipna else xp.quantile)(data, q, axis=axes, **kwargs)
    elif hasattr(xp, func):
        result = getattr(xp, func)(data, axis=axes, **kwargs)
    else:
        raise NotImplementedError(
            f"plain reduction over non-grouper dims has no array-namespace "
            f"equivalent for {func!r}; reduce with groupby_reduce on the raw array."
        )
    out_dims = tuple(d for d in obj.dims if d not in dims)
    vector_q = base == "quantile" and np.ndim(q) > 0
    if vector_q:
        out_dims = ("quantile",) + out_dims
    xr = _get_xr()
    da = xr.DataArray(result, dims=out_dims, name=getattr(obj, "name", None),
                      attrs=dict(obj.attrs) if keep_attrs else {})
    for cname, (cdims, cdata) in getattr(obj, "_coords", {}).items():
        if all(d in out_dims for d in cdims):
            da._coords[cname] = (cdims, cdata)
    if vector_q:
        da = da.assign_coords({"quantile": np.asarray(q, dtype=float)})
    return da


def xarray_reduce(
    obj: Any,
    *by: Any,
    func: str,
    expected_groups: Any = None,
    isbin: bool | Sequence[bool] = False,
    sort: bool = True,
    dim: Hashable | Sequence[Hashable] | None = None,
    fill_value: Any = None,
    dtype: Any = None,
    method: str | None = None,
    engine: str | None = None,
    keep_attrs: bool = True,
    skipna: bool | None = None,
    min_count: int | None = None,
    mesh: Any = None,
    **finalize_kwargs: Any,
) -> Any:
    """GroupBy reduction on an xarray Dataset/DataArray.

    ``by`` entries may be variable/coordinate names or DataArrays. Returns
    an object of the same type with the reduced dims replaced by one dim per
    grouper (named after the grouper, with the discovered/expected groups as
    its coordinate). Works on real xarray objects when xarray is installed,
    and on :mod:`flox_tpu.xrlite` objects otherwise.
    """
    xr = _get_xr()
    from .core import groupby_reduce

    if not by:
        raise TypeError("Must pass at least one `by`")

    func = _rewrite_func_for_skipna(func, skipna)

    if isinstance(obj, xr.Dataset):
        # apply per-variable: variables missing the reduced dims pass through
        # unchanged (parity: the reference's handling of mixed-dim Datasets,
        # xarray.py:303-322)
        by_named = [obj[b] if isinstance(b, str) else b for b in by]
        probe_dims = tuple(dict.fromkeys(d for b in by_named for d in b.dims))
        target_dims = _resolve_dim(dim, probe_dims, tuple(obj.dims))
        reduced_vars = {}
        passthrough = {}
        for name, var in obj.data_vars.items():
            if all(d in var.dims for d in target_dims):
                reduced = xarray_reduce(
                    var, *by_named, func=func, expected_groups=expected_groups,
                    isbin=isbin, sort=sort, dim=dim, fill_value=fill_value,
                    dtype=dtype, method=method, engine=engine,
                    keep_attrs=keep_attrs, skipna=None, min_count=min_count,
                    mesh=mesh, **finalize_kwargs,
                )
                if len(by_named) == 1 and reduced.ndim > 1:
                    # dataset members put the group dim first (parity:
                    # xarray.py:497-505, no_groupby_reorder)
                    # the group dim is whatever new dim the recursive call
                    # produced (it already applied the binned-name rule);
                    # don't re-derive it here. No new dim means the group
                    # dim reuses an existing name (grouping by a dim
                    # coordinate) — keep the grouper's own name then.
                    new_dims = [
                        d for d in reduced.dims
                        if d not in var.dims and d != "quantile"
                    ]
                    by_o = by_named[0]
                    if new_dims and new_dims[0] != by_o.name:
                        by_o = by_o.rename(new_dims[0])
                    reduced = _restore_dim_order(
                        reduced, var, by_o, no_groupby_reorder=True
                    )
                reduced_vars[name] = reduced
            else:
                passthrough[name] = var
        out = xr.Dataset(reduced_vars, attrs=obj.attrs if keep_attrs else None)
        for name, var in passthrough.items():
            out[name] = var
        return out

    # resolve groupers to DataArrays (parity: xarray.py:243-269)
    by_das: list = []
    for b in by:
        if isinstance(b, str):
            if isinstance(obj, xr.Dataset) and b in obj:
                by_das.append(obj[b])
            elif b in obj.coords:
                by_das.append(obj[b])
            else:
                raise ValueError(f"Grouper {b!r} not found in object")
        else:
            by_das.append(b)
    by_names = [getattr(b, "name", None) or f"group_{i}" for i, b in enumerate(by_das)]

    def _mi_level_names(b):
        """Level names when the grouper is MultiIndex-backed, else None."""
        if isinstance(getattr(b, "data", None), pd.MultiIndex):
            return tuple(b.data.names)
        if getattr(b, "ndim", 0) == 1 and hasattr(b, "to_index"):
            try:
                idx = b.to_index()
            except Exception:
                return None
            if isinstance(idx, pd.MultiIndex):
                return tuple(idx.names)
        return None

    mi_names = [_mi_level_names(b) for b in by_das]

    grouper_dims = tuple(dict.fromkeys(d for b in by_das for d in b.dims))
    dims = _resolve_dim(dim, grouper_dims, tuple(obj.dims))
    bad = [d for d in dims if d not in obj.dims]
    if bad:
        raise ValueError(f"Cannot reduce over missing dims {bad}")

    isbin_seq = (isbin,) * len(by_das) if isinstance(isbin, bool) else tuple(isbin)
    if dims and all(d not in grouper_dims for d in dims) and not any(isbin_seq):
        # groups do not vary along any reduced dim: this is a plain
        # reduction, no groupby at all (parity: xarray.py:303-322). The
        # groupers still must align with the object — the general path
        # enforces this via broadcast + join='exact', so the shortcut
        # cannot be laxer.
        for b in by_das:
            for d, sz in b.sizes.items():
                if d not in obj.dims or obj.sizes[d] != sz:
                    raise ValueError(
                        f"grouper {getattr(b, 'name', None)!r} dim {d!r} "
                        f"(size {sz}) does not align with the object "
                        f"(dims {dict(obj.sizes)})"
                    )
        return _plain_reduce(obj, dims, func, finalize_kwargs, keep_attrs)

    # broadcast groupers against each other (parity: xarray.py:284-301);
    # reduced dims the labels don't span are broadcast by expand_dims
    by_b = list(xr.broadcast(*by_das))
    by_dims = tuple(dict.fromkeys(d for b in by_b for d in b.dims))
    missing_dims = tuple(d for d in dims if d not in by_dims)
    if missing_dims:
        sizes = obj.sizes
        by_b = [
            b.expand_dims({d: sizes[d] for d in missing_dims if d not in b.dims})
            for b in by_b
        ]
        by_b = list(xr.broadcast(*by_b))
        by_dims = tuple(dict.fromkeys(d for b in by_b for d in b.dims))

    # normalize expected groups per grouper
    nby = len(by_b)
    if expected_groups is None:
        expected_t: tuple = (None,) * nby
    elif nby == 1 and not isinstance(expected_groups, tuple):
        expected_t = (expected_groups,)
    else:
        expected_t = tuple(expected_groups)
    isbin_t = isbin_seq  # normalized once at the fast-path gate (same length)

    reduce_dims = tuple(d for d in by_dims if d in dims)
    # groupby_reduce requires by to span the trailing reduced dims of the
    # array: core dims are (kept by-dims..., reduced dims...), and every
    # grouper is transposed to that same order
    input_core = list(
        dict.fromkeys(tuple(d for d in by_dims if d not in reduce_dims) + reduce_dims)
    )
    by_b = [b.transpose(*input_core) for b in by_b]

    # a grouper is binned when isbin is set OR its expected groups are an
    # IntervalIndex (parity: xarray.py:334)
    new_dim_names = [
        f"{name}_bins" if (bin_ or isinstance(exp, pd.IntervalIndex)) else name
        for name, bin_, exp in zip(by_names, isbin_t, expected_t)
    ]
    keep_by_dims = [d for d in input_core if d not in reduce_dims]
    q = finalize_kwargs.get("q") if finalize_kwargs else None
    has_q_dim = func in ("quantile", "nanquantile") and q is not None and np.ndim(q) > 0
    output_core = keep_by_dims + new_dim_names + (["quantile"] if has_q_dim else [])

    groups_out: list = []

    n_reduce = len(reduce_dims)

    def wrapper(arr, *by_arrays):
        result, *groups = groupby_reduce(
            arr,
            *by_arrays,
            func=func,
            axis=tuple(range(-n_reduce, 0)),
            expected_groups=expected_t if any(e is not None for e in expected_t) else None,
            isbin=isbin_t,
            sort=sort,
            fill_value=fill_value,
            dtype=dtype,
            min_count=min_count,
            method=method,
            engine=engine,
            mesh=mesh,
            finalize_kwargs=finalize_kwargs or None,
        )
        groups_out.clear()
        groups_out.extend(groups)
        result = np.asarray(result)
        if has_q_dim:
            # groupby_reduce puts the q dim first; apply_ufunc wants core
            # dims last, so quantile becomes the trailing output dim
            result = np.moveaxis(result, 0, -1)
        return result

    actual = xr.apply_ufunc(
        wrapper,
        obj,
        *by_b,
        input_core_dims=[input_core] + [input_core] * len(by_b),
        output_core_dims=[output_core],
        dask="forbidden",
        keep_attrs=keep_attrs,
        vectorize=False,
        join="exact",
        dataset_fill_value=np.nan,
    )

    # attach group coordinates (parity: xarray.py:448-516)
    def _assign_multiindex(obj_, name, mi):
        """Modern real xarray rejects a raw MultiIndex in assign_coords;
        it wants Coordinates.from_pandas_multiindex. xrlite (and older
        xarray) accept the index directly."""
        if HAS_XARRAY and hasattr(xr, "Coordinates"):
            try:
                return obj_.assign_coords(xr.Coordinates.from_pandas_multiindex(mi, name))
            except Exception:
                pass
        return obj_.assign_coords({name: mi})

    for name, groups, names_mi in zip(new_dim_names, groups_out, mi_names):
        if isinstance(groups, pd.MultiIndex):
            actual = _assign_multiindex(actual, name, groups)
        elif isinstance(groups, pd.IntervalIndex):
            actual = actual.assign_coords({name: groups})
        elif names_mi is not None and len(groups) and isinstance(groups[0], tuple):
            # grouping by a MultiIndex coord: factorize discovered tuples;
            # rebuild the MultiIndex with its level names (parity:
            # xarray.py:468-479)
            actual = _assign_multiindex(
                actual, name, pd.MultiIndex.from_tuples(list(groups), names=names_mi)
            )
        else:
            actual = actual.assign_coords({name: np.asarray(groups)})
    if has_q_dim:
        actual = actual.assign_coords({"quantile": np.asarray(q, dtype=float)})
    # dim order: slot the group dim where the grouped dim was
    # (parity: xarray.py:37-50, applied at 495-505). The lookup compares
    # against the result's dim name, so binned groupers need the _bins name.
    if nby == 1 and actual.ndim > 1:
        by_for_order = by_das[0]
        if new_dim_names[0] != by_names[0]:
            by_for_order = by_for_order.rename(new_dim_names[0])
        actual = _restore_dim_order(actual, obj, by_for_order)
    return actual


def rechunk_for_blockwise(obj, dim: str, labels, n_shards: int | None = None):
    """xarray-level wrapper over rechunk.reshard_for_blockwise
    (parity: xarray.py:567-612).

    Returns ``(resharded DataArray, codes, groups)`` with ``dim`` replaced by
    the padded shard-local layout (length ``n_shards * shard_len``); feed the
    pair to ``groupby_reduce(..., method='blockwise')``.
    """
    xr = _require_xarray()
    from . import rechunk as _rechunk

    if isinstance(obj, xr.Dataset):
        raise NotImplementedError(
            "rechunk_for_blockwise takes a DataArray; reshard each variable "
            "or use flox_tpu.rechunk.reshard_for_blockwise directly."
        )
    axis = obj.dims.index(dim)
    arr, codes, groups = _rechunk.rechunk_for_blockwise(
        obj.data, axis, np.asarray(labels), n_shards
    )
    new_dims = tuple(d for d in obj.dims if d != dim) + (dim,)
    out = xr.DataArray(
        np.asarray(arr), dims=new_dims, attrs=obj.attrs,
        coords={d: obj.coords[d] for d in obj.coords if d != dim and d in new_dims},
    )
    return out, codes, groups


def rechunk_for_cohorts(
    obj, dim: str, labels, force_new_chunk_at, chunksize: int | None = None
):
    """xarray-level wrapper over rechunk.rechunk_for_cohorts
    (parity: reference xarray.py:519-566).

    Returns the chunk-length tuple for ``dim`` with boundaries anchored at
    ``force_new_chunk_at`` label starts — feed it to
    ``cohorts.find_group_cohorts`` or use the lengths as shard sizes.
    """
    from . import rechunk as _rechunk

    if dim not in getattr(obj, "dims", ()):
        raise ValueError(f"Object has no dim {dim!r}; dims: {tuple(obj.dims)}")
    labels_np = np.asarray(getattr(labels, "data", labels)).reshape(-1)
    dim_len = obj.sizes[dim]
    if labels_np.shape[0] != dim_len:
        raise ValueError(
            f"labels have length {labels_np.shape[0]} but dim {dim!r} has "
            f"size {dim_len}; pass labels aligned with that dimension."
        )
    return _rechunk.rechunk_for_cohorts(
        None, 0, labels_np, force_new_chunk_at, chunksize=chunksize,
    )
