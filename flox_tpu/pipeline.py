"""Pipelined streaming executor: prefetched H2D staging + donated carry.

The streaming entry points (`streaming.py`) used to stage every slab inline
in the Python loop: ``loader(s, e)`` IO, the pad ``np.concatenate``, and the
``jax.device_put`` all ran on the consumer thread, serialized against each
other and against the step dispatch. jax's async dispatch hides device
*compute* behind that staging, but nothing hides the staging itself — at
ERA5 slab sizes the load+stage wall IS the streaming throughput. This module
is the explicit pipeline:

* :func:`stream_slabs` is the ONE slab source all three streaming runtimes
  (reduce, scan, quantile) iterate, on both the single-device and mesh
  paths. It stages slab ``i+k`` — load, pad, ``device_put`` against the
  SAME shardings the synchronous path used — while the device reduces slab
  ``i``. Prefetch changes only WHEN staging happens, never what bytes land
  on device, so prefetch on/off is bit-identical by construction.
* The prefetch stage is a bounded pool: at most ``OPTIONS["stream_prefetch"]``
  slabs in flight, staged by that many background threads. Depth > 1 also
  overlaps the loads themselves — the realistic win for latency-dominated
  loaders (zarr/S3 range reads), where a single serial worker could never
  beat the inline loop by more than the dispatch overhead. Loaders must
  therefore tolerate concurrent ``(start, stop)`` calls when depth > 1
  (zarr, memmap, and object-store readers do); a stateful serial reader
  should run with ``stream_prefetch=1`` (one background worker, loads still
  strictly ordered) or ``0`` (the original inline loop).
* A loader exception is captured by the staging pool and re-raised on the
  consumer thread at the failing slab's position in the stream; in-flight
  stages are cancelled, nothing hangs.
* :func:`maybe_donate` jits step programs with ``donate_argnums`` on the
  carry state so every step reuses the accumulator HBM instead of
  allocating a fresh dense ``(…, size)`` buffer set per slab — with a
  probed fallback for platforms/versions that reject donation (the probe
  result is memoized per backend in ``_DONATION_OK``, cleared by
  ``cache.clear_all``).
* :class:`DispatchThrottle` bounds dispatch depth: with prefetch feeding an
  async device, nothing otherwise stops K slabs (plus their staged copies)
  from piling up in HBM; every ``OPTIONS["stream_dispatch_depth"]`` steps
  the throttle blocks on the carry, capping in-flight slabs.

Per-slab load/stage/wait/dispatch timings flow into
:mod:`flox_tpu.profiling` (``stream_monitor`` / ``StreamReport``), including
an overlap fraction — the share of staging wall hidden off the consumer's
critical path.
"""

from __future__ import annotations

import math
import threading
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field
from time import perf_counter
from typing import Any, Callable, Iterator

import numpy as np

__all__ = [
    "Slab",
    "SlabStager",
    "stream_slabs",
    "maybe_donate",
    "donation_supported",
    "DispatchThrottle",
]

# backend name -> whether buffer donation actually works there (probed once;
# a set_options(stream_donate=...) override bypasses it). Registered in
# cache.clear_all with the other module-level caches.
_DONATION_OK: dict[str, bool] = {}

# process-wide prefetch-pool occupancy: slabs currently in flight (staging
# or staged-awaiting-consumption) summed over every live _SlabPrefetcher.
# The saturation sampler (telemetry.sample_saturation) publishes it as the
# stream.prefetch_occupancy gauge — a drained pool under a stalled stream
# is the "loader-bound" verdict at a glance. Single-element list so
# cache.clear_all can reset it in place (same idiom as
# factorize._FACTORIZE_CACHE_BYTES).
_PREFETCH_INFLIGHT: list[int] = [0]
_PREFETCH_LOCK = threading.Lock()


def prefetch_occupancy() -> int:
    """How many slabs the prefetch pools hold in flight right now."""
    return max(0, _PREFETCH_INFLIGHT[0])


def _prefetch_track(delta: int) -> None:
    with _PREFETCH_LOCK:
        _PREFETCH_INFLIGHT[0] += delta


@dataclass
class Slab:
    """One staged slab: device-resident data/codes plus host metadata."""

    index: int
    start: int
    stop: int
    data: Any
    codes: Any
    codes_host: np.ndarray
    offset: Any = None
    load_ms: float = 0.0
    stage_ms: float = 0.0
    wait_ms: float = 0.0
    dispatch_ms: float = 0.0


class SlabStager:
    """The ONE staging implementation: load an arbitrary ``[s, e)`` range,
    check the loader contract, pad, and ``device_put`` against the stream's
    shardings — with transient failures retried under the stream's
    ``RetryPolicy`` (``stream_retries`` / ``stream_backoff`` /
    ``stream_slab_timeout``, frozen at stager construction).

    :func:`stream_slabs` stages its batches through this, and
    ``resilience.dispatch_slab`` re-stages OOM-split sub-slabs through the
    SAME object — so split staging cannot drift from stream staging.
    Retries run inside whatever thread stages the slab (the prefetch pool's
    workers), so a flaky slab never poisons the other queued slabs; a fatal
    classification, retry exhaustion, or a blown per-slab deadline raises.
    """

    def __init__(
        self,
        loader: Callable[[int, int], Any],
        codes: np.ndarray,
        *,
        n: int,
        batch_len: int,
        lead_shape: tuple,
        pad: bool = True,
        slab_shard: Any = None,
        codes_shard: Any = None,
        with_offset: bool = False,
        counters: Any = None,
    ) -> None:
        from .resilience import RetryPolicy

        self.loader = loader
        self.codes = codes
        self.n = n
        self.batch_len = batch_len
        self.lead = tuple(lead_shape)
        self.pad = pad
        self.slab_shard = slab_shard
        self.codes_shard = codes_shard
        self.with_offset = with_offset
        self.counters = counters
        self.policy = RetryPolicy.from_options()
        self._dtype0: Any = None
        self._lock = threading.Lock()
        # the stream's trace context, frozen at stager construction: the
        # prefetch pool's worker threads do NOT inherit the consumer's
        # contextvars, so stage spans and retry events re-bind it per call
        # — one request's streaming activity stays joinable by trace id
        from . import telemetry

        self._trace_id = telemetry.current_trace()

    def stage_index(self, i: int) -> Slab:
        s, e = i * self.batch_len, min((i + 1) * self.batch_len, self.n)
        return self.stage_range(
            s, e, pad_to=self.batch_len if self.pad else None, index=i
        )

    def stage_range(self, s: int, e: int, pad_to: int | None = None, index: int = -1) -> Slab:
        from . import telemetry
        from .resilience import call_with_retry

        def _staged() -> Slab:
            return call_with_retry(
                lambda: self._stage_once(s, e, pad_to, index),
                policy=self.policy, counters=self.counters, what=f"[{s}:{e})",
            )

        if self._trace_id is None or telemetry.current_trace() is not None:
            return _staged()
        # worker thread with no trace of its own: rebind the stream's.
        # observe=False — only the root trace feeds the tail-sampling
        # histogram; this binding just tags records and parks detail
        with telemetry.trace(self._trace_id, observe=False):
            return _staged()

    def _stage_once(self, s: int, e: int, pad_to: int | None, index: int) -> Slab:
        import jax
        import jax.numpy as jnp

        t0 = perf_counter()
        slab = np.asarray(self.loader(s, e))
        self._check_contract(slab, s, e)
        chost = self.codes[s:e]
        t1 = perf_counter()
        padn = (pad_to - (e - s)) if pad_to else 0
        if padn:
            slab = np.concatenate(
                [slab, np.zeros(self.lead + (padn,), slab.dtype)], axis=-1
            )
            cfull = np.concatenate([chost, np.full(padn, -1, dtype=chost.dtype)])
        else:
            cfull = chost
        if self.slab_shard is not None:
            # one host->N-device scatter per slab: each chip receives and
            # reduces its contiguous 1/ndev of the slab
            data = jax.device_put(slab, self.slab_shard)
            cdev = jax.device_put(cfull, self.codes_shard)
        else:
            data, cdev = jnp.asarray(slab), jnp.asarray(cfull)
        offset = jnp.asarray(np.int64(s)) if self.with_offset else None
        t2 = perf_counter()
        from . import telemetry

        if telemetry.enabled():
            telemetry.METRICS.inc("bytes.h2d", int(slab.nbytes) + int(cfull.nbytes))
            if telemetry.tail_detail():
                # staging runs on the prefetch workers: standalone spans,
                # interleaved with the consumer's stream span by timestamp.
                # detail=True: at level="basic" inside a trace these park on
                # the trace and survive only when it blows its running p99
                telemetry.record_span(
                    "stage", t0, t2, attrs={"start": s, "stop": e, "index": index},
                    detail=True,
                )
        return Slab(
            index=index, start=s, stop=e, data=data, codes=cdev, codes_host=chost,
            offset=offset, load_ms=(t1 - t0) * 1e3, stage_ms=(t2 - t1) * 1e3,
        )

    def _check_contract(self, slab: np.ndarray, s: int, e: int) -> None:
        """Loader-contract check: a drifting shape or dtype raises a clear
        ValueError naming the slab range HERE, instead of a cryptic XLA
        shape error (or a silent retrace) deep inside the jitted step.
        ValueError is classified fatal, so a contract break never burns
        retries."""
        want = self.lead + (e - s,)
        if tuple(slab.shape) != want:
            raise ValueError(
                f"loader contract violation for slab [{s}:{e}): returned shape "
                f"{tuple(slab.shape)}, expected {want} (lead dims {self.lead} "
                "+ the requested span)"
            )
        with self._lock:
            if self._dtype0 is None:
                self._dtype0 = slab.dtype
            elif slab.dtype != self._dtype0:
                raise ValueError(
                    f"loader contract violation for slab [{s}:{e}): dtype "
                    f"{slab.dtype} != {self._dtype0} from the first loaded slab"
                )


def stream_slabs(
    loader: Callable[[int, int], Any],
    codes: np.ndarray,
    *,
    n: int,
    batch_len: int,
    lead_shape: tuple,
    pad: bool = True,
    reverse: bool = False,
    slab_shard: Any = None,
    codes_shard: Any = None,
    with_offset: bool = False,
    prefetch: int | None = None,
    label: str = "",
    skip: int = 0,
    counters: Any = None,
    stager: SlabStager | None = None,
) -> Iterator[Slab]:
    """Yield staged :class:`Slab` objects for every batch of ``[0, n)``.

    ``codes`` must be the full-span contiguous host code array (int32 —
    the entry points precompute it once, so per-slab slices are zero-copy
    contiguous views). With ``slab_shard``/``codes_shard`` the device copy
    is a sharded ``jax.device_put``; otherwise a plain ``jnp.asarray``.
    ``pad=False`` keeps the tail slab ragged (the single-device scan
    contract); ``reverse`` streams the slabs back-to-front (bfill).
    ``prefetch=None`` reads ``OPTIONS["stream_prefetch"]``; ``0`` is the
    synchronous inline loop, byte-identical staging either way.

    ``skip`` drops the first k slabs in STREAM order (checkpoint resume —
    for a reversed stream that is the last k batches, exactly the ones a
    resumed bfill already folded). ``counters`` is the run's
    ``resilience.StreamCounters``, attached to the emitted ``StreamReport``
    and fed by the staging retries. ``stager`` supplies a pre-built
    :class:`SlabStager` (the entry points share it with the OOM splitter);
    when given, its staging parameters win over the ones passed here.
    """
    from .options import OPTIONS
    from .profiling import StreamReport, record_stream

    depth = OPTIONS["stream_prefetch"] if prefetch is None else prefetch
    if prefetch is None and OPTIONS["autotune"]:
        from .options import explicitly_set

        if not explicitly_set("stream_prefetch"):
            # observed-best depth for this size band — but ONLY while the
            # depth rides its built-in default: an env mirror or
            # set_options(stream_prefetch=...) is an explicit user choice
            # the tuner never second-guesses. Prefetch changes only when
            # staging happens, never what bytes land on device, so the
            # adaptive depth keeps the bit-identity contract.
            from .autotune import pick_stream_prefetch

            nelems_total = n * int(np.prod(lead_shape)) if lead_shape else n
            depth = pick_stream_prefetch(depth, nelems=nelems_total)
    nbatches = math.ceil(n / batch_len) if n else 0
    order_full = range(nbatches - 1, -1, -1) if reverse else range(nbatches)
    order = order_full[skip:] if skip else order_full

    if stager is not None and (n, batch_len, pad) != (stager.n, stager.batch_len, stager.pad):
        # the stager's staging parameters are the ones that run; a caller
        # whose explicit arguments drifted from them must hear about it
        raise ValueError(
            "stream_slabs staging parameters disagree with the supplied "
            f"stager: (n, batch_len, pad) = {(n, batch_len, pad)} vs "
            f"{(stager.n, stager.batch_len, stager.pad)}"
        )
    if stager is None:
        stager = SlabStager(
            loader, codes, n=n, batch_len=batch_len, lead_shape=tuple(lead_shape),
            pad=pad, slab_shard=slab_shard, codes_shard=codes_shard,
            with_offset=with_offset, counters=counters,
        )
    stage = stager.stage_index

    report = StreamReport(label=label, prefetch=depth, nbatches=nbatches, counters=counters)
    source: Iterator[Slab]
    prefetcher = None
    if depth > 0 and len(order) > 1:
        prefetcher = _SlabPrefetcher(stage, order, depth)
        source = iter(prefetcher)
    else:
        source = (stage(i) for i in order)

    from . import telemetry

    # cost-ledger baseline: compiles the pass provokes are the delta of the
    # process-wide jax counters across it. tm_cost remembers whether the
    # baseline was actually taken — telemetry toggled on mid-stream must
    # not attribute the process-lifetime compile totals to this one pass
    compiles0 = compile_ms0 = 0.0
    tm_cost = telemetry.enabled()
    if tm_cost:
        compiles0 = telemetry.METRICS.get("jax.compiles")
        compile_ms0 = telemetry.METRICS.get("jax.compile_ms")
    t_begin = perf_counter()
    try:
        while True:
            t0 = perf_counter()
            try:
                slab = next(source)
            except StopIteration:
                break
            # synchronous path: the whole load+stage ran inside next() on
            # this thread, so wait == the staging cost on the critical path
            slab.wait_ms = (perf_counter() - t0) * 1e3
            t_yield = perf_counter()
            yield slab
            slab.dispatch_ms = (perf_counter() - t_yield) * 1e3
            # the report keeps the Slab for its timings only: drop the
            # device references so finished slabs don't stay pinned in HBM
            # for the rest of the stream
            slab.data = slab.codes = slab.offset = None
            report.slabs.append(slab)
    finally:
        if prefetcher is not None:
            prefetcher.close()
        t_end = perf_counter()
        report.wall_ms = (t_end - t_begin) * 1e3
        record_stream(report)
        # feed the autotune store (record-only safe): throughput per
        # prefetch depth and slab band, plus the overlap fraction — the
        # StreamReport signal ROADMAP item 4 names
        nbytes_staged = 0
        if report.slabs and stager._dtype0 is not None:
            from .autotune import observe_stream

            lead_elems = int(np.prod(lead_shape)) if lead_shape else 1
            span_elems = lead_elems * sum(s.stop - s.start for s in report.slabs)
            nbytes_staged = span_elems * np.dtype(stager._dtype0).itemsize
            observe_stream(report, nbytes=nbytes_staged, nelems=n * lead_elems)
        if telemetry.enabled():
            prog = f"stream[{label}]" if label else "stream"
            # HBM pressure right after the pass — in-flight slabs + carry
            # state is exactly when a streaming run's footprint peaks
            telemetry.sample_hbm(program=prog)
            # the pass's row in the cost ledger: dispatch wall (the
            # device-time proxy), bytes staged, compiles provoked. Only
            # when the baseline was taken at pass start (tm_cost) — else
            # the compile delta would be the process-lifetime totals.
            if tm_cost:
                telemetry.observe_cost(
                    prog,
                    device_ms=report.dispatch_ms,
                    nbytes=nbytes_staged,
                    compiles=int(telemetry.METRICS.get("jax.compiles") - compiles0),
                    compile_ms=telemetry.METRICS.get("jax.compile_ms") - compile_ms0,
                )
            # one span per streaming pass, carrying the StreamReport totals
            # as attributes — the report object stays the programmatic API,
            # the span is its trace-file view
            telemetry.record_span(
                f"stream[{label}]" if label else "stream", t_begin, t_end,
                attrs={
                    "slabs": len(report.slabs), "nbatches": nbatches,
                    "prefetch": depth, "skip": skip,
                    "load_ms": round(report.load_ms, 3),
                    "stage_ms": round(report.stage_ms, 3),
                    "wait_ms": round(report.wait_ms, 3),
                    "dispatch_ms": round(report.dispatch_ms, 3),
                    "overlap_fraction": round(report.overlap_fraction, 4),
                    "retries": report.retries,
                    "oom_splits": report.oom_splits,
                    "checkpoints": report.checkpoints,
                },
            )


class _SlabPrefetcher:
    """Bounded in-order prefetch over a staging function.

    At most ``depth`` slabs are in flight at once (the pool has ``depth``
    threads and the pending deque never grows past it), delivered strictly
    in stream order. A staging exception re-raises on the consumer thread
    at its position in the stream; ``close`` cancels everything pending so
    an abandoned stream leaves no worker behind.
    """

    def __init__(self, stage: Callable[[int], Slab], indices: Any, depth: int) -> None:
        self._stage = stage
        self._indices = iter(indices)
        self._pending: deque[Future] = deque()
        self._pool: ThreadPoolExecutor | None = ThreadPoolExecutor(
            max_workers=depth, thread_name_prefix="flox-tpu-stage"
        )
        for _ in range(depth):
            self._submit_next()

    def _submit_next(self) -> None:
        if self._pool is None:
            return
        try:
            i = next(self._indices)
        except StopIteration:
            return
        self._pending.append(self._pool.submit(self._stage, i))
        _prefetch_track(1)

    def __iter__(self) -> "_SlabPrefetcher":
        return self

    def __next__(self) -> Slab:
        if not self._pending:
            self.close()
            raise StopIteration
        fut = self._pending.popleft()
        _prefetch_track(-1)
        self._submit_next()
        try:
            return fut.result()
        except BaseException:
            # the loader (or device_put) failed for this slab: surface it
            # NOW on the consumer thread and tear the pipeline down
            self.close()
            raise

    def close(self) -> None:
        if self._pool is None:
            return
        for fut in self._pending:
            fut.cancel()
        _prefetch_track(-len(self._pending))
        self._pending.clear()
        self._pool.shutdown(wait=False, cancel_futures=True)
        self._pool = None


def donation_supported() -> bool:
    """Whether step programs should donate their carry buffers.

    ``OPTIONS["stream_donate"]``: ``"on"``/``"off"`` force it; ``"auto"``
    probes the active backend once — a platform that cannot alias donated
    buffers emits the jax donation warning (older CPU backends) or raises,
    and the fallback keeps the undonated path.
    """
    from .options import OPTIONS

    mode = OPTIONS["stream_donate"]
    if mode == "off":
        return False
    if mode == "on":
        return True
    import jax

    backend = jax.default_backend()
    ok = _DONATION_OK.get(backend)
    if ok is None:
        ok = _probe_donation()
        _DONATION_OK[backend] = ok
    return ok


def _probe_donation() -> bool:
    import warnings

    import jax
    import jax.numpy as jnp

    probe = jax.jit(lambda acc, x: acc + x, donate_argnums=(0,))
    try:
        with warnings.catch_warnings(record=True) as captured:
            warnings.simplefilter("always")
            jax.block_until_ready(probe(jnp.zeros(8), jnp.ones(8)))
        return not any("donat" in str(w.message).lower() for w in captured)
    except Exception:
        return False


def maybe_donate(fun: Callable, *, donate_argnums: tuple[int, ...]) -> Callable:
    """``jax.jit(fun, donate_argnums=...)`` when the platform supports
    donation, plain ``jax.jit(fun)`` otherwise. Streaming step programs
    thread their carry through a donated argnum so the dense ``(…, size)``
    accumulators are updated in place across slabs instead of reallocated
    per step. Callers must treat the passed-in carry as consumed (every
    streaming loop already rebinds it to the step's return)."""
    import jax

    if donation_supported():
        return jax.jit(fun, donate_argnums=donate_argnums)
    return jax.jit(fun)


@dataclass
class DispatchThrottle:
    """Bound the number of in-flight slab steps.

    Async dispatch + prefetch means nothing else limits how many dispatched
    slabs (and their staged device copies) can stack up in HBM when the
    host runs ahead of the device. Every ``depth`` ticks the throttle
    blocks until the carry is ready, draining the dispatch queue. ``0``
    disables it. ``depth=None`` reads ``OPTIONS["stream_dispatch_depth"]``
    at construction."""

    depth: int | None = None
    _ticks: int = field(default=0, repr=False)

    def __post_init__(self) -> None:
        if self.depth is None:
            from .options import OPTIONS

            self.depth = OPTIONS["stream_dispatch_depth"]

    def tick(self, carry: Any) -> None:
        if not self.depth or carry is None:
            return
        self._ticks += 1
        if self._ticks % self.depth == 0:
            import jax

            jax.block_until_ready(carry)
