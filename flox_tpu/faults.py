"""Deterministic fault injection for the streaming executor (test substrate).

Resilience claims are only as good as the faults they were tested against,
and real faults (S3 throttling, HBM exhaustion, preemption) are neither
deterministic nor available on CPU CI. This module injects them exactly
where the resilience layer must handle them:

* :class:`FlakyLoader` wraps a loader callable and raises a chosen
  exception for chosen slab start offsets a fixed number of times before
  recovering — the substrate for the retry/backoff tests (transient
  ``IOError`` retried; fatal ``ValueError`` surfaced immediately; a fault
  repeated past ``stream_retries`` surfacing the original).
* :func:`inject` installs a dispatch-side fault plan consulted by
  ``resilience.dispatch_slab`` immediately before each slab step runs
  (:func:`poke`): :class:`SimulatedOOM` at chosen slab starts (exercises
  the halve-and-re-stage ladder, recursively when ``times > 1``), and
  :class:`StreamKilled` at a chosen slab start or after a chosen number of
  dispatches (simulated host preemption — exercises checkpoint/resume).

Everything is index-deterministic: the same plan against the same stream
fires at the same slabs in the same order, prefetch on or off. The plan
hook costs one ``is None`` check per slab when no plan is installed.
"""

from __future__ import annotations

import contextlib
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator

import numpy as np

__all__ = [
    "SimulatedOOM",
    "StreamKilled",
    "FlakyLoader",
    "inject",
    "poke",
    "active",
    "misshaping_loader",
]


class SimulatedOOM(RuntimeError):
    """Stands in for jaxlib's ``XlaRuntimeError: RESOURCE_EXHAUSTED``: the
    message carries the status token, so ``resilience.classify_error``
    routes it down the same slab-splitting path as the real thing."""

    def __init__(self, where: str = "") -> None:
        super().__init__(f"RESOURCE_EXHAUSTED (simulated): out of memory {where}".rstrip())


class StreamKilled(RuntimeError):
    """Simulated host preemption: classified fatal (never retried, never
    split), so the stream dies exactly as a killed process would — leaving
    only the checkpoint behind."""

    def __init__(self, where: str = "") -> None:
        super().__init__(f"stream killed (simulated preemption) {where}".rstrip())


@dataclass
class _Fault:
    exc: type[BaseException]
    times: int  # remaining firings; -1 = always


@dataclass
class _Plan:
    """One installed dispatch-fault plan, with an injection log for
    asserting determinism."""

    at_start: dict[int, _Fault] = field(default_factory=dict)
    kill_after: int | None = None
    pokes: int = 0
    #: (exc name | None, start, stop) per dispatch, in dispatch order
    log: list = field(default_factory=list)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)


_PLAN: _Plan | None = None


def active() -> bool:
    return _PLAN is not None


def poke(start: int, stop: int) -> None:
    """Dispatch-side injection hook: ``resilience.dispatch_slab`` calls this
    immediately before running (or re-running, for split sub-slabs) a slab
    step. No-op unless a plan is installed via :func:`inject`."""
    plan = _PLAN
    if plan is None:
        return
    with plan._lock:
        plan.pokes += 1
        if plan.kill_after is not None and plan.pokes > plan.kill_after:
            plan.log.append(("StreamKilled", start, stop))
            raise StreamKilled(f"at dispatch #{plan.pokes}, slab [{start}:{stop})")
        fault = plan.at_start.get(start)
        if fault is not None and fault.times != 0:
            if fault.times > 0:
                fault.times -= 1
            plan.log.append((fault.exc.__name__, start, stop))
            raise fault.exc(f"at slab [{start}:{stop})")
        plan.log.append((None, start, stop))


@contextlib.contextmanager
def inject(
    *,
    oom_at: tuple[int, ...] | list[int] = (),
    oom_times: int = 1,
    kill_at: tuple[int, ...] | list[int] = (),
    kill_after: int | None = None,
) -> Iterator[_Plan]:
    """Install a deterministic dispatch-side fault plan for the scope.

    ``oom_at``: slab START offsets (elements, not indices) whose dispatch
    raises :class:`SimulatedOOM`, each ``oom_times`` times — ``times > 1``
    re-fires on the first re-staged sub-slab (same start offset), driving
    the splitter one rung deeper per firing. ``kill_at``: starts whose
    dispatch raises :class:`StreamKilled` once. ``kill_after``: kill at
    dispatch number ``kill_after + 1`` regardless of position (the way to
    land inside a chosen quantile pass). Yields the plan; its ``log``
    records every dispatch for determinism assertions.
    """
    global _PLAN
    plan = _Plan(kill_after=kill_after)
    for s in oom_at:
        plan.at_start[int(s)] = _Fault(SimulatedOOM, oom_times)
    for s in kill_at:
        plan.at_start[int(s)] = _Fault(StreamKilled, 1)
    prev = _PLAN
    _PLAN = plan
    try:
        yield plan
    finally:
        _PLAN = prev


class FlakyLoader:
    """Wrap a loader so chosen slabs fail a fixed number of times.

    ``faults`` maps slab START offsets (the ``start`` argument the stream
    passes the loader) to the exception to raise — an exception type
    (instantiated with a descriptive message), an instance (raised as-is),
    or a zero-arg factory. Each entry fires ``times`` times, then the
    loader recovers and serves the real bytes — the shape of a transient
    IO fault. Thread-safe (the prefetch pool loads concurrently);
    ``calls`` and ``injected`` record every access in call order.

    >>> flaky = FlakyLoader(loader, {2048: IOError}, times=2)  # doctest: +SKIP
    """

    def __init__(
        self,
        loader: Callable[[int, int], Any],
        faults: dict[int, Any],
        *,
        times: int = 1,
    ) -> None:
        self._loader = loader
        self._faults = {int(s): [spec, times] for s, spec in faults.items()}
        self._lock = threading.Lock()
        self.calls: list[tuple[int, int]] = []
        self.injected: list[tuple[int, int, str]] = []

    def _build(self, spec: Any, s: int, e: int) -> BaseException:
        if isinstance(spec, BaseException):
            return spec
        if isinstance(spec, type) and issubclass(spec, BaseException):
            return spec(f"injected loader fault at slab [{s}:{e})")
        return spec()

    def __call__(self, s: int, e: int) -> Any:
        with self._lock:
            self.calls.append((s, e))
            entry = self._faults.get(s)
            if entry is not None and entry[1] != 0:
                if entry[1] > 0:
                    entry[1] -= 1
                exc = self._build(entry[0], s, e)
                self.injected.append((s, e, type(exc).__name__))
                raise exc
        return self._loader(s, e)

    def loads_of(self, start: int) -> int:
        """How many times the underlying slab at ``start`` was actually
        requested (fault firings included)."""
        return sum(1 for (s, _e) in self.calls if s == start)


def misshaping_loader(
    loader: Callable[[int, int], Any], at: int, shape: tuple
) -> Callable[[int, int], Any]:
    """A loader that returns a wrong-shaped array for the slab starting at
    ``at`` — the substrate for the loader-contract check (a clear
    ``ValueError`` naming the slab range, not a cryptic XLA shape error)."""

    def bad(s: int, e: int) -> Any:
        out = np.asarray(loader(s, e))
        if s == at:
            return np.zeros(shape, out.dtype)
        return out

    return bad
