"""Deterministic fault injection for the streaming executor (test substrate).

Resilience claims are only as good as the faults they were tested against,
and real faults (S3 throttling, HBM exhaustion, preemption) are neither
deterministic nor available on CPU CI. This module injects them exactly
where the resilience layer must handle them:

* :class:`FlakyLoader` wraps a loader callable and raises a chosen
  exception for chosen slab start offsets a fixed number of times before
  recovering — the substrate for the retry/backoff tests (transient
  ``IOError`` retried; fatal ``ValueError`` surfaced immediately; a fault
  repeated past ``stream_retries`` surfacing the original).
* :func:`inject` installs a dispatch-side fault plan consulted by
  ``resilience.dispatch_slab`` immediately before each slab step runs
  (:func:`poke`): :class:`SimulatedOOM` at chosen slab starts (exercises
  the halve-and-re-stage ladder, recursively when ``times > 1``), and
  :class:`StreamKilled` at a chosen slab start or after a chosen number of
  dispatches (simulated host preemption — exercises checkpoint/resume).
* :func:`serve_inject` installs the SERVE-level fault plan consulted by
  ``serve.dispatcher.Dispatcher._execute`` immediately before each device
  dispatch (:func:`serve_poke`): poison one micro-batch member by payload
  digest (drives the request-quarantine bisection — the fault re-fires for
  every sub-batch still containing the poisoned leaf), fail compiles for a
  chosen program label (drives the per-program circuit breaker),
  :class:`SimulatedDeviceLoss` at dispatch N (drives backend recovery),
  and hang a chosen dispatch (drives the dispatch watchdog).
* :func:`slo_inject` installs the SLO-plane plan ``flox_tpu.slo``
  consults: a controllable clock plus synthetic SLI event bursts (so the
  multi-window burn-rate alert lifecycle walks in test time, not wall
  time) and canary-response corruption (so CI proves a silent wrong
  answer is caught as a correctness-SLO breach).

Everything is index-deterministic: the same plan against the same stream
fires at the same slabs in the same order, prefetch on or off. The plan
hook costs one ``is None`` check per slab when no plan is installed.

* :func:`stress_schedule` is the scheduling analogue: instead of injecting
  a fault it injects *adversarial thread interleavings* — the switch
  interval drops to ~1 µs so the microscopic race windows the GIL
  normally hides get hit within a test run, and (optionally) the
  module-level locks of named ``flox_tpu`` modules are wrapped in
  acquisition-order-asserting proxies that raise
  :class:`LockOrderViolation` at the exact acquire completing an
  inversion. CI's schedule-stress leg re-runs the serve-chaos and fleet
  suites under it (``FLOX_TPU_STRESS_SCHEDULE=1``, hooked in
  ``tests/conftest.py``); the static complement is floxlint's
  FLX013/FLX014.
"""

from __future__ import annotations

import contextlib
import sys
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator

import numpy as np

__all__ = [
    "SimulatedOOM",
    "StreamKilled",
    "SimulatedDeviceLoss",
    "SimulatedCompileError",
    "FlakyLoader",
    "inject",
    "poke",
    "active",
    "dispatch_delay_inject",
    "dispatch_delay_poke",
    "dispatch_delay_active",
    "serve_inject",
    "serve_poke",
    "serve_active",
    "StoreWriteKilled",
    "store_inject",
    "store_poke",
    "store_active",
    "slo_inject",
    "slo_active",
    "slo_now",
    "slo_injected",
    "slo_canary_corrupt",
    "misshaping_loader",
    "stress_schedule",
    "LockOrderViolation",
]


class SimulatedOOM(RuntimeError):
    """Stands in for jaxlib's ``XlaRuntimeError: RESOURCE_EXHAUSTED``: the
    message carries the status token, so ``resilience.classify_error``
    routes it down the same slab-splitting path as the real thing."""

    def __init__(self, where: str = "") -> None:
        super().__init__(f"RESOURCE_EXHAUSTED (simulated): out of memory {where}".rstrip())


class StreamKilled(RuntimeError):
    """Simulated host preemption: classified fatal (never retried, never
    split), so the stream dies exactly as a killed process would — leaving
    only the checkpoint behind."""

    def __init__(self, where: str = "") -> None:
        super().__init__(f"stream killed (simulated preemption) {where}".rstrip())


class SimulatedDeviceLoss(RuntimeError):
    """Stands in for a PJRT device-loss ``XlaRuntimeError``: the message
    carries the ``DEVICE_LOST`` status token, so ``resilience.classify_error``
    routes it down the same backend-recovery path as the real thing."""

    def __init__(self, where: str = "") -> None:
        super().__init__(f"DEVICE_LOST (simulated): device lost {where}".rstrip())


class SimulatedCompileError(RuntimeError):
    """A deterministically-failing compile/dispatch: classified FATAL (no
    status token), the substrate for the request-quarantine and
    circuit-breaker chaos tests — never retried, never split."""

    def __init__(self, where: str = "") -> None:
        super().__init__(f"INVALID_PROGRAM (simulated): compile failed {where}".rstrip())


@dataclass
class _Fault:
    exc: type[BaseException]
    times: int  # remaining firings; -1 = always


@dataclass
class _Plan:
    """One installed dispatch-fault plan, with an injection log for
    asserting determinism."""

    at_start: dict[int, _Fault] = field(default_factory=dict)
    kill_after: int | None = None
    pokes: int = 0
    #: (exc name | None, start, stop) per dispatch, in dispatch order
    log: list = field(default_factory=list)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)


_PLAN: _Plan | None = None


def active() -> bool:
    return _PLAN is not None


def poke(start: int, stop: int) -> None:
    """Dispatch-side injection hook: ``resilience.dispatch_slab`` calls this
    immediately before running (or re-running, for split sub-slabs) a slab
    step. No-op unless a plan is installed via :func:`inject`."""
    plan = _PLAN
    if plan is None:
        return
    with plan._lock:
        plan.pokes += 1
        if plan.kill_after is not None and plan.pokes > plan.kill_after:
            plan.log.append(("StreamKilled", start, stop))
            raise StreamKilled(f"at dispatch #{plan.pokes}, slab [{start}:{stop})")
        fault = plan.at_start.get(start)
        if fault is not None and fault.times != 0:
            if fault.times > 0:
                fault.times -= 1
            plan.log.append((fault.exc.__name__, start, stop))
            raise fault.exc(f"at slab [{start}:{stop})")
        plan.log.append((None, start, stop))


@contextlib.contextmanager
def inject(
    *,
    oom_at: tuple[int, ...] | list[int] = (),
    oom_times: int = 1,
    kill_at: tuple[int, ...] | list[int] = (),
    kill_after: int | None = None,
) -> Iterator[_Plan]:
    """Install a deterministic dispatch-side fault plan for the scope.

    ``oom_at``: slab START offsets (elements, not indices) whose dispatch
    raises :class:`SimulatedOOM`, each ``oom_times`` times — ``times > 1``
    re-fires on the first re-staged sub-slab (same start offset), driving
    the splitter one rung deeper per firing. ``kill_at``: starts whose
    dispatch raises :class:`StreamKilled` once. ``kill_after``: kill at
    dispatch number ``kill_after + 1`` regardless of position (the way to
    land inside a chosen quantile pass). Yields the plan; its ``log``
    records every dispatch for determinism assertions.
    """
    global _PLAN
    plan = _Plan(kill_after=kill_after)
    for s in oom_at:
        plan.at_start[int(s)] = _Fault(SimulatedOOM, oom_times)
    for s in kill_at:
        plan.at_start[int(s)] = _Fault(StreamKilled, 1)
    prev = _PLAN
    _PLAN = plan
    try:
        yield plan
    finally:
        _PLAN = prev


class FlakyLoader:
    """Wrap a loader so chosen slabs fail a fixed number of times.

    ``faults`` maps slab START offsets (the ``start`` argument the stream
    passes the loader) to the exception to raise — an exception type
    (instantiated with a descriptive message), an instance (raised as-is),
    or a zero-arg factory. Each entry fires ``times`` times, then the
    loader recovers and serves the real bytes — the shape of a transient
    IO fault. Thread-safe (the prefetch pool loads concurrently);
    ``calls`` and ``injected`` record every access in call order.

    >>> flaky = FlakyLoader(loader, {2048: IOError}, times=2)  # doctest: +SKIP
    """

    def __init__(
        self,
        loader: Callable[[int, int], Any],
        faults: dict[int, Any],
        *,
        times: int = 1,
    ) -> None:
        self._loader = loader
        self._faults = {int(s): [spec, times] for s, spec in faults.items()}
        self._lock = threading.Lock()
        self.calls: list[tuple[int, int]] = []
        self.injected: list[tuple[int, int, str]] = []

    def _build(self, spec: Any, s: int, e: int) -> BaseException:
        if isinstance(spec, BaseException):
            return spec
        if isinstance(spec, type) and issubclass(spec, BaseException):
            return spec(f"injected loader fault at slab [{s}:{e})")
        return spec()

    def __call__(self, s: int, e: int) -> Any:
        with self._lock:
            self.calls.append((s, e))
            entry = self._faults.get(s)
            if entry is not None and entry[1] != 0:
                if entry[1] > 0:
                    entry[1] -= 1
                exc = self._build(entry[0], s, e)
                self.injected.append((s, e, type(exc).__name__))
                raise exc
        return self._loader(s, e)

    def loads_of(self, start: int) -> int:
        """How many times the underlying slab at ``start`` was actually
        requested (fault firings included)."""
        return sum(1 for (s, _e) in self.calls if s == start)


# ---------------------------------------------------------------------------
# dispatch-delay injection: the drift-sentinel substrate


@dataclass
class _DelayPlan:
    """A deterministic dispatch slowdown: program labels containing
    ``substr`` sleep ``seconds`` before their device dispatch ``times``
    times (-1 = always). The substrate for ``costmodel.drift_report``
    tests — the observed wall honestly diverges from the analytical model
    because the dispatch really was that slow."""

    substr: str = ""
    seconds: float = 0.0
    times: int = -1
    fired: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)


_DELAY_PLAN: _DelayPlan | None = None


def dispatch_delay_active() -> bool:
    return _DELAY_PLAN is not None


def dispatch_delay_poke(label: str) -> None:
    """Dispatch-side hook (``core.chunk_reduce`` calls this just before the
    eager bundle dispatch with its program label). No-op unless a plan is
    installed via :func:`dispatch_delay_inject`."""
    plan = _DELAY_PLAN
    if plan is None or plan.substr not in str(label):
        return
    with plan._lock:
        if plan.times == 0:
            return
        if plan.times > 0:
            plan.times -= 1
        plan.fired += 1
        seconds = plan.seconds
    import time

    time.sleep(seconds)


@contextlib.contextmanager
def dispatch_delay_inject(
    substr: str, seconds: float, *, times: int = -1
) -> Iterator[_DelayPlan]:
    """Install a deterministic dispatch-delay plan for the scope: every
    dispatch whose program label contains ``substr`` sleeps ``seconds``
    first, ``times`` times (-1 = for the whole scope). Yields the plan;
    ``fired`` counts the injected delays."""
    global _DELAY_PLAN
    plan = _DelayPlan(substr=str(substr), seconds=float(seconds), times=int(times))
    prev = _DELAY_PLAN
    _DELAY_PLAN = plan
    try:
        yield plan
    finally:
        _DELAY_PLAN = prev


# ---------------------------------------------------------------------------
# serve-level injection: the chaos substrate for the serve fault domain


@dataclass
class _ServePlan:
    """One installed serve-level fault plan, with an injection log for
    asserting determinism. Consulted by ``Dispatcher._execute`` via
    :func:`serve_poke` immediately before each device dispatch."""

    #: payload digest -> fault: a dispatch whose leaf set CONTAINS the
    #: digest raises — so the quarantine bisection keeps hitting it until
    #: the poisoned member dispatches alone
    poison: dict[str, _Fault] = field(default_factory=dict)
    #: program func label -> fault (fail-compile-for-program-key)
    fail_compile: dict[str, _Fault] = field(default_factory=dict)
    #: 1-based dispatch numbers that raise SimulatedDeviceLoss
    device_loss_at: frozenset = frozenset()
    #: 1-based dispatch numbers that hang for ``hang_seconds``
    hang_at: frozenset = frozenset()
    hang_seconds: float = 1.0
    dispatches: int = 0
    #: (kind | None, label, dispatch_no) per dispatch, in dispatch order
    log: list = field(default_factory=list)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)


_SERVE_PLAN: _ServePlan | None = None


def serve_active() -> bool:
    return _SERVE_PLAN is not None


def serve_poke(label: str, digests: tuple = ()) -> None:
    """Serve-dispatch injection hook: ``Dispatcher._execute`` calls this at
    the top of every device dispatch with the program's func label and the
    payload digests of the leaves being dispatched. No-op unless a plan is
    installed via :func:`serve_inject`. Hangs run OUTSIDE the plan lock so
    a concurrent healthy dispatch is never blocked by an injected hang."""
    plan = _SERVE_PLAN
    if plan is None:
        return
    hang = 0.0
    with plan._lock:
        plan.dispatches += 1
        n = plan.dispatches
        for digest in digests:
            fault = plan.poison.get(digest)
            if fault is not None and fault.times != 0:
                if fault.times > 0:
                    fault.times -= 1
                plan.log.append(("poison", label, n))
                raise fault.exc(f"poisoned member {digest[:8]} in dispatch #{n}")
        fault = plan.fail_compile.get(label)
        if fault is not None and fault.times != 0:
            if fault.times > 0:
                fault.times -= 1
            plan.log.append(("fail-compile", label, n))
            raise fault.exc(f"for program {label!r} at dispatch #{n}")
        if n in plan.device_loss_at:
            plan.log.append(("device-loss", label, n))
            raise SimulatedDeviceLoss(f"at dispatch #{n}")
        if n in plan.hang_at:
            plan.log.append(("hang", label, n))
            hang = plan.hang_seconds
        else:
            plan.log.append((None, label, n))
    if hang > 0:
        import time

        time.sleep(hang)


@contextlib.contextmanager
def serve_inject(
    *,
    poison_digests: tuple[str, ...] | list[str] = (),
    poison_times: int = -1,
    fail_compile_for: tuple[str, ...] | list[str] = (),
    fail_times: int = -1,
    device_loss_at: tuple[int, ...] | list[int] = (),
    hang_at: tuple[int, ...] | list[int] = (),
    hang_seconds: float = 1.0,
) -> Iterator[_ServePlan]:
    """Install a deterministic serve-level fault plan for the scope.

    ``poison_digests``: payload digests (``serve.dispatcher.payload_digest``
    of the request's array) whose every containing dispatch raises
    :class:`SimulatedCompileError` — the default ``times=-1`` keeps firing
    through the quarantine bisection until the poisoned member dispatches
    alone (and would fail a retry too, as a genuinely poisoned payload
    does). ``fail_compile_for``: program func labels whose dispatches raise
    :class:`SimulatedCompileError` ``fail_times`` times (-1 = always) — the
    circuit-breaker substrate. ``device_loss_at``: 1-based dispatch numbers
    that raise :class:`SimulatedDeviceLoss` once. ``hang_at``: 1-based
    dispatch numbers that sleep ``hang_seconds`` before executing — the
    watchdog substrate. Yields the plan; its ``log`` records every dispatch
    for determinism assertions.
    """
    global _SERVE_PLAN
    plan = _ServePlan(
        device_loss_at=frozenset(int(n) for n in device_loss_at),
        hang_at=frozenset(int(n) for n in hang_at),
        hang_seconds=float(hang_seconds),
    )
    for d in poison_digests:
        plan.poison[str(d)] = _Fault(SimulatedCompileError, poison_times)
    for label in fail_compile_for:
        plan.fail_compile[str(label)] = _Fault(SimulatedCompileError, fail_times)
    prev = _SERVE_PLAN
    _SERVE_PLAN = plan
    try:
        yield plan
    finally:
        _SERVE_PLAN = prev


# ---------------------------------------------------------------------------
# store-level injection: the chaos substrate for the durable store's
# kill-at-every-fault-point recovery matrix (flox_tpu/store.py)


class StoreWriteKilled(RuntimeError):
    """Simulated ``kill -9`` landing inside a durable store write: the
    process "dies" mid-append/mid-compaction, leaving whatever bytes the
    injected action put on disk. Never caught by the store itself — the
    test reopens the directory and asserts recovery."""

    def __init__(self, where: str = "") -> None:
        super().__init__(f"store write killed (simulated crash) {where}".rstrip())


@dataclass
class _StorePlan:
    """One installed store-fault plan, with an injection log for asserting
    determinism. Consulted by the store's durable-write funnel via
    :func:`store_poke` once per durable event (a journal fsync, a segment
    landing, a compaction-swap delete), in write order."""

    #: 1-based durable-write ordinals that die BEFORE any bytes land
    kill_at: frozenset = frozenset()
    #: ordinals whose write lands HALF its bytes at the final path, then dies
    torn_at: frozenset = frozenset()
    #: ordinals whose write lands fully but with one bit flipped (silent —
    #: the on-disk rot a checksum verify must catch at the next open)
    flip_at: frozenset = frozenset()
    #: restrict counting to one event kind ("journal"|"segment"|"swap");
    #: None counts every durable event
    op: str | None = None
    writes: int = 0
    #: (action | None, kind, basename, ordinal) per counted event, in order
    log: list = field(default_factory=list)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)


_STORE_PLAN: _StorePlan | None = None


def store_active() -> bool:
    return _STORE_PLAN is not None


def store_poke(kind: str, path: str) -> str | None:
    """Store durable-write injection hook: the store's write funnel calls
    this immediately before each durable event and acts on the answer —
    ``None`` (write normally), ``"kill"`` (raise before any bytes),
    ``"torn"`` (land half the bytes at the final path, then raise), or
    ``"flip"`` (land all bytes with one bit flipped, silently). The raise
    itself is the funnel's job so the torn/flip byte mangling happens at
    the real write site; :class:`StoreWriteKilled` is what it raises."""
    import os

    plan = _STORE_PLAN
    if plan is None:
        return None
    with plan._lock:
        if plan.op is not None and plan.op != kind:
            return None
        plan.writes += 1
        n = plan.writes
        action = None
        if n in plan.kill_at:
            action = "kill"
        elif n in plan.torn_at:
            action = "torn"
        elif n in plan.flip_at:
            action = "flip"
        plan.log.append((action, kind, os.path.basename(str(path)), n))
        return action


@contextlib.contextmanager
def store_inject(
    *,
    kill_at: tuple[int, ...] | list[int] = (),
    torn_at: tuple[int, ...] | list[int] = (),
    flip_at: tuple[int, ...] | list[int] = (),
    op: str | None = None,
) -> Iterator[_StorePlan]:
    """Install a deterministic store-fault plan for the scope.

    Ordinals are 1-based positions in the store's durable-write sequence
    (journal appends, segment landings, compaction-swap deletes — the
    exact fault points the recovery matrix must kill at), counted across
    the scope; ``op`` narrows the counting to one event kind. ``kill_at``
    dies before any bytes land; ``torn_at`` lands a half-written file at
    the FINAL path (the rename-happened-but-bytes-did-not-flush crash);
    ``flip_at`` lands a silent single-bit flip (detected only by the
    checksum verify at the next open). Yields the plan; its ``log``
    records every counted event for determinism assertions.
    """
    global _STORE_PLAN
    plan = _StorePlan(
        kill_at=frozenset(int(n) for n in kill_at),
        torn_at=frozenset(int(n) for n in torn_at),
        flip_at=frozenset(int(n) for n in flip_at),
        op=op,
    )
    prev = _STORE_PLAN
    _STORE_PLAN = plan
    try:
        yield plan
    finally:
        _STORE_PLAN = prev


@dataclass
class _SLOPlan:
    """One installed SLO-plane injection plan: a controllable clock plus
    synthetic SLI events, so the multi-window burn-rate math and the alert
    state machine (``flox_tpu.slo``) are testable without wall-clock
    sleeps. Consulted by ``slo._now`` (clock), ``slo._collect``
    (synthetic events) and the canary's bit-exact compare
    (``corrupt_canary`` — the injected wrong answer CI proves is caught)."""

    #: the plan's synthetic "now" (seconds); ``advance`` moves it forward.
    #: None leaves the real clock in charge (events-only plans)
    clock: float | None = None
    #: objective name -> [good, bad] cumulative synthetic SLI events,
    #: added on top of the real collectors by ``slo._collect``
    events: dict = field(default_factory=dict)
    #: canary op name (or "*") -> how many of its next comparisons to
    #: corrupt (-1 = every one)
    corrupt_canary: dict = field(default_factory=dict)
    #: ("burst"|"advance"|"corrupt", ...) per consulted event, in order
    log: list = field(default_factory=list)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def advance(self, seconds: float) -> float:
        """Move the synthetic clock forward; returns the new now."""
        with self._lock:
            if self.clock is None:
                raise ValueError("slo_inject plan has no clock (pass clock0=)")
            self.clock += float(seconds)
            self.log.append(("advance", float(seconds), self.clock))
            return self.clock

    def burst(self, objective: str, *, good: int = 0, bad: int = 0) -> None:
        """Add synthetic SLI events to ``objective``'s cumulative totals
        (they appear in every evaluation until the plan is uninstalled —
        uninstalling makes counters drop, which the window math clamps
        to zero burn, i.e. the incident ends)."""
        with self._lock:
            slot = self.events.setdefault(str(objective), [0, 0])
            slot[0] += int(good)
            slot[1] += int(bad)
            self.log.append(("burst", str(objective), int(good), int(bad)))


_SLO_PLAN: _SLOPlan | None = None


def slo_active() -> bool:
    return _SLO_PLAN is not None


def slo_now() -> float | None:
    """The installed plan's synthetic clock, or None when the real clock
    is in charge (no plan, or a plan without ``clock0``)."""
    plan = _SLO_PLAN
    if plan is None:
        return None
    with plan._lock:
        return plan.clock


def slo_injected(objective: str) -> tuple[int, int]:
    """Cumulative synthetic (good, bad) events for ``objective`` from the
    installed plan; (0, 0) with no plan."""
    plan = _SLO_PLAN
    if plan is None:
        return (0, 0)
    with plan._lock:
        slot = plan.events.get(str(objective))
        return (int(slot[0]), int(slot[1])) if slot else (0, 0)


def slo_canary_corrupt(op: str) -> bool:
    """Canary-corruption hook: True tells the prober's compare to perturb
    the received result (simulating silent wrong-answer corruption).
    Budgeted per op name ("*" matches any); -1 corrupts every compare."""
    plan = _SLO_PLAN
    if plan is None:
        return False
    with plan._lock:
        key = str(op) if str(op) in plan.corrupt_canary else "*"
        times = plan.corrupt_canary.get(key, 0)
        if times == 0:
            return False
        if times > 0:
            plan.corrupt_canary[key] = times - 1
        plan.log.append(("corrupt", str(op)))
        return True


@contextlib.contextmanager
def slo_inject(
    *,
    clock0: float | None = None,
    corrupt_canary: dict | tuple | list | None = None,
) -> Iterator[_SLOPlan]:
    """Install a deterministic SLO-plane injection plan for the scope.

    ``clock0`` seeds the synthetic clock ``slo.evaluate`` reads (advance
    it with ``plan.advance(seconds)`` to walk burn-rate windows without
    sleeping); ``corrupt_canary`` maps canary op names to how many of
    their next bit-exact compares to corrupt (a bare tuple/list corrupts
    each named op once; -1 = every compare). Synthetic SLI events are
    added with ``plan.burst(objective, good=..., bad=...)``. Yields the
    plan; its ``log`` records every consulted event in order.
    """
    global _SLO_PLAN
    plan = _SLOPlan(clock=float(clock0) if clock0 is not None else None)
    if corrupt_canary:
        if isinstance(corrupt_canary, dict):
            plan.corrupt_canary = {str(k): int(v) for k, v in corrupt_canary.items()}
        else:
            plan.corrupt_canary = {str(op): 1 for op in corrupt_canary}
    prev = _SLO_PLAN
    _SLO_PLAN = plan
    try:
        yield plan
    finally:
        _SLO_PLAN = prev


def misshaping_loader(
    loader: Callable[[int, int], Any], at: int, shape: tuple
) -> Callable[[int, int], Any]:
    """A loader that returns a wrong-shaped array for the slab starting at
    ``at`` — the substrate for the loader-contract check (a clear
    ``ValueError`` naming the slab range, not a cryptic XLA shape error)."""

    def bad(s: int, e: int) -> Any:
        out = np.asarray(loader(s, e))
        if s == at:
            return np.zeros(shape, out.dtype)
        return out

    return bad


# ---------------------------------------------------------------------------
# schedule-stress race harness
# ---------------------------------------------------------------------------


class LockOrderViolation(AssertionError):
    """An acquire completed a cycle in the observed lock acquisition order
    (or re-entered a non-reentrant lock on its own thread) — the static
    shape FLX014 flags, caught live at the exact acquire that closed it."""


_LOCK_TYPE = type(threading.Lock())
_RLOCK_TYPE = type(threading.RLock())


def _caller_site() -> str:
    """``path:line`` of the nearest frame outside this module — the acquire
    site a violation message points at."""
    frame = sys._getframe(2)
    while frame is not None and frame.f_globals.get("__name__") == __name__:
        frame = frame.f_back
    if frame is None:  # pragma: no cover - only when called at module top
        return "<unknown>"
    return f"{frame.f_code.co_filename}:{frame.f_lineno}"


class _LockOrderWatcher:
    """Cumulative acquisition-order graph fed by every proxied acquire.

    Each thread keeps a stack of proxied locks it holds; acquiring ``L``
    while holding ``H`` records the edge ``H -> L``. An acquire whose new
    edges would make the graph cyclic raises :class:`LockOrderViolation`
    *before* blocking on the underlying lock — the test fails with both
    witness sites instead of deadlocking the suite. Seeding with floxlint's
    ``--lock-graph`` JSON makes the static edges count as already-observed,
    so one runtime acquire against the static order is enough to fail."""

    def __init__(self, seed_edges: dict[tuple[str, str], str] | None = None):
        self._mu = threading.Lock()
        #: (held, acquired) -> first witness site ("path:line")
        self.edges: dict[tuple[str, str], str] = dict(seed_edges or {})
        self._tls = threading.local()

    # -- per-thread held stack ----------------------------------------------

    def _held(self) -> list[str]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    # -- graph --------------------------------------------------------------

    def _path(self, src: str, dst: str) -> list[str] | None:
        """One ``src -> … -> dst`` node path over current edges, or None."""
        parent: dict[str, str | None] = {src: None}
        frontier = [src]
        adj: dict[str, list[str]] = {}
        for a, b in self.edges:
            adj.setdefault(a, []).append(b)
        while frontier:
            cur = frontier.pop()
            if cur == dst:
                out = [cur]
                while parent[out[-1]] is not None:
                    out.append(parent[out[-1]])
                return out[::-1]
            for nxt in adj.get(cur, ()):
                if nxt not in parent:
                    parent[nxt] = cur
                    frontier.append(nxt)
        return None

    def before_acquire(self, name: str, reentrant: bool, site: str) -> None:
        held = self._held()
        if name in held:
            if reentrant:
                return
            raise LockOrderViolation(
                f"non-reentrant lock {name} re-acquired at {site} by the "
                "thread already holding it — guaranteed self-deadlock"
            )
        with self._mu:
            for h in held:
                if (h, name) in self.edges:
                    continue
                cycle = self._path(name, h)
                if cycle is not None:
                    ring = " -> ".join(cycle + [name])
                    first = self.edges.get(
                        (cycle[0], cycle[1]), "<seed>"
                    ) if len(cycle) > 1 else "<seed>"
                    raise LockOrderViolation(
                        f"lock-order inversion: acquiring {name} at {site} "
                        f"while holding {h}, but the established order is "
                        f"{ring} (first observed at {first}) — pick one "
                        "global order"
                    )
                self.edges[(h, name)] = site

    def after_acquire(self, name: str) -> None:
        self._held().append(name)

    def after_release(self, name: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return


class _OrderedLockProxy:
    """Drop-in wrapper for a module-level ``Lock``/``RLock`` that feeds the
    watcher on every acquire/release. Delegates to the wrapped lock, so
    code holding the raw lock across the wrap/unwrap boundary stays
    correct — the proxy and the original contend on the same object."""

    def __init__(
        self,
        inner: Any,
        name: str,
        watcher: _LockOrderWatcher,
        reentrant: bool,
    ) -> None:
        self._inner = inner
        self._name = name
        self._watcher = watcher
        self._reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._watcher.before_acquire(self._name, self._reentrant, _caller_site())
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._watcher.after_acquire(self._name)
        return ok

    def release(self) -> None:
        self._inner.release()
        self._watcher.after_release(self._name)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "_OrderedLockProxy":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<_OrderedLockProxy {self._name} of {self._inner!r}>"


def _seed_edges_from(order_graph: Any) -> dict[tuple[str, str], str]:
    """Accept floxlint's ``--lock-graph`` JSON (a dict, or a path to one)
    and return its edge table keyed the way the watcher keys it."""
    data = order_graph
    if isinstance(order_graph, (str, bytes)) or hasattr(order_graph, "read_text"):
        import json

        with open(order_graph) as fh:  # noqa: FLX015 — test harness setup, never on a serve loop
            data = json.load(fh)
    out: dict[tuple[str, str], str] = {}
    for edge in data.get("edges", []):
        out[(str(edge["from"]), str(edge["to"]))] = str(edge.get("site", "<static>"))
    return out


@contextlib.contextmanager
def stress_schedule(
    switch_interval: float = 1e-6,
    watch: tuple[str, ...] = (),
    order_graph: Any = None,
) -> Iterator[_LockOrderWatcher | None]:
    """Run the body under an adversarial thread schedule.

    Drops ``sys.setswitchinterval`` to ``switch_interval`` (default ~1 µs:
    a potential preemption every few bytecodes, so the race windows the
    default 5 ms interval hides get hit within one test run) and restores
    it on exit. When ``watch`` names modules (``"flox_tpu.telemetry"``,
    …), their module-level ``Lock``/``RLock`` attributes are wrapped in
    :class:`_OrderedLockProxy` for the duration: every acquire feeds a
    cumulative acquisition-order graph and an acquire that would complete
    a cycle — or re-enter a plain ``Lock`` on its own thread — raises
    :class:`LockOrderViolation` *before* blocking, so the suite fails
    with both witness sites instead of deadlocking. ``order_graph``
    optionally seeds the graph with floxlint's ``--lock-graph`` JSON
    (dict or path), making one runtime acquire against the static order
    sufficient to fail. Yields the watcher (None when nothing is
    watched); instance-attribute locks (``self._lock``) are out of scope.
    """
    import importlib

    watcher: _LockOrderWatcher | None = None
    if watch or order_graph is not None:
        seed = _seed_edges_from(order_graph) if order_graph is not None else None
        watcher = _LockOrderWatcher(seed)
    wrapped: list[tuple[Any, str, Any]] = []
    if watcher is not None:
        for mod_name in watch:
            mod = importlib.import_module(mod_name)
            for attr, value in list(vars(mod).items()):
                if isinstance(value, (_LOCK_TYPE, _RLOCK_TYPE)):
                    proxy = _OrderedLockProxy(
                        value,
                        f"{mod_name}.{attr}",
                        watcher,
                        isinstance(value, _RLOCK_TYPE),
                    )
                    setattr(mod, attr, proxy)
                    wrapped.append((mod, attr, value))
    prev = sys.getswitchinterval()
    sys.setswitchinterval(float(switch_interval))
    try:
        yield watcher
    finally:
        sys.setswitchinterval(prev)
        for mod, attr, value in wrapped:
            setattr(mod, attr, value)
