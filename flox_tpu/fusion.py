"""Single-pass multi-statistic fusion: ``groupby_aggregate_many`` (L4).

A climatology asking for ``{mean, std, min, max}`` over the same codes used
to stage and read the same bytes once PER STATISTIC and compile one program
each. flox's own ``Aggregation`` blueprint is explicitly multi-output (mean
is sum+count in one chunk pass — reference aggregations.py:161); this
module generalizes that to an arbitrary statistic set:

* The **fusion planner** (``aggregations.plan_fused``) merges the requested
  blueprints into one deduplicated multi-output chunk plan — sum/count feed
  mean AND var through the Chan triple's leaves, min/max ride free next to
  them, presence counts collapse to one leg.
* The **eager path** traces chunk legs + every per-statistic finalize into
  ONE jitted program (cached in :data:`_FUSED_PROGRAM_CACHE`); on the
  Pallas policy the legs collapse further into the multi-statistic
  megakernel (``pallas_kernels.segment_multistat_pallas``) — one HBM pass,
  all accumulators resident in VMEM.
* The **mesh path** runs the fused plan as one SPMD program under one
  ``_PROGRAM_CACHE`` key: one psum-combined collective serves all N
  statistics (``parallel.mapreduce`` consumes the plan through the same
  ``_local_chunk`` / ``_combine_intermediates`` contract as any agg).
* The **streaming path** (``streaming.streaming_groupby_aggregate_many``)
  folds the fused intermediates through the carry — an ERA5-style
  mean+std+extremes job is one streaming pass instead of four, with
  checkpoint/resume and OOM slab-splitting working on the fused carry.
* **Dispatch integration**: the ``"fused"`` autotune family arbitrates
  fused-vs-sequential from measured GB/s (bench.py's ``fused_sweep_gbps``
  seeds it), and the cost ledger bills the staged bytes exactly once under
  the fused program key.
"""

from __future__ import annotations

import logging
from typing import Any

import numpy as np

from . import cache, factorize as fct, telemetry, utils
from .aggregations import (
    FUSABLE_FUNCS,
    FusedAggregation,
    fused_chunk_stats,
    plan_fused,
)
from .options import OPTIONS

logger = logging.getLogger("flox_tpu.fusion")

__all__ = ["groupby_aggregate_many", "FUSABLE_FUNCS"]

#: compiled fused eager programs, keyed on the fused plan's semantic
#: identity (per-statistic fills/dtypes included) + size +
#: trace_fingerprint — the multi-output analogue of core._jitted_bundle.
#: LRU-bounded and registered in cache.clear_all (floxlint FLX08 pattern).
_FUSED_PROGRAM_CACHE: cache.LRUCache = cache.LRUCache(maxsize=256)


def fused_program_label(funcs) -> str:
    """The cost-ledger / serve program label of a fused statistic set."""
    return "fused[" + "+".join(funcs) + "]"


def store_program_label(kind: str, funcs) -> str:
    """The cost-ledger program label of a durable-store operation
    (``store.append[fused[sum+count]]``): the op kind wrapping the fused
    statistic set the store carries, so per-store ledger rows join the
    same program axis as inline fused dispatches."""
    return f"store.{kind}[{fused_program_label(funcs)}]"


def _fused_key(fused: FusedAggregation, size: int) -> tuple:
    from .options import trace_fingerprint
    from .parallel.mapreduce import _agg_cache_key

    return (_agg_cache_key(fused), size, trace_fingerprint())


def finalize_many(fused: FusedAggregation, results, out_shape=None) -> dict:
    """Per-statistic final dtype casts (+ reshape) -> ``{func: array}``,
    shared by the eager, mesh, and streaming drivers."""
    from .core import _astype_final

    out = {}
    for f, agg, r in zip(fused.funcs, fused.aggs, results):
        r = _astype_final(r, agg, None)
        if out_shape is not None and tuple(r.shape) != tuple(out_shape):
            r = r.reshape(out_shape)
        out[f] = r
    return out


def _sequential_fallback(
    array, bys, funcs, *, per_func_kw, common_kw
) -> tuple:
    """N independent ``groupby_reduce`` passes — the measured-loser branch
    of the fused-vs-sequential autotune family (and the reference
    behavior the fused path is benchmarked against)."""
    from .core import groupby_reduce

    results = {}
    groups: tuple = ()
    for f in funcs:
        r, *groups = groupby_reduce(
            array, *bys, func=f, **per_func_kw(f), **common_kw
        )
        results[f] = r
    return (results, *groups)


def groupby_aggregate_many(
    array: Any,
    *by: Any,
    funcs: "tuple | list" = ("sum", "count", "min", "max", "var"),
    expected_groups: Any = None,
    sort: bool = True,
    isbin: Any = False,
    axis: Any = None,
    fill_value: Any = None,
    dtype: Any = None,
    min_count: int | None = None,
    engine: str | None = None,
    finalize_kwargs: dict | None = None,
    method: str | None = None,
    mesh: Any = None,
    axis_name: str = "data",
) -> tuple:
    """N grouped statistics in ONE pass over the data.

    Returns ``(results, *groups)`` with ``results`` a dict mapping each
    requested func name to its array — each entry bit-identical to the
    corresponding sequential ``groupby_reduce(..., func=f)`` call on the
    same runtime, but the data is staged and read once for the whole set
    and exactly one program compiles per runtime.

    ``funcs``: names from :data:`FUSABLE_FUNCS` (the additive + extrema +
    variance families; argreductions and order statistics keep their
    sequential paths). ``fill_value`` / ``dtype`` / ``finalize_kwargs``
    accept either one value for all statistics or a per-func dict, e.g.
    ``finalize_kwargs={"var": {"ddof": 1}}``. ``method``/``mesh`` run the
    fused plan as one SPMD program (``method='map-reduce'``); for
    out-of-core data see ``streaming_groupby_aggregate_many``.

    Examples
    --------
    >>> import numpy as np
    >>> from flox_tpu import groupby_aggregate_many
    >>> values = np.array([1.0, 2.0, 4.0, 8.0])
    >>> labels = np.array([0, 0, 1, 1])
    >>> out, groups = groupby_aggregate_many(
    ...     values, labels, funcs=("sum", "max"), engine="numpy")
    >>> out["sum"]
    array([ 3., 12.])
    >>> out["max"]
    array([2., 8.])
    """
    with telemetry.span(
        "groupby_aggregate_many", funcs=list(funcs), method=method
    ):
        return _aggregate_many_impl(
            array, *by, funcs=tuple(funcs), expected_groups=expected_groups,
            sort=sort, isbin=isbin, axis=axis, fill_value=fill_value,
            dtype=dtype, min_count=min_count, engine=engine,
            finalize_kwargs=finalize_kwargs, method=method, mesh=mesh,
            axis_name=axis_name,
        )


def _aggregate_many_impl(
    array: Any,
    *by: Any,
    funcs: tuple,
    expected_groups: Any,
    sort: bool,
    isbin: Any,
    axis: Any,
    fill_value: Any,
    dtype: Any,
    min_count: int | None,
    engine: str | None,
    finalize_kwargs: dict | None,
    method: str | None,
    mesh: Any,
    axis_name: str,
) -> tuple:
    from .core import (
        _choose_engine,
        _convert_expected_groups_to_index,
        _normalize_expected,
        _normalize_isbin,
        _normalize_reduce_axes,
    )
    from .sparse import is_sparse_array

    if not by:
        raise TypeError("Must pass at least one `by`")
    if method not in (None, "map-reduce", "cohorts"):
        raise NotImplementedError(
            "groupby_aggregate_many supports method=None (eager) and "
            "'map-reduce'/'cohorts' on a mesh; 'blockwise' finalizes per "
            "shard through the single-statistic kernels — run sequential "
            "groupby_reduce calls there."
        )
    if is_sparse_array(array):
        raise NotImplementedError(
            "sparse inputs are not fusable; run sequential groupby_reduce calls"
        )

    nby = len(by)
    if nby == 1 and isinstance(by[0], fct.Prefactorized):
        # registry fast path: factorization happened at put_dataset time —
        # no factorize span, and the put-staged device codes feed the fused
        # program directly (zero codes H2D on the hit path)
        return _aggregate_many_prefactorized(
            array, by[0], funcs=funcs, expected_groups=expected_groups,
            isbin=isbin, axis=axis, fill_value=fill_value, dtype=dtype,
            min_count=min_count, engine=engine,
            finalize_kwargs=finalize_kwargs, method=method, mesh=mesh,
            axis_name=axis_name,
        )
    bys = [utils.asarray_host(b) for b in by]
    bys = list(np.broadcast_arrays(*bys)) if nby > 1 else bys
    array_is_jax = utils.is_jax_array(array)
    engine = _choose_engine(engine, array, array_is_jax)
    arr = array if array_is_jax else np.asarray(array)
    from . import dtypes as dtps

    arr_dtype = np.dtype(arr.dtype)
    if arr_dtype.kind in "OSUmM" or dtps.is_datetime_like(arr_dtype):
        raise NotImplementedError(
            f"groupby_aggregate_many supports numeric data; got {arr_dtype} "
            "(datetime/object inputs keep the sequential groupby_reduce path)"
        )
    if arr_dtype.kind == "b":
        # core's bool rule, set-wide: additive reductions need the int
        # view (segment add rejects bool); all/any/count are bool-native.
        # A set mixing bools into float/extrema statistics has no single
        # input view that matches every sequential call — reject it.
        addlike = {"sum", "nansum", "prod", "nanprod"}
        boolsafe = {"all", "any", "count"}
        if set(funcs) <= boolsafe:
            pass
        elif set(funcs) <= (addlike | boolsafe):
            arr = arr.astype(np.int64 if utils.x64_enabled() else np.int32)
        else:
            raise NotImplementedError(
                f"bool data fuses only {sorted(addlike | boolsafe)}; run "
                f"{sorted(set(funcs) - addlike - boolsafe)} sequentially"
            )

    from .core import _assert_by_is_aligned

    _assert_by_is_aligned(arr.shape, bys)
    expected = _normalize_expected(expected_groups, nby)
    isbin_t = _normalize_isbin(isbin, nby)
    expected_idx = _convert_expected_groups_to_index(expected, isbin_t, sort)

    arr, bys, n_keep, bndim = _normalize_reduce_axes(arr, bys, axis)
    keep_by_shape = tuple(bys[0].shape[:n_keep])

    with telemetry.span("factorize", nby=nby) as _fsp:
        codes, found_groups, grp_shape, ngroups, size, props = fct.factorize_cached(
            tuple(bys), axes=tuple(range(n_keep, bndim)),
            expected_groups=expected_idx, sort=sort,
        )
        _fsp.set(ngroups=ngroups, size=size)
    if ngroups == 0 or size == 0:
        raise ValueError("No groups to reduce over (empty expected_groups?)")

    min_count_ = 0 if min_count is None else min_count
    fused = plan_fused(funcs, dtype, arr.dtype, fill_value, min_count_, finalize_kwargs)

    # -- flatten for the kernels (the groupby_reduce contract) -------------
    span = int(np.prod(bys[0].shape)) if bys[0].size else 0
    lead_shape = arr.shape[: arr.ndim - bndim]
    arr_flat = arr.reshape(lead_shape + (span,))
    codes_flat = np.asarray(codes).reshape(-1)
    out_shape = lead_shape + keep_by_shape + grp_shape

    def per_func_kw(f):
        def pick(v):
            return v.get(f) if isinstance(v, dict) else v

        return {
            "fill_value": pick(fill_value), "dtype": pick(dtype),
            "finalize_kwargs": pick(finalize_kwargs), "min_count": min_count,
        }

    common_kw = {
        "expected_groups": expected_groups, "sort": sort, "isbin": isbin,
        "axis": axis, "engine": engine, "method": method, "mesh": mesh,
        "axis_name": axis_name,
    }

    # -- fused-vs-sequential dispatch (the "fused" autotune family) --------
    if OPTIONS["autotune"] and engine == "jax":
        from . import autotune

        nelems = int(np.prod(arr_flat.shape)) if arr_flat.ndim else 0
        choice = autotune.decide(
            "fused", "fused", ("fused", "sequential"),
            dtype=str(arr_flat.dtype), ngroups=size, nelems=nelems,
        )
        if choice == "sequential":
            logger.debug("fused autotune: sequential wins for this band")
            return _sequential_fallback(
                array, by, funcs, per_func_kw=per_func_kw, common_kw=common_kw
            )

    if method is not None or mesh is not None:
        # -- one SPMD program for the whole statistic set ------------------
        from .parallel.mapreduce import sharded_groupby_reduce

        with telemetry.span("combine", method=method or "map-reduce", size=size):
            results = sharded_groupby_reduce(
                arr_flat, codes_flat, fused, size=size, mesh=mesh,
                axis_name=axis_name, method=method or "map-reduce",
            )
        with telemetry.span("finalize"):
            out = finalize_many(fused, results, out_shape)
        return (out,) + tuple(_index_values(g) for g in found_groups)

    if engine == "numpy":
        inters = fused_chunk_stats(
            fused, codes_flat, arr_flat, size=size, engine="numpy", eager=True
        )
        with telemetry.span("finalize"):
            out = finalize_many(fused, fused.finalize_fused(inters), out_shape)
        return (out,) + tuple(_index_values(g) for g in found_groups)

    # -- eager jax: ONE jitted program for chunk legs + every finalize -----
    from .parallel.mapreduce import dense_intermediate_bytes

    lead_elems = int(np.prod(lead_shape)) if lead_shape else 1
    est = dense_intermediate_bytes(lead_elems, size, arr_flat.dtype, fused, ndev=1)
    ceiling = OPTIONS["dense_intermediate_bytes_max"]
    if est > ceiling:
        raise ValueError(
            f"{fused.name!r} over {size} groups needs ~{utils.fmt_bytes(est)} "
            f"of dense (..., size) device intermediates, above the "
            f"{utils.fmt_bytes(ceiling)} dense_intermediate_bytes_max ceiling. "
            "Options: pass mesh=; reduce expected_groups; or raise "
            "set_options(dense_intermediate_bytes_max=...)."
        )

    key = _fused_key(fused, size)
    program = _FUSED_PROGRAM_CACHE.get(key)
    if program is None:
        telemetry.count("cache.fused_program_misses")
        import jax

        def run(codes_d, array_d):
            inters = fused_chunk_stats(
                fused, codes_d, array_d, size=size, engine="jax", eager=True
            )
            return fused.finalize_fused(inters)

        program = jax.jit(run)
        _FUSED_PROGRAM_CACHE[key] = program
    else:
        telemetry.count("cache.fused_program_hits")

    tm_on = telemetry.enabled()
    if tm_on:
        # cost-ledger baseline (the chunk_reduce discipline): the staged
        # bytes are billed ONCE for the whole statistic set — that 1x-vs-Nx
        # ledger delta IS the fusion win, surfaced per program key
        from time import perf_counter

        compiles0 = telemetry.METRICS.get("jax.compiles")
        compile_ms0 = telemetry.METRICS.get("jax.compile_ms")
        t0 = perf_counter()
    with telemetry.span("dispatch", engine="jax", nstats=len(funcs), size=size):
        # staging stays INSIDE the span (it always covered transfer +
        # execute); the device refs are kept for the card site below
        codes_d = utils.asarray_device(codes_flat)
        arr_d = utils.asarray_device(arr_flat)
        results = program(codes_d, arr_d)
    if tm_on:
        # observed wall snapshotted BEFORE the card analysis: its
        # lower+compile must not bill as device time (it would read as
        # drift on the first dispatch)
        dispatch_ms = (perf_counter() - t0) * 1e3
        prog = fused_program_label(funcs)
        telemetry.sample_hbm(program=prog)
        # analytical card for the ONE fused program (costmodel plane):
        # memoized per shape signature, recorded before the ledger write so
        # the first dispatch's gauge join already finds it
        from . import costmodel

        costmodel.ensure_card(prog, program, (codes_d, arr_d))
        telemetry.observe_cost(
            prog,
            device_ms=dispatch_ms,
            nbytes=int(getattr(arr_flat, "nbytes", 0))
            + int(getattr(codes_flat, "nbytes", 0)),
            compiles=int(telemetry.METRICS.get("jax.compiles") - compiles0),
            compile_ms=telemetry.METRICS.get("jax.compile_ms") - compile_ms0,
        )
    with telemetry.span("finalize"):
        out = finalize_many(fused, results, out_shape)
    return (out,) + tuple(_index_values(g) for g in found_groups)


def _aggregate_many_prefactorized(
    array: Any,
    pf: "fct.Prefactorized",
    *,
    funcs: tuple,
    expected_groups: Any,
    isbin: Any,
    axis: Any,
    fill_value: Any,
    dtype: Any,
    min_count: int | None,
    engine: str | None,
    finalize_kwargs: dict | None,
    method: str | None,
    mesh: Any,
    axis_name: str,
) -> tuple:
    """Fused multi-statistic over a :class:`factorize.Prefactorized` ``by``
    — the registry fast path of :func:`groupby_aggregate_many`. Mirrors the
    inline body from the engine choice onward, minus factorize and minus
    the codes H2D (put-staged ``codes_dev`` feeds the fused program)."""
    from .core import _choose_engine

    bad = [
        name
        for name, val in (
            ("expected_groups", expected_groups),
            ("axis", axis),
        )
        if val is not None
    ]
    if isbin not in (False, (False,)):
        bad.append("isbin")
    if bad:
        raise NotImplementedError(
            f"Prefactorized `by` does not support {bad}: the factorization "
            "is fixed at put time (re-put the dataset with different groups)"
        )
    array_is_jax = utils.is_jax_array(array)
    engine = _choose_engine(engine, array, array_is_jax)
    arr = array if array_is_jax else np.asarray(array)
    arr_dtype = np.dtype(arr.dtype)
    from . import dtypes as dtps

    if arr_dtype.kind in "OSUmM" or dtps.is_datetime_like(arr_dtype):
        raise NotImplementedError(
            f"groupby_aggregate_many supports numeric data; got {arr_dtype}"
        )
    if arr_dtype.kind == "b":
        addlike = {"sum", "nansum", "prod", "nanprod"}
        boolsafe = {"all", "any", "count"}
        if set(funcs) <= boolsafe:
            pass
        elif set(funcs) <= (addlike | boolsafe):
            arr = arr.astype(np.int64 if utils.x64_enabled() else np.int32)
        else:
            raise NotImplementedError(
                f"bool data fuses only {sorted(addlike | boolsafe)}; run "
                f"{sorted(set(funcs) - addlike - boolsafe)} sequentially"
            )
    bndim = len(pf.by_shape)
    if arr.ndim < bndim or tuple(arr.shape[arr.ndim - bndim:]) != tuple(pf.by_shape):
        raise ValueError(
            f"`array` with shape {arr.shape} does not align with the "
            f"prefactorized `by` shape {pf.by_shape}"
        )

    size = pf.size
    min_count_ = 0 if min_count is None else min_count
    fused = plan_fused(funcs, dtype, arr.dtype, fill_value, min_count_, finalize_kwargs)

    lead_shape = arr.shape[: arr.ndim - bndim]
    arr_flat = arr.reshape(lead_shape + (pf.n,))
    out_shape = lead_shape + pf.group_shape

    if OPTIONS["autotune"] and engine == "jax":
        from . import autotune

        nelems = int(np.prod(arr_flat.shape)) if arr_flat.ndim else 0
        choice = autotune.decide(
            "fused", "fused", ("fused", "sequential"),
            dtype=str(arr_flat.dtype), ngroups=size, nelems=nelems,
        )
        if choice == "sequential":
            def per_func_kw(f):
                def pick(v):
                    return v.get(f) if isinstance(v, dict) else v

                return {
                    "fill_value": pick(fill_value), "dtype": pick(dtype),
                    "finalize_kwargs": pick(finalize_kwargs),
                    "min_count": min_count,
                }

            return _sequential_fallback(
                array, (pf,), funcs, per_func_kw=per_func_kw,
                common_kw={
                    "engine": engine, "method": method, "mesh": mesh,
                    "axis_name": axis_name,
                },
            )

    if method is not None or mesh is not None:
        from .parallel.mapreduce import sharded_groupby_reduce

        codes_run = pf.codes if (method == "cohorts" or pf.codes_dev is None) else pf.codes_dev
        with telemetry.span("combine", method=method or "map-reduce", size=size):
            results = sharded_groupby_reduce(
                arr_flat, codes_run, fused, size=size, mesh=mesh,
                axis_name=axis_name, method=method or "map-reduce",
            )
        with telemetry.span("finalize"):
            out = finalize_many(fused, results, out_shape)
        return (out,) + tuple(_index_values(g) for g in pf.found_groups)

    if engine == "numpy":
        inters = fused_chunk_stats(
            fused, pf.codes, arr_flat, size=size, engine="numpy", eager=True
        )
        with telemetry.span("finalize"):
            out = finalize_many(fused, fused.finalize_fused(inters), out_shape)
        return (out,) + tuple(_index_values(g) for g in pf.found_groups)

    from .parallel.mapreduce import dense_intermediate_bytes

    lead_elems = int(np.prod(lead_shape)) if lead_shape else 1
    est = dense_intermediate_bytes(lead_elems, size, arr_flat.dtype, fused, ndev=1)
    ceiling = OPTIONS["dense_intermediate_bytes_max"]
    if est > ceiling:
        raise ValueError(
            f"{fused.name!r} over {size} groups needs ~{utils.fmt_bytes(est)} "
            f"of dense (..., size) device intermediates, above the "
            f"{utils.fmt_bytes(ceiling)} dense_intermediate_bytes_max ceiling."
        )

    key = _fused_key(fused, size)
    program = _FUSED_PROGRAM_CACHE.get(key)
    if program is None:
        telemetry.count("cache.fused_program_misses")
        import jax

        def run(codes_d, array_d):
            inters = fused_chunk_stats(
                fused, codes_d, array_d, size=size, engine="jax", eager=True
            )
            return fused.finalize_fused(inters)

        program = jax.jit(run)
        _FUSED_PROGRAM_CACHE[key] = program
    else:
        telemetry.count("cache.fused_program_hits")

    tm_on = telemetry.enabled()
    if tm_on:
        from time import perf_counter

        compiles0 = telemetry.METRICS.get("jax.compiles")
        compile_ms0 = telemetry.METRICS.get("jax.compile_ms")
        t0 = perf_counter()
    with telemetry.span("dispatch", engine="jax", nstats=len(funcs), size=size):
        codes_d = utils.asarray_device(
            pf.codes_dev if pf.codes_dev is not None else pf.codes
        )
        arr_d = utils.asarray_device(arr_flat)
        results = program(codes_d, arr_d)
    if tm_on:
        dispatch_ms = (perf_counter() - t0) * 1e3
        prog = fused_program_label(funcs)
        telemetry.sample_hbm(program=prog)
        from . import costmodel

        costmodel.ensure_card(prog, program, (codes_d, arr_d))
        telemetry.observe_cost(
            prog,
            device_ms=dispatch_ms,
            nbytes=int(getattr(arr_flat, "nbytes", 0))
            + int(getattr(pf.codes, "nbytes", 0)),
            compiles=int(telemetry.METRICS.get("jax.compiles") - compiles0),
            compile_ms=telemetry.METRICS.get("jax.compile_ms") - compile_ms0,
        )
    with telemetry.span("finalize"):
        out = finalize_many(fused, results, out_shape)
    return (out,) + tuple(_index_values(g) for g in pf.found_groups)


def _index_values(idx):
    from .core import _index_values as _iv

    return _iv(idx)
