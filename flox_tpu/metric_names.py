"""Shared metric-name constants: the contract-checked consumer surface.

The registry names the serve plane emits (``telemetry.METRICS``) and the
Prometheus names scrapers read are two spellings of the same series —
and until this module, every consumer (the SLO evaluator, the fleet
federator's top view) respelled them as inline string literals, which is
exactly how a scrape-name typo ships: the column is silently empty on
every replica and nothing fails.

Consumers import these constants instead. floxlint's FLX018 resolves
every constant here against the contract compiler's emit-site table
(``tools/floxlint/contract.py``), so a name no producer emits is a lint
error at the definition, not a dead dashboard panel in production.

:func:`prom_name` is the single Prometheus respelling — byte-compatible
with ``exposition._metric_name`` (``flox_tpu_`` prefix, non-identifier
characters folded to ``_``, counters suffixed ``_total``): the fleet
scraper and the exposition renderer cannot disagree on a name.
"""

from __future__ import annotations

import re

# -- serve request path (counters unless noted) ------------------------------

SERVE_REQUESTS = "serve.requests"
SERVE_REQUEST_MS = "serve.request_ms"  # histogram
SERVE_QUEUE_MS = "serve.queue_ms"  # histogram
SERVE_DEVICE_MS = "serve.device_ms"  # histogram
SERVE_SHED = "serve.shed"
SERVE_DEADLINE_EXCEEDED = "serve.deadline_exceeded"
SERVE_ERRORS = "serve.errors"

# -- resilience (breakers / device loss / watchdog) --------------------------

SERVE_BREAKER_FASTFAIL = "serve.breaker_fastfail"
SERVE_BREAKERS_OPEN = "serve.breakers_open"  # gauge
SERVE_DEVICE_LOST = "serve.device_lost"
SERVE_WATCHDOG_FIRED = "serve.watchdog_fired"
SERVE_QUEUE_DEPTH = "serve.queue_depth"  # gauge

# -- saturation / residency gauges ------------------------------------------

HBM_BYTES_IN_USE = "hbm.bytes_in_use"  # gauge
HBM_BYTES_LIMIT = "hbm.bytes_limit"  # gauge

# -- canary probes (slo.py both emits and reads these) -----------------------

CANARY_PROBES = "canary.probes"
CANARY_OK = "canary.ok"
CANARY_FAILURES = "canary.failures"

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def prom_name(name: str, *, counter: bool = False) -> str:
    """The Prometheus spelling of a registry ``name`` — identical folding
    to the exposition renderer, so scrape consumers and the renderer can
    never drift: ``prom_name(SERVE_REQUESTS, counter=True)`` ->
    ``"flox_tpu_serve_requests_total"``."""
    return "flox_tpu_" + _NAME_BAD.sub("_", name) + ("_total" if counter else "")
