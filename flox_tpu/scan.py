"""Grouped scans: ``groupby_scan`` (parity: /root/reference/flox/scan.py:101-370).

Supported scans (matching the reference registry, aggregations.py:849-920):
``cumsum``, ``nancumsum``, ``ffill``, ``bfill``.

TPU-first architecture: the reference implements grouped scans as a Blelloch
scan over dask blocks (dask.py:576-663) whose within-block kernel is a
sorted cumulative op (aggregate_flox.py:269-329). Here the within-device
kernel is a *segmented* ``lax.associative_scan`` (kernels.py), which is
already log-depth over the whole axis — on a single chip there is no block
decomposition at all, and across a mesh the same segmented operator is
applied to per-shard carries (parallel/scan.py).

Multi-dimensional labels are handled with the offset-codes trick
(factorize.offset_labels): each non-scanned label row gets a disjoint code
range, so one flat segmented scan handles every row without crossing rows.
"""

from __future__ import annotations

import logging
from typing import Any

import numpy as np

from . import factorize as fct
from . import telemetry, utils
from .aggregations import Scan, _initialize_scan
from .core import _convert_expected_groups_to_index, _normalize_expected, _normalize_isbin
from .options import OPTIONS

logger = logging.getLogger("flox_tpu.scan")

__all__ = ["groupby_scan"]


def groupby_scan(
    array: Any,
    *by: Any,
    func: str | Scan,
    expected_groups: Any = None,
    axis: int = -1,
    dtype: Any = None,
    method: str | None = None,
    engine: str | None = None,
    mesh: Any = None,
) -> Any:
    """Grouped scan along ``axis``; output has the same shape as ``array``.

    Parity: scan.py:101-315 — single-axis validation (scan.py:176-177),
    early factorization (210-220), integer dtype promotion for cumsum
    (272-283). Positions with missing labels (NaN-by) yield NaN.

    Examples
    --------
    >>> import numpy as np
    >>> from flox_tpu import groupby_scan
    >>> groupby_scan(np.array([1.0, 2.0, 4.0, 8.0]), np.array([0, 1, 0, 1]),
    ...              func="cumsum", engine="numpy")
    array([ 1.,  2.,  5., 10.])
    >>> groupby_scan(np.array([1.0, np.nan, np.nan, 8.0]), np.array([0, 1, 0, 1]),
    ...              func="ffill", engine="numpy")
    array([ 1., nan,  1.,  8.])
    """
    with telemetry.span(
        "groupby_scan",
        func=func if isinstance(func, str) else getattr(func, "name", "custom"),
        method=method,
    ):
        return _groupby_scan_impl(
            array, *by, func=func, expected_groups=expected_groups, axis=axis,
            dtype=dtype, method=method, engine=engine, mesh=mesh,
        )


def _groupby_scan_impl(
    array: Any,
    *by: Any,
    func: str | Scan,
    expected_groups: Any,
    axis: int,
    dtype: Any,
    method: str | None,
    engine: str | None,
    mesh: Any,
) -> Any:
    """The :func:`groupby_scan` body, under the public wrapper's root span
    (defaults live only on the wrapper, which forwards everything)."""
    if not by:
        raise TypeError("Must pass at least one `by`")
    if np.ndim(axis) != 0:
        raise ValueError("groupby_scan supports a single axis only (like the reference).")
    if method not in (None, "blelloch", "blockwise"):
        raise ValueError(f"scan method must be None, 'blelloch' or 'blockwise'; got {method!r}")
    if method is None and mesh is not None and engine is not None:
        raise ValueError(
            "engine= selects a single-device kernel but mesh= requests "
            "distributed execution; pass method='blelloch' (engine is "
            "ignored on the mesh) or drop one of the two."
        )
    from .aggregations import normalize_engine

    # normalize here, not only in generic_aggregate: the engine=="jax"
    # guards below (datetime x64 routing) must see the canonical name
    engine = normalize_engine(engine) if engine is not None else OPTIONS["default_engine"]
    nby = len(by)

    bys = [utils.asarray_host(b) for b in by]
    bys = list(np.broadcast_arrays(*bys)) if nby > 1 else bys
    array_is_jax = utils.is_jax_array(array)
    arr = array if array_is_jax else np.asarray(array)

    bndim = bys[0].ndim
    if arr.shape[-bndim:] != bys[0].shape:
        raise ValueError(
            f"`by` has shape {bys[0].shape} which does not align with the trailing "
            f"dimensions of `array` with shape {arr.shape}."
        )

    axis_n = axis % arr.ndim
    first_by_ax = arr.ndim - bndim
    if axis_n < first_by_ax:
        raise ValueError("Scan axis must be covered by the `by` labels.")
    rel_axis = axis_n - first_by_ax

    expected = _normalize_expected(expected_groups, nby)
    expected_idx = _convert_expected_groups_to_index(expected, _normalize_isbin(False, nby), sort=True)

    # move the scan axis to the end of both array and labels
    if rel_axis != bndim - 1:
        by_order = [d for d in range(bndim) if d != rel_axis] + [rel_axis]
        bys = [b.transpose(by_order) for b in bys]
        arr_order = list(range(first_by_ax)) + [first_by_ax + d for d in by_order]
        arr = arr.transpose(arr_order)

    with telemetry.span("factorize", nby=nby) as _fsp:
        codes, found_groups, grp_shape, ngroups, size, props = fct.factorize_(
            bys, axes=(bndim - 1,), expected_groups=expected_idx, sort=True
        )
        _fsp.set(ngroups=ngroups, size=size)
    # factorize_ offsets codes when bndim > 1 (disjoint ranges per row);
    # codes now flatten alongside the trailing by-span of the array.
    codes_flat = np.asarray(codes).reshape(-1)
    span = codes_flat.shape[0]
    lead_shape = arr.shape[: arr.ndim - bndim]
    arr_flat = arr.reshape(lead_shape + (span,))

    scan = _initialize_scan(func)

    # datetime64/timedelta64: scan on the exact int64 view with NaT as the
    # missing sentinel (float64 round-trips lose ns precision; parity with
    # the reference, whose numpy kernels handle NaT natively)
    from . import dtypes as dtps

    arr_dtype = np.dtype(arr.dtype) if not array_is_jax else np.dtype(str(arr.dtype))
    datetime_dtype = arr_dtype if dtps.is_datetime_like(arr_dtype) else None
    if datetime_dtype is not None:
        if scan.name in ("cumsum", "nancumsum") and arr_dtype.kind == "M":
            raise TypeError(
                "cumsum of datetime64 values is undefined (numpy cannot add "
                "points in time); cumsum timedelta64 works."
            )
        if dtype is not None:
            # a float dtype would silently drop sub-float64 ns on the int64
            # round-trip — the exactness this path exists to provide
            raise TypeError(
                "dtype= is not supported for datetime/timedelta scans; the "
                "scan runs on the exact int64 view and returns "
                f"{arr_dtype} unchanged."
            )
        arr_flat = np.asarray(arr_flat).view("int64")
        if engine == "jax" and mesh is None and method != "blelloch" and not utils.x64_enabled():
            logger.debug("datetime scan with x64 disabled: using numpy engine")
            engine = "numpy"
        if (mesh is not None or method == "blelloch") and not utils.x64_enabled():
            raise ValueError(
                "datetime/timedelta scans on the mesh need jax_enable_x64 "
                "(int64 NaT sentinels do not survive int32 truncation)."
            )

    # dtype promotion for accumulating scans (parity: scan.py:272-283)
    if scan.name in ("cumsum", "nancumsum") and dtype is None and datetime_dtype is None:
        if arr_dtype.kind in "iub":
            dtype = np.result_type(arr_dtype, np.int_)
    if method is None and mesh is not None:
        # auto method (parity: _choose_scan_method, reference scan.py:48-78):
        # blockwise when the layout analysis proves every group shard-local
        # AND the scan covers all by dims; the general fallback is Blelloch
        from .cohorts import chunks_from_shards, find_group_cohorts
        from .parallel.mapreduce import _norm_axes

        # shard count = the named mesh axes the scan executes over ("data"),
        # not the whole mesh (same fix as core.groupby_reduce's heuristic)
        n_shards = int(
            np.prod([mesh.shape[a] for a in _norm_axes("data", mesh)])
        )
        preferred, _ = find_group_cohorts(
            codes_flat, chunks_from_shards(codes_flat.shape[0], n_shards),
            expected_groups=range(size),
        )
        method = "blockwise" if (preferred == "blockwise" and bndim == 1) else "blelloch"
        logger.debug("groupby_scan: auto-selected method=%s", method)

    nat = datetime_dtype is not None
    if mesh is not None or method == "blelloch":
        # sharded scan over the mesh (parallel/scan.py); method='blelloch'
        # without a mesh means "distribute over the default mesh"
        from .parallel.scan import sharded_groupby_scan

        with telemetry.span("dispatch", method=method or "blelloch", size=size):
            out = sharded_groupby_scan(
                arr_flat, codes_flat, scan, size=size, dtype=dtype, mesh=mesh,
                method=method or "blelloch", nat=nat,
            )
    else:
        with telemetry.span("dispatch", engine=engine, size=size):
            out = _apply_scan(
                scan, arr_flat, codes_flat, size=size, engine=engine, dtype=dtype, nat=nat
            )

    with telemetry.span("finalize"):
        # missing labels scan to NaN (NaT for datetimes — they belong to no group)
        if (np.asarray(codes_flat) < 0).any():
            nanmask = codes_flat < 0
            out = _mask_positions(out, nanmask, nat=nat)

        if datetime_dtype is not None:
            out = np.asarray(out).astype("int64").view(datetime_dtype)
        out = out.reshape(arr.shape) if out.shape != arr.shape else out
        out = out.reshape(lead_shape + bys[0].shape)
        # undo the axis transpose
        if rel_axis != bndim - 1:
            inv = np.argsort(arr_order)
            out = out.transpose(tuple(inv))
    return out


def _apply_scan(scan: Scan, arr_flat, codes_flat, *, size, engine, dtype, nat=False):
    from .aggregations import generic_aggregate

    kwargs = {"nat": True} if nat else {}
    return generic_aggregate(
        codes_flat,
        arr_flat,
        engine=engine,
        func=scan.scan,
        size=size,
        dtype=dtype,
        **kwargs,
    )


def _mask_positions(out, nanmask, nat=False):
    if nat:
        # int64-viewed datetimes: the missing marker is NaT, dtype unchanged
        nat_val = np.iinfo(np.int64).min
        if utils.is_jax_array(out):
            import jax.numpy as jnp

            return jnp.where(jnp.asarray(nanmask), nat_val, out)
        return np.where(nanmask, nat_val, np.asarray(out))
    if utils.is_jax_array(out):
        import jax.numpy as jnp

        if not jnp.issubdtype(out.dtype, jnp.floating):
            out = out.astype(jnp.float64 if utils.x64_enabled() else jnp.float32)
        return jnp.where(jnp.asarray(nanmask), jnp.nan, out)
    out = np.asarray(out)
    if not np.issubdtype(out.dtype, np.floating):
        out = out.astype(np.float64)
    return np.where(nanmask, np.nan, out)


