"""Resident dataset registry: factorize once, serve from HBM.

flox's core insight is "factorize → reduce" with factorization done once
per grouping — but a JSON-lines request that inlines its payload re-ships,
re-parses, re-factorizes, and re-stages (H2D) the same arrays on every
request, so hot-data request cost is dominated by everything *except* the
reduction. This module is the serving-era fix: ``{"op": "put_dataset"}``
pins named arrays on device ONCE, and aggregation requests reference them
by name (``"dataset": "<name>"`` plus an optional ``rows``/``mask``
selector) instead of carrying data.

The put pays every per-dataset cost up front:

* **factorize once** — labels are factorized at put time into a
  :class:`~flox_tpu.factorize.Prefactorized` (codes, expected-groups
  table, and the sort engine's present/compact tables, all keyed on the
  entry's content fingerprint). A registry-hit request enters the core
  reduction with ZERO factorize work — no ``factorize`` span appears in
  its trace.
* **stage once** — data and codes live on device; the dispatch passes the
  resident buffers straight through ``utils.asarray_device`` (jax arrays
  pass through untouched), so ``bytes.h2d`` does not move on the hit path.
  Arrays at or above ``registry_shard_threshold_bytes`` are mesh-sharded
  over the trailing axis at put time, feeding the parallel plane's
  per-shard codes directly.
* **fingerprint once** — the entry's content fingerprint replaces payload
  hashing in the dispatcher's coalescing identity (``ds:<fp>:<selector>``),
  so hot-path hashing cost on hits is zero and the PR 7 coalescing /
  AOT-warmup contracts keep holding (the program key includes the dataset
  fingerprint).

Capacity is HBM-accounted: entries are bounded by
``registry_budget_fraction`` of the device's ``bytes_limit`` (PR 13 HBM
gauge) — or by the absolute ``registry_budget_bytes`` on backends that
report no limit (CPU) — and evicted least-recently-used. Entries pinned by
in-flight dispatches (refcounted by the dispatcher) are never evicted
mid-dispatch; ``del_dataset`` under in-flight traffic is safe the same way
(the dispatch holds direct references, so the delete only unpublishes the
name). Host-side spill copies make device-loss recovery whole: the
recovery cycle re-stages every registered dataset before ``/readyz`` flips
back, so a recovered replica still answers its registry-referenced
traffic.

The registry table is registered in ``cache.clear_all`` / ``cache.stats``
(floxlint FLX008); ``registry.*`` counters/gauges ride the always-on
metrics registry like the rest of the serve plane, and per-dataset cost
attribution rides the telemetry cost ledger's ``dataset`` axis
(``cache.stats()["cost_by_dataset"]``, ``/debug/datasets``).
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Any

import numpy as np

# options as a module attribute, never from-bound: tests reload
# flox_tpu.options, and a from-import would read the pre-reload dict
from .. import options, telemetry, utils
from ..cache import LRUCache
from ..factorize import Prefactorized, prefactorize
from ..telemetry import METRICS
from .dispatcher import ServeError

__all__ = [
    "DatasetEntry",
    "UnknownDatasetError",
    "budget_bytes",
    "clear",
    "debug_table",
    "delete",
    "list_datasets",
    "pin",
    "put",
    "registry_stats",
    "resolve",
    "restage_all",
    "unpin",
    "view",
]


class UnknownDatasetError(ServeError):
    """The request referenced a ``dataset`` name the registry does not
    hold — never put, already deleted, or evicted under HBM pressure
    (check the ``registry.evictions`` counter). A typed protocol error,
    not an ``execution`` failure: the client's fix is ``put_dataset``
    (or routing to the replica that holds the name)."""

    code = "unknown_dataset"


#: selector views memoized per entry — bounded: selectors are request-
#: shaped, and an adversarial client cycling masks must not grow an
#: entry's footprint without bound
_MAX_VIEWS_PER_ENTRY = 8


class DatasetEntry:
    """One resident dataset: device buffers + precomputed group tables +
    host-side spill copies (the device-loss re-pin source)."""

    __slots__ = (
        "name", "fingerprint", "data", "data_host", "by_host", "pf",
        "nbytes", "pins", "hits", "sharded", "views", "created",
    )

    def __init__(
        self,
        name: str,
        fingerprint: str,
        *,
        data: Any,
        data_host: np.ndarray | None,
        by_host: np.ndarray,
        pf: Prefactorized,
        sharded: bool,
    ) -> None:
        self.name = name
        self.fingerprint = fingerprint
        self.data = data
        self.data_host = data_host
        self.by_host = by_host
        self.pf = pf
        self.nbytes = int(
            (getattr(data, "nbytes", 0) or 0) + pf.device_nbytes()
        )
        self.pins = 0
        self.hits = 0
        self.sharded = sharded
        self.views: dict[str, tuple] = {}
        self.created = time.time()

    def info(self) -> dict:
        """The entry's JSON-safe description (list/debug/stats payloads)."""
        return {
            "name": self.name,
            "fingerprint": self.fingerprint,
            "nbytes": int(self.nbytes),
            "pins": int(self.pins),
            "hits": int(self.hits),
            "sharded": bool(self.sharded),
            "has_data": self.data is not None,
            "by_shape": list(self.by_host.shape),
            "by_dtype": str(self.by_host.dtype),
            "ngroups": int(self.pf.ngroups),
            "size": int(self.pf.size),
            "present": int(len(self.pf.present)),
            "views": len(self.views),
        }


#: the resident dataset table: name -> DatasetEntry, LRU-ordered so budget
#: eviction drops the stalest name first. maxsize is a backstop, never the
#: capacity mechanism — the HBM budget (budget_bytes) is. Registered in
#: cache.clear_all / cache.stats (floxlint FLX008).
_DATASET_REGISTRY: LRUCache = LRUCache(maxsize=4096)

#: budget evictions (deliberate frees, distinct from the LRU's capacity
#: counter): the runbook alarm feed behind registry.evictions
_EVICTIONS = [0]

_LOCK = threading.RLock()


# ---------------------------------------------------------------------------
# capacity
# ---------------------------------------------------------------------------


#: last computed budget — ``registry_stats()`` (the ``cache.stats()``
#: panel) reports this snapshot instead of polling the device: stats on a
#: disabled/idle plane must not touch the backend (the PR 13 HBM sampler
#: owns live polling; puts/evictions and /debug/datasets refresh it)
_BUDGET_SNAPSHOT = [0]


def budget_bytes() -> int:
    """The registry's device-byte budget.

    ``registry_budget_fraction`` of the device's reported HBM capacity
    (the PR 13 ``hbm.bytes_limit`` source) when the backend reports one;
    the absolute ``registry_budget_bytes`` on backends that report no
    limit (CPU test rigs). 0 means unenforced."""
    from .. import device

    stats = device.memory_stats()
    limit = int((stats or {}).get("bytes_limit") or 0)
    if limit > 0:
        budget = int(limit * float(options.OPTIONS["registry_budget_fraction"]))
    else:
        budget = int(options.OPTIONS["registry_budget_bytes"])
    _BUDGET_SNAPSHOT[0] = budget
    return budget


def _total_bytes() -> int:
    return sum(e.nbytes for e in _DATASET_REGISTRY.values())


def _publish_gauges() -> None:
    entries = _DATASET_REGISTRY.values()
    METRICS.set_gauge("registry.datasets", float(len(entries)))
    METRICS.set_gauge(
        "registry.bytes", float(sum(e.nbytes for e in entries))
    )
    METRICS.set_gauge(
        "registry.pinned_bytes",
        float(sum(e.nbytes for e in entries if e.pins > 0)),
    )


def _evict_to_budget(exclude: DatasetEntry | None = None) -> list[str]:
    """Drop stalest entries until the device-byte total fits the budget.

    Pinned entries (in-flight dispatches hold them) and ``exclude`` (the
    put that triggered the sweep) are skipped — a workload whose PINNED
    set alone exceeds the budget runs over it rather than failing
    dispatches mid-flight; the overshoot is visible on ``registry.bytes``
    vs the budget. Caller holds ``_LOCK``."""
    budget = budget_bytes()
    if budget <= 0:
        return []
    evicted: list[str] = []
    total = _total_bytes()
    # items() is stalest-first on the LRU — walk in eviction order
    for name, entry in _DATASET_REGISTRY.items():
        if total <= budget:
            break
        if entry.pins > 0 or entry is exclude:
            continue
        _DATASET_REGISTRY.pop(name, None)
        total -= entry.nbytes
        evicted.append(name)
        _EVICTIONS[0] += 1
        METRICS.inc("registry.evictions")
        telemetry.event(
            "registry-evicted", dataset=name, nbytes=entry.nbytes,
            budget=budget,
        )
    return evicted


# ---------------------------------------------------------------------------
# staging
# ---------------------------------------------------------------------------


def _stage_data(data_host: np.ndarray) -> tuple[Any, bool]:
    """Put one host array on device; mesh-shard over the trailing axis
    when it crosses the single-chip threshold (and the mesh/divisibility
    allow it). Returns ``(device_array, sharded)``; any sharding failure
    degrades to the plain single-device put."""
    thresh = int(options.OPTIONS["registry_shard_threshold_bytes"])
    if thresh and data_host.nbytes >= thresh and data_host.ndim >= 1:
        try:
            import jax
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            from ..parallel.mapreduce import _cached_mesh_default

            mesh = _cached_mesh_default()
            ndev = int(np.prod(list(mesh.shape.values())))
            if ndev > 1 and data_host.shape[-1] % ndev == 0:
                spec = P(*([None] * (data_host.ndim - 1) + [tuple(mesh.shape)]))
                out = jax.device_put(data_host, NamedSharding(mesh, spec))
                telemetry.METRICS.inc("bytes.h2d", int(data_host.nbytes))
                return out, True
        except Exception as exc:  # noqa: BLE001 — sharding is an optimization
            telemetry.record_serve_error(exc, what="registry.stage-sharded")
    return utils.asarray_device(data_host), False


def _fingerprint_update(h: Any, arr: np.ndarray | None) -> None:
    if arr is None:
        h.update(b"<none>")
        return
    a = np.asarray(arr)
    h.update(str(a.dtype).encode())
    h.update(repr(a.shape).encode())
    if a.dtype.kind == "O":
        h.update(repr(a.tolist()).encode())
    else:
        h.update(np.ascontiguousarray(a).tobytes())


def _content_fingerprint(
    by: np.ndarray, data: np.ndarray | None, expected: Any
) -> str:
    h = hashlib.blake2b(digest_size=16)
    _fingerprint_update(h, by)
    _fingerprint_update(h, None if expected is None else np.asarray(expected))
    _fingerprint_update(h, data)
    return h.hexdigest()


# ---------------------------------------------------------------------------
# protocol surface
# ---------------------------------------------------------------------------


def put(
    name: Any,
    array: Any = None,
    by: Any = None,
    *,
    expected_groups: Any = None,
    sort: bool = True,
) -> dict:
    """Pin one named dataset on device, factorized and staged.

    ``by`` (the label arrays) is required — it is what factorize-once
    applies to; ``array`` is optional (a labels-only entry serves requests
    that still inline per-request data over resident codes). Re-putting a
    name replaces the entry. Returns the entry's info dict plus what the
    budget sweep evicted to make room.
    """
    if not isinstance(name, str) or not name:
        raise ValueError("put_dataset requires a non-empty string 'name'")
    if by is None:
        raise ValueError(
            "put_dataset requires 'by' label arrays — factorize-once is "
            "the point of a resident dataset"
        )
    t0 = time.perf_counter()
    by_host = utils.asarray_host(np.asarray(by))
    data_host = np.asarray(array) if array is not None else None
    if data_host is not None and data_host.shape[-by_host.ndim:] != by_host.shape:
        raise ValueError(
            f"dataset array trailing dims {data_host.shape!r} do not align "
            f"with by shape {by_host.shape!r}"
        )
    fingerprint = _content_fingerprint(by_host, data_host, expected_groups)
    with telemetry.span("registry.put", dataset=name):
        pf = prefactorize(
            by_host, expected_groups, sort=sort, fingerprint=fingerprint
        )
        data_dev: Any = None
        sharded = False
        if data_host is not None:
            data_dev, sharded = _stage_data(data_host)
    entry = DatasetEntry(
        name, fingerprint,
        data=data_dev, data_host=data_host, by_host=by_host, pf=pf,
        sharded=sharded,
    )
    with _LOCK:
        _DATASET_REGISTRY[name] = entry
        evicted = _evict_to_budget(exclude=entry)
        _publish_gauges()
    METRICS.inc("registry.puts")
    info = entry.info()
    info["evicted"] = evicted
    info["put_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
    return info


def resolve(name: str) -> DatasetEntry:
    """The live entry for ``name`` (LRU-renewing), or a typed
    :class:`UnknownDatasetError`."""
    entry = _DATASET_REGISTRY.get(name)
    if entry is None:
        METRICS.inc("registry.misses")
        raise UnknownDatasetError(
            f"unknown dataset {name!r}: not put, deleted, or evicted under "
            "HBM pressure (registry.evictions) — put_dataset it again"
        )
    entry.hits += 1
    METRICS.inc("registry.hits")
    return entry


def pin(entry: DatasetEntry) -> None:
    """Refcount ``entry`` as in-flight: a pinned entry is never evicted
    mid-dispatch (``del_dataset`` only unpublishes the name; the dispatch
    holds direct references)."""
    with _LOCK:
        entry.pins += 1
        _publish_gauges()


def unpin(entry: DatasetEntry) -> None:
    with _LOCK:
        entry.pins = max(0, entry.pins - 1)
        _publish_gauges()


def view(
    entry: DatasetEntry, rows: Any = None, mask: Any = None
) -> tuple[Any, Prefactorized, str]:
    """The (data, prefactorized, selector-key) triple a request resolves to.

    ``rows`` is a ``[start, stop)`` pair, ``mask`` a boolean vector over
    the flattened label axis; both select device-side (a slice view for
    rows, a gather for masks) so no H2D moves. Selector views share the
    entry's group tables and are memoized per entry (bounded), so a
    repeated selector costs one dict hit."""
    if rows is None and mask is None:
        return entry.data, entry.pf, ""
    if rows is not None and mask is not None:
        raise ValueError("pass 'rows' or 'mask', not both")
    pf = entry.pf
    if rows is not None:
        lo, hi = int(rows[0]), int(rows[1])
        key = f"rows:{lo}:{hi}"
    else:
        mask = np.asarray(mask, dtype=bool).reshape(-1)
        if mask.shape[0] != pf.n:
            raise ValueError(
                f"mask length {mask.shape[0]} != dataset rows {pf.n}"
            )
        key = "mask:" + hashlib.blake2b(
            mask.tobytes(), digest_size=8
        ).hexdigest()
    cached = entry.views.get(key)
    if cached is not None:
        METRICS.inc("registry.view_hits")
        return cached[0], cached[1], key
    if rows is not None:
        pf_view = pf.slice_rows(lo, hi)
    else:
        pf_view = pf.select_mask(mask)
    data_view: Any = None
    if entry.data is not None:
        lead = entry.data.shape[: entry.data.ndim - len(pf.by_shape)]
        flat = entry.data.reshape(lead + (pf.n,))
        if rows is not None:
            data_view = flat[..., lo:hi]
        else:
            import jax.numpy as jnp

            idx = jnp.asarray(np.flatnonzero(mask))
            data_view = jnp.take(flat, idx, axis=-1)
    if len(entry.views) >= _MAX_VIEWS_PER_ENTRY:
        entry.views.pop(next(iter(entry.views)))
    entry.views[key] = (data_view, pf_view)
    return data_view, pf_view, key


def delete(name: str) -> bool:
    """Unpublish ``name``. In-flight dispatches referencing the entry
    finish normally (they hold direct references + a pin); only NEW
    requests see :class:`UnknownDatasetError`. Returns whether the name
    existed."""
    with _LOCK:
        entry = _DATASET_REGISTRY.pop(name, None)
        _publish_gauges()
    if entry is None:
        return False
    METRICS.inc("registry.deletes")
    return True


def list_datasets() -> list[dict]:
    """Every resident entry's info dict (LRU order, stalest first)."""
    return [entry.info() for entry in _DATASET_REGISTRY.values()]


def debug_table(top: int | None = None) -> dict:
    """The ``/debug/datasets`` payload: per-entry rows (hottest first) +
    capacity summary + the per-dataset cost-ledger join."""
    rows = sorted(list_datasets(), key=lambda r: -r["hits"])
    if top:
        rows = rows[:top]
    return {
        "datasets": rows,
        "bytes": _total_bytes(),
        "budget_bytes": budget_bytes(),
        "evictions": _EVICTIONS[0],
        "cost_by_dataset": telemetry.cost_by_dataset(),
    }


def registry_stats() -> dict:
    """The registry's ``cache.stats()`` panel.

    Reports the budget SNAPSHOT, not a live device poll — ``cache.stats()``
    must stay backend-untouched on an idle plane (use ``/debug/datasets``
    for the live figure)."""
    entries = _DATASET_REGISTRY.values()
    return {
        "datasets": len(entries),
        "bytes": sum(e.nbytes for e in entries),
        "pinned": sum(1 for e in entries if e.pins > 0),
        "pinned_bytes": sum(e.nbytes for e in entries if e.pins > 0),
        "budget_bytes": _BUDGET_SNAPSHOT[0],
        "evictions": _EVICTIONS[0],
    }


def restage_all() -> int:
    """Re-pin every registered dataset from its host-side spill copies —
    the device-loss recovery hook, run after backend reinit and AOT warmup
    but BEFORE ``/readyz`` flips back, so a recovered replica answers its
    registry-referenced traffic immediately. Returns entries restaged."""
    restaged = 0
    with _LOCK:
        for entry in _DATASET_REGISTRY.values():
            entry.pf.stage()
            if entry.data_host is not None:
                entry.data, entry.sharded = _stage_data(entry.data_host)
            # selector views hold dead-device buffers: rebuild on demand
            entry.views.clear()
            entry.nbytes = int(
                (getattr(entry.data, "nbytes", 0) or 0)
                + entry.pf.device_nbytes()
            )
            restaged += 1
        _publish_gauges()
    if restaged:
        METRICS.inc("registry.restaged", restaged)
        telemetry.event("registry-restaged", datasets=restaged)
    return restaged


def clear() -> None:
    """Drop every resident dataset (``cache.clear_all`` calls this; the
    body references ``_DATASET_REGISTRY`` directly for floxlint FLX008).
    In-flight dispatches keep their direct references — a clear only
    unpublishes names."""
    _DATASET_REGISTRY.clear()
    _EVICTIONS[0] = 0
    _BUDGET_SNAPSHOT[0] = 0
    METRICS.set_gauge("registry.datasets", 0.0)
    METRICS.set_gauge("registry.bytes", 0.0)
    METRICS.set_gauge("registry.pinned_bytes", 0.0)
