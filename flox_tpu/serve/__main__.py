"""``python -m flox_tpu.serve`` — JSON-lines serving loop.

One JSON object per input line (stdin by default, ``--input FILE`` for
scripted runs), one JSON object per output line. Request lines carry the
:class:`~flox_tpu.serve.AggregationRequest` fields::

    {"id": "r1", "func": "sum", "array": [...], "by": [...],
     "options": {"default_engine": "numpy"}, "deadline": 0.5}

and are submitted CONCURRENTLY as they are read — lines arriving within
the batching window coalesce / micro-batch exactly as library callers do.
Responses are emitted as each completes (match them by ``id``)::

    {"id": "r1", "ok": true, "result": [...], "groups": [...],
     "coalesced": false, "batch": 1, "queue_ms": 0.4, "device_ms": 2.1}
    {"id": "r2", "ok": false, "error": "LoadShedError", "message": "..."}

Control lines use ``op`` instead of ``func``:

* ``{"op": "warmup"}`` — replay the AOT manifest (:func:`serve.aot.warmup`);
  responds with ``{"warmed": N, "compiles": <jax.compiles so far>}``.
* ``{"op": "stats"}`` — cache.stats() + the telemetry counter snapshot
  (``jax.compiles`` included: the two-process AOT smoke asserts on it;
  the per-program/per-tenant cost ledger rides ``cache.cost_by_program`` /
  ``cache.cost_by_tenant``).
* ``{"op": "profile", "seconds": N}`` — start an on-demand on-chip capture
  into ``OPTIONS["profile_dir"]`` (409-equivalent ``"busy"`` while one
  runs, ``"unavailable"`` on profiler-less backends).
* ``{"op": "drain"}`` — wait for every in-flight request before reading on
  (scripted runs use it to sequence assertions).

Request lines may carry a ``"tenant"`` tag: it feeds the per-tenant cost
ledger and a ``serve.request_ms{tenant=...}`` histogram on /metrics
without affecting coalescing or results.

The loop exits at EOF after draining in-flight work. Malformed lines get
an ``ok: false`` response with ``error: "protocol"`` — one bad client line
must never take the replica down.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
from typing import Any

import numpy as np

from . import aot
from .dispatcher import AggregationRequest, Dispatcher, ServeError

_REQUEST_FIELDS = frozenset(
    {
        "func", "array", "by", "expected_groups", "fill_value", "dtype",
        "min_count", "engine", "finalize_kwargs", "options", "deadline",
        "tenant",
    }
)


def _emit(obj: dict) -> None:
    # all emits run on the event-loop thread, so lines never interleave
    sys.stdout.write(json.dumps(obj) + "\n")
    sys.stdout.flush()


def _counters() -> dict:
    from .. import cache
    from ..telemetry import METRICS

    return {"cache": cache.stats(), "counters": METRICS.snapshot()}


async def _serve_request(dispatcher: Dispatcher, line_no: int, msg: dict) -> None:
    rid = msg.get("id", f"line-{line_no}")
    try:
        unknown = set(msg) - _REQUEST_FIELDS - {"id"}
        if unknown:
            raise ValueError(f"unknown request fields: {sorted(unknown)}")
        request = AggregationRequest(
            request_id=rid, **{k: v for k, v in msg.items() if k != "id"}
        )
    except Exception as exc:  # noqa: BLE001 — malformed envelope, client's bug
        _emit({"id": rid, "ok": False, "error": "protocol", "message": str(exc)})
        return
    try:
        result = await dispatcher.submit(request)
    except ServeError as exc:
        _emit(
            {"id": rid, "ok": False, "error": type(exc).__name__, "message": str(exc)}
        )
    except Exception as exc:  # noqa: BLE001 — execution failed, NOT a protocol
        # error: report the real class so clients can tell a bad func/dtype
        # apart from a malformed line (and never kill the loop over it)
        _emit(
            {"id": rid, "ok": False, "error": type(exc).__name__, "message": str(exc)}
        )
    else:
        # multi-statistic requests (func = a list of names) answer with a
        # {func: values} object; single statistics stay a flat list
        if isinstance(result.result, dict):
            payload = {k: np.asarray(v).tolist() for k, v in result.result.items()}
        else:
            payload = np.asarray(result.result).tolist()
        _emit(
            {
                "id": rid,
                "ok": True,
                "result": payload,
                "groups": np.asarray(result.groups).tolist(),
                "coalesced": result.coalesced,
                "batch": result.batch_size,
                "queue_ms": round(result.queue_ms, 3),
                "device_ms": round(result.device_ms, 3),
            }
        )


async def _amain(args: argparse.Namespace) -> int:
    from .. import exposition
    from ..options import OPTIONS, set_options

    if args.aot_dir:
        set_options(serve_aot_dir=args.aot_dir)
    metrics_port = (
        args.metrics_port if args.metrics_port is not None else OPTIONS["metrics_port"]
    )
    if metrics_port:
        bound = exposition.start_metrics_server(port=metrics_port, host=args.metrics_host)
        _emit({"op": "metrics", "port": bound})
    if args.warmup:
        warmed = await asyncio.to_thread(aot.warmup)
        from ..telemetry import METRICS

        _emit({"warmed": warmed, "compiles": METRICS.get("jax.compiles")})
    # /readyz flips here: the warmup manifest (when requested) has been
    # replayed, so a load balancer routing on readiness never hands traffic
    # to a replica still paying compiles
    exposition.set_ready(True)
    dispatcher = Dispatcher(
        queue_depth=args.queue_depth,
        deadline=args.deadline,
        microbatch_max=args.microbatch_max,
        batch_window=args.batch_window,
    )
    stream = sys.stdin if args.input == "-" else open(args.input)
    pending: set[asyncio.Task] = set()
    line_no = 0
    try:
        while True:
            # one reader thread-hop per line; requests run concurrently
            # because we never await the per-request task here
            line = await asyncio.to_thread(stream.readline)
            if not line:
                break
            line_no += 1
            line = line.strip()
            if not line:
                continue
            try:
                msg = json.loads(line)
                assert isinstance(msg, dict)
            # noqa: FLX006 — not a retry loop: lines are independent client
            # requests, and one malformed line must never kill the replica
            except Exception:  # noqa: FLX006
                _emit(
                    {
                        "id": f"line-{line_no}", "ok": False, "error": "protocol",
                        "message": f"malformed JSON on line {line_no}",
                    }
                )
                continue
            op = msg.get("op")
            if op == "stats":
                _emit({"op": "stats", **_counters()})
            elif op == "profile":
                # on-demand on-chip capture: starts immediately, stops on a
                # timer thread — the serve loop never blocks behind the
                # window, and a busy/unavailable capture is an answer, not
                # a crash (same contract as /debug/profile)
                from .. import profiling

                try:
                    capture_dir = profiling.start_capture(
                        seconds=float(msg.get("seconds", 5.0))
                    )
                except profiling.CaptureBusyError as exc:
                    _emit({"op": "profile", "ok": False, "error": "busy",
                           "message": str(exc)})
                except profiling.CaptureUnavailableError as exc:
                    _emit({"op": "profile", "ok": False, "error": "unavailable",
                           "message": str(exc)})
                except (ValueError, TypeError) as exc:
                    _emit({"op": "profile", "ok": False, "error": "protocol",
                           "message": str(exc)})
                else:
                    _emit({"op": "profile", "ok": True, "dir": capture_dir})
            elif op == "warmup":
                warmed = await asyncio.to_thread(aot.warmup)
                exposition.set_ready(True)
                from ..telemetry import METRICS

                _emit({"warmed": warmed, "compiles": METRICS.get("jax.compiles")})
            elif op == "drain":
                if pending:
                    await asyncio.gather(*pending, return_exceptions=True)
                await dispatcher.close()
                _emit({"op": "drain", "ok": True})
            elif op is not None:
                _emit(
                    {
                        "id": msg.get("id", f"line-{line_no}"), "ok": False,
                        "error": "protocol", "message": f"unknown op {op!r}",
                    }
                )
            else:
                task = asyncio.create_task(_serve_request(dispatcher, line_no, msg))
                pending.add(task)
                task.add_done_callback(pending.discard)
    finally:
        if pending:
            await asyncio.gather(*pending, return_exceptions=True)
        await dispatcher.close()
        if stream is not sys.stdin:
            stream.close()
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m flox_tpu.serve",
        description="JSON-lines groupby serving loop (one request per line)",
    )
    parser.add_argument("--input", default="-", help="request file, or - for stdin")
    parser.add_argument(
        "--aot-dir", default=None,
        help="AOT persistence root (overrides FLOX_TPU_SERVE_AOT_DIR)",
    )
    parser.add_argument(
        "--warmup", action="store_true",
        help="replay the AOT warmup manifest before reading requests",
    )
    parser.add_argument("--queue-depth", type=int, default=None)
    parser.add_argument("--deadline", type=float, default=None)
    parser.add_argument("--microbatch-max", type=int, default=None)
    parser.add_argument("--batch-window", type=float, default=None)
    parser.add_argument(
        "--metrics-port", type=int, default=None,
        help="serve /metrics + /healthz + /readyz on this port "
        "(overrides FLOX_TPU_METRICS_PORT; 0 keeps the endpoint off)",
    )
    parser.add_argument(
        "--metrics-host", default="127.0.0.1",
        help="bind address for the metrics endpoint — the loopback default "
        "suits sidecar scrapers; pass 0.0.0.0 for a remote Prometheus",
    )
    args = parser.parse_args(argv)
    from .. import profiling, telemetry

    # SIGTERM/SIGUSR2 leave a flight-recorder dump (no-op unless telemetry
    # + FLOX_TPU_FLIGHT_RECORDER_PATH are configured); SIGUSR1 starts an
    # on-demand on-chip capture into OPTIONS["profile_dir"]. Both must be
    # installed on the main thread, before the loop starts
    telemetry.install_signal_dumps()
    profiling.install_capture_signal()
    try:
        return asyncio.run(_amain(args))
    except Exception as exc:
        # an unhandled serve-loop exception is exactly what the flight
        # recorder exists for: dump the last N records, then die loudly.
        # Exception, not BaseException: Ctrl-C / SystemExit are clean
        # shutdowns and must not overwrite a genuine earlier fatal dump
        # with a post-shutdown snapshot labeled as a crash
        telemetry.flight_dump(reason=f"serve-loop:{type(exc).__name__}")
        raise


if __name__ == "__main__":
    sys.exit(main())
