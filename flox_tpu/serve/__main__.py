"""``python -m flox_tpu.serve`` — JSON-lines serving loop.

One JSON object per input line (stdin by default, ``--input FILE`` for
scripted runs), one JSON object per output line. Request lines carry the
:class:`~flox_tpu.serve.AggregationRequest` fields::

    {"id": "r1", "func": "sum", "array": [...], "by": [...],
     "options": {"default_engine": "numpy"}, "deadline": 0.5}

and are submitted CONCURRENTLY as they are read — lines arriving within
the batching window coalesce / micro-batch exactly as library callers do.
Responses are emitted as each completes (match them by ``id``)::

    {"id": "r1", "ok": true, "result": [...], "groups": [...],
     "coalesced": false, "batch": 1, "queue_ms": 0.4, "device_ms": 2.1}
    {"id": "r2", "ok": false, "error": "LoadShedError", "code": "load_shed",
     "retry_after_ms": 2.0, "message": "..."}

Serving-layer failures carry a machine-readable ``code``
(``load_shed`` / ``deadline_exceeded`` / ``circuit_open`` /
``device_lost`` / ``watchdog_timeout`` / ``draining``) plus an optional
``retry_after_ms`` hint, so clients branch on the code instead of
string-matching Python class names.

Control lines use ``op`` instead of ``func``:

* ``{"op": "warmup"}`` — replay the AOT manifest (:func:`serve.aot.warmup`);
  responds with ``{"warmed": N, "compiles": <jax.compiles so far>}``.
* ``{"op": "stats"}`` — cache.stats() + the telemetry counter snapshot
  (``jax.compiles`` included: the two-process AOT smoke asserts on it;
  the per-program/per-tenant cost ledger rides ``cache.cost_by_program`` /
  ``cache.cost_by_tenant``; breaker state rides ``cache.serve_breakers``).
* ``{"op": "profile", "seconds": N}`` — start an on-demand on-chip capture
  into ``OPTIONS["profile_dir"]`` (409-equivalent ``"busy"`` while one
  runs, ``"unavailable"`` on profiler-less backends).
* ``{"op": "drain"}`` — wait for every in-flight request before reading on
  (scripted runs use it to sequence assertions).
* ``{"op": "shutdown"}`` — graceful drain: admission stops, ``/readyz``
  flips 503 immediately, in-flight requests finish within
  ``serve_drain_timeout``, the flight recorder dumps, and the process
  exits 0. SIGTERM triggers the exact same path (the supervisor's
  rolling-restart signal must never kill a request mid-flight — the old
  behavior of dump-and-die-143 is still what the standalone metrics
  endpoint does, where there are no requests to finish).

Request lines may carry a ``"tenant"`` tag: it feeds the per-tenant cost
ledger and a ``serve.request_ms{tenant=...}`` histogram on /metrics
without affecting coalescing or results.

The loop exits at EOF after draining in-flight work. Malformed lines get
an ``ok: false`` response with ``error: "protocol"`` — one bad client line
must never take the replica down.
"""

from __future__ import annotations

import argparse
import asyncio
import contextlib
import json
import sys
import threading
import time
from typing import Any

import numpy as np

from . import aot
from .dispatcher import AggregationRequest, Dispatcher, ServeError

_REQUEST_FIELDS = frozenset(
    {
        "func", "array", "by", "expected_groups", "fill_value", "dtype",
        "min_count", "engine", "finalize_kwargs", "options", "deadline",
        "tenant", "traceparent", "dataset", "rows", "mask",
    }
)

#: soft bound on lines buffered ahead of the serve loop — a scripted
#: multi-GB request file must not load wholesale into the line queue
_READER_HIGH_WATER = 512


def _emit(obj: dict) -> None:
    # all emits run on the event-loop thread, so lines never interleave
    sys.stdout.write(json.dumps(obj) + "\n")
    sys.stdout.flush()


def _counters() -> dict:
    from .. import cache
    from ..telemetry import METRICS

    return {"cache": cache.stats(), "counters": METRICS.snapshot()}


def _error_response(rid: str, exc: Exception) -> dict:
    """The typed error envelope: the exception class (back-compat), the
    machine-readable ``code``, and the ``retry_after_ms`` hint when the
    failure kind has one (load shed, open breaker)."""
    out: dict[str, Any] = {
        "id": rid, "ok": False,
        "error": type(exc).__name__, "message": str(exc),
    }
    if isinstance(exc, ServeError):
        out["code"] = exc.code
        if exc.retry_after_ms is not None:
            out["retry_after_ms"] = round(float(exc.retry_after_ms), 3)
        if exc.program is not None:
            out["program"] = exc.program
    else:
        out["code"] = "execution"
    return out


async def _serve_request(dispatcher: Dispatcher, line_no: int, msg: dict) -> None:
    from .. import telemetry

    rid = msg.get("id", f"line-{line_no}")
    try:
        unknown = set(msg) - _REQUEST_FIELDS - {"id"}
        if unknown:
            raise ValueError(f"unknown request fields: {sorted(unknown)}")
        if msg.get("dataset") is not None and (
            msg.get("by") is not None or msg.get("expected_groups") is not None
        ):
            raise ValueError(
                "a 'dataset' request must not also carry 'by'/"
                "'expected_groups' — they were fixed at put_dataset time"
            )
        request = AggregationRequest(
            request_id=rid, **{k: v for k, v in msg.items() if k != "id"}
        )
    except Exception as exc:  # noqa: BLE001 — malformed envelope, client's bug
        telemetry.record_serve_error(exc, what=f"protocol line {line_no}")
        _emit({"id": rid, "ok": False, "error": "protocol", "code": "protocol",
               "message": str(exc)})
        return
    try:
        result = await dispatcher.submit(request)
    except ServeError as exc:
        _emit(_error_response(rid, exc))
    except Exception as exc:  # noqa: BLE001 — execution failed, NOT a protocol
        # error: report the real class so clients can tell a bad func/dtype
        # apart from a malformed line (and never kill the loop over it).
        # The flight ring keeps the record (FLX012): the dispatcher already
        # classified the failure, this preserves WHICH request wore it.
        telemetry.record_serve_error(exc, what=f"request {rid}")
        _emit(_error_response(rid, exc))
    else:
        # multi-statistic requests (func = a list of names) answer with a
        # {func: values} object; single statistics stay a flat list
        if isinstance(result.result, dict):
            payload = {k: np.asarray(v).tolist() for k, v in result.result.items()}
        else:
            payload = np.asarray(result.result).tolist()
        out = {
            "id": rid,
            "ok": True,
            "result": payload,
            "groups": np.asarray(result.groups).tolist(),
            "coalesced": result.coalesced,
            "batch": result.batch_size,
            "queue_ms": round(result.queue_ms, 3),
            "device_ms": round(result.device_ms, 3),
        }
        if result.traceparent is not None:
            # trace-context echo: same trace id the request carried, this
            # replica's handling as the new parent span — the hop chains
            out["traceparent"] = result.traceparent
            out["trace_id"] = result.trace_id
        _emit(out)


def _start_reader(stream: Any, loop: asyncio.AbstractEventLoop) -> asyncio.Queue:
    """Feed input lines into an asyncio queue from a daemon thread.

    A daemon reader (instead of ``asyncio.to_thread(stream.readline)``)
    is what makes the graceful drain exit-able: a drain that begins while
    the process is blocked reading stdin must not wait for one more line —
    the loop simply stops consuming the queue, and the parked thread dies
    with the process instead of wedging executor shutdown."""
    queue: asyncio.Queue = asyncio.Queue()

    def _read() -> None:
        line_no = 0
        try:
            for line in stream:
                line_no += 1
                while queue.qsize() > _READER_HIGH_WATER:
                    time.sleep(0.005)  # soft back-pressure on scripted files
                loop.call_soon_threadsafe(queue.put_nowait, (line_no, line))
        except (RuntimeError, ValueError, OSError):
            pass  # loop closed / stream torn down mid-read: exit quietly
        try:
            loop.call_soon_threadsafe(queue.put_nowait, None)  # EOF sentinel
        except RuntimeError:
            pass

    threading.Thread(target=_read, name="flox-tpu-serve-reader", daemon=True).start()
    return queue


async def _drain_and_exit(
    dispatcher: Dispatcher, pending: set[asyncio.Task], source: str
) -> None:
    """Finish in-flight work within ``serve_drain_timeout``, then dump.

    Requests still unfinished past the budget are cancelled (their waiters
    see the cancellation, never a silent drop) and counted on
    ``serve.drain_abandoned``."""
    from .. import telemetry
    from ..options import OPTIONS
    from ..telemetry import METRICS

    budget = float(OPTIONS["serve_drain_timeout"] or 0)
    deadline = time.monotonic() + budget
    abandoned = 0
    if pending:
        done, not_done = await asyncio.wait(
            set(pending), timeout=budget if budget > 0 else 0
        )
        for task in not_done:
            task.cancel()
            abandoned += 1
    remaining = max(0.0, deadline - time.monotonic())
    try:
        await asyncio.wait_for(dispatcher.close(), remaining or 0.001)
    except (asyncio.TimeoutError, TimeoutError):
        abandoned += 1
    if abandoned:
        METRICS.inc("serve.drain_abandoned", abandoned)
    # flight_dump writes files: off the loop so a slow disk cannot stall
    # the final shutdown handshake
    await asyncio.to_thread(telemetry.flight_dump, reason=f"drain:{source}")
    _emit(
        {
            "op": "shutdown", "ok": True, "source": source,
            "abandoned": abandoned,
        }
    )


async def _amain(args: argparse.Namespace) -> int:
    import signal

    from .. import exposition
    from ..options import OPTIONS, set_options

    if args.aot_dir:
        set_options(serve_aot_dir=args.aot_dir)
    if args.replica_id:
        # validated like any set_options value (label-safe, bounded): a
        # bad --replica-id dies at startup, not at first scrape
        set_options(replica_id=args.replica_id)
    metrics_port = (
        args.metrics_port if args.metrics_port is not None else OPTIONS["metrics_port"]
    )
    from .. import telemetry

    if metrics_port:
        bound = exposition.start_metrics_server(port=metrics_port, host=args.metrics_host)
        _emit({"op": "metrics", "port": bound,
               "replica": telemetry.replica_instance()})
    # a clock anchor near startup: trace_join aligns this replica's jsonl
    # export onto the shared fleet timeline from it (no-op, telemetry off)
    telemetry.anchor_event()
    if args.warmup:
        warmed = await asyncio.to_thread(aot.warmup)
        from ..telemetry import METRICS

        _emit({"warmed": warmed, "compiles": METRICS.get("jax.compiles")})
    # /readyz flips here: the warmup manifest (when requested) has been
    # replayed, so a load balancer routing on readiness never hands traffic
    # to a replica still paying compiles
    exposition.set_ready(True)
    dispatcher = Dispatcher(
        queue_depth=args.queue_depth,
        deadline=args.deadline,
        microbatch_max=args.microbatch_max,
        batch_window=args.batch_window,
    )
    # the SLO canary prober: known-answer requests across the op matrix on
    # a period, billed under the reserved tenant, feeding the correctness
    # SLO. Off by default (0); --canary-interval overrides the option.
    from .. import options

    canary_interval = (
        args.canary_interval
        if args.canary_interval is not None
        else options.OPTIONS["slo_canary_interval"]
    )
    canary_task: asyncio.Task | None = None
    if canary_interval:
        from .. import slo

        canary_task = asyncio.ensure_future(
            slo.canary_loop(dispatcher, float(canary_interval))
        )
        _emit({"op": "canary", "interval": float(canary_interval)})
    drain_event = asyncio.Event()
    drain_state: dict[str, str] = {}

    def _begin_drain(source: str) -> None:
        # idempotent: a second SIGTERM during a drain changes nothing.
        # Ordering is the ROADMAP-item-2 contract: readiness flips 503
        # FIRST (the fleet router stops routing), THEN admission closes,
        # THEN in-flight work finishes.
        if drain_state:
            return
        drain_state["source"] = source
        exposition.set_ready(False, reason="draining")
        dispatcher.begin_drain()
        drain_event.set()

    loop = asyncio.get_running_loop()
    try:
        loop.add_signal_handler(
            signal.SIGTERM, _begin_drain, "SIGTERM"
        )
    except (NotImplementedError, RuntimeError, ValueError):
        pass  # platform without unix signals: the shutdown op still drains
    stream = (
        sys.stdin
        if args.input == "-"
        else open(args.input)  # noqa: FLX015 — startup: nothing else is scheduled on the loop yet
    )
    queue = _start_reader(stream, loop)
    pending: set[asyncio.Task] = set()
    # ONE long-lived drain sentinel raced against each line read — per-line
    # task churn would put two allocations and a cancellation on the hot
    # path of every request line for a pure signal
    drainer = asyncio.ensure_future(drain_event.wait())
    try:
        while not drain_event.is_set():
            getter = asyncio.ensure_future(queue.get())
            done, _ = await asyncio.wait(
                {getter, drainer}, return_when=asyncio.FIRST_COMPLETED
            )
            if getter not in done:
                getter.cancel()
                break  # drain began while blocked on input
            item = getter.result()
            if item is None:
                break  # EOF
            line_no, line = item
            line = line.strip()
            if not line:
                continue
            try:
                msg = json.loads(line)
                assert isinstance(msg, dict)
            # noqa: FLX006 — not a retry loop: lines are independent client
            # requests, and one malformed line must never kill the replica
            except Exception as exc:  # noqa: FLX006
                from .. import telemetry

                telemetry.record_serve_error(exc, what=f"malformed line {line_no}")
                _emit(
                    {
                        "id": f"line-{line_no}", "ok": False, "error": "protocol",
                        "code": "protocol",
                        "message": f"malformed JSON on line {line_no}",
                    }
                )
                continue
            op = msg.get("op")
            if op == "stats":
                _emit({"op": "stats", **_counters()})
            elif op == "profile":
                # on-demand on-chip capture: starts immediately, stops on a
                # timer thread — the serve loop never blocks behind the
                # window, and a busy/unavailable capture is an answer, not
                # a crash (same contract as /debug/profile)
                from .. import profiling

                try:
                    # start_capture rotates old capture dirs (rmtree) and
                    # touches the filesystem before arming the profiler:
                    # off the loop, like every other disk path in serve
                    capture_dir = await asyncio.to_thread(
                        profiling.start_capture,
                        seconds=float(msg.get("seconds", 5.0)),
                    )
                except profiling.CaptureBusyError as exc:
                    _emit({"op": "profile", "ok": False, "error": "busy",
                           "code": "busy", "message": str(exc)})
                except profiling.CaptureUnavailableError as exc:
                    _emit({"op": "profile", "ok": False, "error": "unavailable",
                           "code": "unavailable", "message": str(exc)})
                except (ValueError, TypeError) as exc:
                    _emit({"op": "profile", "ok": False, "error": "protocol",
                           "code": "protocol", "message": str(exc)})
                else:
                    _emit({"op": "profile", "ok": True, "dir": capture_dir})
            elif op == "warmup":
                warmed = await asyncio.to_thread(aot.warmup)
                exposition.set_ready(True)
                from ..telemetry import METRICS

                _emit({"warmed": warmed, "compiles": METRICS.get("jax.compiles")})
            elif op == "put_dataset":
                # factorize + stage happen here, ONCE: off the loop (a
                # multi-GB put must not stall every in-flight request's
                # admission), then every later {"dataset": name} request
                # skips parse, factorize, and H2D entirely
                from . import registry

                try:
                    info = await asyncio.to_thread(
                        registry.put,
                        msg.get("name"),
                        array=msg.get("array"),
                        by=msg.get("by"),
                        expected_groups=msg.get("expected_groups"),
                        sort=bool(msg.get("sort", True)),
                    )
                # noqa: FLX006 — not a retry loop: the put is one client
                # request, and a bad payload (or a put racing device loss)
                # must be answered, never kill the replica
                except Exception as exc:  # noqa: FLX006,BLE001
                    from .. import telemetry

                    telemetry.record_serve_error(exc, what="put_dataset")
                    _emit({"op": "put_dataset", "ok": False,
                           "name": msg.get("name"), "error": type(exc).__name__,
                           "code": "protocol", "message": str(exc)})
                else:
                    _emit({"op": "put_dataset", "ok": True, **info})
            elif op == "del_dataset":
                from . import registry

                # same answer-never-crash contract as put_dataset: a
                # malformed name (unhashable, say) must come back as a
                # typed protocol answer, not unwind the loop
                try:
                    deleted = registry.delete(msg.get("name"))
                except Exception as exc:  # noqa: FLX006,BLE001
                    from .. import telemetry

                    telemetry.record_serve_error(exc, what="del_dataset")
                    _emit({"op": "del_dataset", "ok": False,
                           "name": msg.get("name"), "error": type(exc).__name__,
                           "code": "protocol", "message": str(exc)})
                else:
                    _emit({"op": "del_dataset", "ok": True,
                           "name": msg.get("name"), "deleted": bool(deleted)})
            elif op == "list_datasets":
                from . import registry

                try:
                    listing = registry.list_datasets()
                    stats = registry.registry_stats()
                except Exception as exc:  # noqa: FLX006,BLE001
                    from .. import telemetry

                    telemetry.record_serve_error(exc, what="list_datasets")
                    _emit({"op": "list_datasets", "ok": False,
                           "error": type(exc).__name__,
                           "code": "execution", "message": str(exc)})
                else:
                    _emit({"op": "list_datasets", "ok": True,
                           "datasets": listing, "stats": stats})
            elif op in ("append", "query", "compact", "list_stores"):
                # durable incremental aggregation stores (flox_tpu/store.py):
                # every store op touches the WAL/segments on disk, so each
                # runs off the loop like put_dataset. Failures answer with
                # the typed codes (unknown_store / store_corruption); an
                # exactly-once replay is an OK answer with
                # ack == "slab_already_ingested", never an error.
                from . import stores

                try:
                    if op == "append":
                        out = await asyncio.to_thread(
                            stores.append,
                            msg.get("store"),
                            msg.get("codes"),
                            msg.get("array"),
                            slab_id=msg.get("slab_id"),
                            create=msg.get("create"),
                        )
                    elif op == "query":
                        res = await asyncio.to_thread(
                            stores.query, msg.get("store"), msg.get("funcs")
                        )
                        out = {
                            "store": msg.get("store"),
                            "result": {k: np.asarray(v).tolist() for k, v in res.items()},
                        }
                    elif op == "compact":
                        out = await asyncio.to_thread(stores.compact, msg.get("store"))
                    else:
                        out = {"stores": await asyncio.to_thread(stores.list_stores)}
                except ServeError as exc:
                    _emit({"op": op, "store": msg.get("store"),
                           **_error_response(msg.get("id", f"line-{line_no}"), exc)})
                # noqa: FLX006 — not a retry loop: one store op is one client
                # request, and a bad payload must be answered, never kill
                # the replica
                except Exception as exc:  # noqa: FLX006,BLE001
                    from .. import telemetry

                    telemetry.record_serve_error(exc, what=f"store op {op}")
                    _emit({"op": op, "ok": False, "store": msg.get("store"),
                           "error": type(exc).__name__, "code": "protocol",
                           "message": str(exc)})
                else:
                    _emit({"op": op, "ok": True, **out})
            elif op == "drain":
                if pending:
                    await asyncio.gather(*pending, return_exceptions=True)
                await dispatcher.close()
                _emit({"op": "drain", "ok": True})
            elif op == "shutdown":
                _begin_drain("shutdown-op")
                break
            elif op is not None:
                _emit(
                    {
                        "id": msg.get("id", f"line-{line_no}"), "ok": False,
                        "error": "protocol", "code": "protocol",
                        "message": f"unknown op {op!r}",
                    }
                )
            else:
                task = asyncio.create_task(_serve_request(dispatcher, line_no, msg))
                pending.add(task)
                task.add_done_callback(pending.discard)
    finally:
        drainer.cancel()
        if canary_task is not None:
            # the prober holds no state needing a flush — cancel before the
            # drain so no new probe races admission-closed
            canary_task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await canary_task
        if drain_state:
            await _drain_and_exit(dispatcher, pending, drain_state["source"])
        else:
            # EOF: the scripted-run path — finish everything, unbounded,
            # exactly as before the drain machinery existed
            if pending:
                await asyncio.gather(*pending, return_exceptions=True)
            await dispatcher.close()
        if stream is not sys.stdin:
            stream.close()
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m flox_tpu.serve",
        description="JSON-lines groupby serving loop (one request per line)",
    )
    parser.add_argument("--input", default="-", help="request file, or - for stdin")
    parser.add_argument(
        "--aot-dir", default=None,
        help="AOT persistence root (overrides FLOX_TPU_SERVE_AOT_DIR)",
    )
    parser.add_argument(
        "--warmup", action="store_true",
        help="replay the AOT warmup manifest before reading requests",
    )
    parser.add_argument("--queue-depth", type=int, default=None)
    parser.add_argument("--deadline", type=float, default=None)
    parser.add_argument("--microbatch-max", type=int, default=None)
    parser.add_argument("--batch-window", type=float, default=None)
    parser.add_argument(
        "--canary-interval", type=float, default=None,
        help="seconds between SLO canary-prober cycles (known-answer "
        "requests billed to the reserved __canary__ tenant, feeding the "
        "correctness SLO; overrides FLOX_TPU_SLO_CANARY_INTERVAL; "
        "0 keeps the prober off)",
    )
    parser.add_argument(
        "--metrics-port", type=int, default=None,
        help="serve /metrics + /healthz + /readyz on this port "
        "(overrides FLOX_TPU_METRICS_PORT; 0 keeps the endpoint off)",
    )
    parser.add_argument(
        "--replica-id", default=None,
        help="this replica's stable fleet identity (overrides "
        "FLOX_TPU_REPLICA_ID): labels every /metrics series and "
        "/debug/costs payload, prefixes generated request ids, and stamps "
        "telemetry exports for tools/trace_join.py",
    )
    parser.add_argument(
        "--metrics-host", default="127.0.0.1",
        help="bind address for the metrics endpoint — the loopback default "
        "suits sidecar scrapers; pass 0.0.0.0 for a remote Prometheus",
    )
    args = parser.parse_args(argv)
    from .. import profiling, telemetry

    # SIGUSR2 leaves a flight-recorder dump (no-op unless telemetry +
    # FLOX_TPU_FLIGHT_RECORDER_PATH are configured); SIGUSR1 starts an
    # on-demand on-chip capture into OPTIONS["profile_dir"]. Both must be
    # installed on the main thread, before the loop starts. SIGTERM is
    # deliberately NOT taken here (sigterm=False): the serve loop registers
    # its own handler for the graceful drain — finish in-flight requests,
    # flight-dump, exit 0 — instead of the dump-and-die-143 default.
    telemetry.install_signal_dumps(sigterm=False)
    profiling.install_capture_signal()
    try:
        return asyncio.run(_amain(args))
    except Exception as exc:
        # an unhandled serve-loop exception is exactly what the flight
        # recorder exists for: dump the last N records, then die loudly.
        # Exception, not BaseException: Ctrl-C / SystemExit are clean
        # shutdowns and must not overwrite a genuine earlier fatal dump
        # with a post-shutdown snapshot labeled as a crash
        telemetry.flight_dump(reason=f"serve-loop:{type(exc).__name__}")
        raise


if __name__ == "__main__":
    sys.exit(main())
