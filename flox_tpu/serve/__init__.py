"""Groupby-as-a-service: the serving front-end (ROADMAP item 1).

The library below this package is call-at-a-time; a serving replica
amortizes compilation, device dispatch, and admission decisions across
requests instead:

* :mod:`.dispatcher` — the asyncio front-end: request coalescing
  (identical-program-identical-payload requests share ONE execution),
  micro-batching (program-compatible small payloads stack into one device
  dispatch), and admission control (bounded queue depth, per-request
  deadlines with cancellation, load-shed at saturation).
* :mod:`.aot` — program persistence: JAX's persistent compilation cache
  rooted at ``OPTIONS["serve_aot_dir"]`` plus a warmup manifest, so a
  restarted replica serves its first request with zero new backend
  compiles (asserted on the ``jax.compiles`` telemetry counter).
* ``python -m flox_tpu.serve`` — a JSON-lines request loop over the
  dispatcher, for testing and smoke deployment (see :mod:`.__main__`).

Per-request SLO metrics (``serve.queue_ms`` / ``serve.device_ms`` /
``serve.request_ms`` histograms, ``serve.*`` counters) flow through the
process telemetry registry; serving state is visible in ``cache.stats()``
and reset by ``cache.clear_all()``.
"""

from __future__ import annotations

from . import aot
from .dispatcher import (
    AggregationRequest,
    DeadlineExceededError,
    Dispatcher,
    LoadShedError,
    ServeError,
    ServeResult,
)

__all__ = [
    "AggregationRequest",
    "DeadlineExceededError",
    "Dispatcher",
    "LoadShedError",
    "ServeError",
    "ServeResult",
    "aot",
]
