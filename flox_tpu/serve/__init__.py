"""Groupby-as-a-service: the serving front-end (ROADMAP item 1).

The library below this package is call-at-a-time; a serving replica
amortizes compilation, device dispatch, and admission decisions across
requests instead:

* :mod:`.dispatcher` — the asyncio front-end: request coalescing
  (identical-program-identical-payload requests share ONE execution),
  micro-batching (program-compatible small payloads stack into one device
  dispatch), and admission control (bounded queue depth, per-request
  deadlines with cancellation, load-shed at saturation).
* :mod:`.aot` — program persistence: JAX's persistent compilation cache
  rooted at ``OPTIONS["serve_aot_dir"]`` plus a warmup manifest, so a
  restarted replica serves its first request with zero new backend
  compiles (asserted on the ``jax.compiles`` telemetry counter).
* :mod:`.breaker` — per-program circuit breakers: a program key whose
  dispatches keep failing fatally fast-fails at submit with a typed
  :class:`CircuitOpenError` until a half-open probe closes it.
* :mod:`.registry` — resident datasets: ``{"op": "put_dataset"}`` pins
  named arrays on device, factorized ONCE at put time; requests that
  reference them (``"dataset": name`` + optional ``rows``/``mask``
  selector) skip JSON payloads, factorize, and H2D entirely. HBM-budgeted,
  LRU-evicted (never mid-dispatch — refcount pins), re-pinned from host
  spills by device-loss recovery.
* ``python -m flox_tpu.serve`` — a JSON-lines request loop over the
  dispatcher, for testing and smoke deployment (see :mod:`.__main__`).

The serve plane carries its own fault domain (the serving-era analogue of
the streaming resilience layer): request quarantine (a poisoned micro-batch
member fails alone — healthy peers still get results), device-loss
recovery (typed :class:`DeviceLostError` to in-flight waiters, backend
reinit + AOT warmup replay, readiness flipped around the cycle), a
dispatch watchdog (:class:`WatchdogTimeoutError` instead of a wedged
queue), and graceful drain (SIGTERM / ``{"op": "shutdown"}`` answer
in-flight requests and exit 0). Deterministic chaos coverage lives in
``faults.serve_inject`` + ``tests/test_serve_chaos.py``.

Per-request SLO metrics (``serve.queue_ms`` / ``serve.device_ms`` /
``serve.request_ms`` histograms, ``serve.*`` counters) flow through the
process telemetry registry; serving state is visible in ``cache.stats()``
and reset by ``cache.clear_all()``.

The plane is fleet-ready (docs/serving.md "Running a fleet"): a request
carrying a W3C ``traceparent`` runs under the propagated trace id with
the remote parent span linked and echoes the same trace id back, replicas
started with ``replica_id`` label every metric series and prefix their
generated request ids fleet-uniquely, and ``python -m flox_tpu.fleet``
federates N replicas' ``/metrics`` + ``/debug/costs`` + ``/readyz`` into
one merged view (plus a live ops console).
"""

from __future__ import annotations

from . import aot, breaker, registry
from .dispatcher import (
    AggregationRequest,
    CircuitOpenError,
    DeadlineExceededError,
    DeviceLostError,
    Dispatcher,
    DrainingError,
    LoadShedError,
    ServeError,
    ServeResult,
    WatchdogTimeoutError,
    payload_digest,
)
from .registry import UnknownDatasetError

__all__ = [
    "AggregationRequest",
    "CircuitOpenError",
    "DeadlineExceededError",
    "DeviceLostError",
    "Dispatcher",
    "DrainingError",
    "LoadShedError",
    "ServeError",
    "ServeResult",
    "UnknownDatasetError",
    "WatchdogTimeoutError",
    "aot",
    "breaker",
    "payload_digest",
    "registry",
]
