"""Serve-layer surface of the durable incremental aggregation stores.

A store is a registry entry whose state GROWS: ``{"op": "append"}`` folds a
slab into the persisted per-group carry (``flox_tpu/store.py`` — WAL-backed,
exactly-once), ``{"op": "query"}`` serves finalized statistics without
recomputing history, ``{"op": "compact"}`` folds segment history, and
``{"op": "list_stores"}`` enumerates. Stores live under
``OPTIONS["store_root"]`` (one directory per name) and are opened lazily on
first reference — opening IS crash recovery, so a replica restarted over a
killed predecessor's directory answers queries bit-identically to an
uninterrupted run.

Hot state is two-tier: the authoritative carry is host-resident numpy
(compact ``PresentGroups`` layers backed by the checksummed segments — the
host spill), and the last finalized query result is staged device-side per
store, invalidated by generation. Device loss runs the registry's
``restage_all`` contract: the recovery cycle reopens every table entry from
its durable directory (dropping dead-device result caches) before
``/readyz`` flips back.

The store table is registered in ``cache.clear_all`` / ``cache.stats``
(floxlint FLX008); ``store.*`` counters/gauges ride the always-on metrics
registry, per-store cost rows ride the telemetry cost ledger's ``dataset``
axis, and ``/debug/stores`` serves the joined table.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any

import numpy as np

# options as a module attribute, never from-bound: tests reload
# flox_tpu.options, and a from-import would read the pre-reload dict
from .. import options, telemetry
from ..fusion import store_program_label
from ..store import IncrementalAggregationStore, StoreCorruptionError, open_store
from ..telemetry import METRICS
from .dispatcher import ServeError

__all__ = [
    "StoreEntry",
    "StoreCorruptedError",
    "UnknownStoreError",
    "append",
    "clear",
    "compact",
    "debug_table",
    "list_stores",
    "publish_staleness",
    "query",
    "resolve",
    "restage_all",
    "staleness_by_store",
    "stores_stats",
]


class UnknownStoreError(ServeError):
    """The request referenced a ``store`` name that does not exist under
    ``store_root`` (or no root is configured). A typed protocol error: the
    client's fix is an ``append`` carrying ``create`` (or routing to the
    replica whose root holds the store)."""

    code = "unknown_store"


class StoreCorruptedError(ServeError):
    """Opening (or re-opening) the store hit unrecoverable on-disk damage:
    a mid-history segment failed its checksums and no fallback state
    survives. The damaged file is quarantined as ``*.corrupt`` next to the
    store — the operator's runbook is restore-from-replica or re-ingest.
    Not retryable: no ``retry_after_ms`` is ever attached."""

    code = "store_corruption"


class StoreEntry:
    """One open store: the durable store object + the device-side finalized
    result cache (generation-keyed)."""

    __slots__ = (
        "name", "store", "opened", "last_ack", "dev", "dev_gen", "dev_key", "lock",
    )

    def __init__(self, name: str, store: IncrementalAggregationStore) -> None:
        self.name = name
        self.store = store
        self.opened = time.time()
        # the freshness-SLO signal: wall time of the last acked append
        # (open counts as the epoch — a just-recovered store is as fresh
        # as its recovery, not as stale as its history)
        self.last_ack = self.opened
        self.dev: dict | None = None
        self.dev_gen = -1
        self.dev_key: tuple = ()
        self.lock = threading.RLock()

    def info(self) -> dict:
        d = self.store.info()
        d["device_cached"] = self.dev is not None
        d["staleness_s"] = round(max(0.0, time.time() - self.last_ack), 3)
        return d


#: name -> StoreEntry for every store this replica has opened
_STORE_TABLE: dict[str, StoreEntry] = {}
_LOCK = threading.RLock()


def _root() -> str:
    root = options.OPTIONS["store_root"]
    if not root:
        raise UnknownStoreError(
            "no store root configured: set options.store_root "
            "(FLOX_TPU_STORE_ROOT) before using store ops"
        )
    return str(root)


def _publish_gauges() -> None:
    entries = list(_STORE_TABLE.values())
    METRICS.set_gauge("store.open_stores", float(len(entries)))
    METRICS.set_gauge(
        "store.state_bytes", float(sum(e.store.info()["nbytes"] for e in entries))
    )


def resolve(name: Any, *, create: dict | None = None) -> StoreEntry:
    """The table entry for ``name``, lazily opening (= recovering) the
    durable directory on first reference; ``create`` makes a missing store
    instead of failing. Raises the typed protocol errors."""
    if not name or not isinstance(name, str):
        raise UnknownStoreError(f"store name must be a non-empty string, got {name!r}")
    if name != os.path.basename(name) or name.startswith("."):
        raise UnknownStoreError(f"store name {name!r} must be a bare directory name")
    with _LOCK:
        entry = _STORE_TABLE.get(name)
        if entry is not None:
            return entry
        path = os.path.join(_root(), name)
        try:
            store = open_store(path, create=create)
        except FileNotFoundError:
            METRICS.inc("store.misses")
            raise UnknownStoreError(
                f"unknown store {name!r}: not under the store root "
                "(append with 'create' to make it)"
            ) from None
        except StoreCorruptionError as exc:
            telemetry.record_serve_error(exc, what=f"store open {name}")
            raise StoreCorruptedError(str(exc)) from exc
        if store.recovered:
            telemetry.event("store-recovered", store=name, gen=store.gen)
        entry = StoreEntry(name, store)
        _STORE_TABLE[name] = entry
        _publish_gauges()
        return entry


def append(
    name: str,
    codes: Any,
    array: Any,
    *,
    slab_id: str | None = None,
    create: dict | None = None,
) -> dict:
    """Exactly-once slab ingestion; replays ack as no-ops. Returns the
    store's ack dict (``ack`` = ``"ingested"`` | ``"slab_already_ingested"``)."""
    entry = resolve(name, create=create)
    t0 = time.perf_counter()
    codes = np.asarray(codes)
    array = np.asarray(array)
    try:
        ack = entry.store.append(codes, array, slab_id=slab_id)
    except StoreCorruptionError as exc:
        telemetry.record_serve_error(exc, what=f"store append {name}")
        raise StoreCorruptedError(str(exc)) from exc
    entry.last_ack = time.time()
    telemetry.observe_cost(
        store_program_label("append", entry.store.funcs),
        dataset=name,
        device_ms=(time.perf_counter() - t0) * 1e3,
        nbytes=int(array.nbytes),
    )
    _publish_gauges()
    return ack


def query(name: str, funcs: Any = None) -> dict:
    """Finalized ``{func: dense array}`` from the persisted carry. The last
    result is staged device-side per store and served from device while the
    generation is unchanged (the hot path a dashboard polling one store
    rides); any append invalidates it."""
    entry = resolve(name)
    sel = tuple(funcs) if funcs else tuple(entry.store.funcs)
    t0 = time.perf_counter()
    with entry.lock:
        if entry.dev is not None and entry.dev_gen == entry.store.gen and entry.dev_key == sel:
            METRICS.inc("store.query_device_hits")
            return {f: np.asarray(v) for f, v in entry.dev.items()}
        out = entry.store.query(sel)
        try:
            import jax

            entry.dev = {f: jax.device_put(v) for f, v in out.items()}
            entry.dev_gen = entry.store.gen
            entry.dev_key = sel
        except Exception as exc:  # noqa: BLE001 — device staging is an
            # optimization only: a backend mid-recovery (or absent) must
            # never fail a query the host carry can answer
            telemetry.record_serve_error(exc, what=f"store query staging {name}")
            entry.dev = None
    telemetry.observe_cost(
        store_program_label("query", entry.store.funcs),
        dataset=name,
        device_ms=(time.perf_counter() - t0) * 1e3,
        nbytes=sum(int(v.nbytes) for v in out.values()),
    )
    return out


def compact(name: str) -> dict:
    """Crash-safe segment compaction for one store."""
    entry = resolve(name)
    try:
        return entry.store.compact()
    except StoreCorruptionError as exc:
        telemetry.record_serve_error(exc, what=f"store compact {name}")
        raise StoreCorruptedError(str(exc)) from exc


def list_stores() -> list[dict]:
    """Info dicts for every OPEN store plus the names present under the
    root but not yet opened (listed with ``"open": false``)."""
    with _LOCK:
        rows = [dict(e.info(), open=True) for e in _STORE_TABLE.values()]
        opened = {e.name for e in _STORE_TABLE.values()}
    try:
        root = _root()
    except UnknownStoreError:
        return rows
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return rows
    for n in names:
        if n not in opened and os.path.isfile(os.path.join(root, n, "journal.log")):
            rows.append({"store": n, "open": False})
    return rows


def stores_stats() -> dict:
    """The store table's ``cache.stats()`` panel — a snapshot, never a
    device or disk poll."""
    with _LOCK:
        entries = list(_STORE_TABLE.values())
        infos = [e.store.info() for e in entries]
        return {
            "stores": len(entries),
            "generations": {i["store"]: i["gen"] for i in infos},
            "state_bytes": sum(i["nbytes"] for i in infos),
            "device_cached": sum(1 for e in entries if e.dev is not None),
        }


def staleness_by_store(now: float | None = None) -> dict[str, float]:
    """Seconds since each OPEN store's last acked append (its ``last_ack``
    epoch is the open itself until an append lands) — the raw freshness-SLO
    signal ``flox_tpu.slo`` ticks per evaluation. ``now`` lets the SLO
    plane's injected clock drive the math in tests."""
    t = time.time() if now is None else float(now)
    with _LOCK:
        return {e.name: max(0.0, t - e.last_ack) for e in _STORE_TABLE.values()}


def publish_staleness(now: float | None = None) -> None:
    """Publish per-store ``store.staleness_s|store=<name>`` gauges — called
    by the saturation sampler between requests, so an idle replica's stores
    visibly age on /metrics instead of freezing at their last append."""
    for name, stale_s in staleness_by_store(now).items():
        METRICS.set_gauge(f"store.staleness_s|store={name}", round(stale_s, 3))


def debug_table(top: int | None = None) -> dict:
    """The ``/debug/stores`` payload: per-store rows (highest generation
    first) + the per-store cost-ledger join."""
    with _LOCK:
        rows = sorted((e.info() for e in _STORE_TABLE.values()), key=lambda r: -r["gen"])
    if top:
        rows = rows[:top]
    return {"stores": rows, "cost_by_store": telemetry.cost_by_dataset()}


def restage_all() -> int:
    """Reopen every table entry from its durable directory — the
    device-loss recovery hook, run with the dataset registry's restage
    before ``/readyz`` flips back. Reopening runs the store's full crash
    recovery, and the device-side result caches (dead buffers now) drop;
    the host carry is rebuilt from the checksummed segments, so a store
    answers identically after the cycle. Returns stores restaged."""
    restaged = 0
    with _LOCK:
        for entry in _STORE_TABLE.values():
            try:
                entry.store = IncrementalAggregationStore.open(entry.store.path)
            except (FileNotFoundError, StoreCorruptionError) as exc:
                # a store whose directory died with the device stays in the
                # table but unreadable: queries surface the typed error
                telemetry.record_serve_error(exc, what=f"store restage {entry.name}")
                continue
            entry.dev = None
            entry.dev_gen = -1
            restaged += 1
        _publish_gauges()
    if restaged:
        METRICS.inc("store.restaged", restaged)
        telemetry.event("stores-restaged", stores=restaged)
    return restaged


def clear() -> None:
    """Forget every open store (``cache.clear_all`` calls this; the body
    references ``_STORE_TABLE`` directly for floxlint FLX008). Durable
    state on disk is untouched — a later reference reopens it."""
    _STORE_TABLE.clear()
    METRICS.set_gauge("store.open_stores", 0.0)
    METRICS.set_gauge("store.state_bytes", 0.0)
