"""Per-program circuit breakers: fast-fail a key that keeps failing.

A fatally-failing program key — a payload shape that trips an XLA bug, a
custom option overlay that cannot lower — fails every request sent at it,
and each failure burns a full admission + (attempted) device dispatch
before the waiter learns anything. After ``serve_breaker_threshold``
consecutive fatal failures on ONE program key its breaker opens: further
identical-program requests fail immediately at submit with a typed
:class:`~flox_tpu.serve.dispatcher.CircuitOpenError` carrying the program
label and the cooldown remaining (``retry_after_ms``) — no dispatch, no
device time, and the queue stays clear for healthy programs. After
``serve_breaker_cooldown`` seconds the breaker admits ONE half-open probe
request; the probe's success closes the breaker (the key serves normally
again), its failure re-opens it for a fresh cooldown.

State lives in :data:`_BREAKER_REGISTRY` (program key -> :class:`_Breaker`),
registered in ``cache.clear_all`` / surfaced in ``cache.stats()`` (floxlint
FLX008) and as the ``serve.breakers_open`` saturation gauge +
``serve.breaker_*`` counters on ``/metrics``. Only keys with a recorded
failure ever hold an entry — a healthy replica's registry is empty.
``serve_breaker_threshold = 0`` disables the whole mechanism.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Any

from .. import options, telemetry
from ..telemetry import METRICS

__all__ = [
    "breaker_stats",
    "check",
    "open_breakers",
    "record_failure",
    "record_success",
    "release_probe",
]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class _Breaker:
    """Breaker state for one program key."""

    __slots__ = ("label", "failures", "state", "opened_at", "probing")

    def __init__(self, label: str) -> None:
        self.label = label
        self.failures = 0
        self.state = CLOSED
        self.opened_at = 0.0
        self.probing = False


#: program key -> breaker state; entries exist only for keys that recorded
#: at least one fatal failure (record_success pops the entry, so a healthy
#: replica's registry is empty). Registered in cache.clear_all (FLX008).
_BREAKER_REGISTRY: dict[tuple, _Breaker] = {}
_LOCK = threading.RLock()


def _threshold() -> int:
    return int(options.OPTIONS["serve_breaker_threshold"])


def _cooldown() -> float:
    return float(options.OPTIONS["serve_breaker_cooldown"])


def _breaker_id(key: tuple, label: str) -> str:
    digest = hashlib.blake2b(repr(key).encode(), digest_size=4).hexdigest()
    return f"{label}#{digest}"


def check(key: tuple, label: str) -> None:
    """Admission-time breaker gate for one program key.

    Returns normally for a closed (or absent, or disabled) breaker. For an
    open one inside its cooldown, raises ``CircuitOpenError`` carrying the
    program label and ``retry_after_ms`` — the fast-fail that spares the
    device. Past the cooldown the breaker goes half-open and THIS request
    becomes the probe (concurrent requests keep fast-failing until the
    probe's verdict lands via :func:`record_failure`/:func:`record_success`).
    """
    if not _threshold():
        return
    with _LOCK:
        breaker = _BREAKER_REGISTRY.get(key)
        if breaker is None or breaker.state == CLOSED:
            return
        now = time.monotonic()
        cooldown = _cooldown()
        if breaker.state == OPEN:
            remaining = breaker.opened_at + cooldown - now
            if remaining > 0:
                METRICS.inc("serve.breaker_fastfail")
                raise _open_error(key, breaker, remaining)
            breaker.state = HALF_OPEN
            breaker.probing = True
            METRICS.inc("serve.breaker_half_open")
            telemetry.event("breaker-half-open", program=breaker.label)
            return  # this request is the probe
        # HALF_OPEN: one probe at a time — a second arrival must not pile
        # onto a key whose probe has not answered yet
        if breaker.probing:
            METRICS.inc("serve.breaker_fastfail")
            raise _open_error(key, breaker, cooldown)
        breaker.probing = True


def _open_error(key: tuple, breaker: _Breaker, retry_after_s: float):
    from .dispatcher import CircuitOpenError

    retry_after_ms = max(0.0, retry_after_s) * 1e3
    return CircuitOpenError(
        f"circuit open for program {breaker.label!r} after "
        f"{breaker.failures} consecutive fatal failure(s); "
        f"retry in {retry_after_ms / 1e3:.3f}s",
        program=_breaker_id(key, breaker.label),
        retry_after_ms=retry_after_ms,
    )


def record_failure(key: tuple, label: str) -> None:
    """Count one fatal failure against ``key``; open (or re-open) the
    breaker when the consecutive-failure threshold is reached. Called by
    the dispatcher for fatal-classified dispatch failures and watchdog
    timeouts — never for transient/oom/load-control outcomes."""
    threshold = _threshold()
    if not threshold:
        return
    with _LOCK:
        breaker = _BREAKER_REGISTRY.setdefault(key, _Breaker(label))
        breaker.failures += 1
        if breaker.state == HALF_OPEN:
            # the probe failed: straight back to open, fresh cooldown
            breaker.state = OPEN
            breaker.opened_at = time.monotonic()
            breaker.probing = False
            METRICS.inc("serve.breaker_reopened")
            telemetry.event("breaker-reopen", program=breaker.label)
        elif breaker.state == CLOSED and breaker.failures >= threshold:
            breaker.state = OPEN
            breaker.opened_at = time.monotonic()
            METRICS.inc("serve.breaker_opened")
            telemetry.event(
                "breaker-open", program=breaker.label, failures=breaker.failures
            )
        _publish_gauge()


def release_probe(key: tuple) -> None:
    """The in-flight half-open probe ended WITHOUT a verdict — its dispatch
    outcome was neither a success nor a fatal failure (transient-classified
    error, batch abandoned with every waiter expired, device loss). Re-arm
    the probe slot so the NEXT request becomes the probe; without this the
    breaker would stay half-open with ``probing=True`` forever and
    fast-fail the key permanently."""
    with _LOCK:
        b = _BREAKER_REGISTRY.get(key)
        if b is not None and b.state == HALF_OPEN and b.probing:
            b.probing = False


def record_success(key: tuple) -> None:
    """One successful dispatch on ``key``: the failure streak is over.
    Closes a half-open breaker (the probe succeeded) and drops the entry —
    the registry only tracks failing keys."""
    with _LOCK:
        breaker = _BREAKER_REGISTRY.pop(key, None)
        if breaker is not None and breaker.state != CLOSED:
            METRICS.inc("serve.breaker_closed")
            telemetry.event("breaker-close", program=breaker.label)
        if breaker is not None:
            _publish_gauge()


def _publish_gauge() -> None:
    """The live open-breaker count as a gauge (callers hold ``_LOCK``)."""
    if telemetry.enabled():
        METRICS.set_gauge(
            "serve.breakers_open",
            sum(1 for b in _BREAKER_REGISTRY.values() if b.state != CLOSED),
        )


def open_breakers() -> dict[str, dict[str, Any]]:
    """Every breaker currently open or half-open:
    ``{label#digest: {state, failures, retry_after_ms}}`` — the operator's
    answer to "which programs are being fast-failed right now"."""
    now = time.monotonic()
    cooldown = _cooldown()
    out: dict[str, dict[str, Any]] = {}
    with _LOCK:
        for key, breaker in _BREAKER_REGISTRY.items():
            if breaker.state == CLOSED:
                continue
            remaining = (
                max(0.0, breaker.opened_at + cooldown - now)
                if breaker.state == OPEN
                else 0.0
            )
            out[_breaker_id(key, breaker.label)] = {
                "state": breaker.state,
                "failures": breaker.failures,
                "retry_after_ms": round(remaining * 1e3, 3),
            }
    return out


def breaker_stats() -> dict[str, Any]:
    """The ``cache.stats()["serve_breakers"]`` panel: entry counts per
    state plus the open/half-open detail of :func:`open_breakers`."""
    with _LOCK:
        states = [b.state for b in _BREAKER_REGISTRY.values()]
    return {
        "total": len(states),
        "open": states.count(OPEN),
        "half_open": states.count(HALF_OPEN),
        "tripped": open_breakers(),
    }
