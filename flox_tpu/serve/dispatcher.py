"""Asyncio serving dispatcher: coalescing, micro-batching, admission control.

The library below this layer is call-at-a-time: every ``groupby_reduce``
pays its own dispatch, and concurrent callers race on process-global knobs.
A serving replica amortizes those costs across requests instead:

* **Coalescing** — concurrent requests that lower to the same compiled
  program AND carry the same payload share ONE execution: the first arrival
  creates a leaf with a future, later identical requests (same semantic
  program key — the same identity ``_PROGRAM_CACHE`` / ``_STEP_CACHE`` key
  on, ``trace_fingerprint()`` included — plus the same payload digest)
  attach to that future. K identical requests -> exactly one device
  dispatch, K correct responses (asserted in tests on the
  ``serve.dispatches`` counter).
* **Micro-batching** — program-compatible small requests with *different*
  payloads stack along a new leading axis into one dispatch: B arrays of
  shape ``(..., N)`` sharing codes + aggregation become one ``(B, ..., N)``
  reduction whose row ``i`` is request ``i``'s result. Per-row accumulation
  order is unchanged, so rows are bit-identical to solo runs. Bounded by
  ``serve_microbatch_max`` requests and ``serve_microbatch_max_elems``
  elements (stacking huge payloads would serialize the batch behind one
  giant program rather than amortize dispatch overhead).
* **Admission control** — ``serve_queue_depth`` bounds requests pending in
  the dispatcher (queued + executing); a submit beyond it is load-shed
  immediately (:class:`LoadShedError`) instead of growing a backlog the
  device can never drain. Per-request deadlines (``deadline=`` or
  ``serve_deadline``) cancel still-queued requests with
  :class:`DeadlineExceededError`; a batch whose every waiter expired is
  abandoned without dispatching, so expired requests never poison the queue.
* **Isolation** — each request may carry an ``options`` overlay; execution
  runs under ``options.scoped(**overrides)`` so concurrent requests with
  different knobs (engine, prefetch, telemetry level) never race on the
  process-global OPTIONS dict. The overlay is part of the program key:
  requests only share a dispatch when their execution-relevant knobs agree.

SLO metrics flow through the PR 4/PR 6 telemetry registry: counters
(``serve.requests`` / ``serve.coalesced`` / ``serve.microbatched`` /
``serve.dispatches`` / ``serve.shed`` / ``serve.deadline_exceeded`` /
``serve.errors``) and log-spaced histograms (``serve.queue_ms`` /
``serve.device_ms`` / ``serve.request_ms``) — queue-time vs device-time
split per request, p50/p99 via ``METRICS.percentile``. The tables here
(:data:`_PENDING_REGISTRY`, :data:`_COALESCE_CACHE`,
:data:`_BATCH_REGISTRY`) are registered in ``cache.clear_all`` /
``cache.stats`` (floxlint FLX008).
"""

from __future__ import annotations

import asyncio
import hashlib
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Any

import numpy as np

# options is accessed as a module attribute (options.OPTIONS / scoped),
# never from-bound: test_resilience importlib.reload()s flox_tpu.options,
# and a from-import here would keep reading the pre-reload dict while
# set_options writes to the post-reload one
from .. import options, resilience, telemetry
from ..telemetry import METRICS
from . import breaker

__all__ = [
    "AggregationRequest",
    "CircuitOpenError",
    "DeadlineExceededError",
    "DeviceLostError",
    "Dispatcher",
    "DrainingError",
    "LoadShedError",
    "ServeError",
    "ServeResult",
    "WatchdogTimeoutError",
    "payload_digest",
]


class ServeError(RuntimeError):
    """Base class for serving-layer request failures.

    Every subclass carries a machine-readable :attr:`code` (stable across
    renames — JSON clients branch on it instead of string-matching the
    Python class name) and an optional :attr:`retry_after_ms` hint for
    load-control failures where retrying is the right move. Both ride the
    JSON-lines protocol on error responses."""

    #: stable machine-readable identity of the failure kind
    code = "serve_error"

    def __init__(
        self,
        message: str,
        *,
        retry_after_ms: float | None = None,
        program: str | None = None,
    ) -> None:
        super().__init__(message)
        #: when retrying makes sense, the soonest it plausibly helps (ms)
        self.retry_after_ms = retry_after_ms
        #: the program label the failure is scoped to, where one applies
        self.program = program


class LoadShedError(ServeError):
    """The dispatcher is saturated (``serve_queue_depth`` reached); the
    request was rejected WITHOUT queueing — retry with backoff."""

    code = "load_shed"


class DeadlineExceededError(ServeError):
    """The request's deadline passed before its result was ready; if it was
    still queued, it will never be dispatched."""

    code = "deadline_exceeded"


class CircuitOpenError(ServeError):
    """This request's program key has an OPEN circuit breaker — recent
    requests for the same compiled program failed fatally
    ``serve_breaker_threshold`` times in a row, so the dispatcher fails
    fast instead of burning another dispatch. Carries the program label and
    the cooldown remaining (``retry_after_ms``); after the cooldown one
    probe request is admitted, and its success closes the breaker."""

    code = "circuit_open"


class DeviceLostError(ServeError):
    """The accelerator (or its backend runtime) died under this request's
    dispatch. In-flight waiters get this typed error while the replica
    recovers: readiness flips 503, the backend reinitializes, the AOT
    warmup manifest replays, and readiness returns — retry against the
    fleet (or this replica once ``/readyz`` answers 200 again)."""

    code = "device_lost"


class WatchdogTimeoutError(ServeError):
    """The device dispatch ran past ``serve_watchdog_timeout``: its waiters
    are failed (the queue must not hang behind a wedged program) and a
    flight dump + on-chip-capture hint are left for the operator."""

    code = "watchdog_timeout"


class DrainingError(ServeError):
    """The replica is draining (SIGTERM / ``{"op": "shutdown"}``):
    admission is closed, in-flight requests are finishing. Retry against
    another replica — this process is about to exit."""

    code = "draining"


@dataclass
class AggregationRequest:
    """One aggregation request: a ``groupby_reduce`` call plus serving
    envelope (option overlay, deadline, id). ``array``/``by`` are host
    arrays (anything ``np.asarray`` accepts)."""

    func: Any
    array: Any = None
    by: Any = None
    expected_groups: Any = None
    fill_value: Any = None
    dtype: Any = None
    min_count: int | None = None
    engine: str | None = None
    finalize_kwargs: dict | None = None
    #: ``options.scoped`` overlay active for this request's execution;
    #: part of the program key, so only knob-identical requests share work
    options: dict = field(default_factory=dict)
    #: seconds from submit (queue wait + device time); ``None`` falls back
    #: to ``OPTIONS["serve_deadline"]`` (0 there = no deadline)
    deadline: float | None = None
    request_id: str | None = None
    #: optional W3C trace-context header (``00-<trace>-<parent>-<flags>``):
    #: a request that arrived carrying one (router hop, traced client) runs
    #: under THAT trace id with the parent span linked, and the response
    #: echoes a ``traceparent`` with the same trace id — so the whole
    #: router→replica path joins into ONE trace. Malformed values are
    #: ignored (counted on ``serve.bad_traceparent``), never errors.
    traceparent: str | None = None
    #: optional cost-attribution tag: requests carrying one feed the
    #: per-tenant cost ledger (``cache.stats()["cost_by_tenant"]``) and a
    #: tenant-labeled ``serve.request_ms{tenant=...}`` latency histogram on
    #: /metrics. Attribution only — a tenant tag never changes the program
    #: key, so tagged and untagged requests still coalesce/batch together.
    tenant: str | None = None
    #: optional resident-dataset reference (``serve.registry``): the
    #: request's ``array``/``by`` come from the named put_dataset entry
    #: (data optional — a labels-only entry still accepts inline ``array``
    #: over resident codes). The entry's content fingerprint replaces
    #: payload hashing in the program key, so hits skip JSON payloads,
    #: factorize, H2D, AND digesting. Unknown names answer a typed
    #: :class:`~flox_tpu.serve.registry.UnknownDatasetError`.
    dataset: str | None = None
    #: optional ``[start, stop)`` row-range selector over the dataset's
    #: flattened label axis (device-side slice — no H2D)
    rows: Any = None
    #: optional boolean-mask selector over the same axis (device-side
    #: gather); mutually exclusive with ``rows``
    mask: Any = None


@dataclass
class ServeResult:
    """A served aggregation: the result/groups arrays plus per-request SLO
    attribution. ``result``/``groups`` may be shared with coalesced peers —
    treat them as read-only. Multi-statistic requests (``func`` a tuple of
    names) return ``result`` as a dict mapping func name -> array."""

    result: Any
    groups: np.ndarray
    request_id: str | None = None
    #: whether this request attached to another request's execution
    coalesced: bool = False
    #: leaves in the device dispatch that produced this result
    batch_size: int = 1
    queue_ms: float = 0.0
    device_ms: float = 0.0
    #: the trace id this request ran under: the W3C trace id when the
    #: request carried a valid ``traceparent``, else its request id
    trace_id: str | None = None
    #: the ``traceparent`` to hand the next hop (same trace id, this
    #: replica's handling as the new parent span) — set only for requests
    #: that propagated one in, so untraced traffic sees no new fields
    traceparent: str | None = None


class _Leaf:
    """One unit of work: a unique (program, payload) pair. Coalesced
    requests are extra waiters on the same leaf."""

    __slots__ = (
        "array", "payload_key", "future", "waiters", "t_dispatch",
        "batch_size", "device_ms",
    )

    def __init__(self, array: np.ndarray, payload_key: tuple) -> None:
        self.array = array
        self.payload_key = payload_key
        self.future: asyncio.Future = asyncio.get_running_loop().create_future()
        self.waiters = 1
        self.t_dispatch: float | None = None
        self.batch_size = 1
        self.device_ms = 0.0


class _Batch:
    """An open micro-batch: leaves sharing one program key, dispatched as
    one device call after the batching window closes."""

    __slots__ = (
        "pkey", "leaves", "open", "func", "by", "agg_kwargs", "overrides",
        "dsentry", "dslabel",
    )

    def __init__(
        self, pkey: tuple, func: Any, by: Any,
        agg_kwargs: dict, overrides: dict,
        dsentry: Any = None, dslabel: str | None = None,
    ) -> None:
        self.pkey = pkey
        self.leaves: list[_Leaf] = []
        self.open = True
        self.func = func
        self.by = by
        self.agg_kwargs = agg_kwargs
        self.overrides = overrides
        #: the pinned registry entry this batch dispatches against (the
        #: pin is released when the batch settles), and its billing label
        self.dsentry = dsentry
        self.dslabel = dslabel


#: admission/pending table: every admitted request (queued OR executing),
#: keyed by a process-unique sequence id — ``len()`` is the queue depth the
#: admission check bounds. Registered in cache.clear_all (FLX008).
_PENDING_REGISTRY: dict[int, AggregationRequest] = {}

#: coalescing table: (program key, payload digest) -> live _Leaf. Entries
#: exist from first submit until their dispatch completes, so identical
#: requests attach to queued AND in-flight executions alike.
_COALESCE_CACHE: dict[tuple, _Leaf] = {}

#: open micro-batches: program key -> the joinable _Batch (closed batches
#: leave the table; their dispatch task keeps them alive).
_BATCH_REGISTRY: dict[tuple, _Batch] = {}

_IDS = itertools.count(1)

#: reductions whose results grow axes (quantile's q-dim) or need run-length
#: structure — stacking them along a lead axis would reshape results per
#: request, so they always dispatch alone
_UNBATCHABLE = frozenset(
    {"quantile", "nanquantile", "median", "nanmedian", "mode", "nanmode"}
)


def _is_multi(func: Any) -> bool:
    """A multi-statistic request: ``func`` is a tuple/list of names — one
    ``groupby_aggregate_many`` dispatch serves the whole set."""
    return isinstance(func, (tuple, list)) and all(
        isinstance(f, str) for f in func
    )


def _func_label(func: Any) -> str:
    if isinstance(func, str):
        return func
    if _is_multi(func):
        from ..fusion import fused_program_label

        return fused_program_label(func)
    return "custom"


def _digest_bytes(*parts: bytes) -> str:
    h = hashlib.blake2b(digest_size=16)
    for p in parts:
        h.update(p)
        h.update(b"|")
    return h.hexdigest()


def _array_digest(arr: np.ndarray) -> str:
    arr = np.ascontiguousarray(arr)
    return _digest_bytes(str(arr.dtype).encode(), repr(arr.shape).encode(), arr.tobytes())


def payload_digest(array: Any) -> str:
    """The payload half of a request's coalescing identity — public so the
    chaos harness (``faults.serve_inject(poison_digests=...)``) can target
    one micro-batch member by the exact digest the dispatcher will see."""
    return _array_digest(np.asarray(array))


#: payloads up to this many bytes hash inline on the event-loop thread (a
#: thread hop costs more than the hash there); bigger ones go off-loop
_INLINE_DIGEST_BYTES = 1 << 16


async def _digest_payload(arr: np.ndarray) -> str:
    if arr.nbytes <= _INLINE_DIGEST_BYTES:
        return _array_digest(arr)
    return await asyncio.to_thread(_array_digest, arr)


def _freeze(v: Any) -> Any:
    """Hashable identity of request kwargs for the program key (same
    spirit as ``mapreduce._agg_cache_key``'s ``h``)."""
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, (list, tuple)):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, np.ndarray):
        return ("__ndarray__", _array_digest(v))
    if isinstance(v, float) and np.isnan(v):
        return "__nan__"
    if isinstance(v, np.generic):
        return repr(v)
    if callable(v):
        return (getattr(v, "__qualname__", repr(v)), id(v))
    return v


def _program_key(
    func: Any, arr: np.ndarray, by_digest: str, agg_kwargs: dict, overrides: dict
) -> tuple:
    """Semantic compiled-program identity of a request.

    The same contract as the ``_PROGRAM_CACHE`` / ``_STEP_CACHE`` /
    ``_jitted_bundle`` keys: aggregation identity + static shapes/dtypes +
    codes identity + ``trace_fingerprint()`` (must be evaluated under the
    request's option scope — a request that pins ``segment_sum_impl`` lowers
    a different program). Two requests with equal keys lower to the same
    compiled program, which is what makes sharing a dispatch safe.
    """
    from ..options import trace_fingerprint

    return (
        "reduce",
        func if isinstance(func, str)
        else tuple(func) if _is_multi(func)
        else ("__agg__", id(func)),
        arr.shape,
        str(arr.dtype),
        by_digest,
        _freeze(agg_kwargs),
        _freeze(overrides),
        trace_fingerprint(),
    )


class Dispatcher:
    """The serving front-end: ``await dispatcher.submit(request)``.

    Constructor knobs override the ``OPTIONS`` defaults per instance
    (``None`` reads the option — scope-aware — at each submit). All state
    mutation happens on the event-loop thread; executions run in worker
    threads via ``asyncio.to_thread`` (which propagates contextvars, so the
    request's option scope and telemetry span context follow the work).
    """

    def __init__(
        self,
        *,
        queue_depth: int | None = None,
        deadline: float | None = None,
        microbatch_max: int | None = None,
        microbatch_max_elems: int | None = None,
        batch_window: float | None = None,
    ) -> None:
        self.queue_depth = queue_depth
        self.deadline = deadline
        self.microbatch_max = microbatch_max
        self.microbatch_max_elems = microbatch_max_elems
        self.batch_window = batch_window
        self._tasks: set[asyncio.Task] = set()
        self._draining = False

    @property
    def draining(self) -> bool:
        """Whether admission is closed (:meth:`begin_drain` was called)."""
        return self._draining

    def begin_drain(self) -> None:
        """Close admission: every later :meth:`submit` fails fast with
        :class:`DrainingError`. In-flight requests are unaffected — the
        serve loop awaits them (bounded by ``serve_drain_timeout``) via
        :meth:`close` before exiting."""
        self._draining = True
        METRICS.inc("serve.drains")

    def _knob(self, explicit: Any, name: str) -> Any:
        return explicit if explicit is not None else options.OPTIONS[name]

    async def submit(
        self, request: AggregationRequest | None = None, **kwargs: Any
    ) -> ServeResult:
        """Admit, (maybe) coalesce/batch, execute, and return one request.

        Accepts a prebuilt :class:`AggregationRequest` or its fields as
        keyword arguments. Raises :class:`LoadShedError` at saturation and
        :class:`DeadlineExceededError` past the deadline; any execution
        error propagates to every waiter of the failed dispatch.
        """
        if request is None:
            request = AggregationRequest(**kwargs)
        t0 = time.perf_counter()
        # canary probes get their own admission counter: serve.requests is
        # the availability SLO's denominator, and synthetic known-answer
        # traffic must neither dilute nor burn a user-facing budget
        if request.tenant == telemetry.CANARY_TENANT:
            METRICS.inc("canary.requests")
        else:
            METRICS.inc("serve.requests")
        if self._draining:
            METRICS.inc("serve.drain_rejected")
            raise DrainingError(
                "replica draining: admission closed, in-flight requests "
                "finishing; retry against another replica"
            )
        depth = self._knob(self.queue_depth, "serve_queue_depth")
        if depth and len(_PENDING_REGISTRY) >= depth:
            # canary admission failures land on their own counter:
            # serve.shed is an availability-SLO bad counter, and synthetic
            # probes hitting a saturated queue is not a user-facing outage
            if request.tenant == telemetry.CANARY_TENANT:
                METRICS.inc("canary.shed")
            else:
                METRICS.inc("serve.shed")
            window = float(self._knob(self.batch_window, "serve_batch_window"))
            raise LoadShedError(
                f"dispatcher saturated: {len(_PENDING_REGISTRY)} requests pending "
                f"(serve_queue_depth={depth}); retry with backoff",
                # the soonest a queue slot plausibly frees: one batch window
                # (the granularity at which pending batches dispatch)
                retry_after_ms=max(1.0, window * 1e3),
            )
        rid = next(_IDS)
        _PENDING_REGISTRY[rid] = request
        # end-to-end trace context: the request_id (or a generated one)
        # rides a contextvar into every child span/event this request emits
        # — the batch task and its asyncio.to_thread execution inherit it,
        # so core phase spans, streaming passes, mesh dispatches, and
        # resilience events all carry it in both export formats.
        # observe=False: this layer feeds serve.request_ms itself (always
        # on — SLO histograms don't ride the telemetry switch); the
        # tail-sampling verdict compares against the p99 snapshotted at
        # trace ENTRY, so the request's own mid-trace observation cannot
        # dilute its own verdict
        if request.request_id is None:
            # replica-prefixed: two replicas behind one router each count
            # their own req-N — without the prefix (the configured
            # replica_id, or a per-process fallback) the fleet's ids
            # collide and traces/exemplars/ledger links cross-attribute
            request.request_id = f"{telemetry.replica_instance()}:req-{rid}"
        # trace propagation: a request that arrived with a (valid) W3C
        # traceparent runs under ITS trace id with the remote parent span
        # linked — the whole router→replica hop becomes one joined trace.
        # Without one, the request id roots a fresh local trace as before.
        parsed = (
            telemetry.parse_traceparent(request.traceparent)
            if request.traceparent is not None
            else None
        )
        if request.traceparent is not None and parsed is None:
            METRICS.inc("serve.bad_traceparent")
        trace_ctx, parent_span = parsed if parsed else (request.request_id, None)
        try:
            with telemetry.trace(
                trace_ctx, hist="serve.request_ms", observe=False,
                parent=parent_span,
            ):
                return await self._submit_admitted(
                    request, t0, trace_ctx, propagated=parsed is not None
                )
        finally:
            _PENDING_REGISTRY.pop(rid, None)

    async def _submit_admitted(
        self,
        request: AggregationRequest,
        t0: float,
        trace_ctx: str | None = None,
        propagated: bool = False,
    ) -> ServeResult:
        if isinstance(request.func, list):
            # JSON clients send statistic sets as lists; the program key
            # and the fused planner both want the hashable tuple form
            request.func = tuple(request.func)
        dsentry = None
        dslabel: str | None = None
        if request.dataset is not None:
            # resident-dataset reference: resolve + refcount-pin the entry
            # (the pin rides the batch and is released when its dispatch
            # settles, so eviction/del_dataset never races an in-flight
            # dispatch), and reuse the put-time content fingerprint as the
            # coalescing identity — zero payload hashing on the hit path
            from . import registry

            if request.by is not None or request.expected_groups is not None:
                raise ValueError(
                    "a dataset-referencing request must not also inline "
                    "'by'/'expected_groups' — they were fixed at put time"
                )
            dsentry = registry.resolve(request.dataset)
            registry.pin(dsentry)
            try:
                data_view, pf_view, selkey = registry.view(
                    dsentry, rows=request.rows, mask=request.mask
                )
                by_digest = f"ds:{dsentry.fingerprint}:{selkey}"
                if request.array is not None:
                    # labels-resident mode: per-request data over the
                    # entry's precomputed codes
                    arr = np.asarray(request.array)
                    arr_digest = await _digest_payload(arr)
                elif data_view is None:
                    raise ValueError(
                        f"dataset {request.dataset!r} holds no data array; "
                        "inline 'array' with the request"
                    )
                else:
                    arr = data_view
                    arr_digest = by_digest
            except BaseException:
                registry.unpin(dsentry)
                raise
            by = pf_view
            dslabel = request.dataset
        else:
            if request.rows is not None or request.mask is not None:
                raise ValueError(
                    "'rows'/'mask' selectors require a 'dataset' reference"
                )
            if request.array is None or request.by is None:
                raise ValueError(
                    "inline requests require both 'array' and 'by' "
                    "(or reference a resident 'dataset')"
                )
            arr = np.asarray(request.array)
            by = np.asarray(request.by)
        # fold the submitter's AMBIENT scoped() overlay under the request's
        # own options (request wins): ambient knobs like default_engine
        # change results without appearing in trace_fingerprint(), so they
        # must be part of the program key AND of the execution overlay — a
        # scoped submit never shares a dispatch with differently-scoped
        # peers, and execution no longer depends on whichever task's
        # context the batch task happened to inherit
        overrides = {**options.scope_overrides(), **(request.options or {})}
        agg_kwargs = {
            "expected_groups": request.expected_groups,
            "fill_value": request.fill_value,
            "dtype": request.dtype,
            "min_count": request.min_count,
            "engine": request.engine,
            "finalize_kwargs": request.finalize_kwargs,
        }
        try:
            if dsentry is None:
                # large payloads hash in a worker thread — a multi-hundred-MB
                # blake2b on the event-loop thread would stall every other
                # request's admission, window timer, and deadline check.
                # Memoized per request OBJECT: a resubmitted request (library
                # retry loops) never rehashes an unchanged payload.
                digests = getattr(request, "_payload_digests", None)
                if digests is None:
                    by_digest = await _digest_payload(by)
                    arr_digest = await _digest_payload(arr)
                    request._payload_digests = (by_digest, arr_digest)
                else:
                    by_digest, arr_digest = digests
            # the fingerprint half of the key must see the request's pinned
            # knobs — evaluate under its scope (validates the overlay too, so a
            # bad option name/value fails HERE, not inside a worker thread)
            with options.scoped(**overrides):
                pkey = _program_key(request.func, arr, by_digest, agg_kwargs, overrides)
            # circuit-breaker gate: a program key whose recent dispatches all
            # failed fatally fast-fails HERE (typed CircuitOpenError with the
            # cooldown remaining) — no queue slot, no batch, no device time
            breaker.check(pkey, _func_label(request.func))
        except BaseException:
            if dsentry is not None:
                from . import registry

                registry.unpin(dsentry)
            raise
        payload_key = (pkey, arr_digest)
        deadline = request.deadline
        if deadline is None:
            deadline = self._knob(self.deadline, "serve_deadline")
        deadline = float(deadline) if deadline else None

        leaf = _COALESCE_CACHE.get(payload_key)
        coalesced = leaf is not None
        if coalesced:
            METRICS.inc("serve.coalesced")
            leaf.waiters += 1
            if dsentry is not None:
                # the leaf's own batch already pins the entry; this
                # request only waits on the shared future
                from . import registry

                registry.unpin(dsentry)
        else:
            leaf = _Leaf(arr, payload_key)
            _COALESCE_CACHE[payload_key] = leaf
            self._enqueue(
                leaf, request, arr, by, agg_kwargs, overrides, pkey,
                dsentry=dsentry, dslabel=dslabel,
            )

        try:
            # shield: one waiter's timeout must not cancel the shared leaf
            if deadline is None:
                row, groups = await asyncio.shield(leaf.future)
            else:
                remaining = deadline - (time.perf_counter() - t0)
                if remaining <= 0:
                    raise asyncio.TimeoutError
                row, groups = await asyncio.wait_for(
                    asyncio.shield(leaf.future), remaining
                )
        except (asyncio.TimeoutError, TimeoutError):
            # drop this waiter; a leaf with no waiters left is abandoned at
            # dispatch time (never dispatched), so expired requests cannot
            # poison the queue
            leaf.waiters -= 1
            # same canary split as serve.shed: deadline_exceeded is an
            # availability-SLO bad counter
            if request.tenant == telemetry.CANARY_TENANT:
                METRICS.inc("canary.deadline_exceeded")
            else:
                METRICS.inc("serve.deadline_exceeded")
            raise DeadlineExceededError(
                f"deadline of {deadline:.4f}s exceeded "
                f"({'dispatched' if leaf.t_dispatch else 'still queued'})"
            ) from None
        t1 = time.perf_counter()
        # clamped: a request that attached to an ALREADY-dispatched leaf
        # waited 0, not a negative interval (t_dispatch predates its t0)
        queue_ms = max(0.0, ((leaf.t_dispatch or t1) - t0) * 1e3)
        request_ms = (t1 - t0) * 1e3
        # the SLO canary's known-answer probes stay OUT of the base latency
        # series (user-facing latency SLOs read serve.request_ms) but keep
        # their own labeled series + cost row below — "billed under the
        # reserved tenant, excluded from user-facing SLOs"
        if request.tenant != telemetry.CANARY_TENANT:
            METRICS.observe("serve.request_ms", request_ms, exemplar=request.request_id)
            METRICS.observe("serve.queue_ms", queue_ms, exemplar=request.request_id)
        if request.tenant is not None:
            # the tenant axis: a labeled latency series on /metrics plus a
            # cost-ledger row. The raw tag is client-supplied, so it goes
            # through tenant_label: unsafe characters fold away (no label
            # injection into the exposition) and distinct labels are
            # cardinality-capped (past the cap, "_other"). A coalesced /
            # batched request is billed its SHARE of the shared dispatch's
            # wall — dividing by the leaves dispatched together and this
            # leaf's waiters keeps tenant totals summing to the program
            # walls instead of multiplying them.
            label = telemetry.tenant_label(request.tenant)
            METRICS.observe(
                f"serve.request_ms|tenant={label}",
                request_ms,
                exemplar=request.request_id,
            )
            telemetry.observe_cost(
                tenant=label,
                device_ms=leaf.device_ms
                / (max(1, leaf.batch_size) * max(1, leaf.waiters)),
                nbytes=int(arr.nbytes),
            )
        telemetry.record_span(
            "serve.request", t0, t1,
            attrs={
                "func": _func_label(request.func),
                "coalesced": coalesced, "batch": leaf.batch_size,
            },
        )
        return ServeResult(
            result=row,
            groups=groups,
            request_id=request.request_id,
            coalesced=coalesced,
            batch_size=leaf.batch_size,
            queue_ms=queue_ms,
            device_ms=leaf.device_ms,
            trace_id=trace_ctx if trace_ctx is not None else request.request_id,
            # echo the SAME trace id with this replica's handling as the
            # new parent span — the next hop (or the client's trace UI)
            # chains onto it. Only for requests that propagated one in.
            traceparent=(
                telemetry.format_traceparent(trace_ctx)
                if propagated and trace_ctx is not None
                else None
            ),
        )

    # -- batching -----------------------------------------------------------

    def _batchable(self, request: AggregationRequest, arr: Any) -> bool:
        if request.dataset is not None:
            # registry-referenced payloads are device-resident and shared:
            # stacking them would force a D2H + restage of the very buffers
            # the registry exists to keep pinned (coalescing still applies)
            return False
        if _is_multi(request.func):
            # fused statistic sets contain only batchable reductions
            # (FUSABLE_FUNCS excludes the axis-growing order statistics),
            # and groupby_aggregate_many handles lead axes — multi-stat
            # requests micro-batch exactly like single-stat ones
            pass
        elif not isinstance(request.func, str) or request.func in _UNBATCHABLE:
            return False
        if request.finalize_kwargs:
            return False
        if self._knob(self.microbatch_max, "serve_microbatch_max") <= 1:
            return False
        ceil = self._knob(self.microbatch_max_elems, "serve_microbatch_max_elems")
        return not (ceil and arr.size > ceil)

    def _enqueue(
        self,
        leaf: _Leaf,
        request: AggregationRequest,
        arr: Any,
        by: Any,
        agg_kwargs: dict,
        overrides: dict,
        pkey: tuple,
        dsentry: Any = None,
        dslabel: str | None = None,
    ) -> None:
        batchable = self._batchable(request, arr)
        if batchable:
            batch = _BATCH_REGISTRY.get(pkey)
            if (
                batch is not None
                and batch.open
                and len(batch.leaves)
                < self._knob(self.microbatch_max, "serve_microbatch_max")
            ):
                batch.leaves.append(leaf)
                METRICS.inc("serve.microbatched")
                return
        batch = _Batch(
            pkey, request.func, by, agg_kwargs, overrides,
            dsentry=dsentry, dslabel=dslabel,
        )
        batch.leaves.append(leaf)
        if batchable:
            _BATCH_REGISTRY[pkey] = batch
        window = self._knob(self.batch_window, "serve_batch_window")
        task = asyncio.create_task(self._run_batch(batch, float(window)))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _run_batch(self, batch: _Batch, window: float) -> None:
        try:
            await self._run_batch_inner(batch, window)
        finally:
            if batch.dsentry is not None:
                # the batch settled (delivered, failed, or abandoned):
                # release the registry pin so eviction / del_dataset can
                # reclaim the entry
                from . import registry

                registry.unpin(batch.dsentry)

    async def _run_batch_inner(self, batch: _Batch, window: float) -> None:
        # even window=0 yields the loop once, so same-tick submits coalesce
        await asyncio.sleep(window)
        batch.open = False
        if _BATCH_REGISTRY.get(batch.pkey) is batch:
            _BATCH_REGISTRY.pop(batch.pkey, None)
        live = [leaf for leaf in batch.leaves if leaf.waiters > 0]
        t_dispatch = time.perf_counter()
        for leaf in batch.leaves:
            if leaf.waiters > 0:
                leaf.t_dispatch = t_dispatch
                leaf.batch_size = len(live)
            else:
                # every waiter's deadline expired while queued: abandon the
                # leaf (its future stays unset — nobody is listening)
                _COALESCE_CACHE.pop(leaf.payload_key, None)
        if not live:
            METRICS.inc("serve.batches_abandoned")
            # an abandoned batch resolves nothing: if it carried the
            # breaker's half-open probe, re-arm the probe slot
            breaker.release_probe(batch.pkey)
            return
        try:
            results = await self._dispatch(batch, live)
        except asyncio.CancelledError:
            # a cancelled batch task (drain budget expiry) must propagate,
            # never be classified: cancel still-waiting futures so no
            # waiter hangs, re-dispatch nothing, pollute no breaker
            for leaf in live:
                _COALESCE_CACHE.pop(leaf.payload_key, None)
                if not leaf.future.done():
                    leaf.future.cancel()
            breaker.release_probe(batch.pkey)
            raise
        except BaseException as exc:  # noqa: BLE001 — classified + fanned out
            # the serve-plane fault domain: classify first (the same gate
            # the streaming path consults), then contain the blast radius —
            # device loss triggers backend recovery, a fatal/oom failure of
            # a multi-leaf batch bisects so healthy peers still get
            # results, a single poisoned leaf fails alone (and feeds its
            # program's circuit breaker)
            for leaf in live:
                _COALESCE_CACHE.pop(leaf.payload_key, None)
            await self._contain_failure(
                batch, live, exc, resilience.classify_error(exc)
            )
            return
        finally:
            for leaf in live:
                _COALESCE_CACHE.pop(leaf.payload_key, None)
        breaker.record_success(batch.pkey)
        rows, groups = results
        for leaf, row in zip(live, rows):
            if not leaf.future.done():
                leaf.future.set_result((row, groups))

    # -- fault domain -------------------------------------------------------

    async def _dispatch(self, batch: _Batch, live: list[_Leaf]) -> tuple:
        """One watchdog-guarded device dispatch for ``live``'s leaves.

        ``serve_watchdog_timeout`` bounds the worker-thread execution: a
        dispatch stuck past it fails its waiters with a typed
        :class:`WatchdogTimeoutError` (flight dump + capture hint recorded)
        instead of wedging the queue behind one hung program. The stuck
        thread itself cannot be killed — its eventual result is discarded
        — but every queue decision stops waiting on it."""
        watchdog = float(options.OPTIONS["serve_watchdog_timeout"] or 0)
        if not watchdog:
            return await asyncio.to_thread(self._execute, batch, live)
        try:
            return await asyncio.wait_for(
                asyncio.to_thread(self._execute, batch, live), watchdog
            )
        except (asyncio.TimeoutError, TimeoutError):
            label = _func_label(batch.func)
            METRICS.inc("serve.watchdog_fired")
            telemetry.event(
                "watchdog",
                program=label,
                timeout_s=watchdog,
                hint="dispatch wedged: grab an on-chip capture "
                "(/debug/profile?seconds=N or SIGUSR1) while it hangs",
            )
            # flight_dump writes files: off the loop so the dump of a
            # wedged dispatch cannot also wedge every other queue
            await asyncio.to_thread(
                telemetry.flight_dump, reason=f"watchdog:{label}"
            )
            raise WatchdogTimeoutError(
                f"dispatch for program {label!r} exceeded "
                f"serve_watchdog_timeout={watchdog:g}s; its waiters were "
                "failed so the queue keeps moving",
                program=label,
            ) from None

    async def _contain_failure(
        self, batch: _Batch, live: list[_Leaf], exc: BaseException, cls: str
    ) -> None:
        """Route one classified dispatch failure down its containment path."""
        if isinstance(exc, WatchdogTimeoutError):
            # a hang is not bisectable — re-dispatching sub-batches would
            # hang serially through N more watchdog windows. Fail the whole
            # batch and count it against the program's breaker.
            breaker.record_failure(batch.pkey, _func_label(batch.func))
            self._fail_leaves(live, exc)
            return
        if cls == resilience.DEVICE_LOST:
            await self._handle_device_loss(batch, live, exc)
            return
        if len(live) > 1 and cls in (resilience.FATAL, resilience.OOM):
            # request quarantine: one poisoned member must not take its
            # coalesced/micro-batched peers down with it
            await self._quarantine(batch, live, exc)
            return
        if cls == resilience.FATAL:
            breaker.record_failure(batch.pkey, _func_label(batch.func))
        else:
            # transient/oom outcomes carry no breaker verdict — a pending
            # half-open probe must be re-armed, not leaked
            breaker.release_probe(batch.pkey)
        self._fail_leaves(live, exc)

    async def _quarantine(
        self, batch: _Batch, live: list[_Leaf], cause: BaseException
    ) -> None:
        """Bisect a failed multi-leaf dispatch so only the poisoned member
        fails.

        The split rides the same power-of-two ladder as
        ``resilience.dispatch_slab`` (half the span, rounded up to a power
        of two), so the re-dispatched sub-batch shapes form a small
        reusable set — each rung's stacked program compiles once. Healthy
        sub-batches produce rows bit-identical to solo runs (the PR 7
        micro-batching invariant); a failing sub-batch recurses until the
        poisoned leaf dispatches alone and gets the typed error, which also
        feeds its program's circuit breaker."""
        METRICS.inc("serve.quarantine_splits")
        telemetry.event(
            "quarantine-split",
            program=_func_label(batch.func),
            leaves=len(live),
            error=type(cause).__name__,
        )
        half = resilience._ladder_half(len(live), 1)
        for lo in range(0, len(live), half):
            sub = live[lo : lo + half]
            try:
                results = await self._dispatch(batch, sub)
            except asyncio.CancelledError:
                for leaf in sub:
                    if not leaf.future.done():
                        leaf.future.cancel()
                breaker.release_probe(batch.pkey)
                raise
            except BaseException as sub_exc:  # noqa: BLE001 — classified below
                if isinstance(sub_exc, WatchdogTimeoutError):
                    breaker.record_failure(batch.pkey, _func_label(batch.func))
                    self._fail_leaves(sub, sub_exc)
                    continue
                cls = resilience.classify_error(sub_exc)
                if cls == resilience.DEVICE_LOST:
                    await self._handle_device_loss(batch, sub, sub_exc)
                    continue
                if len(sub) > 1 and cls in (resilience.FATAL, resilience.OOM):
                    await self._quarantine(batch, sub, sub_exc)
                    continue
                # a single leaf failing alone IS the poisoned member
                METRICS.inc("serve.quarantined")
                telemetry.event(
                    "quarantined",
                    program=_func_label(batch.func),
                    error=type(sub_exc).__name__,
                )
                if cls == resilience.FATAL:
                    breaker.record_failure(batch.pkey, _func_label(batch.func))
                else:
                    breaker.release_probe(batch.pkey)
                self._fail_leaves(sub, sub_exc)
                continue
            breaker.record_success(batch.pkey)
            rows, groups = results
            for leaf, row in zip(sub, rows):
                if not leaf.future.done():
                    leaf.future.set_result((row, groups))

    async def _handle_device_loss(
        self, batch: _Batch, live: list[_Leaf], exc: BaseException
    ) -> None:
        """The dispatch died WITH the device: quarantine its waiters behind
        a typed error, flip readiness, and recover the backend.

        Recovery (reinitialize the backend, replay the AOT warmup manifest,
        flip readiness back) runs in a worker thread under a process-wide
        guard — concurrent batches discovering the same dead device fail
        their own waiters but only one recovery cycle runs."""
        from .. import exposition

        METRICS.inc("serve.device_lost")
        # device loss is not a program-key verdict: never counted toward
        # the breaker, but a pending half-open probe must be re-armed
        breaker.release_probe(batch.pkey)
        telemetry.event(
            "device-lost", program=_func_label(batch.func), error=str(exc)[:200]
        )
        # flight_dump writes files: off the loop so recovery latency is
        # not gated on disk speed
        await asyncio.to_thread(telemetry.flight_dump, reason="device-lost")
        exposition.set_ready(False, reason="device-lost")
        self._fail_leaves(
            live,
            DeviceLostError(
                f"device lost under dispatch for program "
                f"{_func_label(batch.func)!r}; replica recovering "
                f"(/readyz 503 until the backend is back): {exc}",
                program=_func_label(batch.func),
            ),
        )
        await asyncio.to_thread(_recover_device)

    def _fail_leaves(self, leaves: list[_Leaf], exc: BaseException) -> None:
        """Fan one failure out to every waiter of ``leaves``."""
        METRICS.inc("serve.errors")
        for leaf in leaves:
            if not leaf.future.done():
                leaf.future.set_exception(exc)
                # mark retrieved: if every waiter timed out meanwhile,
                # an unretrieved exception would warn at GC
                leaf.future.exception()

    def _execute(self, batch: _Batch, live: list[_Leaf]) -> tuple[list, np.ndarray]:
        """One device dispatch for every live leaf of ``batch`` (worker
        thread; contextvars — option scope, span context — propagated by
        ``asyncio.to_thread``)."""
        from . import aot

        # point jax's persistent cache at the AOT dir BEFORE the compile
        # this dispatch may trigger, so the executable is written through
        # (or retrieved) — idempotent no-op when serve_aot_dir is unset
        aot.configure()
        METRICS.inc("serve.dispatches")
        from .. import faults

        # chaos hook: the serve fault plan (faults.serve_inject) fires here,
        # exactly where a real compile/dispatch failure would — one is None
        # check when no plan is installed
        faults.serve_poke(
            _func_label(batch.func),
            tuple(leaf.payload_key[1] for leaf in live),
        )
        # captured ONCE: a set_options(telemetry=True) landing mid-dispatch
        # must not make the post-dispatch block read baselines that were
        # never taken (same discipline as core.chunk_reduce)
        tm_on = telemetry.enabled()
        prog = None
        if tm_on:
            # cost-ledger baseline for this dispatch's compile delta
            compiles0 = telemetry.METRICS.get("jax.compiles")
            compile_ms0 = telemetry.METRICS.get("jax.compile_ms")
            # the serve program key, computed BEFORE the dispatch so the
            # costmodel alias can index whatever program compiles inside
            # it under this serving label (the /debug/programs join key)
            pdigest = _digest_bytes(repr(batch.pkey).encode())[:8]
            prog = "serve[" + _func_label(batch.func) + f"#{pdigest}]"
            # card-analysis baseline: the costmodel's lower+compile runs
            # INSIDE this window (chunk_reduce/fusion record cards mid-
            # dispatch) but is bookkeeping, not served work — net its wall
            # out of device_ms below, like the compile wall is netted by
            # the drift model
            analysis0 = telemetry.METRICS.get("costmodel.card_analysis_ms")
        t0 = time.perf_counter()
        from ..core import groupby_reduce
        from ..costmodel import serve_alias

        kwargs = {k: v for k, v in batch.agg_kwargs.items() if v is not None}
        multi = _is_multi(batch.func)
        with serve_alias(prog), options.scoped(**batch.overrides):
            with telemetry.span(
                "serve.execute", func=_func_label(batch.func), batch=len(live),
            ):
                if multi:
                    # one fused dispatch serves every statistic of every
                    # leaf: the payload is staged once for the whole set
                    from ..fusion import groupby_aggregate_many

                    if len(live) == 1:
                        result, groups = groupby_aggregate_many(
                            live[0].array, batch.by, funcs=batch.func, **kwargs
                        )
                        rows = [{k: np.asarray(v) for k, v in result.items()}]
                        dispatched = live[0].array
                    else:
                        dispatched = np.stack([leaf.array for leaf in live])
                        result, groups = groupby_aggregate_many(
                            dispatched, batch.by, funcs=batch.func, **kwargs
                        )
                        stats = {k: np.asarray(v) for k, v in result.items()}
                        rows = [
                            {k: v[i] for k, v in stats.items()}
                            for i in range(len(live))
                        ]
                elif len(live) == 1:
                    result, groups = groupby_reduce(
                        live[0].array, batch.by, func=batch.func, **kwargs
                    )
                    rows = [np.asarray(result)]
                    dispatched = live[0].array
                else:
                    dispatched = np.stack([leaf.array for leaf in live])
                    result, groups = groupby_reduce(
                        dispatched, batch.by, func=batch.func, **kwargs
                    )
                    result = np.asarray(result)
                    rows = [result[i] for i in range(len(live))]
        groups = np.asarray(groups)
        device_ms = (time.perf_counter() - t0) * 1e3
        if tm_on:
            device_ms = max(
                0.0,
                device_ms
                - (
                    telemetry.METRICS.get("costmodel.card_analysis_ms")
                    - analysis0
                ),
            )
            # HBM pressure right after the dispatch, attributed to THIS
            # program key (cache.stats()["hbm_by_program"]): the digest
            # keeps the label bounded while separating shape/dtype/option
            # variants. Gated: the repr+hash must cost nothing when off.
            telemetry.sample_hbm(program=prog)
            # the program's cost-ledger row: one dispatch (however many
            # coalesced/batched waiters it served), its device wall, the
            # bytes it staged, and the compiles it provoked. nbytes reads
            # .nbytes straight off the dispatched array — np.asarray on a
            # device-resident payload would D2H-copy it just to count it.
            # A registry-referenced dispatch also bills the per-dataset
            # ledger axis (cache.stats()["cost_by_dataset"]).
            telemetry.observe_cost(
                prog,
                dataset=batch.dslabel,
                device_ms=device_ms,
                nbytes=int(getattr(dispatched, "nbytes", 0))
                + int(getattr(batch.by, "nbytes", 0)),
                compiles=int(telemetry.METRICS.get("jax.compiles") - compiles0),
                compile_ms=telemetry.METRICS.get("jax.compile_ms") - compile_ms0,
            )
        METRICS.observe(
            "serve.device_ms", device_ms, exemplar=telemetry.current_trace()
        )
        for leaf in live:
            leaf.device_ms = device_ms
        # dtype via getattr, never np.asarray: a device-resident payload
        # must not round-trip through host memory for a string. Registry
        # dispatches record their RESOLVED post-selector shapes — the
        # inline warmup replay then compiles the identical XLA program
        # (program identity is shapes/dtypes/ngroups, never residency).
        aot.record_reduce(
            func=batch.func,
            shape=tuple(np.shape(dispatched)),
            dtype=str(dispatched.dtype),
            by_shape=tuple(batch.by.shape),
            by_dtype=str(batch.by.dtype),
            ngroups=int(groups.shape[0]) if groups.ndim else 1,
            agg_kwargs=kwargs,
            options=batch.overrides,
            dataset=batch.dslabel,
        )
        return rows, groups

    async def close(self) -> None:
        """Wait for every in-flight batch task to finish (results/errors are
        delivered to their waiters as usual)."""
        while self._tasks:
            await asyncio.gather(*list(self._tasks), return_exceptions=True)


#: one recovery cycle at a time: concurrent batches discovering the same
#: dead device each fail their own waiters, but reinit + warmup + ready
#: must not run twice in parallel (the second cycle would re-tear-down the
#: backend the first just rebuilt)
_RECOVERY_GUARD = threading.Lock()


def _recover_device() -> None:
    """The device-loss recovery cycle (worker thread): reinitialize the
    backend, replay the AOT warmup manifest so the rebuilt backend holds
    live programs again (zero NEW compiles against a warm AOT dir), then
    flip ``/readyz`` back to 200. Failures leave readiness at 503 — a
    replica that could not recover must not take traffic."""
    from .. import device, exposition

    if not _RECOVERY_GUARD.acquire(blocking=False):
        return  # a recovery is already running; it owns the ready flip
    try:
        telemetry.event("device-recovery-start")
        torn_down = device.reinitialize()
        from . import aot

        warmed = aot.warmup()
        # re-pin every registered dataset from its host-side spill copies
        # BEFORE readiness flips: a recovered replica that answered 200
        # while its resident datasets still pointed at dead-device buffers
        # would fail exactly the traffic the router sends it first
        from . import registry

        restaged = registry.restage_all()
        # reopen every durable aggregation store from disk with the same
        # before-readiness ordering: reopening runs crash recovery, drops
        # dead-device result caches, and rebuilds the host carry from the
        # checksummed segments
        from . import stores as store_registry

        stores_restaged = store_registry.restage_all()
        # flip ready back ONLY if the 503 is still ours: a graceful drain
        # that began mid-recovery set reason "draining", and that 503 must
        # hold until the process exits — a recovered-but-draining replica
        # answering 200 would pull router traffic straight into
        # DrainingError
        if exposition.ready_reason() == "device-lost":
            exposition.set_ready(True)
        METRICS.inc("serve.recoveries")
        telemetry.event(
            "device-recovery-done", reinitialized=torn_down, warmed=warmed,
            restaged=restaged, stores_restaged=stores_restaged,
        )
    except Exception as exc:  # noqa: BLE001 — an unrecoverable replica stays
        # unready (503) rather than crashing the loop; the record is the
        # operator's signal to replace it
        telemetry.record_serve_error(exc, what="device-recovery")
    finally:
        _RECOVERY_GUARD.release()
