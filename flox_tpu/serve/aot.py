"""AOT program persistence: compile once, restart warm.

A serving replica's worst request is its first — absent persistence, every
program it serves pays a fresh XLA compile (seconds of wall for a mesh
program) exactly when the replica joins the fleet. Two layers remove that
stall, both rooted at ``OPTIONS["serve_aot_dir"]``:

* **persistent compilation cache** — :func:`configure` points JAX's
  on-disk executable cache (``jax_compilation_cache_dir``) at the AOT
  directory, with the entry-size/compile-time floors lowered so every
  program qualifies (the defaults skip small/fast programs, which on CPU
  test rigs is all of them). Backend compiles — ``jit`` internally runs the
  same ``lower().compile()`` AOT path — are then written through to disk,
  and a restarted process's compiles become cache *retrievals*. The
  telemetry listener nets those retrievals out of ``jax.compiles``, so the
  acceptance counter reads 0 for a warmed program.
* **warmup manifest** — the executable cache is keyed by XLA program hash,
  which a fresh process can only reproduce by *lowering* the same programs
  again, and lowering only happens when a request arrives. The manifest
  (``manifest.json`` in the AOT dir) closes that gap: every dispatch
  records its request spec (:func:`record_reduce` — func, shapes, dtypes,
  group count, option overlay), and :func:`warmup` replays the specs
  against synthetic payloads at startup. Tracing is cheap and host-side;
  the compile lands as a disk hit; the first real request finds a live
  program.

The in-memory manifest memo (:data:`_MANIFEST_MEMO`) is registered in
``cache.clear_all`` / ``cache.stats`` (floxlint FLX008). Persistence is
atomic merge-on-save (tmp + rename, same discipline as the autotune
store): concurrent replicas sharing one AOT dir union their manifests
instead of clobbering each other.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from pathlib import Path
from typing import Any

import numpy as np

# options resolved as a module attribute, never from-bound: tests reload
# flox_tpu.options, and a from-import would read the pre-reload dict while
# set_options writes to the post-reload one
from .. import options, telemetry

logger = logging.getLogger(__name__)

__all__ = ["configure", "deconfigure", "record_reduce", "save_manifest", "warmup"]

_MANIFEST_VERSION = 1
_MANIFEST_NAME = "manifest.json"

#: warmup manifest memo: spec digest -> replayable request spec. Mirrors
#: the on-disk manifest (union of every load + this process's dispatches);
#: registered in cache.clear_all (FLX008) — a clear resets to "never
#: loaded", and the next record/warmup re-reads the disk state.
_MANIFEST_MEMO: dict[str, dict] = {}

# configuration is process-global (jax's cache dir is), so remember what we
# already pointed jax at: re-configuring with the same dir is a no-op,
# switching dirs mid-process is allowed but logged (tests do it; prod won't)
_STATE: dict[str, Any] = {"configured": None, "loaded": None}
_LOCK = threading.Lock()


def _aot_dir(path: Any = None) -> Path | None:
    root = path if path is not None else options.OPTIONS["serve_aot_dir"]
    return Path(root) if root else None


def configure(path: Any = None) -> Path | None:
    """Point JAX's persistent compilation cache at the AOT directory.

    ``path`` defaults to ``OPTIONS["serve_aot_dir"]``; ``None`` there means
    persistence is off and this is a no-op returning ``None``. Idempotent
    per directory; safe to call before every dispatch (the dispatcher
    does). Never raises: a jax too old for the cache config knobs degrades
    to in-process caching with a warning — serving still works, restarts
    just pay the compile.
    """
    root = _aot_dir(path)
    if root is None:
        return None
    with _LOCK:
        if _STATE["configured"] == str(root):
            return root
        root.mkdir(parents=True, exist_ok=True)
        try:
            import jax

            jax.config.update("jax_compilation_cache_dir", str(root))
            # the default floors skip programs that compile fast or lower
            # small — on a CPU test rig that is every program, and on TPU a
            # skipped "fast" compile is still a first-request stall. Persist
            # everything; the dir is bounded by what the replica serves.
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        except Exception as exc:  # noqa: BLE001 — version drift must not break serving
            telemetry.record_serve_error(exc, what="aot.configure")
            logger.warning(
                "persistent compilation cache unavailable (jax too old?); "
                "AOT warmup will re-trace but restarts pay full compiles"
            )
            return None
        if _STATE["configured"] is not None:
            logger.info("AOT cache dir moved %s -> %s", _STATE["configured"], root)
        _STATE["configured"] = str(root)
    return root


def deconfigure() -> None:
    """Detach JAX's persistent compilation cache (the config is
    process-global; tests detach between cases so later compiles stop
    writing through to a dead tmp dir). The manifest memo is untouched —
    ``cache.clear_all`` owns that."""
    with _LOCK:
        if _STATE["configured"] is None:
            return
        try:
            import jax

            jax.config.update("jax_compilation_cache_dir", None)
        except Exception as exc:  # noqa: BLE001
            telemetry.record_serve_error(exc, what="aot.deconfigure")
        _STATE["configured"] = None


def _jsonable(value: Any) -> Any:
    """``value`` rendered JSON-serializable, or raise TypeError: ndarrays
    become lists, numpy scalars become items — anything else non-JSON
    (callables, custom Aggregations) disqualifies the spec from the
    manifest (it cannot be replayed from text)."""
    if isinstance(value, np.ndarray):
        return value.tolist()
    if isinstance(value, np.generic):
        return value.item()
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, type):  # dtype classes like np.float64
        return np.dtype(value).name
    raise TypeError(f"not manifest-serializable: {value!r}")


def record_reduce(
    *,
    func: Any,
    shape: tuple,
    dtype: str,
    by_shape: tuple,
    by_dtype: str,
    ngroups: int,
    agg_kwargs: dict,
    options: dict,
    dataset: str | None = None,
) -> bool:
    """Record one served program's request spec into the warmup manifest.

    Called by the dispatcher after every device dispatch. Returns whether
    the spec was recorded: ``False`` when persistence is off, when the spec
    cannot be replayed from JSON (custom Aggregation objects, callable
    kwargs), or when it is already in the manifest. A *new* spec persists
    the manifest immediately (merge-on-save), so a replica killed mid-run
    still leaves every program it served warmable.

    ``dataset`` stamps registry-referenced dispatches for the operator
    reading the manifest; it is EXCLUDED from the spec digest — program
    identity is shapes/dtypes/ngroups, never residency, so the inline
    warmup replay warms the very program a registry hit runs.
    """
    multi = isinstance(func, (tuple, list)) and all(
        isinstance(f, str) for f in func
    )
    if _aot_dir() is None or not (isinstance(func, str) or multi):
        return False
    try:
        spec = _jsonable(
            {
                "func": list(func) if multi else func,
                "shape": list(shape),
                "dtype": str(dtype),
                "by_shape": list(by_shape),
                "by_dtype": str(by_dtype),
                "ngroups": int(ngroups),
                "agg_kwargs": {k: v for k, v in agg_kwargs.items() if v is not None},
                "options": options,
            }
        )
    except TypeError:
        return False
    digest = spec_digest(spec)
    if dataset is not None:
        # informational only (excluded from the digest above): the replay
        # path ignores it, dedup stays residency-blind
        spec = {**spec, "dataset": str(dataset)}
    with _LOCK:
        if digest in _MANIFEST_MEMO:
            return False
        _MANIFEST_MEMO[digest] = spec
    telemetry.count("serve.aot_recorded")
    save_manifest()
    return True


def spec_digest(spec: dict) -> str:
    """Stable identity of a manifest spec (canonical-JSON blake2b)."""
    import hashlib

    payload = json.dumps(spec, sort_keys=True, separators=(",", ":"))
    return hashlib.blake2b(payload.encode(), digest_size=16).hexdigest()


def _manifest_path(path: Any = None) -> Path | None:
    root = _aot_dir(path)
    return root / _MANIFEST_NAME if root is not None else None


def _load_into_memo(path: Any = None) -> None:
    """Union the on-disk manifest into the memo (corrupt/alien files warn
    and are ignored — a broken manifest must never take serving down)."""
    mpath = _manifest_path(path)
    if mpath is None or not mpath.exists():
        return
    try:
        payload = json.loads(mpath.read_text())
        if payload.get("version") != _MANIFEST_VERSION:
            raise ValueError(f"manifest version {payload.get('version')!r}")
        entries = payload["programs"]
        assert isinstance(entries, dict)
    except Exception as exc:  # noqa: BLE001 — fall back to what we have
        telemetry.record_serve_error(exc, what="aot.load-manifest")
        logger.warning("ignoring unreadable AOT manifest %s: %s", mpath, exc)
        return
    with _LOCK:
        for digest, spec in entries.items():
            _MANIFEST_MEMO.setdefault(digest, spec)


def save_manifest(path: Any = None) -> Path | None:
    """Persist the manifest memo, merged with whatever is on disk.

    Atomic tmp+rename so readers never see a torn file; merge-on-save so
    two replicas sharing the dir union their programs. Returns the path
    written, or ``None`` when persistence is off."""
    mpath = _manifest_path(path)
    if mpath is None:
        return None
    _load_into_memo(path)
    with _LOCK:
        payload = {"version": _MANIFEST_VERSION, "programs": dict(_MANIFEST_MEMO)}
    mpath.parent.mkdir(parents=True, exist_ok=True)
    tmp = mpath.with_name(mpath.name + f".tmp.{os.getpid()}")
    tmp.write_text(json.dumps(payload, sort_keys=True, indent=1))
    tmp.replace(mpath)
    return mpath


def _synthesize(spec: dict) -> tuple[np.ndarray, np.ndarray]:
    """Payload + labels with the spec's compiled-program identity.

    Program identity is shapes/dtypes/group-count, never data: zeros for
    the payload, and labels cycling through exactly ``ngroups`` distinct
    values so factorization finds the recorded group count (which fixes
    the output shape the program was compiled for)."""
    arr = np.zeros(tuple(spec["shape"]), dtype=np.dtype(spec["dtype"]))
    nby = int(np.prod(spec["by_shape"])) if spec["by_shape"] else 1
    ngroups = max(1, int(spec["ngroups"]))
    labels = np.arange(nby) % ngroups
    try:
        labels = labels.astype(spec["by_dtype"])
    except (TypeError, ValueError):
        pass  # exotic label dtype: int labels trace the same program
    return arr, labels.reshape(tuple(spec["by_shape"]))


def warmup(path: Any = None) -> int:
    """Replay every manifest spec so the first real request finds a live,
    disk-warmed program.

    Configures the persistent cache, loads the manifest, and runs each
    recorded spec against synthetic payloads under its recorded option
    scope. Compiles triggered here are served from the persistent cache
    when the dir is warm (``jax.compiles`` stays 0 net of retrievals — the
    acceptance counter) and are written through when it is not (first boot
    populates the dir for the fleet). Returns the number of specs warmed;
    a spec that fails to replay is logged and skipped — warmup must never
    take serving down.
    """
    if configure(path) is None:
        return 0
    # bootstrap the compile listener BEFORE the first replay, so warmup
    # compiles are counted (and netted against cache retrievals) rather
    # than silently missed — the zero-compile assertion is only meaningful
    # if counting was live while the compiles could have happened
    with telemetry.span("serve.warmup"):
        _load_into_memo(path)
        with _LOCK:
            specs = list(_MANIFEST_MEMO.values())
        from ..core import groupby_reduce

        # captured ONCE: telemetry toggled on mid-warmup must not make the
        # post-replay block read baselines that were never taken
        tm_on = telemetry.enabled()
        if tm_on:
            compiles0 = telemetry.METRICS.get("jax.compiles")
            compile_ms0 = telemetry.METRICS.get("jax.compile_ms")
            # costmodel card analysis running inside the replays is
            # bookkeeping — netted out of the warmup row's device wall
            analysis0 = telemetry.METRICS.get("costmodel.card_analysis_ms")
            t_warm0 = time.perf_counter()
        warmed = 0
        from ..costmodel import serve_alias

        for spec in specs:
            try:
                arr, labels = _synthesize(spec)
                kwargs = dict(spec.get("agg_kwargs") or {})
                # cards recorded during the replay also index under the
                # warmup ledger label — the replica's standing program set
                # is card-covered BEFORE the first real request arrives
                with serve_alias("serve.warmup"), options.scoped(
                    **(spec.get("options") or {})
                ):
                    if isinstance(spec["func"], list):
                        # multi-statistic spec: warm the fused program
                        from ..fusion import groupby_aggregate_many

                        groupby_aggregate_many(
                            arr, labels, funcs=tuple(spec["func"]), **kwargs
                        )
                    else:
                        groupby_reduce(arr, labels, func=spec["func"], **kwargs)
                warmed += 1
            # noqa: FLX006 — not a retry loop: specs are independent, and a
            # bad one must be skipped (warmup can never take serving down)
            except Exception as exc:  # noqa: FLX006
                telemetry.record_serve_error(exc, what="aot.warmup-spec")
                logger.warning("AOT warmup skipped %s: %s", spec.get("func"), exc)
        telemetry.count("serve.aot_warmed", warmed)
        # warmup just materialized every program the replica will serve:
        # its HBM mark is the replica's standing footprint before traffic
        telemetry.sample_hbm(program="serve.warmup")
        if tm_on:
            # warmup's ledger row: the replica's startup cost in one place
            # (a warm AOT dir reads compiles == 0 here — the acceptance
            # criterion — a cold one shows exactly what the fleet paid)
            telemetry.observe_cost(
                "serve.warmup",
                dispatches=warmed,
                device_ms=max(
                    0.0,
                    (time.perf_counter() - t_warm0) * 1e3
                    - (
                        telemetry.METRICS.get("costmodel.card_analysis_ms")
                        - analysis0
                    ),
                ),
                compiles=int(telemetry.METRICS.get("jax.compiles") - compiles0),
                compile_ms=telemetry.METRICS.get("jax.compile_ms") - compile_ms0,
            )
    return warmed
