"""End-to-end telemetry: hierarchical spans, a metrics registry, and
compile/retrace tracking for every execution path.

The streaming executor already reported per-slab timings (``StreamReport``),
but the non-streaming core, mesh, and cohort paths were dark: there was no
way to answer "where did this groupby spend its time, how many times did it
compile, and how many bytes crossed H2D". This module is the cross-cutting
observability layer:

* **Hierarchical spans** (:func:`span`): a contextvar-based tracer. Every
  execution path opens a root span (``groupby_reduce``, ``groupby_scan``,
  ``streaming_groupby_reduce``, ...) whose children are the pipeline phases —
  ``factorize`` / ``dispatch`` / ``combine`` / ``finalize`` eagerly,
  ``program-build`` / ``dispatch`` on the mesh, per-pass ``stream[...]``
  spans for the streaming runtimes. Disabled (the default) it is a true
  no-op: :func:`span` returns one shared singleton, no objects are
  allocated, no clocks are read.
* **Metrics registry** (:data:`METRICS`): process-wide counters and gauges —
  compilations, program-cache hits/misses, retrace events (the runtime
  complement to floxlint FLX002's static analysis), H2D/D2H bytes, retries,
  OOM splits, checkpoints. ``cache.clear_all`` resets it with the other
  process-wide state.
* **Compile tracking**: a ``jax.monitoring`` listener counts every backend
  compile and jaxpr trace the process performs (``jax.compiles`` /
  ``jax.traces`` counters, ``jax.compile_ms`` gauge), so a retrace storm is
  a number in the report, not a hunch.
* **Exporters**: JSON-lines (:func:`export_jsonl`) and Chrome trace-event
  format (:func:`export_chrome_trace`) — the latter loads directly in
  ``ui.perfetto.dev`` / ``chrome://tracing``. With
  ``set_options(telemetry_export_path=...)`` (env
  ``FLOX_TPU_TELEMETRY_EXPORT_PATH``) finished records stream to the path:
  ``*.jsonl`` appends incrementally, anything else is written as one Chrome
  trace JSON at :func:`flush` / process exit.
* **Report CLI**: ``python -m flox_tpu.telemetry report <file>`` prints a
  per-phase summary table (count / total / mean / max ms) plus the counter
  snapshot embedded in the export — either format.
* **Request tracing** (:func:`trace`): a contextvar trace context — the
  serving layer binds each request's ``request_id``, and every record a
  traced execution emits (core phase spans, streaming passes, mesh
  dispatches, resilience events — including ones fired on prefetch worker
  threads, which re-bind the stream's trace) carries it in both export
  formats. Tail-based detail: at ``telemetry_level="basic"``,
  ``detailed``-level records produced inside a trace are parked per trace
  and kept only when the trace blows its running p99 (or errors), so a
  slow request's trace is always explainable without paying detailed-level
  volume on every fast one.
* **HBM accounting** (:func:`sample_hbm`): ``device.memory_stats()``
  sampled around dispatches feeds the ``hbm.bytes_in_use`` /
  ``hbm.peak_bytes_in_use`` gauges plus a per-program-key peak table
  surfaced through ``cache.stats()["hbm_by_program"]``.
* **Flight recorder** (:data:`FLIGHT_RECORDER` / :func:`flight_dump`): a
  bounded ring of the most recent records, always on while telemetry is
  enabled. :func:`flight_dump` writes it atomically as JSON-lines (readable
  by the report CLI) to ``OPTIONS["flight_recorder_path"]`` — triggered on
  fatal-classified faults (``resilience.classify_error``), unhandled serve
  loop exceptions, and SIGTERM/SIGUSR2 (:func:`install_signal_dumps`).
* **Live exposition**: ``python -m flox_tpu.telemetry serve-metrics``
  serves the registry over stdlib HTTP as Prometheus text format
  (``/metrics`` + ``/healthz`` + ``/readyz`` — :mod:`flox_tpu.exposition`);
  ``python -m flox_tpu.serve`` embeds the same endpoint.

Knobs (all validated at set time, mirrored from the environment):

* ``telemetry`` (``FLOX_TPU_TELEMETRY``): master switch, default off.
* ``telemetry_level`` (``FLOX_TPU_TELEMETRY_LEVEL``): ``"basic"`` records
  phase spans; ``"detailed"`` adds per-slab staging spans and per-kernel
  dispatch counters on the hot paths.
* ``telemetry_export_path`` (``FLOX_TPU_TELEMETRY_EXPORT_PATH``): stream
  finished records to a file; ``None`` keeps them in the in-process buffer
  (read with :func:`drain` / :func:`spans`).

Instrumentation never changes results: CI runs the tier-1 suite once with
``FLOX_TPU_TELEMETRY=1`` and the enabled/disabled bit-identity is asserted
in tests/test_telemetry.py.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import math
import os
import re
import threading
import time
from collections import deque
from typing import Any, Iterable

__all__ = [
    "CANARY_TENANT",
    "FLIGHT_RECORDER",
    "HIST_EDGES_MS",
    "METRICS",
    "RESIDENT_GAUGES",
    "SATURATION_GAUGES",
    "MetricsRegistry",
    "anchor_event",
    "annotated",
    "card_compile_accounting",
    "cost_by_dataset",
    "cost_by_program",
    "cost_by_tenant",
    "count",
    "current_trace",
    "current_trace_parent",
    "detailed",
    "drain",
    "enabled",
    "event",
    "export_chrome_trace",
    "export_jsonl",
    "flight_dump",
    "flush",
    "format_traceparent",
    "host_name",
    "install_signal_dumps",
    "new_span_hex",
    "observe_cost",
    "parse_traceparent",
    "profile_call",
    "record_serve_error",
    "record_span",
    "replica_id",
    "replica_instance",
    "reset",
    "sample_hbm",
    "sample_resident_state",
    "sample_saturation",
    "seed_hbm_limit",
    "seed_saturation_gauges",
    "span",
    "spans",
    "start_saturation_sampler",
    "stop_saturation_sampler",
    "tail_detail",
    "trace",
]

# perf_counter origin for span timestamps; the wall anchor lets exports
# carry an absolute start time without re-reading two clocks per span
_EPOCH = time.perf_counter()
_WALL0 = time.time()

_PID = os.getpid()

# short host name (label-sanitized): the `host` half of the fleet identity
# every /metrics series, /debug/costs payload, and export stamp carries
try:
    import socket

    _HOST = re.sub(r"[^A-Za-z0-9_.:\-]", "_", socket.gethostname().split(".")[0]) or "?"
except Exception:  # noqa: BLE001 — identity must never break import
    _HOST = "?"


def host_name() -> str:
    """This process's short, label-safe host name."""
    return _HOST


def replica_id() -> str | None:
    """The configured replica identity (``OPTIONS["replica_id"]`` /
    ``FLOX_TPU_REPLICA_ID``), or ``None`` on an unconfigured single-replica
    process — the fleet surfaces (metric labels, export stamps) activate
    only when one is set, so solo deployments stay byte-identical."""
    from .options import OPTIONS

    return OPTIONS["replica_id"]


def replica_instance() -> str:
    """A process-unique replica name: the configured ``replica_id`` when
    set, else a stable per-process fallback (``p<pid>``). Request-id
    generation and the trace-join export stamps use THIS — two replicas an
    operator forgot to name must still never collide."""
    return replica_id() or f"p{_PID}"


def _process_index() -> int:
    """This process's index in a ``jax.distributed`` mesh (0 outside one);
    stamped into jsonl export tails so ``tools/trace_join.py`` can order
    mesh tracks deterministically."""
    try:
        import jax

        return int(jax.process_index())
    except Exception:  # noqa: BLE001 — identity must never break exports
        return 0

#: buffer cap — a runaway instrumented loop must degrade (drop + count),
#: never hold the process's memory hostage
_MAX_RECORDS = 200_000


def enabled() -> bool:
    """Whether telemetry is on (``OPTIONS["telemetry"]``)."""
    from .options import OPTIONS

    return bool(OPTIONS["telemetry"])


def detailed() -> bool:
    """Whether per-slab / per-kernel detail is on (level ``"detailed"``).

    Counter sites gate on this. It stays a strict level check on purpose:
    counters cannot be retracted, so a tail-sampled trace must not inflate
    detailed-only counters (``kernel.trace.*``) — record sites that WANT
    tail sampling gate on :func:`tail_detail` instead."""
    from .options import OPTIONS

    return bool(OPTIONS["telemetry"]) and OPTIONS["telemetry_level"] == "detailed"


def tail_detail() -> bool:
    """Whether detailed-level RECORDS should be produced: level
    ``"detailed"``, or a live :func:`trace` context at ``"basic"`` — there
    the records are parked per trace (``detail=True``) and kept only when
    the trace blows its running p99, so producing them is free for fast
    requests. Records only; counter sites use :func:`detailed`."""
    from .options import OPTIONS

    if not OPTIONS["telemetry"]:
        return False
    return OPTIONS["telemetry_level"] == "detailed" or _TRACE.get() is not None


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


#: fixed log-spaced histogram bucket edges (upper bounds), shared by every
#: histogram: 1 µs .. ~9 min in ms, factor 2 per bucket. Fixed-and-shared is
#: what makes histograms mergeable across processes and exports — the
#: autotune store and the report CLI both rely on it.
HIST_EDGES_MS: tuple[float, ...] = tuple(0.001 * 2.0**i for i in range(30))


def _hist_bucket(value_ms: float) -> int:
    """Index of the first bucket whose upper edge holds ``value_ms`` (the
    last bucket absorbs overflow)."""
    for i, edge in enumerate(HIST_EDGES_MS):
        if value_ms <= edge:
            return i
    return len(HIST_EDGES_MS) - 1


class MetricsRegistry:
    """Process-wide counters, gauges and histograms, thread-safe.

    Counters only ever increase (``inc``); gauges hold the latest value
    (``set_gauge``) or a running max (``max_gauge``); histograms
    (``observe``) count observations into the fixed log-spaced
    :data:`HIST_EDGES_MS` buckets, from which ``percentile`` interpolates
    p50/p99-style summaries. ``snapshot`` returns a plain dict of
    counters+gauges for exports and the bench rows (histograms travel
    separately via ``histograms()`` — they are vectors, not scalars);
    ``reset`` zeroes everything (wired into ``cache.clear_all``).
    """

    def __init__(self) -> None:
        # RLock, not Lock: the SIGTERM/SIGUSR2 flight-dump handler runs ON
        # the main thread between bytecodes and reads the registry — if the
        # signal lands while that same thread holds the lock in inc(), a
        # plain Lock would deadlock the dump instead of writing it
        self._lock = threading.RLock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, dict] = {}

    def inc(self, name: str, value: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def max_gauge(self, name: str, value: float) -> None:
        with self._lock:
            if value > self._gauges.get(name, float("-inf")):
                self._gauges[name] = value

    def get(self, name: str, default: float = 0) -> float:
        with self._lock:
            if name in self._counters:
                return self._counters[name]
            return self._gauges.get(name, default)

    def observe(self, name: str, value: float, exemplar: str | None = None) -> None:
        """Count one observation into ``name``'s log-spaced histogram.

        ``exemplar`` (a trace/request id) is remembered per BUCKET for the
        max observation that landed there — the exposition layer emits it
        OpenMetrics-style on the ``_bucket`` line, so an operator reading a
        p99 blow-up on /metrics gets the trace id of the request that put
        the worst observation in that bucket, not just a count."""
        with self._lock:
            hist = self._hists.get(name)
            if hist is None:
                hist = self._hists[name] = {
                    "counts": [0] * len(HIST_EDGES_MS),
                    "count": 0,
                    "sum": 0.0,
                    "min": float("inf"),
                    "max": float("-inf"),
                    # bucket index -> [trace id, value] of the bucket's max
                    # exemplar-carrying observation (sparse: only buckets
                    # that ever saw a traced observation hold a slot)
                    "exemplars": {},
                }
            value = float(value)
            bucket = _hist_bucket(value)
            hist["counts"][bucket] += 1
            hist["count"] += 1
            hist["sum"] += value
            hist["min"] = min(hist["min"], value)
            hist["max"] = max(hist["max"], value)
            if exemplar is not None:
                slot = hist["exemplars"].get(bucket)
                if slot is None or value >= slot[1]:
                    hist["exemplars"][bucket] = [str(exemplar), value]

    def histograms(self) -> dict[str, dict]:
        """A deep copy of every histogram (name -> counts/count/sum/min/max/
        exemplars); bucket upper edges are the shared :data:`HIST_EDGES_MS`."""
        with self._lock:
            return {
                name: {
                    **hist,
                    "counts": list(hist["counts"]),
                    "exemplars": {
                        b: list(slot)
                        for b, slot in hist.get("exemplars", {}).items()
                    },
                }
                for name, hist in self._hists.items()
            }

    def percentile(self, name: str, q: float) -> float | None:
        """The ``q``-quantile (0..1) of ``name``'s histogram, interpolated
        within the holding bucket and clamped to the observed min/max (so
        p0/p100 are exact). ``None`` for an unknown or empty histogram."""
        with self._lock:
            hist = self._hists.get(name)
            if hist is None or not hist["count"]:
                return None
            return _hist_percentile(hist, q)

    def counters(self) -> dict[str, float]:
        """A copy of the counters alone — the Prometheus renderer needs the
        counter/gauge split ``snapshot`` merges away (counters get the
        ``_total`` suffix and the ``counter`` TYPE, gauges do not)."""
        with self._lock:
            return dict(self._counters)

    def gauges(self) -> dict[str, float]:
        """A copy of the gauges alone (see :meth:`counters`)."""
        with self._lock:
            return dict(self._gauges)

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            return {**self._counters, **self._gauges}

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


def _hist_percentile(hist: dict, q: float) -> float:
    """Percentile from a bucket-count vector: walk the cumulative counts to
    the target rank, then interpolate linearly inside the holding bucket
    (lower edge = previous bucket's upper edge, 0 for the first)."""
    target = max(0.0, min(1.0, q)) * hist["count"]
    cum = 0
    for i, c in enumerate(hist["counts"]):
        if not c:
            continue
        if cum + c >= target:
            lo = HIST_EDGES_MS[i - 1] if i else 0.0
            hi = HIST_EDGES_MS[i]
            frac = (target - cum) / c
            value = lo + frac * (hi - lo)
            return min(max(value, hist["min"]), hist["max"])
        cum += c
    return hist["max"]


METRICS = MetricsRegistry()


def count(name: str, value: float = 1) -> None:
    """Increment a counter — only when telemetry is enabled, so the
    disabled mode leaves the registry untouched (asserted in tests)."""
    if enabled():
        METRICS.inc(name, value)


# ---------------------------------------------------------------------------
# span tracer
# ---------------------------------------------------------------------------

_CURRENT: contextvars.ContextVar["_Span | None"] = contextvars.ContextVar(
    "flox_tpu_span", default=None
)
#: the active trace id (a request_id in the serving layer): every record
#: emitted while it is set carries it, so one request's spans are joinable
#: across core/streaming/mesh/resilience in both export formats
_TRACE: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "flox_tpu_trace", default=None
)
#: the REMOTE parent span of the active trace (the ``parent-id`` half of a
#: client-supplied W3C ``traceparent``): root-level records emitted inside
#: the trace carry it as ``trace_parent``, which is what lets
#: ``tools/trace_join.py`` hang a replica's spans under the hop that sent
#: the request (router→replica, client→replica) in ONE joined trace
_TRACE_PARENT: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "flox_tpu_trace_parent", default=None
)
_IDS = itertools.count(1)

# ---------------------------------------------------------------------------
# W3C trace-context (traceparent) propagation
# ---------------------------------------------------------------------------

#: ``version-traceid-parentid-flags`` per the W3C trace-context spec; the
#: serve protocol accepts exactly this shape (lowercase hex, version != ff,
#: ids nonzero) and ignores anything else rather than guessing
_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


def parse_traceparent(header: Any) -> tuple[str, str] | None:
    """``(trace_id, parent_span_id)`` from a W3C ``traceparent`` string,
    or ``None`` for anything malformed (wrong shape, uppercase hex, the
    forbidden ``ff`` version, all-zero ids) — a bad header degrades to a
    locally rooted trace, never to an error or a half-parsed id."""
    if not isinstance(header, str):
        return None
    m = _TRACEPARENT_RE.match(header.strip())
    if m is None:
        return None
    version, trace_id, parent_id, _flags = m.groups()
    if version == "ff" or set(trace_id) == {"0"} or set(parent_id) == {"0"}:
        return None
    return trace_id, parent_id


def _hex_trace_id(trace_id: str) -> str:
    """``trace_id`` as 32 lowercase hex chars: pass-through when it already
    is one (a propagated W3C id), else a stable blake2b digest of it — so a
    plain request id still formats into a valid ``traceparent``."""
    if (
        len(trace_id) == 32
        and set(trace_id) != {"0"}
        and _TRACEPARENT_RE.match(f"00-{trace_id}-{'1' * 16}-01")
    ):
        return trace_id
    import hashlib

    digest = hashlib.blake2b(trace_id.encode(), digest_size=16).hexdigest()
    # an (astronomically unlikely) all-zero digest would format into the
    # spec's forbidden all-zero trace id — nudge it valid
    return digest if set(digest) != {"0"} else "1" + digest[1:]


def new_span_hex() -> str:
    """A fresh 16-hex span id, unique per process AND across replicas (the
    pid + replica instance are folded in): the replica's own hop identity
    in the ``traceparent`` it echoes downstream."""
    import hashlib

    seed = f"{replica_instance()}|{_PID}|{next(_IDS)}|{time.perf_counter_ns()}"
    return hashlib.blake2b(seed.encode(), digest_size=8).hexdigest()


def format_traceparent(trace_id: str, span_id: str | None = None) -> str:
    """A W3C ``traceparent`` for ``trace_id`` (hex-normalized via
    :func:`_hex_trace_id`) with ``span_id`` (or a fresh one) as the
    parent-id field — what a replica echoes so the NEXT hop keeps the same
    trace and parents onto this replica's handling."""
    return f"00-{_hex_trace_id(str(trace_id))}-{span_id or new_span_hex()}-01"

#: per-trace parked detail records (tail-based sampling at level="basic"):
#: trace id -> records kept only if the trace blows its running p99.
#: Registered in cache.clear_all (floxlint FLX008).
_TAIL_REGISTRY: dict[str, list] = {}
#: detail-record cap per trace — one runaway streaming request must not
#: hold unbounded parked records hostage while its trace is open
_TAIL_MAX_PER_TRACE = 1024

# finished records (span + event dicts) pending export/drain. RLock: the
# signal-handler flight dump (and its flush) may interrupt this very thread
# mid-_commit — a plain Lock would deadlock the dump (see MetricsRegistry)
_RECORDS: list[dict] = []
_RECORDS_LOCK = threading.RLock()
# serializes file appends: concurrent batch flushes from prefetch-worker
# and consumer threads must not interleave mid-line in the export file
# (RLock: the signal-handler flush may interrupt an in-progress append)
_EXPORT_LOCK = threading.RLock()
_EXPORT_STATE: dict[str, Any] = {"atexit": False, "listener": False}


class _NoopSpan:
    """The disabled-mode span: one shared instance, every method a no-op —
    ``span()`` allocates nothing when telemetry is off."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False

    def set(self, **attrs: Any) -> "_NoopSpan":
        return self


_NOOP = _NoopSpan()


class _Span:
    """One live span. Context-manager protocol; ``set`` attaches attributes
    any time before exit. Finished spans append a plain-dict record to the
    buffer (and stream to the export path, if configured)."""

    __slots__ = ("name", "attrs", "span_id", "parent_id", "_t0", "_token", "_tid")

    def __init__(self, name: str, attrs: dict) -> None:
        self.name = name
        self.attrs = attrs
        self.span_id = next(_IDS)
        self.parent_id: int | None = None
        self._t0 = 0.0
        self._token: contextvars.Token | None = None
        self._tid = threading.get_ident()

    def __enter__(self) -> "_Span":
        parent = _CURRENT.get()
        self.parent_id = parent.span_id if parent is not None else None
        self._token = _CURRENT.set(self)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> bool:
        t1 = time.perf_counter()
        if self._token is not None:
            _CURRENT.reset(self._token)
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        _emit(
            {
                "type": "span",
                "name": self.name,
                "id": self.span_id,
                "parent": self.parent_id,
                "tid": self._tid,
                "ts_us": round((self._t0 - _EPOCH) * 1e6, 1),
                "dur_us": round((t1 - self._t0) * 1e6, 1),
                "attrs": self.attrs,
            }
        )
        return False

    def set(self, **attrs: Any) -> "_Span":
        self.attrs.update(attrs)
        return self


def span(name: str, **attrs: Any):
    """Open a hierarchical span: ``with telemetry.span("factorize"): ...``.

    Returns the shared no-op singleton when telemetry is disabled — no
    allocation, no clock read. Nesting is tracked through a contextvar, so
    spans opened on worker threads become roots of their own stacks (they
    still interleave correctly by timestamp in the trace view).
    """
    if not enabled():
        return _NOOP
    _bootstrap()
    return _Span(name, attrs)


def annotated(name: str, **attrs: Any):
    """A span that ALSO opens a ``jax.profiler.TraceAnnotation``, so the
    region shows up inside xprof/TensorBoard device traces next to the XLA
    ops it covers (the mesh dispatch paths use this). Falls back to a plain
    span if the profiler API is unavailable."""
    if not enabled():
        return _NOOP
    try:
        import jax

        annotation = jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001 — profiling must never break execution
        return span(name, **attrs)
    return _AnnotatedSpan(span(name, **attrs), annotation)


class _AnnotatedSpan:
    __slots__ = ("_span", "_annotation")

    def __init__(self, sp: Any, annotation: Any) -> None:
        self._span = sp
        self._annotation = annotation

    def __enter__(self) -> Any:
        self._span.__enter__()
        self._annotation.__enter__()
        return self._span

    def __exit__(self, *exc: Any) -> bool:
        self._annotation.__exit__(*exc)
        return self._span.__exit__(*exc)


def record_span(
    name: str,
    t0: float,
    t1: float,
    attrs: dict | None = None,
    parent_id: int | None = None,
    detail: bool = False,
) -> None:
    """Record an already-timed span (``t0``/``t1`` from ``perf_counter``).

    For code that cannot hold a ``with`` block open across its timing — the
    streaming generator records one span per finished pass this way, with
    the ``StreamReport`` totals as attributes. ``detail=True`` marks the
    span as detailed-level: at ``telemetry_level="basic"`` it is parked on
    the active trace and survives only if the trace blows its running p99."""
    if not enabled():
        return
    _bootstrap()
    if parent_id is None:
        parent = _CURRENT.get()
        parent_id = parent.span_id if parent is not None else None
    _emit(
        {
            "type": "span",
            "name": name,
            "id": next(_IDS),
            "parent": parent_id,
            "tid": threading.get_ident(),
            "ts_us": round((t0 - _EPOCH) * 1e6, 1),
            "dur_us": round((t1 - t0) * 1e6, 1),
            "attrs": attrs or {},
        },
        detail=detail,
    )


def event(name: str, **attrs: Any) -> None:
    """Record an instant event (retry, OOM split, checkpoint, resume).

    Events are standalone records — resilience events fire on prefetch
    worker threads where no span context exists, and an instant mark at the
    right timestamp is exactly what the trace view needs there."""
    if not enabled():
        return
    _bootstrap()
    parent = _CURRENT.get()
    _emit(
        {
            "type": "event",
            "name": name,
            "id": next(_IDS),
            "parent": parent.span_id if parent is not None else None,
            "tid": threading.get_ident(),
            "ts_us": round((time.perf_counter() - _EPOCH) * 1e6, 1),
            "attrs": attrs,
        }
    )


def record_serve_error(exc: BaseException, what: str = "") -> None:
    """Record one serve-plane exception into the flight ring + counters.

    The sanctioned tail of a broad ``except`` in ``flox_tpu/serve/`` that
    answers the error instead of re-raising it (floxlint FLX012): the
    handler must either consult ``resilience.classify_error`` or leave a
    flight-recorder trace through this — a swallowed serve error must never
    be invisible to the crash forensics. No-op when telemetry is off; never
    raises (the handler's own answer must not be masked)."""
    if not enabled():
        return
    try:
        METRICS.inc("serve.swallowed_errors")
        event("serve-error", what=what, error=type(exc).__name__, detail=str(exc)[:200])
    except Exception:  # noqa: BLE001 — forensics never break the answer path
        pass


def current_set(**attrs: Any) -> None:
    """Attach attributes to the innermost live span, if any."""
    sp = _CURRENT.get() if enabled() else None
    if sp is not None:
        sp.attrs.update(attrs)


# ---------------------------------------------------------------------------
# request tracing: trace context + tail-based detail sampling
# ---------------------------------------------------------------------------


def current_trace() -> str | None:
    """The active trace id, or ``None`` outside any :func:`trace` context
    (worker-thread code rebinds it via ``trace(..., observe=False)`` — a
    plain thread does not inherit the submitting context's contextvars)."""
    return _TRACE.get()


def current_trace_parent() -> str | None:
    """The active trace's REMOTE parent span id (the ``parent-id`` of the
    ``traceparent`` the request arrived with), or ``None`` for a locally
    rooted trace."""
    return _TRACE_PARENT.get()


def trace(
    trace_id: Any,
    hist: str = "trace_ms",
    observe: bool = True,
    parent: str | None = None,
):
    """Bind a trace context: ``with telemetry.trace(request_id): ...``.

    Every record emitted inside (phase spans, streaming passes, mesh
    dispatches, resilience events) carries ``trace_id``, in the buffer and
    in both export formats. On exit the trace's duration is compared with
    the running p99 of the ``hist`` histogram (and observed into it, unless
    ``observe=False`` — the serving layer feeds ``serve.request_ms``
    itself): a trace that blew the p99, or errored, promotes its parked
    ``detailed``-level records into the buffer; a fast one drops them. The
    no-op singleton is returned when telemetry is disabled — no allocation.

    ``parent`` is the REMOTE parent span id for a trace that began on
    another process (the ``parent-id`` half of a W3C ``traceparent`` — the
    serve layer passes the parsed header through): root-level records then
    carry it as ``trace_parent``, which ``tools/trace_join.py`` uses to
    hang this process's spans under the sending hop in one joined trace.
    """
    if not enabled():
        return _NOOP
    _bootstrap()
    return _Trace(str(trace_id), hist, observe, parent)


class _Trace:
    __slots__ = (
        "trace_id", "_hist", "_observe", "_token", "_ptoken", "_parent",
        "_t0", "_owns_tail", "_p99",
    )

    def __init__(
        self, trace_id: str, hist: str, observe: bool, parent: str | None = None
    ) -> None:
        self.trace_id = trace_id
        self._hist = hist
        self._observe = observe
        self._parent = parent
        self._token: contextvars.Token | None = None
        self._ptoken: contextvars.Token | None = None
        self._t0 = 0.0
        self._owns_tail = False
        self._p99: float | None = None

    def __enter__(self) -> "_Trace":
        from .options import OPTIONS

        self._token = _TRACE.set(self.trace_id)
        if self._parent is not None:
            self._ptoken = _TRACE_PARENT.set(str(self._parent))
        if OPTIONS["telemetry_level"] != "detailed":
            # open the tail-parking buffer for this trace; detail records
            # emitted inside land here instead of the main buffer. Only the
            # OPENING binding owns the buffer and the keep/drop verdict —
            # a worker-thread rebinding of a live trace must never pop the
            # root's parked records mid-request
            with _RECORDS_LOCK:
                if self.trace_id not in _TAIL_REGISTRY:
                    _TAIL_REGISTRY[self.trace_id] = []
                    self._owns_tail = True
            if self._owns_tail:
                # the verdict compares against the distribution this trace
                # JOINED: snapshot the p99 at entry, so neither this trace
                # (the serve layer observes its own latency mid-trace with
                # observe=False) nor its contemporaries dilute the bar a
                # cold-start outlier is judged against
                self._p99 = METRICS.percentile(self._hist, 0.99)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> bool:
        dur_ms = (time.perf_counter() - self._t0) * 1e3
        if self._ptoken is not None:
            _TRACE_PARENT.reset(self._ptoken)
            self._ptoken = None
        if self._token is not None:
            _TRACE.reset(self._token)
            self._token = None
        parked = None
        if self._owns_tail:
            with _RECORDS_LOCK:
                parked = _TAIL_REGISTRY.pop(self.trace_id, None)
        if self._observe:
            METRICS.observe(self._hist, dur_ms, exemplar=self.trace_id)
        if parked:
            # keep on error, on blowing the entry-time p99, or when there
            # was no distribution to compare against (the first traced
            # request after a restart IS the cold-start outlier worth
            # explaining — dropping it for lack of a baseline would lose
            # exactly the trace the feature exists for)
            if exc_type is not None or self._p99 is None or dur_ms > self._p99:
                METRICS.inc("telemetry.tail_kept", len(parked))
                for rec in parked:
                    if rec.get("type") == "span":
                        # promoted spans feed the per-phase histograms HERE
                        # — dropped ones never do, so /metrics shows the
                        # same per-phase distributions whether or not fast
                        # requests were traced
                        METRICS.observe(
                            "span_ms." + rec["name"],
                            rec.get("dur_us", 0.0) / 1e3,
                            exemplar=rec.get("trace"),
                        )
                _commit(parked)
            else:
                METRICS.inc("telemetry.tail_dropped", len(parked))
        return False


# ---------------------------------------------------------------------------
# flight recorder: bounded ring of recent records, dumped on crash signals
# ---------------------------------------------------------------------------


class _FlightRecorder:
    """A bounded ring of the most recent span/event records.

    Always fed while telemetry is enabled (``_emit`` appends every record);
    the deque's ``maxlen`` (``OPTIONS["flight_recorder_size"]``) makes the
    allocation fixed — the oldest record falls out first. :func:`flight_dump`
    snapshots it to disk when the process is about to die."""

    __slots__ = ("_ring", "_lock")

    def __init__(self) -> None:
        self._ring: deque | None = None
        # RLock for the same reason as the registry's: the signal-handler
        # dump snapshots the ring on the thread that may be mid-append
        self._lock = threading.RLock()

    def append(self, record: dict) -> None:
        from .options import OPTIONS

        cap = OPTIONS["flight_recorder_size"]
        with self._lock:
            if self._ring is None or self._ring.maxlen != cap:
                self._ring = deque(self._ring or (), maxlen=cap)
            self._ring.append(record)

    def records(self) -> list[dict]:
        with self._lock:
            return list(self._ring or ())

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring or ())

    def clear(self) -> None:
        with self._lock:
            self._ring = None


#: the process-wide ring; registered in cache.clear_all (floxlint FLX008)
FLIGHT_RECORDER = _FlightRecorder()


def _breaker_snapshot() -> dict:
    """``cache.stats()["serve_breakers"]`` for the flight-dump header —
    imported lazily and guarded, since a dump must succeed even on a
    process that never touched the serve plane (or mid-interpreter-
    shutdown when the import machinery is already torn down)."""
    try:
        from .serve.breaker import breaker_stats

        return breaker_stats()
    except Exception:  # noqa: BLE001 — forensics are best-effort by contract
        return {}


def _alert_snapshot() -> dict:
    """Current SLO alert state for the flight-dump header (same lazy
    guarded contract as :func:`_breaker_snapshot`: a dump must succeed on
    a process that never evaluated an objective)."""
    try:
        from . import slo

        return slo.alert_snapshot()
    except Exception:  # noqa: BLE001 — forensics are best-effort by contract
        return {}


def flight_dump(path: Any = None, reason: str = "") -> str | None:
    """Dump the flight-recorder ring atomically as JSON-lines.

    ``path`` defaults to ``OPTIONS["flight_recorder_path"]`` (env
    ``FLOX_TPU_FLIGHT_RECORDER_PATH``); ``None`` there means dumping is off
    and this is a no-op returning ``None`` (so the fault-path triggers cost
    nothing unconfigured). The file is a header event + the ring records +
    a counters line — exactly what ``python -m flox_tpu.telemetry report``
    reads. Written tmp+rename, so a crash mid-dump never leaves a torn
    file; never raises (a failing dump must not mask the original fault).
    """
    from .options import OPTIONS

    if path is None:
        path = OPTIONS["flight_recorder_path"]
    if path is None or not enabled():
        return None
    try:
        METRICS.inc("flight.dumps")
        records = FLIGHT_RECORDER.records()
        header = {
            "type": "event",
            "name": "flight-recorder",
            "id": 0,
            "ts_us": round((time.perf_counter() - _EPOCH) * 1e6, 1),
            "tid": threading.get_ident(),
            "attrs": {
                "reason": reason,
                "records": len(records),
                "pid": _PID,
                "wall": time.time(),
                "replica": replica_instance(),
                "host": _HOST,
                # breaker + saturation state AT CRASH TIME: the ring holds
                # spans, but a post-mortem's first questions — was a
                # breaker open, was the queue building — need the live
                # state, not an inference from record archaeology
                "breakers": _breaker_snapshot(),
                # ...and since PR 19, whether an SLO alert was already
                # pending/firing when the dump fired — "was this crash
                # the incident or a symptom of one" in one line
                "alerts": _alert_snapshot(),
                "saturation": {
                    name: METRICS.get(name) for name in SATURATION_GAUGES
                },
            },
        }
        path = str(path)
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = f"{path}.tmp.{_PID}"
        with open(tmp, "w") as f:  # noqa: FLX015 — page-transition forensic dump: rare by design, and losing the loop for one write beats losing the evidence
            for rec in [header, *records, _counters_record()]:
                f.write(json.dumps(rec, default=str) + "\n")
        os.replace(tmp, path)  # noqa: FLX015 — atomic publish of the dump above; same rare page-transition path
        return path
    except Exception as exc:  # noqa: BLE001 — dumping is best-effort by contract
        import logging

        logging.getLogger(__name__).warning("flight-recorder dump failed: %s", exc)
        return None


def install_signal_dumps(sigterm: bool = True) -> None:
    """Dump the flight recorder on SIGTERM (then die with the default
    disposition, so exit codes stay honest) and on SIGUSR2 (dump and keep
    running — the operator's "what are you doing right now" poke). Only
    callable from the main thread; the standalone metrics endpoint installs
    this at startup. The serve loop passes ``sigterm=False`` and owns
    SIGTERM itself: there it triggers the graceful drain (finish in-flight
    requests, flight-dump, exit 0) instead of dying 143 mid-request. No-op
    on platforms missing the signals."""
    import signal

    def _dump(signum: int, frame: Any) -> None:
        try:
            name = signal.Signals(signum).name
        except ValueError:
            name = str(signum)
        flight_dump(reason=f"signal:{name}")
        if signum == getattr(signal, "SIGTERM", None):
            flush()
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

    names = ("SIGTERM", "SIGUSR2") if sigterm else ("SIGUSR2",)
    for signame in names:
        signum = getattr(signal, signame, None)
        if signum is None:
            continue
        try:
            signal.signal(signum, _dump)
        except (ValueError, OSError):  # not the main thread / exotic platform
            return


# ---------------------------------------------------------------------------
# cost ledger + device-memory accounting
# ---------------------------------------------------------------------------


#: per-program / per-tenant cost ledger: ``(axis, label)`` ->
#: dispatches / device_ms (total + max) / bytes / compiles / compile_ms /
#: hbm_peak / last_slow_trace. ``axis`` is ``"program"`` (the compiled-
#: program key the caches and the serve coalescer share — the unit of cost
#: in a system whose native speed all lives in XLA programs) or
#: ``"tenant"`` (the serve layer's optional request tag). Absorbs the old
#: per-program HBM table: :func:`sample_hbm` writes its peaks into the
#: same entries the dispatch sites feed, so "which program is eating the
#: chip" and "which program is eating device time" are one row. Surfaced
#: via ``cache.stats()["cost_by_program"]`` / ``/debug/costs`` /
#: ``python -m flox_tpu.telemetry costs``; registered in cache.clear_all
#: (floxlint FLX008).
_COST_LEDGER: dict[tuple[str, str], dict] = {}


def _cost_entry(axis: str, label: str) -> dict:
    """The (axis, label) ledger row, created empty on first touch.
    Callers hold ``_RECORDS_LOCK``."""
    entry = _COST_LEDGER.get((axis, label))
    if entry is None:
        entry = _COST_LEDGER[(axis, label)] = {
            "dispatches": 0,
            "device_ms": 0.0,
            "device_ms_max": 0.0,
            "bytes": 0,
            "compiles": 0,
            "compile_ms": 0.0,
            "hbm_peak": 0.0,
            "last_slow_trace": None,
        }
    return entry


def observe_cost(
    program: str | None = None,
    *,
    tenant: str | None = None,
    dataset: str | None = None,
    dispatches: int = 1,
    device_ms: float = 0.0,
    nbytes: int | float = 0,
    compiles: int = 0,
    compile_ms: float = 0.0,
) -> None:
    """Attribute one dispatch's cost to its program key (and tenant, and
    — for registry-referenced serve dispatches — resident dataset).

    Called from the same sites that sample HBM — the eager kernel bundle,
    the mesh program dispatch, the streaming pass end, the serve execute,
    and AOT warmup. ``device_ms`` is host-observed dispatch wall (the
    serving layer's device-time proxy), ``nbytes`` the payload staged for
    the dispatch, ``compiles``/``compile_ms`` the ``jax.compiles`` /
    ``jax.compile_ms`` delta the dispatch provoked. A dispatch that sets a
    new ``device_ms_max`` inside a live :func:`trace` records the trace id
    as ``last_slow_trace`` — the ledger row links straight to the flight /
    export records of the worst request it ever served. No-op (no lock, no
    allocation) when telemetry is off."""
    if not enabled():
        return
    trace_id = _TRACE.get()
    program_entry: dict | None = None
    with _RECORDS_LOCK:
        for axis, label in (
            ("program", program), ("tenant", tenant), ("dataset", dataset),
        ):
            if label is None:
                continue
            entry = _cost_entry(axis, str(label))
            entry["dispatches"] += dispatches
            entry["device_ms"] += float(device_ms)
            entry["bytes"] += int(nbytes)
            entry["compiles"] += int(compiles)
            entry["compile_ms"] += float(compile_ms)
            if float(device_ms) >= entry["device_ms_max"]:
                entry["device_ms_max"] = float(device_ms)
                if trace_id is not None:
                    entry["last_slow_trace"] = trace_id
            if axis == "program":
                program_entry = dict(entry)
    if program is not None and program_entry is not None:
        from .options import OPTIONS

        if OPTIONS["costmodel"]:
            # roofline join at dispatch time: the ledger row meets its
            # compiled-program card and the program.utilization /
            # program.predicted_ms gauges update (outside the ledger lock
            # — the registry takes its own)
            from . import costmodel

            costmodel.publish_gauges(str(program), program_entry)


def _ledger_axis(axis: str) -> dict[str, dict]:
    """A locked deep copy of one ledger axis (label -> row) — stats queries
    on the event-loop thread never race a worker-thread dispatch mid-copy."""
    with _RECORDS_LOCK:
        return {
            label: dict(entry)
            for (ax, label), entry in _COST_LEDGER.items()
            if ax == axis
        }


def cost_by_program() -> dict[str, dict]:
    """The per-program-key cost ledger (a locked copy)."""
    return _ledger_axis("program")


def cost_by_tenant(include_canary: bool = False) -> dict[str, dict]:
    """The per-tenant cost ledger (a locked copy; populated only by serve
    requests that carry a ``tenant`` tag). The reserved canary tenant's
    row is synthetic traffic, dropped from the user-facing default view
    (``include_canary=True`` keeps it — the raw ledger is never lossy)."""
    rows = _ledger_axis("tenant")
    if not include_canary:
        rows.pop(CANARY_TENANT, None)
    return rows


def cost_by_dataset() -> dict[str, dict]:
    """The per-resident-dataset cost ledger (a locked copy; populated only
    by serve dispatches that referenced a registry entry) — the operator's
    answer to "which pinned dataset is earning its HBM"."""
    return _ledger_axis("dataset")


#: distinct tenant labels admitted so far — the cardinality bound for the
#: tenant ledger axis AND the labeled /metrics histograms. Client-supplied
#: tags past the cap fold into "_other" instead of allocating a fresh
#: histogram per unique string (an untrusted client must not be able to
#: grow registry memory without bound). Registered in cache.clear_all.
_TENANT_LABELS: dict[str, bool] = {}
_TENANT_MAX = 64
#: characters allowed through in a tenant label — everything else folds to
#: ``_`` so a client-chosen tag can never inject label syntax (quotes,
#: the registry's ``|key=value`` separator, newlines) into the exposition
_TENANT_UNSAFE = re.compile(r"[^A-Za-z0-9_.:\-]")

#: the reserved tenant the SLO plane's canary prober bills its known-answer
#: requests under. Always resolvable as a label but NEVER admitted into
#: :data:`_TENANT_LABELS` (synthetic traffic must not consume one of the
#: :data:`_TENANT_MAX` real-tenant cardinality slots) and filtered out of
#: user-facing surfaces (``cost_by_tenant`` rows, base latency histograms).
CANARY_TENANT = "__canary__"


def tenant_label(tenant: Any, register: bool = True) -> str:
    """The sanitized, cardinality-bounded label for a client tenant tag.

    The serve layer passes every request's raw ``tenant`` through here
    before using it as a ledger key or a metric label: unsafe characters
    fold to ``_``, length is capped, and once :data:`_TENANT_MAX` distinct
    labels exist, new ones collapse into ``"_other"`` (their cost is still
    counted — just not per-tenant). ``register=False`` sanitizes without
    admitting a new label — read-side callers (the ``/debug/costs``
    ``?tenant=`` filter) must not burn cardinality slots on lookups."""
    label = _TENANT_UNSAFE.sub("_", str(tenant))[:64] or "_"
    if label == CANARY_TENANT:
        # the reserved canary tenant never occupies a cardinality slot
        return label
    with _RECORDS_LOCK:
        if label in _TENANT_LABELS:
            return label
        if not register:
            return label
        if len(_TENANT_LABELS) >= _TENANT_MAX:
            return "_other"
        _TENANT_LABELS[label] = True
    return label


def sample_hbm(program: str | None = None) -> None:
    """Sample ``device.memory_stats()`` into the HBM gauges.

    Called around dispatches (eager bundle, mesh program, streaming pass,
    serving execute). Feeds ``hbm.bytes_in_use`` (latest) and
    ``hbm.peak_bytes_in_use`` (running max — the allocator's own peak when
    it reports one); with ``program`` set, also attributes the observed
    ``bytes_in_use`` to that program's cost-ledger row (``hbm_peak``), so
    an operator can see WHICH compiled program is eating the chip. No-op
    when telemetry is off or the backend exposes no memory stats (CPU)."""
    if not enabled():
        return
    from . import device

    stats = device.memory_stats()
    if not stats:
        return
    in_use = float(stats.get("bytes_in_use", 0.0))
    peak = float(stats.get("peak_bytes_in_use", in_use))
    METRICS.set_gauge("hbm.bytes_in_use", in_use)
    METRICS.max_gauge("hbm.peak_bytes_in_use", peak)
    limit = stats.get("bytes_limit")
    if limit:
        # per-device capacity summed by device.memory_stats(): the
        # denominator that makes the in-use gauge an HBM fraction
        METRICS.set_gauge("hbm.bytes_limit", float(limit))
    if program is not None:
        with _RECORDS_LOCK:
            entry = _cost_entry("program", program)
            if in_use > entry["hbm_peak"]:
                entry["hbm_peak"] = in_use


def seed_hbm_limit() -> None:
    """Publish the ``hbm.bytes_limit`` gauge (per-device HBM capacity
    summed by ``device.memory_stats()``) once, at metrics-server start —
    utilization math and the ``fleet top`` HBM column need the denominator
    BEFORE the first dispatch samples it. No-op while telemetry is off or
    when no device reports a capacity (CPU)."""
    if not enabled():
        return
    from . import device

    stats = device.memory_stats()
    limit = (stats or {}).get("bytes_limit")
    if limit:
        METRICS.set_gauge("hbm.bytes_limit", float(limit))


def hbm_by_program() -> dict[str, float]:
    """Per-program peak HBM — the ``hbm_peak`` column of the cost ledger,
    kept as its own view because "which program is eating the chip" is the
    question an OOM postmortem starts with. Only rows that ever observed a
    sample appear (a CPU backend with no memory stats contributes none)."""
    return {
        label: entry["hbm_peak"]
        for label, entry in cost_by_program().items()
        if entry["hbm_peak"] > 0.0
    }


# ---------------------------------------------------------------------------
# saturation sampler: live gauges between requests
# ---------------------------------------------------------------------------


#: the live saturation gauges the sampler publishes. Seeded to 0 when the
#: metrics endpoint starts (exposition.start_metrics_server), so a freshly
#: booted replica exposes the series BEFORE its first request — an absent
#: series reads as a broken scrape, a zero reads as idle.
SATURATION_GAUGES: tuple[str, ...] = (
    "serve.queue_depth",
    "serve.inflight_batches",
    "serve.breakers_open",
    "stream.prefetch_occupancy",
)

#: resident-state gauges (dataset registry occupancy + store footprint)
#: the sampler also publishes between requests — freshness SLOs need a
#: staleness signal on an IDLE replica, exactly when no append is
#: refreshing the store gauges. Seeded with the saturation gauges; the
#: per-store ``store.staleness_s|store=`` series are labeled (dynamic)
#: and appear with the first sample instead.
RESIDENT_GAUGES: tuple[str, ...] = (
    "registry.bytes",
    "registry.pinned_bytes",
    "registry.budget_bytes",
    "registry.occupancy",
    "store.open_stores",
    "store.state_bytes",
)

_SAMPLER_LOCK = threading.Lock()
_SAMPLER_STATE: dict[str, Any] = {"thread": None, "stop": None}


def seed_saturation_gauges() -> None:
    """Publish every saturation gauge at 0 unless it is already live — a
    metrics-endpoint restart must never rewind a gauge the sampler (or a
    dispatcher) is actively feeding. No-op while telemetry is off (the
    disabled path leaves the registry untouched, as everywhere)."""
    if not enabled():
        return
    live = METRICS.gauges()
    for name in (*SATURATION_GAUGES, *RESIDENT_GAUGES):
        if name not in live:
            METRICS.set_gauge(name, 0)


def sample_saturation() -> None:
    """One sample of the live saturation gauges: serve queue depth and
    open micro-batches, prefetch-pool occupancy, and the HBM gauges.

    The histograms answer "how did requests do"; these answer "what is the
    process doing RIGHT NOW" — queue building, prefetch pool drained, HBM
    climbing — which is visible between requests, exactly when the
    post-hoc histograms are silent. Never raises (sampler contract)."""
    if not enabled():
        return
    try:
        from .serve.dispatcher import _BATCH_REGISTRY, _PENDING_REGISTRY

        METRICS.set_gauge("serve.queue_depth", len(_PENDING_REGISTRY))
        METRICS.set_gauge("serve.inflight_batches", len(_BATCH_REGISTRY))
    except Exception:  # noqa: BLE001 — sampling must never take serving down
        pass
    try:
        from .serve.breaker import open_breakers

        METRICS.set_gauge("serve.breakers_open", len(open_breakers()))
    except Exception:  # noqa: BLE001
        pass
    try:
        from .pipeline import prefetch_occupancy

        METRICS.set_gauge("stream.prefetch_occupancy", prefetch_occupancy())
    except Exception:  # noqa: BLE001
        pass
    sample_resident_state()
    sample_hbm()


def sample_resident_state() -> None:
    """One sample of the resident-state gauges: dataset-registry occupancy
    against its HBM budget and per-store append staleness.

    Resident state (PR 17 datasets, PR 18 stores) outlives any request, so
    its health is invisible to the request histograms by construction —
    this is the between-requests signal the freshness SLO and the fleet
    resident-state columns read. Never raises (sampler contract); each
    source is guarded separately so a serve plane that never imported
    cannot block the other's gauges."""
    if not enabled():
        return
    try:
        from .serve.registry import budget_bytes, registry_stats

        budget = float(budget_bytes())
        stats = registry_stats()
        METRICS.set_gauge("registry.bytes", float(stats["bytes"]))
        METRICS.set_gauge("registry.pinned_bytes", float(stats["pinned_bytes"]))
        METRICS.set_gauge("registry.budget_bytes", budget)
        METRICS.set_gauge(
            "registry.occupancy",
            round(float(stats["bytes"]) / budget, 4) if budget > 0 else 0.0,
        )
    except Exception:  # noqa: BLE001 — sampling must never take serving down
        pass
    try:
        from .serve import stores as serve_stores

        serve_stores.publish_staleness()
    except Exception:  # noqa: BLE001
        pass


def start_saturation_sampler(interval: float | None = None) -> bool:
    """Start the opt-in saturation-sampler daemon thread.

    ``interval`` defaults to ``OPTIONS["metrics_sample_interval"]`` — 0
    there (the default) means the sampler stays off and this returns
    ``False``. Idempotent while a sampler is running; the thread is a
    daemon fed by an Event, so :func:`stop_saturation_sampler` (and
    process exit) never hang on it. Returns whether a sampler is live."""
    from .options import OPTIONS

    if interval is None:
        interval = OPTIONS["metrics_sample_interval"]
    if not interval or not enabled():
        return False
    with _SAMPLER_LOCK:
        thread = _SAMPLER_STATE["thread"]
        if thread is not None and thread.is_alive():
            return True
        stop = threading.Event()
        period = float(interval)

        def _run() -> None:
            while not stop.wait(period):
                sample_saturation()

        thread = threading.Thread(
            target=_run, name="flox-tpu-saturation", daemon=True
        )
        _SAMPLER_STATE.update(thread=thread, stop=stop)
        thread.start()
    return True


def stop_saturation_sampler() -> None:
    """Stop the sampler thread (tests; the endpoint teardown calls this)."""
    with _SAMPLER_LOCK:
        stop = _SAMPLER_STATE["stop"]
        thread = _SAMPLER_STATE["thread"]
        _SAMPLER_STATE.update(thread=None, stop=None)
    if stop is not None:
        stop.set()
    if thread is not None:
        thread.join(timeout=2)


#: jsonl streaming appends in batches of this many records — one
#: open/write/close per span would compete with the prefetch workers the
#: pipeline exists to keep busy (flush() and atexit drain the remainder)
_JSONL_BATCH = 64


def _emit(record: dict, detail: bool = False) -> None:
    from .options import OPTIONS

    tid = _TRACE.get()
    if tid is not None:
        record["trace"] = tid
        # a remote parent attaches to ROOT-level records only: the local
        # span hierarchy already links everything below them, so one
        # trace_parent per root is exactly what the join tool needs
        parent_span = _TRACE_PARENT.get()
        if parent_span is not None and record.get("parent") is None:
            record["trace_parent"] = parent_span
    rid = OPTIONS["replica_id"]
    if rid is not None:
        # fleet identity on every record: jsonl/flight files from N
        # replicas stay attributable after they are merged or joined
        record["replica"] = rid
    # the flight ring sees EVERY record (bounded: oldest falls out), so a
    # crash dump always holds the freshest activity regardless of export
    # configuration or tail-sampling verdicts
    FLIGHT_RECORDER.append(record)
    if detail and OPTIONS["telemetry_level"] != "detailed":
        # tail-based sampling: park the record on its trace WITHOUT feeding
        # the histograms — a dropped record must leave no registry mark
        # (promotion observes span_ms then), or traced-but-fast requests
        # would inflate /metrics with detail the verdict discarded. Detail
        # without a trace context never reaches here — tail_detail() is
        # False there.
        if tid is None:
            return
        with _RECORDS_LOCK:
            buf = _TAIL_REGISTRY.get(tid)
            if buf is not None and len(buf) < _TAIL_MAX_PER_TRACE:
                buf.append(record)
        return
    if record.get("type") == "span":
        # every finished span feeds the per-phase latency histogram — the
        # p50/p99 source for the report CLI, the Perfetto export, and the
        # serving-layer SLO metrics (ROADMAP item 1). The trace id rides as
        # the bucket exemplar, so a /metrics p99 row names the request
        METRICS.observe(
            "span_ms." + record["name"],
            record.get("dur_us", 0.0) / 1e3,
            exemplar=tid,
        )
    _commit([record])


def _commit(records: list[dict]) -> None:
    """Append finished records to the buffer (and stream a jsonl batch out
    when one is due) — the shared tail of :func:`_emit` and the tail-kept
    promotion in :class:`trace`."""
    from .options import OPTIONS

    path = OPTIONS["telemetry_export_path"]
    with _RECORDS_LOCK:
        if len(_RECORDS) + len(records) > _MAX_RECORDS:
            METRICS.inc("telemetry.dropped_records", len(records))
            return
        _RECORDS.extend(records)
        stream_now = (
            path is not None
            and str(path).endswith(".jsonl")
            and len(_RECORDS) >= _JSONL_BATCH
        )
        batch = list(_RECORDS) if stream_now else None
        if stream_now:
            _RECORDS.clear()
    if stream_now and batch:
        _append_jsonl(str(path), batch)


def _bootstrap() -> None:
    """One-time side wiring for an enabled session: the atexit flush and the
    jax.monitoring compile listener."""
    if not _EXPORT_STATE["atexit"]:
        _EXPORT_STATE["atexit"] = True
        import atexit

        atexit.register(flush)
    if not _EXPORT_STATE["listener"]:
        _EXPORT_STATE["listener"] = True
        _install_jax_listener()


#: thread-local compile-accounting route: the costmodel's card analysis
#: lowers+compiles programs that are never executed — those compile events
#: must count on ``costmodel.card_*``, not ``jax.compiles`` (whose value
#: the AOT zero-compile acceptance and the per-program ledger depend on).
#: Thread-local because jax compiles synchronously on the calling thread,
#: so the monitoring events fire on the thread that set the route.
_COMPILE_ROUTE = threading.local()


class card_compile_accounting:
    """Scope under which jax compile/trace monitoring events count on the
    ``costmodel.card_*`` counters instead of ``jax.compiles``/``jax.traces``
    — the costmodel's analysis compiles are bookkeeping, not served work."""

    __slots__ = ("_prev",)

    def __enter__(self) -> "card_compile_accounting":
        self._prev = getattr(_COMPILE_ROUTE, "route", None)
        _COMPILE_ROUTE.route = "costmodel"
        return self

    def __exit__(self, *exc: Any) -> bool:
        _COMPILE_ROUTE.route = self._prev
        return False


def _install_jax_listener() -> None:
    """Count every backend compile / jaxpr trace the process performs.

    The listener registers once and gates on :func:`enabled` per event, so a
    later ``set_options(telemetry=False)`` stops the counting without
    needing (unsupported) listener removal. A jax without ``monitoring``
    degrades to the cache-layer counters only."""
    try:
        from jax import monitoring
    except Exception:  # noqa: BLE001 — version drift must not break import
        return

    def _on_duration(name: str, duration_s: float, **kw: Any) -> None:
        if not enabled():
            return
        if getattr(_COMPILE_ROUTE, "route", None) == "costmodel":
            # card-analysis compiles: real wall, but not served programs —
            # routed so `jax.compiles` keeps meaning NEW backend work
            if name.endswith("backend_compile_duration"):
                METRICS.inc("costmodel.card_compiles")
                METRICS.inc("costmodel.card_compile_ms", duration_s * 1e3)
            elif name.endswith("jaxpr_trace_duration"):
                METRICS.inc("costmodel.card_traces")
            return
        if name.endswith("backend_compile_duration"):
            METRICS.inc("jax.compiles")
            METRICS.inc("jax.compile_ms", duration_s * 1e3)
        elif name.endswith("jaxpr_trace_duration"):
            # every trace counts; re-traces of an already-compiled program
            # show up as traces in excess of compiles — the runtime
            # complement to floxlint FLX002's static recompile-trap analysis
            METRICS.inc("jax.traces")

    def _on_event(name: str, **kw: Any) -> None:
        if not enabled():
            return
        if getattr(_COMPILE_ROUTE, "route", None) == "costmodel":
            # a card compile served from the persistent cache must not net
            # -1 against jax.compiles (its +1 was routed away above)
            if name.endswith("compilation_cache/cache_hits"):
                METRICS.inc("costmodel.card_cache_hits")
            return
        if name.endswith("compilation_cache/cache_hits"):
            # jax fires backend_compile_duration even when the persistent
            # compilation cache serves the executable (the event wraps the
            # whole compile call, retrieval included), with a paired
            # cache_hits event on the retrievals. Net those out so
            # `jax.compiles` means what the serving acceptance criterion
            # needs it to mean: NEW backend compilations — a replica warmed
            # from a persistent cache dir (serve/aot.py) reads 0.
            METRICS.inc("jax.compiles", -1)
            METRICS.inc("jax.persistent_cache_hits")
        elif name.endswith("compilation_cache/cache_misses"):
            METRICS.inc("jax.persistent_cache_misses")

    try:
        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:  # noqa: BLE001
        return
    try:
        monitoring.register_event_listener(_on_event)
    except Exception:  # noqa: BLE001 — older jax without plain-event
        pass  # listeners keeps the duration counters; hits go uncounted


# ---------------------------------------------------------------------------
# buffer access + exporters
# ---------------------------------------------------------------------------


def spans() -> list[dict]:
    """A copy of the buffered records (spans + events), oldest first."""
    with _RECORDS_LOCK:
        return list(_RECORDS)


def drain() -> list[dict]:
    """Remove and return all buffered records."""
    with _RECORDS_LOCK:
        out = list(_RECORDS)
        _RECORDS.clear()
    return out


def reset() -> None:
    """Clear the record buffer, the metrics registry (exemplar slots
    included), the flight-recorder ring, the parked tail buffers, and the
    cost ledger (tests; ``cache.clear_all`` resets the same state)."""
    with _RECORDS_LOCK:
        _RECORDS.clear()
        _TAIL_REGISTRY.clear()
        _COST_LEDGER.clear()
        _TENANT_LABELS.clear()
    FLIGHT_RECORDER.clear()
    METRICS.reset()
    # the compiled-program cards annotate the ledger being dropped; a
    # reset must not leave cards pointing at vanished observations
    from .costmodel import _CARD_LABELS, _CARD_REGISTRY

    _CARD_REGISTRY.clear()
    _CARD_LABELS.clear()
    # the SLO plane judges the counters being dropped; alert state and
    # burn-rate window snapshots must not outlive their evidence
    from . import slo

    slo.clear()


def _counters_record() -> dict:
    return {
        "type": "counters",
        "counters": METRICS.snapshot(),
        "histograms": METRICS.histograms(),
        "hist_edges_ms": list(HIST_EDGES_MS),
        "wall0": _WALL0,
        # fleet/mesh identity + a fresh two-clock anchor: trace_join reads
        # these to give each process its own Perfetto track and to shift
        # its monotonic timestamps onto the shared wall clock
        "replica": replica_instance(),
        "host": _HOST,
        "pid": _PID,
        "process_index": _process_index(),
        "anchor": {
            "wall": time.time(),
            "ts_us": round((time.perf_counter() - _EPOCH) * 1e6, 1),
        },
    }


def anchor_event() -> None:
    """Emit a ``clock-anchor`` instant event pairing the wall clock with
    the process's monotonic span clock (plus its replica/mesh identity).

    ``tools/trace_join.py`` prefers the freshest anchor it finds when
    aligning per-process files onto one timeline — emit one near the work
    being joined (the serve loop emits one at startup; the mesh smoke
    emits one per process) so monotonic-vs-wall drift since import cannot
    skew the merged trace. No-op while telemetry is off."""
    event(
        "clock-anchor",
        wall=time.time(),
        replica=replica_instance(),
        host=_HOST,
        pid=_PID,
        process_index=_process_index(),
    )


def export_jsonl(path: str, records: Iterable[dict] | None = None) -> None:
    """Write records as JSON-lines: one record object per line, with a final
    ``{"type": "counters", ...}`` snapshot line."""
    records = spans() if records is None else list(records)
    parent = os.path.dirname(str(path))
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
        f.write(json.dumps(_counters_record()) + "\n")


def _append_jsonl(path: str, records: list[dict]) -> None:
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with _EXPORT_LOCK, open(path, "a") as f:  # noqa: FLX015 — bounded page-cache append; batch export is best-effort by contract
        for rec in records:
            f.write(json.dumps(rec) + "\n")


def to_chrome_trace(records: Iterable[dict] | None = None) -> dict:
    """Records -> one Chrome trace-event JSON object (Perfetto-loadable).

    Spans become complete (``"ph": "X"``) events, instants become
    thread-scoped instant (``"ph": "i"``) events; the counter snapshot rides
    the top-level ``floxTpuCounters`` key (the trace-event format allows
    extra top-level metadata keys)."""
    records = spans() if records is None else list(records)
    trace_events = []
    for rec in records:
        # the trace context rides args (Chrome events have no trace field),
        # so a request_id is searchable in Perfetto exactly like in jsonl
        args = dict(rec.get("attrs") or {})
        if rec.get("trace") is not None:
            args["trace_id"] = rec["trace"]
        if rec.get("type") == "span":
            trace_events.append(
                {
                    "name": rec["name"],
                    "ph": "X",
                    "ts": rec["ts_us"],
                    "dur": rec["dur_us"],
                    "pid": _PID,
                    "tid": rec["tid"],
                    "args": args,
                }
            )
        elif rec.get("type") == "event":
            trace_events.append(
                {
                    "name": rec["name"],
                    "ph": "i",
                    "s": "t",
                    "ts": rec["ts_us"],
                    "pid": _PID,
                    "tid": rec["tid"],
                    "args": args,
                }
            )
    return {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "floxTpuCounters": METRICS.snapshot(),
        "floxTpuHistograms": METRICS.histograms(),
        "floxTpuHistEdgesMs": list(HIST_EDGES_MS),
        "floxTpuWall0": _WALL0,
    }


def export_chrome_trace(path: str, records: Iterable[dict] | None = None) -> None:
    """Write a Chrome trace-event JSON file — open it in ``ui.perfetto.dev``
    (Open trace file) or ``chrome://tracing``."""
    payload = to_chrome_trace(records)
    parent = os.path.dirname(str(path))
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = str(path) + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)  # a crash mid-write never leaves a truncated trace


def flush() -> None:
    """Write buffered records to ``OPTIONS["telemetry_export_path"]``.

    ``*.jsonl`` paths stream incrementally as spans finish, so flush only
    appends the final counters line; any other path is (re)written as one
    Chrome trace JSON. No export path -> records stay in the buffer. Runs
    at process exit for enabled sessions."""
    from .options import OPTIONS

    path = OPTIONS["telemetry_export_path"]
    if path is None:
        return
    path = str(path)
    if path.endswith(".jsonl"):
        pending = drain()
        _append_jsonl(path, pending + [_counters_record()])
    else:
        export_chrome_trace(path)


def profile_call(fn: Any) -> dict:
    """Run ``fn()`` once with telemetry enabled and return a compact profile:
    compile/trace counts, compile wall, H2D bytes, and the span-phase
    breakdown in ms. The bench harnesses embed this in their JSON rows so a
    benchmark round is diagnosable after the fact (was it a retrace storm? a
    numpy-engine fallback? staging-bound?) — including on CPU fallback,
    where the throughput number alone says nothing."""
    from .options import set_options

    base = METRICS.snapshot()
    with _RECORDS_LOCK:
        mark = len(_RECORDS)
    # export_path=None for the call: a configured .jsonl path would stream
    # records OUT of the buffer as they finish and the slice below would
    # see nothing — the profile must capture its own spans
    with set_options(telemetry=True, telemetry_export_path=None):
        _bootstrap()  # the compile listener must be live before fn traces
        fn()
    with _RECORDS_LOCK:
        records = list(_RECORDS[mark:])
    after = METRICS.snapshot()
    delta = {k: after[k] - base.get(k, 0) for k in after}
    from . import cache

    return {
        "compile_count": int(delta.get("jax.compiles", 0)),
        "trace_count": int(delta.get("jax.traces", 0)),
        "compile_ms": round(delta.get("jax.compile_ms", 0.0), 1),
        "h2d_bytes": int(delta.get("bytes.h2d", 0)),
        "phase_ms": {
            row["name"]: round(row["total_ms"], 3) for row in summarize(records)
        },
        "cache_sizes": cache.stats(),
    }


# ---------------------------------------------------------------------------
# report CLI: python -m flox_tpu.telemetry report <file>
# ---------------------------------------------------------------------------


def _parse_export(path: str) -> tuple[list[dict], dict, dict]:
    """Parse either export format to (span records, counters, histograms).

    Format detection is by content, not extension: a Chrome trace is ONE
    JSON document with a ``traceEvents`` key; anything that fails a
    whole-file parse (or parses to a non-trace object) is read as
    JSON-lines — every record line there is an object too, so peeking at
    the first byte cannot distinguish them. A malformed JSON-lines line is
    an error naming the line number, never a silent skip: a truncated or
    interleaved export must fail the report (and its CI step), not
    quietly under-count."""
    with open(path) as f:
        text = f.read()
    try:
        payload = json.loads(text)
    except json.JSONDecodeError:
        payload = None
    if isinstance(payload, dict) and "traceEvents" in payload:
        counters = payload.get("floxTpuCounters", {})
        histograms = payload.get("floxTpuHistograms", {})
        spans_ = [
            {
                "type": "span" if ev.get("ph") == "X" else "event",
                "name": ev.get("name", "?"),
                "ts_us": ev.get("ts", 0.0),
                "dur_us": ev.get("dur", 0.0),
                "attrs": ev.get("args", {}),
            }
            for ev in payload.get("traceEvents", [])
        ]
        return spans_, counters, histograms
    counters: dict = {}
    histograms: dict = {}
    spans_ = []
    for lineno, line in enumerate(text.splitlines(), start=1):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as exc:
            raise ValueError(
                f"{path}:{lineno}: malformed JSON-lines record ({exc})"
            ) from exc
        if not isinstance(rec, dict):
            raise ValueError(
                f"{path}:{lineno}: JSON-lines record is "
                f"{type(rec).__name__}, expected an object"
            )
        if rec.get("type") == "counters":
            # later snapshots supersede earlier ones (append-mode files
            # may carry one per flush)
            counters = rec.get("counters", {})
            histograms = rec.get("histograms", {})
        else:
            spans_.append(rec)
    return spans_, counters, histograms


def _load_export(path: str) -> tuple[list[dict], dict]:
    """Back-compat 2-tuple view of :func:`_parse_export`."""
    spans_, counters, _ = _parse_export(path)
    return spans_, counters


def summarize(records: list[dict]) -> list[dict]:
    """Aggregate span records per name: count / total / mean / p50 / p99 /
    max ms, sorted by total descending. Percentiles here are EXACT (from
    the raw durations) — the registry histograms trade that exactness for
    a bounded, mergeable representation. ``max_trace`` is the trace id of
    the slowest span of the name (when it carried one): the link from a
    p99 row to the flight/export records of the request that caused it."""
    agg: dict[str, dict] = {}
    durs: dict[str, list[float]] = {}
    for rec in records:
        if rec.get("type") != "span":
            continue
        row = agg.setdefault(
            rec["name"],
            {
                "name": rec["name"], "count": 0, "total_ms": 0.0,
                "max_ms": 0.0, "max_trace": None,
            },
        )
        dur_ms = rec.get("dur_us", 0.0) / 1e3
        row["count"] += 1
        row["total_ms"] += dur_ms
        if dur_ms >= row["max_ms"]:
            row["max_ms"] = dur_ms
            trace_id = rec.get("trace") or (rec.get("attrs") or {}).get("trace_id")
            if trace_id is not None:
                row["max_trace"] = trace_id
        durs.setdefault(rec["name"], []).append(dur_ms)
    out = sorted(agg.values(), key=lambda r: -r["total_ms"])
    for row in out:
        row["mean_ms"] = row["total_ms"] / row["count"] if row["count"] else 0.0
        seq = sorted(durs[row["name"]])
        # nearest-rank with ceiling: the upper percentile of a small sample
        # must not round down past its tail (p99 of 5 spans IS the max)
        row["p50_ms"] = seq[min(len(seq) - 1, math.ceil(0.50 * (len(seq) - 1)))]
        row["p99_ms"] = seq[min(len(seq) - 1, math.ceil(0.99 * (len(seq) - 1)))]
    return out


def _report_lines(path: str, histograms: bool = False) -> list[str]:
    records, counters, hists = _parse_export(path)
    rows = summarize(records)
    nevents = sum(1 for r in records if r.get("type") == "event")
    lines = [
        f"telemetry report — {path}",
        f"{len(records) - nevents} span(s), {nevents} event(s)",
        "",
        f"{'phase':<36} {'count':>7} {'total ms':>12} {'mean ms':>10} "
        f"{'p50 ms':>10} {'p99 ms':>10} {'max ms':>10}  slowest trace",
        "-" * 116,
    ]
    for row in rows:
        trace_col = str(row.get("max_trace") or "-")
        lines.append(
            f"{row['name'][:36]:<36} {row['count']:>7} {row['total_ms']:>12.2f} "
            f"{row['mean_ms']:>10.3f} {row['p50_ms']:>10.3f} "
            f"{row['p99_ms']:>10.3f} {row['max_ms']:>10.2f}  {trace_col[:24]}"
        )
    # the SLO plane's series get their own section instead of being
    # buried in (or silently dropped from) the generic counter list: a
    # post-mortem reader's first question about an exported incident is
    # "what was alerting", not "what was counting"
    slo_rows = {
        name: counters[name]
        for name in sorted(counters or {})
        if name.partition("|")[0].startswith(("slo.", "alert.", "canary."))
    }
    transitions = [
        r
        for r in records
        if r.get("type") == "event"
        and str(r.get("name", "")).startswith(("alert-", "canary-", "slo-"))
    ]
    if slo_rows or transitions:
        lines += ["", "slo / alert plane:"]
        for name, value in slo_rows.items():
            shown = f"{value:.4f}" if isinstance(value, float) and value % 1 else f"{int(value)}"
            lines.append(f"  {name:<40} {shown:>14}")
        for rec in transitions[-12:]:
            attrs = rec.get("attrs") or {}
            detail = ", ".join(f"{k}={attrs[k]}" for k in sorted(attrs) if k != "trace_id")
            lines.append(f"  event {rec['name']:<20} {detail[:80]}")
    if histograms:
        lines += ["", "histograms (registry, log-spaced buckets):"]
        if not hists:
            lines.append("  (export carries no histogram snapshot)")
        for name in sorted(hists):
            hist = hists[name]
            count = hist.get("count", 0)
            if not count:
                continue
            p50, p90, p99 = (
                _hist_percentile(hist, q) for q in (0.50, 0.90, 0.99)
            )
            line = (
                f"  {name[:38]:<38} {count:>7} obs "
                f"p50 {p50:>10.3f}  p90 {p90:>10.3f}  p99 {p99:>10.3f}"
            )
            # the exemplar of the highest populated bucket IS the request
            # behind the histogram's tail — name it next to the p99 (the
            # exposition layer emits the same ids per bucket on /metrics)
            exemplars = hist.get("exemplars") or {}
            if exemplars:
                top_bucket = max(exemplars, key=lambda b: int(b))
                line += f"  p99 trace {exemplars[top_bucket][0]}"
            lines.append(line)
    if counters:
        lines += ["", "counters/gauges:"]
        for name in sorted(counters):
            value = counters[name]
            shown = f"{value:.2f}" if isinstance(value, float) and value % 1 else f"{int(value)}"
            lines.append(f"  {name:<40} {shown:>14}")
    return lines


def _load_costs(path: str | None) -> tuple[dict, dict, str | None]:
    """(cost_by_program, cost_by_tenant, replica) — from a file (a
    ``/debug/costs`` scrape — possibly ``?tenant=``/``?top=``-filtered —
    a serve ``stats`` line, or a bare ``{label: row}`` mapping) or, with
    no file, from the live in-process ledger. ``replica`` is the scrape's
    fleet identity stamp when it carries one."""
    if path is None:
        return cost_by_program(), cost_by_tenant(), None
    with open(path) as f:
        payload = json.load(f)
    if not isinstance(payload, dict):
        raise ValueError(f"{path}: expected a JSON object, got {type(payload).__name__}")
    if "cost_by_program" in payload:
        return (
            payload.get("cost_by_program") or {},
            payload.get("cost_by_tenant") or {},
            payload.get("replica"),
        )
    # a serve `stats` response line carries the ledger under cache stats
    stats = payload.get("cache") or {}
    if "cost_by_program" in stats:
        return (
            stats.get("cost_by_program") or {},
            stats.get("cost_by_tenant") or {},
            None,
        )
    return payload, {}, None


def _cost_lines(
    programs: dict, tenants: dict, top: int | None = None, source: str = "live process"
) -> list[str]:
    """The ``costs`` CLI table: ledger rows ranked by total device time —
    the operator's top-N answer to "which compiled program (and which
    tenant) is the chip actually spending itself on"."""
    lines = [f"cost ledger — {source}"]
    for title, table in (("program", programs), ("tenant", tenants)):
        if title == "tenant" and not table:
            continue  # tenants are opt-in; an all-untagged run has none
        ranked = sorted(
            table.items(),
            key=lambda kv: (-float(kv[1].get("device_ms", 0.0)),
                            -int(kv[1].get("dispatches", 0))),
        )
        if top is not None:
            dropped = max(0, len(ranked) - top)
            ranked = ranked[:top]
        else:
            dropped = 0
        lines += [
            "",
            f"{'%s key' % title:<44} {'disp':>6} {'device ms':>11} {'max ms':>9} "
            f"{'MBytes':>9} {'compiles':>8} {'cmpl ms':>9} {'hbm peak':>10}  slow trace",
            "-" * 132,
        ]
        if not ranked:
            lines.append(f"  (no {title} entries recorded)")
        for label, row in ranked:
            lines.append(
                f"{label[:44]:<44} {int(row.get('dispatches', 0)):>6} "
                f"{float(row.get('device_ms', 0.0)):>11.2f} "
                f"{float(row.get('device_ms_max', 0.0)):>9.2f} "
                f"{float(row.get('bytes', 0)) / 1e6:>9.2f} "
                f"{int(row.get('compiles', 0)):>8} "
                f"{float(row.get('compile_ms', 0.0)):>9.1f} "
                f"{_fmt_bytes(row.get('hbm_peak', 0.0)):>10}  "
                f"{str(row.get('last_slow_trace') or '-')[:24]}"
            )
        if dropped:
            lines.append(f"  ... {dropped} more {title} row(s) below --top")
    return lines


def _load_programs(path: str | None) -> tuple[dict, str | None]:
    """(program rows, replica stamp) — from a ``/debug/programs`` scrape
    (possibly ``?top=``/``?program=``-filtered) or a bare ``{label: row}``
    mapping; with no file, the live in-process card/ledger join."""
    if path is None:
        from . import costmodel

        report = costmodel.program_report()
        return report["programs"], None
    with open(path) as f:
        payload = json.load(f)
    if not isinstance(payload, dict):
        raise ValueError(
            f"{path}: expected a JSON object, got {type(payload).__name__}"
        )
    if "programs" in payload:
        return payload.get("programs") or {}, payload.get("replica")
    return payload, None


def _program_lines(
    rows: dict, top: int | None = None, source: str = "live process"
) -> list[str]:
    """The ``programs`` CLI table: compiled-program cards joined with the
    observed ledger, ranked by observed device time — the operator's
    answer to "is this program GOOD, not just how long did it take"."""
    ranked = sorted(
        rows.items(),
        key=lambda kv: (
            -float((kv[1].get("observed") or {}).get("device_ms", 0.0)),
            -int((kv[1].get("observed") or {}).get("dispatches", 0)),
            kv[0],
        ),
    )
    dropped = 0
    if top is not None:
        dropped = max(0, len(ranked) - top)
        ranked = ranked[:top]
    lines = [
        f"compiled-program cards — {source}",
        "",
        f"{'program':<40} {'flops':>11} {'MB acc':>8} {'pred ms':>9} "
        f"{'obs ms/disp':>12} {'util':>7} {'drift':>7} {'disp':>6}  analysis",
        "-" * 118,
    ]
    if not ranked:
        lines.append("  (no program cards recorded)")
    for label, row in ranked:
        obs = row.get("observed") or {}
        obs_ms = row.get("observed_ms_per_dispatch")
        util = row.get("utilization")
        drift = row.get("drift_ratio")
        lines.append(
            f"{label[:40]:<40} {float(row.get('flops', 0.0)):>11.3g} "
            f"{float(row.get('bytes_accessed', 0.0)) / 1e6:>8.2f} "
            f"{float(row.get('predicted_ms', 0.0)):>9.4f} "
            f"{('%.3f' % obs_ms) if obs_ms is not None else '-':>12} "
            f"{('%.1f%%' % (100 * util)) if util is not None else '-':>7} "
            f"{('%.1fx' % drift) if drift is not None else '-':>7} "
            f"{int(obs.get('dispatches', 0)):>6}  {str(row.get('analysis', '?'))[:20]}"
        )
    if dropped:
        lines.append(f"  ... {dropped} more program row(s) below --top")
    return lines


def _drift_lines(report: dict) -> list[str]:
    """The drift-sentinel table (``programs --drift``)."""
    lines = [
        f"drift sentinel — threshold {report['threshold']:g}x, "
        f"overhead floor {report['overhead_ms']:g} ms",
        "",
        f"{'program':<44} {'obs ms/disp':>12} {'model ms':>10} {'drift':>8}  verdict",
        "-" * 92,
    ]
    if not report["rows"]:
        lines.append("  (no program has both a card and observed dispatches)")
    for row in report["rows"]:
        lines.append(
            f"{row['program'][:44]:<44} "
            f"{float(row.get('observed_ms_per_dispatch') or 0.0):>12.3f} "
            f"{float(row.get('model_ms') or 0.0):>10.4f} "
            f"{float(row.get('drift_ratio') or 0.0):>7.1f}x  "
            f"{'DRIFT' if row['flagged'] else 'ok'}"
        )
    if report["flagged"]:
        lines += ["", f"{len(report['flagged'])} program(s) flagged: "
                  + ", ".join(report["flagged"])]
    else:
        lines += ["", "clean: no program diverges from the model"]
    return lines


def _load_slo(path: str | None) -> tuple[dict, str | None]:
    """(``/slo`` payload, replica stamp) — from a scrape file, or a fresh
    live evaluation when no file is given."""
    if path is None:
        from . import slo

        return slo.evaluate(), None
    with open(path) as f:
        payload = json.load(f)
    if not isinstance(payload, dict) or "objectives" not in payload:
        raise ValueError(f"{path}: expected a /slo JSON payload with 'objectives'")
    return payload, payload.get("replica")


def _slo_lines(payload: dict, source: str = "live process") -> list[str]:
    """The ``slo`` CLI table: one row per (objective, window rule) with
    burn rates against thresholds, then the alert rows — the operator's
    terminal answer to "are we in or out of budget, and is it paging"."""
    lines = [
        f"slo report — {source}"
        + ("" if payload.get("healthy", True) else "  ** ALERT FIRING **"),
        "",
        f"{'objective':<22} {'kind':<13} {'target':>8} {'budget':>8} "
        f"{'window':<8} {'sev':<7} {'burn s':>9} {'burn l':>9} {'thresh':>7}  state",
        "-" * 110,
    ]
    for obj in payload.get("objectives", []):
        for i, win in enumerate(obj.get("windows", [])):
            lead = obj["name"] if i == 0 else ""
            kind = obj.get("kind", "?") if i == 0 else ""
            target = f"{obj.get('target', 0):.4g}" if i == 0 else ""
            budget = f"{obj.get('budget_remaining', 0):.3f}" if i == 0 else ""
            lines.append(
                f"{lead[:22]:<22} {kind:<13} {target:>8} {budget:>8} "
                f"{str(win.get('window', '?'))[:8]:<8} {str(win.get('severity', '?')):<7} "
                f"{float(win.get('burn_short', 0)):>9.2f} "
                f"{float(win.get('burn_long', 0)):>9.2f} "
                f"{float(win.get('burn_threshold', 0)):>7.1f}  "
                f"{'BREACH' if win.get('breach') else 'ok'}"
            )
    alerts = payload.get("alerts") or []
    lines += ["", f"alerts ({len(alerts)}):"]
    if not alerts:
        lines.append("  (none — state machine clean)")
    for a in alerts:
        lines.append(
            f"  {a.get('state', '?'):<9} {a.get('severity', '?'):<7} "
            f"{a.get('objective', '?')}/{a.get('window', '?')}  "
            f"burn {float(a.get('burn_short', 0)):.2f}/{float(a.get('burn_long', 0)):.2f}"
        )
    return lines


def _fmt_bytes(value: Any) -> str:
    value = float(value or 0.0)
    if value <= 0:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if value < 1024 or unit == "TiB":
            return f"{value:.1f}{unit}" if unit != "B" else f"{int(value)}B"
        value /= 1024
    return f"{value:.1f}TiB"


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m flox_tpu.telemetry",
        description="Inspect flox_tpu telemetry exports.",
    )
    sub = parser.add_subparsers(dest="command", required=True)
    rep = sub.add_parser("report", help="per-phase summary table of an export file")
    rep.add_argument("file", help="a .jsonl or Chrome-trace .json telemetry export")
    rep.add_argument(
        "--histograms", action="store_true",
        help="also print the registry histograms (per-metric p50/p90/p99)",
    )
    costs = sub.add_parser(
        "costs",
        help="per-program (and per-tenant) cost-ledger table, ranked by "
        "device time — reads a /debug/costs scrape or serve stats JSON, "
        "or the live in-process ledger when no file is given",
    )
    costs.add_argument(
        "file", nargs="?", default=None,
        help="a /debug/costs JSON scrape (default: the in-process ledger)",
    )
    costs.add_argument(
        "--top", type=int, default=None, metavar="K",
        help="show only the K most expensive rows per axis",
    )
    progs = sub.add_parser(
        "programs",
        help="compiled-program card table (analytical flops/bytes, roofline "
        "predicted ms, observed-vs-predicted drift) — reads a "
        "/debug/programs scrape, or the live in-process registry when no "
        "file is given",
    )
    progs.add_argument(
        "file", nargs="?", default=None,
        help="a /debug/programs JSON scrape (default: the live registry)",
    )
    progs.add_argument(
        "--top", type=int, default=None, metavar="K",
        help="show only the K rows with the most observed device time",
    )
    progs.add_argument(
        "--drift", action="store_true",
        help="run the drift sentinel over the rows instead: exit 2 when any "
        "program's observed time diverges past the threshold, 0 when clean",
    )
    progs.add_argument(
        "--threshold", type=float, default=None, metavar="N",
        help="drift ratio that flags a program (default: "
        "OPTIONS['costmodel_drift_threshold'])",
    )
    slo_cmd = sub.add_parser(
        "slo",
        help="SLO burn-rate + alert-state table — reads a /slo JSON scrape, "
        "or evaluates the live in-process objectives when no file is given",
    )
    slo_cmd.add_argument(
        "file", nargs="?", default=None,
        help="a /slo JSON scrape (default: evaluate the live objectives)",
    )
    srv = sub.add_parser(
        "serve-metrics",
        help="standalone /metrics + /healthz + /readyz HTTP endpoint "
        "(Prometheus text format, stdlib-only)",
    )
    srv.add_argument(
        "--port", type=int, default=None,
        help="TCP port (default: OPTIONS['metrics_port'] or 8000; 0 picks "
        "an ephemeral port and prints it)",
    )
    srv.add_argument("--host", default="127.0.0.1")
    args = parser.parse_args(argv)
    if args.command == "costs":
        if args.top is not None and args.top < 1:
            parser.error("--top must be >= 1")
        try:
            programs, tenants, replica = _load_costs(args.file)
            source = args.file or "live process"
            if replica:
                source = f"{source} (replica {replica})"
            lines = _cost_lines(programs, tenants, top=args.top, source=source)
        except OSError as exc:
            parser.error(f"cannot read {args.file}: {exc}")
        except (ValueError, KeyError, TypeError, AttributeError) as exc:
            parser.error(f"{args.file} is not a readable cost export: {exc}")
        print("\n".join(lines))
        return 0
    if args.command == "programs":
        if args.top is not None and args.top < 1:
            parser.error("--top must be >= 1")
        if args.threshold is not None and args.threshold <= 0:
            parser.error("--threshold must be > 0")
        try:
            rows, replica = _load_programs(args.file)
            source = args.file or "live process"
            if replica:
                source = f"{source} (replica {replica})"
            if args.drift:
                from . import costmodel

                report = costmodel.drift_report(rows, threshold=args.threshold)
                print("\n".join(_drift_lines(report)))
                return 2 if report["flagged"] else 0
            lines = _program_lines(rows, top=args.top, source=source)
        except OSError as exc:
            parser.error(f"cannot read {args.file}: {exc}")
        except (ValueError, KeyError, TypeError, AttributeError) as exc:
            parser.error(f"{args.file} is not a readable program-card export: {exc}")
        print("\n".join(lines))
        return 0
    if args.command == "slo":
        try:
            payload, replica = _load_slo(args.file)
            source = args.file or "live process"
            if replica:
                source = f"{source} (replica {replica})"
        except OSError as exc:
            parser.error(f"cannot read {args.file}: {exc}")
        except (ValueError, KeyError, TypeError, AttributeError) as exc:
            parser.error(f"{args.file} is not a readable /slo export: {exc}")
        print("\n".join(_slo_lines(payload, source=source)))
        # exit 2 while an alert is firing — scriptable like `programs
        # --drift`, so a canary deploy gate is one CLI call
        return 0 if payload.get("healthy", True) else 2
    if args.command == "serve-metrics":
        # a process whose only job is to be scraped (smoke tests,
        # sidecars): telemetry forced on (an endpoint over a dead registry
        # is useless), ready immediately (no warmup manifest to replay),
        # crash-signal dumps installed so SIGTERM leaves a flight record
        from . import exposition, profiling
        from .options import OPTIONS, set_options

        set_options(telemetry=True)
        install_signal_dumps()
        # SIGUSR1 -> on-demand on-chip capture into OPTIONS["profile_dir"]
        profiling.install_capture_signal()
        port = args.port if args.port is not None else (OPTIONS["metrics_port"] or 8000)
        bound = exposition.start_metrics_server(port=port, host=args.host)
        exposition.set_ready(True)
        print(f"serving /metrics /healthz /readyz on http://{args.host}:{bound}")
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
        finally:
            exposition.stop_metrics_server()
        return 0
    try:
        lines = _report_lines(args.file, histograms=args.histograms)
    except OSError as exc:
        parser.error(f"cannot read {args.file}: {exc}")
    except (ValueError, KeyError, TypeError) as exc:
        # ValueError covers json.JSONDecodeError AND _parse_export's
        # malformed-line error (which names file:line) — both exit non-zero
        parser.error(f"{args.file} is not a readable telemetry export: {exc}")
    print("\n".join(lines))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
