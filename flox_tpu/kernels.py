"""The "jax" engine: grouped-reduction kernels on XLA (L1).

This is the TPU replacement for the reference's engine layer
(/root/reference/flox/aggregate_flox.py, aggregate_npg.py): one function per
reduction with the uniform plugin signature

    f(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw)

where ``group_idx`` is an integer code array of shape ``(N,)`` (code ``-1``
means "missing label"), ``array`` has shape ``(..., N)`` (the reduced axes
flattened into the trailing dim), and ``size`` is the **static** number of
groups. Returns shape ``(..., size)`` (quantile adds a leading q-dim).

Design notes (why this is not a port):

* The reference's engines are sort+``ufunc.reduceat`` (aggregate_flox.py:133-192)
  or bincount tricks (numpy_groupies). On TPU the natural primitive is the
  XLA segment reduction (``jax.ops.segment_sum`` family) — a single fused
  scatter-reduce that XLA lowers efficiently; no host-side argsort needed for
  the common reductions.
* Missing labels: code ``-1`` is clamped to an extra trailing segment which
  is sliced off — the device-shape-stable analogue of the reference's
  nan-sentinel size bump (factorize.py:201-210).
* Order statistics (quantile/median/mode) use ``jax.lax.sort`` with
  ``num_keys=2`` for a (group, value) lexicographic sort — the TPU-native
  replacement for the reference's complex-number partition trick
  (aggregate_flox.py:50-130), which does not translate to XLA.
* Grouped scans (cumsum/ffill) use a segmented binary operator under
  ``jax.lax.associative_scan`` — log-depth on device, and the same operator
  is reused across shards by the distributed Blelloch scan.
* Denormal (subnormal) inputs follow XLA's flush-to-zero semantics — the
  same behavior TPU hardware has — so comparisons against host numpy can
  differ in the last bit for values below ~1e-308 (f64) / ~1e-38 (f32).
* Everything here is shape-static and jit-safe; ``core.chunk_reduce`` traces
  the full multi-kernel bundle into ONE jitted program so XLA fuses the
  shared factorize/scatter work across outputs (e.g. mean = sum+count in one
  pass).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from .multiarray import MultiArray

__all__ = ["KERNELS", "generic_kernel", "fused_segment_stats"]

_BIG = np.iinfo(np.int32).max


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _to_leading(array):
    """(..., N) -> (N, ...) so segment ops reduce axis 0."""
    return jnp.moveaxis(jnp.asarray(array), -1, 0)


def _from_leading(out):
    return jnp.moveaxis(out, 0, -1)


def _safe_codes(group_idx, size: int):
    codes = jnp.asarray(group_idx).astype(jnp.int32).reshape(-1)
    return jnp.where(codes < 0, size, codes)


def minmax_identity(op: str, dtype):
    """Identity element of grouped min/max for ``dtype``: -inf (floats) /
    iinfo.min (ints) for max, +inf / iinfo.max for min. The ABSORBING
    element — what NaN/NaT maps to so it wins the reduction — is the
    opposite op's identity. Single source of truth for the scatter and
    Pallas paths and the argreductions."""
    if jnp.issubdtype(jnp.dtype(dtype), jnp.floating):
        return float("-inf") if op == "max" else float("inf")
    info = np.iinfo(np.dtype(str(dtype)))
    return info.min if op == "max" else info.max


def _acc_dtype(dt):
    """Accumulation dtype for additive segment reductions.

    Sub-f32 floats (bf16/f16) accumulate in f32: their mantissas cannot even
    count past 256, so running sums and counts saturate (nanmean of 2000 bf16
    values would return the last partial, not the mean). The MXU natively
    accumulates bf16 GEMMs into f32, so the GEMM/Pallas paths pay nothing
    for this; the scatter path pays one upcast.
    """
    if dt in (jnp.bfloat16, jnp.float16):
        return jnp.float32
    return dt


def _on_tpu() -> bool:
    """One definition of "a real TPU-like backend": the pallas kernel runs
    natively there and in interpret mode anywhere else, and the auto policy
    keys off it. New accelerator backend names belong HERE only."""
    return jax.default_backend() in ("tpu", "axon")


def _use_matmul_path(op: str, data, size: int) -> bool:
    """Additive segment reductions over few groups run as a one-hot matmul.

    ``out[g, k] = Σ_n onehot[n, g] · data[n, k]`` is a plain GEMM: on TPU it
    rides the MXU at full HBM streaming bandwidth, where XLA's scatter-add
    serializes on the VPU. The one-hot is (N, size) — negligible traffic
    while ``size`` is small, which is the common climatology case (12
    months, 366 days). Float-only (integer sums must stay exact beyond the
    f32 mantissa); policy "auto" engages it on TPU backends only — on CPU
    XLA's scatter beats the un-tiled one-hot GEMM.
    """
    from .options import OPTIONS

    if op != "sum":
        return False
    if not (size <= OPTIONS["matmul_num_groups_max"] and jnp.issubdtype(data.dtype, jnp.floating)):
        return False
    # footprint guard: the one-hot is (N, size); its traffic relative to the
    # data is size/K. Keep it bounded and never let the materialized one-hot
    # exceed a hard cap — a long 1-D array with many groups must stay on the
    # scatter path.
    n = data.shape[0]
    k = int(np.prod(data.shape[1:])) if data.ndim > 1 else 1
    itemsize = np.dtype(str(data.dtype)).itemsize
    if size > 4 * k:
        return False
    if n * size * itemsize > 2**31:
        return False
    # wide-K inputs are safe: _seg_matmul_sum blocks the K axis so the
    # per-block marker masks stay ~matmul_block_bytes (an unblocked
    # bench-scale array OOMed on chip: 2.3 GB input -> 9.1 GB of mask
    # temporaries -> allocation failure). Blocking bounds K but not N, and
    # the block floors at 8 rows — when even the smallest possible block's
    # four (min(k, 8), N) masks would reach 2 GB, refuse and fall back to
    # scatter.
    if 4 * min(k, 8) * n * itemsize >= 2**31:
        return False
    return True


def _seg_matmul_sum(data, codes, size: int, *, skipna: bool = False, return_nan_counts: bool = False):
    """(N, ...) × one-hot(N, size) -> (size, ...) on the MXU.

    A thin IEEE-reapply wrapper over :func:`_seg_matmul_raw`."""
    sums, nan_c, pos_c, neg_c = _seg_matmul_raw(data, codes, size)
    from .utils import reapply_nonfinite

    out_v = reapply_nonfinite(sums, nan_c, pos_c, neg_c, skipna=skipna)
    if return_nan_counts:
        # lets nanmean fuse its count: non-NaN count = rowcount - nan_c,
        # with rowcount a codes-only (no data traffic) segment sum
        return out_v, nan_c
    return out_v


def _seg_matmul_raw(data, codes, size: int):
    """The GEMM core: raw zero-filled sums plus NaN/±inf marker counts,
    each shaped ``(size,) + data.shape[1:]`` — callers re-apply IEEE
    propagation per skipna mode (one GEMM pass can serve BOTH the sum and
    nansum variants of the fused multi-statistic plan).

    codes may contain the missing sentinel (== size); the one-hot row is all
    zeros there, so missing labels drop out for free.

    Non-finite values cannot ride the GEMM directly — ``0 × inf`` and
    ``0 × NaN`` against other groups' zero one-hot entries would poison
    their sums — so the data is zero-filled and per-column marker blocks
    (NaN / +inf / -inf indicators) are appended to the K axis; a single GEMM
    produces sums and markers, and IEEE propagation rules are re-applied.
    The extra traffic is why ``_use_matmul_path`` requires a wide kept axis;
    the endgame for narrow shapes is the Pallas segment-sum kernel.

    precision=HIGHEST keeps f32 operands f32 on the MXU (the default would
    demote them to bf16, losing accuracy vs the scatter path this replaces).

    Like the Pallas kernel, the GEMMs consume the data through its (K, N)
    transpose: every caller reaches here via ``_to_leading`` (a lazy
    ``moveaxis(-1, 0)``), so the transposes cancel and the original HBM
    buffer streams into the MXU with no transposed copy — at benchmark
    scale (~7 GB) that copy alone was an OOM.
    """
    from .options import OPTIONS

    n = data.shape[0]
    onehot = (codes[:, None] == jnp.arange(size, dtype=codes.dtype)[None, :]).astype(
        data.dtype
    )  # (N, size)
    # explicit K: reshape(-1) is ambiguous for zero-length inputs
    k = int(np.prod(data.shape[1:])) if data.ndim > 1 else 1
    flat_t = data.reshape(n, k).T  # (K, N) — cancels the caller's moveaxis
    acc = _acc_dtype(data.dtype)

    def stats_gemm(block):
        """(kb, N) -> (kb, 4, size): [sums, nan, +inf, -inf] per col/group.

        bf16 operands stream at full rate while the MXU accumulates into f32
        (its native mode); without this the sums AND the marker counts would
        saturate at bf16's 8-bit mantissa.
        """
        isnan = jnp.isnan(block)
        ispos = jnp.isposinf(block)
        isneg = jnp.isneginf(block)
        zeroed = jnp.where(isnan | ispos | isneg, jnp.zeros((), block.dtype), block)

        def gemm(x):
            return jax.lax.dot_general(
                x,
                onehot,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=acc,
                precision=jax.lax.Precision.HIGHEST,
            )  # (kb, size)

        return jnp.stack(
            [gemm(zeroed), gemm(isnan.astype(block.dtype)),
             gemm(ispos.astype(block.dtype)), gemm(isneg.astype(block.dtype))],
            axis=1,
        )

    # the (kb, N) marker masks are the path's only HBM-scale temps; bound
    # them by looping row blocks sequentially (lax.map) when K is wide —
    # per-block temps stay ~matmul_block_bytes while the data still streams
    # through the MXU once. The ragged tail block runs unpadded outside the
    # loop, so no full-size padded copy is ever made.
    itemsize = np.dtype(str(data.dtype)).itemsize
    kb_max = max(
        8,
        (OPTIONS["matmul_block_bytes"] // (4 * max(n, 1) * itemsize)) // 8 * 8,
    )
    if k <= kb_max:
        parts = stats_gemm(flat_t)  # (K, 4, size)
    else:
        nfull = k // kb_max

        def one(i):
            return stats_gemm(
                jax.lax.dynamic_slice_in_dim(flat_t, i * kb_max, kb_max, axis=0)
            )

        outs = jax.lax.map(one, jnp.arange(nfull))  # (nfull, kb, 4, size)
        parts = outs.reshape(nfull * kb_max, 4, size)
        if nfull * kb_max < k:
            parts = jnp.concatenate(
                [parts, stats_gemm(flat_t[nfull * kb_max :])], axis=0
            )

    trail = (size,) + data.shape[1:]
    sums = parts[:, 0].T.reshape(trail)  # (size, K) -> (size, ...)
    nan_c = parts[:, 1].T.reshape(trail)
    pos_c = parts[:, 2].T.reshape(trail)
    neg_c = parts[:, 3].T.reshape(trail)
    return sums, nan_c, pos_c, neg_c


_PALLAS_PROBE_RESULT: list = []  # memoized one-time runtime validation
_PALLAS_COMPILE_PROBE: list = []  # weaker compile-only probe (in-trace calls)
_PALLAS_MINMAX_PROBE_RESULT: list = []
_PALLAS_MINMAX_COMPILE_PROBE: list = []


def _probed_ok(final_memo, compile_memo, exec_probe, compile_probe, label) -> bool:
    """One-time probe: compile+run a Pallas kernel on a tiny input on the
    real backend. The kernels are tested in interpret mode on CPU, but a
    real TPU lowering can still fail (tiling constraints, toolchain drift) —
    and the 'auto' policy must never take down a reduction it could have run
    on the battle-tested paths. Any failure logs once and disables the
    kernel for the process.

    The first resolution may happen while an outer jit is tracing (the
    policy is consulted at trace time). Under an ambient trace the executing
    probe's arrays become tracers and np.asarray raises — which would be
    mis-recorded as "unavailable" — so in-trace calls probe by
    lowering+compiling against abstract shapes instead. That weaker verdict
    is memoized separately and NOT promoted to the final result: the next
    clean call still runs the full execute-and-check probe."""
    if final_memo:
        return final_memo[0]
    import logging

    log = logging.getLogger("flox_tpu.kernels")
    try:
        from jax._src import core as _jcore  # jax.core stopped re-exporting it

        clean = getattr(_jcore, "trace_state_clean", lambda: True)()
    except Exception:  # noqa: BLE001
        # private API drift (removal OR behavior change) must degrade to the
        # fallback paths, never crash the reduction; without the trace-state
        # signal assume the worst (tracing) and take the compile-only leg.
        clean = False
    if not clean:
        if not compile_memo:
            try:
                compile_probe()
                compile_memo.append(True)
            except Exception as exc:  # noqa: BLE001
                log.warning(
                    "pallas %s failed to compile on this backend (%s); "
                    "falling back to the XLA paths", label, exc,
                )
                compile_memo.append(False)
        return compile_memo[0]
    try:
        ok = bool(exec_probe())
    except Exception as exc:  # noqa: BLE001 — any lowering failure disables it
        log.warning(
            "pallas %s unavailable on this backend (%s); "
            "falling back to the XLA paths", label, exc,
        )
        ok = False
    final_memo.append(ok)
    return ok


def _pallas_runtime_ok() -> bool:
    from .pallas_kernels import probe_compile, segment_sum_pallas

    def _exec():
        probe = segment_sum_pallas(
            jnp.ones((8, 128), jnp.float32), jnp.zeros(8, jnp.int32), 2
        )
        return np.asarray(probe)[0, 0] == 8.0

    return _probed_ok(
        _PALLAS_PROBE_RESULT, _PALLAS_COMPILE_PROBE, _exec, probe_compile,
        "segment-sum",
    )


_PALLAS_RADIXBIN_PROBE_RESULT: list = []
_PALLAS_RADIXBIN_COMPILE_PROBE: list = []


def _pallas_radixbin_runtime_ok() -> bool:
    from .pallas_kernels import probe_compile_radixbin, segment_sum_radixbin_pallas

    def _exec():
        probe = segment_sum_radixbin_pallas(
            jnp.ones((8, 128), jnp.float32), jnp.zeros(8, jnp.int32), 2
        )
        return np.asarray(probe)[0, 0] == 8.0

    return _probed_ok(
        _PALLAS_RADIXBIN_PROBE_RESULT, _PALLAS_RADIXBIN_COMPILE_PROBE, _exec,
        probe_compile_radixbin, "radixbin-segment-sum",
    )


_PALLAS_SCAN_PROBE_RESULT: list = []
_PALLAS_SCAN_COMPILE_PROBE: list = []


def _pallas_scan_runtime_ok() -> bool:
    from .pallas_kernels import probe_compile_scan, segment_cumsum_pallas

    def _exec():
        data = jnp.ones((16, 128), jnp.float32)
        probe = segment_cumsum_pallas(
            data, jnp.zeros(16, jnp.int32), 2, skipna=False
        )
        return np.asarray(probe)[15, 0] == 16.0

    return _probed_ok(
        _PALLAS_SCAN_PROBE_RESULT, _PALLAS_SCAN_COMPILE_PROBE, _exec,
        probe_compile_scan, "grouped-scan",
    )


def _scan_impl_choice(data, size) -> str:
    """Pick the grouped-cumsum lowering: the sort+log-depth segmented scan
    vs the Pallas triangular-matmul kernel (one HBM pass)."""
    from .options import OPTIONS

    policy = OPTIONS["scan_impl"]
    ok = (
        isinstance(size, int)
        and str(data.dtype) in ("float32", "bfloat16")
        and size + 1 <= OPTIONS["pallas_scan_num_groups_max"]
        and data.shape[0] >= 8
    )
    if policy == "segmented" or not ok:
        return "segmented"
    on_tpu = _on_tpu()
    if policy == "pallas":
        return "pallas" if (not on_tpu or _pallas_scan_runtime_ok()) else "segmented"
    # auto: interpret-mode pallas is slow on CPU; on TPU the sort-based path
    # pays an argsort plus a log-depth scan through HBM
    if on_tpu and _pallas_scan_runtime_ok():
        return "pallas"
    return "segmented"


def _pallas_minmax_runtime_ok() -> bool:
    from .pallas_kernels import probe_compile_minmax, segment_minmax_pallas

    def _exec():
        data = jnp.arange(8 * 128, dtype=jnp.float32).reshape(8, 128)
        probe = segment_minmax_pallas(data, jnp.zeros(8, jnp.int32), 2, "max")
        return np.asarray(probe)[0, 0] == 7 * 128.0

    return _probed_ok(
        _PALLAS_MINMAX_PROBE_RESULT, _PALLAS_MINMAX_COMPILE_PROBE, _exec,
        probe_compile_minmax, "segment-min/max",
    )


def _segment_sum_impl(data, size: int) -> str:
    """Pick the segment-sum implementation per the policy + constraints.

    Policy ``"auto"`` consults the autotune store when the tuner is on
    (``FLOX_TPU_AUTOTUNE=1``): an observed winner among the lowerings whose
    guards pass on this call wins over the static platform heuristic. With
    the tuner off (record-only mode) the heuristic below is the whole
    story — bit-identical to the pre-autotune dispatch."""
    from .options import OPTIONS

    policy = OPTIONS["segment_sum_impl"]
    floating = jnp.issubdtype(data.dtype, jnp.floating)
    if policy == "scatter" or not floating:
        return "scatter"
    if policy == "matmul":
        return "matmul" if _use_matmul_path("sum", data, size) else "scatter"
    pallas_ok = (
        str(data.dtype) in ("float32", "bfloat16")
        and size <= OPTIONS["pallas_num_groups_max"]
        and data.shape[0] >= 8
    )
    # the radix-binning grid covers the group counts past the dense
    # kernel's VMEM cap — the sort engine's compact domain lives here
    radixbin_ok = (
        str(data.dtype) in ("float32", "bfloat16")
        and size <= OPTIONS["radixbin_num_groups_max"]
        and data.shape[0] >= 8
    )
    on_tpu = _on_tpu()
    if policy == "pallas":
        return "pallas" if pallas_ok and (not on_tpu or _pallas_runtime_ok()) else "scatter"
    if policy == "radixbin":
        return (
            "radixbin"
            if radixbin_ok and (not on_tpu or _pallas_radixbin_runtime_ok())
            else "scatter"
        )
    # auto on TPU: pallas if it validates at runtime, else radix-binning for
    # the group counts past its VMEM cap, else the GEMM path if its guards
    # pass (pure XLA, no custom lowering), else scatter
    if on_tpu and pallas_ok and _pallas_runtime_ok():
        heuristic = "pallas"
    elif on_tpu and not pallas_ok and radixbin_ok and _pallas_radixbin_runtime_ok():
        heuristic = "radixbin"
    elif on_tpu and _use_matmul_path("sum", data, size):
        heuristic = "matmul"
    else:
        heuristic = "scatter"
    if OPTIONS["autotune"]:
        from . import autotune

        eligible = ["scatter"]
        if _use_matmul_path("sum", data, size):
            eligible.append("matmul")
        if pallas_ok and on_tpu and _pallas_runtime_ok():
            eligible.append("pallas")
        if radixbin_ok and on_tpu and _pallas_radixbin_runtime_ok():
            eligible.append("radixbin")
        nelems = data.shape[0] * (
            int(np.prod(data.shape[1:])) if data.ndim > 1 else 1
        )
        return autotune.decide(
            "segment_sum", heuristic, eligible,
            dtype=str(data.dtype), ngroups=size, nelems=nelems,
        )
    return heuristic


def _segment_minmax_impl(data, size: int) -> str:
    """Pick the segment-min/max implementation per the policy + constraints.

    Min/max cannot ride the MXU (no (max, ·) semiring), so the choice is
    scatter vs the VPU select-reduce Pallas kernel, whose cost grows with
    the group count — hence the ``pallas_minmax_num_groups_max`` gate.
    """
    from .options import OPTIONS

    policy = OPTIONS["segment_minmax_impl"]
    ok = (
        str(data.dtype) in ("float32", "bfloat16", "int32")
        and size <= OPTIONS["pallas_minmax_num_groups_max"]
        and data.shape[0] >= 8
    )
    if policy == "scatter" or not ok:
        return "scatter"
    on_tpu = _on_tpu()
    if policy == "pallas":
        return "pallas" if (not on_tpu or _pallas_minmax_runtime_ok()) else "scatter"
    # auto: scatter is competitive on CPU; on TPU it serializes on the VPU
    if on_tpu and _pallas_minmax_runtime_ok():
        return "pallas"
    return "scatter"


def _seg(op: str, data, codes, size: int):
    """Segment-reduce ``data`` (N, ...) by ``codes`` (N,) into (size, ...).

    Allocates one extra segment for missing labels and slices it off, so the
    output shape depends only on the static ``size``. Additive float
    reductions may take the MXU one-hot-matmul or Pallas path per the
    ``segment_sum_impl`` policy; both carry non-finite marker columns, since
    even skipna-masked data may contain legitimate ±inf values.

    Additive ops on sub-f32 floats accumulate — and return — f32 (see
    ``_acc_dtype``); callers that want the input dtype back cast at the end.
    """
    if op in ("max", "min") and _segment_minmax_impl(data, size) == "pallas":
        from .pallas_kernels import segment_minmax_pallas

        return segment_minmax_pallas(data, codes, size, op, interpret=not _on_tpu())
    if op == "sum":
        impl = _segment_sum_impl(data, size)
        if impl == "pallas":
            from .pallas_kernels import segment_sum_pallas

            # interpret mode keeps the kernel testable off-TPU
            return segment_sum_pallas(
                data, codes, size, interpret=not _on_tpu()
            )
        if impl == "radixbin":
            from .pallas_kernels import segment_sum_radixbin_pallas

            return segment_sum_radixbin_pallas(
                data, codes, size, interpret=not _on_tpu()
            )
        if impl == "matmul":
            # non-finite handling is built into the GEMM (marker columns), so
            # skipna-masked and raw data take the same path
            return _seg_matmul_sum(data, codes, size)
    if op in ("sum", "prod") and jnp.issubdtype(data.dtype, jnp.floating):
        acc = _acc_dtype(data.dtype)
        if data.dtype != acc:
            data = data.astype(acc)
    fn = {
        "sum": jax.ops.segment_sum,
        "prod": jax.ops.segment_prod,
        "max": jax.ops.segment_max,
        "min": jax.ops.segment_min,
    }[op]
    out = fn(data, codes, num_segments=size + 1)
    return out[:size]


def _counts(codes, size: int, mask=None, dtype=jnp.int32):
    """Per-group element counts, optionally restricted by ``mask`` (N, ...)."""
    if mask is None:
        ones = jnp.ones(codes.shape, dtype=dtype)
    else:
        ones = mask.astype(dtype)
    return _seg("sum", ones, codes, size)


def _is_nan_fill(fv) -> bool:
    from . import utils as _u

    return _u.is_nan_fill(fv)


def _promote_for_nan_fill(out, fv):
    """A NaN fill on integer output must promote, not truncate to garbage."""
    inexact = jnp.issubdtype(out.dtype, jnp.floating) or jnp.issubdtype(
        out.dtype, jnp.complexfloating
    )
    if _is_nan_fill(fv) and not inexact:
        from . import utils as _u

        return out.astype(jnp.float64 if _u.x64_enabled() else jnp.float32)
    return out


def _fill_empty(out, present, fill_value):
    """Replace groups with no contributing elements by ``fill_value``."""
    if fill_value is None:
        return out
    out = _promote_for_nan_fill(out, fill_value)
    present = _bcast_present(jnp.asarray(present), out)
    return jnp.where(present, out, jnp.asarray(fill_value).astype(out.dtype))


_NAT_INT = np.iinfo(np.int64).min  # NaT viewed as int64 (core passes nat=True)


def _nan_mask(array, nat: bool = False):
    if jnp.issubdtype(array.dtype, jnp.floating) or jnp.issubdtype(array.dtype, jnp.complexfloating):
        return ~jnp.isnan(array)
    if nat and jnp.issubdtype(array.dtype, jnp.signedinteger):
        # datetime64 data arrives viewed as int64; INT64_MIN is NaT
        return array != jnp.asarray(_NAT_INT, dtype=array.dtype)
    return None  # non-float: nothing is NaN


def _maybe_cast(array, dtype):
    if dtype is not None and array.dtype != np.dtype(dtype):
        return array.astype(dtype)
    return array


def _iota_like(data):
    """(N, ...) index-along-axis-0 array broadcast to data's shape."""
    n = data.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    return jnp.broadcast_to(idx.reshape((n,) + (1,) * (data.ndim - 1)), data.shape)


# ---------------------------------------------------------------------------
# simple reductions
# ---------------------------------------------------------------------------


def _make_addlike(op: str, identity, skipna: bool):
    def kernel(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
        codes = _safe_codes(group_idx, size)
        data = _to_leading(array)
        mask = _nan_mask(data, kw.get("nat", False)) if skipna else None
        if mask is not None:
            data = jnp.where(mask, data, jnp.asarray(identity, dtype=data.dtype))
        data = _maybe_cast(data, dtype)
        out = _seg(op, data, codes, size)  # f32-accumulated for bf16/f16
        if fill_value is not None and fill_value != identity:
            # numpy semantics: nansum of an all-NaN group is the identity (0),
            # so "empty" means zero *total* elements, not zero non-NaN ones.
            present = _counts(codes, size) > 0
            out = _fill_empty(out, present, fill_value)
        if (
            jnp.issubdtype(data.dtype, jnp.floating)
            and out.dtype != data.dtype
            and not kw.get("keep_acc", False)
        ):
            # result dtype contract: same as the (request-resolved) input.
            # (int data is untouched — a NaN fill may have promoted it.)
            # keep_acc=True keeps the f32 accumulator — the mesh chunk stage
            # uses it so bf16 intermediates travel/psum in f32, casting back
            # only at finalize.
            out = out.astype(data.dtype)
        return _from_leading(out)

    return kernel


sum_ = _make_addlike("sum", 0, skipna=False)
nansum = _make_addlike("sum", 0, skipna=True)
prod = _make_addlike("prod", 1, skipna=False)
nanprod = _make_addlike("prod", 1, skipna=True)


def _make_minmax(op: str, skipna: bool):
    def kernel(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
        codes = _safe_codes(group_idx, size)
        data = _to_leading(array)
        data = _maybe_cast(data, dtype)
        nat = kw.get("nat", False)
        mask = _nan_mask(data, nat)
        isint = not jnp.issubdtype(data.dtype, jnp.floating)
        if skipna and mask is not None:
            ident = jnp.asarray(minmax_identity(op, data.dtype), dtype=data.dtype)
            data = jnp.where(mask, data, ident)
        elif not skipna and mask is not None:
            # NaN/NaT propagates through min/max in numpy; segment_min/max on
            # TPU would otherwise drop it. Force-propagate by mapping the
            # missing marker to the absorbing element (the opposite op's
            # identity).
            absorb = jnp.asarray(
                minmax_identity("min" if op == "max" else "max", data.dtype),
                dtype=data.dtype,
            )
            missing_marker = jnp.asarray(
                _NAT_INT if isint else jnp.nan, dtype=data.dtype
            )
            has_nan = _seg("max", (~mask).astype(jnp.int8), codes, size) > 0
            data = jnp.where(mask, data, absorb)
            out = _seg(op, data, codes, size)
            out = jnp.where(has_nan, missing_marker, out)
            present = _counts(codes, size) > 0
            out = _fill_empty(out, _bcast_present(present, out), fill_value)
            return _from_leading(out)
        out = _seg(op, data, codes, size)
        present = _counts(codes, size, mask=mask if skipna else None) > 0
        out = _fill_empty(out, _bcast_present(present, out), fill_value)
        return _from_leading(out)

    return kernel


def _bcast_present(present, out):
    """Broadcast a (size,)-or-(size, ...) presence mask against out."""
    if present.ndim < out.ndim:
        present = present.reshape(present.shape + (1,) * (out.ndim - present.ndim))
    return present


max_ = _make_minmax("max", skipna=False)
nanmax = _make_minmax("max", skipna=True)
min_ = _make_minmax("min", skipna=False)
nanmin = _make_minmax("min", skipna=True)


def nanlen(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    """Count of non-NaN elements per group (the reference's 'nanlen')."""
    codes = _safe_codes(group_idx, size)
    data = _to_leading(array)
    mask = _nan_mask(data, kw.get("nat", False))
    out = _counts(codes, size, mask=mask, dtype=dtype or jnp.int32)
    if mask is None and out.ndim < data.ndim:
        out = jnp.broadcast_to(
            out.reshape(out.shape + (1,) * (data.ndim - out.ndim)), (size,) + data.shape[1:]
        )
    if fill_value is not None and fill_value != 0:
        out = _fill_empty(out, out > 0, fill_value)
    return _from_leading(out)


def len_(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    codes = _safe_codes(group_idx, size)
    data = _to_leading(array)
    out = _counts(codes, size, dtype=dtype or jnp.int32)
    out = jnp.broadcast_to(
        out.reshape(out.shape + (1,) * (data.ndim - out.ndim)), (size,) + data.shape[1:]
    )
    return _from_leading(out)


_PALLAS_MULTISTAT_PROBE_RESULT: list = []
_PALLAS_MULTISTAT_COMPILE_PROBE: list = []


def _pallas_multistat_runtime_ok() -> bool:
    from .pallas_kernels import probe_compile_multistat, segment_multistat_pallas

    def _exec():
        data = jnp.arange(8 * 128, dtype=jnp.float32).reshape(8, 128)
        sums, _nan, _pos, _neg, mins, maxs = segment_multistat_pallas(
            data, jnp.zeros(8, jnp.int32), 2
        )
        return (
            np.asarray(sums)[0, 0] == float(sum(range(0, 8 * 128, 128)))
            and np.asarray(mins)[0, 0] == 0.0
            and np.asarray(maxs)[0, 0] == 7 * 128.0
        )

    return _probed_ok(
        _PALLAS_MULTISTAT_PROBE_RESULT, _PALLAS_MULTISTAT_COMPILE_PROBE,
        _exec, probe_compile_multistat, "multistat",
    )


_FUSABLE_LEG_NAMES = frozenset(
    {"sum", "nansum", "len", "nanlen", "min", "nanmin", "max", "nanmax"}
)


def _fused_stats_leading(data, codes, size: int, want: tuple):
    """Multi-output single-pass segment statistics on the marker paths —
    the general form of the old ``_fused_sum_counts`` special case, shared
    by the mean/var kernels and the multi-statistic fusion planner
    (aggregations.fused_chunk_stats).

    ``data`` (N, ...) in the leading kernel layout, ``codes`` already
    sentinel-safe; ``want`` ⊆ {sum, nansum, len, nanlen, min, nanmin, max,
    nanmax}. The GEMM/Pallas kernels zero non-finite values themselves and
    emit NaN/±inf marker counts, so ONE pass yields every sum variant
    (IEEE re-applied per skipna mode), non-NaN counts as
    ``rowcount(codes) - nan_c`` (rowcount touches only the codes), and —
    on the Pallas megakernel — grouped min/max with all accumulators
    resident in VMEM across the sequential grid. Returns ``{name: (size,
    ...)}`` or None when the policy resolves to scatter or a guard fails
    (callers then run the per-leg kernels, which XLA still fuses into one
    program).
    """
    want = tuple(want)
    if not set(want) <= _FUSABLE_LEG_NAMES:
        return None
    sumish = [w for w in want if w in ("sum", "nansum")]
    minmaxish = [w for w in want if w in ("min", "nanmin", "max", "nanmax")]
    if not (sumish or minmaxish):
        return None  # counts alone never justify a fused data pass
    if not jnp.issubdtype(data.dtype, jnp.floating) or data.shape[0] >= 2**24:
        # 2^24: the f32 marker-count exactness guard
        return None
    impl = _segment_sum_impl(data, size)
    mins = maxs = None
    if minmaxish:
        from .options import OPTIONS

        ok = (
            str(data.dtype) in ("float32", "bfloat16")
            and size <= min(
                OPTIONS["pallas_num_groups_max"],
                OPTIONS["pallas_minmax_num_groups_max"],
            )
            and data.shape[0] >= 8
            and impl == "pallas"
            and (not _on_tpu() or _pallas_multistat_runtime_ok())
        )
        if not ok:
            return None
        from .pallas_kernels import segment_multistat_pallas

        sums, nan_c, pos_c, neg_c, mins, maxs = segment_multistat_pallas(
            data, codes, size, interpret=not _on_tpu()
        )
    elif impl == "matmul":
        sums, nan_c, pos_c, neg_c = _seg_matmul_raw(data, codes, size)
    elif impl == "pallas":
        from .pallas_kernels import segment_sum_raw_pallas

        sums, nan_c, pos_c, neg_c = segment_sum_raw_pallas(
            data, codes, size, interpret=not _on_tpu()
        )
    else:
        return None

    from .utils import reapply_nonfinite

    out: dict = {}
    acc = sums.dtype
    if "sum" in want:
        out["sum"] = reapply_nonfinite(sums, nan_c, pos_c, neg_c, skipna=False)
    if "nansum" in want:
        out["nansum"] = reapply_nonfinite(sums, nan_c, pos_c, neg_c, skipna=True)
    if "len" in want or "nanlen" in want:
        rowcount = _bcast_present(_counts(codes, size), sums)  # codes-only
        if "len" in want:
            out["len"] = jnp.broadcast_to(rowcount, sums.shape)
        if "nanlen" in want:
            out["nanlen"] = (
                jnp.broadcast_to(rowcount, sums.shape).astype(acc)
                - nan_c.astype(acc)
            )
    if minmaxish:
        # the megakernel computes min/max NaN-masked; the propagating
        # variants re-inject NaN exactly as _make_minmax does (its
        # has_nan flag IS nan_c > 0 here)
        has_nan = nan_c > 0
        nanv = jnp.asarray(jnp.nan, mins.dtype)
        if "nanmin" in want:
            out["nanmin"] = mins
        if "min" in want:
            out["min"] = jnp.where(has_nan, nanv, mins)
        if "nanmax" in want:
            out["nanmax"] = maxs
        if "max" in want:
            out["max"] = jnp.where(has_nan, nanv, maxs)
    return out


def fused_segment_stats(group_idx, array, *, size: int, want: tuple):
    """Plugin-layout entry to :func:`_fused_stats_leading`: ``array``
    (..., N) in, ``{name: (..., size)}`` out (or None) — what the fusion
    planner's chunk executor calls."""
    codes = _safe_codes(group_idx, size)
    data = _to_leading(array)
    raw = _fused_stats_leading(data, codes, size, tuple(want))
    if raw is None:
        return None
    return {k: _from_leading(v) for k, v in raw.items()}


def _fused_sum_counts(cast, codes, size: int):
    """Single-pass skipna (total, non-NaN count): the mean/var fast path,
    now one ``want`` set of the general fused primitive."""
    got = _fused_stats_leading(cast, codes, size, ("nansum", "nanlen"))
    if got is None:
        return None
    return got["nansum"], got["nanlen"]


def _mean_impl(group_idx, array, *, size, fill_value, dtype, skipna):
    codes = _safe_codes(group_idx, size)
    data = _to_leading(array)
    if dtype is None and not jnp.issubdtype(data.dtype, jnp.floating):
        dtype = jnp.result_type(data.dtype, jnp.float32)

    cast = _maybe_cast(data, dtype)
    fused = _fused_sum_counts(cast, codes, size) if skipna else None
    if fused is not None:
        total, cnt = fused
        orig_dtype = cast.dtype
    else:
        mask = _nan_mask(data) if skipna else None
        sdata = cast if mask is None else jnp.where(mask, cast, jnp.zeros((), cast.dtype))
        total = _seg("sum", sdata, codes, size)  # f32-accumulated for bf16/f16
        # counts in int32: exact, and immune to the data dtype (bf16 counts
        # saturate at 256 — the mean of 2000 values must not divide by 256)
        cnt = _bcast_present(_counts(codes, size, mask=mask), total).astype(total.dtype)
        orig_dtype = sdata.dtype
    out = total / cnt
    out = _fill_empty(out, cnt > 0, fill_value if fill_value is not None else jnp.nan)
    if out.dtype != orig_dtype and jnp.issubdtype(orig_dtype, jnp.floating):
        out = out.astype(orig_dtype)  # divide in f32, present as bf16
    return _from_leading(out)


def mean(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _mean_impl(group_idx, array, size=size, fill_value=fill_value, dtype=dtype, skipna=False)


def nanmean(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _mean_impl(group_idx, array, size=size, fill_value=fill_value, dtype=dtype, skipna=True)


def _sum_of_squares(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, skipna=False, **kw):
    arr = jnp.asarray(array)
    out_dtype = arr.dtype
    if jnp.issubdtype(arr.dtype, jnp.floating) and _acc_dtype(arr.dtype) != arr.dtype:
        arr = arr.astype(_acc_dtype(arr.dtype))  # square in f32, not bf16
    out = (nansum if skipna else sum_)(
        group_idx, arr * arr, axis=axis, size=size, fill_value=fill_value, dtype=dtype,
        keep_acc=kw.get("keep_acc", False),
    )
    if dtype is None and not kw.get("keep_acc", False) and out.dtype != out_dtype and jnp.issubdtype(out_dtype, jnp.floating):
        out = out.astype(out_dtype)
    return out


sum_of_squares = partial(_sum_of_squares, skipna=False)
nansum_of_squares = partial(_sum_of_squares, skipna=True)


# ---------------------------------------------------------------------------
# variance: single-pass-per-chunk triple, numerically shifted by the group
# mean (the TPU analogue of the reference's var_chunk, aggregations.py:348-389)
# ---------------------------------------------------------------------------


def _var_impl(group_idx, array, *, size, fill_value, dtype, ddof, skipna, std):
    codes = _safe_codes(group_idx, size)
    data = _to_leading(array)
    if dtype is None and not jnp.issubdtype(data.dtype, jnp.floating):
        dtype = jnp.result_type(data.dtype, jnp.float32)
    cast = _maybe_cast(data, dtype)
    # mask on the PRE-cast data: an int dtype request would destroy the
    # NaNs before the mask sees them (review regression)
    mask = _nan_mask(data) if skipna else None
    zdata = cast if mask is None else jnp.where(mask, cast, jnp.zeros((), cast.dtype))
    fused = _fused_sum_counts(cast, codes, size) if skipna else None
    if fused is not None:
        total, cnt_f = fused
        cnt_b = cnt_f
    else:
        total = _seg("sum", zdata, codes, size)  # f32-accumulated for bf16/f16
        cnt_b = _bcast_present(_counts(codes, size, mask=mask), total)  # int32, exact
        cnt_f = cnt_b.astype(total.dtype)
    mean_g = total / jnp.where(cnt_f > 0, cnt_f, 1)
    # gather each element's group mean and accumulate squared deviations
    # (zdata - gathered promotes bf16 deviations to the f32 mean dtype, so
    # the squared-deviation accumulation stays f32 end-to-end)
    gathered = jnp.take(jnp.concatenate([mean_g, jnp.zeros((1,) + mean_g.shape[1:], mean_g.dtype)]), codes, axis=0)
    dev = zdata - gathered
    if mask is not None:
        dev = jnp.where(mask, dev, jnp.zeros((), dev.dtype))
    m2 = _seg("sum", dev * dev, codes, size)
    denom = cnt_f - ddof
    out = m2 / jnp.where(denom > 0, denom, 1)
    out = jnp.where(denom > 0, out, jnp.asarray(jnp.nan, out.dtype))
    if std:
        out = jnp.sqrt(out)
    out = _fill_empty(out, cnt_b > 0, fill_value if fill_value is not None else jnp.nan)
    if out.dtype != zdata.dtype and jnp.issubdtype(zdata.dtype, jnp.floating):
        out = out.astype(zdata.dtype)
    return _from_leading(out)


def var(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, ddof=0, **kw):
    return _var_impl(group_idx, array, size=size, fill_value=fill_value, dtype=dtype, ddof=ddof, skipna=False, std=False)


def nanvar(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, ddof=0, **kw):
    return _var_impl(group_idx, array, size=size, fill_value=fill_value, dtype=dtype, ddof=ddof, skipna=True, std=False)


def std(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, ddof=0, **kw):
    return _var_impl(group_idx, array, size=size, fill_value=fill_value, dtype=dtype, ddof=ddof, skipna=False, std=True)


def nanstd(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, ddof=0, **kw):
    return _var_impl(group_idx, array, size=size, fill_value=fill_value, dtype=dtype, ddof=ddof, skipna=True, std=True)


def var_chunk(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, skipna=True, **kw):
    """Per-chunk variance statistics: MultiArray (sum_sq_dev, sum, count).

    The deviations are taken about the *chunk's* per-group mean, so the
    combine stage needs only the Chan-style merge (see parallel.mapreduce /
    aggregations._var_combine) — this is the numerically-stable single-pass
    strategy of the reference (aggregations.py:348-451), expressed as a
    pytree so collectives apply leaf-wise.
    """
    codes = _safe_codes(group_idx, size)
    data = _to_leading(array)
    if dtype is None and not jnp.issubdtype(data.dtype, jnp.floating):
        dtype = jnp.result_type(data.dtype, jnp.float32)
    cast = _maybe_cast(data, dtype)
    # mask on the PRE-cast data: an int dtype request would destroy the
    # NaNs before the mask sees them (review regression)
    mask = _nan_mask(data) if skipna else None
    zdata = cast if mask is None else jnp.where(mask, cast, jnp.zeros((), cast.dtype))
    fused = _fused_sum_counts(cast, codes, size) if skipna else None
    if fused is not None:
        total, cnt_f = fused
    else:
        total = _seg("sum", zdata, codes, size)  # f32-accumulated for bf16/f16
        cnt_f = _bcast_present(_counts(codes, size, mask=mask), total).astype(total.dtype)
    mean_g = total / jnp.where(cnt_f > 0, cnt_f, 1)
    gathered = jnp.take(
        jnp.concatenate([mean_g, jnp.zeros((1,) + mean_g.shape[1:], mean_g.dtype)]), codes, axis=0
    )
    dev = zdata - gathered
    if mask is not None:
        dev = jnp.where(mask, dev, jnp.zeros((), dev.dtype))
    m2 = _seg("sum", dev * dev, codes, size)
    # the triple stays in the f32 accumulator dtype deliberately: these are
    # cross-shard intermediates (psum'd by the Chan merge); the final dtype
    # cast happens once, at finalize
    if cnt_f.shape != total.shape:
        cnt_f = jnp.broadcast_to(cnt_f, total.shape)
    return MultiArray(
        (_from_leading(m2), _from_leading(total), _from_leading(cnt_f))
    )


# ---------------------------------------------------------------------------
# bool reductions
# ---------------------------------------------------------------------------


def all_(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    codes = _safe_codes(group_idx, size)
    data = _to_leading(array).astype(bool).astype(jnp.int8)
    out = _seg("min", data, codes, size).astype(bool)
    present = _counts(codes, size) > 0
    out = jnp.where(_bcast_present(present, out), out, True if fill_value is None else fill_value)
    return _from_leading(out)


def any_(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    codes = _safe_codes(group_idx, size)
    data = _to_leading(array).astype(bool).astype(jnp.int8)
    out = _seg("max", data, codes, size).astype(bool)
    present = _counts(codes, size) > 0
    out = jnp.where(_bcast_present(present, out), out, False if fill_value is None else fill_value)
    return _from_leading(out)


# ---------------------------------------------------------------------------
# argreductions and positional first/last
# ---------------------------------------------------------------------------


def _arg_impl(group_idx, array, *, size, fill_value, skipna, arg_of_max, nat=False):
    codes = _safe_codes(group_idx, size)
    data = _to_leading(array)
    mask = _nan_mask(data, nat)
    key = data
    if mask is not None:
        op = "max" if arg_of_max else "min"
        if skipna:
            ident = jnp.asarray(minmax_identity(op, data.dtype), dtype=data.dtype)
            key = jnp.where(mask, data, ident)
        else:
            # NaN propagates with numpy's exact semantics: the FIRST NaN
            # position wins outright, even when the group also holds ±inf
            # (np.argmax([inf, nan]) == 1). NaNs are excluded from the
            # value race here and re-applied as a position override below.
            absorb = jnp.asarray(
                minmax_identity("min" if arg_of_max else "max", data.dtype),
                dtype=data.dtype,
            )
            key = jnp.where(mask, data, absorb)
    best = _seg("max" if arg_of_max else "min", key, codes, size)
    best_per_elem = jnp.take(
        jnp.concatenate([best, jnp.zeros((1,) + best.shape[1:], best.dtype)]), codes, axis=0
    )
    iota = _iota_like(key)
    cand = jnp.where(key == best_per_elem, iota, _BIG)
    if skipna and mask is not None:
        cand = jnp.where(mask, cand, _BIG)
    out = _seg("min", cand, codes, size)
    if not skipna and mask is not None:
        # numpy parity: any NaN (NaT) in the group short-circuits the value
        # race — the first missing position is the answer
        first_nan = _seg("min", jnp.where(mask, _BIG, iota), codes, size)
        out = jnp.where(first_nan < _BIG, first_nan, out)
    valid_counts = _counts(codes, size, mask=mask if skipna else None)
    present = _bcast_present(valid_counts, out) > 0
    fv = -1 if fill_value is None else fill_value
    out = jnp.where(present & (out < _BIG), out, fv)
    return _from_leading(out)


def argmax(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _arg_impl(group_idx, array, size=size, fill_value=fill_value, skipna=False, arg_of_max=True, nat=kw.get("nat", False))


def argmin(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _arg_impl(group_idx, array, size=size, fill_value=fill_value, skipna=False, arg_of_max=False, nat=kw.get("nat", False))


def nanargmax(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _arg_impl(group_idx, array, size=size, fill_value=fill_value, skipna=True, arg_of_max=True, nat=kw.get("nat", False))


def nanargmin(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _arg_impl(group_idx, array, size=size, fill_value=fill_value, skipna=True, arg_of_max=False, nat=kw.get("nat", False))


def _firstlast_impl(group_idx, array, *, size, fill_value, skipna, last, nat=False):
    codes = _safe_codes(group_idx, size)
    data = _to_leading(array)
    mask = _nan_mask(data, nat) if skipna else None
    iota = _iota_like(data)
    if mask is not None:
        iota = jnp.where(mask, iota, -1 if last else _BIG)
    pos = _seg("max" if last else "min", iota, codes, size)
    valid = (pos >= 0) & (pos < _BIG)
    gather_at = jnp.clip(pos, 0, data.shape[0] - 1)
    out = jnp.take_along_axis(data, gather_at, axis=0)
    is_inexact = jnp.issubdtype(data.dtype, jnp.floating) or jnp.issubdtype(
        data.dtype, jnp.complexfloating
    )
    fv = fill_value if fill_value is not None else (jnp.nan if is_inexact else 0)
    out = _promote_for_nan_fill(out, fv)
    out = jnp.where(valid, out, jnp.asarray(fv).astype(out.dtype))
    return _from_leading(out)


def first(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _firstlast_impl(group_idx, array, size=size, fill_value=fill_value, skipna=False, last=False, nat=kw.get("nat", False))


def last(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _firstlast_impl(group_idx, array, size=size, fill_value=fill_value, skipna=False, last=True, nat=kw.get("nat", False))


def nanfirst(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _firstlast_impl(group_idx, array, size=size, fill_value=fill_value, skipna=True, last=False, nat=kw.get("nat", False))


def nanlast(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _firstlast_impl(group_idx, array, size=size, fill_value=fill_value, skipna=True, last=True, nat=kw.get("nat", False))


# ---------------------------------------------------------------------------
# order statistics: quantile / median / mode via a (group, value) lex sort.
#
# jax.lax.sort with num_keys=2 gives a per-column lexicographic sort along
# axis 0 — the shape-static TPU replacement for the reference's complex-
# number partition trick (aggregate_flox.py:50-130).
# ---------------------------------------------------------------------------


def _group_sort(codes, data):
    """Sort (codes, data) lexicographically along axis 0; NaNs sort last
    within each group (lax.sort total order puts NaN after +inf)."""
    codes_b = jnp.broadcast_to(
        codes.reshape((codes.shape[0],) + (1,) * (data.ndim - 1)), data.shape
    ).astype(jnp.int32)
    iota = _iota_like(data)
    sorted_codes, sorted_data, sorted_iota = jax.lax.sort(
        (codes_b, data, iota), dimension=0, num_keys=2
    )
    return sorted_codes, sorted_data, sorted_iota


def _uint_type(dtype):
    return {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}[
        jnp.dtype(dtype).itemsize
    ]


def _monotonic_uint(data):
    """Order-preserving unsigned-integer view: floats use the IEEE sign
    trick (negatives bit-invert, non-negatives set the sign bit — unsigned
    compare then matches total order, NaN above +inf); signed ints flip
    the sign bit (two's complement is already ordered below it); unsigned
    ints pass through."""
    ut = _uint_type(data.dtype)
    nbits = jnp.dtype(ut).itemsize * 8
    bits = jax.lax.bitcast_convert_type(data, ut)
    sign = jnp.asarray(1, ut) << (nbits - 1)
    if jnp.issubdtype(data.dtype, jnp.floating):
        return jnp.where((bits & sign) != 0, ~bits, bits | sign)
    if jnp.issubdtype(data.dtype, jnp.signedinteger):
        return bits ^ sign
    return bits


def _uint_to_value(key, dtype):
    ut = _uint_type(dtype)
    nbits = jnp.dtype(ut).itemsize * 8
    sign = jnp.asarray(1, ut) << (nbits - 1)
    if jnp.issubdtype(jnp.dtype(dtype), jnp.floating):
        bits = jnp.where((key & sign) != 0, key ^ sign, ~key)
    elif jnp.issubdtype(jnp.dtype(dtype), jnp.signedinteger):
        bits = key ^ sign
    else:
        bits = key
    return jax.lax.bitcast_convert_type(bits.astype(ut), dtype)


def _radix_select(data, codes, size, ranks, valid_mask, axis_name=None):
    """Exact per-group order statistics WITHOUT sorting: MSB radix
    bisection over the monotonic integer view of ``data``.

    ``ranks``: (m, size) + data.shape[1:], m independent sets of 0-based
    within-group ranks. Returns the same shape — the exact rank-th
    smallest valid value per group/column (bit-identical to indexing the
    sorted data).

    Why: ``lax.sort`` on TPU is many materialized HBM passes; this runs
    ``nbits`` counting passes where each count is a segment-sum — i.e. the
    one-hot MXU GEMM / Pallas path under the ``segment_sum_impl`` policy —
    and ALL m rank lanes share every pass's data read (their predicates
    stack into one widened segment-sum). The sort-free analogue of the
    reference's complex-partition trick (aggregate_flox.py:50-130), shaped
    for the hardware instead of for numpy.

    ``axis_name``: mesh axis name(s) when running inside ``shard_map`` on a
    SHARD of the data. The bisection state (prefix, rank) is per-group and
    replicated; the only cross-element op is the counting segment-sum, so a
    ``psum`` per pass makes the selection exactly global — the selected
    value is reconstructed bit-by-bit from the counts, never gathered from
    any one shard. This is what lets quantile/median run method='map-reduce'
    on a mesh (the reference must force blockwise for order statistics,
    core.py:685-709: its combine would need whole groups on one worker).
    """
    ut = _uint_type(data.dtype)
    nbits = jnp.dtype(ut).itemsize * 8
    keys = _valid_keys(data, valid_mask)
    n = data.shape[0]
    if axis_name is not None:
        from .parallel.mesh import axis_size

        axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
        n = n * int(np.prod([axis_size(a) for a in axes]))
    # counts ride f32 (the MXU path) when the GLOBAL count cannot overflow
    # its exact integer range; int32 scatter otherwise
    cdtype = jnp.float32 if n < 2**24 else jnp.int32
    m = ranks.shape[0]
    trail = data.shape[1:]

    state0 = (jnp.zeros((m, size) + trail, ut), ranks.astype(jnp.int32))

    def body(i, st):
        prefix, rank = st
        bshift = jnp.asarray(nbits - 1 - i, ut)
        cnt = _radix_pass_count(keys, codes, size, prefix, bshift, cdtype)
        if axis_name is not None:
            # int32 psum: exact, and local f32 counts were exact below 2^24
            cnt = jax.lax.psum(cnt, axis_name)
        return _radix_update(prefix, rank, cnt, bshift)

    prefix, _ = jax.lax.fori_loop(0, nbits, body, state0)
    return _uint_to_value(prefix, data.dtype)


def _valid_keys(data, valid_mask):
    """Monotonic uint view with invalid lanes parked at the maximal key:
    every valid key is strictly below it (valid data is never
    NaN-with-full-payload), so ranks targeting the first nn elements can
    never land on one."""
    ut = _uint_type(data.dtype)
    keys = _monotonic_uint(data)
    if valid_mask is not None:
        keys = jnp.where(valid_mask, keys, ~jnp.zeros((), ut))
    return keys


def _radix_pass_count(keys, codes, size, prefix, bshift, cdtype):
    """One counting pass of the radix bisection: per rank lane, how many
    elements fall in the candidate subtree whose high bits match the
    prefix. Shared by the eager/mesh select (fori body above) and the
    streaming driver (streaming._stream_quantile), which accumulates it
    slab by slab."""
    ut = keys.dtype
    m = prefix.shape[0]
    pad_row = jnp.zeros((m, 1) + keys.shape[1:], ut)
    shifted = jnp.right_shift(keys, bshift)
    # candidate subtree with bit b == 0: high bits match the prefix
    # (whose bit b is still 0) after the shift
    table = jnp.concatenate([jnp.right_shift(prefix, bshift), pad_row], axis=1)
    pred = shifted[None] == jnp.take(table, codes, axis=1)
    # one widened segment-sum counts every rank lane in a single pass
    cnt = _seg("sum", jnp.moveaxis(pred, 0, -1).astype(cdtype), codes, size)
    return jnp.moveaxis(cnt, -1, 0).astype(jnp.int32)  # (m, size, ...)


def _radix_update(prefix, rank, cnt, bshift):
    """Bisection step: lanes whose rank falls past the zero-subtree count
    descend into the one-subtree (set bit b, discount the count)."""
    take_hi = rank >= cnt
    bit = jnp.asarray(1, prefix.dtype) << bshift
    return (
        jnp.where(take_hi, prefix | bit, prefix),
        jnp.where(take_hi, rank - cnt, rank),
    )


# Continuous interpolation families share numpy's (alpha, beta)
# plotting-position parametrization: h = q*(n + 1 - a - b) + a - 1,
# clipped to [0, n-1], linearly interpolated. The discrete variants
# (lower/higher/nearest/midpoint) derive from the linear h.
_ALPHA_BETA = {
    "linear": (1.0, 1.0),
    "hazen": (0.5, 0.5),
    "weibull": (0.0, 0.0),
    "interpolated_inverted_cdf": (0.0, 1.0),
    "median_unbiased": (1 / 3, 1 / 3),
    "normal_unbiased": (3 / 8, 3 / 8),
}


def _quantile_alpha_beta(method: str):
    if method in _ALPHA_BETA:
        return _ALPHA_BETA[method]
    if method in ("lower", "higher", "nearest", "midpoint"):
        return 1.0, 1.0
    raise ValueError(
        f"Unsupported quantile method {method!r}; supported: "
        f"{sorted(_ALPHA_BETA) + ['lower', 'higher', 'nearest', 'midpoint']} "
        "(the numpy engine additionally supports every np.quantile method)."
    )


def _quantile_rank_sets(qs, nnf, method, alpha, beta):
    """Every within-group rank the stacked bisection must select, across
    ALL q values (each counting pass serves every lane), plus per-q meta
    (pos, lo_in, ia, ib) for the interpolation. Shared by the eager/mesh
    select and the streaming driver."""
    rank_list: list = []
    meta = []
    for qi in qs:
        pos = qi * (nnf + 1 - alpha - beta) + (alpha - 1)
        pos = jnp.clip(pos, 0, jnp.maximum(nnf - 1, 0))
        lo_in = jnp.floor(pos).astype(jnp.int32)
        hi_in = jnp.ceil(pos).astype(jnp.int32)
        if method == "nearest":
            # np.quantile rounds the virtual index half-to-even
            ia = ib = len(rank_list)
            rank_list.append(jnp.round(pos).astype(jnp.int32))
        elif method == "lower":
            ia = ib = len(rank_list)
            rank_list.append(lo_in)
        elif method == "higher":
            ia = ib = len(rank_list)
            rank_list.append(hi_in)
        else:
            ia, ib = len(rank_list), len(rank_list) + 1
            rank_list += [lo_in, hi_in]
        meta.append((pos, lo_in, ia, ib))
    return jnp.stack(rank_list), meta


def _quantile_interp_value(method, meta_k, selected, dtype):
    """Interpolate one q's value from the radix-selected order statistics —
    the ONE place the select-path method branches live, shared by the
    eager/mesh kernel and the streaming driver (streaming._stream_quantile).
    'nearest' selected its rounded rank directly, so it reads v_lo."""
    pos, lo_in, ia, ib = meta_k
    v_lo, v_hi = selected[ia], selected[ib]
    if method in ("lower", "nearest"):
        return v_lo
    if method == "higher":
        return v_hi
    if method == "midpoint":
        return (v_lo + v_hi) / 2
    frac = (pos - lo_in).astype(dtype)
    return v_lo + frac * (v_hi - v_lo)


def _quantile_impl_choice(data=None, size: int = 0) -> str:
    """Sort-vs-select for grouped order statistics. ``"auto"`` resolves to
    the autotune store's observed winner when the tuner is on (the on-chip
    ``quantile_gbps`` sweep and seeded BENCH_HISTORY rounds feed it —
    mechanically resolving the open decision docs/engines.md used to
    carry); sort is the measured CPU status quo otherwise."""
    from .options import OPTIONS

    policy = OPTIONS["quantile_impl"]
    if policy == "auto":
        if OPTIONS["autotune"] and data is not None:
            from . import autotune

            nelems = int(np.prod(data.shape)) if data.ndim else 0
            return autotune.decide(
                "quantile", "sort", ("sort", "select"),
                dtype=str(data.dtype), ngroups=size, nelems=nelems,
            )
        return "sort"
    return policy


def _quantile_impl(group_idx, array, *, size, fill_value, dtype, q, skipna,
                   method="linear", axis_name=None):
    codes = _safe_codes(group_idx, size)
    data = _to_leading(array)
    if not jnp.issubdtype(data.dtype, jnp.floating):
        default_float = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        data = data.astype(dtype if dtype is not None else default_float)
    mask = _nan_mask(data)
    if not skipna and mask is not None:
        # NaN propagates: a group containing any NaN yields NaN.
        has_nan_local = _seg("max", (~mask).astype(jnp.int8), codes, size)
        if axis_name is not None:
            has_nan_local = jax.lax.pmax(has_nan_local, axis_name)
        group_has_nan = has_nan_local > 0
    else:
        group_has_nan = None
    qs = np.atleast_1d(np.asarray(q, dtype=np.float64))
    scalar_q = np.ndim(q) == 0
    # on a mesh shard only the counting bisection distributes (the sort
    # path would sort shard-locally and select wrong elements)
    sel = axis_name is not None or _quantile_impl_choice(data, size) == "select"

    if sel:
        sorted_data = data  # only its shape/dtype are consulted below
        off_b = None
    else:
        _, sorted_data, _ = _group_sort(codes, data)
        full_counts = _counts(codes, size)  # (size,)
        offsets = jnp.cumsum(full_counts) - full_counts  # exclusive, (size,)
        # broadcast offsets across trailing dims; keep them INTEGER — only
        # the within-group position goes through float, so gather indices
        # stay exact even when the total length exceeds float32's integer
        # range.
        off_b = offsets.reshape((size,) + (1,) * (sorted_data.ndim - 1))
    nn = _counts(codes, size, mask=mask)  # non-NaN counts, (size, ...) or (size,)
    if axis_name is not None:
        nn = jax.lax.psum(nn, axis_name)  # global group sizes
    nn_full = jnp.broadcast_to(
        _bcast_present(nn, sorted_data[:1]), (size,) + sorted_data.shape[1:]
    )

    alpha, beta = _quantile_alpha_beta(method)

    outs = []
    nmax = sorted_data.shape[0]
    # index arithmetic in f32/f64, never the data dtype: bf16 cannot even
    # represent odd counts above 256, which would select wrong elements
    idx_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    nnf = nn_full.astype(idx_dtype)

    def _pos_ranks(qi):
        pos = qi * (nnf + 1 - alpha - beta) + (alpha - 1)  # within-group, float
        pos = jnp.clip(pos, 0, jnp.maximum(nnf - 1, 0))
        return pos, jnp.floor(pos).astype(jnp.int32), jnp.ceil(pos).astype(jnp.int32)

    if sel:
        ranks, meta = _quantile_rank_sets(qs, nnf, method, alpha, beta)
        selected = _radix_select(data, codes, size, ranks, mask, axis_name=axis_name)

    for k, qi in enumerate(qs):
        if sel:
            val = _quantile_interp_value(method, meta[k], selected, sorted_data.dtype)
        else:
            pos, lo_in, hi_in = _pos_ranks(qi)
            lo_c = jnp.clip(off_b + lo_in, 0, nmax - 1)
            hi_c = jnp.clip(off_b + hi_in, 0, nmax - 1)
            v_lo = jnp.take_along_axis(sorted_data, lo_c, axis=0)
            v_hi = jnp.take_along_axis(sorted_data, hi_c, axis=0)
            frac = (pos - lo_in).astype(sorted_data.dtype)
            if method == "lower":
                val = v_lo
            elif method == "higher":
                val = v_hi
            elif method == "nearest":
                # np.quantile rounds the virtual index half-to-even
                nr = jnp.clip(off_b + jnp.round(pos).astype(jnp.int32), 0, nmax - 1)
                val = jnp.take_along_axis(sorted_data, nr, axis=0)
            elif method == "midpoint":
                val = (v_lo + v_hi) / 2
            else:  # all continuous families: linear interpolation at h
                val = v_lo + frac * (v_hi - v_lo)
        empty = nn_full <= 0
        fv = fill_value if fill_value is not None else jnp.nan
        val = jnp.where(empty, jnp.asarray(fv).astype(val.dtype), val)
        if group_has_nan is not None:
            val = jnp.where(
                _bcast_present(group_has_nan, val), jnp.asarray(jnp.nan, val.dtype), val
            )
        outs.append(_from_leading(val))
    if scalar_q:
        return outs[0]
    return jnp.stack(outs, axis=0)


def quantile(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, q, method="linear", axis_name=None, **kw):
    return _quantile_impl(group_idx, array, size=size, fill_value=fill_value, dtype=dtype, q=q, skipna=False, method=method, axis_name=axis_name)


def nanquantile(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, q, method="linear", axis_name=None, **kw):
    return _quantile_impl(group_idx, array, size=size, fill_value=fill_value, dtype=dtype, q=q, skipna=True, method=method, axis_name=axis_name)


def median(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, axis_name=None, **kw):
    return _quantile_impl(group_idx, array, size=size, fill_value=fill_value, dtype=dtype, q=0.5, skipna=False, axis_name=axis_name)


def nanmedian(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, axis_name=None, **kw):
    return _quantile_impl(group_idx, array, size=size, fill_value=fill_value, dtype=dtype, q=0.5, skipna=True, axis_name=axis_name)


def _mode_impl(group_idx, array, *, size, fill_value, skipna):
    codes = _safe_codes(group_idx, size)
    data = _to_leading(array)
    mask = _nan_mask(data)
    sorted_codes, sorted_data, _ = _group_sort(codes, data)
    smask = None
    if mask is not None:
        smask = ~jnp.isnan(sorted_data)
    n = sorted_data.shape[0]
    iota = _iota_like(sorted_data)
    val_same = sorted_data[1:] == sorted_data[:-1]
    if smask is not None and not skipna:
        # scipy.stats.mode "propagate" (scipy >= 1.11, via np.unique's
        # equal_nan): NaNs count as ONE candidate value with their full
        # multiplicity. The sort parks NaNs last within each group, so
        # merging adjacent NaN lanes makes them a single run.
        val_same = val_same | (~smask[1:] & ~smask[:-1])
    prev_same = jnp.concatenate(
        [
            jnp.zeros((1,) + sorted_data.shape[1:], bool),
            val_same & (sorted_codes[1:] == sorted_codes[:-1]),
        ]
    )
    # run start index per position: cumulative max of start markers
    start_marker = jnp.where(prev_same, -1, iota)
    run_start = jax.lax.cummax(start_marker, axis=0)
    next_diff = jnp.concatenate(
        [prev_same[1:], jnp.zeros((1,) + sorted_data.shape[1:], bool)]
    )
    end_marker = jnp.where(next_diff, n, iota)
    run_end = jax.lax.cummin(end_marker[::-1], axis=0)[::-1]
    run_len = run_end - run_start + 1
    if smask is not None and skipna:
        run_len = jnp.where(smask, run_len, -1)
    # codes are identical across trailing columns; segment ids must be 1-D
    codes1d = sorted_codes if sorted_codes.ndim == 1 else sorted_codes[(slice(None),) + (0,) * (sorted_codes.ndim - 1)]
    best_len = _seg("max", run_len, codes1d, size)
    best_per_elem = jnp.take(
        jnp.concatenate([best_len, jnp.zeros((1,) + best_len.shape[1:], best_len.dtype)]),
        codes1d,
        axis=0,
    )
    cand = jnp.where((run_len == best_per_elem) & (run_len > 0), iota, _BIG)
    pos = _seg("min", cand, codes1d, size)
    valid = pos < _BIG
    out = jnp.take_along_axis(sorted_data, jnp.clip(pos, 0, n - 1), axis=0)
    fv = fill_value if fill_value is not None else (jnp.nan if jnp.issubdtype(out.dtype, jnp.floating) else 0)
    out = _promote_for_nan_fill(out, fv)
    out = jnp.where(valid, out, jnp.asarray(fv).astype(out.dtype))
    return _from_leading(out)


def mode(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _mode_impl(group_idx, array, size=size, fill_value=fill_value, skipna=False)


def nanmode(group_idx, array, *, axis=-1, size, fill_value=None, dtype=None, **kw):
    return _mode_impl(group_idx, array, size=size, fill_value=fill_value, skipna=True)


# ---------------------------------------------------------------------------
# grouped scans: segmented associative_scan (log-depth on device).
#
# The segmented-scan operator ``((v1,f1),(v2,f2)) -> (f2 ? v2 : v1⊕v2, f1|f2)``
# is associative for any associative ⊕; flags mark group-run starts after a
# stable sort by code. The same operator drives the cross-shard Blelloch
# combine in parallel/scan.py (reference analogue: aggregations.py:792-846).
# ---------------------------------------------------------------------------


def _segmented_scan(values, flags, op, reverse=False):
    def combine(a, b):
        va, fa = a
        vb, fb = b
        return jnp.where(fb, vb, op(va, vb)), fa | fb

    out, _ = jax.lax.associative_scan(combine, (values, flags), axis=0, reverse=reverse)
    return out


def _grouped_scan_setup(group_idx, array):
    """Stable-sort by code; return permutation machinery + flags."""
    codes = jnp.asarray(group_idx).astype(jnp.int32).reshape(-1)
    data = _to_leading(array)
    perm = jnp.argsort(codes, stable=True)
    inv = jnp.argsort(perm)
    sorted_codes = codes[perm]
    sorted_data = jnp.take(data, perm, axis=0)
    starts = jnp.concatenate(
        [jnp.ones((1,), bool), sorted_codes[1:] != sorted_codes[:-1]]
    )
    flags = jnp.broadcast_to(
        starts.reshape((starts.shape[0],) + (1,) * (data.ndim - 1)), data.shape
    )
    return sorted_codes, sorted_data, flags, inv


def _cumsum_impl(group_idx, array, *, size, dtype, skipna, nat=False):
    if not nat:
        data = _to_leading(array)
        cast = _maybe_cast(data, dtype)
        if _scan_impl_choice(cast, size) == "pallas":
            from .pallas_kernels import segment_cumsum_pallas

            codes = jnp.asarray(group_idx).astype(jnp.int32).reshape(-1)
            out = segment_cumsum_pallas(
                cast, codes, size, skipna=skipna, interpret=not _on_tpu()
            )
            return _from_leading(out)
    _, sorted_data, flags, inv = _grouped_scan_setup(group_idx, array)
    # nat: int64-viewed datetimes/timedeltas, missing = INT64_MIN. Unlike
    # floats (where NaN propagates through + arithmetically), the sentinel
    # must be masked out of the running sum and, for the non-skipna scan,
    # re-poisoned from the first missing position onward (numpy cumsum of a
    # NaT timedelta is NaT thereafter).
    mask = _nan_mask(sorted_data, nat) if (skipna or nat) else None
    vals = sorted_data if mask is None else jnp.where(mask, sorted_data, jnp.zeros((), sorted_data.dtype))
    vals = _maybe_cast(vals, dtype)
    out_dtype = vals.dtype
    if jnp.issubdtype(vals.dtype, jnp.floating) and _acc_dtype(vals.dtype) != vals.dtype:
        vals = vals.astype(_acc_dtype(vals.dtype))  # bf16 running sums saturate
    scanned = _segmented_scan(vals, flags, jnp.add)
    if nat and not skipna and mask is not None:
        seen_missing = _segmented_scan((~mask).astype(jnp.int32), flags, jnp.maximum)
        scanned = jnp.where(seen_missing > 0, jnp.asarray(_NAT_INT, scanned.dtype), scanned)
    if scanned.dtype != out_dtype:
        scanned = scanned.astype(out_dtype)
    return _from_leading(jnp.take(scanned, inv, axis=0))


def cumsum(group_idx, array, *, axis=-1, size=None, fill_value=None, dtype=None, **kw):
    return _cumsum_impl(group_idx, array, size=size, dtype=dtype, skipna=False, nat=kw.get("nat", False))


def nancumsum(group_idx, array, *, axis=-1, size=None, fill_value=None, dtype=None, **kw):
    return _cumsum_impl(group_idx, array, size=size, dtype=dtype, skipna=True, nat=kw.get("nat", False))


def _ffill_impl(group_idx, array, *, reverse, nat=False):
    codes = jnp.asarray(group_idx).astype(jnp.int32).reshape(-1)
    data = _to_leading(array)
    if reverse:
        codes = codes[::-1]
        data = data[::-1]
    sorted_codes, sorted_data, flags, inv = _grouped_scan_setup(codes, _from_leading(data))
    mask = _nan_mask(sorted_data, nat)
    if mask is None:
        out = sorted_data
    else:
        iota = _iota_like(sorted_data)
        valid_idx = jnp.where(mask, iota, -1)
        last_valid = _segmented_scan(valid_idx, flags, jnp.maximum)
        gathered = jnp.take_along_axis(sorted_data, jnp.clip(last_valid, 0, None), axis=0)
        # "no prior valid" stays missing: NaT for int64-viewed datetimes
        missing = (
            jnp.asarray(_NAT_INT, sorted_data.dtype)
            if nat and jnp.issubdtype(sorted_data.dtype, jnp.signedinteger)
            else jnp.asarray(jnp.nan, sorted_data.dtype)
        )
        out = jnp.where(last_valid >= 0, gathered, missing)
    out = jnp.take(out, inv, axis=0)
    if reverse:
        out = out[::-1]
    return _from_leading(out)


def ffill(group_idx, array, *, axis=-1, size=None, fill_value=None, dtype=None, **kw):
    return _ffill_impl(group_idx, array, reverse=False, nat=kw.get("nat", False))


def bfill(group_idx, array, *, axis=-1, size=None, fill_value=None, dtype=None, **kw):
    return _ffill_impl(group_idx, array, reverse=True, nat=kw.get("nat", False))


# ---------------------------------------------------------------------------
# registry + dispatch
# ---------------------------------------------------------------------------

KERNELS: dict[str, Callable[..., Any]] = {
    "sum": sum_,
    "nansum": nansum,
    "prod": prod,
    "nanprod": nanprod,
    "max": max_,
    "nanmax": nanmax,
    "min": min_,
    "nanmin": nanmin,
    "mean": mean,
    "nanmean": nanmean,
    "var": var,
    "nanvar": nanvar,
    "std": std,
    "nanstd": nanstd,
    "var_chunk": var_chunk,
    "count": nanlen,
    "nanlen": nanlen,
    "len": len_,
    "all": all_,
    "any": any_,
    "argmax": argmax,
    "argmin": argmin,
    "nanargmax": nanargmax,
    "nanargmin": nanargmin,
    "first": first,
    "last": last,
    "nanfirst": nanfirst,
    "nanlast": nanlast,
    "median": median,
    "nanmedian": nanmedian,
    "quantile": quantile,
    "nanquantile": nanquantile,
    "mode": mode,
    "nanmode": nanmode,
    "sum_of_squares": sum_of_squares,
    "nansum_of_squares": nansum_of_squares,
    "cumsum": cumsum,
    "nancumsum": nancumsum,
    "ffill": ffill,
    "bfill": bfill,
}


def generic_kernel(func: str, group_idx, array, **kwargs):
    """Engine entry point for the 'jax' engine (plugin-boundary parity with
    generic_aggregate, aggregations.py:60-133)."""
    try:
        fn = KERNELS[func]
    except KeyError:
        raise NotImplementedError(f"jax engine has no kernel for {func!r}") from None
    from . import telemetry

    if telemetry.detailed():
        # under jit this fires at TRACE time, so per-kernel counts are a
        # retrace signal (executions are fused into compiled programs);
        # eager (jit=False) calls count once per execution
        telemetry.METRICS.inc(f"kernel.trace.{func}")
    return fn(group_idx, array, **kwargs)


# ---------------------------------------------------------------------------
# sort engine: present-groups execution (the high-cardinality regime).
#
# Every kernel above is dense over the static label universe ``size`` — the
# right shape contract for XLA, and an OOM machine when ``size`` is millions
# while each call touches a few thousand groups (user IDs, geohashes,
# station IDs). The sort engine is the TPU-native analogue of the
# reference's sort+``ufunc.reduceat`` engine (aggregate_flox.py:133-192):
# unique-ify the codes once (a sort), relabel them into the compact
# [0, n_present) domain, run the UNCHANGED dense kernels over a small
# banded capacity, and scatter back to the dense layout only where the
# caller asks for it — so accumulator bytes track the data, not the label
# universe. Element order is never permuted (only the codes are relabeled,
# monotonically), so every kernel family — including float sums, order
# statistics and position-tracking argreductions — is bit-identical to the
# dense path on the present groups WHEN both domains resolve to the same
# segment-op lowering. Off-TPU (the tier-1 surface) that is always true
# (auto = scatter at every size); on TPU the compact domain may cross the
# pallas/radixbin/matmul size gates the dense domain did not, reassociating
# float sums within the documented accuracy of those lowerings — the same
# caveat any segment_sum_impl flip has always carried (docs/engines.md).
# ---------------------------------------------------------------------------


from .cache import LRUCache

#: host-side memo of present-group tables: the serve/pipeline hot loops
#: re-reduce over the same factorized codes many times, and the O(N log N)
#: unique pass is pure overhead after the first call. Keyed on a content
#: fingerprint (not object identity — factorize_cached may rebuild equal
#: codes). Registered in cache.clear_all / cache.stats ("present_tables").
_PRESENT_CACHE: LRUCache = LRUCache(maxsize=64)

#: capacity bands are powers of two so repeated calls with drifting
#: present-group counts reuse the same compiled programs (the same reason
#: resilience's OOM ladder re-stages on a power-of-two ladder)
_PRESENT_CAP_MIN = 8


def _codes_fingerprint(codes: "np.ndarray", size: int) -> tuple:
    """Cheap content key for the present-table memo: blake2b over the raw
    code bytes (a few ms/1e6 codes — an order cheaper than the unique pass
    it saves) + shape/dtype/size."""
    import hashlib

    h = hashlib.blake2b(np.ascontiguousarray(codes).view(np.uint8), digest_size=16)
    return (h.hexdigest(), codes.shape, str(codes.dtype), int(size))


def present_groups(codes: "np.ndarray", size: int) -> "np.ndarray":
    """Sorted unique valid codes of a host code array (the "present" table).

    ``codes``: integer codes with -1 meaning "missing label". Memoized on
    content (see :data:`_PRESENT_CACHE`).
    """
    codes = np.asarray(codes).reshape(-1)
    fingerprint = _codes_fingerprint(codes, size)
    hit = _PRESENT_CACHE.get(fingerprint)
    if hit is not None:
        return hit
    present = np.unique(codes[codes >= 0]).astype(np.int64, copy=False)
    _PRESENT_CACHE[fingerprint] = present
    return present


def compact_codes(codes: "np.ndarray", present: "np.ndarray") -> "np.ndarray":
    """Relabel ``codes`` into the compact [0, n_present) domain.

    Monotone (present is sorted), order-preserving, and -1 (missing) maps
    to -1 — so per-group element order, and therefore every accumulation
    order, is exactly the dense path's.
    """
    codes = np.asarray(codes).reshape(-1)
    out = np.searchsorted(present, codes).astype(np.int32)
    out[codes < 0] = -1
    return np.ascontiguousarray(out)


def present_cap(n_present: int, size: int) -> int:
    """Banded compact-domain capacity: the next power of two above
    ``n_present``, with at least one empty pad slot whenever the dense
    universe has absent groups. The pad slot is load-bearing for
    bit-identity: it makes the compact reduction contain an empty group
    exactly when the dense one does, so the empty-fill dtype promotions
    (``_promote_for_nan_fill``) and ``_astype_final``'s NaN-carrying
    downcast guard fire identically on both paths — and its value is
    byte-for-byte the dense path's empty-group value, which the dense
    scatter-back reuses as its fill.
    """
    n_present = int(n_present)
    if n_present >= size:
        return max(1, n_present)
    want = max(_PRESENT_CAP_MIN, n_present + 1)
    cap = 1 << (want - 1).bit_length()
    return min(cap, size)


def scatter_present_dense(result_c, present: "np.ndarray", size: int):
    """Expand a compact (..., cap) result to the dense (..., size) layout.

    Host-side by design: the dense layout exists only in host RAM, never as
    a device allocation — that is the whole point of the engine. Absent
    groups take the value of the compact result's first pad slot (an empty
    group that went through the identical kernel/finalize pipeline), so the
    fill is bit-identical to the dense path's empty-group value for every
    aggregation family, min_count mask and datetime round-trip included.
    Thin wrapper over :class:`multiarray.PresentGroups` — the container
    every runtime's compact layer rides to the host boundary.
    """
    from .multiarray import PresentGroups

    return PresentGroups(present, np.asarray(result_c), size).scatter_dense()


def sort_segment_reduce(op: str, data, codes, *, ncap: int):
    """Device-side present-groups segment reduction: ONE stable lex-sort of
    ``(codes, position)`` bins the rows by group, run boundaries on the
    sorted codes yield compact segment ids, and a single segment-``op``
    over ``ncap`` segments reduces each run.

    This is the jit-safe sibling of the host unique+compact orchestration
    (``present_groups``/``compact_codes``) for callers whose codes are
    traced. No shipped runtime needs it yet — every current flow's codes
    are host-known before tracing, so compaction happens once up front —
    but traced-codes callers (a fully-fused serve program, per-shard
    re-compaction) get the same shape contract from it, tested directly.
    ``ncap`` must be a static upper bound on the number of distinct
    present groups (overflowing runs are dropped, so size the cap from
    host knowledge).

    ``data``: (N, ...) leading layout; ``codes``: (N,) int, -1 missing.
    Returns ``(present, out, n_present)``: the sorted present codes padded
    with -1 to (ncap,), the per-present-group reductions (ncap, ...), and
    the scalar count of distinct present groups.

    The position key makes the sort stable, so within a group the data
    keeps stream order and additive reductions accumulate in exactly the
    dense scatter path's order (bit-identity, not just equality).
    """
    codes = jnp.asarray(codes).astype(jnp.int32).reshape(-1)
    n = codes.shape[0]
    data = jnp.asarray(data)
    safe = jnp.where(codes < 0, _BIG, codes)
    iota = jnp.arange(n, dtype=jnp.int32)
    sorted_codes, perm = jax.lax.sort((safe, iota), dimension=0, num_keys=2)
    data_s = jnp.take(data, perm, axis=0)
    valid = sorted_codes != _BIG
    boundary = jnp.concatenate(
        [valid[:1], valid[1:] & (sorted_codes[1:] != sorted_codes[:-1])]
    )
    seg = jnp.cumsum(boundary.astype(jnp.int32)) - 1  # -1 until the first run
    n_present = jnp.sum(boundary.astype(jnp.int32))
    # invalid rows (missing labels) and cap overflow park in segment ncap
    seg = jnp.where(valid & (seg >= 0) & (seg < ncap), seg, ncap)
    out = _seg_op_dense(op, data_s, seg, ncap)
    present = jax.ops.segment_max(
        jnp.where(valid, sorted_codes, -1), seg, num_segments=ncap + 1
    )[:ncap]
    present = jnp.where(present < 0, -1, present)  # empty segment_max -> INT_MIN
    return present, out, n_present


def _seg_op_dense(op: str, data_s, seg, ncap: int):
    """The segment-reduce leg of :func:`sort_segment_reduce` (split out so
    the radix-binning Pallas path can swap in below it)."""
    if op == "sum" and jnp.issubdtype(data_s.dtype, jnp.floating):
        acc = _acc_dtype(data_s.dtype)
        if data_s.dtype != acc:
            data_s = data_s.astype(acc)
    fn = {
        "sum": jax.ops.segment_sum,
        "prod": jax.ops.segment_prod,
        "max": jax.ops.segment_max,
        "min": jax.ops.segment_min,
    }[op]
    return fn(data_s, seg, num_segments=ncap + 1)[:ncap]


def sort_kernel(func: str, group_idx, array, *, axis=-1, size, fill_value=None,
                dtype=None, **kwargs):
    """Engine entry point for the 'sort' engine: host unique + compact
    relabel, the unchanged dense kernel over the banded capacity, then the
    dense scatter-back (this per-kernel form keeps the dense (..., size)
    return contract of ``generic_aggregate``; the memory-saving flows —
    eager/mesh/streaming orchestration in core/streaming — compact once
    per call and scatter once at the very end instead).

    Traced codes cannot be uniqued host-side; those calls fall back to the
    dense jax kernel (the mesh/fused programs compact before tracing).
    """
    if not isinstance(group_idx, np.ndarray):
        return generic_kernel(
            func, group_idx, array, axis=axis, size=size,
            fill_value=fill_value, dtype=dtype, **kwargs
        )
    present = present_groups(group_idx, size)
    ncap = present_cap(len(present), size)
    ccodes = compact_codes(group_idx, present)
    out = generic_kernel(
        func, ccodes, array, axis=axis, size=ncap,
        fill_value=fill_value, dtype=dtype, **kwargs
    )
    return scatter_present_dense(np.asarray(out), present, size)
