"""Cohort detection: the method-selection brain (L3).

Parity target: /root/reference/flox/cohorts.py:109-301 —
``find_group_cohorts`` builds a sparse boolean bitmask ``S[chunk, label]``
(cohorts.py:34-105), walks a decision ladder (single chunk → blockwise;
every label in one chunk → blockwise; single cohort → map-reduce; …), and
otherwise measures *containment* ``C = S.T @ S / chunks_per_label``
(cohorts.py:241-244) and greedily merges labels whose chunk-sets overlap
≥ 75 % into cohorts (cohorts.py:256-290).

TPU reading of the same quantities: a "chunk" is a shard of the reduced
axis (equal slices of length ``N / n_shards``). The ladder's outcomes map to
the three mesh programs (parallel/mapreduce.py):

* ``blockwise`` — every group is shard-local already; skip the combine.
* ``cohorts``  — labels cluster into shard-subsets; psum_scatter ownership
  pays off because each device finalizes only its cohort's groups.
* ``map-reduce`` — labels are spread over most shards; dense psum combine.

Everything here is host-side numpy/scipy, exactly as the reference keeps its
detection in scipy-sparse land; the result only parameterizes which SPMD
program gets traced.
"""

from __future__ import annotations

import logging
import math
from typing import Sequence

import numpy as np


logger = logging.getLogger("flox_tpu.cohorts")

__all__ = ["find_group_cohorts", "chunks_from_shards", "ownership_permutation"]


def ownership_permutation(
    mapping: dict[tuple[int, ...], list[int]], size: int, n_shards: int
) -> np.ndarray | None:
    """Group-ownership permutation aligning psum_scatter slices with cohorts.

    ``mapping`` is ``find_group_cohorts``' cohort → labels dict. The cohorts
    mesh program scatters the group axis in ``n_shards`` equal tiles — tile
    ``d`` lands on device ``d`` — so ownership is positional. This computes a
    permutation placing each cohort's labels in the tiles of the shards that
    actually hold the cohort's data (the locality economics of the
    reference's per-cohort subgraphs, cohorts.py:109-301, expressed as a
    static gather): device ``d`` then finalizes groups whose rows mostly
    live on ``d``, and downstream shard-local consumers read their own
    groups without cross-device traffic.

    Returns ``perm`` of length ``n_shards * ceil(size / n_shards)`` mapping
    slot → group id (ids ≥ ``size`` are padding), or None when the mapping
    gives no usable locality (empty, or one cohort spanning everything).
    """
    if not mapping:
        return None
    cap = math.ceil(size / n_shards)
    size_pad = cap * n_shards
    load = [0] * n_shards
    slots: list[list[int]] = [[] for _ in range(n_shards)]

    def place(label: int, prefs: Sequence[int]) -> None:
        for d in prefs:
            if load[d] < cap:
                slots[d].append(label)
                load[d] += 1
                return
        d = int(np.argmin(load))
        slots[d].append(label)
        load[d] += 1

    assigned = np.zeros(size, dtype=bool)
    # widest cohorts first so their preferred shards still have capacity
    for chunk_set, labels in sorted(mapping.items(), key=lambda kv: -len(kv[1])):
        prefs = sorted(
            (d for d in chunk_set if d < n_shards), key=lambda d: load[d]
        )
        for lab in labels:
            if 0 <= lab < size and not assigned[lab]:
                place(int(lab), prefs)
                assigned[lab] = True
    for lab in np.flatnonzero(~assigned):
        place(int(lab), ())

    perm = np.full(size_pad, size, dtype=np.int64)  # `size` = zero-pad column
    for d in range(n_shards):
        start = d * cap
        perm[start : start + len(slots[d])] = slots[d]
    identity = np.arange(size_pad)
    identity[size:] = size
    if np.array_equal(perm, identity):
        return None  # positional ownership is already aligned
    return perm


def chunks_from_shards(n: int, n_shards: int) -> tuple[int, ...]:
    """Equal-slice chunk lengths for a sharded axis (last shard may be short)."""
    per = math.ceil(n / n_shards)
    chunks = []
    left = n
    while left > 0:
        take = min(per, left)
        chunks.append(take)
        left -= take
    return tuple(chunks)


def _is_nested_chunks(chunks) -> bool:
    """Multi-axis chunk grids are sequences of per-axis chunk tuples."""
    return bool(len(chunks)) and isinstance(chunks[0], (tuple, list, np.ndarray))


def _chunk_ids(shape: tuple[int, ...], chunks) -> np.ndarray:
    """Flattened chunk index per element of an nD label array chunked by a
    per-axis grid (row-major over the block grid, matching dask's
    block_id ordering in the reference's bitmask, cohorts.py:34-105)."""
    cid: np.ndarray | None = None
    ndim = len(shape)
    for ax, ch in enumerate(chunks):
        ch = tuple(int(c) for c in ch)
        if sum(ch) != shape[ax]:
            raise ValueError(
                f"chunks for axis {ax} sum to {sum(ch)}, label axis is {shape[ax]}"
            )
        bounds = np.cumsum(ch)
        block = np.searchsorted(bounds, np.arange(shape[ax]), side="right").astype(np.int64)
        bshape = [1] * ndim
        bshape[ax] = shape[ax]
        block = block.reshape(bshape)
        cid = block if cid is None else cid * len(ch) + block
    return np.broadcast_to(cid, shape)


def _label_chunk_bitmask(labels: np.ndarray, chunks, nlabels: int):
    """Sparse boolean S[chunk, label]: does chunk i contain label j?

    Parity: _compute_label_chunk_bitmask (cohorts.py:34-105). ``chunks`` is
    a chunk-length sequence over the flattened labels, or — for nD label
    arrays chunked on every axis (the reference's NWM county case) — a
    sequence of per-axis chunk tuples. The reference's write-True-uniques
    trick becomes a per-chunk ``np.unique`` / a coo-dedup here — the chunk
    count is small (shards), so this stays cheap.
    """
    import scipy.sparse

    if _is_nested_chunks(chunks):
        labels = np.asarray(labels)
        if labels.ndim != len(chunks):
            raise ValueError(
                f"nested chunks describe {len(chunks)} axes but labels have "
                f"{labels.ndim} dims"
            )
        cid = _chunk_ids(labels.shape, chunks).reshape(-1)
        flat = labels.reshape(-1)
        keep = flat >= 0
        nchunks = int(np.prod([len(c) for c in chunks]))
        mat = scipy.sparse.csc_array(
            (np.ones(int(keep.sum()), dtype=np.int64), (cid[keep], flat[keep])),
            shape=(nchunks, nlabels), dtype=np.int64,
        )
        mat.data = np.ones_like(mat.data)  # construction summed duplicates
        return mat

    labels = np.asarray(labels).reshape(-1)
    rows: list[np.ndarray] = []
    cols: list[np.ndarray] = []
    start = 0
    for i, c in enumerate(chunks):
        seg = labels[start : start + c]
        start += c
        uniq = np.unique(seg[seg >= 0])
        rows.append(np.full(uniq.shape, i, dtype=np.int64))
        cols.append(uniq)
    rows_a = np.concatenate(rows) if rows else np.empty(0, dtype=np.int64)
    cols_a = np.concatenate(cols) if cols else np.empty(0, dtype=np.int64)
    data = np.ones(rows_a.shape, dtype=np.int64)
    return scipy.sparse.csc_array(
        (data, (rows_a, cols_a)), shape=(len(chunks), nlabels), dtype=np.int64
    )


_COHORTS_CACHE: dict = {}


def find_group_cohorts(
    labels,
    chunks: Sequence[int],
    expected_groups=None,
    merge: bool = True,
) -> tuple[str, dict[tuple[int, ...], list[int]]]:
    """Detect cohorts and recommend an execution method.

    Returns ``(method, chunks_cohorts)`` where ``method`` is one of
    "blockwise" | "cohorts" | "map-reduce" and ``chunks_cohorts`` maps a
    tuple of chunk indices to the list of labels they own (empty for
    map-reduce, as in the reference). ``merge=False`` skips the containment
    merge and returns one cohort per label (parity: cohorts.py merge flag).

    Results are memoized on a label fingerprint — repeated reductions over
    the same layout (e.g. one climatology per training step) skip the
    O(nlabels²) containment analysis (parity: the reference memoizes its
    chunk analyses through cachey, cache.py:7-9).

    Decision ladder parity: cohorts.py:109-301.
    """
    import hashlib

    nested = _is_nested_chunks(chunks)
    labels = np.asarray(labels) if nested else np.asarray(labels).reshape(-1)
    key = (
        hashlib.sha1(np.ascontiguousarray(labels)).hexdigest(),
        labels.shape,
        tuple(tuple(int(x) for x in c) for c in chunks) if nested else tuple(chunks),
        None if expected_groups is None else len(expected_groups),
        merge,
    )
    hit = _COHORTS_CACHE.get(key)
    if hit is not None:
        return hit
    out = _find_group_cohorts(labels, chunks, expected_groups, merge)
    if len(_COHORTS_CACHE) > 128:
        _COHORTS_CACHE.clear()
    _COHORTS_CACHE[key] = out
    return out


def _find_group_cohorts(
    labels: np.ndarray,
    chunks: Sequence[int],
    expected_groups,
    merge: bool,
) -> tuple[str, dict[tuple[int, ...], list[int]]]:
    if expected_groups is None:
        nlabels = int(labels.max()) + 1 if labels.size and labels.max() >= 0 else 0
    else:
        nlabels = len(expected_groups)
    nchunks = (
        int(np.prod([len(c) for c in chunks]))
        if _is_nested_chunks(chunks)
        else len(chunks)
    )

    if nlabels == 0:
        return "map-reduce", {}

    # single chunk: everything is local (cohorts.py:151-152)
    if nchunks == 1:
        logger.debug("find_group_cohorts: single chunk -> blockwise")
        return "blockwise", {(0,): list(range(nlabels))}

    bitmask = _label_chunk_bitmask(labels, chunks, nlabels)
    chunks_per_label = np.asarray(bitmask.sum(axis=0)).reshape(-1)
    present = chunks_per_label > 0

    # every label lives in exactly one chunk -> blockwise (cohorts.py:182-184)
    if (chunks_per_label[present] == 1).all():
        coo = bitmask.tocoo()
        mapping: dict[tuple[int, ...], list[int]] = {}
        for chunk, label in zip(coo.coords[0], coo.coords[1]):
            mapping.setdefault((int(chunk),), []).append(int(label))
        logger.debug("find_group_cohorts: one chunk per label -> blockwise")
        return "blockwise", mapping

    # single cohort: every label occupies every chunk (cohorts.py:187-189)
    if (chunks_per_label[present] == nchunks).all():
        logger.debug("find_group_cohorts: all labels in all chunks -> map-reduce")
        return "map-reduce", {}

    if not merge:
        coo = bitmask.tocoo()
        per_label: dict[int, set[int]] = {}
        for chunk, label in zip(coo.coords[0], coo.coords[1]):
            per_label.setdefault(int(label), set()).add(int(chunk))
        raw: dict[tuple[int, ...], list[int]] = {}
        for lab, cset in sorted(per_label.items()):
            raw.setdefault(tuple(sorted(cset)), []).append(lab)
        return "cohorts", raw

    # containment matrix C[i, j] = |chunks(i) ∩ chunks(j)| / |chunks(i)|
    # (cohorts.py:241-244)
    S = bitmask.T  # (nlabels, nchunks)
    overlap = (S @ S.T).astype(np.float64)  # (nlabels, nlabels)
    denom = np.where(chunks_per_label > 0, chunks_per_label, 1).astype(np.float64)
    containment = overlap.multiply(1.0 / denom[:, None]).tocsr()

    # sparsity guard: highly-overlapping labels -> map-reduce (cohorts.py:220-237)
    sparsity = containment.nnz / max(nlabels * nlabels, 1)
    MAX_SPARSITY = 0.4
    if sparsity > MAX_SPARSITY:
        logger.debug(
            "find_group_cohorts: containment sparsity %.2f > %.2f -> map-reduce",
            sparsity, MAX_SPARSITY,
        )
        return "map-reduce", {}

    # greedy merge of labels with containment >= 0.75 (cohorts.py:256-290)
    THRESHOLD = 0.75
    bcoo = bitmask.tocoo()
    label_chunks: dict[int, set[int]] = {}
    for chunk, label in zip(bcoo.coords[0], bcoo.coords[1]):
        label_chunks.setdefault(int(label), set()).add(int(chunk))
    indptr, indices, data = containment.indptr, containment.indices, containment.data
    merged: dict[tuple[int, ...], list[int]] = {}
    assigned = np.full(nlabels, False)
    order = np.argsort(-chunks_per_label)  # widest labels first
    for lab in order:
        lab = int(lab)
        if not present[lab] or assigned[lab]:
            continue
        row_cols = indices[indptr[lab] : indptr[lab + 1]]
        row_vals = data[indptr[lab] : indptr[lab + 1]]
        members = [
            int(j)
            for j, v in zip(row_cols, row_vals)
            if v >= THRESHOLD and not assigned[j] and present[j]
        ]
        if lab not in members:
            members.append(lab)
        chunk_set: set[int] = set()
        for m in members:
            assigned[m] = True
            chunk_set.update(label_chunks[m])
        merged.setdefault(tuple(sorted(chunk_set)), []).extend(sorted(members))

    ncohorts = len(merged)
    if ncohorts == 1:
        logger.debug("find_group_cohorts: merged into one cohort -> map-reduce")
        return "map-reduce", {}
    logger.debug("find_group_cohorts: %d cohorts -> cohorts", ncohorts)
    return "cohorts", merged
