"""flox_tpu: TPU-native grouped reductions and scans.

A from-scratch framework with the capabilities of the reference flox library
(/root/reference/flox/__init__.py:25-36 defines the parity API surface),
built on JAX/XLA: device-resident group codes, jit-compiled segment-reduce
kernels, and shard_map/collective execution strategies over a TPU mesh.
"""

from . import autotune, cache, cohorts, faults, kernels, profiling, resilience, serve, telemetry, xrlite
from .aggregations import Aggregation, Scan, is_supported_aggregation
from .xarray import xarray_reduce
from .rechunk import rechunk_for_blockwise, rechunk_for_cohorts, reshard_for_blockwise
from .reindex import ReindexArrayType, ReindexStrategy
from .core import groupby_reduce
from .device import codes_device, groupby_reduce_device
from .fusion import FUSABLE_FUNCS, groupby_aggregate_many
from .scan import groupby_scan
from .streaming import (
    streaming_groupby_aggregate_many,
    streaming_groupby_reduce,
    streaming_groupby_scan,
)
from .dtypes import INF, NA, NINF
from .factorize import factorize_, factorize_single
from .multiarray import MultiArray
from .options import set_options

__all__ = [
    "Aggregation",
    "FUSABLE_FUNCS",
    "INF",
    "NA",
    "NINF",
    "MultiArray",
    "Scan",
    "autotune",
    "cache",
    "cohorts",
    "factorize_",
    "factorize_single",
    "faults",
    "codes_device",
    "groupby_aggregate_many",
    "groupby_reduce",
    "groupby_reduce_device",
    "groupby_scan",
    "is_supported_aggregation",
    "kernels",
    "profiling",
    "rechunk_for_blockwise",
    "rechunk_for_cohorts",
    "reshard_for_blockwise",
    "ReindexArrayType",
    "ReindexStrategy",
    "resilience",
    "serve",
    "set_options",
    "streaming_groupby_aggregate_many",
    "streaming_groupby_reduce",
    "streaming_groupby_scan",
    "telemetry",
    "xarray_reduce",
    "xrlite",
]

__version__ = "0.1.0"
