"""Live metrics exposition: a zero-dependency Prometheus endpoint.

The telemetry registry (PR 4/6) holds counters, gauges, and log-spaced
latency histograms — but until now they only left the process via file
export after the fact. A serving replica (``python -m flox_tpu.serve``)
needs an operator-scrapable surface instead; this module provides it with
nothing but the stdlib:

* :func:`prometheus_text` renders ``telemetry.METRICS`` in the Prometheus
  text exposition format (version 0.0.4): counters as ``*_total``, gauges
  plain, histograms with CUMULATIVE ``_bucket{le=...}`` series over the
  shared :data:`~flox_tpu.telemetry.HIST_EDGES_MS` edges plus ``_sum`` /
  ``_count``. Metric names are ``flox_tpu_`` + the registry name with
  non-identifier characters folded to ``_`` (``serve.request_ms`` ->
  ``flox_tpu_serve_request_ms``). A registry name carrying ``|key=value``
  suffixes renders as a LABELED series of the base metric
  (``serve.request_ms|tenant=acme`` -> ``flox_tpu_serve_request_ms
  {tenant="acme"}``) — the serve layer's per-tenant histograms ride this.
  Histogram buckets that remember an exemplar (the trace id of the max
  observation that landed there) emit it OpenMetrics-style after the
  sample: ``..._bucket{le="1.02"} 7 # {trace_id="req-42"} 0.91``.
* :class:`MetricsServer` / :func:`start_metrics_server`: a
  ``ThreadingHTTPServer`` on a daemon background thread serving
  ``/metrics``, ``/healthz`` (200 while the process lives), ``/readyz``
  (200 only after :func:`set_ready` — the serve loop flips it once the AOT
  warmup manifest has been replayed, so a load balancer never routes
  traffic to a replica still paying compiles), ``/debug/costs`` (the
  per-program / per-tenant cost ledger as JSON — what ``python -m
  flox_tpu.telemetry costs`` tabulates), ``/debug/datasets`` (the resident
  dataset registry: pinned entries, HBM budget, evictions, per-dataset
  cost ledger), ``/debug/profile?seconds=N`` (starts an on-demand
  on-chip capture; 409 while one runs, 501 on profiler-less backends),
  and ``/slo`` + ``/alerts`` (one ``flox_tpu.slo`` burn-rate evaluation
  as JSON — the scraper polling them IS the alert evaluator; what
  ``python -m flox_tpu.telemetry slo`` tabulates and the fleet federator
  unions). Starting the server seeds the saturation + resident-state
  gauges to 0, runs one SLO evaluation, and starts the opt-in saturation
  sampler (``OPTIONS["metrics_sample_interval"]``).

Embedded automatically by ``python -m flox_tpu.serve`` when
``OPTIONS["metrics_port"]`` (env ``FLOX_TPU_METRICS_PORT``) or
``--metrics-port`` is nonzero; standalone via
``python -m flox_tpu.telemetry serve-metrics``.
"""

from __future__ import annotations

import json
import re
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

__all__ = [
    "MetricsServer",
    "prometheus_text",
    "ready",
    "ready_reason",
    "set_ready",
    "start_metrics_server",
    "stop_metrics_server",
]

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _parse_top(params: dict) -> tuple[int | None, tuple[bytes, int] | None]:
    """Parse a ``?top=K`` query param shared by the ``/debug/*`` endpoints:
    ``(K, None)`` for a valid positive integer, ``(None, None)`` when
    absent, and ``(None, (body, 400))`` for anything malformed — one
    contract, one implementation, both endpoints."""
    top_raw = params.get("top", [None])[0]
    if top_raw is None:
        return None, None
    try:
        top = int(top_raw)
    except ValueError:
        top = -1
    if top < 1:
        body = (
            json.dumps(
                {"ok": False, "error": f"top must be a positive integer, got {top_raw!r}"}
            )
            + "\n"
        ).encode()
        return None, (body, 400)
    return top, None


def _top_rows(table: dict, top: int) -> dict:
    """The ``top`` most expensive ledger rows, ranked by device time then
    dispatch count — the same order the costs CLI prints."""
    ranked = sorted(
        table.items(),
        key=lambda kv: (
            -float(kv[1].get("device_ms", 0.0)),
            -int(kv[1].get("dispatches", 0)),
        ),
    )
    return dict(ranked[:top])

#: process-wide endpoint state: the live server (one per process — the
#: registry it exposes is process-wide too) and the readiness flag
_SERVER_STATE: dict[str, Any] = {"server": None, "ready": False, "reason": "warming"}
_STATE_LOCK = threading.Lock()


def set_ready(flag: bool = True, reason: str | None = None) -> None:
    """Flip the ``/readyz`` verdict. The serve loop calls this once its AOT
    warmup manifest has been replayed (immediately when there is nothing to
    replay); the drain path and device-loss recovery flip it back with a
    ``reason`` (``"draining"`` / ``"device-lost"``) that becomes the 503
    body, so a fleet router's probe log says WHY the replica left rotation."""
    with _STATE_LOCK:
        _SERVER_STATE["ready"] = bool(flag)
        _SERVER_STATE["reason"] = (
            "warming" if flag or reason is None else str(reason)
        )


def ready() -> bool:
    """Whether ``/readyz`` currently answers 200."""
    return bool(_SERVER_STATE["ready"])


def ready_reason() -> str:
    """The current 503 body for an unready replica (``"warming"`` at boot,
    ``"draining"`` during graceful shutdown, ``"device-lost"`` while the
    backend recovers)."""
    return str(_SERVER_STATE.get("reason") or "warming")


def _metric_name(name: str, suffix: str = "") -> str:
    return "flox_tpu_" + _NAME_BAD.sub("_", name) + suffix


def _escape_label(value: str) -> str:
    """A label value escaped per the exposition format (backslash, quote,
    newline) — shared by the ``|key=value`` labels and the exemplar trace
    ids, both of which can carry client-supplied strings."""
    return str(value).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _split_labels(name: str) -> tuple[str, str]:
    """Split a registry name into (base, rendered label pairs).

    Registry names may carry ``|key=value`` suffixes (the serve layer's
    ``serve.request_ms|tenant=acme``); each becomes a Prometheus label on
    the base metric."""
    base, sep, rest = name.partition("|")
    if not sep:
        return base, ""
    pairs = []
    for part in rest.split("|"):
        key, _, value = part.partition("=")
        pairs.append(f'{_NAME_BAD.sub("_", key)}="{_escape_label(value)}"')
    return base, ",".join(pairs)


def _fmt(value: float) -> str:
    value = float(value)
    if value.is_integer() and abs(value) < 2**63:
        return str(int(value))
    return repr(value)


def prometheus_text(exemplars: bool = True) -> str:
    """The telemetry registry in Prometheus text exposition format.

    Histogram buckets are cumulative (each ``le`` counts every observation
    at or below that edge), as the format requires — the registry stores
    per-bucket counts, so the walk accumulates. The final shared edge
    absorbs overflow in the registry, so ``le="+Inf"`` equals the total
    count by construction. ``|key=value`` registry-name suffixes become
    labels (one TYPE line per base metric, however many labeled series).

    With ``exemplars`` (the default for programmatic callers), bucket
    lines carrying an exemplar append it OpenMetrics-style after the
    sample value. The classic text format (version 0.0.4, what a default
    Prometheus scrape parses) does NOT allow exemplars — a scrape would
    abort on the first one — so the HTTP handler serves them only when the
    scraper asks (``/metrics?exemplars=1``), keeping the default scrape
    spec-clean.

    With ``OPTIONS["replica_id"]`` set, EVERY series additionally carries
    ``replica="<id>",host="<short hostname>"`` labels (merged ahead of any
    per-series ``|key=value`` labels) — the fleet-identity contract the
    ``python -m flox_tpu.fleet`` federator keys its merge on. Unset (the
    single-replica default), the output is byte-identical to before.
    """
    from .telemetry import HIST_EDGES_MS, METRICS, host_name, replica_id

    rid = replica_id()
    identity = (
        f'replica="{_escape_label(rid)}",host="{_escape_label(host_name())}"'
        if rid is not None
        else ""
    )

    def _merge(labels: str) -> str:
        return ",".join(part for part in (identity, labels) if part)

    lines: list[str] = []
    typed: set[str] = set()

    def _type_line(metric: str, kind: str) -> None:
        if metric not in typed:
            typed.add(metric)
            lines.append(f"# TYPE {metric} {kind}")

    for name, value in sorted(METRICS.counters().items()):
        base, labels = _split_labels(name)
        labels = _merge(labels)
        metric = _metric_name(base, "_total")
        _type_line(metric, "counter")
        label_str = f"{{{labels}}}" if labels else ""
        lines.append(f"{metric}{label_str} {_fmt(value)}")
    for name, value in sorted(METRICS.gauges().items()):
        base, labels = _split_labels(name)
        labels = _merge(labels)
        metric = _metric_name(base)
        _type_line(metric, "gauge")
        label_str = f"{{{labels}}}" if labels else ""
        lines.append(f"{metric}{label_str} {_fmt(value)}")
    for name, hist in sorted(METRICS.histograms().items()):
        base, labels = _split_labels(name)
        labels = _merge(labels)
        metric = _metric_name(base)
        _type_line(metric, "histogram")
        prefix = f"{labels}," if labels else ""
        suffix_labels = f"{{{labels}}}" if labels else ""
        slots = (hist.get("exemplars") or {}) if exemplars else {}
        cum = 0
        for i, (edge, n) in enumerate(zip(HIST_EDGES_MS, hist["counts"])):
            cum += n
            line = f'{metric}_bucket{{{prefix}le="{_fmt(edge)}"}} {cum}'
            slot = slots.get(i)
            if slot is not None:
                # OpenMetrics exemplar: the trace id of the max observation
                # that landed in THIS bucket — the p99 row names its
                # request. Escaped: trace ids are client-supplied strings.
                line += f' # {{trace_id="{_escape_label(slot[0])}"}} {_fmt(slot[1])}'
            lines.append(line)
        lines.append(f'{metric}_bucket{{{prefix}le="+Inf"}} {hist["count"]}')
        lines.append(f"{metric}_sum{suffix_labels} {_fmt(hist['sum'])}")
        lines.append(f"{metric}_count{suffix_labels} {hist['count']}")
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self) -> None:  # noqa: N802 — http.server's naming contract
        path, _, query = self.path.partition("?")
        if path == "/metrics":
            # count actual scrapes only — health/readiness probes arrive at
            # probe rate and would swamp the number otherwise
            from .telemetry import METRICS

            METRICS.inc("metrics.scrapes")
            # exemplars only on request: the classic 0.0.4 text parser (a
            # default Prometheus scrape) aborts the whole scrape on an
            # exemplar, so the plain endpoint must stay spec-clean
            params = urllib.parse.parse_qs(query)
            with_exemplars = params.get("exemplars", ["0"])[0] == "1"
            body = prometheus_text(exemplars=with_exemplars).encode()
            status, ctype = 200, "text/plain; version=0.0.4; charset=utf-8"
        elif path == "/healthz":
            body, status, ctype = b"ok\n", 200, "text/plain; charset=utf-8"
        elif path == "/readyz":
            if ready():
                body, status = b"ready\n", 200
            else:
                # the reason IS the payload: "warming" at boot, "draining"
                # during graceful shutdown, "device-lost" mid-recovery
                body, status = ready_reason().encode() + b"\n", 503
            ctype = "text/plain; charset=utf-8"
        elif path == "/debug/costs":
            body, status = self._costs(query)
            ctype = "application/json; charset=utf-8"
        elif path == "/debug/programs":
            body, status = self._programs(query)
            ctype = "application/json; charset=utf-8"
        elif path == "/debug/datasets":
            body, status = self._datasets(query)
            ctype = "application/json; charset=utf-8"
        elif path == "/debug/stores":
            body, status = self._stores(query)
            ctype = "application/json; charset=utf-8"
        elif path == "/debug/profile":
            body, status = self._profile(query)
            ctype = "application/json; charset=utf-8"
        elif path == "/slo":
            body, status = self._slo()
            ctype = "application/json; charset=utf-8"
        elif path == "/alerts":
            body, status = self._alerts()
            ctype = "application/json; charset=utf-8"
        else:
            body, status, ctype = b"not found\n", 404, "text/plain; charset=utf-8"
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    @staticmethod
    def _costs(query: str = "") -> tuple[bytes, int]:
        """The cost ledger as JSON — the machine-readable face of
        ``cache.stats()["cost_by_program"]`` (``python -m flox_tpu.telemetry
        costs <scrape>`` tabulates exactly this payload).

        ``?tenant=<label>`` narrows the tenant axis to that (sanitized)
        label; ``?top=K`` keeps only the K most expensive rows per axis,
        ranked exactly as the costs CLI ranks them (device time, then
        dispatches) — so a fleet scrape of 40 replicas does not have to
        ship every cold row just to build a top-10 table. A malformed
        ``top`` is a 400, never a silent full dump."""
        from . import telemetry

        params = urllib.parse.parse_qs(query)
        top, error = _parse_top(params)
        if error is not None:
            return error
        tenant = params.get("tenant", [None])[0]
        programs = telemetry.cost_by_program()
        tenants = telemetry.cost_by_tenant()
        if tenant is not None:
            # sanitize-only (register=False): a GET filter for a tenant
            # nobody ever billed must not burn a cardinality slot
            wanted = telemetry.tenant_label(tenant, register=False)
            tenants = {k: v for k, v in tenants.items() if k == wanted}
        if top is not None:
            programs = _top_rows(programs, top)
            tenants = _top_rows(tenants, top)
        payload = {
            "cost_by_program": programs,
            "cost_by_tenant": tenants,
            "hbm_by_program": {
                k: v for k, v in telemetry.hbm_by_program().items() if k in programs
            },
            "replica": telemetry.replica_instance(),
            "host": telemetry.host_name(),
        }
        return (json.dumps(payload, default=str) + "\n").encode(), 200

    @staticmethod
    def _programs(query: str = "") -> tuple[bytes, int]:
        """The compiled-program card table joined with the observed cost
        ledger, as JSON — the machine-readable face of
        ``costmodel.program_report()`` (``python -m flox_tpu.telemetry
        programs <scrape>`` tabulates exactly this payload).

        ``?top=K`` keeps the K rows with the most observed device time
        (malformed = 400, same contract as ``/debug/costs``);
        ``?program=<substr>`` narrows to labels containing the substring."""
        from . import costmodel, telemetry

        params = urllib.parse.parse_qs(query)
        top, error = _parse_top(params)
        if error is not None:
            return error
        program = params.get("program", [None])[0]
        payload = costmodel.program_report(top=top, program=program)
        payload["replica"] = telemetry.replica_instance()
        payload["host"] = telemetry.host_name()
        return (json.dumps(payload, default=str) + "\n").encode(), 200

    @staticmethod
    def _datasets(query: str = "") -> tuple[bytes, int]:
        """The resident-dataset registry as JSON: every pinned entry (bytes,
        pins, hits, selector-view count), the HBM budget verdict, eviction
        count, and the per-dataset cost ledger — the operator's answer to
        "what is holding device memory and is it earning its keep".

        ``?top=K`` keeps the K most-hit entries (malformed = 400, same
        contract as the other ``/debug/*`` endpoints)."""
        from . import telemetry
        from .serve import registry

        params = urllib.parse.parse_qs(query)
        top, error = _parse_top(params)
        if error is not None:
            return error
        payload = registry.debug_table(top=top)
        payload["replica"] = telemetry.replica_instance()
        payload["host"] = telemetry.host_name()
        return (json.dumps(payload, default=str) + "\n").encode(), 200

    @staticmethod
    def _stores(query: str = "") -> tuple[bytes, int]:
        """The durable aggregation stores as JSON: every open store's
        generation, ingested-slab count, present-group count, segment count
        and state bytes, plus the per-store cost-ledger join — the
        operator's answer to "what incremental state does this replica
        carry and how far has it advanced".

        ``?top=K`` keeps the K highest-generation stores (malformed = 400,
        same contract as the other ``/debug/*`` endpoints)."""
        from . import telemetry
        from .serve import stores

        params = urllib.parse.parse_qs(query)
        top, error = _parse_top(params)
        if error is not None:
            return error
        payload = stores.debug_table(top=top)
        payload["replica"] = telemetry.replica_instance()
        payload["host"] = telemetry.host_name()
        return (json.dumps(payload, default=str) + "\n").encode(), 200

    @staticmethod
    def _slo() -> tuple[bytes, int]:
        """One SLO evaluation as JSON: per-objective burn rates against
        every window rule, budget remaining, and the live alert rows —
        the machine-readable face of ``slo.evaluate()`` (``python -m
        flox_tpu.telemetry slo <scrape>`` tabulates exactly this payload,
        and the fleet federator unions it across replicas).

        Evaluating ON scrape keeps the endpoint and the state machine in
        lockstep: a scraper polling /slo IS the alert evaluator, no extra
        daemon required. An invalid configured spec is this endpoint's
        500 — loudly, per the no-silent-fallback contract."""
        from . import slo, telemetry

        try:
            payload = slo.evaluate()
        except ValueError as exc:
            return (json.dumps({"error": str(exc)}) + "\n").encode(), 500
        payload["replica"] = telemetry.replica_instance()
        payload["host"] = telemetry.host_name()
        return (json.dumps(payload, default=str) + "\n").encode(), 200

    @staticmethod
    def _alerts() -> tuple[bytes, int]:
        """The alert state machine's rows as JSON (evaluated fresh, same
        contract as ``/slo`` — a firing alert must not need a second
        scrape to appear)."""
        from . import slo, telemetry

        try:
            payload = slo.evaluate()
        except ValueError as exc:
            return (json.dumps({"error": str(exc)}) + "\n").encode(), 500
        body = {
            "alerts": payload["alerts"],
            "healthy": payload["healthy"],
            "evaluated_at": payload["evaluated_at"],
            "replica": telemetry.replica_instance(),
            "host": telemetry.host_name(),
        }
        return (json.dumps(body, default=str) + "\n").encode(), 200

    @staticmethod
    def _profile(query: str) -> tuple[bytes, int]:
        """Start an on-demand on-chip capture (``?seconds=N``, default 5).

        202 with the capture dir on success (the stop runs on a timer
        thread — the reply never blocks behind the window), 409 while a
        capture is already running, 501 when the backend has no profiler
        or no capture root is configured, 400 for a bad window. Never
        raises into the serve loop."""
        from . import profiling

        try:
            params = urllib.parse.parse_qs(query)
            seconds = float(params.get("seconds", ["5"])[0])
            capture_dir = profiling.start_capture(seconds=seconds)
        except profiling.CaptureBusyError as exc:
            return (json.dumps({"ok": False, "error": str(exc)}) + "\n").encode(), 409
        except profiling.CaptureUnavailableError as exc:
            return (json.dumps({"ok": False, "error": str(exc)}) + "\n").encode(), 501
        except (ValueError, TypeError) as exc:
            return (json.dumps({"ok": False, "error": str(exc)}) + "\n").encode(), 400
        except Exception as exc:  # noqa: BLE001 — observability never kills serving
            return (json.dumps({"ok": False, "error": str(exc)}) + "\n").encode(), 500
        payload = {"ok": True, "dir": capture_dir, "seconds": seconds}
        return (json.dumps(payload) + "\n").encode(), 202

    def log_message(self, format: str, *args: Any) -> None:
        # a probe every few seconds must not spam stderr; scrape counts
        # are visible in the registry itself (metrics.scrapes)
        pass


class MetricsServer:
    """The background exposition endpoint: a ``ThreadingHTTPServer`` on a
    daemon thread. ``port=0`` binds an ephemeral port; :attr:`port` is the
    bound one either way."""

    def __init__(self, port: int, host: str = "127.0.0.1") -> None:
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="flox-tpu-metrics",
            daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def start_metrics_server(port: int | None = None, host: str = "127.0.0.1") -> int | None:
    """Start (or reuse) the process-wide exposition endpoint.

    ``port=None`` reads ``OPTIONS["metrics_port"]`` — 0 there means the
    endpoint is off and this returns ``None``. An explicit ``port``
    argument always starts one (0 = ephemeral). Returns the bound port;
    idempotent while a server is already running (the registry is
    process-wide, so one endpoint is the right number of endpoints).
    """
    if port is None:
        from .options import OPTIONS

        port = OPTIONS["metrics_port"]
        if not port:
            return None
    from . import telemetry

    with _STATE_LOCK:
        server = _SERVER_STATE["server"]
        if server is None:
            server = MetricsServer(int(port), host=host)
            _SERVER_STATE["server"] = server
    # a freshly booted replica must EXPOSE the saturation series before its
    # first request — an absent gauge reads as a broken scrape, a zero
    # reads as idle. Idempotent (live values are never rewound), and the
    # opt-in sampler (OPTIONS["metrics_sample_interval"]) starts with the
    # endpoint it feeds.
    telemetry.seed_saturation_gauges()
    # the HBM capacity denominator is static per backend: publish it once
    # at endpoint start so utilization math never reads an absent gauge
    telemetry.seed_hbm_limit()
    telemetry.start_saturation_sampler()
    # resident-state gauges (registry occupancy, store staleness) + one
    # SLO evaluation seed with the endpoint too: freshness SLOs need a
    # signal on an idle replica, and /slo + the budget gauges must answer
    # from the very first scrape
    telemetry.sample_resident_state()
    from . import slo

    slo.seed_gauges()
    return server.port


def stop_metrics_server() -> None:
    """Shut the endpoint down (tests; the serve loop just exits — the
    thread is a daemon). Readiness and the saturation sampler reset with
    it."""
    from . import telemetry

    telemetry.stop_saturation_sampler()
    with _STATE_LOCK:
        server = _SERVER_STATE.pop("server", None)
        _SERVER_STATE["server"] = None
        _SERVER_STATE["ready"] = False
        _SERVER_STATE["reason"] = "warming"
    if server is not None:
        server.close()
