"""Live metrics exposition: a zero-dependency Prometheus endpoint.

The telemetry registry (PR 4/6) holds counters, gauges, and log-spaced
latency histograms — but until now they only left the process via file
export after the fact. A serving replica (``python -m flox_tpu.serve``)
needs an operator-scrapable surface instead; this module provides it with
nothing but the stdlib:

* :func:`prometheus_text` renders ``telemetry.METRICS`` in the Prometheus
  text exposition format (version 0.0.4): counters as ``*_total``, gauges
  plain, histograms with CUMULATIVE ``_bucket{le=...}`` series over the
  shared :data:`~flox_tpu.telemetry.HIST_EDGES_MS` edges plus ``_sum`` /
  ``_count``. Metric names are ``flox_tpu_`` + the registry name with
  non-identifier characters folded to ``_`` (``serve.request_ms`` ->
  ``flox_tpu_serve_request_ms``).
* :class:`MetricsServer` / :func:`start_metrics_server`: a
  ``ThreadingHTTPServer`` on a daemon background thread serving
  ``/metrics``, ``/healthz`` (200 while the process lives), and
  ``/readyz`` (200 only after :func:`set_ready` — the serve loop flips it
  once the AOT warmup manifest has been replayed, so a load balancer never
  routes traffic to a replica still paying compiles).

Embedded automatically by ``python -m flox_tpu.serve`` when
``OPTIONS["metrics_port"]`` (env ``FLOX_TPU_METRICS_PORT``) or
``--metrics-port`` is nonzero; standalone via
``python -m flox_tpu.telemetry serve-metrics``.
"""

from __future__ import annotations

import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

__all__ = [
    "MetricsServer",
    "prometheus_text",
    "ready",
    "set_ready",
    "start_metrics_server",
    "stop_metrics_server",
]

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")

#: process-wide endpoint state: the live server (one per process — the
#: registry it exposes is process-wide too) and the readiness flag
_SERVER_STATE: dict[str, Any] = {"server": None, "ready": False}
_STATE_LOCK = threading.Lock()


def set_ready(flag: bool = True) -> None:
    """Flip the ``/readyz`` verdict. The serve loop calls this once its AOT
    warmup manifest has been replayed (immediately when there is nothing to
    replay); tests and drains may flip it back."""
    _SERVER_STATE["ready"] = bool(flag)


def ready() -> bool:
    """Whether ``/readyz`` currently answers 200."""
    return bool(_SERVER_STATE["ready"])


def _metric_name(name: str, suffix: str = "") -> str:
    return "flox_tpu_" + _NAME_BAD.sub("_", name) + suffix


def _fmt(value: float) -> str:
    value = float(value)
    if value.is_integer() and abs(value) < 2**63:
        return str(int(value))
    return repr(value)


def prometheus_text() -> str:
    """The telemetry registry in Prometheus text exposition format.

    Histogram buckets are cumulative (each ``le`` counts every observation
    at or below that edge), as the format requires — the registry stores
    per-bucket counts, so the walk accumulates. The final shared edge
    absorbs overflow in the registry, so ``le="+Inf"`` equals the total
    count by construction.
    """
    from .telemetry import HIST_EDGES_MS, METRICS

    lines: list[str] = []
    for name, value in sorted(METRICS.counters().items()):
        metric = _metric_name(name, "_total")
        lines += [f"# TYPE {metric} counter", f"{metric} {_fmt(value)}"]
    for name, value in sorted(METRICS.gauges().items()):
        metric = _metric_name(name)
        lines += [f"# TYPE {metric} gauge", f"{metric} {_fmt(value)}"]
    for name, hist in sorted(METRICS.histograms().items()):
        metric = _metric_name(name)
        lines.append(f"# TYPE {metric} histogram")
        cum = 0
        for edge, n in zip(HIST_EDGES_MS, hist["counts"]):
            cum += n
            lines.append(f'{metric}_bucket{{le="{_fmt(edge)}"}} {cum}')
        lines.append(f'{metric}_bucket{{le="+Inf"}} {hist["count"]}')
        lines.append(f"{metric}_sum {_fmt(hist['sum'])}")
        lines.append(f"{metric}_count {hist['count']}")
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self) -> None:  # noqa: N802 — http.server's naming contract
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            # count actual scrapes only — health/readiness probes arrive at
            # probe rate and would swamp the number otherwise
            from .telemetry import METRICS

            METRICS.inc("metrics.scrapes")
            body = prometheus_text().encode()
            status, ctype = 200, "text/plain; version=0.0.4; charset=utf-8"
        elif path == "/healthz":
            body, status, ctype = b"ok\n", 200, "text/plain; charset=utf-8"
        elif path == "/readyz":
            if ready():
                body, status = b"ready\n", 200
            else:
                body, status = b"warming\n", 503
            ctype = "text/plain; charset=utf-8"
        else:
            body, status, ctype = b"not found\n", 404, "text/plain; charset=utf-8"
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, format: str, *args: Any) -> None:
        # a probe every few seconds must not spam stderr; scrape counts
        # are visible in the registry itself (metrics.scrapes)
        pass


class MetricsServer:
    """The background exposition endpoint: a ``ThreadingHTTPServer`` on a
    daemon thread. ``port=0`` binds an ephemeral port; :attr:`port` is the
    bound one either way."""

    def __init__(self, port: int, host: str = "127.0.0.1") -> None:
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="flox-tpu-metrics",
            daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def start_metrics_server(port: int | None = None, host: str = "127.0.0.1") -> int | None:
    """Start (or reuse) the process-wide exposition endpoint.

    ``port=None`` reads ``OPTIONS["metrics_port"]`` — 0 there means the
    endpoint is off and this returns ``None``. An explicit ``port``
    argument always starts one (0 = ephemeral). Returns the bound port;
    idempotent while a server is already running (the registry is
    process-wide, so one endpoint is the right number of endpoints).
    """
    if port is None:
        from .options import OPTIONS

        port = OPTIONS["metrics_port"]
        if not port:
            return None
    with _STATE_LOCK:
        server = _SERVER_STATE["server"]
        if server is not None:
            return server.port
        server = MetricsServer(int(port), host=host)
        _SERVER_STATE["server"] = server
        return server.port


def stop_metrics_server() -> None:
    """Shut the endpoint down (tests; the serve loop just exits — the
    thread is a daemon). Readiness resets with it."""
    with _STATE_LOCK:
        server = _SERVER_STATE.pop("server", None)
        _SERVER_STATE["server"] = None
        _SERVER_STATE["ready"] = False
    if server is not None:
        server.close()
