"""Core orchestration: ``groupby_reduce`` and the chunk-level reducer (L4).

Parity target: /root/reference/flox/core.py — ``groupby_reduce``
(core.py:739-1222), ``chunk_reduce`` (214-394), ``_finalize_results``
(410-475), ``_reduce_blockwise`` (478-524), plus the argreduction chunk
wrapper (157-211).

TPU-first architecture:

* The hot path, ``chunk_reduce``, traces ALL requested kernels into one
  ``jax.jit`` program (cached per static signature), so XLA fuses the shared
  scatter work — mean's sum+count are one pass, exactly the fusion the
  reference gets by hand-deduplicating ``nanlen`` (core.py:348-391).
* Group codes are computed host-side by pandas when labels are unknown
  (data-dependent → host, as the reference keeps them) and can stay fully
  on-device when ``expected_groups`` is known (factorize.factorize_device).
* The eager path below IS the single-chip program; the distributed methods
  (map-reduce / blockwise / cohorts over a mesh) build on the same
  ``chunk_reduce`` inside ``shard_map`` (see parallel/).
"""

from __future__ import annotations

import functools
import logging
from typing import Any, Callable, Sequence

import numpy as np
import pandas as pd

from . import dtypes, factorize as fct, telemetry, utils
from .aggregations import Aggregation, _initialize_aggregation, generic_aggregate, normalize_engine
from .options import OPTIONS

logger = logging.getLogger("flox_tpu.core")

__all__ = ["groupby_reduce", "chunk_reduce"]

_NAT_INT = np.iinfo(np.int64).min  # NaT viewed as int64


# ---------------------------------------------------------------------------
# argument normalization
# ---------------------------------------------------------------------------


def _assert_by_is_aligned(shape: tuple[int, ...], bys: Sequence[np.ndarray]) -> None:
    """All ``by`` arrays must match the trailing dims of ``array``
    (parity: core.py:589-607)."""
    for b in bys:
        if b.ndim > len(shape) or shape[-b.ndim :] != b.shape:
            raise ValueError(
                f"`by` has shape {b.shape} which does not align with the trailing "
                f"dimensions of `array` with shape {shape}."
            )


def _convert_expected_groups_to_index(
    expected, isbin: Sequence[bool], sort: bool
) -> tuple[pd.Index | None, ...]:
    """Normalize user expected_groups to pandas Indexes
    (parity: core.py:616-682)."""
    out = []
    for exp, bin_ in zip(expected, isbin):
        if exp is None:
            out.append(None)
        elif isinstance(exp, pd.IntervalIndex):
            out.append(exp)
        elif isinstance(exp, pd.Index) and not bin_:
            out.append(exp)
        elif bin_:
            out.append(pd.IntervalIndex.from_breaks(np.asarray(exp)))
        else:
            values = utils.asarray_host(np.asarray(exp))
            if sort:
                values = np.sort(values)
            out.append(pd.Index(values))
    return tuple(out)


def _normalize_expected(expected, nby: int):
    if expected is None:
        return (None,) * nby
    if nby == 1 and not isinstance(expected, tuple):
        return (expected,)
    if not isinstance(expected, tuple):
        raise ValueError("With multiple `by`, `expected_groups` must be a tuple.")
    if len(expected) != nby:
        raise ValueError(
            f"Must have one expected_groups entry per `by` ({nby}); got {len(expected)}."
        )
    return expected


def _normalize_isbin(isbin, nby: int) -> tuple[bool, ...]:
    if isinstance(isbin, bool):
        return (isbin,) * nby
    return tuple(isbin)


# ---------------------------------------------------------------------------
# chunk_reduce: the hot kernel bundle
# ---------------------------------------------------------------------------


def _norm_chunk_entry(entry) -> tuple[str | Callable, dict]:
    if isinstance(entry, tuple):
        return entry[0], dict(entry[1])
    return entry, {}


@functools.lru_cache(maxsize=512)
def _jitted_bundle(funcs_key, size: int, engine: str, opts_key: tuple = ()):
    """Build & cache one jitted program running all kernels of a reduction.

    ``funcs_key`` is a hashable encoding of (func, fill_value, dtype-str,
    extra-kwargs) per kernel. jit caching is on this key + jax's own shape
    tracing.
    """
    import jax

    # body runs only on an lru_cache miss: a fresh jit program is built (it
    # still traces/compiles per input shape — jax.compiles counts those)
    telemetry.count("cache.bundle_builds")

    specs = funcs_key

    def run(codes, array):
        outs = []
        for func, fv, dt, kw in specs:
            outs.append(
                generic_aggregate(
                    codes,
                    array,
                    engine="jax",
                    func=func,
                    size=size,
                    fill_value=np.nan if isinstance(fv, str) and fv == "__nan__" else fv,
                    dtype=np.dtype(dt) if dt is not None else None,
                    **dict(kw),
                )
            )
        return tuple(outs)

    return jax.jit(run)


def chunk_reduce(
    array,
    codes,
    *,
    funcs: Sequence[str | Callable | tuple],
    size: int,
    fill_values: Sequence[Any],
    dtypes_: Sequence[Any],
    engine: str,
    kwargss: Sequence[dict] | None = None,
    jit: bool = True,
    prog_family: str = "bundle",
):
    """Run a bundle of grouped reductions over the trailing axis.

    ``array``: (..., N); ``codes``: (N,) int with -1 missing. Returns a list
    of per-func results, each (..., size) (parity: core.py:214-394 minus the
    re-factorization, which happens once in groupby_reduce here).

    Repeated (func, kwargs) entries are computed once and fanned out
    (parity: the nanlen dedup at core.py:352).

    ``prog_family`` names the cost-ledger / program-card label family
    (``bundle[...]`` for the dense eager path, ``sort[...]`` when the
    present-groups engine dispatches this bundle over the compact domain),
    so ``/debug/programs`` utilization and the drift sentinel can tell the
    two apart.
    """
    if kwargss is None:
        kwargss = [{}] * len(funcs)

    # dedup identical kernel invocations
    seen: dict[tuple, int] = {}
    plan: list[tuple] = []
    positions: list[int] = []
    for func, fv, dt, kw in zip(funcs, fill_values, dtypes_, kwargss):
        func_n, extra = _norm_chunk_entry(func)
        merged = {k: (tuple(v) if isinstance(v, list) else v) for k, v in {**extra, **kw}.items()}
        key = (
            func_n if isinstance(func_n, str) else id(func_n),
            None if fv is None else (repr(fv)),
            # .name, not .str: extension dtypes (bfloat16) stringify to
            # '|V2' via .str, which round-trips to a void dtype
            None if dt is None else np.dtype(dt).name,
            tuple(sorted(merged.items())),
        )
        if key in seen:
            positions.append(seen[key])
        else:
            seen[key] = len(plan)
            positions.append(len(plan))
            plan.append((func_n, fv, dt, merged))

    if engine == "jax" and jit and all(isinstance(p[0], str) for p in plan):
        funcs_key = tuple(
            (f, _hashable_fill(fv), None if dt is None else np.dtype(dt).name, tuple(sorted(kw.items())))
            for f, fv, dt, kw in plan
        )
        from .options import trace_fingerprint

        telemetry.count("cache.bundle_calls")
        bundle = _jitted_bundle(funcs_key, size, engine, trace_fingerprint())
        tm_on = telemetry.enabled()
        if tm_on:
            # cost-ledger baseline: dispatch wall + the jax-compile delta
            # this bundle call provokes, attributed per program key below.
            # All of it gated so the disabled hot path reads no clock and
            # builds no label.
            from time import perf_counter

            compiles0 = telemetry.METRICS.get("jax.compiles")
            compile_ms0 = telemetry.METRICS.get("jax.compile_ms")
            t_dispatch0 = perf_counter()
        if tm_on:
            prog = prog_family + "[" + "+".join(str(p[0]) for p in plan) + "]"
            # deterministic drift-injection hook (faults.dispatch_delay):
            # the sentinel tests delay THIS dispatch so the observed wall
            # honestly diverges from the analytical model
            from . import faults

            if faults.dispatch_delay_active():
                faults.dispatch_delay_poke(prog)
        with telemetry.span(
            "dispatch", engine=engine, nkernels=len(plan), size=size,
            funcs=[p[0] for p in plan if isinstance(p[0], str)],
        ):
            # staging stays INSIDE the span: the dispatch span has always
            # covered transfer + execute, and the trace view must not
            # silently shrink; the device refs are kept for the card site
            codes_d = utils.asarray_device(codes)
            array_d = utils.asarray_device(array)
            results = bundle(codes_d, array_d)
        if tm_on:
            # observed wall snapshotted BEFORE the card analysis below: its
            # lower+compile is bookkeeping, and billing it as device time
            # would read as drift on the very first dispatch
            dispatch_ms = (perf_counter() - t_dispatch0) * 1e3
            # HBM pressure right after the device dispatch, attributed to
            # this kernel bundle (cache.stats()["hbm_by_program"]); no-op
            # off-device, and the label join costs nothing when off
            telemetry.sample_hbm(program=prog)
            # the program's analytical card (costmodel plane, opt-in): one
            # lower+compile per (label, shape signature), memoized — the
            # roofline join behind program.utilization/predicted_ms
            from . import costmodel

            costmodel.ensure_card(prog, bundle, (codes_d, array_d))
            telemetry.observe_cost(
                prog,
                device_ms=dispatch_ms,
                nbytes=int(getattr(array, "nbytes", 0))
                + int(getattr(codes, "nbytes", 0)),
                compiles=int(telemetry.METRICS.get("jax.compiles") - compiles0),
                compile_ms=telemetry.METRICS.get("jax.compile_ms") - compile_ms0,
            )
    else:
        with telemetry.span(
            "dispatch", engine=engine, nkernels=len(plan), size=size,
        ):
            results = [
                generic_aggregate(
                    codes,
                    array,
                    engine=engine,
                    func=f,
                    size=size,
                    fill_value=fv,
                    dtype=dt,
                    **kw,
                )
                for f, fv, dt, kw in plan
            ]
    return [results[i] for i in positions]


def _hashable_fill(fv):
    if fv is None:
        return None
    try:
        if np.ndim(fv) == 0 and np.isnan(fv):
            return "__nan__"  # nan != nan would defeat the lru_cache
    except (TypeError, ValueError):
        pass
    if isinstance(fv, (bool, int, float, complex, str)):
        return fv
    return float(fv) if np.ndim(fv) == 0 else repr(fv)


# ---------------------------------------------------------------------------
# groupby_reduce
# ---------------------------------------------------------------------------


def _normalize_reduce_axes(arr, bys, axis):
    """Move the reduced by-dims to the trailing position (the flatten
    contract shared by groupby_reduce, groupby_scan and the streaming
    runtime — parity: reference core.py:957-1018).

    Returns ``(arr, bys, n_keep, bndim)``: the (possibly transposed) array
    and labels, the count of kept (non-reduced) by-dims now leading the
    by-span, and the by-span rank after any broadcast. ``axis`` entries
    below the by-span broadcast the labels over those dims first.
    """
    bndim = bys[0].ndim
    if axis is None:
        axes = tuple(range(arr.ndim - bndim, arr.ndim))
    else:
        axes = utils.normalize_axis_tuple(axis, arr.ndim)
    first_by_ax = arr.ndim - bndim
    if any(ax < first_by_ax for ax in axes):
        # reducing over dims the labels don't cover: broadcast labels over them
        new_bndim = arr.ndim - min(axes)
        target_shape = arr.shape[-new_bndim:]
        bys = [np.broadcast_to(b, target_shape) for b in bys]
        bndim = new_bndim
        first_by_ax = arr.ndim - bndim

    rel_axes = tuple(ax - first_by_ax for ax in axes)  # axes within by dims
    # transpose the by-dims block so reduced dims are trailing
    by_keep = [d for d in range(bndim) if d not in rel_axes]
    by_order = by_keep + list(rel_axes)
    if by_order != list(range(bndim)):
        bys = [b.transpose(by_order) for b in bys]
        arr_order = list(range(first_by_ax)) + [first_by_ax + d for d in by_order]
        arr = arr.transpose(arr_order)
    return arr, bys, len(by_keep), bndim


def _choose_engine(engine, array, array_is_jax: bool) -> str:
    """Default engine choice (parity: _choose_engine, core.py:712-736).

    The jit path wins for device arrays and anything sizeable; small host
    arrays skip jit dispatch overhead via the numpy engine — but only when
    both engines produce the same result dtype (x64 on), so the choice is
    invisible to the caller. The size crossover is
    ``OPTIONS["numpy_engine_max_elems"]`` (measured round 5, CPU host,
    nanmean, 10 groups, median of 20: numpy/jax ms = 0.15/0.60 @512,
    0.19/0.64 @2048, 0.93/1.93 @32768, 8.4/6.3 @131072 — crossover
    ~64-100k; 32768 is the last measured point where numpy wins 2x, and
    device dispatch only pushes the crossover higher on an accelerator).
    With the autotuner on, a measured "engine" record for the size band
    overrides the threshold — both engines are x64-equivalent here, so the
    swap stays invisible to the caller.
    """
    if engine is not None:
        return normalize_engine(engine)
    if not array_is_jax and utils.x64_enabled():
        arr = np.asarray(array)
        nelems = int(arr.size)
        heuristic = (
            "numpy"
            if nelems < OPTIONS["numpy_engine_max_elems"]
            else OPTIONS["default_engine"]
        )
        # consult the tuner only when the fallback is the jax engine — a
        # default_engine="numpy" session forced the host engine and the
        # tuner must not second-guess that
        if OPTIONS["autotune"] and OPTIONS["default_engine"] == "jax":
            from . import autotune

            dt = arr.dtype
            autotune.prime_engine(dt, nelems)
            chosen = autotune.decide(
                "engine", heuristic, ("numpy", "jax"),
                dtype=str(dt), nelems=nelems,
            )
            if chosen != heuristic:
                logger.debug("engine autotune: %s (heuristic %s)", chosen, heuristic)
            return chosen
        if heuristic == "numpy":
            logger.debug("engine heuristic: small host array -> numpy")
        return heuristic
    return OPTIONS["default_engine"]


_NON_NUMERIC_FUNCS = ("first", "last", "nanfirst", "nanlast", "count")


def _reduce_non_numeric(arr, bys, func: str, *, fill_value, **passthrough):
    """first/last/count on string/object arrays (reference: its numpy
    engines take any dtype, tests/strategies.py unicode data).

    Non-numeric values cannot live on device, but their *positions* can:
    reduce a float64 global-position proxy through the normal machinery
    (so every engine/method/mesh works unchanged), then gather host-side.
    Positions are exact to 2**53 elements with x64, 2**24 without (the jax
    engine computes in f32 then) — the caller guards the latter.
    """
    valid = ~pd.isna(arr)
    if func == "count":
        proxy = np.where(valid, 1.0, np.nan)
        return groupby_reduce(proxy, *bys, func="count", fill_value=fill_value, **passthrough)

    pos = np.arange(arr.size, dtype=np.float64).reshape(arr.shape)
    skipna = func.startswith("nan")
    proxy = np.where(valid, pos, np.nan) if skipna else pos
    minmax = "nanmin" if "first" in func else "nanmax"
    posr, *groups = groupby_reduce(proxy, *bys, func=minmax, **passthrough)
    posr = np.asarray(posr)
    empty = ~np.isfinite(posr)
    idx = np.where(empty, 0, posr).astype(np.int64)
    out = arr.reshape(-1)[idx]
    if empty.any():
        fill = fill_value  # None is a fine missing marker for objects
        if out.dtype.kind in "SU":
            out = out.astype(object)
        out[empty] = fill
    return (out, *groups)


def groupby_reduce(
    array: Any,
    *by: Any,
    func: str | Aggregation,
    expected_groups: Any = None,
    sort: bool = True,
    isbin: bool | Sequence[bool] = False,
    axis: int | Sequence[int] | None = None,
    fill_value: Any = None,
    dtype: Any = None,
    min_count: int | None = None,
    method: str | None = None,
    engine: str | None = None,
    reindex: Any = None,
    finalize_kwargs: dict | None = None,
    mesh: Any = None,
    axis_name: str = "data",
) -> tuple:
    """GroupBy reduction (parity: core.py:739-1222; same signature contract).

    Returns ``(result, *groups)`` where ``result`` has the reduced axes
    replaced by one axis per grouper (plus any new dims, e.g. quantile's q).

    ``method=None`` runs the fused eager path on one device. Passing
    ``method`` ("map-reduce" | "cohorts" | "blockwise") runs the reduction
    as one SPMD program over ``mesh`` (default: a 1-D mesh over all
    devices), sharding the reduced axis and combining with collectives —
    the TPU analogue of the reference's dask execution methods (core.py:89).

    Examples
    --------
    >>> import numpy as np
    >>> from flox_tpu import groupby_reduce
    >>> values = np.array([1.0, 2.0, 4.0, 8.0])
    >>> labels = np.array([0, 0, 1, 1])
    >>> result, groups = groupby_reduce(values, labels, func="sum", engine="numpy")
    >>> result
    array([ 3., 12.])
    >>> groups
    array([0, 1])

    Binning, and a group with no members filled per the aggregation:

    >>> result, bins = groupby_reduce(
    ...     values, values, func="count", engine="numpy",
    ...     expected_groups=np.array([0.0, 3.0, 6.0, 9.0]), isbin=True,
    ... )
    >>> result
    array([2, 1, 1])
    """
    with telemetry.span(
        "groupby_reduce",
        func=func if isinstance(func, str) else getattr(func, "name", "custom"),
        method=method,
    ):
        return _groupby_reduce_impl(
            array, *by, func=func, expected_groups=expected_groups, sort=sort,
            isbin=isbin, axis=axis, fill_value=fill_value, dtype=dtype,
            min_count=min_count, method=method, engine=engine, reindex=reindex,
            finalize_kwargs=finalize_kwargs, mesh=mesh, axis_name=axis_name,
        )


def _groupby_reduce_impl(
    array: Any,
    *by: Any,
    func: str | Aggregation,
    expected_groups: Any,
    sort: bool,
    isbin: bool | Sequence[bool],
    axis: int | Sequence[int] | None,
    fill_value: Any,
    dtype: Any,
    min_count: int | None,
    method: str | None,
    engine: str | None,
    reindex: Any,
    finalize_kwargs: dict | None,
    mesh: Any,
    axis_name: str,
) -> tuple:
    """The :func:`groupby_reduce` body, under the public wrapper's root
    telemetry span (the wrapper exists so the span covers every early
    dispatch — sparse, non-numeric — without touching their returns).
    Defaults live ONLY on the public wrapper, which forwards every
    argument — no defaults here, so signature drift fails loudly."""
    if not by:
        raise TypeError("Must pass at least one `by`")
    if method not in (None, "map-reduce", "blockwise", "cohorts"):
        raise ValueError(
            f"method must be one of None, 'map-reduce', 'blockwise', 'cohorts'; got {method!r}"
        )
    # -- reindex mapping (parity: _validate_reindex, reference core.py:527-586)
    # dense-by-design: every intermediate is already dense over
    # expected_groups (shape-static is what XLA fusion and mesh collectives
    # require — docs/implementation.md), so reindex=True is implicit.
    # ReindexStrategy values map onto that reality instead of raising:
    #   * blockwise=True/None + AUTO/NUMPY  -> the implicit dense behavior
    #   * array_type=SPARSE_COO             -> sparse host result leg
    #   * blockwise=False (dense type)      -> no-op eagerly and for
    #     cohorts/blockwise (label-aligned combine is already what those do;
    #     the reference *requires* False there); raises for mesh map-reduce,
    #     where the dense combine cannot be skipped — the bytes ceiling +
    #     blocked program provide that capability instead.
    from .reindex import ReindexArrayType, ReindexStrategy

    reindex_sparse: ReindexStrategy | None = None
    reindex_blockwise_false = False
    if isinstance(reindex, ReindexStrategy):
        if reindex.array_type is ReindexArrayType.SPARSE_COO:
            reindex_sparse = reindex
        elif reindex.blockwise is False:
            reindex_blockwise_false = True
    elif reindex is False:
        reindex_blockwise_false = True
    elif reindex not in (None, True):
        raise TypeError(
            f"reindex must be None, a bool, or a ReindexStrategy; got {reindex!r}"
        )
    if reindex_sparse is not None:
        _fname = func if isinstance(func, str) else getattr(func, "name", "")
        if not isinstance(_fname, str) or any(
            f in _fname for f in ("first", "last", "prod", "var", "std", "arg")
        ):
            # parity: _is_reindex_sparse_supported_reduction (reference
            # lib.py:134-139) — these have no meaningful implicit fill
            raise ValueError(
                f"reindex with array_type=SPARSE_COO does not support {_fname!r}"
            )
        if len(by) > 1:
            raise NotImplementedError(
                "SPARSE_COO reindex supports a single `by` (the sparse axis "
                "is the trailing group axis)"
            )
    nby = len(by)

    if nby == 1 and isinstance(by[0], fct.Prefactorized):
        # registry fast path: factorization (codes, group tables, present
        # table) happened once at put_dataset time — route around the
        # factorize span and the codes H2D entirely
        return _prefactorized_reduce(
            array, by[0], func=func, expected_groups=expected_groups,
            axis=axis, isbin=isbin, fill_value=fill_value, dtype=dtype,
            min_count=min_count, method=method, engine=engine,
            reindex=reindex, finalize_kwargs=finalize_kwargs, mesh=mesh,
            axis_name=axis_name,
        )

    from .sparse import is_sparse_array

    if is_sparse_array(array):
        # sparse inputs reduce without densifying (parity: aggregate_sparse);
        # options the sparse reducer cannot honor are rejected, not dropped
        unsupported = {
            "min_count": min_count, "axis": axis, "method": method,
            "finalize_kwargs": finalize_kwargs, "mesh": mesh,
            # dense strategies / False are eager no-ops here; only the
            # sparse result leg is unplumbed for sparse inputs
            "reindex (SPARSE_COO)": reindex_sparse,
        }
        bad = [k for k, v in unsupported.items() if v is not None]
        if bad:
            raise NotImplementedError(
                f"sparse inputs do not support {bad} (grouping is over the last "
                "axis, eagerly, with the reference's aggregate_sparse func subset)"
            )
        return _sparse_path(
            array, by, func=func, expected_groups=expected_groups, isbin=isbin,
            sort=sort, fill_value=fill_value, dtype=dtype,
            # validate/alias even though the sparse reducer is engine-fixed:
            # engine='numbagg' etc. must fail the same way everywhere
            engine=normalize_engine(engine) if engine is not None else None,
        )

    # -- host-side label normalization ------------------------------------
    bys = [utils.asarray_host(b) for b in by]
    bys = list(np.broadcast_arrays(*bys)) if nby > 1 else bys
    array_is_jax = utils.is_jax_array(array)
    # explicit engine choices are never second-guessed (the autotuner's own
    # rule): only a heuristic-chosen dense engine may re-route to the sort
    # (present-groups) engine in _route_highcard below
    engine_explicit = engine is not None
    engine = _choose_engine(engine, array, array_is_jax)
    arr = array if array_is_jax else np.asarray(array)
    _assert_by_is_aligned(arr.shape, bys)

    if not array_is_jax and arr.dtype.kind in "OSU":
        if not isinstance(func, str) or func not in _NON_NUMERIC_FUNCS:
            raise TypeError(
                f"non-numeric data (dtype {arr.dtype}) supports only "
                f"{_NON_NUMERIC_FUNCS}; got {func!r}"
            )
        if dtype is not None:
            raise TypeError("dtype= is not supported for non-numeric reductions")
        if finalize_kwargs:
            # rejected, not dropped (same stance as the sparse path)
            raise NotImplementedError(
                "finalize_kwargs are not supported for non-numeric reductions"
            )
        if reindex_sparse is not None:
            raise NotImplementedError(
                "SPARSE_COO reindex is not supported for non-numeric reductions"
            )
        if not utils.x64_enabled() and arr.size >= 2**24:
            # f32 positions are exact only to 2**24; beyond that the gather
            # silently returns wrong elements
            if mesh is not None or method is not None:
                raise ValueError(
                    f"non-numeric reductions of {arr.size} elements on the "
                    "mesh need jax_enable_x64 (positions exceed f32's exact "
                    "integer range)."
                )
            logger.debug("non-numeric proxy with x64 disabled: numpy engine")
            engine = "numpy"
        return _reduce_non_numeric(
            arr, bys, func, fill_value=fill_value,
            expected_groups=expected_groups, sort=sort, isbin=isbin, axis=axis,
            min_count=min_count, method=method, engine=engine,
            mesh=mesh, axis_name=axis_name, reindex=reindex,
        )

    expected = _normalize_expected(expected_groups, nby)
    isbin_t = _normalize_isbin(isbin, nby)
    expected_idx = _convert_expected_groups_to_index(expected, isbin_t, sort)

    # -- axis normalization: reduce axes must be trailing -----------------
    arr, bys, n_keep, bndim = _normalize_reduce_axes(arr, bys, axis)
    nred_shape = tuple(bys[0].shape[n_keep:])
    keep_by_shape = tuple(bys[0].shape[:n_keep])

    # -- factorize (host) --------------------------------------------------
    with telemetry.span("factorize", nby=nby) as _fsp:
        codes, found_groups, grp_shape, ngroups, size, props = fct.factorize_cached(
            tuple(bys), axes=tuple(range(n_keep, bndim)), expected_groups=expected_idx, sort=sort
        )
        _fsp.set(ngroups=ngroups, size=size)
    logger.debug(
        "groupby_reduce: func=%s ngroups=%d size=%d offset=%s engine=%s",
        func if isinstance(func, str) else func.name,
        ngroups,
        size,
        props.offset_group,
        engine,
    )
    if ngroups == 0 or size == 0:
        raise ValueError("No groups to reduce over (empty expected_groups?)")

    # -- method/engine heuristics (parity: core.py:685-736) ----------------
    if method is None and mesh is not None:
        # user opted into the mesh without picking a method: let cohort
        # detection recommend one (the reference's _choose_method defers to
        # find_group_cohorts the same way). Shard count = the product of the
        # *named* mesh axes — on a 2-D mesh sharded over one axis, the data
        # splits over that axis only, not mesh.devices.size.
        from .cohorts import chunks_from_shards, find_group_cohorts
        from .parallel.mapreduce import _norm_axes

        n_shards = int(
            np.prod([mesh.shape[a] for a in _norm_axes(axis_name, mesh)])
        )
        flat = np.asarray(codes).reshape(-1)
        method, _ = find_group_cohorts(
            flat, chunks_from_shards(flat.shape[0], n_shards),
            expected_groups=range(size),
        )
        logger.debug("groupby_reduce: auto-selected method=%s", method)

    if reindex_blockwise_false:
        # any non-None method runs the sharded program (a default mesh is
        # substituted when mesh=None), so key on the resolved method
        if method == "map-reduce":
            raise NotImplementedError(
                "reindex=False (blockwise=False) with method='map-reduce' on a "
                "mesh: the SPMD combine is dense over expected_groups by design "
                "and cannot be skipped. The capability it targets — avoiding "
                "huge dense intermediates — is provided instead by "
                "set_options(dense_intermediate_bytes_max=...): additive "
                "reductions above the ceiling auto-route to the blocked "
                "owner-by-owner program. Use method='cohorts'/'blockwise', or "
                "drop reindex=."
            )
        # eager / cohorts / blockwise: combine (if any) is already
        # label-aligned — the request is the behavior; nothing to change
        logger.debug("reindex(blockwise=False): no-op on this path")

    # -- dtype round-trips -------------------------------------------------
    func_name = func if isinstance(func, str) else func.name
    arr_dtype = np.dtype(arr.dtype)
    datetime_dtype = arr_dtype if dtypes.is_datetime_like(arr_dtype) else None
    if datetime_dtype is not None:
        arr = arr.view("int64") if not array_is_jax else arr
        if engine in ("jax", "sort") and not utils.x64_enabled():
            # int64-ns timestamps cannot survive the x64-off int32 downcast;
            # route to the host engine rather than corrupt values
            logger.debug("datetime input with x64 disabled: using numpy engine")
            engine = "numpy"
    bool_input = arr_dtype.kind == "b"
    if bool_input and func_name in ("sum", "nansum", "prod", "nanprod", "count"):
        arr = arr.astype(np.int64 if utils.x64_enabled() else np.int32)

    # -- min_count semantics (parity: core.py:1026-1038) -------------------
    if min_count is None:
        min_count_ = 0
        if fill_value is not None and func_name in ("nansum", "nanprod"):
            min_count_ = 1
    else:
        min_count_ = min_count

    agg = _initialize_aggregation(
        func, dtype, arr.dtype if datetime_dtype is None else np.dtype("int64"),
        fill_value, min_count_, finalize_kwargs
    )
    if datetime_dtype is not None and agg.preserves_dtype:
        from .aggregations import set_nat_final_fill

        set_nat_final_fill(agg, fill_value)
    elif (
        datetime_dtype is not None
        and agg.reduction_type != "argreduce"
        and agg.name not in ("count", "len", "any", "all")
    ):
        # float-returning reductions of datetimes (mean/var/median/quantile/
        # sum): convert NaT -> NaN once, here, so every skipna/propagation
        # rule applies unchanged; timestamp-valued results round back to the
        # datetime dtype in _astype_final (parity: core.py:985-1001,
        # 1205-1211). f64 keeps ~256 ns resolution on epoch values — the
        # same loss the reference's float interpolation/division has.
        arr_f = np.asarray(arr).astype(np.float64)
        arr_f[np.asarray(arr) == _NAT_INT] = np.nan
        arr = arr_f

    # -- flatten for the kernel -------------------------------------------
    nred = int(np.prod(nred_shape)) if nred_shape else 1
    span = int(np.prod(keep_by_shape + nred_shape)) if (keep_by_shape or nred_shape) else 1
    lead_shape = arr.shape[: arr.ndim - bndim]
    arr_flat = arr.reshape(lead_shape + (span,))
    codes_flat = np.asarray(codes).reshape(-1)

    if method is not None:
        # -- sharded SPMD reduction over the mesh ---------------------------
        if datetime_dtype is not None and not utils.x64_enabled():
            raise ValueError(
                "datetime inputs on the mesh path need jax_enable_x64 "
                "(int64 timestamps cannot survive the int32 downcast)."
            )
        from .parallel.mapreduce import sharded_groupby_reduce

        # present-groups mesh execution: compact the (host-known) codes
        # once before the SPMD program builds, so every per-device
        # accumulator AND every collective — psum, and the cohorts
        # psum_scatter whose ownership tiles now slice the compact domain —
        # carries only present-group slices; the dense layout reappears
        # host-side after finalize (kernels.scatter_present_dense)
        mesh_present = None
        codes_run = codes_flat  # codes_flat itself stays in the dense code
        size_run = size         # domain (_sparsify_result reads it below)
        if engine == "sort":
            from .kernels import compact_codes, present_cap, present_groups

            mesh_present = present_groups(codes_flat, size)
            if len(mesh_present) < size:
                ncap = present_cap(len(mesh_present), size)
                codes_run = compact_codes(codes_flat, mesh_present)
                _note_highcard(size, ncap, len(mesh_present))
                size_run = ncap
            else:
                mesh_present = None

        # "combine" here is the whole SPMD program: per-shard chunk reduce +
        # the collective tree-combine + on-device finalize, fused in one
        # shard_map (the program-build / dispatch child spans live in
        # parallel/mapreduce.py)
        with telemetry.span("combine", method=method, size=size_run):
            result = sharded_groupby_reduce(
                arr_flat,
                codes_run,
                agg,
                size=size_run,
                mesh=mesh,
                axis_name=axis_name,
                method=method,
                nat=datetime_dtype is not None,
            )
        with telemetry.span("finalize"):
            result = _astype_final(result, agg, datetime_dtype)
            if mesh_present is not None:
                from .kernels import scatter_present_dense

                result = _redevice_scattered(
                    scatter_present_dense(np.asarray(result), mesh_present, size),
                    array_is_jax,
                )
    else:
        # -- eager single-device reduction ---------------------------------
        if engine in ("jax", "sort"):
            engine = _route_highcard(
                engine, codes_flat, arr_flat, lead_shape, size, agg,
                explicit=engine_explicit,
            )
        if engine == "jax" and OPTIONS["autotune"]:
            # first-call candidate measurement (budgeted, once per banded
            # key): runs HERE, on the host outside any trace, so the traced
            # decision points below only ever do a dict lookup
            from . import autotune

            autotune.prime_reduce(
                func_name, arr_flat.dtype, size, int(np.prod(arr_flat.shape))
            )
        if engine == "sort":
            # -- present-groups (sort) engine: compact once, reduce over the
            # banded capacity with the unchanged jax kernels, scatter the
            # dense layout host-side at the very end. Accumulator bytes
            # track n_present, not the label universe.
            from .kernels import compact_codes, present_cap, present_groups

            present = present_groups(codes_flat, size)
            n_present = len(present)
            ncap = present_cap(n_present, size)
            ccodes = compact_codes(codes_flat, present)
            _note_highcard(size, ncap, n_present)
            if OPTIONS["autotune"]:
                from . import autotune

                autotune.prime_reduce(
                    func_name, arr_flat.dtype, ncap, int(np.prod(arr_flat.shape))
                )
            result_c = _reduce_blockwise(
                arr_flat,
                ccodes,
                agg,
                size=ncap,
                engine="jax",
                datetime_dtype=datetime_dtype,
                prog_family="sort",
            )
            from .kernels import scatter_present_dense

            result = _redevice_scattered(
                scatter_present_dense(np.asarray(result_c), present, size),
                array_is_jax,
            )
        else:
            result = _reduce_blockwise(
                arr_flat,
                codes_flat,
                agg,
                size=size,
                engine=engine,
                datetime_dtype=datetime_dtype,
            )

    # -- reshape: (..., size) -> (..., *keep_by, *grp_shape) ---------------
    out_shape = lead_shape + keep_by_shape + grp_shape
    new_dims = agg.new_dims()
    if new_dims:
        out_shape = new_dims + out_shape
    result = result.reshape(out_shape)

    if reindex_sparse is not None:
        result = _sparsify_result(result, codes_flat, ngroups, agg)

    groups = tuple(_index_values(g) for g in found_groups)
    return (result,) + groups


def _prefactorized_reduce(
    array: Any,
    pf: "fct.Prefactorized",
    *,
    func: str | Aggregation,
    expected_groups: Any,
    axis: Any,
    isbin: Any,
    fill_value: Any,
    dtype: Any,
    min_count: int | None,
    method: str | None,
    engine: str | None,
    reindex: Any,
    finalize_kwargs: dict | None,
    mesh: Any,
    axis_name: str,
) -> tuple:
    """The registry (serve) fast path: ``by`` arrived as a
    :class:`factorize.Prefactorized`, so codes, the expected-groups table,
    and the sort engine's present table were computed — and device-staged —
    at ``put_dataset`` time. This path never opens a ``factorize`` span,
    and with a device-resident ``array`` it dispatches with zero
    ``bytes.h2d`` (both codes and data pass ``utils.asarray_device``
    untouched).

    Options that would require re-deriving the factorization are rejected,
    not dropped — re-put the dataset to change the grouping.
    """
    bad = [
        name
        for name, val in (
            ("expected_groups", expected_groups),
            ("axis", axis),
            ("reindex", reindex),
        )
        if val is not None
    ]
    if isbin not in (False, (False,)):
        bad.append("isbin")
    if bad:
        raise NotImplementedError(
            f"Prefactorized `by` does not support {bad}: the factorization "
            "is fixed at put time (re-put the dataset with different groups)"
        )

    array_is_jax = utils.is_jax_array(array)
    engine_explicit = engine is not None
    engine = _choose_engine(engine, array, array_is_jax)
    arr = array if array_is_jax else np.asarray(array)

    func_name = func if isinstance(func, str) else func.name
    arr_dtype = np.dtype(arr.dtype)
    if arr_dtype.kind in "OSU" or dtypes.is_datetime_like(arr_dtype):
        raise NotImplementedError(
            f"Prefactorized `by` supports numeric data; got dtype {arr_dtype} "
            "(datetime/object inputs keep the inline groupby_reduce path)"
        )
    bndim = len(pf.by_shape)
    if arr.ndim < bndim or tuple(arr.shape[arr.ndim - bndim:]) != tuple(pf.by_shape):
        raise ValueError(
            f"`array` with shape {arr.shape} does not align with the "
            f"prefactorized `by` shape {pf.by_shape}"
        )
    if arr_dtype.kind == "b" and func_name in ("sum", "nansum", "prod", "nanprod", "count"):
        arr = arr.astype(np.int64 if utils.x64_enabled() else np.int32)

    # -- min_count semantics: identical to the inline path ----------------
    if min_count is None:
        min_count_ = 0
        if fill_value is not None and func_name in ("nansum", "nanprod"):
            min_count_ = 1
    else:
        min_count_ = min_count
    agg = _initialize_aggregation(
        func, dtype, arr.dtype, fill_value, min_count_, finalize_kwargs
    )

    lead_shape = arr.shape[: arr.ndim - bndim]
    arr_flat = arr.reshape(lead_shape + (pf.n,))

    if method is None and mesh is not None:
        from .cohorts import chunks_from_shards, find_group_cohorts
        from .parallel.mapreduce import _norm_axes

        n_shards = int(np.prod([mesh.shape[a] for a in _norm_axes(axis_name, mesh)]))
        method, _ = find_group_cohorts(
            pf.codes, chunks_from_shards(pf.n, n_shards),
            expected_groups=range(pf.size),
        )
        logger.debug("prefactorized: auto-selected method=%s", method)

    if method is not None:
        # -- sharded SPMD reduction: put-staged device codes feed the mesh
        # program directly (cohorts keeps host codes — ownership detection
        # is host-side)
        from .parallel.mapreduce import sharded_groupby_reduce

        mesh_present = None
        size_run = pf.size
        if engine == "sort" and len(pf.present) < pf.size:
            mesh_present = pf.present
            size_run = pf.ncap
            _note_highcard(pf.size, pf.ncap, len(pf.present))
            codes_run = pf.ccodes if method == "cohorts" or pf.ccodes_dev is None else pf.ccodes_dev
        else:
            codes_run = pf.codes if method == "cohorts" or pf.codes_dev is None else pf.codes_dev
        with telemetry.span("combine", method=method, size=size_run):
            result = sharded_groupby_reduce(
                arr_flat, codes_run, agg, size=size_run, mesh=mesh,
                axis_name=axis_name, method=method, nat=False,
            )
        with telemetry.span("finalize"):
            result = _astype_final(result, agg, None)
            if mesh_present is not None:
                from .kernels import scatter_present_dense

                result = _redevice_scattered(
                    scatter_present_dense(np.asarray(result), mesh_present, pf.size),
                    array_is_jax,
                )
    else:
        # -- eager single-device reduction ---------------------------------
        if engine in ("jax", "sort"):
            engine = _route_highcard_prefactorized(
                engine, pf, arr_flat, lead_shape, agg, explicit=engine_explicit
            )
        if engine == "sort":
            _note_highcard(pf.size, pf.ncap, len(pf.present))
            ccodes = pf.ccodes_dev if pf.ccodes_dev is not None else pf.ccodes
            result_c = _reduce_blockwise(
                arr_flat, ccodes, agg, size=pf.ncap, engine="jax",
                prog_family="sort",
            )
            from .kernels import scatter_present_dense

            result = _redevice_scattered(
                scatter_present_dense(np.asarray(result_c), pf.present, pf.size),
                array_is_jax,
            )
        else:
            codes = pf.codes_dev if engine == "jax" and pf.codes_dev is not None else pf.codes
            result = _reduce_blockwise(arr_flat, codes, agg, size=pf.size, engine=engine)

    out_shape = lead_shape + pf.group_shape
    new_dims = agg.new_dims()
    if new_dims:
        out_shape = new_dims + out_shape
    result = result.reshape(out_shape)
    return (result,) + tuple(_index_values(g) for g in pf.found_groups)


def _route_highcard_prefactorized(engine, pf, arr_flat, lead_shape, agg, *,
                                  explicit: bool) -> str:
    """Dense-vs-sort routing off the put-time tables: the same decisions as
    :func:`_route_highcard`, with zero per-request hashing — ``present`` /
    ``ncap`` come off the :class:`factorize.Prefactorized` instead of the
    content-fingerprinted ``present_groups`` memo."""
    from .options import OPTIONS
    from .parallel.mapreduce import dense_intermediate_bytes

    lead_elems = int(np.prod(lead_shape)) if lead_shape else 1
    ceiling = OPTIONS["dense_intermediate_bytes_max"]
    est = dense_intermediate_bytes(lead_elems, pf.size, arr_flat.dtype, agg, ndev=1)
    over = est > ceiling
    if engine == "jax" and not over and (
        explicit or pf.size < OPTIONS["sort_engine_min_groups"]
    ):
        return "jax"
    if over:
        est_sort = dense_intermediate_bytes(lead_elems, pf.ncap, arr_flat.dtype, agg, ndev=1)
        if est_sort > ceiling or (engine == "jax" and explicit):
            from .utils import fmt_bytes

            raise ValueError(
                f"{agg.name!r} over {pf.size} groups needs ~{fmt_bytes(est)} "
                f"of dense (..., size) device intermediates, above the "
                f"{fmt_bytes(ceiling)} dense_intermediate_bytes_max ceiling. "
                "Options: pass mesh=; use engine='sort'; or raise "
                "set_options(dense_intermediate_bytes_max=...)."
            )
        telemetry.count("highcard.ceiling_routes")
        return "sort"
    if engine == "sort":
        return "sort"
    return "sort" if pf.ncap * _HIGHCARD_DENSITY_DEN <= pf.size else "jax"


def _sparsify_result(result, codes_flat, ngroups: int, agg: Aggregation):
    """SPARSE_COO result leg (parity: ReindexStrategy(array_type=SPARSE_COO),
    reference reindex.py:106-157 + core.py:527-586).

    The *compute* stays dense — static shapes are load-bearing for XLA — and
    the sparse container packages the host result, storing only the groups
    that actually occur in `by`. Occurrence is the UNION over kept rows: a
    group found in any kept row of a multi-row `by` is stored for every
    row (the container's columns are shared), so nnz can exceed what a
    strictly per-row sparse reindex (the reference's, which stores each
    block's own groups) would produce; for a single-row `by` the two agree.
    Returns a jax BCOO when the implicit fill is zero, HostCOO otherwise.
    """
    host = np.asarray(result)
    if host.dtype.kind in "mMOSU":
        raise NotImplementedError(
            f"SPARSE_COO reindex does not support results of dtype {host.dtype}"
        )
    # codes are offset by kept-row (row*ngroups + g, factorize.offset_labels)
    # when `by` has kept axes; fold back to group ids. A group is stored if
    # it occurs in ANY kept row — the container's columns are shared.
    valid = codes_flat[codes_flat >= 0]
    present = np.unique(valid % ngroups)
    from .reindex import reindex_sparse_coo

    return reindex_sparse_coo(
        host[..., present],
        pd.Index(present),
        pd.RangeIndex(ngroups),
        fill_value=agg.final_fill_value,
    )


def _index_values(idx: pd.Index):
    if isinstance(idx, pd.IntervalIndex):
        return idx
    return idx.values


def _redevice_scattered(result, array_is_jax: bool):
    """Keep the dense path's return-type contract after a host-side
    present-groups scatter: a device-array input yields a device-array
    result (one H2D put of the single dense result buffer — the output
    contract either way). Host inputs keep the host array. The put is
    skipped only when the dense result ALONE would breach the dense
    ceiling — there the routed run's alternative was an exception, and a
    host result is the usable degradation.
    """
    if not array_is_jax:
        return result
    from .options import OPTIONS  # function-local: follows a reloaded module

    if result.nbytes > OPTIONS["dense_intermediate_bytes_max"]:
        logger.debug(
            "highcard: dense result (%d bytes) above the ceiling; "
            "returning a host array", result.nbytes,
        )
        return result
    import jax

    return jax.device_put(result)


def _note_highcard(size: int, ncap: int, n_present: int) -> None:
    """Allocation accounting of the present-groups engine as telemetry
    gauges: the compact capacity actually accumulated over vs the dense
    universe it replaced. Exported on /metrics like every gauge; the CI
    highcard leg asserts "no dense (..., ngroups) allocation" through
    these plus the program-card memory numbers."""
    telemetry.count("highcard.sort_dispatches")
    if not telemetry.enabled():
        return
    telemetry.METRICS.set_gauge("highcard.acc_groups", float(ncap))
    telemetry.METRICS.set_gauge("highcard.present_groups", float(n_present))
    telemetry.METRICS.set_gauge(
        "highcard.dense_groups_avoided", float(max(0, size - ncap))
    )


#: density heuristic for the cold dense-vs-sort call: the sort engine's
#: overheads (one host unique pass, one compact relabel, the final dense
#: scatter) are worth paying once the dense accumulators outweigh the
#: compact ones ~8x — i.e. <= 1/8 of the universe is present. Autotuned
#: bands and the cost-model analytic prior refine this per platform.
_HIGHCARD_DENSITY_DEN = 8


def _route_highcard(engine, codes_flat, arr_flat, lead_shape, size, agg, *,
                    explicit: bool) -> str:
    """Dense-vs-sort routing for the eager device path.

    The hard ceiling first: a dense (..., size) intermediate estimate above
    ``dense_intermediate_bytes_max`` auto-routes heuristic-chosen engines to
    the sort (present-groups) engine — the huge-label-space guard that used
    to be a dead end now degrades to the engine built for that regime. An
    explicitly pinned ``engine="jax"`` still fails actionably (explicit
    choices are never second-guessed), with the sort engine named as the
    remedy. Below the ceiling, universes past ``sort_engine_min_groups``
    consult the "highcard" autotune family: measured ngroups/nelems bands
    outrank the cost-model analytic prior, which outranks the density
    heuristic (:data:`_HIGHCARD_DENSITY_DEN`).
    """
    # OPTIONS re-imported here, not the module-level binding: the option
    # suite reloads flox_tpu.options, and a function-local import follows
    # the live module (the old ceiling guard did the same)
    from .options import OPTIONS
    from .parallel.mapreduce import dense_intermediate_bytes

    lead_elems = int(np.prod(lead_shape)) if lead_shape else 1
    ceiling = OPTIONS["dense_intermediate_bytes_max"]
    est = dense_intermediate_bytes(lead_elems, size, arr_flat.dtype, agg, ndev=1)
    over = est > ceiling
    if engine == "jax" and not over and (
        explicit or size < OPTIONS["sort_engine_min_groups"]
    ):
        return "jax"  # the common case pays neither a unique pass nor routing
    from .kernels import present_cap, present_groups

    present = present_groups(codes_flat, size)  # memoized; the sort path reuses it
    ncap = present_cap(len(present), size)
    if over:
        est_sort = dense_intermediate_bytes(lead_elems, ncap, arr_flat.dtype, agg, ndev=1)
        if est_sort > ceiling or (engine == "jax" and explicit):
            from .utils import fmt_bytes

            sort_note = (
                f"even the sort engine's compact domain ({ncap} present-group "
                f"slots, ~{fmt_bytes(est_sort)}) exceeds the ceiling"
                if est_sort > ceiling
                else "engine='sort' (FLOX_TPU_DEFAULT_ENGINE=sort) reduces over "
                f"only the {len(present)} groups actually present"
            )
            raise ValueError(
                f"{agg.name!r} over {size} groups needs ~{fmt_bytes(est)} "
                f"of dense (..., size) device intermediates, above the "
                f"{fmt_bytes(ceiling)} dense_intermediate_bytes_max "
                f"ceiling; {sort_note}. Options: pass mesh= (map-reduce "
                "auto-routes to the blocked owner-by-owner program for "
                "additive reductions); reduce expected_groups; use "
                "engine='sort' or engine='numpy' on host data; or raise "
                "set_options(dense_intermediate_bytes_max=...) if the device "
                "really has the headroom."
            )
        if engine == "jax":
            logger.debug(
                "highcard: dense estimate over ceiling -> sort engine "
                "(size=%d present=%d)", size, len(present),
            )
            telemetry.count("highcard.ceiling_routes")
        return "sort"
    if engine == "sort":
        return "sort"
    nelems = int(np.prod(arr_flat.shape))
    heuristic = "sort" if ncap * _HIGHCARD_DENSITY_DEN <= size else "dense"
    chosen = heuristic
    if OPTIONS["autotune"]:
        from . import autotune

        autotune.prime_highcard(arr_flat.dtype, size, len(present), nelems)
        chosen = autotune.decide(
            "highcard", heuristic, ("dense", "sort"),
            dtype=str(arr_flat.dtype), ngroups=size, nelems=nelems,
        )
    if chosen != heuristic:
        logger.debug("highcard autotune: %s (heuristic %s)", chosen, heuristic)
    return "sort" if chosen == "sort" else "jax"


def _reduce_blockwise(arr_flat, codes_flat, agg: Aggregation, *, size, engine,
                      datetime_dtype=None, prog_family="bundle"):
    """Single-pass eager reduction + finalize (parity: core.py:478-524)."""
    numpy_funcs = list(agg.numpy)
    fills: list[Any] = [agg.final_fill_value] * len(numpy_funcs)
    kdtypes: list[Any] = [None] * len(numpy_funcs)
    base_kwargs = dict(agg.finalize_kwargs)
    if datetime_dtype is not None:
        base_kwargs["nat"] = True  # INT64_MIN is a missing marker, not a value
    kwargss: list[dict] = [dict(base_kwargs) for _ in numpy_funcs]

    if agg.min_count > 0:
        numpy_funcs.append("nanlen")
        fills.append(0)
        kdtypes.append(None)
        kwargss.append({"nat": True} if datetime_dtype is not None else {})

    # dtype request for the kernel: the final dtype for accumulating funcs.
    # Not on the datetime path — there the data was converted to float64
    # with NaT as NaN, and an int64 request would cast the NaNs to garbage
    # mid-reduction; the int64 view happens once, in _astype_final.
    if datetime_dtype is None:
        if not agg.preserves_dtype and agg.name in ("sum", "nansum", "prod", "nanprod"):
            kdtypes[0] = agg.final_dtype
        if agg.name in ("mean", "nanmean", "var", "nanvar", "std", "nanstd") and np.dtype(agg.final_dtype).kind == "f":
            kdtypes[0] = agg.final_dtype

    results = chunk_reduce(
        arr_flat,
        codes_flat,
        funcs=numpy_funcs,
        size=size,
        fill_values=fills,
        dtypes_=kdtypes,
        engine=engine,
        kwargss=kwargss,
        prog_family=prog_family,
    )

    # "combine" eagerly: fold the per-kernel intermediates into one result
    # (multi-stage finalize + the min_count mask) — the single-device
    # analogue of the mesh path's collective combine
    with telemetry.span("combine", nresults=len(results)):
        if agg.min_count > 0:
            counts = results[-1]
            results = results[:-1]
        else:
            counts = None

        if agg.finalize is not None and len(agg.numpy) > 1:
            # multi-stage custom Aggregation: the eager stages are intermediates
            # and finalize folds them (parity: _finalize_results, core.py:410-475).
            # Registry aggs use a single fused eager kernel, already final.
            result = agg.finalize(*results, **agg.finalize_kwargs)
        else:
            result = results[0]

        if counts is not None:
            result = _where(counts < agg.min_count, agg.final_fill_value, result)

    with telemetry.span("finalize"):
        result = _astype_final(result, agg, datetime_dtype)
    return result


def _where(cond, fill, x):
    if utils.is_jax_array(x):
        import jax.numpy as jnp

        cond = jnp.broadcast_to(jnp.asarray(cond), x.shape)
        inexact = jnp.issubdtype(x.dtype, jnp.floating) or jnp.issubdtype(
            x.dtype, jnp.complexfloating
        )
        if utils.is_nan_fill(fill) and not inexact:
            x = x.astype(jnp.float64 if utils.x64_enabled() else jnp.float32)
        return jnp.where(cond, jnp.asarray(fill).astype(x.dtype), x)
    cond = np.broadcast_to(np.asarray(cond), np.shape(x))
    xdt = np.asarray(x).dtype
    inexact = np.issubdtype(xdt, np.floating) or np.issubdtype(xdt, np.complexfloating)
    if utils.is_nan_fill(fill) and not inexact:
        x = np.asarray(x).astype(np.float64)
    return np.where(cond, fill, x)


# datetime reductions whose result is NOT a point in time: counts, bools,
# indices, and variance (units of ns²) stay numeric (the reference casts
# var/std back too, core.py:1205-1211 — a unit error this build corrects)
_DT_KEEP_NUMERIC = frozenset(
    {"count", "len", "any", "all", "var", "nanvar", "std", "nanstd"}
)


def _astype_final(result, agg: Aggregation, datetime_dtype=None):
    final = np.dtype(agg.final_dtype)
    if datetime_dtype is not None and agg.preserves_dtype:
        # values stayed int64 end-to-end; missing groups carry _NAT_INT == NaT
        res = np.asarray(result)
        if res.dtype.kind == "f":  # only via an explicit float user fill
            res = np.where(np.isnan(res), _NAT_INT, res)
        return res.astype("int64").view(datetime_dtype)
    if (
        datetime_dtype is not None
        and agg.name not in _DT_KEEP_NUMERIC
        and agg.reduction_type != "argreduce"
    ):
        # non-dtype-preserving timestamp results (mean/median/quantile/sum of
        # datetimes) round-trip back from float epoch values, NaN -> NaT
        # (parity: core.py:1205-1211)
        res = np.asarray(result)
        if res.dtype.kind == "f":
            nanmask = np.isnan(res)
            out = np.round(np.where(nanmask, 0.0, res)).astype("int64")
            out[nanmask] = _NAT_INT
        else:
            out = res.astype("int64")
        return out.view(datetime_dtype)
    if utils.is_jax_array(result):
        import jax.numpy as jnp

        if not utils.x64_enabled() and final.itemsize == 8 and final.kind in "fiu":
            final = np.dtype(final.kind + "4")
        if result.dtype != final:
            # don't downcast float results carrying NaN fills into ints
            if final.kind in "iu" and jnp.issubdtype(result.dtype, jnp.floating):
                if bool(jnp.isnan(result).any()):
                    return result
            result = result.astype(final)
        return result
    res = np.asarray(result)
    if res.dtype != final:
        if final.kind in "iub" and res.dtype.kind == "f" and np.isnan(res).any():
            return res  # promoted to hold missing values
        res = res.astype(final)
    return res


def _sparse_path(array, by, *, func, expected_groups, isbin, sort, fill_value, dtype, engine):
    """Route BCOO inputs to the sparse reducer (grouping over the last axis,
    1-D labels — the reference's aggregate_sparse scope)."""
    from .sparse import sparse_groupby_reduce

    if len(by) != 1:
        raise NotImplementedError("sparse inputs support a single 1-D `by`")
    if not isinstance(func, str):
        raise NotImplementedError("sparse inputs support named funcs only")
    bys = [utils.asarray_host(by[0])]
    if bys[0].ndim != 1 or bys[0].shape[0] != array.shape[-1]:
        raise ValueError("sparse inputs need a 1-D `by` matching the last axis")
    expected = _normalize_expected(expected_groups, 1)
    isbin_t = _normalize_isbin(isbin, 1)
    expected_idx = _convert_expected_groups_to_index(expected, isbin_t, sort)
    codes, found_groups, grp_shape, ngroups, size, props = fct.factorize_(
        bys, axes=(0,), expected_groups=expected_idx, sort=sort
    )
    result = sparse_groupby_reduce(
        array, np.asarray(codes).reshape(-1), func=func, size=size,
        fill_value=fill_value, dtype=dtype,
    )
    return (result,) + tuple(_index_values(g) for g in found_groups)
