"""Shared type definitions (parity: /root/reference/flox/types.py:28-42 and
the TypeAlias block at core.py:62-93, trimmed to what the TPU build uses)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import TYPE_CHECKING, Any, Literal, TypedDict, Union

import numpy as np

if TYPE_CHECKING:
    import jax

T_Array = Union[np.ndarray, "jax.Array"]
T_Axes = tuple[int, ...]
T_Engine = Literal["jax", "numpy"]
T_Method = Literal["map-reduce", "blockwise", "cohorts"]
T_ScanMethod = Literal["blelloch", "blockwise"]
T_Func = str
T_ExpectedGroups = Any  # pd.Index | array-like | tuple thereof | None


class IntermediateDict(TypedDict):
    """Per-chunk reduction output: discovered groups + one array per chunk-func."""

    groups: tuple[T_Array, ...]
    intermediates: list[T_Array]


class FinalResultsDict(TypedDict, total=False):
    groups: T_Array


@dataclass(frozen=True)
class FactorProps:
    """Bookkeeping emitted by factorization (parity: types.py:42 FactorProps)."""

    offset_group: bool  # labels were offset per leading-position (partial-axis reduce)
    nan_sentinel: bool  # -1 codes were remapped to an extra trailing group
    nanmask: Any  # host bool mask of NaN-labelled positions (or None)
