"""Grouped reductions on sparse arrays without densifying (L1).

Parity target: /root/reference/flox/aggregate_sparse.py — group the *stored*
values by (leading-position ⊗ group-of-last-axis) via a composite segment id
(aggregate_sparse.py:71-80), reduce them densely, then fold the implicit
fill-value contribution in algebraically using counts
(aggregate_sparse.py:106-132). Supported funcs mirror the reference:
``sum, nansum, min, max, nanmin, nanmax, mean, nanmean, count``
(aggregate_sparse.py:201-206).

TPU realization: the sparse container is ``jax.experimental.sparse.BCOO``
(implicit fill value 0), the stored-value reduction is the same XLA segment
primitive the dense engine uses, and everything stays traceable — a BCOO
input to ``groupby_reduce`` routes here automatically.
"""

from __future__ import annotations

import numpy as np

__all__ = ["sparse_groupby_reduce", "SPARSE_FUNCS", "is_sparse_array"]

SPARSE_FUNCS = frozenset(
    {"sum", "nansum", "min", "max", "nanmin", "nanmax", "mean", "nanmean", "count"}
)


def is_sparse_array(x) -> bool:
    try:
        from jax.experimental.sparse import BCOO, BCSR

        return isinstance(x, (BCOO, BCSR))
    except ImportError:  # pragma: no cover
        return False


def sparse_groupby_reduce(
    mat,
    codes,
    *,
    func: str,
    size: int,
    fill_value=None,
    dtype=None,
):
    """Grouped reduction over the last axis of a BCOO matrix.

    ``codes``: (ncols,) int with -1 = missing. Returns a DENSE
    (..., size) result — the group axis is small by construction.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental.sparse import BCSR, BCOO

    if func not in SPARSE_FUNCS:
        raise NotImplementedError(
            f"sparse grouped {func!r} is not supported (the reference supports the "
            f"same subset, aggregate_sparse.py:201-206): {sorted(SPARSE_FUNCS)}"
        )
    if isinstance(mat, BCSR):
        mat = mat.to_bcoo()
    if mat.n_batch or mat.n_dense:
        raise NotImplementedError("batched/dense-suffix BCOO layouts are not supported")

    codes = jnp.asarray(np.asarray(codes)).astype(jnp.int32).reshape(-1)
    if dtype is not None:
        mat = BCOO((mat.data.astype(dtype), mat.indices), shape=mat.shape)
    lead_shape = mat.shape[:-1]
    ncols = mat.shape[-1]
    nlead = int(np.prod(lead_shape)) if lead_shape else 1

    data = mat.data
    idx = mat.indices  # (nse, ndim)
    if lead_shape:
        strides = np.concatenate([np.cumprod(lead_shape[::-1])[-2::-1], [1]]).astype(np.int64)
        lead_idx = (idx[:, :-1] * jnp.asarray(strides)).sum(axis=1).astype(jnp.int32)
    else:
        lead_idx = jnp.zeros(idx.shape[0], dtype=jnp.int32)
    col = idx[:, -1]
    gcode = jnp.take(codes, col)  # (nse,)

    # composite segment id over (lead, group); missing labels -> overflow slot
    nseg = nlead * size
    seg = jnp.where(gcode >= 0, lead_idx * size + gcode, nseg)

    def _seg(op, vals):
        fn = {"sum": jax.ops.segment_sum, "min": jax.ops.segment_min, "max": jax.ops.segment_max}[op]
        return fn(vals, seg, num_segments=nseg + 1)[:nseg].reshape(lead_shape + (size,))

    skipna = func.startswith("nan") or func == "count"
    isnan = jnp.isnan(data) if jnp.issubdtype(data.dtype, jnp.floating) else jnp.zeros(data.shape, bool)

    # per-(lead, group) stored counts; per-group total column counts
    stored = _seg("sum", jnp.ones_like(data, dtype=jnp.int32).astype(jnp.int32))
    stored_nan = _seg("sum", isnan.astype(jnp.int32))
    col_counts = jax.ops.segment_sum(
        jnp.ones_like(codes), jnp.where(codes >= 0, codes, size), num_segments=size + 1
    )[:size]  # (size,): columns per group
    total = jnp.broadcast_to(col_counts, lead_shape + (size,))
    implicit = total - stored  # implicit zeros per (lead, group)

    fv = jnp.nan if fill_value is None else fill_value

    def _promote_for_fill(out):
        """NaN fills force float output, as the dense path promotes."""
        import math

        fv_is_nan = isinstance(fv, float) and math.isnan(fv)
        if fv_is_nan and not jnp.issubdtype(out.dtype, jnp.floating):
            from . import utils as _u

            return out.astype(jnp.float64 if _u.x64_enabled() else jnp.float32)
        return out

    if func in ("sum", "nansum"):
        vals = jnp.where(isnan, 0, data) if func == "nansum" else data
        out = _seg("sum", vals)
        if func == "sum" and jnp.issubdtype(out.dtype, jnp.floating):
            has_nan = stored_nan > 0
            out = jnp.where(has_nan, jnp.asarray(jnp.nan, out.dtype), out)
        # implicit zeros contribute 0; a user fill replaces truly empty groups
        empty = total == 0
        sum_fill = 0 if fill_value is None else fill_value
        return jnp.where(empty, jnp.asarray(sum_fill).astype(out.dtype), out)

    if func == "count":
        return total - stored_nan

    if func in ("mean", "nanmean"):
        vals = jnp.where(isnan, 0, data) if func == "nanmean" else data
        s = _seg("sum", vals)
        denom = (total - stored_nan) if func == "nanmean" else total
        out = s / jnp.where(denom > 0, denom, 1).astype(s.dtype)
        out = _promote_for_fill(out)
        if func == "mean":
            out = jnp.where(stored_nan > 0, jnp.asarray(jnp.nan, out.dtype), out)
        return jnp.where(denom > 0, out, jnp.asarray(fv).astype(out.dtype))

    # min/max family: compare the stored extreme against the implicit zero
    is_max = "max" in func
    if jnp.issubdtype(data.dtype, jnp.floating):
        ident = -jnp.inf if is_max else jnp.inf
    else:
        info = np.iinfo(np.dtype(str(data.dtype)))
        ident = info.min if is_max else info.max
    vals = jnp.where(isnan, jnp.asarray(ident, data.dtype), data) if skipna else data
    ext = _seg("max" if is_max else "min", vals)
    # NaN propagation for the non-skipna variants (float data only — integer
    # data cannot hold NaN, and asarray(nan, int) would raise)
    if not skipna and jnp.issubdtype(ext.dtype, jnp.floating):
        ext = jnp.where(stored_nan > 0, jnp.asarray(jnp.nan, ext.dtype), ext)
    zero = jnp.asarray(0, ext.dtype)
    with_fill = jnp.where(
        implicit > 0, jnp.maximum(ext, zero) if is_max else jnp.minimum(ext, zero), ext
    )
    # all-stored-NaN groups with no implicit zeros -> fill
    with_fill = _promote_for_fill(with_fill)
    if skipna:
        all_nan_stored = (stored_nan == stored) & (implicit == 0) & (total > 0)
        with_fill = jnp.where(all_nan_stored, jnp.asarray(fv).astype(with_fill.dtype), with_fill)
    return jnp.where(total > 0, with_fill, jnp.asarray(fv).astype(with_fill.dtype))
