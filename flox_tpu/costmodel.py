"""Analytical cost-model plane: compiled-program cards + roofline drift.

Every number the observability plane reported before this module was
*measured* — the cost ledger says how long a program took, never how long
the hardware would have allowed. XLA already computed the missing half at
compile time: ``Compiled.cost_analysis()`` (flops, bytes accessed,
transcendentals) and ``Compiled.memory_analysis()`` (argument/output/temp
bytes) sit unread behind the same ``lower().compile()`` path the AOT layer
uses. This module reads them:

* **Compiled-program cards** (:func:`ensure_card`): every compile site —
  the eager kernel bundle, the fused multi-statistic program, the mesh
  shard_map program, the streaming step programs, the Pallas compile
  probes, and the serve/AOT replays — records one card per (label, input
  signature): analytical flops, bytes accessed, memory footprint, an HLO
  hash, the compile wall, and a roofline ``predicted_ms`` against the
  per-platform peak table. The analysis pass lowers and compiles the SAME
  program a second time purely for inspection (never executed, so results
  are bit-identical with the plane on); its compile/trace events are
  routed to ``costmodel.card_*`` counters so ``jax.compiles`` keeps
  meaning what the AOT acceptance criterion needs it to mean. Backends
  whose ``cost_analysis`` raises degrade to a card with
  ``analysis: "unavailable"`` — never an error into the dispatch path.
* **Roofline utilization**: at dispatch time the cost ledger row joins its
  card — achieved GB/s and FLOP/s against :data:`PEAK_TABLE` become the
  ``program.utilization`` / ``program.predicted_ms`` gauges (labeled per
  program on /metrics), and :func:`program_report` is the JSON face
  (``/debug/programs``, ``python -m flox_tpu.telemetry programs``).
* **Drift sentinel** (:func:`drift_report`): programs whose observed
  per-dispatch device time diverges more than
  ``OPTIONS["costmodel_drift_threshold"]``× from the model (roofline
  prediction floored at ``costmodel_overhead_ms`` — tiny programs are
  judged against dispatch overhead, not microsecond analytics) are
  flagged: the "this program silently got 10× slower after a JAX upgrade"
  detector, wired into the bench JSON and the fleet federator.
* **Autotune prior** (:func:`analytic_prior`): when ``autotune.decide``
  finds no measured band, the analytical model supplies a cold-start
  prior for the families it can reason about.

Everything is gated on :func:`enabled` — ``OPTIONS["telemetry"]`` AND
``OPTIONS["costmodel"]`` — and the registry is bounded, registered in
``cache.clear_all`` / ``cache.stats`` (floxlint FLX008).
"""

from __future__ import annotations

import contextvars
import hashlib
import logging
import threading
import time
from typing import Any

from . import telemetry
from .options import OPTIONS

logger = logging.getLogger(__name__)

__all__ = [
    "PEAK_TABLE",
    "analytic_prior",
    "aval_args",
    "card_for",
    "cards",
    "dispatch_marks",
    "drift_report",
    "enabled",
    "ensure_card",
    "program_report",
    "publish_gauges",
    "record_compiled",
    "serve_alias",
    "stamp_capture",
]

#: per-platform roofline peaks the predicted-time model divides by:
#: memory bandwidth (GB/s per chip) and compute (GFLOP/s per chip).
#: Deliberately conservative round numbers — the model's job is detecting
#: order-of-magnitude drift and ranking engine families, not citing
#: datasheets; utilization reads as "fraction of this table's ceiling".
PEAK_TABLE: dict[str, dict[str, float]] = {
    "tpu": {"bw_gbps": 819.0, "gflops": 90_000.0},
    "gpu": {"bw_gbps": 900.0, "gflops": 30_000.0},
    "cpu": {"bw_gbps": 20.0, "gflops": 100.0},
    "default": {"bw_gbps": 10.0, "gflops": 50.0},
}

#: digest -> card: the compiled-program card registry. Bounded by program
#: diversity (same bound as the compiled-program caches); registered in
#: cache.clear_all / cache.stats (floxlint FLX008).
_CARD_REGISTRY: dict[str, dict] = {}
#: program label -> digest of the newest card recorded under that label
#: (serve aliases land here too); cleared with the registry.
_CARD_LABELS: dict[str, str] = {}
_REGISTRY_MAX = 1024
_LOCK = threading.RLock()

#: the serve layer's program label for whatever compiles inside its
#: dispatch: cards recorded (or re-touched) inside a :func:`serve_alias`
#: scope also index under the serving label, so a ``serve[mean#ab12]``
#: ledger row joins the underlying bundle/mesh/fused card.
_ALIAS: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "flox_tpu_costmodel_alias", default=None
)


def enabled() -> bool:
    """Whether the cost-model plane is on: ``OPTIONS["costmodel"]`` AND
    telemetry (cards join the cost ledger, which only exists enabled)."""
    return bool(OPTIONS["costmodel"]) and telemetry.enabled()


def platform_name() -> str:
    """The active jax backend name (``"cpu"`` when jax cannot answer)."""
    try:
        import jax

        return str(jax.default_backend())
    except Exception:  # noqa: BLE001 — identity must never break dispatch
        return "cpu"


def peaks_for(platform: str | None = None) -> dict[str, float]:
    """The :data:`PEAK_TABLE` row for ``platform`` (default: the active
    backend), falling back to the ``"default"`` row."""
    if platform is None:
        platform = platform_name()
    return PEAK_TABLE.get(platform, PEAK_TABLE["default"])


class serve_alias:
    """Context manager binding the serving layer's program label: any card
    recorded or re-touched inside also indexes under ``label``, so the
    serve ledger row (``serve[mean#ab12]``) joins the card of whatever
    program its dispatch actually compiled."""

    __slots__ = ("_label", "_token")

    def __init__(self, label: str | None) -> None:
        self._label = label
        self._token: contextvars.Token | None = None

    def __enter__(self) -> "serve_alias":
        if self._label is not None:
            self._token = _ALIAS.set(str(self._label))
        return self

    def __exit__(self, *exc: Any) -> bool:
        if self._token is not None:
            _ALIAS.reset(self._token)
            self._token = None
        return False


def _aval_signature(args: tuple, kwargs: dict | None = None) -> str:
    """A stable text signature of the call's abstract values: pytree
    structure + per-leaf (shape, dtype). Cheap — the per-dispatch memo
    check hashes this, so it must cost microseconds, not a trace."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs or {}))
    parts = [str(treedef)]
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            parts.append(repr(leaf))
        else:
            parts.append(f"{tuple(shape)}:{dtype}")
    return "|".join(parts)


def _digest(label: str, sig: str) -> str:
    return hashlib.blake2b(f"{label}\x1f{sig}".encode(), digest_size=12).hexdigest()


def _index(label: str, digest: str) -> None:
    """Point ``label`` (and the active serve alias, if any) at ``digest``.
    Callers hold :data:`_LOCK`."""
    _CARD_LABELS[label] = digest
    alias = _ALIAS.get()
    if alias is not None:
        _CARD_LABELS[alias] = digest


def _cost_totals(compiled: Any) -> dict[str, float]:
    """flops / bytes accessed / transcendentals summed across the
    executable's modules. ``cost_analysis()`` returns a list of dicts on
    older jax and a plain dict on newer — both shapes land here."""
    analysis = compiled.cost_analysis()
    if isinstance(analysis, dict):
        analysis = [analysis]
    totals = {"flops": 0.0, "bytes_accessed": 0.0, "transcendentals": 0.0}
    for entry in analysis or []:
        totals["flops"] += float(entry.get("flops", 0.0) or 0.0)
        totals["bytes_accessed"] += float(entry.get("bytes accessed", 0.0) or 0.0)
        totals["transcendentals"] += float(entry.get("transcendentals", 0.0) or 0.0)
    return totals


def _memory_totals(compiled: Any) -> dict[str, int]:
    """argument/output/temp/generated-code bytes from
    ``memory_analysis()`` (zeros where the backend reports none)."""
    out = {"argument_bytes": 0, "output_bytes": 0, "temp_bytes": 0,
           "generated_code_bytes": 0}
    try:
        mem = compiled.memory_analysis()
    except Exception:  # noqa: BLE001 — per-backend degradation by contract
        return out
    if mem is None:
        return out
    out["argument_bytes"] = int(getattr(mem, "argument_size_in_bytes", 0) or 0)
    out["output_bytes"] = int(getattr(mem, "output_size_in_bytes", 0) or 0)
    out["temp_bytes"] = int(getattr(mem, "temp_size_in_bytes", 0) or 0)
    out["generated_code_bytes"] = int(
        getattr(mem, "generated_code_size_in_bytes", 0) or 0
    )
    return out


def _hlo_hash(compiled: Any) -> str | None:
    try:
        text = compiled.as_text()
    except Exception:  # noqa: BLE001 — some backends cannot re-render
        return None
    return hashlib.blake2b(str(text).encode(), digest_size=8).hexdigest()


def predicted_ms(card: dict, platform: str | None = None) -> float:
    """Roofline time for one dispatch of the card's program: the larger of
    the bandwidth leg (bytes accessed / peak GB/s) and the compute leg
    (flops / peak GFLOP/s), in milliseconds."""
    peaks = peaks_for(platform or card.get("platform"))
    bw_s = float(card.get("bytes_accessed", 0.0)) / (peaks["bw_gbps"] * 1e9)
    fl_s = float(card.get("flops", 0.0)) / (peaks["gflops"] * 1e9)
    return max(bw_s, fl_s) * 1e3


def record_compiled(
    label: str,
    compiled: Any,
    *,
    compile_ms: float = 0.0,
    sig: str = "",
    in_shapes: list | None = None,
) -> str | None:
    """Record one card from an already-compiled executable (the Pallas
    compile probes hold one in hand; :func:`ensure_card` builds one).
    Returns the card digest; never raises."""
    try:
        digest = _digest(label, sig)
        platform = platform_name()
        card: dict[str, Any] = {
            "label": label,
            "digest": digest,
            "platform": platform,
            "flops": 0.0,
            "bytes_accessed": 0.0,
            "transcendentals": 0.0,
            "compile_ms": round(float(compile_ms), 3),
            "analysis": "ok",
            "in_shapes": in_shapes or [],
            "recorded_at": time.time(),
        }
        try:
            card.update(_cost_totals(compiled))
        except Exception as exc:  # noqa: BLE001 — stat-less backend: a card
            # with analysis "unavailable", never an error into dispatch
            card["analysis"] = f"unavailable:{type(exc).__name__}"
        card.update(_memory_totals(compiled))
        card["peak_bytes"] = (
            card["argument_bytes"] + card["output_bytes"] + card["temp_bytes"]
        )
        card["hlo_hash"] = _hlo_hash(compiled)
        card["predicted_ms"] = round(predicted_ms(card), 6)
        with _LOCK:
            if len(_CARD_REGISTRY) >= _REGISTRY_MAX and digest not in _CARD_REGISTRY:
                # bounded: a pathological label churn drops the card, never
                # grows the registry without bound (counted, not silent)
                telemetry.count("costmodel.cards_dropped")
                return None
            _CARD_REGISTRY[digest] = card
            _index(label, digest)
        telemetry.count("costmodel.cards_recorded")
        return digest
    except Exception as exc:  # noqa: BLE001 — observability never breaks dispatch
        logger.debug("costmodel card for %r failed: %s", label, exc)
        return None


def ensure_card(label: str, fn: Any, args: tuple, kwargs: dict | None = None) -> str | None:
    """Record (once per label + input signature) the analytical card of the
    jitted ``fn`` as called with ``args``/``kwargs``.

    Called from the dispatch sites right where the program executes, with
    the same arguments — the card's program identity matches the program
    actually served. A registry hit is a dict lookup; a miss lowers and
    compiles the program once more purely for analysis, with its compile
    events routed to ``costmodel.card_*`` (``jax.compiles`` untouched).
    Never raises; returns the digest or ``None``.
    """
    if not enabled() or fn is None or not hasattr(fn, "lower"):
        return None
    try:
        sig = _aval_signature(args, kwargs)
        digest = _digest(label, sig)
        with _LOCK:
            if digest in _CARD_REGISTRY:
                _index(label, digest)
                return digest
            if len(_CARD_REGISTRY) >= _REGISTRY_MAX:
                # capacity checked BEFORE the analysis compile: a full
                # registry must not pay a fresh lower+compile on every
                # dispatch just to drop the result (counted, not silent)
                telemetry.count("costmodel.cards_dropped")
                return None
        t0 = time.perf_counter()
        with telemetry.card_compile_accounting():
            compiled = fn.lower(*args, **(kwargs or {})).compile()
        compile_ms = (time.perf_counter() - t0) * 1e3
        # full analysis wall (lowering included), accumulated so wrappers
        # timing a whole dispatch from outside (the serve execute window,
        # AOT warmup) can net it out of their observed device time
        telemetry.METRICS.inc("costmodel.card_analysis_ms", compile_ms)
        shapes = [
            [list(getattr(leaf, "shape", ())), str(getattr(leaf, "dtype", "?"))]
            for leaf in _leaves(args)
        ][:8]
        return record_compiled(
            label, compiled, compile_ms=compile_ms, sig=sig, in_shapes=shapes
        )
    except Exception as exc:  # noqa: BLE001 — observability never breaks dispatch
        logger.debug("costmodel lower/compile for %r failed: %s", label, exc)
        telemetry.count("costmodel.card_errors")
        return None


def aval_args(args: tuple) -> tuple:
    """``args`` with every array leaf replaced by a
    ``jax.ShapeDtypeStruct`` — a lowering-ready snapshot a caller can hold
    past the arrays' lifetime (the streaming path captures its step
    arguments this way and records the card AFTER the timed stream loop,
    so the analysis compile never lands in a pass's dispatch wall)."""
    import jax

    def leaf(x: Any) -> Any:
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is None or dtype is None:
            return x
        return jax.ShapeDtypeStruct(tuple(shape), dtype)

    return jax.tree_util.tree_map(leaf, args)


def _leaves(args: tuple) -> list:
    try:
        import jax

        return [
            leaf for leaf in jax.tree_util.tree_leaves(args)
            if hasattr(leaf, "shape")
        ]
    except Exception:  # noqa: BLE001
        return []


def cards() -> dict[str, dict]:
    """A locked copy of the card registry (digest -> card)."""
    with _LOCK:
        return {digest: dict(card) for digest, card in _CARD_REGISTRY.items()}


def card_for(label: str) -> dict | None:
    """The newest card recorded under ``label`` (serve aliases included)."""
    with _LOCK:
        digest = _CARD_LABELS.get(label)
        card = _CARD_REGISTRY.get(digest) if digest is not None else None
        return dict(card) if card is not None else None


def _net_device_ms(entry: dict) -> float:
    """Observed device wall net of the compile wall the same row billed:
    an honest first dispatch pays trace+compile inside its dispatch span,
    and judging THAT against the steady-state roofline would flag every
    cold start as drift. Floored at 0 (a cache-served compile can bill
    more compile_ms than wall on pathological clocks)."""
    return max(
        0.0,
        float(entry.get("device_ms", 0.0)) - float(entry.get("compile_ms", 0.0)),
    )


def _utilization(entry: dict, card: dict) -> dict[str, float]:
    """The roofline join of one ledger row and its card: achieved GB/s and
    GFLOP/s, and utilization = model time / observed time (the fraction of
    the peak-table ceiling the dispatches actually reached). Times are
    compile-net (:func:`_net_device_ms`)."""
    device_ms = _net_device_ms(entry)
    dispatches = int(entry.get("dispatches", 0))
    if device_ms <= 0.0 or dispatches <= 0:
        return {"utilization": 0.0, "achieved_gbps": 0.0, "achieved_gflops": 0.0}
    seconds = device_ms / 1e3
    achieved_gbps = float(card.get("bytes_accessed", 0.0)) * dispatches / seconds / 1e9
    achieved_gflops = float(card.get("flops", 0.0)) * dispatches / seconds / 1e9
    util = float(card.get("predicted_ms", 0.0)) * dispatches / device_ms
    return {
        "utilization": round(util, 6),
        "achieved_gbps": round(achieved_gbps, 6),
        "achieved_gflops": round(achieved_gflops, 6),
    }


def publish_gauges(label: str, entry: dict) -> None:
    """Update the per-program roofline gauges after one dispatch's ledger
    write: ``program.utilization|program=<label>`` (fraction of the peak
    ceiling reached so far) and ``program.predicted_ms|program=<label>``
    (the model's per-dispatch time). No-op without a card for the label."""
    card = card_for(label)
    if card is None or not str(card.get("analysis", "")).startswith("ok"):
        return
    safe = _label_safe(label)
    join = _utilization(entry, card)
    telemetry.METRICS.set_gauge(
        f"program.utilization|program={safe}", join["utilization"]
    )
    telemetry.METRICS.set_gauge(
        f"program.predicted_ms|program={safe}", float(card["predicted_ms"])
    )


def _label_safe(label: str) -> str:
    """A program label safe as a registry ``|key=value`` label value: the
    separator characters fold away (quotes/backslashes are escaped at
    render time by the exposition layer)."""
    return str(label).replace("|", "_").replace("=", "_")[:120]


def program_report(top: int | None = None, program: str | None = None) -> dict:
    """The compiled-program card table joined with the observed cost
    ledger — the payload behind ``/debug/programs`` and the ``programs``
    CLI.

    One row per program label: the card (analytical flops/bytes/footprint/
    predicted time) plus ``observed`` (the ledger row) and the roofline
    join (utilization, achieved GB/s and GFLOP/s, drift ratio vs the
    overhead-floored model). ``program`` filters labels by substring;
    ``top`` keeps the K rows with the most observed device time (rows
    without observations rank last)."""
    ledger = telemetry.cost_by_program()
    with _LOCK:
        labels = dict(_CARD_LABELS)
        registry = {d: dict(c) for d, c in _CARD_REGISTRY.items()}
    overhead = float(OPTIONS["costmodel_overhead_ms"])
    rows: dict[str, dict] = {}
    for label, digest in labels.items():
        card = registry.get(digest)
        if card is None:
            continue
        if program is not None and program not in label:
            continue
        row = dict(card, label=label)
        entry = ledger.get(label)
        row["observed"] = dict(entry) if entry is not None else None
        if entry is not None:
            row.update(_utilization(entry, card))
            dispatches = int(entry.get("dispatches", 0))
            if dispatches > 0:
                obs_ms = _net_device_ms(entry) / dispatches
                model_ms = max(float(card.get("predicted_ms", 0.0)), overhead)
                row["observed_ms_per_dispatch"] = round(obs_ms, 6)
                row["model_ms"] = round(model_ms, 6)
                row["drift_ratio"] = round(obs_ms / model_ms, 6) if model_ms else None
        rows[label] = row
    if top is not None:
        ranked = sorted(
            rows.items(),
            key=lambda kv: (
                -float((kv[1].get("observed") or {}).get("device_ms", 0.0)),
                -int((kv[1].get("observed") or {}).get("dispatches", 0)),
                kv[0],
            ),
        )
        rows = dict(ranked[:top])
    return {
        "programs": rows,
        "peaks": dict(peaks_for()),
        "platform": platform_name(),
        "overhead_ms": overhead,
        "drift_threshold": float(OPTIONS["costmodel_drift_threshold"]),
        # per-dataset attribution: device time billed against resident
        # registry entries ({"op": "put_dataset"} names), so the report
        # answers "which pinned dataset is earning its HBM"
        "datasets": telemetry.cost_by_dataset(),
    }


def drift_report(rows: dict | None = None, threshold: float | None = None) -> dict:
    """The predicted-vs-observed drift sentinel.

    A program drifts when its observed per-dispatch device time exceeds
    ``threshold``× the model, where the model is the roofline prediction
    floored at ``OPTIONS["costmodel_overhead_ms"]`` (tiny programs are
    judged against dispatch overhead — an honest CPU run of microsecond
    programs must exit clean, a synthetically delayed dispatch must not).
    Programs with a single observed dispatch are reported but never
    flagged: one cold call is all trace/staging warm-up (the compile wall
    is already netted out, the trace wall is not) — drift is a
    steady-state verdict. ``rows`` defaults to the live
    :func:`program_report` table and also
    accepts a ``/debug/programs`` scrape's ``programs`` mapping, so the
    sentinel runs against a saved scrape of another process. Returns
    ``{"rows": [...], "flagged": [labels], "threshold", "overhead_ms"}``.
    """
    if threshold is None:
        threshold = float(OPTIONS["costmodel_drift_threshold"])
    if rows is None:
        rows = program_report()["programs"]
    out_rows = []
    flagged = []
    for label in sorted(rows):
        row = rows[label]
        ratio = row.get("drift_ratio")
        if ratio is None or not str(row.get("analysis", "")).startswith("ok"):
            continue
        dispatches = int((row.get("observed") or {}).get("dispatches", 0))
        verdict = dispatches >= 2 and float(ratio) > float(threshold)
        out_rows.append(
            {
                "program": label,
                "observed_ms_per_dispatch": row.get("observed_ms_per_dispatch"),
                "predicted_ms": row.get("predicted_ms"),
                "model_ms": row.get("model_ms"),
                "drift_ratio": ratio,
                "flagged": verdict,
            }
        )
        if verdict:
            flagged.append(label)
    if flagged:
        telemetry.count("costmodel.drift_flagged", len(flagged))
    return {
        "threshold": float(threshold),
        "overhead_ms": float(OPTIONS["costmodel_overhead_ms"]),
        "rows": out_rows,
        "flagged": flagged,
    }


# ---------------------------------------------------------------------------
# autotune prior: the analytical model as the cold-start decision
# ---------------------------------------------------------------------------


def analytic_prior(
    family: str,
    fallback: str,
    candidates: tuple,
    *,
    dtype: Any = None,
    ngroups: int = 0,
    nelems: int = 0,
) -> str | None:
    """An analytical prior for an autotune family with no measured band.

    Consulted by ``autotune.decide`` only when the store holds nothing
    close enough. Families the roofline model can reason about get a
    verdict; everything else returns ``None`` (the heuristic fallback
    stands). Counted on ``costmodel.prior_consults`` /
    ``costmodel.prior_decisions``."""
    if not enabled():
        return None
    telemetry.count("costmodel.prior_consults")
    try:
        import numpy as np

        itemsize = np.dtype(dtype).itemsize if dtype is not None else 8
    except (TypeError, ValueError):
        itemsize = 8
    peaks = peaks_for()
    data_bytes = max(0, int(nelems)) * itemsize
    cands = set(candidates)
    choice: str | None = None
    if family == "fused" and {"fused", "sequential"} <= cands:
        # fused reads the data once for the whole statistic set and
        # dispatches once; sequential reads it >= twice and pays >= two
        # dispatch overheads — strictly dominated in the roofline model at
        # every size, and the PR 10 measurements agree (fused won even the
        # small shapes, 5.4x). The analytical prior is unconditional.
        choice = "fused"
    elif family == "highcard" and {"dense", "sort"} <= cands:
        # dense streams the data once and writes ~3 dense (ngroups-sized)
        # intermediates; sort pays ~2 extra passes over the data (the
        # stable binning sort / host unique + compact relabel) but its
        # accumulators track the present groups, bounded above by nelems.
        # Both modeled as bandwidth passes — grouped reductions are
        # memory-bound on every platform in the peak table.
        n_acc = 3
        present_cap_elems = min(max(0, int(nelems)), max(1, int(ngroups)))
        dense_ms = (data_bytes + n_acc * max(1, ngroups) * itemsize) / (
            peaks["bw_gbps"] * 1e9
        ) * 1e3
        sort_ms = (3 * data_bytes + n_acc * present_cap_elems * itemsize) / (
            peaks["bw_gbps"] * 1e9
        ) * 1e3
        choice = "sort" if sort_ms < dense_ms else "dense"
    elif family == "store_query" and {"store", "recompute"} <= cands:
        # serving a finalized read from the durable store scatters the
        # present-groups carry (bounded by the label universe) and
        # finalizes; recomputing re-reduces the FULL history bytes. Both
        # are bandwidth passes; the store wins as soon as history
        # meaningfully exceeds the carry — nelems here is the total
        # history element count, ngroups the store's label universe.
        n_acc = 3
        store_ms = (n_acc * max(1, ngroups) * itemsize) / (
            peaks["bw_gbps"] * 1e9
        ) * 1e3
        recompute_ms = (data_bytes + n_acc * max(1, ngroups) * itemsize) / (
            peaks["bw_gbps"] * 1e9
        ) * 1e3
        choice = "store" if store_ms < recompute_ms else "recompute"
    elif family == "segment_sum" and "matmul" in cands and "scatter" in cands:
        # one-hot GEMM: 2·N·G flops at peak compute vs scatter's serialized
        # updates, modeled as a deeply de-rated bandwidth pass (scatters
        # cannot stream). Matmul wins while the group count is small enough
        # that the redundant flops stay cheaper than the scatter stall.
        matmul_ms = (2.0 * nelems * max(1, ngroups)) / (peaks["gflops"] * 1e9) * 1e3
        scatter_ms = data_bytes / (0.05 * peaks["bw_gbps"] * 1e9) * 1e3
        choice = "matmul" if matmul_ms < scatter_ms else "scatter"
    if choice is None or choice not in cands:
        return None
    telemetry.count("costmodel.prior_decisions")
    return choice


# ---------------------------------------------------------------------------
# capture stamping: tie a profiler capture dir to the programs it saw
# ---------------------------------------------------------------------------


def dispatch_marks() -> dict[str, int]:
    """Per-program-label cumulative dispatch counts from the cost ledger —
    the snapshot :func:`flox_tpu.profiling.start_capture` takes at window
    start so the finished capture can be stamped with exactly the programs
    dispatched inside it."""
    return {
        label: int(entry.get("dispatches", 0))
        for label, entry in telemetry.cost_by_program().items()
    }


def stamp_capture(capture_dir: str, marks: dict[str, int] | None) -> str | None:
    """Write ``programs.json`` into a finished capture dir: the program
    labels dispatched during the window (cumulative ledger dispatches now
    minus ``marks``), each with its card digest where one exists — the
    join key back to ``/debug/costs`` and ``/debug/programs`` rows.
    Best-effort by contract: never raises, returns the path or ``None``."""
    import json
    import os

    try:
        now = dispatch_marks()
        before = marks or {}
        window: dict[str, dict] = {}
        with _LOCK:
            labels = dict(_CARD_LABELS)
        for label, total in now.items():
            delta = total - int(before.get(label, 0))
            if delta <= 0:
                continue
            window[label] = {"dispatches": delta, "digest": labels.get(label)}
        path = os.path.join(str(capture_dir), "programs.json")
        payload = {
            "programs": window,
            "replica": telemetry.replica_instance(),
            "host": telemetry.host_name(),
        }
        os.makedirs(str(capture_dir), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path
    except Exception as exc:  # noqa: BLE001 — stamping must never break a capture
        logger.debug("capture stamp for %s failed: %s", capture_dir, exc)
        return None
